"""DLRM_DCN, the MLPerf 2022 config (reference: modelzoo/mlperf)."""
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from common import ev_option, main


def model_fn(args):
    from deeprec_tpu.models import DLRMDCN

    return DLRMDCN(emb_dim=args.emb_dim, capacity=args.capacity,
                   bottom=(512, 256, args.emb_dim), ev=ev_option(args))


if __name__ == "__main__":
    main("mlperf", model_fn, "criteo")
