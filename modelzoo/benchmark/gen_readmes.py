#!/usr/bin/env python
"""Generate per-model README.md files from the latest MODELZOO_SMOKE.json —
the measured-numbers tables of the reference's modelzoo READMEs
(modelzoo/wide_and_deep/README.md:195-215), kept honest by regenerating
from the benchmark harness output instead of hand-editing.

Usage: python modelzoo/benchmark/gen_readmes.py [--smoke MODELZOO_SMOKE.json]
"""
from __future__ import annotations

import argparse
import json
import os

ZOO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PAPER = {
    "wide_and_deep": ("WDL", "Wide & Deep Learning for Recommender Systems",
                      "https://arxiv.org/abs/1606.07792"),
    "deepfm": ("DeepFM", "DeepFM: A Factorization-Machine based Neural Network",
               "https://arxiv.org/abs/1703.04247"),
    "dlrm": ("DLRM", "Deep Learning Recommendation Model",
             "https://arxiv.org/abs/1906.00091"),
    "dcn": ("DCN", "Deep & Cross Network for Ad Click Predictions",
            "https://arxiv.org/abs/1708.05123"),
    "dcnv2": ("DCNv2", "DCN V2: Improved Deep & Cross Network",
              "https://arxiv.org/abs/2008.13535"),
    "mlperf": ("DLRM_DCN", "MLPerf 2022 DLRM with DCNv2 interactions",
               "https://arxiv.org/abs/2008.13535"),
    "masknet": ("MaskNet", "MaskNet: CTR Ranking with Instance-Guided Mask",
                "https://arxiv.org/abs/2102.07619"),
    "din": ("DIN", "Deep Interest Network for CTR Prediction",
            "https://arxiv.org/abs/1706.06978"),
    "dien": ("DIEN", "Deep Interest Evolution Network",
             "https://arxiv.org/abs/1809.03672"),
    "bst": ("BST", "Behavior Sequence Transformer",
            "https://arxiv.org/abs/1905.06874"),
    "dssm": ("DSSM", "Learning Deep Structured Semantic Models",
             "https://dl.acm.org/doi/10.1145/2505515.2505665"),
    "esmm": ("ESMM", "Entire Space Multi-Task Model",
             "https://arxiv.org/abs/1804.07931"),
    "mmoe": ("MMoE", "Multi-gate Mixture-of-Experts",
             "https://dl.acm.org/doi/10.1145/3219819.3220007"),
    "ple": ("PLE", "Progressive Layered Extraction",
            "https://dl.acm.org/doi/10.1145/3383313.3412236"),
    "dbmtl": ("DBMTL", "Deep Bayesian Multi-Target Learning",
              "https://arxiv.org/abs/1902.09154"),
    "simple_multitask": ("SimpleMultiTask", "Shared-bottom multi-task baseline",
                         "https://arxiv.org/abs/1706.05098"),
}

TEMPLATE = """# {title}

[{paper}]({url})

TPU-native implementation (`deeprec_tpu.models`); reference implementation:
DeepRec `modelzoo/{name}/train.py`.

## Usage

Stand-alone training (synthetic data by default; pass a Criteo TSV or
parquet glob via `--data` for the real dataset):

```bash
python train.py [--steps 2000] [--batch_size 2048] [--data 'day_*.tsv']
```

Mesh-sharded training over all local devices (tables hash-sharded,
batch split; `--comm a2a` selects the budgeted all2all exchange):

```bash
python train.py --sharded [--comm a2a]
```

Feature flags shared by every model (see `../common.py`): `--optimizer
{{sgd,adagrad,adagrad_decay,adam,adam_async,adamw,ftrl}}`, admission
filtering `--filter_freq`, TTL eviction `--steps_to_live`, checkpoints
`--checkpoint DIR --save_steps N --incremental_save_steps M`.

## Benchmark

Measured by `modelzoo/benchmark/benchmark.py` (single device, synthetic
workload, batch {batch}, {steps} steps — the smoke protocol; TPU numbers
land in BENCH_r*.json via the top-level bench.py):

| Model | Device | Throughput (examples/sec) | global_step/sec | AUC |
|---|---|---|---|---|
| {title} | {device} | {eps:,.0f} | {sps:.2f} | {auc} |
{task_rows}
Regenerate after changes: `python ../benchmark/gen_readmes.py`.
"""


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--smoke",
                   default=os.path.join(ZOO, "..", "MODELZOO_SMOKE.json"))
    p.add_argument("--device", default="CPU (virtual mesh host)")
    args = p.parse_args(argv)

    with open(args.smoke) as f:
        report = json.load(f)
    by_model = {r["model"]: r for r in report["results"]}
    for name, (title, paper, url) in PAPER.items():
        r = by_model.get(name)
        if r is None or not r.get("ok"):
            continue
        tasks = r.get("auc_tasks") or {}
        task_rows = ""
        if len(tasks) > 1:
            task_rows = "\nPer-task AUC: " + ", ".join(
                f"`{k}`={v:.4f}" for k, v in sorted(tasks.items())
            ) + "\n"
        out = TEMPLATE.format(
            title=title, paper=paper, url=url, name=name,
            batch=report["batch_size"], steps=report["steps"],
            device=args.device, eps=r["examples_per_sec"],
            sps=r["global_step_per_sec"],
            auc=f"{r['auc']:.4f}" if r.get("auc") else "n/a",
            task_rows=task_rows,
        )
        path = os.path.join(ZOO, name, "README.md")
        with open(path, "w") as f:
            f.write(out)
        print(f"wrote {path}")


if __name__ == "__main__":
    main()
