"""Modelzoo benchmark harness — trains every model, scrapes throughput/AUC.

Parity with the reference harness (modelzoo/benchmark/{cpu,gpu}/benchmark.sh +
config.yaml + log_process.py): each model runs `train.py` as a subprocess for
`--steps` steps at `--batch_size`; throughput = mean(global_step/sec over the
post-warmup window) × batch_size; final AUC scraped from the log. Emits one
JSON report.

Usage:  python modelzoo/benchmark/benchmark.py --steps 600 --batch_size 2048
        [--models wide_and_deep,dlrm,...] [--sharded]
"""
from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys

ZOO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ALL_MODELS = [
    "wide_and_deep", "deepfm", "dlrm", "dcn", "dcnv2", "mlperf", "masknet",
    "din", "dien", "bst", "dssm",
    "esmm", "mmoe", "ple", "dbmtl", "simple_multitask",
]

STEP_RE = re.compile(r"global_step/sec: ([0-9.]+)")
AUC_RE = re.compile(r"Eval AUC: ([0-9.]+) \((\w+)\)")

# Per-model eval-AUC floors for the --full / --extended tiers (the
# reference harness asserts converged AUC the same way,
# /root/reference/modelzoo/benchmark/cpu/config.yaml). Floors sit ~0.02
# under the measured extended-tier AUCs (MODELZOO_FULL.json, round 5:
# 0.70-0.73 criteo/behavior, 0.78 dssm, 0.666 multitask ctr; BST 0.719
# after the target-position head fix) minus an extra 0.01 seed-noise
# allowance — the extended tier has ONE observation per model so far, and
# 1000-step runs carry more seed variance than the 12k-step protocol
# (which measured ±0.002 across seeds, AUC_PROTOCOL.json). Tighten as
# multi-seed evidence accumulates; a run below these floors means
# training quality actually broke.
AUC_FLOORS = {
    "wide_and_deep": 0.70, "deepfm": 0.69, "dlrm": 0.68, "dcn": 0.70,
    "dcnv2": 0.70, "mlperf": 0.70, "masknet": 0.70, "din": 0.67,
    "dien": 0.67, "bst": 0.68, "dssm": 0.74, "esmm": 0.63, "mmoe": 0.63,
    "ple": 0.63, "dbmtl": 0.63, "simple_multitask": 0.63,
}


def run_model(name: str, args) -> dict:
    cmd = [
        sys.executable, os.path.join(ZOO, name, "train.py"),
        "--steps", str(args.steps),
        "--batch_size", str(args.batch_size),
        "--capacity", str(args.capacity),
        "--eval_every", str(args.steps),
        "--log_every", "50",
        "--seed", str(args.seed),
    ]
    if args.sharded:
        cmd.append("--sharded")
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=args.timeout,
            cwd=os.path.join(ZOO, name),
        )
    except subprocess.TimeoutExpired as e:
        # one hung model must not abort an hours-long grid. log_tail is a
        # LIST of lines on every failure path (auc_protocol.py convention)
        # so consumers iterate lines, never characters.
        return {
            "model": name, "ok": False, "global_step_per_sec": 0.0,
            "examples_per_sec": 0.0, "auc": None, "auc_tasks": None,
            "log_tail": ["timeout after %ss" % args.timeout]
            + str(e.stdout or "")[-400:].splitlines(),
        }
    log = proc.stdout + proc.stderr
    sps = [float(m) for m in STEP_RE.findall(log)]
    # final per-task AUCs; the headline is the main/ctr task, NOT whichever
    # task happened to print last (cvr/ctcvr are sparse-label tasks with
    # structurally lower AUC — using them made MTL models look broken)
    aucs = {}
    for v, k in AUC_RE.findall(log):
        aucs[k] = float(v)
    headline = aucs.get("auc", aucs.get("auc_ctr"))
    if headline is None and aucs:
        headline = max(aucs.values())
    warm = sps[1:] if len(sps) > 1 else sps  # drop the compile window
    out = {
        "model": name,
        "ok": proc.returncode == 0 and bool(warm),
        "global_step_per_sec": round(sum(warm) / len(warm), 2) if warm else 0.0,
        "examples_per_sec": round(
            (sum(warm) / len(warm)) * args.batch_size, 1
        ) if warm else 0.0,
        "auc": headline,
        "auc_tasks": aucs or None,
    }
    if not out["ok"]:
        out["log_tail"] = log[-800:].splitlines()
    return out


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--models", default=",".join(ALL_MODELS))
    p.add_argument("--steps", type=int, default=600)
    p.add_argument("--batch_size", type=int, default=2048)
    p.add_argument("--capacity", type=int, default=1 << 18)
    p.add_argument("--sharded", action="store_true")
    p.add_argument("--timeout", type=int, default=1800)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out", default="")
    p.add_argument("--full", action="store_true",
                   help="reference protocol (12k steps, bs 2048, AUC "
                        "floors asserted) — overnight on one CPU core")
    p.add_argument("--extended", action="store_true",
                   help="floor-asserted middle tier (1000 steps, bs 1024) "
                        "for boxes where --full does not fit")
    args = p.parse_args(argv)
    if args.full:
        args.steps, args.batch_size = 12000, 2048
        args.timeout = max(args.timeout, 6 * 3600)
    elif args.extended:
        args.steps, args.batch_size = 1000, 1024
        args.timeout = max(args.timeout, 2 * 3600)
    check_floors = args.full or args.extended

    tier = "full" if args.full else ("extended" if args.extended else "custom")
    results = []
    report = {
        "tier": tier,
        "batch_size": args.batch_size,
        "steps": args.steps,
        "seed": args.seed,
        "floors_asserted": check_floors,
        "results": results,
    }
    for name in args.models.split(","):
        print(f"=== {name} ===", flush=True)
        r = run_model(name.strip(), args)
        if check_floors:
            floor = AUC_FLOORS.get(name.strip())
            r["auc_floor"] = floor
            if floor is None:
                # model without a floor entry: report, don't fail the run
                r["floor_ok"] = None
            else:
                r["floor_ok"] = bool(r["ok"] and (r["auc"] or 0) >= floor)
                if not r["floor_ok"]:
                    r["ok"] = False
        print(json.dumps(r), flush=True)
        results.append(r)
        if args.out:  # incremental + atomic: hours-long grids must survive
            tmp = args.out + ".tmp"
            with open(tmp, "w") as f:
                json.dump(report, f, indent=2)
            os.replace(tmp, args.out)
    print(json.dumps(report))
    return report


if __name__ == "__main__":
    main()
