"""Modelzoo benchmark harness — trains every model, scrapes throughput/AUC.

Parity with the reference harness (modelzoo/benchmark/{cpu,gpu}/benchmark.sh +
config.yaml + log_process.py): each model runs `train.py` as a subprocess for
`--steps` steps at `--batch_size`; throughput = mean(global_step/sec over the
post-warmup window) × batch_size; final AUC scraped from the log. Emits one
JSON report.

Usage:  python modelzoo/benchmark/benchmark.py --steps 600 --batch_size 2048
        [--models wide_and_deep,dlrm,...] [--sharded]
"""
from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys

ZOO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ALL_MODELS = [
    "wide_and_deep", "deepfm", "dlrm", "dcn", "dcnv2", "mlperf", "masknet",
    "din", "dien", "bst", "dssm",
    "esmm", "mmoe", "ple", "dbmtl", "simple_multitask",
]

STEP_RE = re.compile(r"global_step/sec: ([0-9.]+)")
AUC_RE = re.compile(r"Eval AUC: ([0-9.]+) \((\w+)\)")

# Per-model eval-AUC floors for the --full / --extended tiers (the
# reference harness asserts converged AUC the same way,
# /root/reference/modelzoo/benchmark/cpu/config.yaml). Floors are set
# ~0.02 under the worst observed smoke-tier AUC (MODELZOO_SMOKE.json,
# 300 steps) — longer runs must not do WORSE than smoke; raise them as
# full-tier evidence accumulates. BST's floor reflects the round-5 head
# fix (target-position encoding feeds the MLP): 0.687 at smoke size.
AUC_FLOORS = {
    "wide_and_deep": 0.66, "deepfm": 0.66, "dlrm": 0.63, "dcn": 0.66,
    "dcnv2": 0.66, "mlperf": 0.66, "masknet": 0.65, "din": 0.62,
    "dien": 0.62, "bst": 0.64, "dssm": 0.68, "esmm": 0.62, "mmoe": 0.62,
    "ple": 0.62, "dbmtl": 0.62, "simple_multitask": 0.62,
}


def run_model(name: str, args) -> dict:
    cmd = [
        sys.executable, os.path.join(ZOO, name, "train.py"),
        "--steps", str(args.steps),
        "--batch_size", str(args.batch_size),
        "--capacity", str(args.capacity),
        "--eval_every", str(args.steps),
        "--log_every", "50",
    ]
    if args.sharded:
        cmd.append("--sharded")
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=args.timeout,
            cwd=os.path.join(ZOO, name),
        )
    except subprocess.TimeoutExpired as e:
        # one hung model must not abort an hours-long grid
        return {
            "model": name, "ok": False, "global_step_per_sec": 0.0,
            "examples_per_sec": 0.0, "auc": None, "auc_tasks": None,
            "log_tail": "timeout after %ss: %s" % (
                args.timeout, str(e.stdout or "")[-400:]),
        }
    log = proc.stdout + proc.stderr
    sps = [float(m) for m in STEP_RE.findall(log)]
    # final per-task AUCs; the headline is the main/ctr task, NOT whichever
    # task happened to print last (cvr/ctcvr are sparse-label tasks with
    # structurally lower AUC — using them made MTL models look broken)
    aucs = {}
    for v, k in AUC_RE.findall(log):
        aucs[k] = float(v)
    headline = aucs.get("auc", aucs.get("auc_ctr"))
    if headline is None and aucs:
        headline = max(aucs.values())
    warm = sps[1:] if len(sps) > 1 else sps  # drop the compile window
    out = {
        "model": name,
        "ok": proc.returncode == 0 and bool(warm),
        "global_step_per_sec": round(sum(warm) / len(warm), 2) if warm else 0.0,
        "examples_per_sec": round(
            (sum(warm) / len(warm)) * args.batch_size, 1
        ) if warm else 0.0,
        "auc": headline,
        "auc_tasks": aucs or None,
    }
    if not out["ok"]:
        out["log_tail"] = log[-800:]
    return out


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--models", default=",".join(ALL_MODELS))
    p.add_argument("--steps", type=int, default=600)
    p.add_argument("--batch_size", type=int, default=2048)
    p.add_argument("--capacity", type=int, default=1 << 18)
    p.add_argument("--sharded", action="store_true")
    p.add_argument("--timeout", type=int, default=1800)
    p.add_argument("--out", default="")
    p.add_argument("--full", action="store_true",
                   help="reference protocol (12k steps, bs 2048, AUC "
                        "floors asserted) — overnight on one CPU core")
    p.add_argument("--extended", action="store_true",
                   help="floor-asserted middle tier (1000 steps, bs 1024) "
                        "for boxes where --full does not fit")
    args = p.parse_args(argv)
    if args.full:
        args.steps, args.batch_size = 12000, 2048
        args.timeout = max(args.timeout, 6 * 3600)
    elif args.extended:
        args.steps, args.batch_size = 1000, 1024
        args.timeout = max(args.timeout, 2 * 3600)
    check_floors = args.full or args.extended

    tier = "full" if args.full else ("extended" if args.extended else "custom")
    results = []
    report = {
        "tier": tier,
        "batch_size": args.batch_size,
        "steps": args.steps,
        "floors_asserted": check_floors,
        "results": results,
    }
    for name in args.models.split(","):
        print(f"=== {name} ===", flush=True)
        r = run_model(name.strip(), args)
        if check_floors:
            floor = AUC_FLOORS.get(name.strip())
            r["auc_floor"] = floor
            if floor is None:
                # model without a floor entry: report, don't fail the run
                r["floor_ok"] = None
            else:
                r["floor_ok"] = bool(r["ok"] and (r["auc"] or 0) >= floor)
                if not r["floor_ok"]:
                    r["ok"] = False
        print(json.dumps(r), flush=True)
        results.append(r)
        if args.out:  # incremental + atomic: hours-long grids must survive
            tmp = args.out + ".tmp"
            with open(tmp, "w") as f:
                json.dump(report, f, indent=2)
            os.replace(tmp, args.out)
    print(json.dumps(report))
    return report


if __name__ == "__main__":
    main()
