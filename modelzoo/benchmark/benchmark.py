"""Modelzoo benchmark harness — trains every model, scrapes throughput/AUC.

Parity with the reference harness (modelzoo/benchmark/{cpu,gpu}/benchmark.sh +
config.yaml + log_process.py): each model runs `train.py` as a subprocess for
`--steps` steps at `--batch_size`; throughput = mean(global_step/sec over the
post-warmup window) × batch_size; final AUC scraped from the log. Emits one
JSON report.

Usage:  python modelzoo/benchmark/benchmark.py --steps 600 --batch_size 2048
        [--models wide_and_deep,dlrm,...] [--sharded]
"""
from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys

ZOO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ALL_MODELS = [
    "wide_and_deep", "deepfm", "dlrm", "dcn", "dcnv2", "mlperf", "masknet",
    "din", "dien", "bst", "dssm",
    "esmm", "mmoe", "ple", "dbmtl", "simple_multitask",
]

STEP_RE = re.compile(r"global_step/sec: ([0-9.]+)")
AUC_RE = re.compile(r"Eval AUC: ([0-9.]+) \((\w+)\)")


def run_model(name: str, args) -> dict:
    cmd = [
        sys.executable, os.path.join(ZOO, name, "train.py"),
        "--steps", str(args.steps),
        "--batch_size", str(args.batch_size),
        "--capacity", str(args.capacity),
        "--eval_every", str(args.steps),
        "--log_every", "50",
    ]
    if args.sharded:
        cmd.append("--sharded")
    proc = subprocess.run(
        cmd, capture_output=True, text=True, timeout=args.timeout,
        cwd=os.path.join(ZOO, name),
    )
    log = proc.stdout + proc.stderr
    sps = [float(m) for m in STEP_RE.findall(log)]
    # final per-task AUCs; the headline is the main/ctr task, NOT whichever
    # task happened to print last (cvr/ctcvr are sparse-label tasks with
    # structurally lower AUC — using them made MTL models look broken)
    aucs = {}
    for v, k in AUC_RE.findall(log):
        aucs[k] = float(v)
    headline = aucs.get("auc", aucs.get("auc_ctr"))
    if headline is None and aucs:
        headline = max(aucs.values())
    warm = sps[1:] if len(sps) > 1 else sps  # drop the compile window
    out = {
        "model": name,
        "ok": proc.returncode == 0 and bool(warm),
        "global_step_per_sec": round(sum(warm) / len(warm), 2) if warm else 0.0,
        "examples_per_sec": round(
            (sum(warm) / len(warm)) * args.batch_size, 1
        ) if warm else 0.0,
        "auc": headline,
        "auc_tasks": aucs or None,
    }
    if not out["ok"]:
        out["log_tail"] = log[-800:]
    return out


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--models", default=",".join(ALL_MODELS))
    p.add_argument("--steps", type=int, default=600)
    p.add_argument("--batch_size", type=int, default=2048)
    p.add_argument("--capacity", type=int, default=1 << 18)
    p.add_argument("--sharded", action="store_true")
    p.add_argument("--timeout", type=int, default=1800)
    p.add_argument("--out", default="")
    args = p.parse_args(argv)

    results = []
    for name in args.models.split(","):
        print(f"=== {name} ===", flush=True)
        r = run_model(name.strip(), args)
        print(json.dumps(r), flush=True)
        results.append(r)
    report = {
        "batch_size": args.batch_size,
        "steps": args.steps,
        "results": results,
    }
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2)
    print(json.dumps(report))
    return report


if __name__ == "__main__":
    main()
