"""Reference-protocol AUC runs on the pinned CriteoStats generator.

The reference's modelzoo asserts real-Criteo AUC (wide_and_deep/README.md:
195-215: WDL 0.7741/0.7748; benchmark/cpu/config.yaml: 12,000 steps at
batch 2048). No Criteo mount exists here, so this harness runs the same
PROTOCOL on the deterministic Criteo-statistics-matched stream
(deeprec_tpu/data/synthetic.py: CriteoStats — published Kaggle
cardinalities/CTR/missing-rates, per-column zipf spectra, hash-derived
logistic labels) and reports trained AUC against the generator's
computable Bayes ceiling — an honest parity argument with explicit
provenance instead of synthetic numbers dressed up as real-Criteo.

Usage:
    python modelzoo/benchmark/auc_protocol.py \
        [--models wide_and_deep,dlrm] [--seeds 0,1,2] [--steps 12000] \
        [--batch_size 2048] [--out AUC_PROTOCOL.json]

Each run is `train.py --data criteo_stats` in a subprocess; eval is 50
batches of the held-out eval split. Results append to --out after every
run (the grid takes hours on one CPU core; partial results survive).
"""
from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
import time

ZOO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
AUC_RE = re.compile(r"Eval AUC: ([0-9.]+) \(auc\)")
SPS_RE = re.compile(r"global_step/sec: ([0-9.]+)")


def run_one(model: str, seed: int, args) -> dict:
    cmd = [
        sys.executable, os.path.join(ZOO, model, "train.py"),
        "--data", "criteo_stats",
        "--steps", str(args.steps),
        "--batch_size", str(args.batch_size),
        "--capacity", str(args.capacity),
        "--eval_every", str(args.steps),
        "--eval_batches", str(args.eval_batches),
        "--log_every", "500",
        "--seed", str(seed),
    ]
    t0 = time.time()
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=args.timeout,
                              cwd=os.path.join(ZOO, model))
    except subprocess.TimeoutExpired as e:
        # one slow run must not abort the grid: record and move on
        return {
            "model": model, "seed": seed, "auc": None, "ok": False,
            "wall_clock_s": round(time.time() - t0, 1),
            "log_tail": ["timeout after %ss" % args.timeout]
            + str(e.stdout or "")[-500:].splitlines()[-5:],
        }
    log = proc.stdout + proc.stderr
    aucs = [float(m) for m in AUC_RE.findall(log)]
    sps = [float(m) for m in SPS_RE.findall(log)]
    warm = sps[1:] if len(sps) > 1 else sps
    out = {
        "model": model,
        "seed": seed,
        "auc": aucs[-1] if aucs else None,
        "examples_per_sec": round(
            args.batch_size * sum(warm) / len(warm), 1) if warm else None,
        "wall_clock_s": round(time.time() - t0, 1),
        "ok": proc.returncode == 0 and bool(aucs),
    }
    if not out["ok"]:
        out["log_tail"] = log.strip().splitlines()[-15:]
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--models", default="wide_and_deep,dlrm")
    ap.add_argument("--seeds", default="0,1,2")
    ap.add_argument("--steps", type=int, default=12000)
    ap.add_argument("--batch_size", type=int, default=2048)
    ap.add_argument("--capacity", type=int, default=1 << 17)
    ap.add_argument("--eval_batches", type=int, default=50)
    ap.add_argument("--timeout", type=int, default=3 * 3600)
    ap.add_argument("--out", default="AUC_PROTOCOL.json")
    args = ap.parse_args()

    sys.path.insert(0, os.path.dirname(ZOO))
    from deeprec_tpu.data.synthetic import CriteoStats

    report = {
        "protocol": {
            "data": "criteo_stats (deterministic Criteo-marginal-matched; "
                    "see deeprec_tpu/data/synthetic.py docstrings for the "
                    "published-statistics provenance)",
            "steps": args.steps,
            "batch_size": args.batch_size,
            "capacity_per_table": args.capacity,
            "eval": f"{args.eval_batches} held-out eval-split batches",
            "reference_match": "modelzoo/benchmark/cpu/config.yaml "
                               "(12000 steps, bs 2048); "
                               "wide_and_deep/README.md real-Criteo AUC "
                               "0.7741-0.7748",
        },
        "bayes_ceiling_auc": round(CriteoStats(seed=0).bayes_auc(500_000), 4),
        "runs": [],
    }
    for model in args.models.split(","):
        for seed in (int(s) for s in args.seeds.split(",")):
            print(f"=== {model} seed {seed} ===", flush=True)
            res = run_one(model, seed, args)
            print(json.dumps(res), flush=True)
            report["runs"].append(res)
            # atomic update: a crash mid-dump must not eat prior runs
            tmp = args.out + ".tmp"
            with open(tmp, "w") as f:
                json.dump(report, f, indent=1)
            os.replace(tmp, args.out)
    ok = [r for r in report["runs"] if r["ok"]]
    print(f"done: {len(ok)}/{len(report['runs'])} runs ok -> {args.out}")


if __name__ == "__main__":
    main()
