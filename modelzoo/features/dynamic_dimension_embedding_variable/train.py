"""Dynamic-dimension EV demo (reference
features/dynamic_dimension_embedding_variable): rare keys train/serve a
PREFIX of the embedding vector; the dim steps up with frequency."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "..", ".."))

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from deeprec_tpu import EmbeddingTable, TableConfig  # noqa: E402
from deeprec_tpu.embedding.compose import DynamicDimEmbedding  # noqa: E402


def main():
    t = EmbeddingTable(TableConfig(name="dyn", dim=32, capacity=1 << 12))
    dyn = DynamicDimEmbedding(t, dim_tiers=(8, 16, 32), freq_tiers=(3, 10))
    s = t.create()
    rng = np.random.default_rng(0)
    for step in range(12):
        # zipf-ish stream: id 1 is hot, tail ids rare
        ids = jnp.asarray(np.minimum(rng.zipf(1.5, 512), 4000), jnp.int32)
        s, res = dyn.lookup_unique(s, ids, step=step)
    eff = dyn.effective_dim(s, res)
    uids = np.asarray(res.uids)[np.asarray(res.valid)]
    effv = np.asarray(eff)[np.asarray(res.valid)]
    hot = effv[uids == 1]
    print(f"hot id dim: {hot[0] if len(hot) else '-'}; "
          f"tail ids at dim 8: {(effv == 8).sum()}/{len(effv)}")
    assert len(hot) and hot[0] == 32  # hot key graduated to full width


if __name__ == "__main__":
    main()
