"""Multi-tier storage demo (reference features/pmem + tiered storage):
HBM working set + DRAM overflow + SSD log — cold rows demote, returning
keys promote WITH their optimizer state, all three tiers stay servable."""
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "..", ".."))

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from deeprec_tpu import (  # noqa: E402
    EmbeddingTable,
    EmbeddingVariableOption,
    StorageOption,
    TableConfig,
)
from deeprec_tpu.config import StorageType  # noqa: E402
from deeprec_tpu.embedding.multi_tier import MultiTierTable  # noqa: E402


def main():
    tmp = tempfile.mkdtemp(prefix="tier_demo_")
    cfg = TableConfig(
        name="tiered", dim=16, capacity=256,
        ev=EmbeddingVariableOption(storage=StorageOption(
            storage_type=StorageType.HBM_DRAM_SSD,
            storage_path=os.path.join(tmp, "tier"),
            host_capacity=64,
        )),
    )
    t = EmbeddingTable(cfg)
    mt = MultiTierTable(t, high_watermark=0.75, low_watermark=0.5)
    s = t.create()
    s, _ = t.lookup_unique(s, jnp.arange(210, dtype=jnp.int32), step=0)
    s, stats = mt.sync(s, step=1)
    print(f"after sync: device {stats.device_size} rows, "
          f"host {stats.host_size}, disk {stats.disk_size} "
          f"(demoted {stats.demoted}, spilled {stats.spilled})")
    emb = mt.lookup_with_fallback(s, jnp.arange(210, dtype=jnp.int32))
    assert np.isfinite(np.asarray(emb)).all()
    print("all 210 ids servable across the three tiers")


if __name__ == "__main__":
    main()
