"""AdagradDecay demo (reference features/adagraddecay_optimizer):
Adagrad whose accumulator decays every N global steps, so old gradients
stop dominating long-running streams."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "..", ".."))
from _demo import parse_args, train  # noqa: E402

from deeprec_tpu.models import WDL  # noqa: E402
from deeprec_tpu.optim import AdagradDecay  # noqa: E402


def main():
    args = parse_args()
    model = WDL(emb_dim=16, capacity=1 << 14, hidden=(64, 32), num_cat=4,
                num_dense=2)
    train(model, args,
          sparse_opt=AdagradDecay(lr=0.1, accumulator_decay_step=100,
                                 accumulator_decay_rate=0.9))


if __name__ == "__main__":
    main()
