"""Kafka streaming demo (reference contrib/kafka + kafka_dataset_op):
train from a Kafka topic via the wire-protocol consumer with
exactly-once offset resume. --servers points at a real broker;
--selftest spins the scripted broker stub from the test suite (real
Kafka frames over a real socket) so the demo runs in this image."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "..", ".."))


def main():
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--servers", default=None, help="host:port of a broker")
    p.add_argument("--topic", default="clicks:0:0")
    p.add_argument("--selftest", action="store_true")
    args = p.parse_args()

    from deeprec_tpu.data import KafkaStreamReader

    broker = None
    selftest = args.selftest or args.servers is None
    if selftest:
        # The scripted broker stub lives with the wire-protocol tests; a
        # demo-local import path keeps this optional and explicit.
        tests_dir = os.path.join(os.path.dirname(__file__), "..", "..",
                                 "..", "tests")
        sys.path.append(tests_dir)  # append, not prepend: no shadowing
        from test_kafka import TOPIC, BrokerStub, tsv_rows

        broker = BrokerStub(tsv_rows(512), encoding="v2", page=64)
        args.servers = f"127.0.0.1:{broker.port}"
        args.topic = f"{TOPIC}:0:0"
        print(f"selftest: scripted broker at {args.servers}")

    reader = KafkaStreamReader(
        args.servers, args.topic, batch_size=128, stop_at_eof=True,
        num_dense=2, num_cat=2, group="demo",
    )
    rows = 0
    resumed = False
    for i, batch in enumerate(reader):
        rows += len(batch["label"])
        if i == 1:  # checkpoint mid-stream, then resume in a NEW reader
            state = reader.save()
            reader.close()
            print(f"consumed {rows} rows; offsets checkpointed at "
                  f"{state['offset']}; resuming in a fresh consumer...")
            reader2 = KafkaStreamReader(
                args.servers, args.topic, batch_size=128, stop_at_eof=True,
                num_dense=2, num_cat=2, group="demo",
            )
            reader2.restore(state)
            for b2 in reader2:
                rows += len(b2["label"])
            reader2.commit()  # broker-side group offset
            reader2.close()
            resumed = True
            break
    if not resumed:
        reader.commit()
        reader.close()
        print("stream fit in one batch: no mid-stream checkpoint exercised")
    print(f"total rows consumed exactly once: {rows}")
    if selftest:  # known stream: assert the exactly-once accounting
        assert resumed and rows == 512
        print(f"group offset committed broker-side: "
              f"{broker.committed.get('demo')}")
        broker.stop()


if __name__ == "__main__":
    main()
