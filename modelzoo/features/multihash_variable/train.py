"""Multi-hash variable demo (reference features/multihash_variable):
quotient-remainder composition — two small dense tables emulate a huge
id space, memory = Q + R rows instead of Q*R."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "..", ".."))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from deeprec_tpu.embedding.compose import (  # noqa: E402
    MultiHashConfig,
    MultiHashTable,
)


def main():
    mh = MultiHashTable(MultiHashConfig(
        name="mh", dim=16, num_buckets_q=1 << 10, num_buckets_r=1 << 10,
    ))
    params = mh.create(jax.random.PRNGKey(0))
    ids = jnp.asarray(np.arange(0, 1_000_000, 31_013), jnp.int32)
    emb = mh.lookup(params, ids)
    n = len(np.asarray(ids))
    print(f"{n} ids from a ~1M space through 2x1024-row tables "
          f"({(1 << 10) * 2} rows total) -> emb {emb.shape}")
    flat = np.asarray(emb).reshape(n, -1)
    assert len(np.unique(flat.round(5), axis=0)) == n  # distinct vectors


if __name__ == "__main__":
    main()
