"""Shared scaffolding for the feature demos: tiny WDL on synthetic
Criteo, a train loop with loss/AUC logging — the MonitoredTrainingSession
shape of the reference demos, minus the boilerplate."""
from __future__ import annotations

import argparse
import time


def parse_args(extra=None):
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=200)
    p.add_argument("--batch", type=int, default=256)
    p.add_argument("--vocab", type=int, default=5000)
    for fn in extra or ():
        fn(p)
    return p.parse_args()


def train(model, args, sparse_opt=None, dense_opt=None, hook=None,
          batches=None):
    import jax.numpy as jnp
    import numpy as np
    import optax

    from deeprec_tpu.data import SyntheticCriteo
    from deeprec_tpu.optim import Adagrad
    from deeprec_tpu.training import Trainer
    from deeprec_tpu.training.metrics import AucState, auc_compute, auc_update

    tr = Trainer(model, sparse_opt or Adagrad(lr=0.1),
                 dense_opt or optax.adam(2e-3))
    st = tr.init(0)
    num_cat = len([f for f in model.features if hasattr(f, "table")])
    gen = batches or SyntheticCriteo(
        batch_size=args.batch, num_cat=num_cat or 4, num_dense=2,
        vocab=args.vocab, seed=3,
    )
    it = iter(gen) if not hasattr(gen, "batch") else None
    t0 = time.time()
    for step in range(args.steps):
        raw = next(it) if it is not None else gen.batch()
        b = {k: jnp.asarray(v) for k, v in raw.items()}
        st, mets = tr.train_step(st, b)
        if hook is not None:
            st = hook(tr, st, step) or st
        if step % 50 == 0 or step == args.steps - 1:
            print(f"step {step:5d}  loss {float(mets['loss']):.4f}  "
                  f"({(step + 1) / (time.time() - t0):.1f} steps/s)")
    auc = AucState.create()
    for _ in range(5):
        raw = next(it) if it is not None else gen.batch()
        b = {k: jnp.asarray(v) for k, v in raw.items()}
        _, p = tr.eval_step(st, b)
        auc = auc_update(auc, p, b["label"])
    print(f"eval AUC {float(auc_compute(auc)):.4f}")
    return tr, st
