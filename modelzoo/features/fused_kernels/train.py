"""Fused-kernel demo (reference features/gpu_fused_embedding): opt a
table into the Pallas DMA kernels + bf16 values with stochastic
rounding. On CPU every path falls back to identical-semantics XLA; on
TPU kernel eligibility is dim%128==0 (f32 rows / bf16 pair granules)."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "..", ".."))
from _demo import parse_args, train  # noqa: E402

import dataclasses  # noqa: E402

from deeprec_tpu.features import SparseFeature  # noqa: E402
from deeprec_tpu.models import WDL  # noqa: E402


def main():
    args = parse_args(extra=[lambda p: p.add_argument(
        "--bf16", action="store_true")])
    model = WDL(emb_dim=128, capacity=1 << 14, hidden=(64, 32), num_cat=4,
                num_dense=2)
    over = {"kernel": "pallas"}
    if args.bf16:
        over["value_dtype"] = "bfloat16"
    model.features = [
        dataclasses.replace(f, table=dataclasses.replace(f.table, **over))
        if isinstance(f, SparseFeature) else f
        for f in model.features
    ]
    train(model, args)


if __name__ == "__main__":
    main()
