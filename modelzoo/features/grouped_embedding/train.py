"""Grouped embedding demo (reference features/grouped_embedding):
same-config tables auto-bundle into ONE stacked [T, C, D] table and one
vmapped probe — the group_embedding_lookup analog with zero user code."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "..", ".."))
from _demo import parse_args, train  # noqa: E402

from deeprec_tpu.models import WDL  # noqa: E402


def main():
    args = parse_args()
    model = WDL(emb_dim=16, capacity=1 << 14, hidden=(64, 32), num_cat=8,
                num_dense=2)
    tr, st = train(model, args)
    print("bundles:", {n: len(b.features) for n, b in tr.bundles.items()},
          "(8 features -> 1 stacked probe)")


if __name__ == "__main__":
    main()
