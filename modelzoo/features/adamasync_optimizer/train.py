"""AdamAsync sparse optimizer demo (reference
features/adamasync_optimizer): per-key Adam with per-row beta-power
slots — the PS-free translation of DeepRec's AdamAsync."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "..", ".."))
from _demo import parse_args, train  # noqa: E402

from deeprec_tpu.models import WDL  # noqa: E402
from deeprec_tpu.optim import AdamAsync  # noqa: E402


def main():
    args = parse_args()
    model = WDL(emb_dim=16, capacity=1 << 14, hidden=(64, 32), num_cat=4,
                num_dense=2)
    train(model, args, sparse_opt=AdamAsync(lr=0.01))


if __name__ == "__main__":
    main()
