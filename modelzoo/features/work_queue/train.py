"""WorkQueue demo (reference features/work_queue): dynamic file sharding
— workers PULL file slices from a shared queue instead of static
assignment, so stragglers never strand data. Single-process here;
tests/test_launch.py drives the multi-process file-coordinated mode."""
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "..", ".."))

import numpy as np  # noqa: E402

from deeprec_tpu.data import SyntheticCriteo, WorkQueue  # noqa: E402


def main():
    # write 4 small criteo-ish TSV shards
    tmp = tempfile.mkdtemp(prefix="wq_demo_")
    gen = SyntheticCriteo(batch_size=64, num_cat=3, num_dense=2, vocab=500,
                          seed=0)
    paths = []
    for i in range(4):
        b = gen.batch()
        rows = []
        for r in range(64):
            cats = "\t".join(str(int(b[f"C{c+1}"][r])) for c in range(3))
            dens = "\t".join(f"{float(b[f'I{c+1}'][r, 0]):.3f}"
                             for c in range(2))
            rows.append(f"{int(b['label'][r])}\t{dens}\t{cats}")
        p = os.path.join(tmp, f"part-{i}.tsv")
        with open(p, "w") as f:
            f.write("\n".join(rows) + "\n")
        paths.append(p)

    q = WorkQueue(paths, num_epochs=2, shuffle=True, num_slices=2)
    n_items, n_rows = 0, 0
    for batch in q.input_dataset(batch_size=32, num_dense=2, num_cat=3):
        n_rows += len(batch["label"])
        n_items += 1
    print(f"drained {n_rows} rows in {n_items} batches from "
          f"{len(paths)} files x 2 slices x 2 epochs")
    assert n_rows == 64 * 4 * 2


if __name__ == "__main__":
    main()
