"""SOK-style sharded embedding demo (reference
features/sparse_operation_kit): model-parallel tables over a device
mesh with a budgeted all-to-all exchange. Runs on a virtual 8-device
CPU mesh; on a pod the same code rides ICI."""
import os
import sys

# Demo fallback ONLY: force a virtual 8-device CPU mesh when no TPU
# runtime is present (checked WITHOUT initializing jax — env flags must
# be set before first backend init). On a TPU host, jax is left alone so
# the same code actually rides ICI.
if not os.environ.get("JAX_PLATFORMS"):
    import importlib.util as _ilu

    _has_tpu = (
        _ilu.find_spec("libtpu") is not None
        or os.path.exists("/dev/accel0")
        or os.environ.get("TPU_NAME")
    )
    if not _has_tpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "..", ".."))

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

from deeprec_tpu.data import SyntheticCriteo  # noqa: E402
from deeprec_tpu.models import WDL  # noqa: E402
from deeprec_tpu.optim import Adagrad  # noqa: E402
from deeprec_tpu.parallel import (  # noqa: E402
    ShardedTrainer,
    make_mesh,
    shard_batch,
)


def main():
    mesh = make_mesh(8)
    model = WDL(emb_dim=16, capacity=1 << 13, hidden=(64, 32), num_cat=4,
                num_dense=2)
    tr = ShardedTrainer(model, Adagrad(lr=0.1), optax.adam(2e-3), mesh=mesh,
                        comm="a2a")  # the SOK all2all path
    st = tr.init(0)
    gen = SyntheticCriteo(batch_size=512, num_cat=4, num_dense=2,
                          vocab=4000, zipf_a=1.3, seed=5)
    for step in range(60):
        st, m = tr.train_step(st, shard_batch(mesh, {
            k: jnp.asarray(v) for k, v in gen.batch().items()}))
        if step % 20 == 0:
            print(f"step {step:3d}  loss {float(m['loss']):.4f}")
    overflow = sum(int(np.asarray(ts.a2a_overflow).sum())
                   for ts in st.tables.values())
    print(f"8-shard a2a training done; budget overflow: {overflow}")


if __name__ == "__main__":
    main()
