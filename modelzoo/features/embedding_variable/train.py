"""EmbeddingVariable demo (reference features/embedding_variable):
hash-table embeddings with a counter admission filter and TTL eviction —
no vocabulary size planning, cold ids filtered, stale ids evicted."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "..", ".."))
from _demo import parse_args, train  # noqa: E402

from deeprec_tpu.config import (  # noqa: E402
    CounterFilter,
    EmbeddingVariableOption,
    GlobalStepEvict,
)
from deeprec_tpu.models import WDL  # noqa: E402


def main():
    args = parse_args()
    ev = EmbeddingVariableOption(
        counter_filter=CounterFilter(filter_freq=2),   # admit at 2nd sight
        global_step_evict=GlobalStepEvict(steps_to_live=500),
    )
    model = WDL(emb_dim=16, capacity=1 << 14, hidden=(64, 32), num_cat=4,
                num_dense=2, ev=ev)

    def evict_hook(tr, st, step):
        if step and step % 100 == 0:
            st = tr.evict_tables(st)
            sizes = {n: int(t.size(tr.table_state(st, n)))
                     for n, t in tr.tables.items()}
            print(f"  evict @ {step}: table sizes {sizes}")
        return st

    train(model, args, hook=evict_hook)


if __name__ == "__main__":
    main()
