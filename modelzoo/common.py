"""Shared modelzoo training driver — the `python train.py` CLI every model
directory exposes (reference: modelzoo/<model>/train.py argument surface:
--batch_size --steps --checkpoint ... README per model).

Supports synthetic data (default; no dataset mounted) or real Criteo TSV /
parquet files, single-device or mesh-sharded execution, full + incremental
checkpointing, periodic eval with AUC, and benchmark-harness-compatible log
lines:  `global_step/sec: <v>`  and  `Eval AUC: <v>`  (scraped by
modelzoo/benchmark/benchmark.py the way log_process.py does).
"""
from __future__ import annotations

import argparse
import sys
import time
from typing import Callable, Dict, Optional

import numpy as np


def build_argparser(name: str) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=f"Train {name} on TPU (deeprec_tpu)")
    p.add_argument("--batch_size", type=int, default=2048)
    p.add_argument("--steps", type=int, default=2000)
    p.add_argument("--emb_dim", type=int, default=16)
    p.add_argument("--capacity", type=int, default=1 << 20)
    p.add_argument("--vocab", type=int, default=1_000_000,
                   help="synthetic id vocabulary per feature")
    p.add_argument("--learning_rate", type=float, default=0.05)
    p.add_argument("--dense_lr", type=float, default=1e-3)
    p.add_argument("--optimizer", default="adagrad",
                   choices=["sgd", "adagrad", "adagrad_decay", "adam",
                            "adam_async", "adamw", "ftrl"])
    p.add_argument("--data", default="synthetic",
                   help="'synthetic', 'criteo_stats' (pinned Criteo-marginal stream), a criteo .tsv glob, or a .parquet glob")
    p.add_argument("--sharded", action="store_true",
                   help="shard tables + batch over all local devices")
    p.add_argument("--comm", default="allgather", choices=["allgather", "a2a"],
                   help="sharded embedding exchange: exact allgather or "
                        "budgeted all2all (SOK path)")
    p.add_argument("--checkpoint", default="",
                   help="checkpoint directory (enables save/restore)")
    p.add_argument("--save_steps", type=int, default=1000)
    p.add_argument("--incremental_save_steps", type=int, default=0)
    p.add_argument("--eval_every", type=int, default=500)
    p.add_argument("--eval_batches", type=int, default=8)
    p.add_argument("--log_every", type=int, default=100)
    p.add_argument("--filter_freq", type=int, default=0,
                   help="counter-filter admission threshold")
    p.add_argument("--steps_to_live", type=int, default=0,
                   help="TTL eviction in steps (0 = off)")
    p.add_argument("--evict_every", type=int, default=0,
                   help="run eviction policies every N steps (0 = only with "
                        "checkpoints)")
    p.add_argument("--bf16", action="store_true", default=False,
                   help="bfloat16 embedding tables (halves table HBM; "
                        "updates use stochastic rounding). Dense compute "
                        "is bf16-on-MXU regardless (nn.py).")
    p.add_argument("--kernel", default="auto", choices=["auto", "xla", "pallas"],
                   help="embedding hot-path kernel (TableConfig.kernel)")
    p.add_argument("--micro_batch", type=int, default=0,
                   help="split each batch into N micro-batches "
                        "(Auto-Micro-Batch: sparse applies per micro, dense "
                        "grads accumulated; batch_size must divide by N)")
    p.add_argument("--workqueue", action="store_true",
                   help="shard --data files through a WorkQueue (dynamic "
                        "work-item sharding; straggler-proof multi-worker "
                        "input). Requires --data.")
    p.add_argument("--num_slices", type=int, default=1,
                   help="with --workqueue: split each file into N slices")
    p.add_argument("--epochs", type=int, default=1,
                   help="with --workqueue: dataset epochs in the queue")
    p.add_argument("--maintain_every", type=int, default=0,
                   help="run capacity management (auto-grow / tiering) "
                        "every N steps (0 = off)")
    p.add_argument("--hbm_budget_mb", type=int, default=0,
                   help="with --maintain_every: total table-bytes budget; "
                        "growth beyond it auto-tiers to the host store")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--timeline", type=int, default=0,
                   help="trace steps [N, N+10) to --timeline_dir")
    p.add_argument("--timeline_dir", default="/tmp/deeprec_tpu_trace")
    p.add_argument("--metrics_file", default="",
                   help="append JSONL metrics records here")
    return p


def ev_option(args):
    from deeprec_tpu import (
        CounterFilter,
        EmbeddingVariableOption,
        GlobalStepEvict,
    )

    return EmbeddingVariableOption(
        counter_filter=CounterFilter(args.filter_freq) if args.filter_freq else None,
        global_step_evict=(
            GlobalStepEvict(args.steps_to_live) if args.steps_to_live else None
        ),
    )


def make_optimizers(args):
    import optax

    from deeprec_tpu.optim import make

    return make(args.optimizer, lr=args.learning_rate), optax.adam(args.dense_lr)


def make_data(args, kind: str):
    """kind: 'criteo' | 'multitask' | 'behavior' | 'twotower'."""
    import glob

    from deeprec_tpu import data as D

    if args.data == "criteo_stats":
        if kind != "criteo":
            raise ValueError(
                "criteo_stats generates Criteo-shaped batches; model kind "
                f"{kind!r} wants a different schema"
            )
        # The deterministic Criteo-marginal-matched stream (AUC protocol,
        # docs/auc_protocol.md): train and eval are disjoint splits of the
        # same fixed task, so eval AUC is held-out, not memorized.
        gen = D.CriteoStats(args.batch_size, seed=args.seed, split="train")
        args._eval_iter = iter(
            D.CriteoStats(args.batch_size, seed=args.seed, split="eval")
        )
        # Stream position checkpoints with the model (CriteoStats is a pure
        # function of index: a restore must NOT replay consumed batches and
        # must NOT skip un-consumed ones). The auto-stage ring runs ahead of
        # the train step, so run() wires gen.mark_consumed into the staged
        # iterator and save() records the CONSUMED index — the producer
        # index would silently skip the in-flight batches.
        args._datasets = {"criteo_stats": gen}
        return iter(gen)
    if args.data != "synthetic":
        paths = sorted(glob.glob(args.data))
        if not paths:
            raise FileNotFoundError(f"--data glob matched nothing: {args.data}")
        if getattr(args, "workqueue", False):
            parquet = paths[0].endswith(".parquet")
            if parquet and args.num_slices > 1:
                raise ValueError(
                    "--num_slices applies to TSV files only (parquet has no "
                    "byte-range slicing; shard by file instead)"
                )
            q = D.WorkQueue(paths, num_epochs=args.epochs, shuffle=True,
                            seed=args.seed, num_slices=args.num_slices)
            # registered with the CheckpointManager in run(): queue
            # position checkpoints WITH the model
            args._datasets = {"workqueue": q}
            # training wants one compiled batch shape: drop per-slice
            # remainders (size the slices >= batch_size)
            return q.input_dataset(
                args.batch_size, drop_remainder=True,
                reader_cls=D.ParquetReader if parquet else None,
            )
        if paths[0].endswith(".parquet"):
            return iter(D.ParquetReader(paths, args.batch_size))
        return iter(D.CriteoCSVReader(paths, args.batch_size))
    if kind == "criteo":
        gen = D.SyntheticCriteo(args.batch_size, vocab=args.vocab, seed=args.seed)
    elif kind == "multitask":
        gen = D.SyntheticMultiTask(
            args.batch_size, num_cat=8, num_dense=4, vocab=args.vocab,
            seed=args.seed,
        )
    elif kind == "behavior":
        gen = D.SyntheticBehaviorSequence(
            args.batch_size, vocab=args.vocab, seed=args.seed
        )
    elif kind == "twotower":
        gen = D.SyntheticTwoTower(args.batch_size, vocab=args.vocab,
                                  seed=args.seed)
    else:
        raise ValueError(kind)
    return iter(gen)


def _retable(model, **cfg_overrides):
    """Rewrite every sparse feature's TableConfig (bf16 values, kernel
    choice) — one hook instead of plumbing flags through every model."""
    import dataclasses

    from deeprec_tpu.features import SparseFeature

    model.features = [
        dataclasses.replace(
            f, table=dataclasses.replace(f.table, **cfg_overrides)
        )
        if isinstance(f, SparseFeature) and f.table is not None
        else f
        for f in model.features
    ]
    return model


def run(model, args, data_kind: str) -> Dict[str, float]:
    """The MonitoredTrainingSession loop: train, log steps/sec, eval AUC,
    checkpoint (full + incremental)."""
    import jax
    import jax.numpy as jnp

    from deeprec_tpu.training import Trainer
    from deeprec_tpu.training.checkpoint import CheckpointManager

    overrides = {}
    if args.bf16:
        overrides["value_dtype"] = "bfloat16"
    if args.kernel != "auto":
        overrides["kernel"] = args.kernel
    if overrides:
        model = _retable(model, **overrides)

    sparse_opt, dense_opt = make_optimizers(args)
    if args.sharded:
        from deeprec_tpu.parallel import ShardedTrainer, make_mesh

        mesh = make_mesh()
        trainer = ShardedTrainer(model, sparse_opt, dense_opt, mesh=mesh,
                                 comm=args.comm)
    else:
        trainer = Trainer(model, sparse_opt, dense_opt)
    state = trainer.init(args.seed)
    # data FIRST: make_data registers input-state carriers (WorkQueue,
    # CriteoStats) in args._datasets, which the CheckpointManager must
    # know about BEFORE restore() so stream positions rewind with the
    # model. Staging starts strictly AFTER restore: the prefetch ring
    # pulls ahead the moment it exists, and batches queued pre-restore
    # would replay data the checkpointed run already trained on.
    raw_data = make_data(args, data_kind)
    ck = None
    if args.checkpoint:
        ck = CheckpointManager(args.checkpoint, trainer,
                               datasets=getattr(args, "_datasets", None))
        try:
            state = ck.restore()
            print(f"restored from step {int(state.step)}")
        except FileNotFoundError:
            pass
    # Auto-stage (SmartStage analog): the trainer derives the staged
    # boundary from the model's input signature — IO, key filtering and
    # the (mesh-aware) host->device transfer overlap the train step with
    # zero manual staged() calls here or in make_data. Batches from
    # `data` are device-ready; only out-of-band eval batches need the
    # explicit stage_batch call.
    # Stream-position carriers track the CONSUMED index through the staging
    # ring (depth-2 prefetch runs the producer ahead; checkpoints must
    # record what the train loop actually received).
    marks = []
    for d in getattr(args, "_datasets", {}).values():
        if hasattr(d, "mark_consumed"):
            marks.append(d.mark_consumed)
            if hasattr(d, "attach_consumer"):
                # flip to consumed-position checkpointing BEFORE the ring's
                # producer runs ahead (a save prior to the first delivery
                # must not report the producer index)
                d.attach_consumer()
    on_consume = (lambda: [m() for m in marks]) if marks else None
    data = trainer.stage(raw_data, on_consume=on_consume)
    eval_src = getattr(args, "_eval_iter", None)
    eval_batches = [
        trainer.stage_batch(next(eval_src)) if eval_src else next(iter(data))
        for _ in range(args.eval_batches)
    ]

    tracer = None
    if args.timeline:
        from deeprec_tpu.training.profiler import StepWindowTracer

        tracer = StepWindowTracer(args.timeline, args.timeline + 10,
                                  args.timeline_dir)
    mlog = None
    if args.metrics_file:
        from deeprec_tpu.training.logging import MetricsLogger

        mlog = MetricsLogger(args.metrics_file)

    t0 = time.perf_counter()
    window_start = int(state.step)
    last_metrics = {}
    for batch in data:
        step = int(state.step)
        if step >= args.steps:
            break
        if tracer:
            tracer.on_step(step)
        if args.micro_batch > 1:
            state, mets = trainer.train_step_accum(
                state, batch, args.micro_batch
            )
        else:
            state, mets = trainer.train_step(state, batch)
        step += 1
        if step % args.log_every == 0:
            jax.block_until_ready(mets["loss"])
            dt = time.perf_counter() - t0
            sps = (step - window_start) / max(dt, 1e-9)
            print(
                f"step {step} loss {float(mets['loss']):.5f} "
                f"global_step/sec: {sps:.2f}",
                flush=True,
            )
            if mlog:
                mlog.log(step, loss=mets["loss"], steps_per_sec=sps)
            t0 = time.perf_counter()
            window_start = step
        if args.eval_every and step % args.eval_every == 0:
            ev = trainer.evaluate(state, eval_batches)
            for k, v in ev.items():
                if k.startswith("auc"):
                    print(f"Eval AUC: {v:.6f} ({k})", flush=True)
            last_metrics = ev
            t0 = time.perf_counter()
            window_start = step
        if args.evict_every and step % args.evict_every == 0:
            state = trainer.evict_tables(state)
        if args.maintain_every and step % args.maintain_every == 0:
            state, report = trainer.maintain(
                state,
                hbm_budget_bytes=args.hbm_budget_mb << 20 or None,
            )
            acted = {
                bn: r for bn, r in report.items()
                if "grew_to" in r or r.get("demoted") or r.get("auto_tiered")
            }
            if acted:
                print(f"maintain: {acted}", flush=True)
        if ck and args.save_steps and step % args.save_steps == 0:
            state = trainer.evict_tables(state)  # evict at ckpt time (ref cadence)
            state, path = ck.save(state)
            print(f"saved full checkpoint: {path}", flush=True)
        elif (
            ck
            and args.incremental_save_steps
            and step % args.incremental_save_steps == 0
        ):
            state, path = ck.save_incremental(state)
            print(f"saved incremental checkpoint: {path}", flush=True)

    if tracer:
        tracer.close()
    ev = trainer.evaluate(state, eval_batches)
    for k, v in ev.items():
        if k.startswith("auc"):
            print(f"Eval AUC: {v:.6f} ({k})", flush=True)
    if ck:
        state, path = ck.save(state)
        print(f"saved final checkpoint: {path}", flush=True)
    return ev


def main(name: str, model_fn: Callable, data_kind: str, argv=None,
         defaults: Optional[Dict] = None):
    """defaults: per-model argparse default overrides (the reference's
    per-model train.py files hard-code model-appropriate vocab/lr the same
    way)."""
    p = build_argparser(name)
    if defaults:
        p.set_defaults(**defaults)
    args = p.parse_args(argv)
    model = model_fn(args)
    return run(model, args, data_kind)
