"""DCN (v1, vector-weight cross net) on Criteo (reference: modelzoo/dcn)."""
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from common import ev_option, main


def model_fn(args):
    from deeprec_tpu.models import DCN

    return DCN(emb_dim=args.emb_dim, capacity=args.capacity, ev=ev_option(args))


if __name__ == "__main__":
    main("dcn", model_fn, "criteo")
