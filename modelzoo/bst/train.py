"""BST on user-behavior sequences (reference: modelzoo/bst)."""
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from common import ev_option, main


def model_fn(args):
    from deeprec_tpu.models import BST

    return BST(emb_dim=args.emb_dim, capacity=args.capacity, ev=ev_option(args))


if __name__ == "__main__":
    main("bst", model_fn, "behavior",
         defaults={"vocab": 100_000, "learning_rate": 0.2})
