"""Multi-step device loop (`train_steps`): K steps per dispatch via
`lax.scan` must be SEMANTICALLY IDENTICAL to K sequential `train_step`
calls — dense params and optimizer state allclose, hash-table state
(keys, freq, version) exact — including windows where new ids are
inserted mid-window, for Trainer, ShardedTrainer and the async stage."""
import jax
import jax.numpy as jnp
import numpy as np
import optax

from deeprec_tpu.data import SyntheticCriteo
from deeprec_tpu.models import WDL
from deeprec_tpu.optim import Adagrad
from deeprec_tpu.training import Trainer, stack_batches


def J(b):
    return {k: jnp.asarray(v) for k, v in b.items()}


def model():
    return WDL(emb_dim=8, capacity=1 << 12, hidden=(16,), num_cat=4,
               num_dense=2)


def window_batches(K=4, batch_size=64, seed=7):
    """K batches where later batches introduce ids no earlier batch held,
    so the scan body's insert path is exercised mid-window."""
    gen = SyntheticCriteo(batch_size=batch_size, num_cat=4, num_dense=2,
                          vocab=500, seed=seed)
    batches = [J(gen.batch()) for _ in range(K)]
    for t in range(1, K):
        # fresh id range per step: vocab*t offset guarantees first-seen ids
        batches[t]["C1"] = batches[t]["C1"] + jnp.int32(10_000 * t)
    return batches


def assert_tables_equal(tr, s_scan, s_seq):
    for bname in s_scan.tables:
        a, b = s_scan.tables[bname], s_seq.tables[bname]
        np.testing.assert_array_equal(np.asarray(a.keys), np.asarray(b.keys))
        np.testing.assert_array_equal(np.asarray(a.freq), np.asarray(b.freq))
        np.testing.assert_array_equal(
            np.asarray(a.version), np.asarray(b.version)
        )
        np.testing.assert_allclose(
            np.asarray(a.values), np.asarray(b.values), atol=1e-5
        )


def assert_dense_equal(s_scan, s_seq, atol=1e-5):
    for a, b in zip(jax.tree.leaves(s_scan.dense), jax.tree.leaves(s_seq.dense)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=atol)
    for a, b in zip(
        jax.tree.leaves(s_scan.opt_state), jax.tree.leaves(s_seq.opt_state)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=atol)


def test_train_steps_matches_sequential():
    K = 4
    batches = window_batches(K)
    tr = Trainer(model(), Adagrad(lr=0.1), optax.adam(2e-3))

    s_seq = tr.init(0)
    seq_losses = []
    for b in batches:
        s_seq, m = tr.train_step(s_seq, b)
        seq_losses.append(float(m["loss"]))

    s_scan, mets = tr.train_steps(tr.init(0), batches)
    # per-step metric stacks: one entry per inner step, same values
    assert mets["loss"].shape == (K,)
    np.testing.assert_allclose(np.asarray(mets["loss"]), seq_losses, atol=1e-5)
    assert int(s_scan.step) == K == int(s_seq.step)
    assert_tables_equal(tr, s_scan, s_seq)
    assert_dense_equal(s_scan, s_seq)


def test_train_steps_takes_stacked_pytree():
    batches = window_batches(3)
    tr = Trainer(model(), Adagrad(lr=0.1))
    stacked = stack_batches(batches)
    s1, m1 = tr.train_steps(tr.init(0), stacked)
    s2, m2 = tr.train_steps(tr.init(0), batches)
    np.testing.assert_array_equal(np.asarray(m1["loss"]), np.asarray(m2["loss"]))
    assert int(s1.step) == 3


def test_train_steps_inserts_new_ids_mid_window():
    """Ids first seen at inner step t>0 must land in the table with
    freq/version bookkeeping identical to the sequential path."""
    batches = window_batches(4)
    tr = Trainer(model(), Adagrad(lr=0.1))
    s_scan, _ = tr.train_steps(tr.init(0), batches)
    # the offset ids from the last batch are present in the final state
    ts = tr.table_state(s_scan, "C1")
    keys = np.asarray(ts.keys)
    last_ids = np.asarray(batches[3]["C1"]).ravel()
    assert np.isin(last_ids, keys).all()
    # and their version stamp is the step they arrived at (3), not 0
    occupied = {int(k): int(v) for k, v in zip(keys, np.asarray(ts.version))}
    assert all(occupied[int(i)] == 3 for i in last_ids)


def test_sharded_train_steps_matches_sequential():
    from deeprec_tpu.parallel import ShardedTrainer, make_mesh, shard_batch

    K = 3
    mesh = make_mesh(8)
    tr = ShardedTrainer(model(), Adagrad(lr=0.1), optax.adam(2e-3), mesh=mesh)
    batches = [
        shard_batch(mesh, b) for b in window_batches(K, batch_size=64, seed=9)
    ]

    s_seq = tr.init(0)
    seq_losses = []
    for b in batches:
        s_seq, m = tr.train_step(s_seq, b)
        seq_losses.append(float(m["loss"]))

    s_scan, mets = tr.train_steps(tr.init(0), batches)
    assert mets["loss"].shape == (K,)
    np.testing.assert_allclose(np.asarray(mets["loss"]), seq_losses, atol=1e-5)
    assert int(s_scan.step) == K
    assert_tables_equal(tr, s_scan, s_seq)
    assert_dense_equal(s_scan, s_seq)


def test_sharded_train_steps_a2a_comm():
    """The scan body reuses _sharded_step's exchange — including the
    budgeted all2all path."""
    from deeprec_tpu.parallel import ShardedTrainer, make_mesh, shard_batch

    mesh = make_mesh(8)
    tr = ShardedTrainer(model(), Adagrad(lr=0.1), mesh=mesh, comm="a2a")
    batches = [
        shard_batch(mesh, b) for b in window_batches(3, batch_size=64, seed=2)
    ]
    s_seq = tr.init(0)
    for b in batches:
        s_seq, _ = tr.train_step(s_seq, b)
    s_scan, mets = tr.train_steps(tr.init(0), batches)
    assert mets["loss"].shape == (3,)
    assert_tables_equal(tr, s_scan, s_seq)


def test_async_train_steps_matches_sequential():
    """K inner async steps per dispatch keep the stale-by-one pipeline
    semantics of K sequential train_step_async calls."""
    from deeprec_tpu.parallel import AsyncShardedTrainer, make_mesh, shard_batch

    K = 3
    mesh = make_mesh(8)
    tr = AsyncShardedTrainer(model(), Adagrad(lr=0.1), optax.adam(2e-3),
                             mesh=mesh)
    batches = [
        shard_batch(mesh, b) for b in window_batches(K + 1, seed=11)
    ]

    a_seq = tr.bootstrap(tr.init(0), batches[0])
    seq_losses = []
    for b in batches[1:]:
        a_seq, m = tr.train_step_async(a_seq, b)
        seq_losses.append(float(m["loss"]))

    a_scan = tr.bootstrap(tr.init(0), batches[0])
    a_scan, mets = tr.train_steps_async(a_scan, batches[1:])
    assert mets["loss"].shape == (K,)
    np.testing.assert_allclose(np.asarray(mets["loss"]), seq_losses, atol=1e-5)
    assert int(a_scan.inner.step) == K == int(a_seq.inner.step)
    assert_tables_equal(tr, a_scan.inner, a_seq.inner)
    assert_dense_equal(a_scan.inner, a_seq.inner)


def test_train_steps_then_maintain_boundary():
    """Host-side table maintenance composes at K-step boundaries: a grown
    table recompiles the K-path once and training continues."""
    batches = window_batches(4, batch_size=64, seed=13)
    tr = Trainer(model(), Adagrad(lr=0.1))
    st, _ = tr.train_steps(tr.init(0), batches[:2])
    st, report = tr.maintain(st)
    st, mets = tr.train_steps(st, batches[2:])
    assert int(st.step) == 4
    assert np.isfinite(np.asarray(mets["loss"])).all()
