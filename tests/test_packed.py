"""Packed small-dim layout (ops/packed.py): oracle tests.

The packed layout is the round-4 answer to "the headline DLRM shape
(dim 16) is ineligible for every Pallas kernel": P = 128/dim rows ride one
128-lane granule, so granule gathers/scatters reuse the measured dim-128
kernels. These tests pin the layout algebra (pack/unpack round-trip), the
gather/scatter semantics against the unpacked oracle (XLA path on CPU and
the Pallas branch in interpret mode), and the end-to-end table behavior at
dim 16 — the flagship shape.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeprec_tpu.ops.packed import (
    gather_rows_any,
    pack_array,
    pack_factor,
    row_factor,
    scatter_rows_any,
    unpack_array,
)


def test_pack_factor_rules():
    assert pack_factor(16, 1024) == 8
    assert pack_factor(1, 1024) == 128
    assert pack_factor(32, 1024) == 4
    assert pack_factor(128, 1024) == 1  # already lane-sized
    assert pack_factor(48, 1024) == 1  # does not divide 128
    assert pack_factor(16, 100) == 1  # capacity not a granule multiple
    assert pack_factor(128, 64) == 1
    # capacity smaller than the would-be factor
    assert pack_factor(1, 64) == 1


def test_pack_unpack_roundtrip_and_row_factor():
    C, D = 64, 16
    arr = jnp.arange(C * D, dtype=jnp.float32).reshape(C, D)
    p = pack_factor(D, C)
    packed = pack_array(arr, p)
    assert packed.shape == (C // p, p * D)
    assert row_factor(packed, C) == p
    assert row_factor(arr, C) == 1
    np.testing.assert_array_equal(unpack_array(packed, C), arr)
    # numpy unpack is a free view of the same row-major data
    np_packed = np.asarray(packed)
    np.testing.assert_array_equal(
        unpack_array(np_packed, C), np.asarray(arr)
    )


@pytest.mark.parametrize("use_pallas", [False, True])
def test_gather_packed_matches_oracle(use_pallas):
    C, D = 64, 16
    rng = np.random.RandomState(0)
    logical = jnp.asarray(rng.randn(C, D).astype(np.float32))
    packed = pack_array(logical, pack_factor(D, C))
    ix = jnp.asarray([0, 1, 7, 8, 9, 63, 62, 5, 5, 0], jnp.int32)
    out = gather_rows_any(packed, ix, C, use_pallas=use_pallas,
                          interpret=use_pallas)
    np.testing.assert_allclose(out, logical[ix], rtol=0, atol=0)


def test_gather_packed_clips_out_of_range():
    C, D = 32, 32
    logical = jnp.arange(C * D, dtype=jnp.float32).reshape(C, D)
    packed = pack_array(logical, pack_factor(D, C))
    ix = jnp.asarray([-3, C + 5, C - 1], jnp.int32)
    out = gather_rows_any(packed, ix, C)
    np.testing.assert_array_equal(out[0], logical[0])
    np.testing.assert_array_equal(out[1], logical[C - 1])
    np.testing.assert_array_equal(out[2], logical[C - 1])


@pytest.mark.parametrize("use_pallas", [False, True])
def test_scatter_packed_matches_oracle(use_pallas):
    """Updates hitting several rows of the same granule plus skips."""
    C, D = 64, 16
    rng = np.random.RandomState(1)
    logical = jnp.asarray(rng.randn(C, D).astype(np.float32))
    packed = pack_array(logical, pack_factor(D, C))
    slot_ix = jnp.asarray([0, 1, 2, 9, -1, 63], jnp.int32)  # 0..2 share g0
    rows = jnp.asarray(rng.randn(6, D).astype(np.float32))
    out = scatter_rows_any(packed, slot_ix, rows, C, seed=3,
                           use_pallas=use_pallas, interpret=use_pallas)
    expect = np.array(logical)
    for i, s in enumerate([0, 1, 2, 9, -1, 63]):
        if s >= 0:
            expect[s] = np.asarray(rows[i])
    np.testing.assert_allclose(unpack_array(out, C), expect, rtol=0, atol=0)


def test_scatter_packed_all_skipped_is_noop():
    C, D = 32, 16
    logical = jnp.ones((C, D), jnp.float32)
    packed = pack_array(logical, pack_factor(D, C))
    out = scatter_rows_any(
        packed, jnp.full((4,), -1, jnp.int32), jnp.zeros((4, D)), C
    )
    np.testing.assert_array_equal(out, packed)


def test_scatter_packed_bf16_preserves_untouched_lanes():
    """The SR-identity property the merge relies on: granule-mates of an
    updated row come back bit-identical."""
    C, D = 64, 16
    rng = np.random.RandomState(2)
    logical = jnp.asarray(rng.randn(C, D).astype(np.float32)).astype(
        jnp.bfloat16
    )
    packed = pack_array(logical, pack_factor(D, C))
    # update row 3 only; rows 0-7 share its granule
    out = scatter_rows_any(packed, jnp.asarray([3], jnp.int32),
                           jnp.full((1, D), 0.123, jnp.float32), C, seed=11)
    got = unpack_array(out, C)
    for r in [0, 1, 2, 4, 5, 6, 7, 8]:
        np.testing.assert_array_equal(
            np.asarray(got[r]), np.asarray(logical[r])
        )
    # the updated row is a stochastic rounding of 0.123 (one of the two
    # bf16 truncation neighbors, never something else)
    up = np.asarray(got[3].astype(jnp.float32))
    u = np.float32(0.123).view(np.uint32) & np.uint32(0xFFFF0000)
    lo = u.view(np.float32)
    hi = (u + np.uint32(0x10000)).view(np.float32)
    assert all(v in (lo, hi) for v in up), (up, lo, hi)


def test_scatter_packed_width1():
    """[C, 1] per-row slots pack 128 rows per granule."""
    C = 256
    logical = jnp.zeros((C, 1), jnp.float32)
    p = pack_factor(1, C)
    assert p == 128
    packed = pack_array(logical, p)
    assert packed.shape == (2, 128)
    slot_ix = jnp.asarray([0, 127, 128, 255, 7], jnp.int32)
    rows = jnp.asarray([[1.0], [2.0], [3.0], [4.0], [5.0]], jnp.float32)
    out = scatter_rows_any(packed, slot_ix, rows, C)
    got = unpack_array(out, C)
    for s, v in zip([0, 127, 128, 255, 7], [1, 2, 3, 4, 5]):
        assert float(got[s, 0]) == v
    back = gather_rows_any(out, slot_ix, C)
    np.testing.assert_array_equal(back, rows)


def test_packed_knob_resolution():
    """cfg.packed gates the layout: "auto" is backend-dependent (unpacked
    off-TPU — packing measured -36% train throughput on CPU, BENCH_r04 vs
    r03), "on"/"off" force it. Slots follow the same policy."""
    from deeprec_tpu.config import TableConfig
    from deeprec_tpu.embedding.table import EmbeddingTable, _backend_is_tpu
    from deeprec_tpu.optim.apply import ensure_slots
    from deeprec_tpu.optim.sparse import Adagrad

    on = EmbeddingTable(TableConfig(name="a", dim=16, capacity=256,
                                    packed="on"))
    off = EmbeddingTable(TableConfig(name="b", dim=16, capacity=256,
                                     packed="off"))
    auto = EmbeddingTable(TableConfig(name="c", dim=16, capacity=256))
    assert auto.cfg.packed == "auto"
    assert on.pack() == 8
    assert off.pack() == 1
    # tests run with JAX_PLATFORMS=cpu (conftest) -> auto stays unpacked
    assert auto.pack() == (8 if _backend_is_tpu() else 1)
    assert not _backend_is_tpu()

    s_on, s_off = on.create(), off.create()
    assert s_on.values.shape == (32, 128)
    assert s_off.values.shape == (256, 16)
    # layout is invisible to semantics: same lookups, same rows
    ids = jnp.asarray([5, 9, 700, 12], jnp.int32)
    s_on, r_on = on.lookup_unique(s_on, ids, step=1)
    s_off, r_off = off.lookup_unique(s_off, ids, step=1)
    assert r_on.embeddings.shape == r_off.embeddings.shape == (4, 16)
    # slot layout follows the knob too
    s_on = ensure_slots(on, s_on, Adagrad(lr=0.1))
    s_off = ensure_slots(off, s_off, Adagrad(lr=0.1))
    assert s_on.slots["accum"].shape == (32, 128)
    assert s_off.slots["accum"].shape == (256, 16)

    with pytest.raises(ValueError):
        TableConfig(name="x", dim=16, capacity=256, packed="maybe")


def test_packed_off_grow_stays_unpacked():
    from deeprec_tpu.config import TableConfig
    from deeprec_tpu.embedding.table import EmbeddingTable

    t = EmbeddingTable(TableConfig(name="g0", dim=16, capacity=64,
                                   packed="off"))
    s = t.create()
    ids = jnp.arange(10, dtype=jnp.int32) * 3 + 1
    s, res = t.lookup_unique(s, ids, step=1)
    grown = t.grow(s, 256)
    assert grown.values.shape == (256, 16)
    np.testing.assert_allclose(
        np.asarray(t.lookup_readonly(grown, ids)),
        np.asarray(res.embeddings)[np.asarray(res.inverse)],
        rtol=0, atol=0,
    )


def test_table_dim16_end_to_end_packed():
    """The flagship shape: a dim-16 table stores packed and trains."""
    from deeprec_tpu.config import TableConfig
    from deeprec_tpu.embedding.table import EmbeddingTable
    from deeprec_tpu.optim.apply import apply_gradients, ensure_slots
    from deeprec_tpu.optim.sparse import Adagrad

    cfg = TableConfig(name="pk", dim=16, capacity=256, packed="on")
    t = EmbeddingTable(cfg)
    assert t.pack() == 8
    s = t.create()
    assert s.values.shape == (32, 128)
    assert s.dim == 16 and s.capacity == 256

    ids = jnp.asarray([5, 9, 5, 1000, 77], jnp.int32)
    s, res = t.lookup_unique(s, ids, step=1)
    assert res.embeddings.shape[1] == 16
    # deterministic initializer: same ids re-looked-up give same rows
    s2, res2 = t.lookup_unique(s, ids, step=2)
    np.testing.assert_allclose(
        np.asarray(res.embeddings), np.asarray(res2.embeddings),
        rtol=0, atol=0,
    )

    opt = Adagrad(lr=0.1)
    s2 = ensure_slots(t, s2, opt)
    assert s2.slots["accum"].shape == (32, 128)  # packed slot too
    g = jnp.ones_like(res2.embeddings)
    s3 = apply_gradients(t, s2, opt, res2, g, step=2)
    s3, res3 = t.lookup_unique(s3, ids, step=3)
    # the update moved every looked-up row
    assert not np.allclose(
        np.asarray(res3.embeddings), np.asarray(res2.embeddings)
    )


def test_table_dim16_checkpoint_roundtrip_packed():
    """Checkpoint format stays LOGICAL rows: export from a packed table,
    import into a fresh one, values identical."""
    from deeprec_tpu.config import TableConfig
    from deeprec_tpu.embedding.table import EmbeddingTable
    from deeprec_tpu.training.checkpoint import (
        _state_to_np,
        export_table_arrays,
        import_rows,
    )

    cfg = TableConfig(name="ck", dim=16, capacity=256, packed="on")
    t = EmbeddingTable(cfg)
    s = t.create()
    ids = jnp.asarray([3, 14, 159, 26, 535], jnp.int32)
    s, res = t.lookup_unique(s, ids, step=7)

    out = export_table_arrays(t, _state_to_np(s), only_dirty=False)
    assert out["values"].shape[1] == 16  # logical rows on disk
    assert out["keys"].shape[0] == 5

    fresh = t.create()
    fresh = import_rows(t, fresh, out)
    emb = t.lookup_readonly(fresh, ids)
    # res.embeddings is in unique-id order; map back to ids order
    expect = np.asarray(res.embeddings)[np.asarray(res.inverse)]
    np.testing.assert_allclose(np.asarray(emb), expect, rtol=0, atol=1e-7)


def test_table_rebuild_grow_packed():
    """Rebuild/grow relocates logical rows across a layout change."""
    from deeprec_tpu.config import TableConfig
    from deeprec_tpu.embedding.table import EmbeddingTable

    cfg = TableConfig(name="gr", dim=16, capacity=64, packed="on")
    t = EmbeddingTable(cfg)
    s = t.create()
    ids = jnp.arange(20, dtype=jnp.int32) * 7 + 1
    s, res = t.lookup_unique(s, ids, step=1)
    before = np.asarray(res.embeddings)

    grown = t.grow(s, 256)
    assert grown.capacity == 256
    # pack factor is per-capacity: 64/8=8 granules before, 32 after
    assert grown.values.shape == (32, 128)
    emb = t.lookup_readonly(grown, ids)
    np.testing.assert_allclose(np.asarray(emb), before, rtol=0, atol=0)
