"""Multi-tier (HBM + host DRAM) storage tests — HbmDramStorage semantics
(reference embedding_variable_ops_test.cc multi-tier cases)."""
import os

import jax.numpy as jnp
import numpy as np

from deeprec_tpu import EmbeddingTable, EmbeddingVariableOption, StorageOption, TableConfig
from deeprec_tpu.config import StorageType
from deeprec_tpu.embedding.multi_tier import MultiTierTable
from deeprec_tpu.ops.packed import scatter_rows_any, unpack_array


def make(capacity=64, strategy="lfu"):
    cfg = TableConfig(
        name="mt",
        dim=4,
        capacity=capacity,
        ev=EmbeddingVariableOption(
            storage=StorageOption(storage_type=StorageType.HBM_DRAM,
                                  cache_strategy=strategy)
        ),
    )
    t = EmbeddingTable(cfg)
    return t, MultiTierTable(t, high_watermark=0.75, low_watermark=0.5)


def test_demotion_on_pressure_and_fallback_serving():
    t, mt = make()
    s = t.create()
    # fill beyond the high watermark (48/64); hot ids looked up many times
    hot = jnp.arange(10, dtype=jnp.int32)
    for _ in range(5):
        s, _ = t.lookup_unique(s, hot, step=1)
    cold = jnp.arange(10, 52, dtype=jnp.int32)
    s, _ = t.lookup_unique(s, cold, step=2)

    s, stats = mt.sync(s, step=3)
    assert stats.demoted > 0
    assert stats.device_size <= 32  # low watermark
    assert stats.host_size == stats.demoted
    # hot keys survive on device (LFU)
    for k in range(10):
        assert np.abs(np.asarray(t.lookup_readonly(s, jnp.array([k], jnp.int32)))).max() > 0
    # demoted keys still servable through the fallback path
    emb = mt.lookup_with_fallback(s, jnp.arange(52, dtype=jnp.int32))
    assert np.isfinite(np.asarray(emb)).all()


def test_promotion_restores_values():
    t, mt = make()
    s = t.create()
    ids = jnp.arange(52, dtype=jnp.int32)
    s, res = t.lookup_unique(s, ids, step=0)
    # write recognizable values then force demotion
    marked = jnp.full_like(res.embeddings, 3.25)
    s = t.scatter_update(s, res.slot_ix, marked, mask=res.valid)
    s, stats = mt.sync(s, step=1)
    assert stats.demoted > 0
    host_before = stats.host_size

    # demoted key 0..? — find one demoted id
    demoted = [
        k for k in range(52)
        if np.abs(np.asarray(t.lookup_readonly(s, jnp.array([k], jnp.int32)))).max() < 3
    ]
    assert demoted
    k = demoted[0]
    # key comes back: device re-creates it with init values...
    s, _ = t.lookup_unique(s, jnp.array([k], jnp.int32), step=2)
    # ...and sync promotes the host row back
    s, stats2 = mt.sync(s, step=3)
    assert stats2.promoted >= 1
    emb = np.asarray(t.lookup_readonly(s, jnp.array([k], jnp.int32)))
    np.testing.assert_allclose(emb[0], 3.25, rtol=1e-6)
    assert stats2.host_size < host_before  # host copy dropped after promote


def test_demote_rebuild_restores_slot_init_values():
    """Freed per-key optimizer slots after a demotion rebuild hold the
    optimizer's INIT value (Adagrad 0.1), not 0 — same defect class the
    evict() path guards against (a 0 accumulator rsqrt's to a wrong-scale
    first update for keys later born in that slot)."""
    from deeprec_tpu.optim import Adagrad
    from deeprec_tpu.optim.apply import ensure_slots

    t, _ = make()
    opt = Adagrad(lr=0.1, initial_accumulator_value=0.1)
    fills = tuple(
        (name, init) for name, (_, init) in opt.slot_specs(t.cfg.dim).items()
    )
    mt = MultiTierTable(t, high_watermark=0.75, low_watermark=0.5,
                        slot_fills=fills)
    s = ensure_slots(t, t.create(), opt)
    s, _ = t.lookup_unique(s, jnp.arange(52, dtype=jnp.int32), step=0)
    s, stats = mt.sync(s, step=1)
    assert stats.demoted > 0
    occ = np.asarray(t.occupied(s))
    acc = unpack_array(np.asarray(s.slots["accum"]), s.capacity)
    assert (~occ).any()
    np.testing.assert_allclose(acc[~occ], 0.1)


def test_grow_restores_slot_init_values():
    from deeprec_tpu.optim import Adagrad
    from deeprec_tpu.optim.apply import ensure_slots

    t, _ = make(capacity=32)
    opt = Adagrad(lr=0.1, initial_accumulator_value=0.1)
    fills = tuple(
        (name, init) for name, (_, init) in opt.slot_specs(t.cfg.dim).items()
    )
    s = ensure_slots(t, t.create(), opt)
    s, _ = t.lookup_unique(s, jnp.arange(20, dtype=jnp.int32), step=0)
    s2 = t.grow(s, 128, slot_fills=fills)
    occ = np.asarray(t.occupied(s2))
    acc = unpack_array(np.asarray(s2.slots["accum"]), s2.capacity)
    np.testing.assert_allclose(acc[~occ], 0.1)
    assert int(t.size(s2)) == 20


def make_3tier(tmp_path, capacity=64, host_capacity=16):
    cfg = TableConfig(
        name="mt3",
        dim=4,
        capacity=capacity,
        ev=EmbeddingVariableOption(
            storage=StorageOption(
                storage_type=StorageType.HBM_DRAM_SSD,
                storage_path=str(tmp_path / "tier"),
                host_capacity=host_capacity,
            )
        ),
    )
    t = EmbeddingTable(cfg)
    return t, MultiTierTable(t, high_watermark=0.75, low_watermark=0.5)


def test_three_tier_spills_host_overflow_to_disk(tmp_path):
    """HBM_DRAM_SSD: demotions beyond the host capacity spill the coldest
    rows to the log-structured disk tier; all three tiers stay servable
    through lookup_with_fallback."""
    t, mt = make_3tier(tmp_path)
    s = t.create()
    # mark every row so tier round-trips are checkable
    ids = jnp.arange(52, dtype=jnp.int32)
    s, res = t.lookup_unique(s, ids, step=0)
    s = t.scatter_update(
        s, res.slot_ix,
        jnp.broadcast_to(
            (jnp.asarray(res.uids, jnp.float32) + 1.0)[:, None],
            res.embeddings.shape,
        ),
        mask=res.valid,
    )
    s, stats = mt.sync(s, step=1)
    assert stats.demoted > 0
    assert stats.spilled > 0, stats
    assert stats.host_size <= 16
    assert stats.disk_size == stats.spilled
    # every original id still serves its written value from SOME tier
    emb = np.asarray(mt.lookup_with_fallback(s, ids))
    np.testing.assert_allclose(emb[:, 0], np.arange(52) + 1.0, rtol=1e-6)


def test_three_tier_promotes_from_disk(tmp_path):
    t, mt = make_3tier(tmp_path)
    s = t.create()
    ids = jnp.arange(52, dtype=jnp.int32)
    s, res = t.lookup_unique(s, ids, step=0)
    s = t.scatter_update(s, res.slot_ix,
                         jnp.full_like(res.embeddings, 7.5), mask=res.valid)
    s, stats = mt.sync(s, step=1)
    assert stats.spilled > 0
    # find a disk-resident key, touch it on device, sync -> promoted back
    disk_key = int(next(iter(mt.disk.index)))
    s, _ = t.lookup_unique(s, jnp.asarray([disk_key], jnp.int32), step=2)
    s, stats2 = mt.sync(s, step=3)
    assert stats2.promoted >= 1
    emb = np.asarray(t.lookup_readonly(s, jnp.asarray([disk_key], jnp.int32)))
    np.testing.assert_allclose(emb[0], 7.5, rtol=1e-6)
    assert disk_key not in mt.disk.index  # disk record consumed


def test_disk_kv_persistence(tmp_path):
    from deeprec_tpu.embedding.multi_tier import DiskKV

    p = str(tmp_path / "store.ssd")
    d = DiskKV(p, dim=3)
    d.put(np.asarray([1, 2, 3], np.int64), np.eye(3, dtype=np.float32),
          np.asarray([5, 6, 7], np.int32), np.asarray([1, 1, 1], np.int32))
    d.put(np.asarray([2], np.int64),  # update: append + repoint
          np.full((1, 3), 9.0, np.float32))
    d.close()
    d2 = DiskKV(p, dim=3)  # reopen via index sidecar
    vals, freqs, _, found = d2.get(np.asarray([1, 2, 3, 4], np.int64))
    assert found.tolist() == [True, True, True, False]
    np.testing.assert_allclose(vals[1], 9.0)  # latest record wins
    assert freqs[0] == 5
    os.remove(p + ".idx")
    d3 = DiskKV(p, dim=3)  # reopen via log scan
    vals3, _, _, found3 = d3.get(np.asarray([2], np.int64))
    assert found3[0] and vals3[0, 0] == 9.0

    # crash semantics: records appended AFTER the last save() must survive
    # a reopen (the sidecar records the log length; the tail is scanned)
    d3.save()
    d3.put(np.asarray([2], np.int64), np.full((1, 3), 11.0, np.float32))
    d3.put(np.asarray([9], np.int64), np.full((1, 3), 4.0, np.float32))
    d3._f.flush()  # simulate SIGKILL: no save()/close()
    d4 = DiskKV(p, dim=3)
    vals4, _, _, found4 = d4.get(np.asarray([2, 9], np.int64))
    assert found4.all()
    np.testing.assert_allclose(vals4[:, 0], [11.0, 4.0])


def test_spill_and_load(tmp_path):
    t, mt = make()
    s = t.create()
    s, _ = t.lookup_unique(s, jnp.arange(52, dtype=jnp.int32), step=0)
    s, stats = mt.sync(s, step=1)
    assert stats.host_size > 0
    p = str(tmp_path / "tier.bin")
    mt.spill(p)
    t2, mt2 = make()
    mt2.load(p)
    assert len(mt2.host) == stats.host_size


def test_demote_promote_preserves_optimizer_slots():
    """A demoted-then-promoted key resumes its Adagrad accumulator (host
    tier rows pack values + per-row slots, like DeepRec's DRAM tier
    storing full ValuePtrs — hbm_dram_storage.h), instead of restarting
    optimizer state at init."""
    from deeprec_tpu.optim import Adagrad
    from deeprec_tpu.optim.apply import ensure_slots

    t, _ = make()
    opt = Adagrad(lr=0.1, initial_accumulator_value=0.1)
    fills = tuple(
        (name, init) for name, (_, init) in opt.slot_specs(t.cfg.dim).items()
    )
    mt = MultiTierTable(t, high_watermark=0.75, low_watermark=0.5,
                        slot_fills=fills)
    s = ensure_slots(t, t.create(), opt)
    # touch 52 keys; give key 7 a DISTINCTIVE accumulator + value
    s, res = t.lookup_unique(s, jnp.arange(52, dtype=jnp.int32), step=0)
    keys = np.asarray(s.keys)
    slot7 = int(np.nonzero(keys == 7)[0][0])
    occ0 = np.asarray(t.occupied(s))
    D = t.cfg.dim
    put = jnp.asarray([slot7], jnp.int32)
    s = s.replace(
        values=scatter_rows_any(
            s.values, put, jnp.full((1, D), 2.5), s.capacity
        ),
        slots={
            **s.slots,
            "accum": scatter_rows_any(
                s.slots["accum"], put, jnp.full((1, D), 7.75), s.capacity
            ),
        },
    ).replace_meta(
        # make key 7 STRICTLY the coldest so LFU must demote it
        freq=jnp.where(jnp.asarray(occ0), 5, s.freq).at[slot7].set(1),
    )
    s, stats = mt.sync(s, step=1)
    assert stats.demoted > 0
    assert 7 not in set(np.asarray(s.keys)[np.asarray(t.occupied(s))].tolist())

    # key 7 comes back (fresh slot, init values/slots)...
    s, _ = t.lookup_unique(s, jnp.asarray([7], jnp.int32), step=2)
    s, stats2 = mt.sync(s, step=3)
    assert stats2.promoted >= 1
    keys = np.asarray(s.keys)
    occ = np.asarray(t.occupied(s))
    slot7 = int(np.nonzero((keys == 7) & occ)[0][0])
    # ...with its exact values AND accumulator restored
    np.testing.assert_allclose(
        unpack_array(np.asarray(s.values), s.capacity)[slot7], 2.5
    )
    np.testing.assert_allclose(
        unpack_array(np.asarray(s.slots["accum"]), s.capacity)[slot7], 7.75
    )


def test_diskkv_compaction_bounds_log(tmp_path):
    """Repeated updates to the same keys must not grow the log without
    bound: compaction rewrites live records once garbage dominates
    (reference ssd_hash_kv.h manages its record files the same way)."""
    from deeprec_tpu.embedding.multi_tier import DiskKV

    path = str(tmp_path / "log.ssd")
    kv = DiskKV(path, dim=4)
    keys = np.arange(256, dtype=np.int64)
    for round_ in range(16):  # 16x overwrite: 4096 records, 256 live
        kv.put(keys, np.full((256, 4), float(round_), np.float32),
               np.full(256, round_, np.int32), np.zeros(256, np.int32))
    total_recs = os.path.getsize(path) // kv.rec_bytes
    assert total_recs <= 2 * 256 + 256  # bounded, not 4096
    vals, freqs, _, found = kv.get(keys)
    assert found.all()
    np.testing.assert_allclose(vals, 15.0)  # latest round survives

    # erase-heavy workload compacts too (force): after dropping most keys
    kv.erase(keys[8:])
    kv.compact(force=True)
    assert os.path.getsize(path) // kv.rec_bytes == 8
    vals, _, _, found = kv.get(keys[:8])
    assert found.all() and np.allclose(vals, 15.0)

    # reopen after compaction: index rebuilds cleanly from the new log
    kv.save()
    kv.close()
    kv2 = DiskKV(path, dim=4)
    assert len(kv2) == 8
    vals, _, _, found = kv2.get(keys[:8])
    assert found.all() and np.allclose(vals, 15.0)


def test_fresh_instance_load_serves_all_tiers(tmp_path):
    """Serving flow: a FRESH MultiTierTable (no sync ever run) that
    load()s a prior run's spill serves host-tier AND disk-tier rows
    through lookup_with_fallback — the disk log reopens via its header's
    row width."""
    t, mt = make_3tier(tmp_path)
    s = t.create()
    ids = jnp.arange(52, dtype=jnp.int32)
    s, res = t.lookup_unique(s, ids, step=0)
    s = t.scatter_update(s, res.slot_ix,
                         jnp.full_like(res.embeddings, 4.5), mask=res.valid)
    s, stats = mt.sync(s, step=1)
    assert stats.demoted > 0 and stats.spilled > 0
    p = str(tmp_path / "host.spill")
    mt.spill(p)

    t2, mt2 = make_3tier(tmp_path)
    mt2.load(p)
    assert mt2.disk is not None and len(mt2.disk) == stats.spilled
    emb = np.asarray(mt2.lookup_with_fallback(s, ids))
    np.testing.assert_allclose(emb[:, 0], 4.5, rtol=1e-6)

    # load of a never-spilled path = empty tier, not an error
    t3, mt3 = make(capacity=64)[0], make(capacity=64)[1]
    mt3.load(str(tmp_path / "never_written.bin"))
    assert mt3.host is None


def test_reference_storage_type_names_resolve():
    """All 13 reference StorageType values — names AND proto field
    numbers (embedding/config.proto:5-27) — resolve to the TPU tiers, so
    DeepRec-written configs need no edits."""
    from deeprec_tpu import StorageOption
    from deeprec_tpu.config import StorageType as S

    expect = {
        "DEFAULT": S.HBM, "HBM": S.HBM, "DRAM": S.DRAM,
        "PMEM_MEMKIND": S.DRAM, "PMEM_LIBPMEM": S.DRAM,
        "SSDHASH": S.HBM_DRAM_SSD, "LEVELDB": S.HBM_DRAM_SSD,
        "DRAM_PMEM": S.HBM_DRAM, "DRAM_SSDHASH": S.HBM_DRAM_SSD,
        "HBM_DRAM": S.HBM_DRAM, "DRAM_LEVELDB": S.HBM_DRAM_SSD,
        "DRAM_PMEM_SSDHASH": S.HBM_DRAM_SSD,
        "HBM_DRAM_SSDHASH": S.HBM_DRAM_SSD,
    }
    for name, want in expect.items():
        assert S.from_reference(name) is want, name
        # StorageOption accepts the raw string too
        assert StorageOption(storage_type=name).storage_type is want
    # proto field NUMBERS (DeepRec's canonical config form) work too
    numbers = {0: S.HBM, 1: S.DRAM, 2: S.DRAM, 3: S.DRAM,
               4: S.HBM_DRAM_SSD, 5: S.HBM_DRAM_SSD, 6: S.HBM,
               11: S.HBM_DRAM, 12: S.HBM_DRAM_SSD, 13: S.HBM_DRAM,
               14: S.HBM_DRAM_SSD, 101: S.HBM_DRAM_SSD,
               102: S.HBM_DRAM_SSD}
    for num, want in numbers.items():
        assert S.from_reference(num) is want, num
        assert StorageOption(storage_type=num).storage_type is want
    with __import__("pytest").raises(ValueError, match="field numbers"):
        S.from_reference(57)
    # our own lowercase values still work, unknown names fail loudly
    assert StorageOption(storage_type="hbm_dram").storage_type is S.HBM_DRAM
    import pytest as _pytest

    with _pytest.raises(ValueError, match="unknown storage type"):
        S.from_reference("FLOPPY_DISK")


def test_diskkv_batched_reads_coalesce(tmp_path):
    """A promote burst (restore-after-crash: read back every spilled row)
    must not crawl through a Python seek loop — hits are sorted by offset
    and adjacent records coalesce into sequential reads. Against a
    contiguous log the whole 100k-row burst is ONE read (the reference's
    SSD tier batches its reads the same way — ssd_hash_kv.h)."""
    import time

    from deeprec_tpu.embedding.multi_tier import DiskKV

    path = str(tmp_path / "burst.ssd")
    kv = DiskKV(path, dim=8)
    n = 100_000
    keys = np.arange(n, dtype=np.int64)
    vals = np.arange(n, dtype=np.float32)[:, None].repeat(8, 1)
    kv.put(keys, vals, np.ones(n, np.int32), np.ones(n, np.int32))

    t0 = time.monotonic()
    got, freqs, vers, found = kv.get(keys)
    dt = time.monotonic() - t0
    assert found.all()
    np.testing.assert_array_equal(got[:, 0], np.arange(n, dtype=np.float32))
    assert kv.last_reads == 1  # fully coalesced: one sequential read
    # generous wall bound (loaded CI box): the old per-row loop took
    # multiple seconds at this size
    assert dt < 2.0, f"promote burst took {dt:.2f}s"

    # scattered subset in shuffled order: still correct, reads ≤ hits
    rng = np.random.RandomState(0)
    some = rng.permutation(n)[:1000]
    got2, _, _, found2 = kv.get(some)
    assert found2.all()
    np.testing.assert_array_equal(got2[:, 0], some.astype(np.float32))
    assert kv.last_reads <= 1000

    # overwrite half the keys (their records move to the log tail), then
    # a full read is exactly two runs after the rewrite: old half + tail
    kv.put(keys[: n // 2], vals[: n // 2] + 1.0)
    got3, _, _, found3 = kv.get(keys)
    assert found3.all()
    np.testing.assert_array_equal(got3[: n // 2, 0], np.arange(n // 2) + 1.0)
    assert kv.last_reads <= 3
    kv.close()
