"""Pod-scale parts checkpoint format (per-process shard-part files).

Covers the format matrix the gathered-format tests cover for single files:
parts == gathered bit-for-bit on restore, same-topology exactness, elastic
re-shard (8 -> 4 shards and 8 -> plain single table), incremental deltas
with eviction semantics, and a simulated multi-writer save (a part file
split in two, as two processes would write it). The multi-PROCESS path
itself is exercised end-to-end by tests/test_launch.py, which now saves
parts automatically (process_count > 1)."""
import glob
import json
import os
import shutil

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from deeprec_tpu.config import EmbeddingVariableOption, GlobalStepEvict
from deeprec_tpu.data import SyntheticCriteo
from deeprec_tpu.models import WDL
from deeprec_tpu.optim import Adagrad
from deeprec_tpu.parallel import ShardedTrainer, make_mesh, shard_batch
from deeprec_tpu.training import Trainer
from deeprec_tpu.training.checkpoint import CheckpointManager, is_per_row


def to_jnp(batch):
    return {k: jnp.asarray(v) for k, v in batch.items()}


def small(ttl: int = 0):
    ev = EmbeddingVariableOption(
        global_step_evict=GlobalStepEvict(steps_to_live=ttl) if ttl else None
    )
    return WDL(emb_dim=8, capacity=1 << 12, hidden=(32,), num_cat=4,
               num_dense=2, ev=ev)


def gen(seed=3):
    return SyntheticCriteo(batch_size=256, num_cat=4, num_dense=2, vocab=1500,
                           seed=seed)


def _trained(mesh, steps=3, seed=3, ttl=0):
    tr = ShardedTrainer(small(ttl), Adagrad(lr=0.1), optax.adam(1e-3),
                        mesh=mesh)
    st = tr.init(0)
    g = gen(seed)
    batches = [to_jnp(g.batch()) for _ in range(steps)]
    for b in batches:
        st, _ = tr.train_step(st, shard_batch(mesh, b))
    return tr, st, batches


@pytest.fixture(scope="module")
def trained8():
    """One trained (trainer, state, batches) shared by every test that
    only READS it (each saves to its own tmp dir): the training compile
    dominated this file's runtime when every test trained its own."""
    mesh = make_mesh(8)
    tr, st, batches = _trained(mesh)
    return mesh, tr, st, batches


def _key_value_map(tr, st):
    """key -> value row for every live key across shards/members (host)."""
    out = {}
    for bname, b in tr.bundles.items():
        ts = st.tables[bname]
        keys = np.asarray(ts.keys)
        values = np.asarray(ts.values)
        sentinel = np.iinfo(keys.dtype).min
        # reshape to one LOGICAL row per key — works for both plain [C, D]
        # and packed [C//P, P*D] layouts (row-major packing, ops/packed.py)
        flatk = keys.reshape(-1)
        flatv = values.reshape(flatk.shape[0], -1)
        for i in np.nonzero(flatk != sentinel)[0]:
            out[(bname, int(flatk[i]), i // keys.shape[-1])] = flatv[i]
    return out


def test_parts_save_matches_gathered(tmp_path, trained8):
    mesh, tr, st, batches = trained8
    CheckpointManager(str(tmp_path / "parts"), tr, sharded_io=True).save(st)
    CheckpointManager(str(tmp_path / "single"), tr, sharded_io=False).save(st)

    # parts dir has part files + manifest declaring the format
    pdirs = glob.glob(str(tmp_path / "parts" / "full-*"))
    assert pdirs
    assert glob.glob(os.path.join(pdirs[0], "table_*.part00000.npz"))
    assert not glob.glob(os.path.join(pdirs[0], "table_*_t.npz"))

    # both formats restore to identical predictions (streaming vs merged)
    preds = {}
    for name in ("parts", "single"):
        tr2 = ShardedTrainer(small(), Adagrad(lr=0.1), optax.adam(1e-3),
                             mesh=mesh)
        st2 = CheckpointManager(str(tmp_path / name), tr2,
                                sharded_io=(name == "parts")).restore()
        _, preds[name] = tr2.eval_step(st2, shard_batch(mesh, batches[0]))
    np.testing.assert_array_equal(np.asarray(preds["parts"]),
                                  np.asarray(preds["single"]))


def test_parts_same_topology_exact(tmp_path, trained8):
    mesh, tr, st, batches = trained8
    CheckpointManager(str(tmp_path), tr, sharded_io=True).save(st)
    tr2 = ShardedTrainer(small(), Adagrad(lr=0.1), optax.adam(1e-3), mesh=mesh)
    st2 = CheckpointManager(str(tmp_path), tr2, sharded_io=True).restore()
    assert int(st2.step) == int(st.step)
    m1, m2 = _key_value_map(tr, st), _key_value_map(tr2, st2)
    assert set(m1) == set(m2)  # identical keys in identical shards
    for kk in m1:
        np.testing.assert_array_equal(m1[kk], m2[kk])
    # training continues from the restored state
    st3, mets = tr2.train_step(st2, shard_batch(mesh, batches[0]))
    assert np.isfinite(float(mets["loss"]))


def test_parts_elastic_reshard(tmp_path, trained8):
    mesh, tr, st, batches = trained8
    CheckpointManager(str(tmp_path), tr, sharded_io=True).save(st)
    _, p8 = tr.eval_step(st, shard_batch(mesh, batches[0]))

    # 8 shard-parts -> 4-shard streaming restore (keys re-routed by hash)
    mesh4 = make_mesh(4)
    tr4 = ShardedTrainer(small(), Adagrad(lr=0.1), optax.adam(1e-3),
                         mesh=mesh4)
    st4 = CheckpointManager(str(tmp_path), tr4, sharded_io=True).restore()
    _, p4 = tr4.eval_step(st4, shard_batch(mesh4, batches[0]))
    np.testing.assert_allclose(np.asarray(p8), np.asarray(p4), atol=1e-5)

    # 8 shard-parts -> plain single-table Trainer (merged-parts path)
    tr1 = Trainer(small(), Adagrad(lr=0.1), optax.adam(1e-3))
    st1 = CheckpointManager(str(tmp_path), tr1).restore()
    _, p1 = tr1.eval_step(st1, batches[0])
    np.testing.assert_allclose(np.asarray(p8), np.asarray(p1), atol=1e-5)


@pytest.mark.slow
def test_parts_incremental_with_eviction(tmp_path):
    mesh = make_mesh(8)
    tr, st, batches = _trained(mesh, ttl=2)
    ck = CheckpointManager(str(tmp_path), tr, sharded_io=True)
    st, _ = ck.save(st)
    # advance on a DIFFERENT key distribution so earlier keys go stale,
    # evict them, then delta-save: the delta's live set must prune the
    # evicted keys on restore
    g2 = gen(seed=11)
    for _ in range(3):
        st, _ = tr.train_step(st, shard_batch(mesh, to_jnp(g2.batch())))
    st = tr.evict_tables(st)
    st, ipath = ck.save_incremental(st)
    assert glob.glob(os.path.join(ipath, "table_*.part00000.npz"))

    tr2 = ShardedTrainer(small(ttl=2), Adagrad(lr=0.1), optax.adam(1e-3),
                         mesh=mesh)
    st2 = CheckpointManager(str(tmp_path), tr2, sharded_io=True).restore()
    m1, m2 = _key_value_map(tr, st), _key_value_map(tr2, st2)
    assert set(m1) == set(m2)
    for kk in m1:
        np.testing.assert_array_equal(m1[kk], m2[kk])


def test_parts_multi_writer_simulation(tmp_path, trained8):
    """Split each part file in two (rows + shard metadata), as two writer
    processes would produce, and check the streaming restore merges them."""
    mesh, tr, st, batches = trained8
    ck = CheckpointManager(str(tmp_path), tr, sharded_io=True)
    _, path = ck.save(st)
    _, p8 = tr.eval_step(st, shard_batch(mesh, batches[0]))

    for pf in glob.glob(os.path.join(path, "table_*.part00000.npz")):
        arrs = dict(np.load(pf))
        offs = arrs["partition_offset"]
        sids = arrs["shard_ids"]
        half_s = len(sids) // 2
        cut = int(offs[half_s])
        halves = []
        for lo, hi, s_lo, s_hi in ((0, cut, 0, half_s),
                                   (cut, None, half_s, len(sids))):
            h = {}
            for k, v in arrs.items():
                if k in ("partition_offset", "shard_ids", "num_shards"):
                    continue
                if k == "bloom_parts":
                    h[k] = v[s_lo:s_hi]
                elif is_per_row(k):  # route by NAME, never by shape
                    h[k] = v[lo:hi]
                else:
                    h[k] = v
            h["shard_ids"] = sids[s_lo:s_hi]
            h["num_shards"] = arrs["num_shards"]
            h["partition_offset"] = offs[s_lo:s_hi + 1] - offs[s_lo]
            halves.append(h)
        os.remove(pf)
        base = pf[: -len("00000.npz")]
        np.savez(base + "00000.npz", **halves[0])
        np.savez(base + "00001.npz", **halves[1])

    # A real 2-process save records parts=2 and process 0's manifest
    # digests cover ITS OWN part files (the rewritten part00000); restore
    # validates both. Recompute the digests the simulated writer would
    # have recorded — stale ones would (correctly) quarantine the dir.
    from deeprec_tpu.training.checkpoint import _array_digest

    mf_path = os.path.join(path, "manifest.json")
    with open(mf_path) as f:
        mf = json.load(f)
    mf["parts"] = 2
    for fname in list(mf.get("digests", {})):
        if ".part" not in fname:
            continue
        fpath = os.path.join(path, fname)
        with np.load(fpath) as z:
            mf["digests"][fname] = {
                k: _array_digest(z[k]) for k in z.files
            }
    with open(mf_path, "w") as f:
        json.dump(mf, f)

    tr2 = ShardedTrainer(small(), Adagrad(lr=0.1), optax.adam(1e-3), mesh=mesh)
    st2 = CheckpointManager(str(tmp_path), tr2, sharded_io=True).restore()
    _, p2 = tr2.eval_step(st2, shard_batch(mesh, batches[0]))
    np.testing.assert_array_equal(np.asarray(p8), np.asarray(p2))
    m1, m2 = _key_value_map(tr, st), _key_value_map(tr2, st2)
    assert set(m1) == set(m2)


def test_parts_stale_file_refused_and_cleared(tmp_path, trained8):
    """A part file left by a crashed earlier attempt (e.g. from a larger
    pre-downscale topology) must make restore fail loudly, and a re-save at
    the same step must clear it rather than letting it merge silently."""
    mesh, tr, st, batches = trained8
    ck = CheckpointManager(str(tmp_path), tr, sharded_io=True)
    _, path = ck.save(st)

    # Plant a stale part (as pid 7 of a crashed wider run would leave).
    bname = next(iter(tr.bundles))
    real = glob.glob(os.path.join(path, f"table_{bname}_*.part00000.npz"))[0]
    tag = os.path.basename(real).split("_")[-1].split(".part")[0]
    stale = real.replace(".part00000.npz", ".part00007.npz")
    shutil.copy(real, stale)

    tr2 = ShardedTrainer(small(), Adagrad(lr=0.1), optax.adam(1e-3), mesh=mesh)
    ck2 = CheckpointManager(str(tmp_path), tr2, sharded_io=True)
    try:
        ck2.restore()
        raise AssertionError("restore merged a stale part file")
    except ValueError as e:
        assert "stale or partial" in str(e)

    # A fresh save at the same step clears the stale file first.
    _, path2 = ck.save(st)
    assert path2 == path
    assert not os.path.exists(stale)
    st2 = ck2.restore()
    m1, m2 = _key_value_map(tr, st), _key_value_map(tr2, st2)
    assert set(m1) == set(m2)
