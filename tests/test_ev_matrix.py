"""EmbeddingVariable option x optimizer x sharded matrix — the deeper grid
the reference covers in embedding_variable_ops_test.py:1007-1063 (~80 tests
of option/optimizer combinations), re-cut for the TPU engine.

Coverage matrix (rows here; single-device filter x optimizer lives in
test_compose_elastic.py::test_filter_optimizer_matrix):

| dimension            | values                                   | test |
|----------------------|------------------------------------------|------|
| sharded x filter     | none / counter / cbf   (8-dev mesh)      | test_sharded_filter_optimizer_grid |
| sharded x optimizer  | adagrad / adam_async / ftrl              | test_sharded_filter_optimizer_grid |
| grow under load      | insert_fails mid-training -> grow -> converge | test_maintain.py (single+sharded) |
| a2a forced overflow  | slack so tight the budget MUST overflow  | test_a2a_forced_overflow_serves_default |
| restore after grow   | with a CBF sketch attached               | test_restore_after_grow_with_cbf |
| evict + incremental  | TTL evict between delta saves            | test_evict_then_incremental_restore |
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from deeprec_tpu import (
    CBFFilter,
    CounterFilter,
    EmbeddingTable,
    EmbeddingVariableOption,
    GlobalStepEvict,
    TableConfig,
)
from deeprec_tpu.data import SyntheticCriteo
from deeprec_tpu.models import WDL
from deeprec_tpu.optim import make as make_opt
from deeprec_tpu.parallel import ShardedTrainer, make_mesh, shard_batch
from deeprec_tpu.training import Trainer
from deeprec_tpu.training.checkpoint import (
    CheckpointManager,
    export_table_arrays,
    import_rows,
    _state_to_np,
)


def J(b):
    return {k: jnp.asarray(v) for k, v in b.items()}


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(8)


FILTERS = [
    EmbeddingVariableOption(),
    EmbeddingVariableOption(counter_filter=CounterFilter(filter_freq=2)),
    EmbeddingVariableOption(
        cbf_filter=CBFFilter(filter_freq=2, max_element_size=1 << 12)
    ),
]


_FILTER_IDS = ["none", "counter", "cbf"]
# Default run covers the diagonal (every filter, every optimizer, each
# appearing once); the remaining combinations run under DEEPREC_FULL_TESTS.
_DIAGONAL = {("adagrad", "none"), ("ftrl", "counter"), ("adam_async", "cbf")}


@pytest.mark.parametrize(
    "opt_name,ev",
    [
        pytest.param(
            o, f,
            marks=[] if (o, fid) in _DIAGONAL else pytest.mark.slow,
            id=f"{fid}-{o}",
        )
        for o in ["adagrad", "adam_async", "ftrl"]
        for fid, f in zip(_FILTER_IDS, FILTERS)
    ],
)
def test_sharded_filter_optimizer_grid(mesh, opt_name, ev):
    """Every admission filter x optimizer combination must train sharded
    with a learning signal and zero a2a overflow at default slack."""
    model = WDL(emb_dim=8, capacity=1 << 12, hidden=(16,), num_cat=3,
                num_dense=2, ev=ev)
    tr = ShardedTrainer(model, make_opt(opt_name, lr=0.15), optax.adam(5e-3),
                        mesh=mesh, comm="a2a")
    st = tr.init(0)
    gen = SyntheticCriteo(batch_size=256, num_cat=3, num_dense=2, vocab=900,
                          seed=7)
    losses = []
    for _ in range(12):
        st, m = tr.train_step(st, shard_batch(mesh, J(gen.batch())))
        losses.append(float(m["loss"]))
    assert np.isfinite(losses).all(), (opt_name, losses)
    assert np.mean(losses[-3:]) < np.mean(losses[:3]), (opt_name, losses)
    for ts in st.tables.values():
        assert int(np.asarray(ts.a2a_overflow).sum()) == 0


def test_a2a_forced_overflow_serves_default(mesh):
    """With slack << 1 the per-destination budget must overflow; overflow is
    counted in a2a_overflow (NOT insert_fails), the affected ids serve the
    default value for the step, and training stays finite."""
    model = WDL(emb_dim=8, capacity=1 << 12, hidden=(16,), num_cat=3,
                num_dense=2)
    tr = ShardedTrainer(model, make_opt("adagrad", lr=0.1), optax.adam(1e-3),
                        mesh=mesh, comm="a2a", a2a_slack=0.15)
    st = tr.init(0)
    # big enough local batch that the per-destination budget binds (it has
    # a VPU-friendly floor of 8 slots), mild zipf so uniques stay plentiful
    gen = SyntheticCriteo(batch_size=4096, num_cat=3, num_dense=2,
                          vocab=4000, zipf_a=1.1, seed=3)
    for _ in range(4):
        st, m = tr.train_step(st, shard_batch(mesh, J(gen.batch())))
        assert np.isfinite(float(m["loss"]))
    overflow = sum(
        int(np.asarray(ts.a2a_overflow).sum()) for ts in st.tables.values()
    )
    fails = sum(
        int(np.asarray(ts.insert_fails).sum()) for ts in st.tables.values()
    )
    assert overflow > 0, "slack=0.15 with zipf 1.8 must overflow the budget"
    assert fails == 0, "overflow must not masquerade as capacity pressure"


def test_restore_after_grow_with_cbf():
    """Grow a CBF-filtered table, round-trip it through the checkpoint
    arrays, and verify admissions + values + sketch survive."""
    cfg = TableConfig(
        name="g", dim=8, capacity=256,
        ev=EmbeddingVariableOption(
            cbf_filter=CBFFilter(filter_freq=3, max_element_size=1 << 12)
        ),
    )
    t = EmbeddingTable(cfg)
    s = t.create()
    ids = jnp.arange(100, dtype=jnp.int32)
    for step in range(4):  # freq 4 >= 3: all admitted + resident
        s, res = t.lookup_unique(s, ids, step=step)
    assert int(t.size(s)) == 100
    s = t.grow(s, 1024)
    import dataclasses as dc

    big = EmbeddingTable(dc.replace(cfg, capacity=1024))
    rows = export_table_arrays(big, _state_to_np(s), only_dirty=False)
    s2 = import_rows(big, big.create(), rows)
    # values identical, sketch carried, and admission state preserved:
    # an id at freq 4 stays admitted after restore, a fresh id is filtered
    np.testing.assert_array_equal(np.asarray(s.bloom), np.asarray(s2.bloom))
    emb_a = np.asarray(big.lookup_readonly(s, ids))
    emb_b = np.asarray(big.lookup_readonly(s2, ids))
    np.testing.assert_allclose(emb_a, emb_b, rtol=1e-6)
    s2, res = big.lookup_unique(s2, jnp.asarray([5000], jnp.int32), step=9)
    assert not bool(res.admitted[np.asarray(res.uids) == 5000][0])


def test_evict_then_incremental_restore(tmp_path):
    """TTL eviction between a full save and a delta save: the restored
    state must drop the evicted keys and carry the delta's updates."""
    model = WDL(emb_dim=8, capacity=1 << 12, hidden=(16,), num_cat=2,
                num_dense=2,
                ev=EmbeddingVariableOption(
                    global_step_evict=GlobalStepEvict(steps_to_live=5)))
    tr = Trainer(model, make_opt("adagrad", lr=0.1), optax.adam(1e-3))
    st = tr.init(0)
    gen_a = SyntheticCriteo(batch_size=128, num_cat=2, num_dense=2,
                            vocab=300, seed=1)
    gen_b = SyntheticCriteo(batch_size=128, num_cat=2, num_dense=2,
                            vocab=300, seed=2)
    for _ in range(3):
        st, _ = tr.train_step(st, J(gen_a.batch()))
    ck = CheckpointManager(str(tmp_path), tr)
    st, _ = ck.save(st)
    # age out gen_a's keys: train only gen_b past the TTL, then evict
    for _ in range(8):
        st, _ = tr.train_step(st, J(gen_b.batch()))
    st = tr.evict_tables(st)
    st, _ = ck.save_incremental(st)

    restored = ck.restore()
    for name, table in tr.tables.items():
        live = tr.table_state(st, name)
        back = tr.table_state(restored, name)
        # same live set: delta keys present, evicted keys gone
        a = np.sort(np.asarray(live.keys)[np.asarray(table.occupied(live))])
        b = np.sort(np.asarray(back.keys)[np.asarray(table.occupied(back))])
        np.testing.assert_array_equal(a, b)
    ev = tr.evaluate(restored, [J(gen_b.batch()) for _ in range(2)])
    assert np.isfinite(ev["loss"])
