"""CriteoStats: the deterministic Criteo-marginal-matched generator.

The real-data-AUC proxy (VERDICT r4 ask #3): marginals pinned to public
Kaggle Criteo summary statistics, label from a hash-derived logistic
model with a computable Bayes ceiling. These tests pin the statistical
contract the AUC protocol (modelzoo/benchmark/auc_protocol.py) relies on.
"""
import numpy as np
import pytest

from deeprec_tpu.data.synthetic import (
    CRITEO_DENSE_MISSING,
    CRITEO_KAGGLE_CARDINALITIES,
    CRITEO_KAGGLE_CTR,
    CriteoStats,
    _auc,
)


@pytest.fixture(scope="module")
def gen():
    return CriteoStats(batch_size=1024, seed=0)


def test_batch_at_is_pure(gen):
    a = gen.batch_at(7)
    b = gen.batch_at(7)
    assert sorted(a) == sorted(b)
    for k in a:
        np.testing.assert_array_equal(a[k], b[k])
    # a fresh instance reproduces the same stream
    c = CriteoStats(batch_size=1024, seed=0).batch_at(7)
    for k in a:
        np.testing.assert_array_equal(a[k], c[k])


def test_streams_differ_by_index_seed_split(gen):
    a = gen.batch_at(0)
    for other in (
        gen.batch_at(1),
        CriteoStats(batch_size=1024, seed=1).batch_at(0),
        CriteoStats(batch_size=1024, seed=0, split="eval").batch_at(0),
    ):
        assert not np.array_equal(a["C3"], other["C3"])


def test_ctr_matches_kaggle(gen):
    out, _ = gen.probs_at(0, 200_000)
    assert abs(out["label"].mean() - CRITEO_KAGGLE_CTR) < 0.01


def test_cardinalities_respected(gen):
    out = gen.batch_at(0)
    for c, card in enumerate(gen.cards):
        ids = out[f"C{c + 1}"]
        assert ids.min() >= 0 and ids.max() < card
        assert card == min(CRITEO_KAGGLE_CARDINALITIES[c], 1 << 22)


def test_zipf_head_mass(gen):
    """Heavy tails: the top-100 ids of a multi-million-cardinality column
    carry most of the mass (real Criteo columns are this skewed)."""
    out, _ = gen.probs_at(0, 100_000)
    ids = out["C3"]  # cardinality 10.1M (capped 4.2M)
    cnt = np.bincount(ids)
    share = np.sort(cnt)[::-1][:100].sum() / cnt.sum()
    assert share > 0.5, share


def test_dense_missingness_and_shape(gen):
    out, _ = gen.probs_at(0, 50_000)
    for i in range(13):
        col = out[f"I{i + 1}"]
        assert col.shape == (50_000, 1)
        zero_rate = float((col == 0).mean())
        assert abs(zero_rate - CRITEO_DENSE_MISSING[i]) < 0.02, (i, zero_rate)


def test_bayes_ceiling_band():
    """The task's Bayes AUC sits in the real-Criteo regime (~0.79) and is
    stable across seeds (the hidden task is seed-independent)."""
    a = CriteoStats(seed=0).bayes_auc(100_000)
    b = CriteoStats(seed=3).bayes_auc(100_000)
    assert 0.77 < a < 0.82, a
    assert abs(a - b) < 0.01


def test_label_is_learnable_fast():
    """A linear model on the strongest column's one-hot must beat
    coin-flip from a modest sample — the signal is real, not noise."""
    g = CriteoStats(batch_size=4096, seed=0)
    # strongest column = argmax strength
    c = int(np.argmax(g.strength))
    card = g.cards[c]
    if card > 1 << 16:
        pytest.skip("strongest column too wide for the quick probe")
    w = np.zeros(card)
    n = np.zeros(card)
    for i in range(12):
        out = g.batch_at(i)
        ids, y = out[f"C{c + 1}"], out["label"]
        np.add.at(w, ids, y)
        np.add.at(n, ids, 1)
    rate = (w + 1.0) / (n + 4.0)  # smoothed per-id CTR
    ev = g.batch_at(100)
    auc = _auc(ev["label"], rate[ev[f"C{c + 1}"]])
    assert auc > 0.55, auc


def test_save_restore_stream_position():
    g = CriteoStats(batch_size=256, seed=0)
    g.batch(), g.batch()
    st = g.save()
    a = g.batch()
    g2 = CriteoStats(batch_size=256, seed=0)
    g2.restore(st)
    b = g2.batch()
    np.testing.assert_array_equal(a["C1"], b["C1"])


def test_auc_helper_exact():
    lab = np.asarray([1, 0, 1, 0, 0], np.float32)
    score = np.asarray([0.9, 0.1, 0.8, 0.7, 0.2], np.float32)
    # pairs: (1>.1),(.9>.7),(.9>.2),(.8>.1),(.8>.7),(.8>.2) all correct -> 1.0
    assert _auc(lab, score) == 1.0
    assert _auc(lab, 1 - score) == 0.0
    assert _auc(np.ones(3, np.float32), score[:3]) == 0.5
    # ties take the midrank: order of tied entries must not matter
    assert _auc(np.asarray([1.0, 0.0]), np.asarray([0.5, 0.5])) == 0.5
    assert _auc(np.asarray([0.0, 1.0]), np.asarray([0.5, 0.5])) == 0.5
    assert _auc(
        np.asarray([1, 0, 1, 0], np.float32),
        np.asarray([0.7, 0.7, 0.2, 0.2], np.float32),
    ) == 0.5


def test_consumed_index_checkpoints_behind_prefetch_ring():
    """ADVICE round-5 #2: under a depth-2 prefetch ring the producer index
    runs ahead of what the train loop consumed; save() must checkpoint the
    CONSUMED position so kill-and-resume replays every unconsumed batch
    exactly once."""
    import time

    from deeprec_tpu.data.prefetch import Prefetcher

    g = CriteoStats(batch_size=64, seed=0)
    g.attach_consumer()  # wiring-time: BEFORE the ring's producer runs ahead
    pf = Prefetcher(iter(g), depth=2, transform=lambda b: b,
                    on_consume=g.mark_consumed)
    try:
        # a save BEFORE the first delivery must report position 0 even
        # though the ring's producer is already ahead
        deadline0 = time.time() + 5.0
        while g._index == 0 and time.time() < deadline0:
            time.sleep(0.01)
        assert g._index > 0 and g.save()["index"] == 0
        consumed = [next(pf) for _ in range(3)]
        # let the producer run the ring ahead of the consumer
        deadline = time.time() + 5.0
        while g._index <= 3 and time.time() < deadline:
            time.sleep(0.01)
        assert g._index > 3, "producer never ran ahead (ring broken?)"
        st = g.save()
        assert st["index"] == 3  # consumed, NOT the producer position
    finally:
        pf.close()

    # the consumer saw exactly batches 0..2, in order
    for i, b in enumerate(consumed):
        np.testing.assert_array_equal(b["C1"], g.batch_at(i)["C1"])

    # kill-and-resume: the restored stream hands out batch 3 next — the
    # first batch the dead run never trained on — exactly once
    g2 = CriteoStats(batch_size=64, seed=0)
    g2.restore(st)
    pf2 = Prefetcher(iter(g2), depth=2, transform=lambda b: b,
                     on_consume=g2.mark_consumed)
    try:
        nxt = next(pf2)
        np.testing.assert_array_equal(nxt["C1"], g.batch_at(3)["C1"])
        assert g2.save()["index"] == 4
    finally:
        pf2.close()

    # unstaged use keeps the legacy producer-position semantics
    g3 = CriteoStats(batch_size=64, seed=0)
    g3.batch(), g3.batch()
    assert g3.save()["index"] == 2
