"""Kafka wire-protocol consumer against a scripted broker stub.

The stub speaks REAL Kafka frames over a real socket — responses are
hand-assembled with struct.pack from the protocol spec, independent of
the client's encoder, so these tests check the wire format itself, not
just a codec round-trip. Covers ApiVersions/Metadata/ListOffsets/Fetch
(both record encodings: legacy MessageSet and v2 RecordBatch) and
OffsetCommit/OffsetFetch group storage, plus the reader's exactly-once
save/restore and group-resume semantics."""
import socketserver
import struct
import threading

import numpy as np
import pytest

from deeprec_tpu.data.kafka import (
    KafkaClient,
    KafkaError,
    KafkaStreamReader,
    parse_records,
)

TOPIC = "clicks"


def _s(x: str) -> bytes:  # kafka string
    b = x.encode()
    return struct.pack(">h", len(b)) + b


def _zigzag(v: int) -> bytes:  # record-batch varint
    u = (v << 1) ^ (v >> 63) if v < 0 else v << 1
    out = bytearray()
    while True:
        b = u & 0x7F
        u >>= 7
        if u:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def message_set_v1(records, base_offset):
    """Legacy on-wire encoding (magic 1), one message per record."""
    out = b""
    for i, value in enumerate(records):
        body = (
            b"\x01"          # magic 1
            + b"\x00"        # attributes: uncompressed
            + struct.pack(">q", 1700000000000 + i)  # timestamp
            + struct.pack(">i", -1)                 # null key
            + struct.pack(">i", len(value)) + value
        )
        body = struct.pack(">I", 0xDEAD) + body     # crc (unverified)
        out += struct.pack(">q", base_offset + i)
        out += struct.pack(">i", len(body)) + body
    return out


def record_batch_v2(records, base_offset):
    """Modern on-wire encoding (magic 2, varint records)."""
    recs = b""
    for i, value in enumerate(records):
        body = (
            b"\x00"                       # record attributes
            + _zigzag(i)                  # timestamp delta
            + _zigzag(i)                  # offset delta
            + _zigzag(-1)                 # null key
            + _zigzag(len(value)) + value
            + _zigzag(0)                  # no headers
        )
        recs += _zigzag(len(body)) + body
    after_len = (
        struct.pack(">i", 0)              # partition leader epoch
        + b"\x02"                         # magic 2
        + struct.pack(">I", 0xBEEF)       # crc32c (unverified)
        + struct.pack(">h", 0)            # attributes: uncompressed
        + struct.pack(">i", len(records) - 1)   # last offset delta
        + struct.pack(">q", 1700000000000)      # first timestamp
        + struct.pack(">q", 1700000000099)      # max timestamp
        + struct.pack(">q", -1)           # producer id
        + struct.pack(">h", -1)           # producer epoch
        + struct.pack(">i", -1)           # base sequence
        + struct.pack(">i", len(records))
        + recs
    )
    return (struct.pack(">q", base_offset)
            + struct.pack(">i", len(after_len)) + after_len)


class BrokerStub:
    """Scripted single-partition broker. `encoding` picks the fetch
    record wire format; `page` limits records per fetch response to force
    multi-fetch consumption."""

    def __init__(self, records, encoding="v2", page=7, leader_addr=None,
                 fetch_err=0, earliest=0):
        self.records = list(records)
        self.encoding = encoding
        self.page = page
        self.committed = {}  # group -> offset
        self.requests = []   # (api_key, api_version) log
        # Multi-broker scripting: metadata reports `leader_addr` (host,
        # port) as the partition leader (default: this broker);
        # `fetch_err` != 0 makes every fetch fail with that Kafka error
        # code; offsets below `earliest` fetch OFFSET_OUT_OF_RANGE (code
        # 1) like a retention-trimmed topic.
        self.leader_addr = leader_addr
        self.fetch_err = fetch_err
        self.earliest = earliest
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    while True:
                        hdr = self._exact(4)
                        if hdr is None:
                            return
                        (size,) = struct.unpack(">i", hdr)
                        frame = self._exact(size)
                        if frame is None:
                            return
                        self.request.sendall(outer._respond(frame))
                except (ConnectionResetError, BrokenPipeError):
                    return

            def _exact(self, n):
                buf = b""
                while len(buf) < n:
                    c = self.request.recv(n - len(buf))
                    if not c:
                        return None
                    buf += c
                return buf

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._srv = Server(("127.0.0.1", 0), Handler)
        self.port = self._srv.server_address[1]
        threading.Thread(target=self._srv.serve_forever, daemon=True).start()

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()

    # -- request dispatch (parse just enough of each v0/v1 request)

    def _respond(self, frame: bytes) -> bytes:
        api_key, api_version, corr = struct.unpack(">hhi", frame[:8])
        (cid_len,) = struct.unpack(">h", frame[8:10])
        body = frame[10 + max(cid_len, 0):]
        self.requests.append((api_key, api_version))
        fn = {
            18: self._api_versions,
            3: self._metadata,
            2: self._list_offsets,
            1: self._fetch,
            8: self._offset_commit,
            9: self._offset_fetch,
        }[api_key]
        payload = struct.pack(">i", corr) + fn(body)
        return struct.pack(">i", len(payload)) + payload

    def _api_versions(self, body):
        apis = [(18, 0, 3), (3, 0, 9), (1, 0, 11), (2, 0, 5), (8, 0, 8),
                (9, 0, 8)]
        out = struct.pack(">h", 0) + struct.pack(">i", len(apis))
        for k, lo, hi in apis:
            out += struct.pack(">hhh", k, lo, hi)
        return out

    def _metadata(self, body):
        host, port = self.leader_addr or ("127.0.0.1", self.port)
        out = struct.pack(">i", 1)  # brokers
        out += struct.pack(">i", 0) + _s(host) + struct.pack(">i", port)
        out += struct.pack(">i", 1)  # topics
        out += struct.pack(">h", 0) + _s(TOPIC)
        out += struct.pack(">i", 1)  # partitions
        out += struct.pack(">hii", 0, 0, 0)  # err, pid, leader
        out += struct.pack(">i", 0)  # replicas
        out += struct.pack(">i", 0)  # isr
        return out

    def _list_offsets(self, body):
        when = struct.unpack(">q", body[-12:-4])[0]
        off = len(self.records) if when == -1 else self.earliest
        return (struct.pack(">i", 1) + _s(TOPIC) + struct.pack(">i", 1)
                + struct.pack(">ih", 0, 0)
                + struct.pack(">i", 1) + struct.pack(">q", off))

    def _fetch(self, body):
        # v0: replica i32, max_wait i32, min_bytes i32, topics[1]:
        # string, partitions[1]: pid i32, offset i64, max_bytes i32
        r = 12
        (tlen,) = struct.unpack(">h", body[r + 4:r + 6])
        p = r + 6 + tlen + 4
        pid, offset = struct.unpack(">iq", body[p:p + 12])
        err = self.fetch_err
        if not err and offset < self.earliest:
            err = 1  # OFFSET_OUT_OF_RANGE
        page = [] if err else self.records[offset:offset + self.page]
        enc = message_set_v1 if self.encoding == "v1" else record_batch_v2
        blob = enc(page, offset) if page else b""
        return (struct.pack(">i", 1) + _s(TOPIC) + struct.pack(">i", 1)
                + struct.pack(">i", pid) + struct.pack(">h", err)
                + struct.pack(">q", len(self.records))
                + struct.pack(">i", len(blob)) + blob)

    def _offset_commit(self, body):
        # v2: group, generation i32, member string, retention i64, topics
        (glen,) = struct.unpack(">h", body[:2])
        group = body[2:2 + glen].decode()
        p = 2 + glen
        (gen_id,) = struct.unpack(">i", body[p:p + 4])
        assert gen_id == -1  # simple-consumer path
        p += 4
        (mlen,) = struct.unpack(">h", body[p:p + 2])
        p += 2 + max(mlen, 0)
        p += 8  # retention time
        p += 4  # topics array len
        (tlen,) = struct.unpack(">h", body[p:p + 2])
        p += 2 + tlen + 4
        pid, offset = struct.unpack(">iq", body[p:p + 12])
        self.committed[group] = offset
        return (struct.pack(">i", 1) + _s(TOPIC) + struct.pack(">i", 1)
                + struct.pack(">ih", pid, 0))

    def _offset_fetch(self, body):
        (glen,) = struct.unpack(">h", body[:2])
        group = body[2:2 + glen].decode()
        off = self.committed.get(group, -1)
        return (struct.pack(">i", 1) + _s(TOPIC) + struct.pack(">i", 1)
                + struct.pack(">i", 0) + struct.pack(">q", off)
                + _s("") + struct.pack(">h", 0))


def tsv_rows(n):
    """Criteo-shaped rows: label \t I1..I2 \t C1..C2."""
    return [
        f"{i % 2}\t{i}.5\t{i * 2}\tcat{i}\tid{i % 5}".encode()
        for i in range(n)
    ]


@pytest.mark.parametrize("encoding", ["v1", "v2"])
def test_client_fetch_both_encodings(encoding):
    broker = BrokerStub(tsv_rows(20), encoding=encoding, page=20)
    try:
        c = KafkaClient("127.0.0.1", broker.port)
        assert 1 in c.api_versions()
        brokers, topics = c.metadata([TOPIC])
        assert topics[TOPIC]["partitions"][0]["leader"] == 0
        assert c.list_offsets(TOPIC, 0, -2) == 0
        assert c.list_offsets(TOPIC, 0, -1) == 20
        hw, recs = c.fetch(TOPIC, 0, 5)
        assert hw == 20
        assert [o for o, _, _ in recs] == list(range(5, 20))
        assert recs[0][2] == tsv_rows(20)[5]
        c.close()
    finally:
        broker.stop()


def test_reader_consumes_and_resumes_exactly_once():
    rows = tsv_rows(100)
    broker = BrokerStub(rows, encoding="v2", page=7)
    try:
        reader = KafkaStreamReader(
            f"127.0.0.1:{broker.port}", f"{TOPIC}:0:0",
            batch_size=16, stop_at_eof=True,
            num_dense=2, num_cat=2,
        )
        it = iter(reader)
        got = [next(it) for _ in range(3)]  # 48 rows
        assert all(b["label"].shape == (16,) for b in got)
        state = reader.save()
        assert state["offset"] == 48
        reader.close()

        # crash/restore: a NEW reader from the checkpoint sees the rest,
        # no duplicates, no loss
        r2 = KafkaStreamReader(
            f"127.0.0.1:{broker.port}", f"{TOPIC}:0:0",
            batch_size=16, stop_at_eof=True,
            num_dense=2, num_cat=2,
        )
        r2.restore(state)
        rest = list(r2)
        n_rest = sum(b["label"].shape[0] for b in rest)
        assert n_rest == 100 - 48
        # row identity: dense I1 of the first resumed row is row 48's
        assert rest[0]["I1"][0, 0] == 48.5
        r2.close()
    finally:
        broker.stop()


def test_reader_group_commit_resume():
    rows = tsv_rows(40)
    broker = BrokerStub(rows, encoding="v1", page=40)
    try:
        reader = KafkaStreamReader(
            f"127.0.0.1:{broker.port}", topic=TOPIC, offset=0,
            batch_size=10, stop_at_eof=True, group="trainers",
            num_dense=2, num_cat=2,
        )
        it = iter(reader)
        next(it)
        next(it)
        reader.commit()
        assert broker.committed["trainers"] == 20
        reader.close()

        # offset=-1: resume from the broker-stored group offset
        r2 = KafkaStreamReader(
            f"127.0.0.1:{broker.port}", topic=TOPIC, offset=-1,
            batch_size=10, stop_at_eof=True, group="trainers",
            num_dense=2, num_cat=2,
        )
        out = list(r2)
        assert sum(b["label"].shape[0] for b in out) == 20
        assert out[0]["I1"][0, 0] == 20.5
        r2.close()
    finally:
        broker.stop()


def test_reader_limit_matches_reference_spec():
    """topic:partition:offset:limit — the reference KafkaDataset's bounded
    consume (kafka_dataset_op.cc parses the same 4-part spec)."""
    broker = BrokerStub(tsv_rows(50), encoding="v2", page=50)
    try:
        reader = KafkaStreamReader(
            f"127.0.0.1:{broker.port}", f"{TOPIC}:0:10:30",
            batch_size=8, stop_at_eof=True, num_dense=2, num_cat=2,
        )
        out = list(reader)
        assert sum(b["label"].shape[0] for b in out) == 20  # [10, 30)
        assert out[0]["I1"][0, 0] == 10.5
        reader.close()
    finally:
        broker.stop()


def test_compressed_batch_raises():
    # attrs nonzero -> loud error, not silent corruption
    blob = bytearray(record_batch_v2([b"x"], 0))
    blob[21] = 0  # attributes hi byte
    blob[22] = 1  # gzip
    with pytest.raises(ValueError, match="compress"):
        parse_records(bytes(blob))


def test_reader_resolves_partition_leader_via_metadata():
    """Bootstrap != leader: the reader must follow Metadata to the broker
    that owns the partition (librdkafka does this automatically for the
    reference's consumer; a pinned bootstrap connection would fetch
    NOT_LEADER forever)."""
    rows = tsv_rows(30)
    leader = BrokerStub(rows, encoding="v2", page=30)
    try:
        # the bootstrap broker has NO data and fails every fetch; its
        # metadata points at the real leader
        boot = BrokerStub([], fetch_err=6,
                          leader_addr=("127.0.0.1", leader.port))
        try:
            reader = KafkaStreamReader(
                f"127.0.0.1:{boot.port}", f"{TOPIC}:0:0",
                batch_size=10, stop_at_eof=True, num_dense=2, num_cat=2,
            )
            out = list(reader)
            assert sum(b["label"].shape[0] for b in out) == 30
            # the bootstrap broker answered metadata only — never a fetch
            assert 1 not in [k for k, _ in boot.requests]
            assert any(k == 1 for k, _ in leader.requests)
            reader.close()
        finally:
            boot.stop()
    finally:
        leader.stop()


def test_reader_reresolves_leader_on_not_leader_error():
    """Mid-stream leadership move: the old leader starts answering
    NOT_LEADER_FOR_PARTITION; the reader re-resolves via Metadata and
    resumes on the new leader at the same offset."""
    rows = tsv_rows(40)
    new_leader = BrokerStub(rows, encoding="v2", page=40)
    old_leader = BrokerStub(rows, encoding="v2", page=10)
    try:
        reader = KafkaStreamReader(
            f"127.0.0.1:{old_leader.port}", f"{TOPIC}:0:0",
            batch_size=10, stop_at_eof=True, num_dense=2, num_cat=2,
            reconnect_secs=0.01,
        )
        it = iter(reader)
        first = next(it)
        assert first["I1"][0, 0] == 0.5
        # leadership moves: old broker now errors and redirects
        old_leader.fetch_err = 6
        old_leader.leader_addr = ("127.0.0.1", new_leader.port)
        rest = list(it)
        got = sum(b["label"].shape[0] for b in rest)
        assert got == 30  # no loss, no duplicates across the failover
        assert rest[0]["I1"][0, 0] == 10.5
        reader.close()
    finally:
        old_leader.stop()
        new_leader.stop()


def test_reader_offset_out_of_range_default_raises():
    """A checkpoint older than the topic's retention must fail LOUDLY by
    default (the silent alternative re-trains on a hole)."""
    from deeprec_tpu.data.kafka import KafkaOffsetGapError

    broker = BrokerStub(tsv_rows(50), encoding="v2", page=50, earliest=20)
    try:
        reader = KafkaStreamReader(
            f"127.0.0.1:{broker.port}", f"{TOPIC}:0:5",  # 5 < earliest=20
            batch_size=10, stop_at_eof=True, num_dense=2, num_cat=2,
        )
        with pytest.raises(KafkaOffsetGapError, match="retention"):
            list(reader)
        reader.close()
    finally:
        broker.stop()


def test_reader_offset_out_of_range_reset_earliest():
    """offset_reset='earliest' clamps to the oldest retained record with
    a warning — the reference consumer's auto.offset.reset semantics."""
    broker = BrokerStub(tsv_rows(50), encoding="v2", page=50, earliest=20)
    try:
        reader = KafkaStreamReader(
            f"127.0.0.1:{broker.port}", f"{TOPIC}:0:5",
            batch_size=10, stop_at_eof=True, num_dense=2, num_cat=2,
            offset_reset="earliest",
        )
        out = list(reader)
        assert sum(b["label"].shape[0] for b in out) == 30  # [20, 50)
        assert out[0]["I1"][0, 0] == 20.5
        reader.close()
    finally:
        broker.stop()
