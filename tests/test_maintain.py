"""Capacity-management loop: Trainer.maintain() consumes insert_fails /
occupancy and grows tables or demotes to the host tier — closing the loop
DeepRec closes implicitly (embedding_var.h:142 LookupOrCreateKey never
refuses a key; multi_tier_storage.h:47 + eviction_manager.h:39 manage
tiers in background threads).

The VERDICT round-1 acceptance test: overfill a table DURING training and
converge anyway — single-device and sharded.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
import optax

from deeprec_tpu import EmbeddingVariableOption, StorageOption
from deeprec_tpu.config import StorageType
from deeprec_tpu.data import SyntheticCriteo
from deeprec_tpu.models import WDL
from deeprec_tpu.optim import Adagrad
from deeprec_tpu.training import Trainer


def _model(capacity=256, ev=EmbeddingVariableOption()):
    return WDL(emb_dim=4, capacity=capacity, hidden=(16,), num_cat=2,
               num_dense=2, ev=ev)


def _gen(vocab, seed=0, B=256):
    return SyntheticCriteo(batch_size=B, num_cat=2, num_dense=2,
                           vocab=vocab, seed=seed)


def _batches(gen, n):
    return [{k: jnp.asarray(v) for k, v in gen.batch().items()}
            for _ in range(n)]


def test_overfill_grows_and_converges_single_device():
    model = _model(capacity=256)
    tr = Trainer(model, Adagrad(lr=0.2), optax.adam(5e-3))
    st = tr.init(0)
    gen = _gen(vocab=600)  # 600 uniques/table >> 256 slots: must overflow
    saw_fails = False
    for i in range(40):
        st, mets = tr.train_step(st, _batches(gen, 1)[0])
        if (i + 1) % 10 == 0:
            fails = sum(
                int(jnp.sum(ts.insert_fails)) for ts in st.tables.values()
            )
            saw_fails = saw_fails or fails > 0
            st, report = tr.maintain(st)
    assert saw_fails, "test not overfilling — raise vocab or lower capacity"
    grown = [r for r in report.values() if r["capacity"] > 256]
    assert grown, report
    # after growth the table absorbs everything: keep training, no fails
    for _ in range(25):
        st, _ = tr.train_step(st, _batches(gen, 1)[0])
    st2, report2 = tr.maintain(st)
    assert all(r["insert_fails"] == 0 for r in report2.values()), report2
    evals = tr.evaluate(st2, _batches(_gen(600, seed=9), 4))
    assert np.isfinite(evals["loss"])
    assert evals["auc"] > 0.55, evals


@pytest.mark.slow
def test_overfill_grows_sharded():
    from deeprec_tpu.parallel import ShardedTrainer, make_mesh, shard_batch

    mesh = make_mesh(8)
    model = _model(capacity=512)  # 64 slots per shard
    tr = ShardedTrainer(model, Adagrad(lr=0.2), optax.adam(5e-3), mesh=mesh)
    st = tr.init(0)
    gen = _gen(vocab=1200, B=512)
    saw_fails = False
    grew = []
    for i in range(12):
        st, mets = tr.train_step(st, shard_batch(mesh, _batches(gen, 1)[0]))
        if (i + 1) % 6 == 0:
            fails = sum(
                int(jnp.sum(ts.insert_fails)) for ts in st.tables.values()
            )
            saw_fails = saw_fails or fails > 0
            st, report = tr.maintain(st)
            grew += [r["grew_to"] for r in report.values() if "grew_to" in r]
    assert saw_fails
    assert grew, report
    # training continues, finite, and fails stay cleared
    st, mets = tr.train_step(st, shard_batch(mesh, _batches(gen, 1)[0]))
    assert np.isfinite(float(mets["loss"]))
    st, report2 = tr.maintain(st)
    assert all(r["insert_fails"] == 0 for r in report2.values()), report2


def test_hbm_budget_auto_tiers_instead_of_growing():
    """With an HBM byte budget that growth would bust, maintain() auto-
    places the bundle on the host tier (demote) instead of growing — the
    automated device-placement decision."""
    model = _model(capacity=256)
    tr = Trainer(model, Adagrad(lr=0.2), optax.adam(5e-3))
    st = tr.init(0)
    gen = _gen(vocab=600)
    for _ in range(8):
        st, _ = tr.train_step(st, _batches(gen, 1)[0])
    budget = sum(tr._state_bytes(ts) for ts in st.tables.values())  # no room
    st, report = tr.maintain(st, hbm_budget_bytes=budget)
    assert all(r["capacity"] == 256 for r in report.values()), report
    assert any(r.get("auto_tiered") for r in report.values()), report
    assert sum(r.get("demoted", 0) for r in report.values()) > 0
    st, mets = tr.train_step(st, _batches(gen, 1)[0])
    assert np.isfinite(float(mets["loss"]))
    # and the demotion relieved the pressure: a follow-up maintain with the
    # same budget takes no action at all
    st, report2 = tr.maintain(st, hbm_budget_bytes=budget)
    assert not any(
        r.get("auto_tiered") or "grew_to" in r for r in report2.values()
    ), report2


def test_multi_tier_demotes_inside_trainer():
    """HBM_DRAM tables demote cold rows at maintain() instead of growing;
    capacity stays fixed and training stays finite."""
    ev = EmbeddingVariableOption(
        storage=StorageOption(storage_type=StorageType.HBM_DRAM)
    )
    model = _model(capacity=256, ev=ev)
    tr = Trainer(model, Adagrad(lr=0.2), optax.adam(5e-3))
    st = tr.init(0)
    gen = _gen(vocab=280)  # drives occupancy over the 0.8 watermark
    for _ in range(8):
        st, _ = tr.train_step(st, _batches(gen, 1)[0])
    st, report = tr.maintain(st)
    assert all(r["capacity"] == 256 for r in report.values()), report
    demoted = sum(r.get("demoted", 0) for r in report.values())
    assert demoted > 0, report
    # demoted rows live in the host tier now
    assert any(len(mt.host) for mt in tr._tiers.values())
    st, mets = tr.train_step(st, _batches(gen, 1)[0])
    assert np.isfinite(float(mets["loss"]))
