"""Fault injection: SIGKILL a training process mid-run, restart it, and
verify it resumes from the last checkpoint and completes.

The failure-detection/recovery story (SURVEY.md §5): the reference runs an
external dead-PS detector + restart protocol; here recovery is
checkpoint-shaped — full+incremental state restore plus WorkQueue consumer
state, both validated against a real kill -9 (not a polite exception).
The subprocess machinery lives in deeprec_tpu/online/faults.py (shared
with tools/bench_freshness.py and the supervisor tests)."""
import json
import os
import signal
import sys
import textwrap

import numpy as np
import pytest

from deeprec_tpu.online import faults

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))

WORKER = textwrap.dedent(
    """
    import json, os, sys
    sys.path.insert(0, {repo!r})
    import jax.numpy as jnp
    import optax

    from deeprec_tpu.data import SyntheticCriteo
    from deeprec_tpu.models import WDL
    from deeprec_tpu.optim import Adagrad
    from deeprec_tpu.training import Trainer
    from deeprec_tpu.training.checkpoint import CheckpointManager

    TARGET = 40
    SAVE_EVERY = 10
    model = WDL(emb_dim=4, capacity=1 << 12, hidden=(16,), num_cat=2,
                num_dense=2)
    tr = Trainer(model, Adagrad(lr=0.2), optax.adam(5e-3))
    ck = CheckpointManager({ckpt!r}, tr)
    try:
        st = ck.restore()
        print(f"RESUMED {{int(st.step)}}", flush=True)
    except FileNotFoundError:
        st = tr.init(0)
        print("FRESH", flush=True)

    gen = SyntheticCriteo(batch_size=256, num_cat=2, num_dense=2, vocab=500,
                          seed=0)
    # deterministic stream position: replay the generator to the current
    # step so a resumed run sees the batches it has not yet consumed
    for _ in range(int(st.step)):
        gen.batch()

    while int(st.step) < TARGET:
        st, mets = tr.train_step(
            st, {{k: jnp.asarray(v) for k, v in gen.batch().items()}}
        )
        step = int(st.step)
        print(f"STEP {{step}} {{float(mets['loss']):.5f}}", flush=True)
        if step % SAVE_EVERY == 0:
            st, path = ck.save(st)
            print(f"SAVED {{step}}", flush=True)

    ev = tr.evaluate(
        st, [{{k: jnp.asarray(v) for k, v in gen.batch().items()}}
             for _ in range(4)]
    )
    with open(os.path.join({ckpt!r}, "final.json"), "w") as f:
        json.dump({{"step": int(st.step), **ev}}, f)
    print("DONE", flush=True)
    """
)


def test_async_writer_killed_mid_save_restores_prior_chain(tmp_path):
    """A writer that dies MID-SAVE (some table files on disk, no manifest)
    must be invisible to restore: the manifest is the completeness marker,
    so the torn dir is skipped and restore() falls back to the previous
    full+incr chain BIT-EXACTLY. Deterministic kill via the writer's
    pre-IO seam — the 'files written then death' state is staged by the
    seam itself, which is exactly what a SIGKILL between two np.savez
    calls leaves behind."""
    import jax
    import numpy as np
    import optax

    from deeprec_tpu.models import WDL
    from deeprec_tpu.optim import Adagrad
    from deeprec_tpu.training import Trainer
    from deeprec_tpu.training.checkpoint import CheckpointManager

    def mk():
        model = WDL(emb_dim=4, capacity=1 << 10, hidden=(16,), num_cat=2,
                    num_dense=2)
        return Trainer(model, Adagrad(lr=0.2), optax.adam(5e-3))

    from deeprec_tpu.data import SyntheticCriteo

    gen = SyntheticCriteo(batch_size=128, num_cat=2, num_dense=2, vocab=400,
                          seed=0)

    def step(tr, st):
        return tr.train_step(
            st, {k: jnp.asarray(v) for k, v in gen.batch().items()})[0]

    import jax.numpy as jnp

    tr = mk()
    st = tr.init(0)
    ck = CheckpointManager(str(tmp_path), tr)
    for _ in range(2):
        st = step(tr, st)
    st, _ = ck.save(st)                      # full @2
    st = step(tr, st)
    st, _ = ck.save_incremental(st)          # incr @3 — the good chain
    good = CheckpointManager(str(tmp_path), mk()).restore()

    st = step(tr, st)

    def killed_writer(path):
        # the partial state a mid-save kill leaves: dir created, a real
        # table file already on disk, manifest never written
        os.makedirs(path, exist_ok=True)
        bname = next(iter(tr.bundles))
        np.savez(os.path.join(path, f"table_{bname}_t0.npz"),
                 junk=np.zeros(3))
        raise KeyboardInterrupt("simulated SIGKILL")

    ck.on_write = killed_writer
    st, torn = ck.save_incremental_async(st)
    with pytest.raises(RuntimeError, match="writer failed"):
        ck.wait()
    assert not os.path.exists(os.path.join(torn, "manifest.json"))
    assert os.path.exists(torn)  # torn dir IS there — and must be ignored

    restored = CheckpointManager(str(tmp_path), mk()).restore()
    assert int(restored.step) == int(good.step) == 3
    for bname in tr.bundles:
        for name in ("keys", "meta", "values"):
            np.testing.assert_array_equal(
                np.asarray(getattr(good.tables[bname], name)),
                np.asarray(getattr(restored.tables[bname], name)),
            )


@pytest.mark.slow
def test_sigkill_mid_training_resumes_and_completes(tmp_path):
    ckpt = str(tmp_path / "ckpt")
    script = str(tmp_path / "worker.py")
    with open(script, "w") as f:
        f.write(WORKER.format(repo=REPO, ckpt=ckpt))
    # run 1: kill -9 once it has saved a checkpoint AND run a few steps
    # past it, so the kill genuinely loses progress
    p = faults.spawn_worker([sys.executable, script])
    saved = {"seen": False}

    def past_save(line: str) -> bool:
        if line.startswith("SAVED"):
            saved["seen"] = True
        return (saved["seen"] and line.startswith("STEP")
                and int(line.split()[1]) >= 14)

    hit, lines1 = faults.wait_for_line(p, past_save, timeout=240)
    assert hit is not None and saved["seen"], lines1
    assert faults.sigkill(p) == -signal.SIGKILL
    assert not os.path.exists(os.path.join(ckpt, "final.json"))

    # run 2: must resume from the checkpoint (not step 0) and finish
    p = faults.spawn_worker([sys.executable, script])
    done, lines2 = faults.wait_for_line(
        p, lambda l: l.startswith("DONE"), timeout=240)
    assert p.wait(timeout=30) == 0, lines2[-20:]
    assert done is not None, lines2[-20:]
    assert any(l.startswith("RESUMED") for l in lines2), lines2[:3]
    resumed_at = int([l for l in lines2 if l.startswith("RESUMED")][0].split()[1])
    assert resumed_at >= 10  # a saved step, not a fresh start
    assert "DONE" in lines2[-1]

    with open(os.path.join(ckpt, "final.json")) as f:
        final = json.load(f)
    assert final["step"] == 40
    assert np.isfinite(final["loss"])
    assert final["auc"] > 0.55, final
