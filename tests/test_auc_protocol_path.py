"""The --data criteo_stats modelzoo path: wiring test (fast).

The full protocol (12k steps, modelzoo/benchmark/auc_protocol.py) runs
out-of-band; this pins the harness plumbing — held-out eval split, AUC
scraping — at smoke size.
"""
import os
import re
import subprocess
import sys

import pytest

ZOO = os.path.join(os.path.dirname(__file__), "..", "modelzoo")


@pytest.mark.slow
def test_wdl_criteo_stats_short_run_lifts_auc():
    proc = subprocess.run(
        [sys.executable, os.path.join(ZOO, "wide_and_deep", "train.py"),
         "--data", "criteo_stats", "--steps", "60", "--batch_size", "512",
         "--capacity", str(1 << 14), "--eval_every", "60",
         "--eval_batches", "6", "--log_every", "30"],
        capture_output=True, text=True, timeout=420,
        cwd=os.path.join(ZOO, "wide_and_deep"),
    )
    log = proc.stdout + proc.stderr
    assert proc.returncode == 0, log[-2000:]
    aucs = [float(m) for m in re.findall(r"Eval AUC: ([0-9.]+) \(auc\)", log)]
    assert aucs, log[-2000:]
    # 60 steps at bs 512 on the zipf head is enough to clear coin-flip by
    # a wide margin on HELD-OUT data (the eval split is disjoint)
    assert aucs[-1] > 0.60, aucs


def test_criteo_stats_rejects_non_criteo_kind():
    sys.path.insert(0, ZOO)
    try:
        from common import build_argparser, make_data
    finally:
        sys.path.pop(0)
    args = build_argparser("x").parse_args(["--data", "criteo_stats"])
    with pytest.raises(ValueError, match="criteo_stats"):
        make_data(args, "behavior")
