"""Socket-tier serving scale-out (serving/frontend.py): wire parity,
user-group routing, merged stats, worst-member health, and the fault
matrix — a killed backend costs a retry on a sibling, never a failed
request, with health degraded then recovered."""
import json
import threading
import time
import urllib.request

import jax.numpy as jnp
import numpy as np
import optax
import pytest

from deeprec_tpu.data import SyntheticCriteo, SyntheticTwoTower
from deeprec_tpu.models import DSSM, WDL
from deeprec_tpu.optim import Adagrad
from deeprec_tpu.serving import (
    BackendServer,
    Frontend,
    HttpServer,
    ModelServer,
    Predictor,
)
from deeprec_tpu.training import Trainer
from deeprec_tpu.training.checkpoint import CheckpointManager


def J(b):
    return {k: jnp.asarray(v) for k, v in b.items()}


def strip_labels(b):
    return {k: np.asarray(v) for k, v in b.items() if not k.startswith("label")}


def make_trained(tmp_path, steps=3):
    model = WDL(emb_dim=8, capacity=1 << 12, hidden=(32, 16), num_cat=4,
                num_dense=2)
    tr = Trainer(model, Adagrad(lr=0.1), optax.adam(1e-3))
    st = tr.init(0)
    gen = SyntheticCriteo(batch_size=64, num_cat=4, num_dense=2, vocab=2000,
                          seed=13)
    for _ in range(steps):
        st, _ = tr.train_step(st, J(gen.batch()))
    ck = CheckpointManager(str(tmp_path), tr)
    st, _ = ck.save(st)
    return model, tr, st, ck, gen


@pytest.fixture(scope="module")
def wdl_ckpt(tmp_path_factory):
    """One trained WDL checkpoint + reference predictions shared by the
    read-only frontend tests (each test spins its OWN backends/frontend;
    only the checkpoint dir and the trainer-side artifacts are shared —
    tests that land new deltas get their own copy via make_trained)."""
    tmp = tmp_path_factory.mktemp("fe-wdl")
    model, tr, st, ck, gen = make_trained(tmp)
    req = strip_labels(gen.batch())
    expect = np.asarray(Predictor(model, str(tmp)).predict(req))
    return model, str(tmp), req, expect


def make_tier(model, ckpt, n=2, **fe_kwargs):
    backends = [
        BackendServer(ModelServer(Predictor(model, ckpt), max_batch=64,
                                  max_wait_ms=1.0)).start()
        for _ in range(n)
    ]
    fe = Frontend([("127.0.0.1", b.port) for b in backends], model,
                  **fe_kwargs)
    return backends, fe


def test_frontend_parity_and_merged_surfaces(wdl_ckpt):
    """Requests through the socket tier match a local predictor; the
    merged /v1/stats spans every member; /healthz is worst-member; a
    grouped request against a tower-less model comes back as a
    structured BadRequest through the wire."""
    model, ckpt, req, expect = wdl_ckpt
    backends, fe = make_tier(model, ckpt, n=2)
    try:
        assert fe.warmup(req) == 2
        out, ver = fe.request_versioned(req)
        np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-5,
                                   atol=1e-5)
        assert ver == 0

        from deeprec_tpu.serving.predictor import BadRequest

        with pytest.raises(BadRequest, match="tower"):
            fe.request(req, group_users=True)

        # round-robin spreads plain requests over both members
        for _ in range(6):
            fe.request(req)
        mstats = [m.snapshot() for m in fe._members]
        assert all(s["requests"] > 0 for s in mstats), mstats

        http = HttpServer(fe, port=0).start()
        try:
            body = json.dumps(
                {"features": {k: v.tolist() for k, v in req.items()}}
            ).encode()
            r = urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{http.port}/v1/predict", data=body,
                headers={"Content-Type": "application/json"},
                method="POST"), timeout=30)
            got = json.loads(r.read())
            np.testing.assert_allclose(np.asarray(got["predictions"]),
                                       expect, rtol=1e-4, atol=1e-4)

            stats = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{http.port}/v1/stats", timeout=10).read())
            assert len(stats["members"]) == 2
            assert stats["backend_totals"]["requests"] >= 8
            assert all("stats" in m for m in stats["members"])

            h = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{http.port}/healthz", timeout=10).read())
            assert h["status"] == "ok"
            assert h["members"] == 2 and h["reachable"] == 2

            info = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{http.port}/v1/model_info",
                timeout=10).read())
            assert info["members"] == 2 and info["step"] == 3
        finally:
            http.stop()
    finally:
        fe.close()
        for b in backends:
            b.stop()


def test_frontend_fault_matrix_kill_retry_recover(wdl_ckpt):
    """Backend death mid-traffic: in-flight and subsequent requests retry
    on the sibling (zero failed requests), /healthz degrades to the worst
    member, and a restarted backend is marked back up by the next health
    round."""
    model, ckpt, req, expect = wdl_ckpt
    backends, fe = make_tier(model, ckpt, n=2)
    try:
        fe.warmup(req)
        errors, done = [], threading.Event()

        def driver():
            try:
                while not done.is_set():
                    out = fe.request(req)
                    np.testing.assert_allclose(np.asarray(out), expect,
                                               rtol=1e-5, atol=1e-5)
            except Exception as e:  # pragma: no cover - the assertion
                errors.append(e)

        th = threading.Thread(target=driver)
        th.start()
        time.sleep(0.2)
        backends[0].stop()  # severs live + pooled connections, like SIGKILL
        time.sleep(0.3)
        done.set()
        th.join(timeout=30)
        assert not errors, errors  # zero failed requests through the kill

        h = fe.predictor.health()
        assert h["status"] == "degraded" and h["reachable"] == 1

        # restart on the same port -> next sweep marks the member up
        b0 = BackendServer(
            ModelServer(Predictor(model, ckpt), max_batch=64,
                        max_wait_ms=1.0), port=backends[0].port).start()
        try:
            h2 = fe.predictor.health()
            assert h2["status"] == "ok" and h2["reachable"] == 2
            out = fe.request(req)
            np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-5,
                                       atol=1e-5)
        finally:
            b0.stop()
    finally:
        fe.close()
        for b in backends:
            b.stop()


def test_frontend_all_backends_down_raises(wdl_ckpt):
    model, ckpt, req, expect = wdl_ckpt
    backends, fe = make_tier(model, ckpt, n=2)
    try:
        fe.warmup(req)
        for b in backends:
            b.stop()
        with pytest.raises(RuntimeError, match="unreachable"):
            fe.request(req)
        assert fe.stats.snapshot()["errors"] >= 1
        h = fe.predictor.health()
        assert h["status"] == "down" and h["reachable"] == 0
    finally:
        fe.close()


@pytest.mark.slow
def test_frontend_delta_updates_per_backend(tmp_path):
    """Each backend replays the delta chain in its own process; a
    frontend-driven poll round rolls the update across the tier and the
    response version stamp advances."""
    model, tr, st, ck, gen = make_trained(tmp_path)
    req = strip_labels(gen.batch())
    backends, fe = make_tier(model, str(tmp_path), n=2,
                             poll_backends=True)
    try:
        fe.warmup(req)
        for _ in range(2):
            st, _ = tr.train_step(st, J(gen.batch()))
        st, _ = ck.save_incremental(st)
        assert fe.predictor.poll_updates()
        for _ in range(4):  # both members answer with the new version
            _, ver = fe.request_versioned(req)
            assert ver == 1
        expect = np.asarray(Predictor(model, str(tmp_path)).predict(req))
        np.testing.assert_allclose(np.asarray(fe.request(req)), expect,
                                   rtol=1e-5, atol=1e-5)
    finally:
        fe.close()
        for b in backends:
            b.stop()



@pytest.mark.slow
def test_frontend_groups_route_by_user(tmp_path):
    """group_users requests route by user-feature hash: every request
    for one user lands on ONE member (so sample-aware coalescing
    survives the socket split) and outputs match the direct grouped
    path."""
    model = DSSM(emb_dim=8, capacity=1 << 12, num_user_feats=2,
                 num_item_feats=2, hidden=(32, 16))
    tr = Trainer(model, Adagrad(lr=0.1), optax.adam(2e-3))
    st = tr.init(0)
    gen = SyntheticTwoTower(batch_size=128, num_user=2, num_item=2,
                            vocab=500, seed=29)
    for _ in range(3):
        st, _ = tr.train_step(st, J(gen.batch()))
    CheckpointManager(str(tmp_path), tr).save(st)
    base = strip_labels(gen.batch())

    def user_req(u, n_items=8):
        out = {}
        for k, v in base.items():
            rows = v[u * n_items:(u + 1) * n_items].copy()
            if k in model.user_feats:
                rows = np.repeat(v[u:u + 1], n_items, axis=0)
            out[k] = rows
        return out

    backends, fe = make_tier(model, str(tmp_path), n=2)
    pred = Predictor(model, str(tmp_path))
    try:
        fe.warmup(user_req(0))
        routed = {}
        for u in range(4):
            req = user_req(u)
            before = [m.snapshot()["requests"] for m in fe._members]
            for _ in range(2):
                out, _ = fe.request_versioned(req, group_users=True)
                np.testing.assert_allclose(
                    np.asarray(out), np.asarray(pred.predict(req)),
                    rtol=2e-5, atol=2e-5)
            after = [m.snapshot()["requests"] for m in fe._members]
            hit = [i for i, (a, b) in enumerate(zip(before, after)) if b > a]
            assert len(hit) == 1, (u, before, after)  # one member per user
            routed[u] = hit[0]
        # the hash actually spreads users (2 members, 4 users: both used
        # unless astronomically unlucky with this fixed seed)
        assert len(set(routed.values())) == 2, routed
    finally:
        fe.close()
        for b in backends:
            b.stop()


@pytest.mark.slow
def test_frontend_backend_sigkill_subprocess(tmp_path):
    """True process-level fault matrix: two backend PROCESSES, SIGKILL
    one mid-load — the frontend retries onto the surviving sibling with
    zero failed requests, health degrades, and predictions stay
    bit-identical to the surviving process's snapshot."""
    import os
    import signal

    from deeprec_tpu.serving import spawn_backends

    model, tr, st, ck, gen = make_trained(tmp_path)
    req = strip_labels(gen.batch())
    mj = json.dumps({"emb_dim": 8, "capacity": 4096, "hidden": [32, 16],
                     "num_cat": 4, "num_dense": 2})
    procs, addrs = spawn_backends(
        2, ckpt=str(tmp_path), model="wdl", model_json=mj,
        env={"JAX_PLATFORMS": "cpu"})
    fe = Frontend(addrs, model)
    expect = np.asarray(Predictor(model, str(tmp_path)).predict(req))
    try:
        fe.warmup(req)
        errors, done = [], threading.Event()

        def driver():
            try:
                while not done.is_set():
                    np.testing.assert_allclose(
                        np.asarray(fe.request(req)), expect, rtol=1e-5,
                        atol=1e-5)
            except Exception as e:  # pragma: no cover
                errors.append(e)

        th = threading.Thread(target=driver)
        th.start()
        time.sleep(0.3)
        os.kill(procs[0].pid, signal.SIGKILL)
        procs[0].wait()
        time.sleep(0.7)
        done.set()
        th.join(timeout=60)
        assert not errors, errors
        h = fe.predictor.health()
        assert h["status"] == "degraded" and h["reachable"] == 1
    finally:
        fe.close()
        for p in procs:
            p.kill()
