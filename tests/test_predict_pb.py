"""Protobuf wire codec: round-trip + differential tests.

The differential half compiles the reference's predict.proto with protoc
and checks OUR hand-rolled codec parses bytes produced by the official
protobuf runtime and produces bytes the official runtime parses — the
actual interop contract a reference-built host exercises. Skipped when
protoc / the reference tree / a compatible runtime is unavailable.
"""
import importlib.util
import os
import subprocess
import sys

import numpy as np
import pytest

from deeprec_tpu.serving.predict_pb import (
    DT_FLOAT,
    DT_INT64,
    DT_STRING,
    ArrayProto,
    PredictRequest,
    PredictResponse,
    ServingModelInfo,
)

REF_PROTO = "/root/reference/serving/processor/serving/predict.proto"


def test_array_roundtrip_dtypes():
    cases = [
        np.arange(12, dtype=np.float32).reshape(3, 4) * 0.5,
        np.arange(6, dtype=np.float64).reshape(2, 3) - 2.5,
        np.asarray([[1, -2], [3, -(1 << 40)]], np.int64),
        np.asarray([5, -6, 7], np.int32),
        np.asarray([True, False, True]),
        np.asarray([1, 200, 255], np.uint8),
    ]
    for arr in cases:
        back = ArrayProto.parse(ArrayProto.from_numpy(arr).serialize()).to_numpy()
        assert back.shape == arr.shape
        np.testing.assert_array_equal(back.astype(arr.dtype), arr)


def test_array_strings():
    arr = np.asarray(["user_a", "user_b"], dtype=object)
    p = ArrayProto.from_numpy(arr)
    assert p.dtype == DT_STRING
    back = ArrayProto.parse(p.serialize())
    assert back.string_val == [b"user_a", b"user_b"]


def test_request_roundtrip():
    req = PredictRequest(
        signature_name="serving_default",
        inputs={
            "C1": ArrayProto.from_numpy(np.asarray([[1], [2]], np.int64)),
            "I1": ArrayProto.from_numpy(np.asarray([[0.5], [1.5]], np.float32)),
        },
        output_filter=["probabilities"],
    )
    back = PredictRequest.parse(req.serialize())
    assert back.signature_name == "serving_default"
    assert sorted(back.inputs) == ["C1", "I1"]
    assert back.output_filter == ["probabilities"]
    np.testing.assert_array_equal(
        back.inputs["C1"].to_numpy(), [[1], [2]]
    )


def test_response_roundtrip():
    resp = PredictResponse(
        {"probabilities": ArrayProto.from_numpy(np.asarray([0.1, 0.9], np.float32))}
    )
    back = PredictResponse.parse(resp.serialize())
    np.testing.assert_allclose(
        back.outputs["probabilities"].to_numpy(), [0.1, 0.9], rtol=1e-6
    )


def test_unknown_fields_skipped():
    # field 15, varint 7 prepended: conforming parsers skip unknown fields
    raw = b"\x78\x07" + PredictResponse(
        {"p": ArrayProto.from_numpy(np.asarray([1.0], np.float32))}
    ).serialize()
    back = PredictResponse.parse(raw)
    assert "p" in back.outputs


@pytest.fixture(scope="module")
def eas_pb2(tmp_path_factory):
    if not os.path.exists(REF_PROTO):
        pytest.skip("reference predict.proto not available")
    tmp = tmp_path_factory.mktemp("pb")
    r = subprocess.run(
        ["protoc", f"-I{os.path.dirname(REF_PROTO)}",
         f"--python_out={tmp}", os.path.basename(REF_PROTO)],
        capture_output=True, text=True,
    )
    if r.returncode != 0:
        pytest.skip(f"protoc failed: {r.stderr}")
    spec = importlib.util.spec_from_file_location(
        "predict_pb2", tmp / "predict_pb2.py"
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules["predict_pb2"] = mod
    try:
        spec.loader.exec_module(mod)
    except Exception as e:  # gencode/runtime version mismatch
        pytest.skip(f"protobuf runtime rejected gencode: {e}")
    return mod


def test_differential_request(eas_pb2):
    """Bytes from the official runtime parse identically in our codec."""
    req = eas_pb2.PredictRequest()
    req.signature_name = "serving_default"
    ids = req.inputs["C1"]
    ids.dtype = eas_pb2.DT_INT64
    ids.array_shape.dim.extend([2, 1])
    ids.int64_val.extend([10, -3])
    dense = req.inputs["I1"]
    dense.dtype = eas_pb2.DT_FLOAT
    dense.array_shape.dim.extend([2, 1])
    dense.float_val.extend([0.25, -1.5])
    req.output_filter.append("probabilities")

    ours = PredictRequest.parse(req.SerializeToString())
    assert ours.signature_name == "serving_default"
    assert ours.output_filter == ["probabilities"]
    np.testing.assert_array_equal(
        ours.inputs["C1"].to_numpy(), [[10], [-3]]
    )
    np.testing.assert_allclose(
        ours.inputs["I1"].to_numpy(), [[0.25], [-1.5]], rtol=1e-6
    )


def test_differential_response(eas_pb2):
    """Bytes from our codec parse identically in the official runtime."""
    resp = PredictResponse(
        {"probabilities": ArrayProto.from_numpy(
            np.asarray([[0.1], [0.9]], np.float32))}
    )
    theirs = eas_pb2.PredictResponse()
    theirs.ParseFromString(resp.serialize())
    out = theirs.outputs["probabilities"]
    assert out.dtype == eas_pb2.DT_FLOAT
    assert list(out.array_shape.dim) == [2, 1]
    np.testing.assert_allclose(list(out.float_val), [0.1, 0.9], rtol=1e-6)


def test_differential_model_info(eas_pb2):
    info = eas_pb2.ServingModelInfo()
    info.model_path = "/models/wdl/full-120"
    ours = ServingModelInfo.parse(info.SerializeToString())
    assert ours.model_path == "/models/wdl/full-120"
    theirs = eas_pb2.ServingModelInfo()
    theirs.ParseFromString(ServingModelInfo("/x/y").serialize())
    assert theirs.model_path == "/x/y"


def test_dispatch_never_misroutes(tmp_path):
    """Wire sniffing: a protobuf whose bytes LOOK like whitespace+'{' after
    lstrip (tag 0x0a = '\\n', length 123 = '{') must still take the
    protobuf path, and whitespace-prefixed JSON must still parse."""
    from deeprec_tpu.serving import cabi

    calls = []

    class FakeServer:  # never reached: both payloads fail validation first
        predictor = None

    def fake_json(server, payload):
        calls.append("json")
        return 200, b"{}"

    orig = cabi.process_json
    cabi.process_json = fake_json
    try:
        wire = PredictRequest(signature_name="x" * 123).serialize()
        assert wire.lstrip()[:1] == b"{"  # the trap this test guards
        code, body = cabi.process_request(FakeServer(), wire)
        # took the protobuf path: parsed fine, then failed feature
        # validation (no inputs) — NOT 'bad json'
        assert calls == [] and code == 400 and b"missing" in body
        cabi.process_request(FakeServer(), b'  \n {"features": {}}')
        # leading-whitespace JSON: proto parse fails -> JSON fallback
        assert calls == ["json"]
    finally:
        cabi.process_json = orig
