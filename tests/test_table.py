"""Core hash-embedding table tests — the CRUD/filter/eviction coverage of
DeepRec's embedding_variable_ops_test (reference: core/kernels/
embedding_variable_ops_test.cc, python/ops/embedding_variable_ops_test.py)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeprec_tpu import (
    CBFFilter,
    CounterFilter,
    EmbeddingTable,
    EmbeddingVariableOption,
    GlobalStepEvict,
    InitializerOption,
    L2WeightEvict,
    TableConfig,
    combine,
)


def make_table(**kw):
    base = dict(name="t", dim=8, capacity=256)
    base.update(kw)
    return EmbeddingTable(TableConfig(**base))


def test_create_and_lookup_inserts_keys():
    t = make_table()
    s = t.create()
    ids = jnp.array([3, 7, 3, 11, 7, 3], jnp.int32)
    s, res = t.lookup_unique(s, ids, step=1)
    assert int(t.size(s)) == 3
    # all real ids resolved to distinct slots
    valid = np.asarray(res.valid)
    slots = np.asarray(res.slot_ix)[valid]
    assert (slots >= 0).all()
    assert len(set(slots.tolist())) == len(slots)
    # counts reflect duplication
    uids = np.asarray(res.uids)
    counts = {int(u): int(c) for u, c, v in zip(uids, np.asarray(res.counts), valid) if v}
    assert counts == {3: 3, 7: 2, 11: 1}


def test_lookup_is_stable_across_calls():
    t = make_table()
    s = t.create()
    ids = jnp.arange(32, dtype=jnp.int32)
    s, r1 = t.lookup_unique(s, ids, step=1)
    s, r2 = t.lookup_unique(s, ids, step=2)
    np.testing.assert_array_equal(np.asarray(r1.slot_ix), np.asarray(r2.slot_ix))
    np.testing.assert_allclose(
        np.asarray(r1.embeddings), np.asarray(r2.embeddings), rtol=1e-6
    )
    assert int(t.size(s)) == 32


def test_initializer_deterministic_per_key():
    t = make_table()
    s1 = t.create()
    s2 = t.create()
    ids = jnp.array([5, 9], jnp.int32)
    # insert in different orders / tables — same key must get same init value
    s1, ra = t.lookup_unique(s1, ids)
    s2, rb = t.lookup_unique(s2, jnp.array([9, 100, 5], jnp.int32))
    ua, ea = np.asarray(ra.uids), np.asarray(ra.embeddings)
    ub, eb = np.asarray(rb.uids), np.asarray(rb.embeddings)
    for k in (5, 9):
        va = ea[list(ua).index(k)]
        vb = eb[list(ub).index(k)]
        np.testing.assert_allclose(va, vb, rtol=1e-6)
    # init values look like N(0, 0.05): nonzero, small
    assert 0 < np.abs(ea).mean() < 0.2


def test_padding_ignored():
    t = make_table()
    s = t.create()
    ids = jnp.array([[1, 2, -1], [3, -1, -1]], jnp.int32)
    s, res = t.lookup_unique(s, ids, step=0)
    assert int(t.size(s)) == 3
    assert int(jnp.sum(res.counts)) == 3


def test_collision_heavy_insert_all_resolve():
    # capacity 64, insert 48 ids (75% load) — all must land via probing
    t = make_table(capacity=64)
    s = t.create()
    ids = jnp.arange(48, dtype=jnp.int32) * 7919  # scattered hashes
    s, res = t.lookup_unique(s, ids)
    assert int(t.size(s)) == 48
    assert int(s.insert_fails) == 0
    slots = np.asarray(res.slot_ix)[np.asarray(res.valid)]
    assert len(set(slots.tolist())) == 48


def test_table_full_reports_fails():
    t = make_table(capacity=16, max_probes=16)
    s = t.create()
    s, _ = t.lookup_unique(s, jnp.arange(16, dtype=jnp.int32) * 13)
    s, res = t.lookup_unique(s, (jnp.arange(8, dtype=jnp.int32) + 100) * 17)
    assert int(s.insert_fails) > 0
    # failed ids serve the no-permission default (0) and slot -1
    failed = np.asarray(res.slot_ix) < 0
    assert failed.any()


def test_freq_and_version_tracking():
    t = make_table()
    s = t.create()
    s, r1 = t.lookup_unique(s, jnp.array([42, 42, 7], jnp.int32), step=5)
    s, r2 = t.lookup_unique(s, jnp.array([42], jnp.int32), step=9)
    slot42 = int(np.asarray(r2.slot_ix)[list(np.asarray(r2.uids)).index(42)])
    assert int(s.freq[slot42]) == 3
    assert int(s.version[slot42]) == 9


def test_counter_filter_blocks_until_threshold():
    t = make_table(
        ev=EmbeddingVariableOption(counter_filter=CounterFilter(filter_freq=3))
    )
    s = t.create()
    ids = jnp.array([77], jnp.int32)
    s, r1 = t.lookup_unique(s, ids, step=0)  # freq 1: blocked
    s, r2 = t.lookup_unique(s, ids, step=1)  # freq 2: blocked
    s, r3 = t.lookup_unique(s, ids, step=2)  # freq 3: admitted
    i = list(np.asarray(r1.uids)).index(77)
    assert not bool(r1.admitted[i]) and not bool(r2.admitted[i])
    assert bool(r3.admitted[i])
    np.testing.assert_allclose(np.asarray(r1.embeddings[i]), 0.0)
    assert np.abs(np.asarray(r3.embeddings[i])).max() > 0


def test_cbf_filter_defers_slot_allocation():
    t = make_table(
        ev=EmbeddingVariableOption(
            cbf_filter=CBFFilter(filter_freq=2, max_element_size=1 << 12)
        )
    )
    s = t.create()
    ids = jnp.array([123], jnp.int32)
    s, r1 = t.lookup_unique(s, ids)
    assert int(t.size(s)) == 0  # below threshold: no slot consumed
    s, r2 = t.lookup_unique(s, ids)
    assert int(t.size(s)) == 1  # sketch count reached 2: admitted + created
    i = list(np.asarray(r2.uids)).index(123)
    assert int(r2.slot_ix[i]) >= 0


def test_global_step_eviction():
    t = make_table(
        ev=EmbeddingVariableOption(global_step_evict=GlobalStepEvict(steps_to_live=10))
    )
    s = t.create()
    s, _ = t.lookup_unique(s, jnp.array([1, 2], jnp.int32), step=0)
    s, _ = t.lookup_unique(s, jnp.array([2], jnp.int32), step=50)
    s = t.evict(s, step=55)
    assert int(t.size(s)) == 1  # key 1 (version 0) expired; key 2 survives
    # survivor still resolvable with its value intact
    s2, res = t.lookup_unique(s, jnp.array([2], jnp.int32), step=55)
    i = list(np.asarray(res.uids)).index(2)
    assert int(res.slot_ix[i]) >= 0


def test_l2_eviction():
    t = make_table(
        ev=EmbeddingVariableOption(l2_weight_evict=L2WeightEvict(l2_weight_threshold=0.5))
    )
    s = t.create()
    s, res = t.lookup_unique(s, jnp.array([1, 2], jnp.int32))
    # force key 1 tiny, key 2 large
    ix = {int(u): int(sl) for u, sl in zip(np.asarray(res.uids), np.asarray(res.slot_ix))}
    # Write through scatter_update so the (possibly packed) layout is honored.
    dim = t.cfg.dim
    s = t.scatter_update(
        s,
        jnp.array([ix[1], ix[2]], jnp.int32),
        jnp.stack([jnp.full((dim,), 0.001), jnp.full((dim,), 1.0)]),
    )
    s = t.evict(s, step=0)
    assert int(t.size(s)) == 1


def test_rebuild_preserves_values_and_grow():
    t = make_table(capacity=64)
    s = t.create()
    ids = jnp.arange(40, dtype=jnp.int32) * 3 + 1
    s, r1 = t.lookup_unique(s, ids, step=2)
    before = {
        int(u): np.asarray(r1.embeddings)[i]
        for i, u in enumerate(np.asarray(r1.uids))
        if bool(r1.valid[i])
    }
    s = t.grow(s, 256)
    assert s.capacity == 256
    assert int(t.size(s)) == 40
    t2 = EmbeddingTable(TableConfig(name="t", dim=8, capacity=256))
    s, r2 = t2.lookup_unique(s, ids, step=3)
    for i, u in enumerate(np.asarray(r2.uids)):
        if bool(r2.valid[i]):
            np.testing.assert_allclose(
                np.asarray(r2.embeddings)[i], before[int(u)], rtol=1e-6
            )


def test_scatter_update_and_dirty_tracking():
    t = make_table()
    s = t.create()
    s, res = t.lookup_unique(s, jnp.array([5, 6], jnp.int32))
    s = s.replace_meta(dirty=jnp.zeros_like(s.dirty))  # simulate post-save reset
    new_vals = jnp.ones_like(res.embeddings)
    s = t.scatter_update(s, res.slot_ix, new_vals, mask=res.valid)
    assert int(jnp.sum(s.dirty)) == 2
    emb = t.lookup_readonly(s, jnp.array([5], jnp.int32))
    np.testing.assert_allclose(np.asarray(emb[0]), 1.0)


def test_readonly_missing_serves_initializer():
    t = make_table()
    s = t.create()
    emb = t.lookup_readonly(s, jnp.array([999, -1], jnp.int32))
    assert np.abs(np.asarray(emb[0])).max() > 0  # initializer value
    np.testing.assert_allclose(np.asarray(emb[1]), 0.0)  # padding -> zeros


def test_combiners():
    emb_u = jnp.array([[1.0, 1.0], [2.0, 2.0], [0.0, 0.0]])
    inverse = jnp.array([[0, 1], [1, 2]])
    mask = jnp.array([[True, True], [True, False]])
    np.testing.assert_allclose(
        np.asarray(combine(emb_u, inverse, mask, "sum")), [[3, 3], [2, 2]]
    )
    np.testing.assert_allclose(
        np.asarray(combine(emb_u, inverse, mask, "mean")), [[1.5, 1.5], [2, 2]]
    )
    np.testing.assert_allclose(
        np.asarray(combine(emb_u, inverse, mask, "sqrtn")),
        [[3 / np.sqrt(2), 3 / np.sqrt(2)], [2, 2]],
    )


def test_lookup_jits_and_donates():
    t = make_table()

    @jax.jit
    def step(s, ids):
        s, res = t.lookup_unique(s, ids, step=0)
        return s, res.embeddings

    s = t.create()
    s, e1 = step(s, jnp.array([1, 2, 3], jnp.int32))
    s, e2 = step(s, jnp.array([3, 4, 5], jnp.int32))
    assert int(t.size(s)) == 5
