"""Pallas DMA gather kernel vs oracle (interpret mode on CPU)."""
import jax.numpy as jnp
import numpy as np

from deeprec_tpu.ops.pallas_gather import gather_rows


def test_gather_rows_matches_oracle():
    rng = np.random.default_rng(0)
    vals = jnp.asarray(rng.normal(0, 1, (512, 128)).astype(np.float32))
    ix = jnp.asarray(rng.integers(0, 512, 128), jnp.int32)
    out = gather_rows(vals, ix, block=16, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(vals)[np.asarray(ix)], rtol=1e-6
    )


def test_gather_rows_clamps_out_of_range():
    vals = jnp.arange(64, dtype=jnp.float32).reshape(8, 8) * jnp.ones((8, 8))
    ix = jnp.array([-5, 100, 3, 0, 7, 2, 1, 6], jnp.int32)
    out = gather_rows(vals, ix, block=8, interpret=True)
    expect = np.asarray(vals)[np.clip(np.asarray(ix), 0, 7)]
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-6)
