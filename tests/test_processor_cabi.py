"""Serving C ABI (native/processor.cpp + serving/cabi.py).

Drives the real shared library through ctypes exactly as an external RPC
host would through dlopen: initialize() with a JSON model config, process()
with JSON requests (good, client-error, and post-hot-swap), batch_process,
get_serving_model_info, shutdown. The embedded-interpreter path is
short-circuited (Python is already running), which is the documented
ctypes mode of the library; the symbol contract matches the reference's
serving/processor/serving/processor.h."""
import ctypes
import json
import os
import subprocess

import jax.numpy as jnp
import numpy as np
import optax
import pytest

from deeprec_tpu.data import SyntheticCriteo
from deeprec_tpu.models import WDL
from deeprec_tpu.optim import Adagrad
from deeprec_tpu.training import Trainer
from deeprec_tpu.training.checkpoint import CheckpointManager

NATIVE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "deeprec_tpu", "native",
)
SO = os.path.join(NATIVE, "libdeeprec_processor.so")
# One source of truth for the served model's hyperparameters (fixture +
# the pure-C host test restore the same checkpoint).
MODEL_ARGS = dict(emb_dim=8, capacity=1 << 12, hidden=(32,), num_cat=4,
                  num_dense=2)


def _build_lib():
    try:
        subprocess.run(["make", "-s", "processor"], cwd=NATIVE, check=True,
                       capture_output=True, timeout=180)
    except Exception as e:
        pytest.skip(f"cannot build libdeeprec_processor.so: {e}")
    lib = ctypes.CDLL(SO)
    lib.initialize.restype = ctypes.c_void_p
    lib.initialize.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                               ctypes.POINTER(ctypes.c_int)]
    lib.process.restype = ctypes.c_int
    lib.process.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
                            ctypes.POINTER(ctypes.c_void_p),
                            ctypes.POINTER(ctypes.c_int)]
    lib.get_serving_model_info.restype = ctypes.c_int
    lib.get_serving_model_info.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_int),
    ]
    lib.free_buffer.argtypes = [ctypes.c_void_p]
    lib.shutdown_processor.argtypes = [ctypes.c_void_p]
    lib.batch_process.restype = ctypes.c_int
    lib.batch_process.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_char_p),
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_int),
    ]
    return lib


def _call_json(lib, fn, handle, payload=None):
    out = ctypes.c_void_p()
    n = ctypes.c_int()
    if payload is None:
        rc = fn(handle, ctypes.byref(out), ctypes.byref(n))
    else:
        rc = fn(handle, payload, len(payload), ctypes.byref(out),
                ctypes.byref(n))
    body = ctypes.string_at(out, n.value) if out.value else b"{}"
    if out.value:
        lib.free_buffer(out)
    return rc, json.loads(body)


@pytest.fixture(scope="module")
def served(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("cabi")
    model_args = MODEL_ARGS
    tr = Trainer(WDL(**model_args), Adagrad(lr=0.1), optax.adam(1e-3))
    st = tr.init(0)
    g = SyntheticCriteo(batch_size=128, num_cat=4, num_dense=2, vocab=900,
                        seed=5)
    batches = [
        {k: jnp.asarray(v) for k, v in g.batch().items()} for _ in range(3)
    ]
    for b in batches:
        st, _ = tr.train_step(st, b)
    ck = CheckpointManager(str(tmp), tr)
    st, _ = ck.save(st)

    lib = _build_lib()
    cfg = {
        "model": "wdl",
        "ckpt_dir": str(tmp),
        "model_args": {**model_args, "hidden": list(model_args["hidden"])},
        "max_wait_ms": 1.0,
        "poll_secs": 0.2,
    }
    state = ctypes.c_int(-2)
    handle = lib.initialize(b"", json.dumps(cfg).encode(),
                            ctypes.byref(state))
    assert state.value == 0 and handle
    yield lib, handle, tr, st, ck, batches
    lib.shutdown_processor(handle)


def test_process_matches_inprocess_predictor(served):
    lib, handle, tr, st, ck, batches = served
    b0 = {k: np.asarray(v) for k, v in batches[0].items() if k != "label"}
    feats = {k: v.tolist() for k, v in b0.items()}
    rc, resp = _call_json(
        lib, lib.process, handle,
        json.dumps({"features": feats}).encode(),
    )
    assert rc == 200, resp
    preds = np.asarray(resp["predictions"], np.float32)
    _, ref = tr.eval_step(st, batches[0])
    np.testing.assert_allclose(preds, np.asarray(ref), rtol=2e-5, atol=2e-6)


def test_client_errors_are_400(served):
    lib, handle, *_ = served
    # Not JSON (and not a parseable PredictRequest either): the wire
    # sniffer routes non-'{' payloads to the protobuf path, whose error
    # bodies are plain text like the reference's (processor.cc:38-46).
    out = ctypes.c_void_p()
    n = ctypes.c_int()
    payload = b"not json at all"
    rc = lib.process(handle, payload, len(payload), ctypes.byref(out),
                     ctypes.byref(n))
    assert rc == 400
    assert b"PredictRequest" in ctypes.string_at(out, n.value)
    lib.free_buffer(out)
    rc, resp = _call_json(
        lib, lib.process, handle,
        json.dumps({"features": {"BOGUS": [1]}}).encode(),
    )
    assert rc == 400 and "mismatch" in resp["error"]


def test_model_info_and_hot_swap(served):
    import time

    lib, handle, tr, st, ck, batches = served
    rc, info = _call_json(lib, lib.get_serving_model_info, handle)
    assert rc == 200 and info["step"] == int(st.step)

    # write a newer full checkpoint; the handle's background poller
    # (cfg poll_secs=0.2) must hot-swap it and the C surface must see the
    # new step
    st2 = st
    for b in batches:
        st2, _ = tr.train_step(st2, b)
    st2, _ = ck.save(st2)
    deadline = time.time() + 30
    while time.time() < deadline:
        rc, info2 = _call_json(lib, lib.get_serving_model_info, handle)
        assert rc == 200
        if info2["step"] == int(st2.step):
            break
        time.sleep(0.2)
    assert info2["step"] == int(st2.step)


def test_batch_process(served):
    """Reference-ABI batch_process: batch-of-1 semantics (the reference's
    sizeof(input_data)/sizeof(void*) always yields 1, message_coding.cc:79),
    and NO null terminator — reference hosts don't write one."""
    lib, handle, tr, st, ck, batches = served
    b0 = {k: np.asarray(v)[:4] for k, v in batches[0].items()
          if k != "label"}
    payload = json.dumps(
        {"features": {k: v.tolist() for k, v in b0.items()}}
    ).encode()
    n_req = 3
    inputs = (ctypes.c_char_p * n_req)(*([payload] * n_req))
    sizes = (ctypes.c_int * n_req)(*([len(payload)] * n_req))
    outputs = (ctypes.c_void_p * n_req)()
    out_sizes = (ctypes.c_int * n_req)()
    rc = lib.batch_process(handle, inputs, sizes, outputs, out_sizes)
    assert rc == 200
    body = json.loads(ctypes.string_at(outputs[0], out_sizes[0]))
    assert len(body["predictions"]) == 4
    lib.free_buffer(outputs[0])
    assert not outputs[1] and not outputs[2]  # only request 0 processed


def test_batch_process_n(served):
    """Extension entry point: explicit request count, real batching."""
    lib, handle, tr, st, ck, batches = served
    lib.batch_process_n.restype = ctypes.c_int
    lib.batch_process_n.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_char_p),
        ctypes.POINTER(ctypes.c_int), ctypes.c_int,
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_int),
    ]
    b0 = {k: np.asarray(v)[:4] for k, v in batches[0].items()
          if k != "label"}
    payload = json.dumps(
        {"features": {k: v.tolist() for k, v in b0.items()}}
    ).encode()
    n_req = 3
    inputs = (ctypes.c_char_p * n_req)(*([payload] * n_req))
    sizes = (ctypes.c_int * n_req)(*([len(payload)] * n_req))
    outputs = (ctypes.c_void_p * n_req)()
    out_sizes = (ctypes.c_int * n_req)()
    rc = lib.batch_process_n(handle, inputs, sizes, n_req, outputs, out_sizes)
    assert rc == 200
    for i in range(n_req):
        body = json.loads(ctypes.string_at(outputs[i], out_sizes[i]))
        assert len(body["predictions"]) == 4
        lib.free_buffer(outputs[i])

    # A size-0 slot is a client error for that slot (no info-ping semantics
    # inside an explicit-count batch); the good slot still serves.
    sizes2 = (ctypes.c_int * 2)(0, len(payload))
    inputs2 = (ctypes.c_char_p * 2)(payload, payload)
    outputs2 = (ctypes.c_void_p * 2)()
    out_sizes2 = (ctypes.c_int * 2)()
    rc = lib.batch_process_n(handle, inputs2, sizes2, 2, outputs2, out_sizes2)
    assert rc == 400
    err = json.loads(ctypes.string_at(outputs2[0], out_sizes2[0]))
    assert "error" in err
    ok = json.loads(ctypes.string_at(outputs2[1], out_sizes2[1]))
    assert len(ok["predictions"]) == 4
    for o in outputs2:
        lib.free_buffer(o)


def test_process_empty_payload_returns_model_info(served):
    """input_size==0 mirrors the reference (processor.cc:29-34): model
    debug/serving info with status 200, not a 400."""
    lib, handle, tr, st, ck, batches = served
    out = ctypes.c_void_p()
    n = ctypes.c_int()
    rc = lib.process(handle, b"", 0, ctypes.byref(out), ctypes.byref(n))
    assert rc == 200
    info = json.loads(ctypes.string_at(out, n.value))
    lib.free_buffer(out)
    assert "step" in info


def test_process_protobuf_payload(served):
    """A reference-built host's serialized PredictRequest through the real
    .so: process() sniffs protobuf, returns a PredictResponse."""
    from deeprec_tpu.serving.predict_pb import (
        ArrayProto,
        PredictRequest,
        PredictResponse,
    )

    lib, handle, tr, st, ck, batches = served
    feats = {k: np.asarray(v)[:4] for k, v in batches[0].items()
             if k != "label"}
    wire = PredictRequest(
        inputs={k: ArrayProto.from_numpy(v) for k, v in feats.items()}
    ).serialize()
    out = ctypes.c_void_p()
    n = ctypes.c_int()
    rc = lib.process(handle, wire, len(wire), ctypes.byref(out),
                     ctypes.byref(n))
    assert rc == 200
    resp = PredictResponse.parse(ctypes.string_at(out, n.value))
    lib.free_buffer(out)
    probs = resp.outputs["probabilities"].to_numpy()
    assert probs.shape[0] == 4
    assert np.all((probs >= 0) & (probs <= 1))


@pytest.mark.slow
def test_pure_c_host_boots_embedded_interpreter(served, tmp_path):
    """The EAS integration path for real: a PURE C program (no Python
    running) dlopens libdeeprec_processor.so, which must boot the
    embedded CPython interpreter itself (the initialize() branch the
    ctypes fixture short-circuits), serve a request, and shut down."""
    import sys

    lib, handle, tr, st, ck, batches = served  # reuse the trained ckpt dir
    r = subprocess.run(["make", "-s", "chost"], cwd=NATIVE,
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr

    cfg = {
        "model": "wdl",
        "ckpt_dir": str(ck.dir),
        "model_args": {**MODEL_ARGS, "hidden": list(MODEL_ARGS["hidden"])},
        "max_wait_ms": 1.0,
    }
    b0 = {k: np.asarray(v)[:2].tolist() for k, v in batches[0].items()
          if k != "label"}
    (tmp_path / "config.json").write_text(json.dumps(cfg))
    (tmp_path / "request.json").write_text(
        json.dumps({"features": b0}))

    import sysconfig

    repo = os.path.dirname(os.path.dirname(NATIVE.rstrip(os.sep)))
    env = {
        **os.environ,
        # The embedded interpreter needs the BASE install for the stdlib
        # (a venv prefix has no encodings/), plus the venv site-packages
        # and the repo on PYTHONPATH; jax pinned to CPU (the tunnel
        # plugin would wedge a TPU init).
        "PYTHONHOME": sys.base_prefix,
        "PYTHONPATH": os.pathsep.join(
            [repo, sysconfig.get_paths()["purelib"]]
        ),
        "JAX_PLATFORMS": "cpu",
    }
    r = subprocess.run(
        [os.path.join(NATIVE, "chost_demo"), SO,
         str(tmp_path / "config.json"), str(tmp_path / "request.json")],
        capture_output=True, text=True, timeout=280, env=env,
    )
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "process rc=200" in r.stdout
    body = json.loads(r.stdout.split("body=", 1)[1])
    assert len(body["predictions"]) == 2
