"""Overlapped tier paging: background probe/gather of upcoming batch ids
against the host/disk tiers, folded into the device table at dispatch
boundaries through one fixed-chunk compiled promote program — plus the
machinery that rides along (promote-scan diet, lookup_with_fallback dedup
+ row cache). docs/multi-tier-storage.md#overlapped-tier-paging."""
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from deeprec_tpu import (
    EmbeddingTable,
    EmbeddingVariableOption,
    StorageOption,
    TableConfig,
)
from deeprec_tpu.config import StorageType
from deeprec_tpu.embedding.multi_tier import MultiTierTable
from deeprec_tpu.embedding.tier_prefetch import TierPrefetcher
from deeprec_tpu.ops.packed import scatter_rows_any, unpack_array


def make(capacity=64, **kw):
    cfg = TableConfig(
        name="mt",
        dim=4,
        capacity=capacity,
        ev=EmbeddingVariableOption(
            storage=StorageOption(storage_type=StorageType.HBM_DRAM)
        ),
    )
    t = EmbeddingTable(cfg)
    return t, MultiTierTable(t, high_watermark=0.75, low_watermark=0.5, **kw)


def demote_marked(t, mt, n=52, value=3.25):
    """Insert n keys, write `value` everywhere, demote past the watermark.
    Returns (state, demoted key list)."""
    s = t.create()
    s, res = t.lookup_unique(s, jnp.arange(n, dtype=jnp.int32), step=0)
    s = t.scatter_update(
        s, res.slot_ix, jnp.full_like(res.embeddings, value), mask=res.valid
    )
    s, stats = mt.sync(s, step=1)
    assert stats.demoted > 0
    occ = np.asarray(t.occupied(s))
    on_dev = set(np.asarray(s.keys)[occ].tolist())
    return s, [k for k in range(n) if k not in on_dev]


# ------------------------------------------------------ probe / fold core


def test_probe_rows_dedups_and_stamps_revision():
    t, mt = make()
    s, demoted = demote_marked(t, mt)
    dup_ids = np.array(demoted[:5] * 3 + [9999, 10000], np.int64)
    cand = mt.probe_rows(dup_ids)
    # one candidate per DISTINCT resident id, misses filtered
    assert sorted(cand["keys"].tolist()) == sorted(demoted[:5])
    assert cand["rev"] == mt._gather_gen
    assert cand["rows"].shape[1] >= t.cfg.dim  # packed: values (+ slots)
    # nothing resident -> no package
    assert mt.probe_rows(np.array([9999], np.int64)) is None


def test_fold_restores_values_and_optimizer_slots_bit_exact():
    """A fold must be indistinguishable from a maintain-path promote:
    values AND packed per-row optimizer slots restore bit-exact."""
    from deeprec_tpu.optim import Adagrad
    from deeprec_tpu.optim.apply import ensure_slots

    t, _ = make()
    opt = Adagrad(lr=0.1, initial_accumulator_value=0.1)
    fills = tuple(
        (name, init) for name, (_, init) in opt.slot_specs(t.cfg.dim).items()
    )
    mt = MultiTierTable(t, high_watermark=0.75, low_watermark=0.5,
                        slot_fills=fills)
    s = ensure_slots(t, t.create(), opt)
    s, res = t.lookup_unique(s, jnp.arange(52, dtype=jnp.int32), step=0)
    keys = np.asarray(s.keys)
    occ0 = np.asarray(t.occupied(s))
    slot7 = int(np.nonzero(keys == 7)[0][0])
    put = jnp.asarray([slot7], jnp.int32)
    D = t.cfg.dim
    s = s.replace(
        values=scatter_rows_any(
            s.values, put, jnp.full((1, D), 2.5), s.capacity
        ),
        slots={
            **s.slots,
            "accum": scatter_rows_any(
                s.slots["accum"], put, jnp.full((1, D), 7.75), s.capacity
            ),
        },
    ).replace_meta(
        freq=jnp.where(jnp.asarray(occ0), 5, s.freq).at[slot7].set(1),
    )
    s, stats = mt.sync(s, step=1)
    assert stats.demoted > 0
    occ = np.asarray(t.occupied(s))
    assert 7 not in set(np.asarray(s.keys)[occ].tolist())

    cand = mt.probe_rows(np.array([7], np.int64))
    assert cand is not None and cand["keys"].tolist() == [7]
    # key reappears on device as a fresh insert (init values/slots)...
    s, _ = t.lookup_unique(s, jnp.asarray([7], jnp.int32), step=2)
    # ...and the fold restores the tier copy over it
    s, folded, dropped = mt.fold_candidates(s, cand, chunk=16)
    assert (folded, dropped) == (1, 0)
    keys = np.asarray(s.keys)
    occ = np.asarray(t.occupied(s))
    slot7 = int(np.nonzero((keys == 7) & occ)[0][0])
    np.testing.assert_array_equal(
        unpack_array(np.asarray(s.values), s.capacity)[slot7],
        np.full(D, 2.5, np.float32),
    )
    np.testing.assert_array_equal(
        unpack_array(np.asarray(s.slots["accum"]), s.capacity)[slot7],
        np.full(D, 7.75, np.float32),
    )
    # folded row's tier copy is consumed — same as a maintain promote
    assert mt.probe_rows(np.array([7], np.int64)) is None


def test_fold_loses_to_newer_device_row_bit_exact():
    """The PR 4 ambiguous-key rule at fold time: a key whose device copy
    trained PAST the tier copy mid-flight must not be clobbered — the
    fold drops it (bit-exact no-op on the device row), keeps the tier
    copy, and queues the key for the next promote scan's retry set."""
    t, mt = make()
    s, demoted = demote_marked(t, mt)
    k = demoted[0]
    cand = mt.probe_rows(np.array([k], np.int64))
    host_freq = int(cand["freqs"][0])

    # key reappears and TRAINS past the host copy: lookups drive freq
    # beyond the gathered freq snapshot
    kid = jnp.asarray([k], jnp.int32)
    for step in range(2, 4 + host_freq):
        s, res = t.lookup_unique(s, kid, step=step)
    s = t.scatter_update(
        s, res.slot_ix, jnp.full_like(res.embeddings, -8.5), mask=res.valid
    )

    before = np.asarray(t.lookup_readonly(s, kid)).copy()
    stale0 = mt._m_pf_stale.value
    s, folded, dropped = mt.fold_candidates(s, cand, chunk=16)
    assert (folded, dropped) == (0, 1)
    assert mt._m_pf_stale.value == stale0 + 1
    np.testing.assert_array_equal(np.asarray(t.lookup_readonly(s, kid)), before)
    # tier copy kept for the next scan; key rides the retry set
    assert mt.probe_rows(np.array([k], np.int64)) is not None
    assert k in mt._retry_keys
    # ...and the next maintain scan resolves it (erases the stale host
    # copy — the device copy is newer — instead of retrying forever)
    s, _ = mt.sync(s, step=50)
    assert k not in mt._retry_keys


def test_fold_inserts_missing_keys_ahead_of_lookup():
    """The point of paging: a prefetched row lands BEFORE the lookup that
    would have fresh-initialized it. Keys not yet device-resident INSERT
    with the tier copy's values, freq, version, and a raised dirty bit."""
    from deeprec_tpu.embedding.table import META_DIRTY, META_VERSION

    t, mt = make()
    s, demoted = demote_marked(t, mt)
    picks = demoted[:4]
    cand = mt.probe_rows(np.asarray(picks, np.int64))
    assert cand is not None and len(cand["keys"]) == 4
    occ = np.asarray(t.occupied(s))
    assert not (set(picks) & set(np.asarray(s.keys)[occ].tolist()))

    s, folded, dropped = mt.fold_candidates(s, cand, chunk=16)
    assert (folded, dropped) == (4, 0)
    keys = np.asarray(s.keys)
    occ = np.asarray(t.occupied(s))
    meta = np.asarray(s.meta)
    for i, k in enumerate(cand["keys"].tolist()):
        slot = int(np.nonzero((keys == k) & occ)[0][0])
        np.testing.assert_array_equal(
            unpack_array(np.asarray(s.values), s.capacity)[slot],
            np.full(t.cfg.dim, 3.25, np.float32),
        )
        # tier meta travels with the insert; dirty marks it for the next
        # incremental checkpoint even before its first lookup
        assert meta[0, slot] == int(cand["freqs"][i])  # META_FREQ
        assert meta[META_VERSION, slot] == int(cand["vers"][i])
        assert meta[META_DIRTY, slot] == 1
        # tier copy consumed
    assert mt.probe_rows(np.asarray(picks, np.int64)) is None


def test_fold_erase_keeps_other_packages_valid():
    """Pure erasures (another package's fold) must NOT retire in-flight
    gathers — their content is bit-identical and fold revalidation guards
    against anything the device trained past. Only row-WRITING boundaries
    (demote, load) bump the gather generation."""
    t, mt = make()
    s, demoted = demote_marked(t, mt)
    cand_b = mt.probe_rows(np.asarray(demoted[3:6], np.int64))
    cand_a = mt.probe_rows(np.asarray(demoted[:3], np.int64))
    s, folded, _ = mt.fold_candidates(s, cand_a, chunk=16)
    assert folded == 3  # erased a's tier copies, bumped _tier_rev only
    assert cand_b["rev"] == mt._gather_gen
    s, folded, dropped = mt.fold_candidates(s, cand_b, chunk=16)
    assert (folded, dropped) == (3, 0)


def test_fold_drops_whole_package_on_revision_change():
    """Version-keyed in-flight gathers: a row-WRITING boundary (demote at
    sync, load) between gather and fold invalidates the package whole."""
    t, mt = make()
    s, demoted = demote_marked(t, mt)
    cand = mt.probe_rows(np.asarray(demoted[:3], np.int64))
    s, _ = t.lookup_unique(
        s, jnp.asarray(demoted[:3], jnp.int32), step=2
    )
    s, _ = mt.sync(s, step=3)  # boundary: stores mutated, generation bumped
    assert cand["rev"] != mt._gather_gen
    stale0 = mt._m_pf_stale.value
    s, folded, dropped = mt.fold_candidates(s, cand, chunk=16)
    assert folded == 0 and dropped == 3
    assert mt._m_pf_stale.value == stale0 + 3


def test_fold_fixed_chunk_zero_steady_state_compiles():
    """The fold program compiles once per (table, chunk) and never again —
    candidate-count jitter pads into the same chunk shape."""
    from deeprec_tpu.analysis import trace_guard

    t, mt = make(capacity=128)
    s = t.create()
    s, res = t.lookup_unique(s, jnp.arange(100, dtype=jnp.int32), step=0)
    s = t.scatter_update(
        s, res.slot_ix, jnp.full_like(res.embeddings, 1.5), mask=res.valid
    )
    s, stats = mt.sync(s, step=1)
    assert stats.demoted > 8

    demoted = sorted(int(k) for k in mt.host.export()[0])
    # bring every candidate key back on device OUTSIDE the guarded region
    # (the test's own variable-width lookups would compile; the fold must
    # not) and gather one package per fold round
    groups = [demoted[:3], demoted[3:5], demoted[5:10], demoted[10:11]]
    s, _ = t.lookup_unique(s, jnp.asarray(demoted, jnp.int32), step=2)

    # probe right before each fold — probe_rows is numpy-only, so the
    # guarded region sees exactly the fold programs and nothing else
    cand = mt.probe_rows(np.asarray(groups[0], np.int64))
    s, folded, _ = mt.fold_candidates(s, cand, chunk=8)  # warm chunk
    assert folded == len(groups[0])
    with trace_guard(max_compiles=0, note="tier fold steady state"):
        for g in groups[1:]:
            cand = mt.probe_rows(np.asarray(g, np.int64))  # numpy-only
            s, folded, _ = mt.fold_candidates(s, cand, chunk=8)
            assert folded == len(g)  # counts jitter, shape is the chunk


# ------------------------------------------------------ promote-scan diet


def _replay(scan_diet, steps=14, capacity=64, vocab=90, seed=3):
    """Replay one deterministic rotated-id stream through sync boundaries;
    return (final device state, sorted host keys, per-boundary promote
    counts)."""
    t, mt = make(capacity=capacity, scan_diet=scan_diet)
    s = t.create()
    rng = np.random.default_rng(seed)
    promotes = []
    for i in range(steps):
        ids = rng.integers((i * 7) % 30, vocab, size=24)
        s, _ = t.lookup_unique(
            s, jnp.asarray(ids, jnp.int32), step=2 * i
        )
        if i % 3 == 2:
            s, stats = mt.sync(s, step=2 * i + 1)
            promotes.append((stats.promoted, stats.demoted))
    host_keys = sorted(int(k) for k in mt.host.export()[0])
    return s, host_keys, promotes


def test_scan_diet_bit_identical_promote_outcomes():
    """The diet (scan only window-touched + retry keys) must be invisible:
    bit-identical device state, host store, and promote/demote counts on
    a replayed stream vs the full scan."""
    s_on, host_on, prom_on = _replay(scan_diet=True)
    s_off, host_off, prom_off = _replay(scan_diet=False)
    assert prom_on == prom_off
    assert any(p > 0 for p, _ in prom_on)  # stream actually promotes
    assert host_on == host_off
    for a, b in zip(jax.tree.leaves(s_on), jax.tree.leaves(s_off)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ------------------------------------- serving: dedup + row cache


def test_lookup_with_fallback_dedup_parity():
    """Dedup-before-get serves bit-identical embeddings to the per-
    position path on a repeat-heavy stream, paying one native probe per
    DISTINCT id."""
    t, mt = make()
    s, demoted = demote_marked(t, mt, value=3.25)
    rng = np.random.default_rng(0)
    ids = rng.choice(np.arange(52), size=400, replace=True).astype(np.int32)

    calls = []
    orig_get = mt.host.get

    def counting_get(keys):
        calls.append(len(keys))
        return orig_get(keys)

    mt.host.get = counting_get
    emb = np.asarray(mt.lookup_with_fallback(s, jnp.asarray(ids)))
    mt.host.get = orig_get
    # one probe over the uniques, not one per position
    assert calls == [len(np.unique(ids))]

    # reference: per-position fallback against the same stores
    ref = np.array(t.lookup_readonly(s, jnp.asarray(ids)))
    vals, _, _, found = mt.host.get(ids.astype(np.int64))
    ref[found] = vals[found][:, : t.cfg.dim]
    np.testing.assert_array_equal(emb, ref)


def test_row_cache_serves_hits_without_store_probes():
    t, mt = make(row_cache_bytes=1 << 20)
    s, demoted = demote_marked(t, mt, value=3.25)
    ids = jnp.asarray(demoted[:8], jnp.int32)
    first = np.asarray(mt.lookup_with_fallback(s, ids))

    calls = []
    orig_get = mt.host.get
    mt.host.get = lambda keys: (calls.append(len(keys)), orig_get(keys))[1]
    second = np.asarray(mt.lookup_with_fallback(s, ids))
    mt.host.get = orig_get
    assert calls == []  # all rows served from the cache
    np.testing.assert_array_equal(first, second)


def test_row_cache_never_crosses_a_sync_boundary_that_changed_the_row():
    """The PR 17 version-keyed discipline applied to rows: a sync that
    re-demotes a retrained row invalidates the cached copy."""
    t, mt = make(row_cache_bytes=1 << 20)
    s, demoted = demote_marked(t, mt, value=3.25)
    k = demoted[0]
    kid = jnp.asarray([k], jnp.int32)
    cached = np.asarray(mt.lookup_with_fallback(s, kid))
    np.testing.assert_allclose(cached[0], 3.25)

    # key reappears, trains to a NEW value, and a boundary demotes it again
    s, _ = t.lookup_unique(s, kid, step=2)
    s, _ = mt.sync(s, step=3)  # promotes the host copy back
    s, res = t.lookup_unique(s, kid, step=4)
    s = t.scatter_update(
        s, res.slot_ix, jnp.full_like(res.embeddings, 6.5), mask=res.valid
    )
    occ = np.asarray(t.occupied(s))
    s = s.replace_meta(
        freq=jnp.where(
            jnp.asarray(occ), 5, s.freq
        ).at[int(np.nonzero(np.asarray(s.keys) == k)[0][0])].set(1),
    )
    # force: occupancy sits under the high watermark after the first
    # demotion — the boundary must still demote the coldest row (k)
    s, stats = mt.sync(s, step=5, force=True)
    vals, _, _, found = mt.host.get(np.asarray([k], np.int64))
    assert found[0] and vals[0, 0] == 6.5
    served = np.asarray(mt.lookup_with_fallback(s, kid))
    np.testing.assert_allclose(served[0], 6.5)  # not the cached 3.25


# ------------------------------------------------- prefetcher pump races


def _pump_fixture():
    t, mt = make()
    s, demoted = demote_marked(t, mt)
    tiers = {("b", ()): mt}
    pager = TierPrefetcher(
        resolve=tiers.get,
        extract=lambda batch: {("b", ()): batch["ids"]},
        depth=4,
    )
    return t, mt, s, demoted, pager


def test_pump_gathers_and_training_thread_folds():
    t, mt, s, demoted, pager = _pump_fixture()
    try:
        pager.observe({"ids": np.asarray(demoted[:4], np.int64)})
        pager.observe({"ids": np.asarray(demoted[2:6], np.int64)})
        assert pager.drain(5.0)
        assert pager.pending_keys() == [("b", ())]
        cand = pager.take(("b", ()))
        # merged across batches, deduped
        assert sorted(cand["keys"].tolist()) == sorted(demoted[:6])
        s, _ = t.lookup_unique(
            s, jnp.asarray(demoted[:6], jnp.int32), step=2
        )
        s, folded, dropped = mt.fold_candidates(s, cand, chunk=16)
        assert (folded, dropped) == (6, 0)
        assert pager.take(("b", ())) is None  # consumed
    finally:
        pager.close()


def test_pump_killed_mid_gather_leaves_stores_consistent():
    """Gathers are read-only: a pump killed (or erroring) mid-gather must
    leave the tier stores consistent and the next maintain converge."""
    t, mt, s, demoted, pager = _pump_fixture()
    host_before = sorted(int(k) for k in mt.host.export()[0])

    import threading

    entered = threading.Event()

    def die_mid_gather(batch):
        entered.set()
        raise RuntimeError("killed mid-gather")

    pager.on_gather = die_mid_gather
    pager.observe({"ids": np.asarray(demoted, np.int64)})
    assert entered.wait(5.0)
    assert pager.drain(5.0)
    pager.close()  # and the thread itself dies cleanly
    assert pager.stats()["gather_errors"] == 1
    assert pager.pending_keys() == []

    # stores untouched by the aborted gather
    assert sorted(int(k) for k in mt.host.export()[0]) == host_before
    # the keys it never delivered still promote through the normal scan
    s, _ = t.lookup_unique(s, jnp.asarray(demoted[:4], jnp.int32), step=2)
    s, stats = mt.sync(s, step=3)
    assert stats.promoted >= 4
    emb = np.asarray(
        t.lookup_readonly(s, jnp.asarray(demoted[:4], jnp.int32))
    )
    np.testing.assert_allclose(emb, 3.25)


def test_pump_close_mid_gather_unblocks():
    """close() while a gather is in flight returns promptly and the
    observe() path becomes a no-op."""
    import threading

    t, mt, s, demoted, pager = _pump_fixture()
    hold = threading.Event()
    entered = threading.Event()

    def block(batch):
        entered.set()
        hold.wait(5.0)

    pager.on_gather = block
    pager.observe({"ids": np.asarray(demoted, np.int64)})
    assert entered.wait(5.0)
    closer = threading.Thread(target=pager.close)
    closer.start()
    time.sleep(0.05)
    hold.set()  # release the in-flight gather; close() must now finish
    closer.join(timeout=5.0)
    assert not closer.is_alive()
    pager.observe({"ids": np.asarray(demoted, np.int64)})  # no-op, no raise
    assert pager.stats()["dropped_batches"] == 0


# --------------------------------------------------- trainer integration


def _trainer(pipeline_mode="off", capacity=256, seed=0):
    from deeprec_tpu.models import WDL
    from deeprec_tpu.optim import Adagrad
    from deeprec_tpu.training import Trainer

    ev = EmbeddingVariableOption(
        storage=StorageOption(storage_type=StorageType.HBM_DRAM)
    )
    model = WDL(emb_dim=4, capacity=capacity, hidden=(16,), num_cat=2,
                num_dense=2, ev=ev)
    tr = Trainer(model, Adagrad(lr=0.2), optax.adam(5e-3),
                 pipeline_mode=pipeline_mode)
    return tr, tr.init(seed)


def _stream(n, vocab=280, seed=0, B=256):
    from deeprec_tpu.data import SyntheticCriteo

    gen = SyntheticCriteo(batch_size=B, num_cat=2, num_dense=2,
                          vocab=vocab, seed=seed)
    return [{k: np.asarray(v) for k, v in gen.batch().items()}
            for _ in range(n)]


def test_trainer_paging_end_to_end_through_staged_pipeline():
    """Full wire: enable_tier_paging -> stage() taps the Prefetcher peek
    -> pump gathers demoted rows -> fold_tier_prefetch restores them at
    dispatch boundaries, off the maintain() cadence."""
    tr, st = _trainer()
    pager = tr.enable_tier_paging(depth=8, chunk=64)
    try:
        folds = 0
        for i, b in enumerate(tr.stage(iter(_stream(24)), depth=2)):
            st, mets = tr.train_step(st, b)
            if (i + 1) % 8 == 0:
                st, _ = tr.maintain(st)
            pager.drain(5.0)
            st, frep = tr.fold_tier_prefetch(st)
            folds += sum(r["folded"] for r in frep.values())
        assert folds > 0, "stream never exercised a fold"
        assert np.isfinite(float(mets["loss"]))
        stats = tr.tier_paging_stats()
        assert stats["folded_rows"] == folds
        assert stats["fold_bytes"] > 0
        assert stats["gather_errors"] == 0
    finally:
        tr.close_tier_paging()


def test_kstep_lookahead_parity_with_paging_on():
    """pipeline_mode='lookahead' K-step scan with paging on stays bit-
    identical to pipeline_mode='off' — folds land at dispatch boundaries
    only, so the pipelined schedule sees the same tables."""
    from deeprec_tpu.training.trainer import stack_batches

    K = 4
    stream = _stream(16, seed=7)
    finals = {}
    for mode in ("off", "lookahead"):
        tr, st = _trainer(pipeline_mode=mode)
        pager = tr.enable_tier_paging(depth=16, chunk=64)
        try:
            losses = []
            for i in range(0, len(stream), K):
                chunk = stream[i:i + K]
                for b in chunk:
                    pager.observe(b)
                st, mets = tr.train_steps(st, stack_batches(chunk))
                losses.append(np.asarray(mets["loss"]))
                if (i // K) % 2 == 1:
                    st, _ = tr.maintain(st)
                pager.drain(5.0)
                st, _ = tr.fold_tier_prefetch(st)
            finals[mode] = (st, losses,
                            tr.tier_paging_stats()["folded_rows"])
        finally:
            tr.close_tier_paging()
    st_off, losses_off, folds_off = finals["off"]
    st_la, losses_la, folds_la = finals["lookahead"]
    assert folds_off > 0 and folds_off == folds_la
    np.testing.assert_array_equal(
        np.stack(losses_off), np.stack(losses_la)
    )
    for a, b in zip(jax.tree.leaves(st_off.tables),
                    jax.tree.leaves(st_la.tables)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_async_maintain_with_paging_converges():
    """tier_async=True rounds + the pump + folds interleave without
    deadlock or store corruption (the _store_lock protocol)."""
    tr, st = _trainer()
    pager = tr.enable_tier_paging(depth=8, chunk=64)
    try:
        for i, b in enumerate(tr.stage(iter(_stream(20)), depth=2)):
            st, mets = tr.train_step(st, b)
            if (i + 1) % 5 == 0:
                st, _ = tr.maintain(st, tier_async=True)
            st, _ = tr.fold_tier_prefetch(st)
        st, rep = tr.maintain(st)  # final settle (drains pending rounds)
        assert np.isfinite(float(mets["loss"]))
        assert pager.stats()["gather_errors"] == 0
    finally:
        tr.close_tier_paging()


def test_sharded_trainer_refuses_paging():
    from deeprec_tpu.parallel import ShardedTrainer, make_mesh
    from deeprec_tpu.models import WDL
    from deeprec_tpu.optim import Adagrad

    ev = EmbeddingVariableOption(
        storage=StorageOption(storage_type=StorageType.HBM_DRAM)
    )
    model = WDL(emb_dim=4, capacity=512, hidden=(16,), num_cat=2,
                num_dense=2, ev=ev)
    tr = ShardedTrainer(model, Adagrad(lr=0.2), optax.adam(5e-3),
                        mesh=make_mesh(8))
    with pytest.raises(NotImplementedError):
        tr.enable_tier_paging()


def test_prefetch_counters_registered_and_rendered():
    """Obs satellites: the tier-paging counters/gauge land on the process
    registry with the catalog names (docs/observability.md)."""
    from deeprec_tpu.obs import metrics as obs_metrics

    if not obs_metrics.metrics_enabled():
        pytest.skip("obs disabled")
    t, mt = make()
    s, demoted = demote_marked(t, mt)
    cand = mt.probe_rows(np.asarray(demoted[:3] * 2, np.int64))
    s, _ = t.lookup_unique(s, jnp.asarray(demoted[:3], jnp.int32), step=2)
    s, folded, _ = mt.fold_candidates(s, cand, chunk=16)
    assert folded == 3
    text = obs_metrics.default_registry().render_prometheus()
    for name in (
        "deeprec_tier_prefetch_probed_total",
        "deeprec_tier_prefetch_hits_total",
        "deeprec_tier_prefetch_folds_total",
        "deeprec_tier_prefetch_stale_dropped_total",
        "deeprec_tier_prefetch_fold_lag_ms",
    ):
        assert name in text, name
