"""Flash attention + ring attention correctness vs the reference oracle.

Oracle comparisons run at HIGHEST matmul precision: jax>=0.9 Pallas
interpret mode emulates the TPU's default bf16-multiply precision, so at
"default" the kernel and the f32 CPU oracle legitimately differ at ~5e-3.
Production keeps the default (bf16 multiplies, f32 accumulation) for MXU
throughput; these tests pin f32 multiplies on both sides to compare math,
not hardware rounding.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _f32_matmuls():
    with jax.default_matmul_precision("highest"):
        yield

from deeprec_tpu.ops.flash_attention import (
    attention_reference,
    flash_attention,
)
from deeprec_tpu.parallel import make_mesh
from deeprec_tpu.parallel.ring_attention import ring_attention_sharded


def _inputs(B=2, H=2, L=256, D=32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 4)
    q = jax.random.normal(ks[0], (B, H, L, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, H, L, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, H, L, D), jnp.float32)
    lengths = jax.random.randint(ks[3], (B,), L // 2, L + 1)
    mask = jnp.arange(L)[None, :] < lengths[:, None]
    return q, k, v, mask


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference(causal):
    q, k, v, mask = _inputs()
    ref = attention_reference(q, k, v, mask, causal=causal)
    out = flash_attention(q, k, v, mask, causal, None, 64, 64, True)
    valid = np.asarray(mask)  # rows beyond length still produce finite values
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_gradients_match_reference():
    q, k, v, mask = _inputs(L=128, D=16)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, mask, False, None, 64, 64, True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, mask) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_reference(causal):
    mesh = make_mesh(8, axis="sp")
    B, H, L, D = 2, 2, 256, 16  # L sharded 8 ways -> 32 per device
    q, k, v, mask = _inputs(B=B, H=H, L=L, D=D, seed=3)
    ref = attention_reference(q, k, v, mask, causal=causal)
    out = ring_attention_sharded(mesh, q, k, v, mask, axis="sp", causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_attention_differentiable():
    mesh = make_mesh(4, axis="sp")
    q, k, v, mask = _inputs(B=1, H=1, L=64, D=8, seed=5)

    def loss(q, k, v):
        return jnp.sum(
            ring_attention_sharded(mesh, q, k, v, mask, axis="sp") ** 2
        )

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, mask) ** 2)

    g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-4)
