"""Flash attention + ring attention correctness vs the reference oracle.

Oracle comparisons run at HIGHEST matmul precision: jax>=0.9 Pallas
interpret mode emulates the TPU's default bf16-multiply precision, so at
"default" the kernel and the f32 CPU oracle legitimately differ at ~5e-3.
Production keeps the default (bf16 multiplies, f32 accumulation) for MXU
throughput; these tests pin f32 multiplies on both sides to compare math,
not hardware rounding.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _f32_matmuls():
    with jax.default_matmul_precision("highest"):
        yield

from deeprec_tpu.ops.flash_attention import (
    attention_reference,
    flash_attention,
)
from deeprec_tpu.parallel import make_mesh
from deeprec_tpu.parallel.ring_attention import ring_attention_sharded


def _inputs(B=2, H=2, L=256, D=32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 4)
    q = jax.random.normal(ks[0], (B, H, L, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, H, L, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, H, L, D), jnp.float32)
    lengths = jax.random.randint(ks[3], (B,), L // 2, L + 1)
    mask = jnp.arange(L)[None, :] < lengths[:, None]
    return q, k, v, mask


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference(causal):
    q, k, v, mask = _inputs()
    ref = attention_reference(q, k, v, mask, causal=causal)
    out = flash_attention(q, k, v, mask, causal, None, 64, 64, True)
    valid = np.asarray(mask)  # rows beyond length still produce finite values
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_gradients_match_reference():
    q, k, v, mask = _inputs(L=128, D=16)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, mask, False, None, 64, 64, True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, mask) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_pallas_backward_matches_reference(causal):
    """The Pallas dKdV/dQ kernels (interpret mode on CPU) against the
    autodiff of the dense oracle — exact-probability backward from the
    saved LSE, causal skip on both sides of the diagonal."""
    q, k, v, mask = _inputs(L=256, D=32, seed=5)

    def loss_flash(q, k, v):
        out = flash_attention(q, k, v, mask, causal, None, 64, 64, True)
        return jnp.sum(jnp.where(mask[:, None, :, None], out, 0.0) ** 2)

    def loss_ref(q, k, v):
        out = attention_reference(q, k, v, mask, causal=causal)
        return jnp.sum(jnp.where(mask[:, None, :, None], out, 0.0) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gf, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4, err_msg=f"d{name}"
        )


def test_blockwise_backward_matches_reference():
    """The non-TPU fallback (interpret=False on CPU routes fwd+bwd through
    the blockwise lax.scan path) stays grad-exact too."""
    q, k, v, mask = _inputs(L=128, D=16, seed=6)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, mask, True, None, 64, 64, False) ** 2
        )

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, mask, causal=True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-4)


@pytest.mark.parametrize("interpret", [True, False])
def test_fully_masked_row_zero_gradients(interpret):
    """A batch row whose mask is all-False attends to nothing: output 0,
    and the backward must contribute NOTHING from it (the saved LSE is
    ~NEG_INF there; an unguarded exp(s - lse) would emit p=1 garbage
    into dk/dv/dq). interpret=True drives the Pallas kernels, False the
    blockwise fallback."""
    q, k, v, _ = _inputs(B=2, L=128, D=16, seed=7)
    mask = jnp.asarray(np.array([[True] * 128, [False] * 128]))

    def loss(q, k, v):
        # linear loss -> do is nonzero even where the output is zero
        return jnp.sum(
            flash_attention(q, k, v, mask, False, None, 64, 64, interpret)
        )

    dq, dk, dv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for g in (dq, dk, dv):
        assert np.all(np.isfinite(np.asarray(g)))
    # the dead batch element contributes exactly nothing
    np.testing.assert_array_equal(np.asarray(dq[1]), 0.0)
    np.testing.assert_array_equal(np.asarray(dk[1]), 0.0)
    np.testing.assert_array_equal(np.asarray(dv[1]), 0.0)
    # the live batch element still matches the dense oracle
    ref = jax.grad(
        lambda q, k, v: jnp.sum(
            attention_reference(q[:1], k[:1], v[:1], mask[:1])
        ),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip((dq, dk, dv), ref):
        np.testing.assert_allclose(
            np.asarray(a[0]), np.asarray(b[0]), atol=3e-4
        )


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_reference(causal):
    mesh = make_mesh(8, axis="sp")
    B, H, L, D = 2, 2, 256, 16  # L sharded 8 ways -> 32 per device
    q, k, v, mask = _inputs(B=B, H=H, L=L, D=D, seed=3)
    ref = attention_reference(q, k, v, mask, causal=causal)
    out = ring_attention_sharded(mesh, q, k, v, mask, axis="sp", causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_attention_differentiable():
    mesh = make_mesh(4, axis="sp")
    q, k, v, mask = _inputs(B=1, H=1, L=64, D=8, seed=5)

    def loss(q, k, v):
        return jnp.sum(
            ring_attention_sharded(mesh, q, k, v, mask, axis="sp") ** 2
        )

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, mask) ** 2)

    g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-4)


def test_ring_attention_long_context():
    """SIM-scale sequence: L=2048 sharded 8 ways (256 per device). The
    whole point of ring attention is lengths no single device's O(L^2)
    scores could hold; correctness oracle is the blockwise flash forward,
    which never materializes L^2 either."""
    mesh = make_mesh(8, axis="sp")
    B, H, L, D = 1, 2, 2048, 16
    q, k, v, mask = _inputs(B=B, H=H, L=L, D=D, seed=7)
    out = ring_attention_sharded(mesh, q, k, v, mask, axis="sp")
    ref = flash_attention(q, k, v, mask, False, None, 128, 128, False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=3e-5)


def test_bst_flash_parity():
    """BST(use_flash=True) == BST(use_flash=False) on the same params and
    batch — the flash path (padded to a 128 multiple, Pallas on TPU,
    blockwise scan off-TPU) must be a drop-in for reference attention."""
    import optax

    from deeprec_tpu.data import SyntheticBehaviorSequence
    from deeprec_tpu.models import BST
    from deeprec_tpu.optim import Adagrad
    from deeprec_tpu.training import Trainer

    kw = dict(emb_dim=8, capacity=1 << 12, heads=2, ff=32, max_len=48,
              hidden=(32,))
    gen = SyntheticBehaviorSequence(batch_size=64, vocab=1500, seq_len=48,
                                    seed=3)
    batch = {k: jnp.asarray(v) for k, v in gen.batch().items()}
    outs = {}
    for flash in (False, True):
        tr = Trainer(BST(use_flash=flash, **kw), Adagrad(lr=0.1),
                     optax.adam(1e-3))
        st = tr.init(0)
        st, m = tr.train_step(st, batch)
        assert np.isfinite(float(m["loss"]))
        _, outs[flash] = tr.eval_step(st, batch)
    np.testing.assert_allclose(np.asarray(outs[True]),
                               np.asarray(outs[False]), atol=5e-5)
