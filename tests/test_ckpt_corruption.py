"""Checkpoint-corruption matrix: every way a committed chain link can rot
— truncated npz, bit-flipped payload, missing manifest, missing middle
link, corrupt full anchor — must restore the longest valid prefix
BIT-EXACTLY, quarantine the bad dir where one exists, and never raise
into serving (the Predictor serves through and the trainer's next save
self-heals the chain).

The write-side halves of these guarantees (manifest-last commit, digest
recording) live in training/checkpoint.py; the injectors in
online/faults.py are the same ones tools/bench_freshness.py drives."""
import json
import os
import shutil
from types import SimpleNamespace

import numpy as np
import pytest

from deeprec_tpu.online import faults
from deeprec_tpu.training.checkpoint import CheckpointCorrupt, CheckpointManager


def _mk_trainer():
    import optax

    from deeprec_tpu.models import WDL
    from deeprec_tpu.optim import Adagrad
    from deeprec_tpu.training import Trainer

    model = WDL(emb_dim=4, capacity=1 << 10, hidden=(16,), num_cat=2,
                num_dense=2)
    return Trainer(model, Adagrad(lr=0.2), optax.adam(5e-3)), model


def _tables_np(state):
    out = {}
    for bname, ts in state.tables.items():
        for name in ("keys", "meta", "values"):
            out[f"{bname}/{name}"] = np.asarray(getattr(ts, name))
    return out


def _assert_tables_equal(a, b):
    ka, kb = _tables_np(a), _tables_np(b)
    assert sorted(ka) == sorted(kb)
    for k in ka:
        np.testing.assert_array_equal(ka[k], kb[k])


@pytest.fixture(scope="module")
def chain(tmp_path_factory):
    """One full + two deltas, with a bit-exact restore REFERENCE captured
    after each link landed: refs[s] is what a fresh consumer restoring a
    chain that ends at step s must reproduce. Tests copy the dir and
    corrupt their copy."""
    import jax.numpy as jnp

    from deeprec_tpu.data import SyntheticCriteo

    base = str(tmp_path_factory.mktemp("chain") / "ck")
    tr, _ = _mk_trainer()
    gen = SyntheticCriteo(batch_size=96, num_cat=2, num_dense=2, vocab=300,
                          seed=3)

    def step(st):
        return tr.train_step(
            st, {k: jnp.asarray(v) for k, v in gen.batch().items()})[0]

    ck = CheckpointManager(base, tr)
    st = tr.init(0)
    refs = {}
    for _ in range(2):
        st = step(st)
    st, _ = ck.save(st)                # full-2
    refs[2] = CheckpointManager(base, _mk_trainer()[0]).restore()
    st = step(st)
    st, _ = ck.save_incremental(st)    # incr-3
    refs[3] = CheckpointManager(base, _mk_trainer()[0]).restore()
    st = step(st)
    st, _ = ck.save_incremental(st)    # incr-4
    refs[4] = CheckpointManager(base, _mk_trainer()[0]).restore()
    return SimpleNamespace(dir=base, refs=refs, mk=_mk_trainer)


def _copy(chain, tmp_path):
    dst = str(tmp_path / "ck")
    shutil.copytree(chain.dir, dst)
    return dst


def _table_file(path):
    return os.path.join(
        path, sorted(f for f in os.listdir(path) if f.startswith("table_"))[0]
    )


def test_manifest_records_digests_and_base(chain):
    with open(os.path.join(chain.dir, "incr-4", "manifest.json")) as f:
        m = json.load(f)
    assert m["base"] == 3  # link to incr-3
    assert any(f.startswith("table_") for f in m["digests"])
    assert "dense.npz" in m["digests"]
    for arrays in m["digests"].values():
        for digest in arrays.values():
            assert digest.startswith("crc32:")
    with open(os.path.join(chain.dir, "incr-3", "manifest.json")) as f:
        assert json.load(f)["base"] == 2  # link to full-2


def test_verify_passes_intact_and_catches_tamper(chain, tmp_path):
    d = _copy(chain, tmp_path)
    ck = CheckpointManager(d, chain.mk()[0])
    for link in ("full-2", "incr-3", "incr-4"):
        ck.verify(os.path.join(d, link))
    faults.flip_bit(_table_file(os.path.join(d, "incr-3")))
    ck2 = CheckpointManager(d, chain.mk()[0])  # fresh: no memoized verdicts
    with pytest.raises(CheckpointCorrupt):
        ck2.verify(os.path.join(d, "incr-3"))


def test_truncated_npz_restores_longest_prefix(chain, tmp_path):
    d = _copy(chain, tmp_path)
    faults.truncate_file(_table_file(os.path.join(d, "incr-4")))
    restored = CheckpointManager(d, chain.mk()[0]).restore()
    assert int(restored.step) == 3
    _assert_tables_equal(restored, chain.refs[3])
    assert os.path.exists(os.path.join(d, "incr-4.quarantined"))
    assert not os.path.exists(os.path.join(d, "incr-4"))


def test_bitflip_middle_link_truncates_at_gap(chain, tmp_path):
    """Corrupting incr-3 must (a) quarantine it, (b) also DROP the intact
    incr-4 — its base link points at the missing step — and (c) restore
    full-2 bit-exactly. incr-4 stays on disk un-quarantined (it is not
    corrupt, just unreachable)."""
    d = _copy(chain, tmp_path)
    faults.flip_bit(_table_file(os.path.join(d, "incr-3")))
    restored = CheckpointManager(d, chain.mk()[0]).restore()
    assert int(restored.step) == 2
    _assert_tables_equal(restored, chain.refs[2])
    assert os.path.exists(os.path.join(d, "incr-3.quarantined"))
    assert os.path.exists(os.path.join(d, "incr-4"))


def test_missing_manifest_is_invisible(chain, tmp_path):
    d = _copy(chain, tmp_path)
    os.remove(os.path.join(d, "incr-3", "manifest.json"))
    restored = CheckpointManager(d, chain.mk()[0]).restore()
    # manifest-less dir never enters the chain; incr-4's base link then
    # fails and truncates the chain at the full anchor
    assert int(restored.step) == 2
    _assert_tables_equal(restored, chain.refs[2])


def test_missing_middle_link_truncates(chain, tmp_path):
    d = _copy(chain, tmp_path)
    shutil.rmtree(os.path.join(d, "incr-3"))
    restored = CheckpointManager(d, chain.mk()[0]).restore()
    assert int(restored.step) == 2
    _assert_tables_equal(restored, chain.refs[2])


def test_torn_manifest_quarantines(chain, tmp_path):
    d = _copy(chain, tmp_path)
    with open(os.path.join(d, "incr-4", "manifest.json"), "w") as f:
        f.write('{"step": 4, "kind": "in')  # torn mid-write
    restored = CheckpointManager(d, chain.mk()[0]).restore()
    assert int(restored.step) == 3
    _assert_tables_equal(restored, chain.refs[3])
    assert os.path.exists(os.path.join(d, "incr-4.quarantined"))


def test_corrupt_full_falls_back_to_older_full(chain, tmp_path):
    """A rotten ANCHOR falls back to the previous full; deltas based past
    the quarantined anchor are unreachable and dropped."""
    import jax.numpy as jnp

    from deeprec_tpu.data import SyntheticCriteo

    d = _copy(chain, tmp_path)
    tr = chain.mk()[0]
    ck = CheckpointManager(d, tr)
    st = ck.restore()
    gen = SyntheticCriteo(batch_size=96, num_cat=2, num_dense=2, vocab=300,
                          seed=9)
    st = tr.train_step(
        st, {k: jnp.asarray(v) for k, v in gen.batch().items()})[0]
    st, _ = ck.save(st)                # full-5
    ref4 = chain.refs[4]
    faults.flip_bit(_table_file(os.path.join(d, "full-5")))
    restored = CheckpointManager(d, chain.mk()[0]).restore()
    assert int(restored.step) == 4     # full-2 + incr-3 + incr-4
    _assert_tables_equal(restored, ref4)
    assert os.path.exists(os.path.join(d, "full-5.quarantined"))


def test_corruption_never_raises_into_serving_and_self_heals(chain, tmp_path):
    """The acceptance-pinned loop: a corrupt delta landing under a LIVE
    Predictor is quarantined by the poll (old snapshot keeps serving,
    health reports it, nothing raises); the trainer's next incremental
    save escalates itself to FULL because the chain has a gap; the next
    poll picks the new anchor up and freshness resumes."""
    import jax.numpy as jnp

    from deeprec_tpu.data import SyntheticCriteo
    from deeprec_tpu.serving.predictor import Predictor

    d = _copy(chain, tmp_path)
    tr, model = chain.mk()
    ck = CheckpointManager(d, tr)
    st = ck.restore()
    gen = SyntheticCriteo(batch_size=96, num_cat=2, num_dense=2, vocab=300,
                          seed=5)

    def step(st):
        return tr.train_step(
            st, {k: jnp.asarray(v) for k, v in gen.batch().items()})[0]

    p = Predictor(model, d)
    assert p.step == 4
    req = {k: v for k, v in gen.batch().items() if k != "label"}
    before = p.predict(req)

    # trainer lands a delta; corrupt it BEFORE the predictor polls
    st = step(st)
    st, delta = ck.save_incremental(st)        # incr-5
    faults.flip_bit(_table_file(delta))

    assert p.poll_updates() is False           # served through, no raise
    assert p.step == 4                          # old snapshot intact
    np.testing.assert_array_equal(np.asarray(before),
                                  np.asarray(p.predict(req)))
    h = p.health()
    assert h["quarantined"] >= 1
    assert h["status"] == "ok"                  # poll SUCCEEDED (degraded
    assert os.path.exists(delta + ".quarantined")  # dir, healthy poller)

    # trainer self-heals: the next "incremental" save sees the gap and
    # escalates to a full anchor...
    st = step(st)
    st, path2 = ck.save_incremental(st)
    assert os.path.basename(path2).startswith("full-")
    # ...which the next poll applies: freshness resumes past the gap.
    assert p.poll_updates() is True
    assert p.step == int(st.step)
    assert p.predict(req) is not None
