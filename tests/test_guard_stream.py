"""Input-firewall satellites: garbage-record tolerance in the line
parser / CSV reader / serving feature parsing, and the TCP stream
reader's bounded resync over oversized/undecodable frames. Host-side —
no jax except the serving parse test."""
import numpy as np
import pytest

from deeprec_tpu.data.readers import RecordErrors, sanitize_batch
from deeprec_tpu.data.stream import (
    FileStreamServer,
    TCPStreamReader,
    criteo_line_parser,
)

ND, NC = 2, 2


def _line(label="1", dense=("1.5", "2.0"), cats=("tokA", "tokB")):
    return "\t".join([label, *dense, *cats])


# --------------------------------------------------------- parser matrix


def test_line_parser_garbage_matrix():
    """One bad field clamps THAT field (counted by kind); the rest of
    the record and the batch parse normally — a garbage record must
    never kill the reader thread that feeds a live loop."""
    errors = RecordErrors(metrics=False)
    parse = criteo_line_parser(ND, NC, errors=errors)
    batch = parse([
        _line(),                                  # clean
        _line(label="garbage"),                   # unparseable label
        _line(dense=("not_a_float", "3.0")),      # unparseable float
        _line(dense=("inf", "nan")),              # parse fine, non-finite
        "",                                       # empty record
        "\t".join(["1"] + ["9.0"] * 50),          # overlong record
    ])
    assert batch["label"].shape == (6,)
    assert batch["label"][1] == 0.0
    assert batch["I1"][2, 0] == 0.0 and batch["I2"][2, 0] == 3.0
    assert batch["I1"][3, 0] == 0.0 and batch["I2"][3, 0] == 0.0
    assert np.all(np.isfinite(batch["I1"])) and np.all(
        np.isfinite(batch["I2"]))
    assert errors.counts["bad_label"] == 1
    assert errors.counts["bad_float"] == 1
    assert errors.counts["nonfinite_float"] == 2
    assert errors.total == 4


def test_sanitize_batch_clamps_and_counts():
    errors = RecordErrors(metrics=False)
    batch = {
        "label": np.asarray([1.0, np.nan], np.float32),
        "I1": np.asarray([[np.inf], [2.0]], np.float32),
        "C1": np.asarray([5, -7], np.int32),
        "C2": np.asarray([-1, 3], np.int32),  # -1 IS the pad: untouched
    }
    out = sanitize_batch(batch, errors, pad_value=-1, max_id=1000)
    assert out["label"][1] == 0.0 and out["I1"][0, 0] == 0.0
    assert out["C1"][1] == -1 and out["C2"][0] == -1
    assert errors.counts["nonfinite_float"] == 2
    assert errors.counts["bad_id"] == 1
    big = sanitize_batch({"C1": np.asarray([2000], np.int32)},
                         errors, max_id=1000)
    assert big["C1"][0] == -1
    assert errors.counts["bad_id"] == 2


def test_csv_reader_garbage_matrix(tmp_path):
    from deeprec_tpu.data.readers import CriteoCSVReader

    path = str(tmp_path / "garbage.tsv")
    rows = [_line() for _ in range(6)]
    rows[2] = _line(dense=("inf", "2.0"))
    with open(path, "w") as f:
        f.write("\n".join(rows) + "\n")
    reader = CriteoCSVReader([path], batch_size=6, num_dense=ND, num_cat=NC)
    batch = next(iter(reader))
    assert np.all(np.isfinite(batch["I1"]))
    assert batch["I1"][2, 0] == 0.0  # inf clamped, not 3.4e38
    assert reader.errors.counts.get("nonfinite_float", 0) >= 1


# ------------------------------------------------------ TCP frame resync


def _serve_file(tmp_path, content: bytes):
    path = str(tmp_path / "stream.txt")
    with open(path, "wb") as f:
        f.write(content)
    srv = FileStreamServer(path, follow=False).start()
    return srv, path


def test_tcp_reader_skips_oversized_frame_and_counts(tmp_path):
    """A frame past max_record_bytes is skipped whole (bounded resync):
    valid rows on both sides still arrive, the skip is counted, and the
    offset covers every consumed byte — a reconnect never replays or
    wedges on the garbage."""
    good = [_line(dense=(f"{i}.0", "1.0")).encode() for i in range(8)]
    giant = b"X" * 5000  # newline-terminated but absurd
    content = b"\n".join(good[:4] + [giant] + good[4:]) + b"\n"
    srv, _ = _serve_file(tmp_path, content)
    try:
        r = TCPStreamReader("127.0.0.1", srv.port, batch_size=4,
                            num_dense=ND, num_cat=NC, stop_at_eof=True,
                            max_record_bytes=2048)
        batches = list(r)
        rows = sum(b["label"].shape[0] for b in batches)
        assert rows == 8  # every valid row, none duplicated
        assert r.oversized_frames == 1
        assert r.record_errors.counts["oversized_frame"] == 1
        assert r.offset == len(content)  # skipped bytes are consumed
        dense = np.concatenate([b["I1"][:, 0] for b in batches])
        assert sorted(dense.tolist()) == [float(i) for i in range(8)]
    finally:
        srv.stop()


def test_tcp_reader_oversized_unterminated_frame_resyncs(tmp_path):
    """The torn-frame case: garbage larger than max_record_bytes with
    its newline far beyond the first reads — the reader discards as it
    goes (bounded memory) and resumes at the next record boundary."""
    good = [_line().encode() for _ in range(4)]
    giant = b"Y" * 100_000
    content = b"\n".join(good[:2] + [giant] + good[2:]) + b"\n"
    srv, _ = _serve_file(tmp_path, content)
    try:
        r = TCPStreamReader("127.0.0.1", srv.port, batch_size=2,
                            num_dense=ND, num_cat=NC, stop_at_eof=True,
                            max_record_bytes=1024)
        batches = list(r)
        assert sum(b["label"].shape[0] for b in batches) == 4
        assert r.oversized_frames == 1
        assert r.offset == len(content)
    finally:
        srv.stop()


def test_tcp_reader_oversized_tail_at_eof_counts_and_consumes(tmp_path):
    """Garbage past max_record_bytes at the very END of the stream (no
    terminating newline, ever): the frame is still counted, and the
    drained reader's offset covers every byte — a checkpointed position
    never points back into the skipped garbage."""
    good = [_line(dense=(f"{i}.0", "1.0")).encode() for i in range(3)]
    content = b"\n".join(good) + b"\n" + b"Q" * 50_000  # unterminated tail
    srv, _ = _serve_file(tmp_path, content)
    try:
        r = TCPStreamReader("127.0.0.1", srv.port, batch_size=2,
                            num_dense=ND, num_cat=NC, stop_at_eof=True,
                            max_record_bytes=1024)
        batches = list(r)
        assert sum(b["label"].shape[0] for b in batches) == 3
        assert r.oversized_frames == 1
        assert r.record_errors.counts["oversized_frame"] == 1
        assert r.offset == len(content)
    finally:
        srv.stop()


def test_tcp_reader_undecodable_record_counted_not_fatal(tmp_path):
    """Undecodable text inside a normal-sized frame clamps field-wise in
    the (sanitizing) default parser — the reader thread survives and the
    batch still has its full row count."""
    rows = [_line().encode(), "1\tbad\tworse\t\x00\t\x01".encode(),
            _line().encode(), _line().encode()]
    content = b"\n".join(rows) + b"\n"
    srv, _ = _serve_file(tmp_path, content)
    try:
        r = TCPStreamReader("127.0.0.1", srv.port, batch_size=4,
                            num_dense=ND, num_cat=NC, stop_at_eof=True)
        batches = list(r)
        assert sum(b["label"].shape[0] for b in batches) == 4
        assert r.record_errors.total >= 1
        for b in batches:
            assert np.all(np.isfinite(b["I1"]))
    finally:
        srv.stop()


def test_tcp_reader_offsets_resume_past_skipped_frames(tmp_path):
    """Crash/restore across a skipped frame: a second reader restoring
    the first one's offset sees only the not-yet-delivered rows."""
    good = [_line(dense=(f"{i}.0", "1.0")).encode() for i in range(6)]
    giant = b"Z" * 4000
    content = b"\n".join(good[:2] + [giant] + good[2:]) + b"\n"
    srv, _ = _serve_file(tmp_path, content)
    try:
        r1 = TCPStreamReader("127.0.0.1", srv.port, batch_size=2,
                             num_dense=ND, num_cat=NC, stop_at_eof=True,
                             max_record_bytes=1024)
        it = iter(r1)
        first = next(it)  # rows 0, 1
        assert first["I1"][:, 0].tolist() == [0.0, 1.0]
        saved = r1.save()

        r2 = TCPStreamReader("127.0.0.1", srv.port, batch_size=2,
                             num_dense=ND, num_cat=NC, stop_at_eof=True,
                             max_record_bytes=1024)
        r2.restore(saved)
        rest = np.concatenate([b["I1"][:, 0] for b in r2])
        # exactly-once: rows 2..5, each delivered once, giant skipped
        assert sorted(rest.tolist()) == [2.0, 3.0, 4.0, 5.0]
    finally:
        srv.stop()


# ------------------------------------------------- serving feature parse


def test_parse_features_firewall(tmp_path):
    """Serving-side first line: non-finite dense REJECTS the request
    (counted), negative ids CLAMP to the pad value (counted) — garbage
    never reaches the model with a healthy version stamp."""
    import jax.numpy as jnp
    import optax

    from deeprec_tpu.models import WDL
    from deeprec_tpu.optim import Adagrad
    from deeprec_tpu.serving.predictor import (
        BadRequest,
        Predictor,
        parse_features,
    )
    from deeprec_tpu.training import Trainer
    from deeprec_tpu.training.checkpoint import CheckpointManager

    model = WDL(emb_dim=4, capacity=1 << 9, hidden=(8,), num_cat=2,
                num_dense=2)
    tr = Trainer(model, Adagrad(lr=0.1), optax.adam(5e-3))
    ck = CheckpointManager(str(tmp_path / "ck"), tr)
    st = tr.init(0)
    from deeprec_tpu.data import SyntheticCriteo

    gen = SyntheticCriteo(batch_size=8, num_cat=2, num_dense=2, vocab=50,
                          seed=0)
    b = gen.batch()
    st, _ = tr.train_step(st, {k: jnp.asarray(v) for k, v in b.items()})
    ck.save(st)
    p = Predictor(model, str(tmp_path / "ck"))

    feats = {k: v.tolist() for k, v in b.items() if k != "label"}
    ok = parse_features(p, feats)
    assert ok["I1"].shape == (8, 1)

    nan_feats = dict(feats)
    nan_feats["I1"] = [float("nan")] * 8
    with pytest.raises(BadRequest, match="non-finite"):
        parse_features(p, nan_feats)
    assert p.record_errors["nonfinite_float"] == 8

    neg_feats = dict(feats)
    neg_feats["C1"] = [-5] * 8
    out = parse_features(p, neg_feats)
    assert np.all(out["C1"] == -1)  # clamped to the pad value
    assert p.record_errors["bad_id"] == 8
    # oversized bags trim to max_len, counted — only when the feature
    # declares a max_len (WDL's scalar bags don't), so pin the counter
    # through a ragged feature if one exists, else skip quietly
    seq = [f for f in p._trainer.sparse_specs if f.max_len]
    if seq:
        f0 = seq[0]
        bag_feats = dict(feats)
        bag_feats[f0.name] = [[1] * (f0.max_len + 3)] * 8
        parse_features(p, bag_feats)
        assert p.record_errors["oversized_bag"] == 24
    # and a clamped request still predicts finite probabilities
    probs = p.predict(out)
    assert np.all(np.isfinite(np.asarray(probs)))
