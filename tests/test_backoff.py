"""The shared capped-exponential-with-jitter policy (utils/backoff.py):
the one implementation behind TCPStreamReader reconnects, frontend
member backoff, the serving poll loop, and Supervisor restarts. Pure —
no test here (or anywhere) sleeps to pin the policy."""
import random

from deeprec_tpu.utils import backoff


def test_backoff_delay_is_exponential_and_capped():
    """base * 2^(k-1) per consecutive failure, capped — the value pins
    formerly living on TCPStreamReader.backoff_delay."""
    assert backoff.backoff_delay(1, 0.5, 8.0) == 0.5
    assert backoff.backoff_delay(2, 0.5, 8.0) == 1.0
    assert backoff.backoff_delay(3, 0.5, 8.0) == 2.0
    assert backoff.backoff_delay(5, 0.5, 8.0) == 8.0   # capped
    assert backoff.backoff_delay(50, 0.5, 8.0) == 8.0  # no overflow past cap
    # attempt <= 1 (and even nonsense 0/negative) waits the base
    assert backoff.backoff_delay(0, 0.5, 8.0) == 0.5
    assert backoff.backoff_delay(-3, 0.5, 8.0) == 0.5


def test_backoff_exponent_clamp_prevents_overflow():
    """A six-figure attempt counter (a member dead for days) must stay a
    finite float and still just return the cap."""
    d = backoff.backoff_delay(10 ** 6, 0.25, 30.0)
    assert d == 30.0


def test_backoff_max_exponent_matches_legacy_call_sites():
    """The frontend member path clamps the exponent at 8 and the poll
    loop at 10 (their pre-dedup shapes) — pinned so the knob keeps
    honoring per-caller clamps."""
    # frontend shape: min(cap, base * 2^min(k-1, 8))
    assert backoff.backoff_delay(9, 0.2, 1e9, max_exponent=8) == 0.2 * 2 ** 8
    assert backoff.backoff_delay(99, 0.2, 1e9, max_exponent=8) == 0.2 * 2 ** 8
    # poll-loop shape: n-th failure = attempt n+1, exponent min(n, 10)
    assert backoff.backoff_delay(4, 2.0, 1e9, max_exponent=10) == 2.0 * 2 ** 3


def test_jitter_band_is_half_to_three_halves():
    """Jitter spreads across [0.5, 1.5) * delay for every call site."""
    rng = random.Random(7)
    vals = [backoff.jittered(10.0, rng) for _ in range(2000)]
    assert all(5.0 <= v < 15.0 for v in vals)
    # actually spreads (not stuck at one end)
    assert max(vals) - min(vals) > 8.0


def test_jittered_backoff_composes():
    rng = random.Random(3)
    base, cap = 0.5, 8.0
    for attempt in (1, 3, 7, 40):
        d = backoff.backoff_delay(attempt, base, cap)
        v = backoff.jittered_backoff(attempt, base, cap, random.Random(3))
        rng2 = random.Random(3)
        assert v == backoff.jittered(d, rng2)


def test_seeded_rng_stable_and_distinct():
    """Same identity -> same jitter stream; different identity or pid ->
    a different one (no lockstep across fleet members)."""
    a1 = backoff.seeded_rng("h", 1).random()
    a2 = backoff.seeded_rng("h", 1).random()
    b = backoff.seeded_rng("h", 2).random()
    c = backoff.seeded_rng("h", 1, pid=1234).random()
    assert a1 == a2
    assert a1 != b
    assert a1 != c
