"""Model-quality firewall (deeprec_tpu/guard): the step sentinel's trip
matrix and bit-exact no-op contract, TrainLoop rollback resuming
bit-identically minus the skipped batch, permanent quarantine after R
trips, the pre-swap canary rejecting a NaN-poisoned delta while serving
continues, maintain() row hygiene, and the zero-steady-state-compile
contract with the sentinel enabled."""
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from deeprec_tpu.data import SyntheticCriteo
from deeprec_tpu.guard import (
    FLAG_GRAD_NORM,
    FLAG_LOSS_SPIKE,
    FLAG_NONFINITE_GRAD,
    FLAG_NONFINITE_LOSS,
    FLAG_ROW_NORM,
    GuardPolicy,
    QualityGate,
    SentinelConfig,
    batch_fingerprint,
)
from deeprec_tpu.guard.canary import np_auc
from deeprec_tpu.guard.quarantine import DeadLetter
from deeprec_tpu.guard.sentinel import (
    flag_kinds,
    guard_carry,
    guard_init,
    step_flags,
)
from deeprec_tpu.models import WDL
from deeprec_tpu.online import faults
from deeprec_tpu.online.loop import TrainLoop
from deeprec_tpu.optim import Adagrad
from deeprec_tpu.training import Trainer
from deeprec_tpu.training.checkpoint import CheckpointManager

SEN = SentinelConfig(spike_ratio=4.0, grad_norm_max=1e4, row_norm_max=100.0,
                     row_evict_quantile=0.9, row_evict_factor=8.0)


def _mk_trainer(sentinel=True):
    model = WDL(emb_dim=4, capacity=1 << 10, hidden=(16,), num_cat=2,
                num_dense=2)
    return Trainer(model, Adagrad(lr=0.2), optax.adam(5e-3),
                   sentinel=SEN if sentinel else None), model


def _batches(n, seed=7, B=64):
    gen = SyntheticCriteo(batch_size=B, num_cat=2, num_dense=2, vocab=300,
                          seed=seed)
    return [gen.batch() for _ in range(n)]


# ------------------------------------------------------ sentinel (unit)


def test_step_flags_matrix():
    """Every sentinel bit, driven through the pure fold — the full trip
    matrix without paying a compile per threshold combination."""
    cfg = SentinelConfig(spike_ratio=2.0, ema_decay=0.5, grad_norm_max=10.0,
                         row_norm_max=5.0)
    g = guard_init()
    ok = jnp.asarray(True)
    # clean step seeds the EMA
    f, g = step_flags(cfg, jnp.float32(1.0), ok, jnp.float32(4.0),
                      jnp.float32(1.0), g)
    assert int(f) == 0 and float(g["ema"]) == 1.0
    # non-finite loss
    f, g2 = step_flags(cfg, jnp.float32(np.nan), ok, jnp.float32(4.0),
                       jnp.float32(1.0), g)
    assert int(f) & FLAG_NONFINITE_LOSS
    assert float(g2["ema"]) == 1.0  # tripped steps never advance the EMA
    # non-finite grads
    f, _ = step_flags(cfg, jnp.float32(1.0), jnp.asarray(False),
                      jnp.float32(4.0), jnp.float32(1.0), g)
    assert int(f) & FLAG_NONFINITE_GRAD
    # grad-norm bound (norm_sq > max^2)
    f, _ = step_flags(cfg, jnp.float32(1.0), ok, jnp.float32(101.0 ** 2),
                      jnp.float32(1.0), g)
    assert int(f) & FLAG_GRAD_NORM
    # loss spike vs the seeded EMA
    f, _ = step_flags(cfg, jnp.float32(2.5), ok, jnp.float32(4.0),
                      jnp.float32(1.0), g)
    assert int(f) & FLAG_LOSS_SPIKE
    # row-norm bound, and NaN rows count as over-bound
    f, _ = step_flags(cfg, jnp.float32(1.0), ok, jnp.float32(4.0),
                      jnp.float32(6.0), g)
    assert int(f) & FLAG_ROW_NORM
    f, _ = step_flags(cfg, jnp.float32(1.0), ok, jnp.float32(4.0),
                      jnp.float32(np.nan), g)
    assert int(f) & FLAG_ROW_NORM
    assert flag_kinds(FLAG_NONFINITE_LOSS | FLAG_ROW_NORM) == [
        "nonfinite_loss", "row_norm"]


def test_sentinel_is_bitexact_noop_when_untripped():
    """Sentinel ON vs OFF over the same clean batches: identical state
    bit for bit (the sentinel observes, it never touches the math), and
    a NaN batch trips the expected bits on the next fold."""
    tr, _ = _mk_trainer(sentinel=True)
    tr0, _ = _mk_trainer(sentinel=False)
    s, s0 = tr.init(0), tr0.init(0)
    g = None
    for b in _batches(3):
        jb = {k: jnp.asarray(v) for k, v in b.items()}
        s, m = tr.train_step(s, jb, guard=g)
        g = guard_carry(m)
        s0, _ = tr0.train_step(s0, jb)
        assert int(m["guard_flags"]) == 0
    for bn in s.tables:
        for a, b_ in zip(jax.tree.leaves(s.tables[bn]),
                         jax.tree.leaves(s0.tables[bn])):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b_))
    for a, b_ in zip(jax.tree.leaves(s.dense), jax.tree.leaves(s0.dense)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b_))
    bad = faults.poison_batch(_batches(1)[0], "nan")
    _, m = tr.train_step(s, {k: jnp.asarray(v) for k, v in bad.items()},
                         guard=g)
    flags = int(m["guard_flags"])
    assert flags & FLAG_NONFINITE_LOSS and flags & FLAG_NONFINITE_GRAD


def test_sentinel_flags_ride_the_kstep_scan():
    from deeprec_tpu.training.trainer import stack_batches

    tr, _ = _mk_trainer(sentinel=True)
    st = tr.init(0)
    bs = _batches(3, seed=11)
    bs[1] = faults.poison_batch(bs[1], "nan")
    st, mets = tr.train_steps(
        st, stack_batches([{k: jnp.asarray(v) for k, v in b.items()}
                           for b in bs]))
    flags = np.asarray(mets["guard_flags"])
    assert flags.shape == (3,)
    assert flags[0] == 0 and flags[1] != 0


# -------------------------------------------------- rollback + quarantine


def _logical_rows(tr, st, bn):
    """{(member, key): (value row, freq, version)} — restore re-probes
    keys in a different order than live insertion, so equality is on
    CONTENT, not physical slot layout."""
    from deeprec_tpu.embedding.table import empty_key
    from deeprec_tpu.ops.packed import unpack_array

    ts = st.tables[bn]
    keys = np.asarray(ts.keys)
    C = keys.shape[-1]
    sent = empty_key(tr.bundles[bn].table.cfg)
    out = {}
    members = range(keys.shape[0]) if keys.ndim == 2 else [None]
    for m in members:
        k = keys[m] if m is not None else keys
        v = np.asarray(unpack_array(
            ts.values[m] if m is not None else ts.values, C))
        f = np.asarray(ts.meta[m, 0] if m is not None else ts.meta[0])
        ver = np.asarray(ts.meta[m, 1] if m is not None else ts.meta[1])
        for i in np.nonzero(k != sent)[0]:
            out[(m, int(k[i]))] = (tuple(v[i]), int(f[i]), int(ver[i]))
    return out


def test_rollback_resumes_bit_identically_minus_poisoned_batch(tmp_path):
    """THE recovery contract: a guarded run over a poisoned stream ends
    with exactly the model a clean run over the same stream minus the
    poisoned batch produces — logical table content and dense params
    identical, the poisoned save quarantined, the batch dead-lettered."""
    clean = _batches(14, seed=7)
    poisoned = list(clean)
    poisoned[6] = faults.poison_batch(clean[6], "nan")

    tr, _ = _mk_trainer(sentinel=True)
    ck = CheckpointManager(str(tmp_path / "ckA"), tr)
    loop = TrainLoop(tr, ck, iter(poisoned), save_every=4, full_every=2,
                     guard=GuardPolicy(dead_letter_dir=str(tmp_path / "dl"),
                                       max_batch_trips=2),
                     max_steps=14)
    stA, code = loop.run()
    assert code == 0
    assert loop.guard_trips == 1 and loop.rollbacks == 1
    assert loop.last_rollback_ms is not None
    assert loop.trip_log[0][1] - loop.trip_log[0][0] <= 1  # ≤ 1 dispatch
    # dead letter holds payload + meta
    fp = batch_fingerprint(poisoned[6])
    assert (tmp_path / "dl" / f"batch-{fp}.npz").exists()
    assert (tmp_path / "dl" / f"batch-{fp}.json").exists()

    tr2, _ = _mk_trainer(sentinel=False)
    ckB = CheckpointManager(str(tmp_path / "ckB"), tr2)
    stB, _ = TrainLoop(tr2, ckB, iter(clean[:6] + clean[7:]), save_every=4,
                       full_every=2, max_steps=13).run()
    assert int(stA.step) == int(stB.step) == 13
    for bn in stA.tables:
        assert _logical_rows(tr, stA, bn) == _logical_rows(tr2, stB, bn)
    for a, b in zip(jax.tree.leaves(stA.dense), jax.tree.leaves(stB.dense)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_permanent_quarantine_after_R_trips(tmp_path):
    """The crash-loop breaker: a batch redelivered across R rollbacks is
    permanently quarantined — later deliveries are skipped before
    dispatch, and the quarantine survives a fresh loop (restart)."""
    clean = _batches(10, seed=3)
    bad = faults.poison_batch(clean[2], "nan")
    stream = clean[:2] + [bad] + clean[3:5] + [bad] + clean[5:7] + [bad] + \
        clean[7:]
    tr, _ = _mk_trainer(sentinel=True)
    ck = CheckpointManager(str(tmp_path / "ck"), tr)
    loop = TrainLoop(tr, ck, iter(stream), save_every=3, full_every=2,
                     guard=GuardPolicy(dead_letter_dir=str(tmp_path / "dl"),
                                       max_batch_trips=2))
    loop.run()
    fp = batch_fingerprint(bad)
    assert loop.dead_letter.trip_count(fp) == 2
    assert loop.dead_letter.is_quarantined(fp)
    assert loop.dead_letter.permanent_count == 1
    assert loop.batches_skipped == 1  # third delivery never dispatched
    # the index survives a restart: a fresh DeadLetter refuses the batch
    dl2 = DeadLetter(str(tmp_path / "dl"), 2)
    assert dl2.is_quarantined(fp)


def test_rollback_pins_stream_reader_positions(tmp_path):
    """A rollback must restore MODEL state only: rewinding a registered
    dataset reader would re-deliver the window the rollback already
    replays from memory — the batches would train twice and the stream
    offset would undercount (replaying trained data across the next
    reconnect/restart)."""

    class _Reader:
        def __init__(self):
            self.offset = 0
            self.rewinds = 0

        def save(self):
            return {"offset": self.offset}

        def restore(self, st):
            if int(st["offset"]) < self.offset:
                self.rewinds += 1
            self.offset = int(st["offset"])

    reader = _Reader()
    clean = _batches(10, seed=15)
    stream = list(clean)
    stream[5] = faults.poison_batch(clean[5], "nan")
    tr, _ = _mk_trainer(sentinel=True)
    ck = CheckpointManager(str(tmp_path / "ck"), tr,
                           datasets={"stream": reader})
    loop = TrainLoop(tr, ck, iter(stream), save_every=3, full_every=2,
                     guard=GuardPolicy(dead_letter_dir=str(tmp_path / "dl"),
                                       max_batch_trips=2))
    # the reader position advances monotonically with delivered batches
    loop.on_step = lambda step: setattr(reader, "offset", 1000 + step)
    loop.run()
    assert loop.rollbacks == 1
    # checkpointed positions lag the live offset; the rollback restore
    # must never hand one back to the reader (not even transiently)
    assert reader.rewinds == 0
    assert ck.datasets == {"stream": reader}  # re-attached after


def test_guard_requires_sentinel():
    tr, _ = _mk_trainer(sentinel=False)
    with pytest.raises(ValueError, match="sentinel"):
        TrainLoop(tr, None, [], guard=GuardPolicy(dead_letter_dir="/tmp/x"))


# ------------------------------------------------------- maintain hygiene


def test_maintain_reinitializes_exploded_rows():
    """Row hygiene: a row whose norm exploded past the quantile bound is
    re-initialized at maintain() cadence and counted."""
    tr, _ = _mk_trainer(sentinel=True)
    st = tr.init(0)
    for b in _batches(3, seed=5):
        st, _ = tr.train_step(st, {k: jnp.asarray(v) for k, v in b.items()})
    bn = next(iter(st.tables))
    ts = st.tables[bn]
    # blow one occupied row up to an absurd norm (vmapped member 0)
    keys0 = np.asarray(ts.keys)[0]
    from deeprec_tpu.embedding.table import empty_key

    slot = int(np.nonzero(keys0 != empty_key(tr.bundles[bn].table.cfg))[0][0])
    vals = ts.values.at[0, slot].set(1e9)
    st = st.replace(tables={**st.tables, bn: ts.replace(values=vals)})
    st2, report = tr.maintain(st)
    assert report[bn].get("rows_reinit", 0) >= 1
    norms = np.linalg.norm(np.asarray(st2.tables[bn].values[0]), axis=-1)
    assert norms.max() < 1e6


# ----------------------------------------------------------- quality gate


@pytest.fixture()
def serving_chain(tmp_path):
    tr, model = _mk_trainer(sentinel=False)
    ck = CheckpointManager(str(tmp_path / "ck"), tr)
    st = tr.init(0)
    batches = _batches(4, seed=4)
    for b in batches[:3]:
        st, _ = tr.train_step(st, {k: jnp.asarray(v) for k, v in b.items()})
    st, _ = ck.save(st)
    probe = dict(batches[3])
    labels = probe.pop("label")
    return tr, model, ck, st, probe, labels


def test_canary_gate_rejects_nan_delta_serving_continues(serving_chain,
                                                         tmp_path):
    """A NaN-poisoned delta must be rejected BEFORE the swap: the old
    snapshot keeps serving (finite answers, zero failed requests), the
    delta is quarantined, health reports degraded:quality_gate, and a
    later honest update clears the degradation."""
    from deeprec_tpu.serving.predictor import ModelServer, Predictor

    tr, model, ck, st, probe, labels = serving_chain
    gate = QualityGate(probe=probe, labels=labels, auc_floor=0.0,
                       max_shift=0.25)
    p = Predictor(model, str(tmp_path / "ck"), quality_gate=gate)
    server = ModelServer(p, max_batch=64)
    try:
        before, v0 = server.request_versioned(probe)
        assert np.all(np.isfinite(np.asarray(before)))

        bad = faults.poison_batch(_batches(1, seed=9)[0], "nan")
        st_bad, _ = tr.train_step(
            jax.tree.map(jnp.copy, st),
            {k: jnp.asarray(v) for k, v in bad.items()})
        ck.save_incremental(st_bad)
        assert p.poll_updates() is False  # rejected, not applied
        assert gate.rejections == 1
        assert gate.last_rejection["reason"] == "nonfinite_predictions"
        h = p.health()
        assert h["status"] == "degraded"
        assert h["degraded_reason"] == "quality_gate"
        assert h["quality_gate_rejections"] == 1
        # zero failed requests, answers unchanged and finite
        after, v1 = server.request_versioned(probe)
        assert v1 == v0
        np.testing.assert_array_equal(np.asarray(before), np.asarray(after))
        assert any("quarantined" in d
                   for d in os.listdir(tmp_path / "ck"))
        # an honest update publishes and clears the degradation (the
        # trainer's next save self-escalated to full over the gap)
        good = _batches(1, seed=10)[0]
        st2, _ = tr.train_step(
            jax.tree.map(jnp.copy, st),
            {k: jnp.asarray(v) for k, v in good.items()})
        _, path = ck.save_incremental(st2)
        assert os.path.basename(path).startswith("full-")
        assert p.poll_updates() is True
        assert p.health()["status"] == "ok"
        assert p.version > v0
    finally:
        server.close()


def test_gate_rejects_distribution_shift(serving_chain, tmp_path):
    """The relative bound: a finite but violently shifted delta (here a
    huge-LR step) fails max_shift even though nothing is NaN."""
    from deeprec_tpu.serving.predictor import Predictor

    tr, model, ck, st, probe, labels = serving_chain
    gate = QualityGate(probe=probe, max_shift=0.05)
    p = Predictor(model, str(tmp_path / "ck"), quality_gate=gate)
    v0 = p.version
    b = _batches(1, seed=12)[0]
    st_bad, _ = tr.train_step(
        jax.tree.map(jnp.copy, st),
        {k: jnp.asarray(v) for k, v in b.items()}, lr=50.0)
    ck.save_incremental(st_bad)
    assert p.poll_updates() is False
    assert gate.rejections == 1
    assert gate.last_rejection["reason"] == "prediction_shift"
    assert p.version == v0


def test_np_auc_agrees_with_ranks():
    probs = np.asarray([0.1, 0.4, 0.35, 0.8])
    labels = np.asarray([0.0, 0.0, 1.0, 1.0])
    assert abs(np_auc(probs, labels) - 0.75) < 1e-9
    assert np_auc(np.asarray([0.5, 0.5]), np.asarray([1.0, 1.0])) == 0.5


# ----------------------------------------------------------- obs wiring


def test_guard_metrics_and_heartbeat_wiring(tmp_path):
    """Guard events land in the process-wide obs plane (rendered through
    the same snapshot every /metrics surface serves) and in the
    heartbeat the Supervisor reads its guard-trip field from."""
    from deeprec_tpu.obs import metrics as obs_metrics
    from deeprec_tpu.online.supervisor import Heartbeat, ProcessSpec, Supervisor

    clean = _batches(6, seed=21)
    stream = list(clean)
    stream[3] = faults.poison_batch(clean[3], "nan")
    tr, _ = _mk_trainer(sentinel=True)
    ck = CheckpointManager(str(tmp_path / "ck"), tr)
    hb_path = str(tmp_path / "w.hb")
    loop = TrainLoop(tr, ck, iter(stream), save_every=3, full_every=2,
                     heartbeat=Heartbeat(hb_path),
                     guard=GuardPolicy(dead_letter_dir=str(tmp_path / "dl"),
                                       max_batch_trips=1))
    loop.run()
    text = obs_metrics.render_snapshot(
        obs_metrics.default_registry().snapshot())
    # counters render with the Prometheus _total suffix appended
    assert 'deeprec_guard_trips_total{kind="nonfinite_loss"}' in text
    assert "deeprec_guard_rollbacks_total" in text
    assert "deeprec_guard_batches_quarantined_total" in text
    assert "deeprec_guard_last_verified_step" in text
    beat = Heartbeat.read(hb_path)
    assert beat["guard_trips"] == 1
    assert beat["rollbacks"] == 1
    assert beat["batches_quarantined"] == 1
    assert beat["last_verified_step"] == loop.last_verified_step
    # the Supervisor surfaces the guard-trip field per worker
    import sys as _sys

    spec = ProcessSpec(name="w", argv=[_sys.executable, "-c", "pass"],
                       heartbeat_path=hb_path, lease_secs=None)
    sup = Supervisor([spec], on_event=lambda m: None)
    st = sup.stats()["w"]
    assert st["guard_trips"] == 1 and st["batches_quarantined"] == 1


# ------------------------------------------------- steady-state compiles


def test_trace_guard_zero_compiles_with_sentinel_on():
    """The sentinel adds zero steady-state compiles: after warmup, both
    the single-step and K-step guarded paths are pure cache-hit."""
    from deeprec_tpu.analysis import trace_guard as _tg
    from deeprec_tpu.training.trainer import stack_batches

    tr, _ = _mk_trainer(sentinel=True)
    st = tr.init(0)
    bs = [{k: jnp.asarray(v) for k, v in b.items()}
          for b in _batches(6, seed=13)]
    st, m = tr.train_step(st, bs[0])
    g = guard_carry(m)
    st, m = tr.train_step(st, bs[1], guard=g)
    g = guard_carry(m)
    stacked = stack_batches(bs[2:4])
    st, mets = tr.train_steps(st, stacked, guard=g)
    g = guard_carry(mets)
    jax.block_until_ready(mets["loss"])
    with _tg(max_compiles=0):
        st, m = tr.train_step(st, bs[4], guard=g)
        g = guard_carry(m)
        st, mets = tr.train_steps(st, stack_batches(bs[4:6]), guard=g)
        jax.block_until_ready(mets["loss"])
