"""Compute-reuse layer (serving/reuse.py + ModelServer/RetrievalServer
wiring): fingerprint contract, byte-bounded LRU, answer-cache hits that
are bit-identical to evaluation, in-window memoization, publish-edge
invalidation (a delta swap never serves a mixed-version answer), the
user-tower candidate-only lane, the retrieval candidate cache keyed on
(model version, corpus_rev), and `no_cache` end to end (HTTP body field
and the PRED wire flag) with fleet-merged /metrics series."""
import json
import queue
import threading
import time
import urllib.request

import jax.numpy as jnp
import numpy as np
import optax
import pytest

from deeprec_tpu.data import SyntheticCriteo, SyntheticTwoTower
from deeprec_tpu.models import DSSM, WDL
from deeprec_tpu.optim import Adagrad
from deeprec_tpu.serving import (
    BackendServer,
    Frontend,
    HttpServer,
    ModelServer,
    Predictor,
    RetrievalEngine,
)
from deeprec_tpu.serving.predictor import parse_features
from deeprec_tpu.serving.retrieval import (
    RetrievalServer,
    fill_missing_item_features,
)
from deeprec_tpu.serving.reuse import (
    ReuseCache,
    request_fingerprint,
    value_nbytes,
)
from deeprec_tpu.training import Trainer
from deeprec_tpu.training.checkpoint import CheckpointManager


def J(b):
    return {k: jnp.asarray(v) for k, v in b.items()}


def strip_labels(b):
    return {k: np.asarray(v) for k, v in b.items() if not k.startswith("label")}


def make_trained(tmp_path, steps=3):
    model = WDL(emb_dim=8, capacity=1 << 12, hidden=(32, 16), num_cat=4,
                num_dense=2)
    tr = Trainer(model, Adagrad(lr=0.1), optax.adam(1e-3))
    st = tr.init(0)
    gen = SyntheticCriteo(batch_size=64, num_cat=4, num_dense=2, vocab=2000,
                          seed=13)
    for _ in range(steps):
        st, _ = tr.train_step(st, J(gen.batch()))
    ck = CheckpointManager(str(tmp_path), tr)
    st, _ = ck.save(st)
    return model, tr, st, ck, gen


@pytest.fixture(scope="module")
def wdl_ckpt(tmp_path_factory):
    """One trained WDL checkpoint shared by the read-only cache tests
    (each spins its OWN ModelServer; tests that land deltas build their
    own copy via make_trained)."""
    tmp = tmp_path_factory.mktemp("reuse-wdl")
    model, tr, st, ck, gen = make_trained(tmp)
    req = strip_labels(gen.batch())
    return model, str(tmp), req


def reuse_counts(server, cache="predict"):
    s = server.stats_snapshot()["reuse"][cache]
    return s["hits"], s["misses"]


# --------------------------------------------------------------- primitives


def test_request_fingerprint_contract():
    """Name-bound, order-independent, value/dtype-sensitive; `names`
    restricts to a subset; `extra` always separates keys."""
    a = {"x": np.arange(8, dtype=np.int64), "y": np.ones(4, np.float32)}
    fp = request_fingerprint(a)
    assert len(fp) == 16
    # dict insertion order never moves the digest
    b = {"y": a["y"].copy(), "x": a["x"].copy()}
    assert request_fingerprint(b) == fp
    # renaming a feature always does
    assert request_fingerprint({"x2": a["x"], "y": a["y"]}) != fp
    # so do a value flip, a dtype change and a reshape
    mut = {"x": a["x"].copy(), "y": a["y"].copy()}
    mut["x"][0] += 1
    assert request_fingerprint(mut) != fp
    assert request_fingerprint(
        {"x": a["x"].astype(np.int32), "y": a["y"]}) != fp
    assert request_fingerprint(
        {"x": a["x"].reshape(2, 4), "y": a["y"]}) != fp
    # subset keying (the user-tower cache) ignores the other features
    fx = request_fingerprint(a, names=["x"])
    assert fx == request_fingerprint(
        {"x": a["x"], "y": 7 * a["y"]}, names=["x"])
    assert fx != fp
    # extra folds request params (retrieval folds k; grouped folds lane)
    assert request_fingerprint(a, extra=b"k10") != fp
    assert request_fingerprint(a, extra=b"k10") != request_fingerprint(
        a, extra=b"k100")


def test_reuse_cache_byte_lru_eviction_and_version_invalidation():
    """Byte budget (not entry count) bounds residency: cold-end eviction
    with counters, oversize values never stored, born-stale puts
    rejected, and `invalidate_stale` drops exactly the old-version
    entries."""
    live = [0]
    val = np.zeros(32, np.float32)  # 128 bytes
    c = ReuseCache(capacity_bytes=3 * val.nbytes, name="t",
                   version_fn=lambda: live[0])
    assert value_nbytes({"a": val, "b": (val, val)}) == 3 * val.nbytes
    fps = [b"%016d" % i for i in range(5)]
    for fp in fps[:3]:
        assert c.put(fp, 0, val.copy())
    assert len(c) == 3 and c.occupancy_bytes() == 3 * val.nbytes
    # touch fp0 so fp1 is now the cold end
    assert c.get_current(fps[0]) is not None
    assert c.put(fps[3], 0, val.copy())
    assert c.evictions == 1 and len(c) == 3
    assert c.get_current(fps[1]) is None          # evicted (LRU order)
    assert c.get_current(fps[0]) is not None      # survived the refresh
    # oversize: never resident, nothing evicted for it
    assert not c.put(b"big", 0, np.zeros(1024, np.float32))
    assert c.evictions == 1
    # born stale: produced at version 0 after the publish bumped to 1
    live[0] = 1
    assert not c.put(fps[4], 0, val.copy())
    # every resident entry carries version 0 -> all invalid now
    n = len(c)
    assert c.invalidate_stale() == n
    assert len(c) == 0 and c.occupancy_bytes() == 0
    assert c.invalidations == n
    hits_before = c.hits
    assert c.get_current(fps[0]) is None
    assert c.hits == hits_before and c.misses > 0


# ----------------------------------------------------- answer cache (lane 0)


def test_answer_cache_hit_bit_identity_and_no_cache(wdl_ckpt):
    """A repeat request is served from cache BIT-identically to its
    first evaluation; `no_cache=True` forces a full evaluation that is
    also bit-identical and leaves the cache counters untouched."""
    model, ckpt, req = wdl_ckpt
    server = ModelServer(Predictor(model, ckpt), max_batch=64,
                         max_wait_ms=1.0, reuse_cache_bytes=1 << 20)
    try:
        r1, v1 = server.request_versioned(req)
        h, m = reuse_counts(server)
        assert (h, m) == (0, 1)
        r2, v2 = server.request_versioned(req)
        assert reuse_counts(server) == (1, 1)
        assert v2 == v1
        np.testing.assert_array_equal(np.asarray(r1), np.asarray(r2))
        # no_cache: bypasses the read AND the write — counters frozen
        r3, v3 = server.request_versioned(req, no_cache=True)
        assert reuse_counts(server) == (1, 1)
        assert v3 == v1
        np.testing.assert_array_equal(np.asarray(r1), np.asarray(r3))
        snap = server.stats_snapshot()["reuse"]["predict"]
        assert snap["entries"] == 1
        assert 0 < snap["occupancy_bytes"] <= snap["capacity_bytes"]
    finally:
        server.close()


def test_in_window_memoization_shares_one_dispatch(wdl_ckpt):
    """Identical in-flight requests coalesced into one micro-batch run
    the model ONCE: twins get the leader's slice (bit-identical, same
    version) and are counted as memo_shared; a no_cache twin (fp=None)
    never shares."""
    model, ckpt, req = wdl_ckpt
    pred = Predictor(model, ckpt)
    server = ModelServer(pred, max_batch=256, max_wait_ms=1.0,
                         reuse_cache_bytes=1 << 20)
    try:
        calls = []
        orig = pred.predict_versioned

        def counting(batch, **kw):
            calls.append(1)
            return orig(batch, **kw)

        pred.predict_versioned = counting
        fp = request_fingerprint(req)
        replies = [queue.Queue(maxsize=1) for _ in range(4)]
        t0 = time.monotonic()
        pending = [
            (req, 64, replies[0], t0, 0, None, fp, None, None),
            (req, 64, replies[1], t0, 0, None, fp, None, None),
            (req, 64, replies[2], t0, 0, None, fp, None, None),
            # the no_cache twin: fp=None, must ride the batch itself
            (req, 64, replies[3], t0, 0, None, None, None, None),
        ]
        server._serve(pending)
        assert len(calls) == 1  # one dispatch for all four
        assert server.memo_shared == 2
        outs = [q.get(timeout=5) for q in replies]
        vers = {v for _, v in outs}
        assert len(vers) == 1
        for r, _ in outs[1:]:
            np.testing.assert_array_equal(np.asarray(outs[0][0]),
                                          np.asarray(r))
    finally:
        pred.predict_versioned = orig
        server.close()


def test_publish_boundary_never_mixes_versions(tmp_path):
    """Delta publish mid-stream of hits: while the swap is gated the
    cache keeps serving the OLD version; after the swap every old entry
    is invalidated, the next request is a miss evaluated at the new
    version, bit-identical to a cold predictor on the same
    checkpoint."""
    model, tr, st, ck, gen = make_trained(tmp_path)
    req = strip_labels(gen.batch())
    pred = Predictor(model, str(tmp_path))
    server = ModelServer(pred, max_batch=64, max_wait_ms=1.0,
                         reuse_cache_bytes=1 << 20)
    try:
        r0, v0 = server.request_versioned(req)
        _, vh = server.request_versioned(req)
        assert vh == v0 and reuse_counts(server) == (1, 1)

        in_pre_swap = threading.Event()
        release = threading.Event()

        def gate():
            in_pre_swap.set()
            assert release.wait(10)

        pred._pre_swap = gate
        for _ in range(2):
            st2, _ = tr.train_step(st, J(gen.batch()))
            st = st2
        ck.save_incremental(st)
        th = threading.Thread(target=pred.poll_updates)
        th.start()
        assert in_pre_swap.wait(30)
        # publish parked right before the swap: hits still serve v0 —
        # the cache can be AHEAD of a publish, never across one
        r_mid, v_mid = server.request_versioned(req)
        assert v_mid == v0
        np.testing.assert_array_equal(np.asarray(r0), np.asarray(r_mid))
        release.set()
        th.join(timeout=30)
        pred._pre_swap = None

        snap = server.stats_snapshot()["reuse"]["predict"]
        assert snap["invalidations"] >= 1 and snap["entries"] == 0
        h0, m0 = reuse_counts(server)
        r_new, v_new = server.request_versioned(req)
        assert v_new == v0 + 1
        assert reuse_counts(server) == (h0, m0 + 1)
        assert np.abs(np.asarray(r_new) - np.asarray(r0)).max() > 0
        # post-swap answer == a cold predictor on the same checkpoint
        cold = np.asarray(Predictor(model, str(tmp_path)).predict(req))
        np.testing.assert_array_equal(np.asarray(r_new), cold)
        # and the repeat is a hit AT the new version, bit-identical
        r_hit, v_hit = server.request_versioned(req)
        assert v_hit == v_new
        np.testing.assert_array_equal(np.asarray(r_new), np.asarray(r_hit))
    finally:
        server.close()


# ------------------------------------------------- user tower (lanes 1 / 2)


def test_user_tower_cache_candidate_only_lane(tmp_path):
    """Grouped requests populate the user-tower cache as a side effect
    of their own dispatch; the same user's NEXT candidate set (an
    answer-cache miss) rides the candidate-only lane off the cached
    user vector and matches the full no_cache evaluation."""
    model = DSSM(emb_dim=8, capacity=1 << 12, num_user_feats=2,
                 num_item_feats=2, hidden=(32, 16))
    tr = Trainer(model, Adagrad(lr=0.1), optax.adam(2e-3))
    st = tr.init(0)
    gen = SyntheticTwoTower(batch_size=128, num_user=2, num_item=2,
                            vocab=500, seed=31)
    for _ in range(3):
        st, _ = tr.train_step(st, J(gen.batch()))
    CheckpointManager(str(tmp_path), tr).save(st)
    pred = Predictor(model, str(tmp_path))
    base = strip_labels(gen.batch())

    def user_req(u, lo, n_items=8):
        out = {}
        for k, v in base.items():
            rows = v[lo:lo + n_items].copy()
            if k in model.user_feats:
                rows = np.repeat(v[u:u + 1], n_items, axis=0)
            out[k] = rows
        return out

    req_a, req_b = user_req(0, 0), user_req(0, 8)  # same user, new items
    server = ModelServer(pred, max_batch=64, max_wait_ms=1.0,
                         reuse_cache_bytes=1 << 20)
    try:
        assert server.user_reuse is not None  # DSSM has the tower split
        _, va = server.request_versioned(req_a, group_users=True)
        uh0, um0 = reuse_counts(server, "user_tower")
        assert len(server.user_reuse) == 1  # populated by the dispatch
        rb, vb = server.request_versioned(req_b, group_users=True)
        uh1, um1 = reuse_counts(server, "user_tower")
        assert (uh1 - uh0, um1 - um0) == (1, 0)  # rode lane 2
        assert vb == va
        rb_full, vf = server.request_versioned(req_b, group_users=True,
                                               no_cache=True)
        assert vf == vb
        np.testing.assert_allclose(np.asarray(rb), np.asarray(rb_full),
                                   rtol=1e-6, atol=1e-6)
        # a different user's fingerprint misses the user cache (lane 1)
        server.request_versioned(user_req(1, 16), group_users=True)
        uh2, um2 = reuse_counts(server, "user_tower")
        assert um2 == um1 + 1 and len(server.user_reuse) == 2
    finally:
        server.close()


# -------------------------------------------------------- retrieval lane


def test_retrieval_candidate_cache_versioning_and_k_key(tmp_path):
    """Candidate-cache hits are byte-identical and keyed on k; an item
    ingest (corpus_rev bump) AND a model publish each invalidate; a
    `no_cache` probe never reads or writes."""
    model = DSSM(emb_dim=8, capacity=1 << 12, num_user_feats=2,
                 num_item_feats=2, hidden=(16, 8))
    tr = Trainer(model, Adagrad(lr=0.1), optax.adam(1e-3))
    st = tr.init(0)
    gen = SyntheticTwoTower(batch_size=64, num_user=2, num_item=2,
                            vocab=200, seed=3)
    for _ in range(3):
        st, _ = tr.train_step(st, J(gen.batch()))
    ck = CheckpointManager(str(tmp_path), tr)
    st, _ = ck.save(st)
    pred = Predictor(model, str(tmp_path))
    eng = RetrievalEngine(pred, quantize="fp32", block_rows=256, chunk=128)
    rng = np.random.default_rng(0)
    ids = np.arange(1, 257, dtype=np.int64)
    feats = {"V0": 200 + rng.integers(0, 200, size=256),
             "V1": 400 + rng.integers(0, 200, size=256)}
    eng.upsert_items(ids, feats)
    b = gen.batch()
    user = {k: np.asarray(v)[:4] for k, v in b.items() if k.startswith("U")}
    batch = parse_features(pred, fill_missing_item_features(pred, user))
    rs = RetrievalServer(eng, max_wait_ms=1.0, reuse_cache_bytes=1 << 20)
    try:
        r1 = rs.request_versioned(batch, 10)
        assert (rs.reuse.hits, rs.reuse.misses) == (0, 1)
        r2 = rs.request_versioned(batch, 10)
        assert (rs.reuse.hits, rs.reuse.misses) == (1, 1)
        np.testing.assert_array_equal(r1.ids, r2.ids)
        np.testing.assert_array_equal(r1.scores, r2.scores)
        # k is part of the key: same user at k=5 is a different answer
        r5 = rs.request_versioned(batch, 5)
        assert rs.reuse.misses == 2 and r5.ids.shape[1] == 5
        np.testing.assert_array_equal(r5.ids, r1.ids[:, :5])
        # no_cache: full sweep, counters frozen, same answer
        h, m = rs.reuse.hits, rs.reuse.misses
        r_nc = rs.request_versioned(batch, 10, no_cache=True)
        assert (rs.reuse.hits, rs.reuse.misses) == (h, m)
        np.testing.assert_array_equal(r1.ids, r_nc.ids)
        # ingest invalidates: corpus_rev is half the version key
        rev0 = eng.corpus_rev
        eng.upsert_items(np.array([999], np.int64),
                         {"V0": np.array([250]), "V1": np.array([450])})
        assert eng.corpus_rev == rev0 + 1
        assert rs.reuse.invalidations >= 1 and len(rs.reuse) == 0
        rs.request_versioned(batch, 10)
        assert rs.reuse.misses == m + 1
        # model publish invalidates too (model version is the other half)
        for _ in range(2):
            st, _ = tr.train_step(st, J(gen.batch()))
        ck.save_incremental(st)
        assert pred.poll_updates() is True
        assert len(rs.reuse) == 0
        r_new = rs.request_versioned(batch, 10)
        assert r_new.version == r1.version + 1
    finally:
        rs.close()


# ------------------------------------------------- edges: HTTP, wire, fleet


def test_http_no_cache_body_field_and_metrics_render(wdl_ckpt):
    """`no_cache` as an HTTP body field bypasses a warm cache; /metrics
    renders the reuse counter/gauge family under the bounded `cache`
    label."""
    model, ckpt, req = wdl_ckpt
    server = ModelServer(Predictor(model, ckpt), max_batch=64,
                         max_wait_ms=1.0, reuse_cache_bytes=1 << 20)
    http = HttpServer(server, port=0).start()
    try:
        def post(extra):
            body = json.dumps(dict(
                {"features": {k: v.tolist() for k, v in req.items()}},
                **extra)).encode()
            r = urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{http.port}/v1/predict", data=body,
                headers={"Content-Type": "application/json"},
                method="POST"), timeout=30)
            return json.loads(r.read())["predictions"]

        p1 = post({})
        p2 = post({})  # hit
        assert reuse_counts(server) == (1, 1)
        p3 = post({"no_cache": True})
        assert reuse_counts(server) == (1, 1)
        np.testing.assert_array_equal(np.asarray(p1), np.asarray(p2))
        np.testing.assert_array_equal(np.asarray(p1), np.asarray(p3))

        txt = urllib.request.urlopen(
            f"http://127.0.0.1:{http.port}/metrics", timeout=10
        ).read().decode()
        for series in ("deeprec_reuse_hits_total", "deeprec_reuse_misses_total",
                       "deeprec_reuse_invalidations_total",
                       "deeprec_reuse_occupancy_bytes",
                       "deeprec_reuse_capacity_bytes",
                       "deeprec_reuse_entries"):
            assert series in txt, series
        assert 'cache="predict"' in txt
    finally:
        http.stop()
        server.close()


def test_fleet_wire_no_cache_flag_and_merged_metrics(wdl_ckpt):
    """Through the socket tier: repeats hit each backend's cache, the
    PRED wire flag carries no_cache (counters frozen, same answer), and
    the frontend's merged /metrics re-exports every member's reuse
    series."""
    model, ckpt, req = wdl_ckpt
    backends = [
        BackendServer(ModelServer(Predictor(model, ckpt), max_batch=64,
                                  max_wait_ms=1.0,
                                  reuse_cache_bytes=1 << 20)).start()
        for _ in range(2)
    ]
    fe = Frontend([("127.0.0.1", b.port) for b in backends], model)
    try:
        outs = [fe.request_versioned(req) for _ in range(4)]
        vers = {v for _, v in outs}
        assert len(vers) == 1
        for r, _ in outs[1:]:
            np.testing.assert_array_equal(np.asarray(outs[0][0]),
                                          np.asarray(r))
        def totals():
            hs, ms = zip(*(reuse_counts(b.server) for b in backends))
            return sum(hs), sum(ms)

        h0, m0 = totals()
        assert h0 >= 1  # round-robin repeats landed on a warm member
        r_nc, _ = fe.request_versioned(req, no_cache=True)
        h1, m1 = totals()
        assert (h1, m1) == (h0, m0)  # the wire flag reached the backend
        np.testing.assert_array_equal(np.asarray(outs[0][0]),
                                      np.asarray(r_nc))
        txt = fe.metrics_text()
        assert "deeprec_reuse_hits_total" in txt
        assert 'cache="predict"' in txt and 'member="' in txt
    finally:
        fe.close()
        for b in backends:
            b.stop()
