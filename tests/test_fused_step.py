"""Fused single-pass sparse step vs the split-phase oracle (interpret mode).

The contract (ops/fused_lookup.fused_sparse_forward/backward): the fused
Pallas kernel and the XLA fallback produce the SAME combined bags / updated
rows — bit-identical at fp32, seeded-SR bitwise at bf16 — with BOTH sides
under jax.jit. The jit is part of the contract, not a convenience: eager
op-by-op execution skips the FMA contraction XLA applies inside a compiled
(interpret-mode) kernel, so un-jitted comparisons show 1-ulp float diffs
that vanish in every production context (docs/kernels.md).

uids ORDER is path-dependent (kernel claims in stream order, the XLA
fallback compacts in scratch-slot order), so uids/counts compare as
multisets and `out` — order-independent by construction — compares bitwise.
Overflowed batches keep COUNT parity only: WHICH distinct ids make the
budget is path-dependent (both answers valid), so bitwise cases pin
overflow == 0.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeprec_tpu.ops import fused_lookup as fl
from deeprec_tpu.ops.dedup import resolve_size
from deeprec_tpu.optim.sparse import REGISTRY

B, L = 4, 4
N = B * L


def _ids(rng, vocab, *, pads=True):
    ids = rng.integers(0, vocab, (B, L))
    if pads:
        ids[0, :] = -1            # empty bag
        ids[1, :] = ids[1, 0]     # all-duplicate bag
        ids[2, 2:] = -1           # pad inside a bag
    return jnp.asarray(ids, jnp.int32)


def _fwd(fused, *, combiner, U):
    return jax.jit(lambda v, i: fl.fused_sparse_forward(
        v, i, combiner=combiner, unique_size=U,
        interpret=fused, use_pallas=fused,
    ))


def _step(fused, opt, *, combiner, U, seed=7):
    def fn(v, s, i):
        res = fl.fused_sparse_forward(
            v, i, combiner=combiner, unique_size=U,
            interpret=fused, use_pallas=fused,
        )
        g = res.out * 0.25 + 1.0
        return fl.fused_sparse_backward(
            v, s, g, i, res, opt, combiner=combiner, step=3, seed=seed,
            interpret=fused, use_pallas=fused,
        )
    return jax.jit(fn)


def _table(rng, C, D, dtype):
    return jnp.asarray(rng.normal(0, 0.5, (C, D)), dtype)


def _slots(opt, C, D):
    return {
        name: jnp.full((C, D), init, jnp.float32)
        for name, (shape, init) in opt.slot_specs(D).items()
    }


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
@pytest.mark.parametrize("combiner", ["sum", "mean", "sqrtn"])
@pytest.mark.parametrize("dim", [128, 96, 1])
def test_forward_parity(dtype, combiner, dim):
    seed = sum(map(ord, dtype + combiner)) * 1000 + dim  # hash() is salted
    rng = np.random.default_rng(seed)
    C, U = 32, resolve_size(8, N)
    vals = _table(rng, C, dim, jnp.dtype(dtype))
    ids = _ids(rng, 8)  # vocab 8 < budget: overflow == 0 guaranteed
    ru = _fwd(False, combiner=combiner, U=U)(vals, ids)
    rf = _fwd(True, combiner=combiner, U=U)(vals, ids)

    assert int(ru.overflow) == 0 and int(rf.overflow) == 0
    # out is order-independent: bitwise across paths, f32 both ways.
    assert ru.out.dtype == rf.out.dtype == jnp.float32
    np.testing.assert_array_equal(np.asarray(ru.out), np.asarray(rf.out))
    # uids/counts are order-path-dependent: multiset equality.
    for r in (ru, rf):
        assert int(r.uids[0]) == -1 and int(r.counts[0]) == 0
        # inverse reconstructs the id stream wherever it points past the
        # sentinel slot.
        rec = np.asarray(r.uids)[np.asarray(r.inverse)]
        inv = np.asarray(r.inverse)
        np.testing.assert_array_equal(
            rec[inv > 0], np.asarray(ids)[inv > 0]
        )
    zu = sorted(zip(np.asarray(ru.uids), np.asarray(ru.counts)))
    zf = sorted(zip(np.asarray(rf.uids), np.asarray(rf.counts)))
    assert zu == zf


@pytest.mark.parametrize("opt_name", ["sgd", "adagrad", "adam", "adamw",
                                      "ftrl"])
def test_backward_parity_f32(opt_name):
    rng = np.random.default_rng(1)
    C, D, U = 32, 128, resolve_size(8, N)
    opt = REGISTRY[opt_name]()
    vals, slots = _table(rng, C, D, jnp.float32), _slots(opt, C, D)
    ids = _ids(rng, 8)
    (vu, su) = _step(False, opt, combiner="mean", U=U)(vals, slots, ids)
    (vf, sf) = _step(True, opt, combiner="mean", U=U)(vals, slots, ids)
    np.testing.assert_array_equal(np.asarray(vu), np.asarray(vf))
    assert sorted(su) == sorted(sf)
    for k in su:
        np.testing.assert_array_equal(np.asarray(su[k]), np.asarray(sf[k]))
    # the step actually trained: touched rows moved, untouched rows didn't.
    touched = np.unique(np.asarray(ids)[np.asarray(ids) >= 0])
    moved = np.flatnonzero(
        np.any(np.asarray(vu) != np.asarray(vals), axis=1)
    )
    assert set(moved) == set(touched)


@pytest.mark.parametrize("combiner", ["sum", "sqrtn"])
def test_backward_parity_bf16_sr(combiner):
    """bf16 tables: the fused backward rounds with the same row-keyed SR
    bit stream as the fallback (order-independent hash of (seed, row id,
    column)), so updated values match BITWISE, not just statistically."""
    rng = np.random.default_rng(2)
    C, D, U = 32, 128, resolve_size(8, N)
    opt = REGISTRY["adagrad"]()
    vals, slots = _table(rng, C, D, jnp.bfloat16), _slots(opt, C, D)
    ids = _ids(rng, 8)
    (vu, su) = _step(False, opt, combiner=combiner, U=U)(vals, slots, ids)
    (vf, sf) = _step(True, opt, combiner=combiner, U=U)(vals, slots, ids)
    assert vu.dtype == vf.dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(vu).view(np.uint16), np.asarray(vf).view(np.uint16)
    )
    for k in su:  # slots stay exact f32
        np.testing.assert_array_equal(np.asarray(su[k]), np.asarray(sf[k]))
    # different seed -> different rounding: SR is actually engaged.
    (vu2, _) = _step(False, opt, combiner=combiner, U=U, seed=8)(
        vals, slots, ids
    )
    assert not np.array_equal(
        np.asarray(vu).view(np.uint16), np.asarray(vu2).view(np.uint16)
    )


def test_forward_edge_bags():
    rng = np.random.default_rng(3)
    C, D, U = 32, 128, resolve_size(8, N)
    vals = _table(rng, C, D, jnp.float32)
    ids = _ids(rng, 8)
    for combiner in ("sum", "mean", "sqrtn"):
        r = _fwd(True, combiner=combiner, U=U)(vals, ids)
        # empty bag -> zeros under every combiner (denominator clamps at 1).
        np.testing.assert_array_equal(np.asarray(r.out[0]), 0.0)
    # all-duplicate bag under mean == the row itself.
    r = _fwd(True, combiner="mean", U=U)(vals, ids)
    np.testing.assert_array_equal(
        np.asarray(r.out[1]), np.asarray(vals[int(ids[1, 0])], np.float32)
    )


def test_overflow_count_parity():
    """Past the budget both paths must agree on HOW MANY distinct ids
    overflowed (the budget contract), even though WHICH ids made the cut
    is path-dependent."""
    rng = np.random.default_rng(4)
    C, D = 64, 128
    U = resolve_size(4, N)  # tiny budget, wide vocab -> guaranteed spill
    vals = _table(rng, C, D, jnp.float32)
    ids = _ids(rng, 60, pads=False)
    ru = _fwd(False, combiner="sum", U=U)(vals, ids)
    rf = _fwd(True, combiner="sum", U=U)(vals, ids)
    assert int(ru.overflow) == int(rf.overflow) > 0


def test_non_fusable_optimizers_rejected():
    # Scalar slots (adam_async) and non-[dim] slots (adagrad_decay's
    # (1,)-wide decay counter) keep the split-phase apply.
    assert not fl.fusable_optimizer(REGISTRY["adam_async"](), 128)
    assert not fl.fusable_optimizer(REGISTRY["adagrad_decay"](), 128)
    for name in ("sgd", "adagrad", "adam", "adamw", "ftrl"):
        assert fl.fusable_optimizer(REGISTRY[name](), 128)


def test_packed_slot_layout_rejected():
    rng = np.random.default_rng(5)
    C, D, U = 32, 128, resolve_size(8, N)
    opt = REGISTRY["adagrad"]()
    vals = _table(rng, C, D, jnp.float32)
    ids = _ids(rng, 8)
    res = _fwd(False, combiner="sum", U=U)(vals, ids)
    g = jnp.ones((B, D), jnp.float32)
    with pytest.raises(ValueError, match="packed slot"):
        fl.fused_sparse_backward(
            vals, {"accum": jnp.zeros((C // 2, 2 * D))}, g, ids, res, opt,
            combiner="sum", use_pallas=False,
        )


def test_cpu_dispatch_falls_back_and_counts():
    """On CPU without interpret=True the use_pallas request self-gates to
    XLA (bitwise-identical result) and the rejection shows up on /metrics
    as deeprec_pallas_fallback_total{reason=...} — the silent-fallback
    observability contract."""
    rng = np.random.default_rng(6)
    C, D, U = 32, 128, resolve_size(8, N)
    vals = _table(rng, C, D, jnp.float32)
    ids = _ids(rng, 8)
    a = _fwd(False, combiner="mean", U=U)(vals, ids)
    b = jax.jit(lambda v, i: fl.fused_sparse_forward(
        v, i, combiner="mean", unique_size=U, use_pallas=True,
    ))(vals, ids)
    np.testing.assert_array_equal(np.asarray(a.out), np.asarray(b.out))

    from deeprec_tpu.obs.metrics import default_registry

    text = default_registry().render_prometheus()
    assert "deeprec_pallas_fallback_total" in text
    assert 'kernel="fused_sparse_forward"' in text
    assert 'reason="not_tpu"' in text


def test_dedup_full_fallback_counter():
    from deeprec_tpu.obs.metrics import default_registry
    from deeprec_tpu.ops.dedup import log_full_fallback

    log_full_fallback("fused_step_test_table", 4096)
    text = default_registry().render_prometheus()
    assert 'kernel="dedup"' in text and 'reason="no_budget"' in text


def test_table_bag_forward_and_apply_wiring():
    from deeprec_tpu.embedding.table import EmbeddingTable, TableConfig
    from deeprec_tpu.ops.packed import pack_array
    from deeprec_tpu.optim.apply import apply_bag_gradients, ensure_slots

    rng = np.random.default_rng(7)
    C, D = 64, 128
    tbl = EmbeddingTable(TableConfig(name="t", dim=D, capacity=C))
    opt = REGISTRY["adagrad"]()
    state = ensure_slots(tbl, tbl.create(), opt)
    state = state.replace(values=_table(rng, C, D, jnp.float32))
    ids = _ids(rng, 8)
    U = resolve_size(8, N)
    res = tbl.bag_forward(state, ids, combiner="mean", unique_size=U,
                          interpret=True)
    g = jnp.ones((B, D), jnp.float32)
    ns = apply_bag_gradients(tbl, state, opt, res, g, ids, combiner="mean",
                             step=5, interpret=True)
    touched = np.unique(np.asarray(ids)[np.asarray(ids) >= 0])
    moved = np.flatnonzero(np.any(
        np.asarray(ns.values) != np.asarray(state.values), axis=1
    ))
    assert set(moved) == set(touched)
    # meta stamps mirror apply_gradients: version=step, dirty=1, touched
    # rows only.
    from deeprec_tpu.embedding.table import META_DIRTY, META_VERSION

    meta = np.asarray(ns.meta)
    assert all(meta[META_VERSION, r] == 5 for r in touched)
    assert all(meta[META_DIRTY, r] == 1 for r in touched)
    untouched = sorted(set(range(C)) - set(touched.tolist()))
    assert all(meta[META_VERSION, r] != 5 for r in untouched)

    # packed value layouts keep the split-phase path, loudly.
    tiny = EmbeddingTable(TableConfig(name="p", dim=16, capacity=C))
    st = ensure_slots(tiny, tiny.create(), opt)
    st = st.replace(values=pack_array(st.values, 8))
    with pytest.raises(NotImplementedError, match="packed"):
        tiny.bag_forward(st, ids, combiner="mean", unique_size=U)
    with pytest.raises(NotImplementedError, match="scalar"):
        apply_bag_gradients(tbl, state, REGISTRY["adam_async"](), res, g,
                            ids)
