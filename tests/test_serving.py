"""Serving tests — Processor/SessionGroup/ModelInstanceMgr behaviors
(reference: serving/processor tests, end2end/demo.cc flow: train a toy
model, serve it, hot-swap updates)."""
import threading

import jax.numpy as jnp
import numpy as np
import optax
import pytest

from deeprec_tpu.data import SyntheticCriteo
from deeprec_tpu.models import WDL
from deeprec_tpu.optim import Adagrad
from deeprec_tpu.serving import ModelServer, Predictor
from deeprec_tpu.training import Trainer
from deeprec_tpu.training.checkpoint import CheckpointManager


def J(b):
    return {k: jnp.asarray(v) for k, v in b.items()}


def make_trained(tmp_path, steps=5):
    model = WDL(emb_dim=8, capacity=1 << 12, hidden=(32,), num_cat=4, num_dense=2)
    tr = Trainer(model, Adagrad(lr=0.1), optax.adam(1e-3))
    st = tr.init(0)
    gen = SyntheticCriteo(batch_size=128, num_cat=4, num_dense=2, vocab=800, seed=21)
    batches = [J(gen.batch()) for _ in range(steps)]
    for b in batches:
        st, _ = tr.train_step(st, b)
    ck = CheckpointManager(str(tmp_path), tr)
    st, _ = ck.save(st)
    return model, tr, st, ck, batches, gen


def strip_labels(b):
    return {k: np.asarray(v) for k, v in b.items() if not k.startswith("label")}


def test_predictor_serves_and_matches_training_eval(tmp_path):
    model, tr, st, ck, batches, gen = make_trained(tmp_path)
    p = Predictor(model, str(tmp_path))
    probs = p.predict(strip_labels(batches[0]))
    _, expect = tr.eval_step(st, batches[0])
    np.testing.assert_allclose(np.asarray(probs), np.asarray(expect), atol=1e-6)
    info = p.model_info()
    assert info["step"] == 5 and all(v > 0 for v in info["table_sizes"].values())


def test_delta_model_update(tmp_path):
    model, tr, st, ck, batches, gen = make_trained(tmp_path)
    p = Predictor(model, str(tmp_path))
    before = p.predict(strip_labels(batches[0]))
    # train further, write only a DELTA
    for _ in range(3):
        st, _ = tr.train_step(st, batches[0])
    st, _ = ck.save_incremental(st)
    assert p.poll_updates() is True
    after = p.predict(strip_labels(batches[0]))
    assert p.step == 8
    _, expect = tr.eval_step(st, batches[0])
    np.testing.assert_allclose(np.asarray(after), np.asarray(expect), atol=1e-6)
    assert np.abs(np.asarray(after) - np.asarray(before)).max() > 1e-6
    # idempotent: nothing new
    assert p.poll_updates() is False


def test_full_model_update_supersedes(tmp_path):
    model, tr, st, ck, batches, gen = make_trained(tmp_path)
    p = Predictor(model, str(tmp_path))
    for _ in range(2):
        st, _ = tr.train_step(st, batches[1])
    st, _ = ck.save(st)  # new FULL checkpoint
    assert p.poll_updates() is True
    assert p.step == 7


def test_model_server_batches_concurrent_requests(tmp_path):
    model, tr, st, ck, batches, gen = make_trained(tmp_path)
    server = ModelServer(Predictor(model, str(tmp_path)), max_batch=64,
                         max_wait_ms=5)
    req = strip_labels(batches[0])
    single = {k: v[:1] for k, v in req.items()}
    results = [None] * 16

    def call(i):
        results[i] = server.request(single)

    threads = [threading.Thread(target=call, args=(i,)) for i in range(16)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    server.close()
    assert all(r is not None and r.shape == (1,) for r in results)
    # all identical inputs -> identical outputs
    vals = np.asarray([float(r[0]) for r in results])
    np.testing.assert_allclose(vals, vals[0], atol=1e-6)
