"""Serving tests — Processor/SessionGroup/ModelInstanceMgr behaviors
(reference: serving/processor tests, end2end/demo.cc flow: train a toy
model, serve it, hot-swap updates)."""
import threading

import jax.numpy as jnp
import numpy as np
import optax
import pytest

from deeprec_tpu.data import SyntheticCriteo
from deeprec_tpu.models import WDL
from deeprec_tpu.optim import Adagrad
from deeprec_tpu.serving import ModelServer, Predictor
from deeprec_tpu.training import Trainer
from deeprec_tpu.training.checkpoint import CheckpointManager


def J(b):
    return {k: jnp.asarray(v) for k, v in b.items()}


def make_trained(tmp_path, steps=5):
    model = WDL(emb_dim=8, capacity=1 << 12, hidden=(32,), num_cat=4, num_dense=2)
    tr = Trainer(model, Adagrad(lr=0.1), optax.adam(1e-3))
    st = tr.init(0)
    gen = SyntheticCriteo(batch_size=128, num_cat=4, num_dense=2, vocab=800, seed=21)
    batches = [J(gen.batch()) for _ in range(steps)]
    for b in batches:
        st, _ = tr.train_step(st, b)
    ck = CheckpointManager(str(tmp_path), tr)
    st, _ = ck.save(st)
    return model, tr, st, ck, batches, gen


def strip_labels(b):
    return {k: np.asarray(v) for k, v in b.items() if not k.startswith("label")}


def test_predictor_serves_and_matches_training_eval(tmp_path):
    model, tr, st, ck, batches, gen = make_trained(tmp_path)
    p = Predictor(model, str(tmp_path))
    probs = p.predict(strip_labels(batches[0]))
    _, expect = tr.eval_step(st, batches[0])
    np.testing.assert_allclose(np.asarray(probs), np.asarray(expect), atol=1e-6)
    info = p.model_info()
    assert info["step"] == 5 and all(v > 0 for v in info["table_sizes"].values())


def test_delta_model_update(tmp_path):
    model, tr, st, ck, batches, gen = make_trained(tmp_path)
    p = Predictor(model, str(tmp_path))
    before = p.predict(strip_labels(batches[0]))
    # train further, write only a DELTA
    for _ in range(3):
        st, _ = tr.train_step(st, batches[0])
    st, _ = ck.save_incremental(st)
    assert p.poll_updates() is True
    after = p.predict(strip_labels(batches[0]))
    assert p.step == 8
    _, expect = tr.eval_step(st, batches[0])
    np.testing.assert_allclose(np.asarray(after), np.asarray(expect), atol=1e-6)
    assert np.abs(np.asarray(after) - np.asarray(before)).max() > 1e-6
    # idempotent: nothing new
    assert p.poll_updates() is False


def test_full_model_update_supersedes(tmp_path):
    model, tr, st, ck, batches, gen = make_trained(tmp_path)
    p = Predictor(model, str(tmp_path))
    for _ in range(2):
        st, _ = tr.train_step(st, batches[1])
    st, _ = ck.save(st)  # new FULL checkpoint
    assert p.poll_updates() is True
    assert p.step == 7


def test_feature_store_read_through(tmp_path):
    """Keys missing from the device table serve the store's row instead of
    the initializer — Redis feature-store read-through parity
    (redis_feature_store.h:18)."""
    from deeprec_tpu.native import HostKV

    model, tr, st, ck, batches, gen = make_trained(tmp_path)
    # pick an id that was never trained: it misses in every table
    novel = 999_999
    req = strip_labels(batches[0])
    # stores keyed by table name; fill one table's store with a marked row
    tname = sorted(tr.tables)[0]
    dim = tr.tables[tname].cfg.dim
    kv = HostKV(dim=dim, initial_capacity=64)
    kv.put(np.asarray([novel], np.int64),
           np.full((1, dim), 2.5, np.float32),
           np.asarray([1], np.int32), np.asarray([1], np.int32))

    p_plain = Predictor(model, str(tmp_path))
    p_store = Predictor(model, str(tmp_path), stores={tname: kv})
    req_novel = dict(req)
    req_novel[tname] = np.full_like(req[tname], novel)
    out_plain = p_plain.predict(req_novel)
    out_store = p_store.predict(req_novel)
    # the store row changes the served prediction
    assert np.abs(np.asarray(out_store) - np.asarray(out_plain)).max() > 1e-6
    # and known keys predict identically through both paths
    np.testing.assert_allclose(
        np.asarray(p_store.predict(req)), np.asarray(p_plain.predict(req)),
        atol=1e-6,
    )


def test_http_server_end_to_end(tmp_path):
    """train -> save -> serve over HTTP -> delta-update -> prediction shifts
    (the VERDICT round-1 acceptance flow for the serving frontend)."""
    import json
    import urllib.request

    from deeprec_tpu.serving import HttpServer

    model, tr, st, ck, batches, gen = make_trained(tmp_path)
    server = ModelServer(Predictor(model, str(tmp_path)), max_batch=64,
                         max_wait_ms=2)
    http = HttpServer(server, port=0).start()  # ephemeral port
    base = f"http://127.0.0.1:{http.port}"

    def call(path, payload=None):
        req = urllib.request.Request(
            base + path,
            data=None if payload is None else json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
            method="GET" if payload is None else "POST",
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            return json.loads(r.read())

    try:
        info = call("/v1/model_info")
        assert info["step"] == 5

        feats = {
            k: np.asarray(v)[:4].tolist()
            for k, v in strip_labels(batches[0]).items()
        }
        out1 = call("/v1/predict", {"features": feats})["predictions"]
        assert len(out1) == 4 and all(0.0 <= p <= 1.0 for p in out1)

        # delta-update: train on, save incremental, tell the server to poll
        for _ in range(3):
            st, _ = tr.train_step(st, batches[0])
        st, _ = ck.save_incremental(st)
        assert call("/v1/reload", {})["updated"] is True
        assert call("/v1/model_info")["step"] == 8
        out2 = call("/v1/predict", {"features": feats})["predictions"]
        assert np.abs(np.asarray(out2) - np.asarray(out1)).max() > 1e-6

        # inconsistent row counts -> 400 BEFORE batching (would otherwise
        # poison coalesced neighbors)
        ragged = {k: (v if i else v[:1]) for i, (k, v) in
                  enumerate(sorted(feats.items()))}
        req = urllib.request.Request(
            base + "/v1/predict",
            data=json.dumps({"features": ragged}).encode(),
            headers={"Content-Type": "application/json"}, method="POST",
        )
        try:
            urllib.request.urlopen(req, timeout=10)
            assert False, "expected HTTP 400"
        except urllib.error.HTTPError as e:
            assert e.code == 400
            assert "row counts" in json.loads(e.read())["error"]

        # malformed requests -> 400 with a JSON error, server stays alive:
        # empty body, non-dict body, and a typo'd feature name (validated
        # BEFORE batching so it can't poison coalesced neighbors)
        bad_feats = dict(feats)
        bad_feats["C_TYPO"] = bad_feats.pop(sorted(feats)[0])
        for body in (b"{}", b"[1,2]",
                     json.dumps({"features": bad_feats}).encode()):
            req = urllib.request.Request(
                base + "/v1/predict", data=body,
                headers={"Content-Type": "application/json"}, method="POST",
            )
            try:
                urllib.request.urlopen(req, timeout=10)
                assert False, "expected HTTP 400"
            except urllib.error.HTTPError as e:
                assert e.code == 400
                err = json.loads(e.read())
                assert "error" in err
        hz = call("/healthz")
        assert hz["status"] == "ok"
        assert hz["consecutive_poll_failures"] == 0
        assert "staleness_seconds" in hz
    finally:
        http.stop()
        server.close()


def test_server_warmup_precompiles_buckets(tmp_path):
    model, tr, st, ck, batches, gen = make_trained(tmp_path)
    server = ModelServer(Predictor(model, str(tmp_path)), max_batch=32,
                         max_wait_ms=2)
    try:
        n = server.warmup(strip_labels(batches[0]))
        assert n == 3  # buckets 8, 16, 32
        out = server.request(
            {k: v[:1] for k, v in strip_labels(batches[0]).items()}
        )
        assert out.shape == (1,)
    finally:
        server.close()


def test_checkpoint_option_drops_filtered_features():
    """CheckpointOption(save_filtered_features=False): sub-threshold keys
    are dropped at export (TF_EV_SAVE_FILTERED_FEATURES parity); the
    default keeps them so admission counters survive restarts."""
    import dataclasses

    from deeprec_tpu import (
        CheckpointOption,
        CounterFilter,
        EmbeddingTable,
        EmbeddingVariableOption,
        TableConfig,
    )
    from deeprec_tpu.training.checkpoint import _state_to_np, export_table_arrays

    cfg = TableConfig(
        name="cf", dim=4, capacity=128,
        ev=EmbeddingVariableOption(counter_filter=CounterFilter(filter_freq=3)),
    )
    t = EmbeddingTable(cfg)
    s = t.create()
    hot = jnp.arange(5, dtype=jnp.int32)
    for step in range(3):
        s, _ = t.lookup_unique(s, hot, step=step)  # freq 3: admitted
    s, _ = t.lookup_unique(s, jnp.arange(5, 20, dtype=jnp.int32), step=3)

    keep_all = export_table_arrays(t, _state_to_np(s), only_dirty=False)
    assert keep_all["keys"].shape[0] == 20  # default: everything saved

    t2 = EmbeddingTable(dataclasses.replace(
        cfg, ev=dataclasses.replace(
            cfg.ev, ckpt=CheckpointOption(save_filtered_features=False))))
    shrunk = export_table_arrays(t2, _state_to_np(s), only_dirty=False)
    assert sorted(shrunk["keys"].tolist()) == list(range(5))
    assert (shrunk["freqs"] >= 3).all()


def test_remote_feature_store_over_tcp(tmp_path):
    """Predictor read-through against a REMOTE store (redis_feature_store
    parity): rows served over the network change predictions exactly like
    an in-process HostKV store."""
    from deeprec_tpu.native import HostKV
    from deeprec_tpu.serving import RemoteKVClient, RemoteKVServer

    model, tr, st, ck, batches, gen = make_trained(tmp_path)
    tname = sorted(tr.tables)[0]
    dim = tr.tables[tname].cfg.dim
    kv = HostKV(dim=dim, initial_capacity=64)
    srv = RemoteKVServer(kv, dim=dim).start()
    try:
        client = RemoteKVClient("127.0.0.1", srv.port, dim=dim)
        novel = 424242
        client.put(np.asarray([novel], np.int64),
                   np.full((1, dim), 1.75, np.float32))
        # round-trip sanity straight through the wire
        vals, _, _, found = client.get(np.asarray([novel, 77], np.int64))
        assert found.tolist() == [True, False]
        np.testing.assert_allclose(vals[0], 1.75)

        p_remote = Predictor(model, str(tmp_path), stores={tname: client})
        p_plain = Predictor(model, str(tmp_path))
        req = strip_labels(batches[0])
        req_novel = dict(req)
        req_novel[tname] = np.full_like(req[tname], novel)
        out_r = p_remote.predict(req_novel)
        out_p = p_plain.predict(req_novel)
        assert np.abs(np.asarray(out_r) - np.asarray(out_p)).max() > 1e-6
        # known keys unaffected
        np.testing.assert_allclose(
            np.asarray(p_remote.predict(req)),
            np.asarray(p_plain.predict(req)), atol=1e-6,
        )
        client.close()
    finally:
        srv.stop()


def test_http_serves_ragged_histories_one_shape(tmp_path):
    """Sequence models over HTTP: ragged JSON history lists pad/trim to the
    feature's declared max_len with its pad_value — one compiled shape per
    feature, and short histories predict fine."""
    from deeprec_tpu.data import SyntheticBehaviorSequence
    from deeprec_tpu.models import DIN
    from deeprec_tpu.serving import HttpServer
    import json
    import urllib.request

    model = DIN(emb_dim=4, capacity=1 << 10, hidden=(8,))
    tr = Trainer(model, Adagrad(lr=0.1), optax.adam(1e-3))
    st = tr.init(0)
    gen = SyntheticBehaviorSequence(batch_size=64, vocab=500, seq_len=6,
                                    seed=0)
    for _ in range(2):
        st, _ = tr.train_step(st, J(gen.batch()))
    ck = CheckpointManager(str(tmp_path), tr)
    st, _ = ck.save(st)

    server = ModelServer(Predictor(model, str(tmp_path)), max_batch=16,
                         max_wait_ms=2)
    http = HttpServer(server, port=0).start()
    try:
        feats = {
            "user": [1, 2],
            "target_item": [3, 4],
            "target_cat": [5, 6],
            "hist_items": [[7, 8, 9], [10]],  # ragged
            "hist_cats": [[1, 2, 3], [4]],
        }
        req = urllib.request.Request(
            f"http://127.0.0.1:{http.port}/v1/predict",
            data=json.dumps({"features": feats}).encode(),
            headers={"Content-Type": "application/json"}, method="POST",
        )
        with urllib.request.urlopen(req, timeout=60) as r:
            out = json.loads(r.read())["predictions"]
        assert len(out) == 2 and all(0.0 <= p <= 1.0 for p in out)
    finally:
        http.stop()
        server.close()


def test_model_server_batches_concurrent_requests(tmp_path):
    model, tr, st, ck, batches, gen = make_trained(tmp_path)
    server = ModelServer(Predictor(model, str(tmp_path)), max_batch=64,
                         max_wait_ms=5)
    req = strip_labels(batches[0])
    single = {k: v[:1] for k, v in req.items()}
    results = [None] * 16

    def call(i):
        results[i] = server.request(single)

    threads = [threading.Thread(target=call, args=(i,)) for i in range(16)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    server.close()
    assert all(r is not None and r.shape == (1,) for r in results)
    # all identical inputs -> identical outputs
    vals = np.asarray([float(r[0]) for r in results])
    np.testing.assert_allclose(vals, vals[0], atol=1e-6)


def test_model_server_coalesces_grouped_requests(tmp_path):
    """N-candidate user-tower reuse THROUGH the micro-batcher: concurrent
    `<user, N items>` requests marked group_users coalesce into one
    device batch whose user tower runs once per distinct user across ALL
    of them; outputs are row-identical to direct predicts, every request
    is stamped with the one version its shared batch served from, and a
    plain request arriving in the middle never shares their dispatch."""
    import optax as _optax

    from deeprec_tpu.data import SyntheticTwoTower
    from deeprec_tpu.models import DSSM

    model = DSSM(emb_dim=8, capacity=1 << 12, num_user_feats=2,
                 num_item_feats=2, hidden=(32, 16))
    tr = Trainer(model, Adagrad(lr=0.1), _optax.adam(2e-3))
    st = tr.init(0)
    gen = SyntheticTwoTower(batch_size=128, num_user=2, num_item=2,
                            vocab=500, seed=31)
    for _ in range(3):
        st, _ = tr.train_step(st, J(gen.batch()))
    CheckpointManager(str(tmp_path), tr).save(st)
    pred = Predictor(model, str(tmp_path))
    base = strip_labels(gen.batch())

    def user_req(u, n_items=8):
        out = {}
        for k, v in base.items():
            rows = v[u * n_items:(u + 1) * n_items].copy()
            if k in model.user_feats:
                rows = np.repeat(v[u:u + 1], n_items, axis=0)
            out[k] = rows
        return out

    reqs = {u: user_req(u) for u in range(4)}
    expect = {u: np.asarray(pred.predict(r)) for u, r in reqs.items()}

    # spy: how many rows the user tower traces over per dispatch
    seen = []
    orig_user_vector = type(model).user_vector

    def spy(self, params, inputs):
        u = jnp.concatenate([inputs.pooled[n] for n in self.user_feats], -1)
        seen.append(int(u.shape[0]))
        return orig_user_vector(self, params, inputs)

    server = ModelServer(pred, max_batch=64, max_wait_ms=20)
    try:
        # warm the single-request grouped bucket so the measured batch is
        # the only fresh trace
        server.request(reqs[0], group_users=True)
        type(model).user_vector = spy
        # submit all four <user, 8 items> requests back to back: the
        # batcher's coalescing window gathers them into ONE device batch
        replies = {u: server.submit(reqs[u], group_users=True)
                   for u in reqs}
        results = {u: r.get(timeout=30) for u, r in replies.items()}
        plain_out = server.request(reqs[0])  # plain lane, separate dispatch
    finally:
        type(model).user_vector = orig_user_vector
        server.close()

    versions = set()
    for u, out in results.items():
        assert not isinstance(out, Exception), out
        np.testing.assert_allclose(np.asarray(out[0]), expect[u], rtol=2e-5,
                                   atol=2e-5)
        versions.add(out[1])
    assert versions == {0}  # one shared snapshot stamped every request
    np.testing.assert_allclose(np.asarray(plain_out), expect[0],
                               rtol=2e-5, atol=2e-5)
    # the coalesced grouped batch ran a COMPRESSED user tower: its trace
    # saw at most one row per distinct user (<= 8 for a <=8-user batch),
    # never the 32 item rows the batch carried (spy records at trace
    # time — cache-hit dispatches are invisible, so the warm covers only
    # the single-request shape and the coalesced shape must trace here)
    stats = server.stats_snapshot()
    assert stats["requests"] == 6  # 1 warm + 4 grouped + 1 plain
    assert seen and min(seen) <= 8, seen


def test_multi_model_tfs_routes(tmp_path):
    """Multi-model serving over the TF-Serving REST shapes: two separately
    trained models behind one port, addressed by name; row-major
    'instances' bodies; model status; per-model reload."""
    import json
    import urllib.request
    import urllib.error

    from deeprec_tpu.serving import HttpServer

    dirs = {n: tmp_path / n for n in ("alpha", "beta")}
    trained = {n: make_trained(d, steps=3 if n == "alpha" else 6)
               for n, d in dirs.items()}
    servers = {
        n: ModelServer(Predictor(t[0], str(dirs[n])), max_batch=32,
                       max_wait_ms=1)
        for n, t in trained.items()
    }
    http = HttpServer(servers, port=0, default_model="alpha").start()
    base = f"http://127.0.0.1:{http.port}"

    def call(path, payload=None):
        req = urllib.request.Request(
            base + path,
            data=None if payload is None else json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
            method="GET" if payload is None else "POST",
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            return json.loads(r.read())

    try:
        assert call("/v1/models")["models"] == ["alpha", "beta"]
        # TFS model-status route reports each model's own version
        assert call("/v1/models/alpha")["model_version_status"][0]["version"] == "3"
        assert call("/v1/models/beta")["model_version_status"][0]["version"] == "6"

        batches = trained["alpha"][4]
        feats = {k: np.asarray(v)[:3].tolist()
                 for k, v in strip_labels(batches[0]).items()}
        # column-major per-model predict
        pa = call("/v1/models/alpha:predict", {"features": feats})["predictions"]
        pb = call("/v1/models/beta:predict", {"features": feats})["predictions"]
        assert len(pa) == len(pb) == 3
        assert np.abs(np.asarray(pa) - np.asarray(pb)).max() > 1e-6  # distinct models
        # bare route hits the default model
        pd = call("/v1/predict", {"features": feats})["predictions"]
        np.testing.assert_allclose(pd, pa, atol=1e-6)

        # TFS row-major instances body == column-major features body
        instances = [
            {k: feats[k][i] for k in feats} for i in range(3)
        ]
        pi = call("/v1/models/alpha:predict", {"instances": instances})["predictions"]
        np.testing.assert_allclose(pi, pa, atol=1e-6)

        # per-model reload: advance beta only; alpha's step is untouched
        model, tr, st, ck = trained["beta"][:4]
        for _ in range(2):
            st, _ = tr.train_step(st, trained["beta"][4][0])
        st, _ = ck.save_incremental(st)
        assert call("/v1/models/beta:reload", {})["updated"] is True
        assert call("/v1/models/beta")["model_version_status"][0]["version"] == "8"
        assert call("/v1/models/alpha")["model_version_status"][0]["version"] == "3"

        # unknown model -> 404 with the catalog
        try:
            call("/v1/models/nope:predict", {"features": feats})
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
            assert json.loads(e.read())["models"] == ["alpha", "beta"]
    finally:
        http.stop()
        for s in servers.values():
            s.close()


def test_protobuf_wire_end_to_end(tmp_path):
    """Reference wire format through both frontends: a serialized
    PredictRequest (predict.proto) in, a PredictResponse out, predictions
    byte-identical to the JSON path. Covers the C-ABI dispatch function
    (process_request) and the HTTP content-type route."""
    import urllib.request

    from deeprec_tpu.serving import HttpServer
    from deeprec_tpu.serving.cabi import process_proto, process_request
    from deeprec_tpu.serving.predict_pb import (
        ArrayProto,
        PredictRequest,
        PredictResponse,
    )

    model, tr, st, ck, batches, gen = make_trained(tmp_path)
    server = ModelServer(Predictor(model, str(tmp_path)), max_batch=64,
                         max_wait_ms=2)
    feats = {k: np.asarray(v)[:4] for k, v in strip_labels(batches[0]).items()}
    expect = np.asarray(server.predictor.predict(feats))

    wire = PredictRequest(
        signature_name="serving_default",
        inputs={k: ArrayProto.from_numpy(v) for k, v in feats.items()},
    ).serialize()

    # In-process (what the C ABI's process() forwards to)
    code, body = process_request(server, wire)
    assert code == 200
    out = PredictResponse.parse(body).outputs["probabilities"].to_numpy()
    np.testing.assert_allclose(out, expect, atol=1e-6)

    # output_filter: unknown alias -> client error, not a 500
    bad = PredictRequest(
        inputs={k: ArrayProto.from_numpy(v) for k, v in feats.items()},
        output_filter=["no_such_output"],
    ).serialize()
    code, body = process_proto(server, bad)
    assert code == 400 and b"no_such_output" in body

    # Garbage protobuf -> 400 plain-text, not a crash
    code, body = process_request(server, b"\xff\xfe\xfd")
    assert code == 400

    # HTTP with the protobuf content-type
    http = HttpServer(server, port=0).start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{http.port}/v1/predict", data=wire,
            headers={"Content-Type": "application/x-protobuf"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            assert r.headers.get("Content-Type") == "application/x-protobuf"
            out2 = PredictResponse.parse(r.read())
        np.testing.assert_allclose(
            out2.outputs["probabilities"].to_numpy(), expect, atol=1e-6)
    finally:
        http.stop()
        server.close()


def test_sample_aware_compression_grouped_users(tmp_path):
    """Serving-side sample-aware compression (reference
    serving/processor/framework/graph_optimizer.cc): a <user, N items>
    batch routes the user tower through nn.apply_grouped — G distinct
    users' rows instead of B — with outputs row-for-row identical to the
    plain path."""
    import optax

    from deeprec_tpu.data import SyntheticTwoTower
    from deeprec_tpu.models import DSSM

    model = DSSM(emb_dim=8, capacity=1 << 12, num_user_feats=2,
                 num_item_feats=2, hidden=(32, 16))
    tr = Trainer(model, Adagrad(lr=0.1), optax.adam(2e-3))
    st = tr.init(0)
    gen = SyntheticTwoTower(batch_size=128, num_user=2, num_item=2,
                            vocab=500, seed=17)
    for _ in range(3):
        st, _ = tr.train_step(st, J(gen.batch()))
    ck = CheckpointManager(str(tmp_path), tr)
    ck.save(st)

    pred = Predictor(model, str(tmp_path))

    # <user, N items>: 4 distinct users x 8 candidate items each
    base = {k: np.asarray(v) for k, v in gen.batch().items()
            if not k.startswith("label")}
    B, n_users, n_items = 32, 4, 8
    batch = {}
    for k, v in base.items():
        rows = v[:B].copy()
        if k in model.user_feats:  # repeat each user's features x8
            rows = np.repeat(v[:n_users], n_items, axis=0)
        batch[k] = rows

    # count the rows the user tower actually traces over
    seen = []
    orig_user_vector = type(model).user_vector

    def spy(self, params, inputs):
        u = jnp.concatenate(
            [inputs.pooled[n] for n in self.user_feats], -1)
        seen.append(int(u.shape[0]))
        return orig_user_vector(self, params, inputs)

    type(model).user_vector = spy
    try:
        plain = np.asarray(pred.predict(batch))
        grouped = np.asarray(pred.predict(batch, group_users=True))
    finally:
        type(model).user_vector = orig_user_vector

    np.testing.assert_allclose(grouped, plain, rtol=2e-6, atol=2e-6)
    # plain path traced the full batch; grouped path traced 4 users
    assert max(seen) == B
    assert min(seen) == n_users  # fewer user-tower FLOPs: 4 rows, not 32

    # the HTTP frontend routes the flag end-to-end (and a tower-less
    # model would get a 400 through the same route)
    import json as _json
    import urllib.request

    from deeprec_tpu.serving import HttpServer, ModelServer

    server = ModelServer(pred, max_batch=64, max_wait_ms=1)
    http = HttpServer(server, port=0).start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{http.port}/v1/predict",
            data=_json.dumps({
                "features": {k: v.tolist() for k, v in batch.items()},
                "group_users": True,
            }).encode(),
            headers={"Content-Type": "application/json"}, method="POST",
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            via_http = _json.loads(r.read())["predictions"]
        np.testing.assert_allclose(np.asarray(via_http), plain,
                                   rtol=2e-5, atol=2e-5)
    finally:
        http.stop()
        server.close()

    # odd client batch sizes ride the power-of-two bucket ladder (no
    # per-size compile storm) and slice back to the client row count
    odd = {k: v[:29] for k, v in batch.items()}
    out_odd = np.asarray(pred.predict(odd, group_users=True))
    assert out_odd.shape[0] == 29
    np.testing.assert_allclose(out_odd, plain[:29], rtol=2e-6, atol=2e-6)

    # a model without a tower split fails loudly, not silently wrong
    pred.model = WDL(emb_dim=8, capacity=1 << 12, hidden=(32,),
                     num_cat=4, num_dense=2)
    try:
        pred.predict({}, group_users=True)
        raise AssertionError("expected ValueError")
    except ValueError as e:
        assert "tower" in str(e)


def test_whitespace_prefixed_json_dispatch(tmp_path):
    """Whitespace-prefixed JSON must route to the JSON path even when the
    bytes happen to proto3-parse as a PredictRequest with no inputs
    (unknown fields are skipped, so 'parse succeeded' alone proves
    nothing — the dispatch requires actual inputs before taking the
    protobuf path)."""
    import json

    from deeprec_tpu.serving.cabi import process_request
    from deeprec_tpu.serving.predict_pb import PredictRequest

    model, tr, st, ck, batches, gen = make_trained(tmp_path)
    server = ModelServer(Predictor(model, str(tmp_path)), max_batch=64,
                         max_wait_ms=2)
    try:
        feats = {
            k: np.asarray(v)[:2].tolist()
            for k, v in strip_labels(batches[0]).items()
        }
        body = {"features": feats}

        for prefix in (b" ", b"\n", b"\t", b"\r\n", b"   "):
            payload = prefix + json.dumps(body).encode()
            code, out = process_request(server, payload)
            assert code == 200, (prefix, out)
            assert b"predictions" in out

        # Adversarial: pad the JSON until the bytes ALSO parse as a
        # proto3 PredictRequest with empty inputs — the exact case a
        # parse-failure-only fallback misses.
        crafted = None
        for pad in range(0, 512):
            payload = b" " + json.dumps(
                {"_pad": "x" * pad, "features": feats}
            ).encode()
            try:
                if not PredictRequest.parse(payload).inputs:
                    crafted = payload
                    break
            except Exception:
                continue
        if crafted is not None:
            code, out = process_request(server, crafted)
            assert code == 200 and b"predictions" in out, out
    finally:
        server.close()


def test_server_group_replicas_concurrent_and_rolling_update(tmp_path):
    """SessionGroup parity (direct_session_group.h:28): N replicas on N
    devices serve concurrently behind one request front and one
    checkpoint watcher; an update rolls across every replica."""
    import jax

    from deeprec_tpu.serving import ServerGroup

    model, tr, st, ck, batches, gen = make_trained(tmp_path)
    req = strip_labels(batches[0])
    expect = np.asarray(Predictor(model, str(tmp_path)).predict(req))

    assert len(jax.local_devices()) >= 2  # conftest forces 8 CPU devices
    group = ServerGroup(model, str(tmp_path), replicas=2, max_wait_ms=1.0)
    try:
        # replicas live on distinct devices
        devs = {
            next(iter(jax.tree.leaves(s.predictor._state))).devices().pop()
            for s in group.members
        }
        assert len(devs) == 2
        assert group.predictor.model_info()["replicas"] == 2

        # concurrent clients: all answers correct, both replicas exercised
        errs = []
        outs = [None] * 12

        def client(i):
            try:
                sl = {k: v[i * 8 : i * 8 + 8] for k, v in req.items()}
                outs[i] = np.asarray(group.request(sl))
            except Exception as e:  # surfaced below
                errs.append(e)

        threads = [threading.Thread(target=client, args=(i,)) for i in range(12)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errs, errs
        got = np.concatenate(outs[: 96 // 8])
        np.testing.assert_allclose(got, expect[:96], rtol=2e-5, atol=2e-5)

        # train on, save a newer checkpoint, poll once -> EVERY replica
        st2 = st
        for b in batches:
            st2, _ = tr.train_step(st2, b)
        ck.save(st2)
        assert group.predictor.poll_updates() is True
        steps = {s.predictor.step for s in group.members}
        assert steps == {int(st2.step)}, steps
    finally:
        group.close()
