"""Online-loop robustness: heartbeat leases, supervisor restart/wedge/
budget/EXIT_RESCALE semantics, TrainLoop save cadence surviving writer
faults, and the ServeLoop/poll-thread survivability contract.

Supervisor tests use tiny NON-jax child processes (sleep/exit scripts) so
restart choreography is pinned without paying interpreter+jax startup
per generation; the full jax worker subprocess path is exercised by the
slow-marked end-to-end test and tools/bench_freshness.py --smoke in CI."""
import json
import os
import sys
import threading
import time

import numpy as np
import pytest

from deeprec_tpu.online import faults
from deeprec_tpu.online.supervisor import Heartbeat, ProcessSpec, Supervisor
from deeprec_tpu.parallel.elastic import EXIT_RESCALE


# ------------------------------------------------------------- heartbeat


def test_heartbeat_roundtrip_and_age(tmp_path):
    hb = Heartbeat(str(tmp_path / "w.hb"))
    assert Heartbeat.read(hb.path) is None
    assert Heartbeat.age(hb.path) is None
    hb.beat(step=7, status="ok", custom=3)
    got = Heartbeat.read(hb.path)
    assert got["step"] == 7 and got["status"] == "ok" and got["custom"] == 3
    assert got["pid"] == os.getpid()
    assert Heartbeat.age(hb.path) < 5.0
    # stamp is atomic: no partial tempfile left behind
    assert [f for f in os.listdir(tmp_path)] == ["w.hb"]


def test_heartbeat_write_failure_does_not_raise(tmp_path):
    hb = Heartbeat(str(tmp_path / "sub" / "w.hb"))
    os.rmdir(str(tmp_path / "sub"))
    hb.beat(step=1)  # vanished dir: worker must not die for a heartbeat


# ------------------------------------------------------------ supervisor


def _wait(pred, timeout=30.0, poll=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = pred()
        if v:
            return v
        time.sleep(poll)
    return None


def _spec(name, code, tmp_path, **kw):
    kw.setdefault("lease_secs", None)
    kw.setdefault("backoff_base_secs", 0.05)
    kw.setdefault("backoff_max_secs", 0.2)
    return ProcessSpec(
        name=name, argv=[sys.executable, "-c", code],
        stdout=str(tmp_path / f"{name}.log"), **kw,
    )


def test_supervisor_restarts_killed_worker(tmp_path):
    sup = Supervisor(
        [_spec("w", "import time; time.sleep(600)", tmp_path,
               max_restarts=3)],
        poll_secs=0.05, on_event=lambda m: None,
    ).start()
    try:
        pid1 = _wait(lambda: sup.pid("w"))
        assert sup.kill("w")
        assert _wait(lambda: sup.stats()["w"]["restarts"] == 1)
        pid2 = _wait(lambda: sup.pid("w"))
        assert pid2 and pid2 != pid1
        assert sup.stats()["w"]["gave_up"] is False
    finally:
        sup.stop()


def test_supervisor_budget_exhausts_on_crash_loop(tmp_path):
    sup = Supervisor(
        [_spec("crash", "raise SystemExit(3)", tmp_path, max_restarts=2)],
        poll_secs=0.05, on_event=lambda m: None,
    ).start()
    try:
        assert _wait(lambda: sup.stats()["crash"]["gave_up"], timeout=30)
        st = sup.stats()["crash"]
        assert st["restarts"] == 2  # budget, then loud terminal state
        assert st["last_exit"] == 3
        assert st["alive"] is False
    finally:
        sup.stop()


def test_supervisor_honors_exit_rescale(tmp_path):
    """EXIT_RESCALE is a PLANNED exit: immediate respawn, no budget
    charge, and the on_rescale hook may swap argv for the next
    generation."""
    flag = str(tmp_path / "gen2")
    code = (
        f"import os, sys\n"
        f"if os.path.exists({flag!r}): raise SystemExit(0)\n"
        f"open({flag!r}, 'w').close()\n"
        f"raise SystemExit({EXIT_RESCALE})\n"
    )
    seen = []
    spec = _spec("el", code, tmp_path, max_restarts=1,
                 on_rescale=lambda s: seen.append(1) or None)
    sup = Supervisor([spec], poll_secs=0.05, on_event=lambda m: None).start()
    try:
        assert _wait(lambda: sup.stats()["el"]["done"], timeout=30)
        st = sup.stats()["el"]
        assert st["rescales"] == 1 and seen == [1]
        assert st["restarts"] == 0  # planned exits are free
        assert st["consecutive_failures"] == 0
    finally:
        sup.stop()


def test_supervisor_wedge_detection_kills_and_restarts(tmp_path):
    """A live process whose lease goes stale is WEDGED: SIGKILL + restart
    on budget. The child stamps one beat then hangs forever."""
    hb = str(tmp_path / "w.hb")
    code = (
        "import json, os, sys, time\n"
        f"p = {hb!r}\n"
        "json.dump({'pid': os.getpid(), 'time': time.time(), 'step': 1,"
        " 'status': 'ok'}, open(p + '.tmp', 'w'))\n"
        "os.replace(p + '.tmp', p)\n"
        "time.sleep(600)\n"
    )
    spec = _spec("wedge", code, tmp_path, heartbeat_path=hb,
                 lease_secs=0.4, grace_secs=0.2, max_restarts=1)
    sup = Supervisor([spec], poll_secs=0.05, on_event=lambda m: None).start()
    try:
        assert _wait(lambda: sup.stats()["wedge"]["wedge_kills"] >= 1,
                     timeout=30)
        st = sup.stats()["wedge"]
        assert st["last_exit"] is not None
    finally:
        sup.stop()


# ----------------------------------------------------- TrainLoop (jax)


def _mk_trainer():
    import optax

    from deeprec_tpu.models import WDL
    from deeprec_tpu.optim import Adagrad
    from deeprec_tpu.training import Trainer

    model = WDL(emb_dim=4, capacity=1 << 10, hidden=(16,), num_cat=2,
                num_dense=2)
    return Trainer(model, Adagrad(lr=0.2), optax.adam(5e-3)), model


def _batches(n_cat=2, n_dense=2, B=96, seed=0):
    from deeprec_tpu.data import SyntheticCriteo

    gen = SyntheticCriteo(batch_size=B, num_cat=n_cat, num_dense=n_dense,
                          vocab=300, seed=seed)
    while True:
        yield gen.batch()


def test_train_loop_cadence_and_heartbeat(tmp_path):
    from deeprec_tpu.online.loop import TrainLoop
    from deeprec_tpu.training.checkpoint import CheckpointManager

    tr, _ = _mk_trainer()
    ck = CheckpointManager(str(tmp_path / "ck"), tr)
    hb = Heartbeat(str(tmp_path / "t.hb"))
    loop = TrainLoop(tr, ck, _batches(), save_every=4, full_every=3,
                     heartbeat=hb, max_steps=16)
    state, code = loop.run()
    assert code == 0
    assert int(state.step) == 16
    dirs = sorted(d for d in os.listdir(tmp_path / "ck") if "-" in d)
    # anchor first, then deltas, full again every 3rd save
    assert "full-4" in dirs and "incr-8" in dirs and "full-12" in dirs
    beat = Heartbeat.read(hb.path)
    assert beat["step"] == 16 and beat["status"] == "done"
    assert beat["saves"] == loop.saves >= 4
    # a fresh consumer restores the final state (writer fully drained)
    restored = CheckpointManager(str(tmp_path / "ck"), _mk_trainer()[0]).restore()
    assert int(restored.step) == 16


def test_train_loop_survives_torn_writer_and_self_heals(tmp_path):
    """An async writer dying mid-save must not kill training OR the
    chain: the loop counts the failure, keeps stepping, and the manager's
    force-full escalation re-anchors on the next cadence save."""
    from deeprec_tpu.online.loop import TrainLoop
    from deeprec_tpu.training.checkpoint import CheckpointManager

    tr, _ = _mk_trainer()
    ck = CheckpointManager(str(tmp_path / "ck"), tr)
    loop = TrainLoop(tr, ck, _batches(), save_every=3, full_every=100,
                     max_steps=15)

    armed = {"at": 2}  # tear the writer on the 2nd save (first delta)

    def on_step(step):
        if loop.saves == armed["at"] - 1 and ck.on_write is None:
            faults.install_torn_write(ck)

    loop.on_step = on_step
    state, code = loop.run()
    assert code == 0 and int(state.step) == 15
    assert loop.save_failures >= 1
    # the torn dir is manifest-less (invisible); a later save re-anchored
    names = os.listdir(tmp_path / "ck")
    assert any(d.startswith("full-") and
               os.path.exists(tmp_path / "ck" / d / "manifest.json")
               for d in names)
    restored = CheckpointManager(str(tmp_path / "ck"), _mk_trainer()[0]).restore()
    assert int(restored.step) >= 6


def test_train_loop_rescale_contract(tmp_path):
    """A posted scaling plan makes the loop checkpoint, ack, and return
    EXIT_RESCALE — the supervisor's respawn signal."""
    from deeprec_tpu.online.loop import TrainLoop
    from deeprec_tpu.parallel.elastic import ElasticCoordinator
    from deeprec_tpu.training.checkpoint import CheckpointManager

    tr, _ = _mk_trainer()
    ck = CheckpointManager(str(tmp_path / "ck"), tr)
    coord = ElasticCoordinator(str(tmp_path / "el"))
    epoch = coord.request_scale(2)
    loop = TrainLoop(tr, ck, _batches(), save_every=100, heartbeat=None,
                     coordinator=coord, elastic_every=2, max_steps=50)
    state, code = loop.run()
    assert code == EXIT_RESCALE
    assert int(state.step) <= 4  # exited at the first elastic poll, not 50
    assert coord.acked(epoch, 1)
    restored = CheckpointManager(str(tmp_path / "ck"), _mk_trainer()[0]).restore()
    assert int(restored.step) == int(state.step)  # durable before ack


# --------------------------------------------- poll-thread survivability


def _build_serving_chain(tmp_path, steps=3):
    import jax.numpy as jnp

    from deeprec_tpu.training.checkpoint import CheckpointManager

    tr, model = _mk_trainer()
    ck = CheckpointManager(str(tmp_path / "ck"), tr)
    st = tr.init(0)
    gen = _batches(seed=4)
    for _ in range(steps):
        st = tr.train_step(
            st, {k: jnp.asarray(v) for k, v in next(gen).items()})[0]
    st, _ = ck.save(st)
    req = {k: v for k, v in next(gen).items() if k != "label"}
    return tr, model, ck, st, req, gen


def test_poll_thread_survives_raising_poll_and_recovers(tmp_path):
    """THE pinned bug: a poll_updates that raises (e.g. the checkpoint
    dir becomes unreadable mid-scan) must leave the background poll loop
    RUNNING and the old snapshot serving; when the fault clears, polling
    resumes and new deltas land. Before this round a single escaped
    exception killed the daemon thread silently and the model went
    permanently stale with no signal."""
    import jax.numpy as jnp

    from deeprec_tpu.serving.predictor import ModelServer, Predictor

    tr, model, ck, st, req, gen = _build_serving_chain(tmp_path)
    p = Predictor(model, str(tmp_path / "ck"))
    server = ModelServer(p, max_batch=32, poll_updates_secs=0.05)
    try:
        before = np.asarray(server.request(req))

        # wound the scan: every chain listing now raises
        real_list = p._ck._list

        def bad_list(kind):
            raise RuntimeError("injected: ckpt dir unreadable mid-scan")

        p._ck._list = bad_list
        assert _wait(lambda: p.consecutive_poll_failures >= 2, timeout=30)
        assert server._poller.is_alive()  # the daemon thread SURVIVED
        assert getattr(server, "update_failures", 0) >= 1
        assert p.health()["status"] == "degraded"
        # old snapshot still serves, bit-identically
        np.testing.assert_array_equal(before, np.asarray(server.request(req)))

        # heal the fault; a new delta must flow again through the SAME
        # poll thread (no restart involved)
        p._ck._list = real_list
        st2 = tr.train_step(
            st, {k: jnp.asarray(v) for k, v in next(gen).items()})[0]
        st2, _ = ck.save_incremental(st2)
        assert _wait(
            lambda: p.consecutive_poll_failures == 0
            and p.step == int(st2.step),
            timeout=30,
        )
        assert p.health()["status"] == "ok"
        assert server._poller.is_alive()
    finally:
        server.close()


def test_serve_loop_heartbeats_health_and_pause(tmp_path):
    from deeprec_tpu.online.loop import ServeLoop

    tr, model, ck, st, req, gen = _build_serving_chain(tmp_path)
    hb = str(tmp_path / "s.hb")
    sl = ServeLoop(model, str(tmp_path / "ck"), poll_secs=0.05,
                   heartbeat=Heartbeat(hb))
    try:
        out, ver = sl.request_versioned(req)
        assert np.asarray(out).shape[0] == 96
        beat = _wait(lambda: Heartbeat.read(hb), timeout=30)
        assert beat["status"] == "ok"
        assert "staleness_seconds" in beat and "quarantined" in beat

        # pause gates the poller: a new delta stays un-applied until resume
        import jax.numpy as jnp

        sl.pause()
        time.sleep(0.2)
        v0 = sl.predictor.version
        st2 = tr.train_step(
            st, {k: jnp.asarray(v) for k, v in next(gen).items()})[0]
        st2, _ = ck.save_incremental(st2)
        time.sleep(0.3)
        assert sl.predictor.version == v0
        sl.resume()
        assert _wait(lambda: sl.predictor.version > v0, timeout=30)
        assert sl.health()["step"] == int(st2.step)
    finally:
        sl.close()


# -------------------------------------------------- launcher integration


def test_trainloop_picks_up_heartbeat_env(tmp_path, monkeypatch):
    """The supervise_worker contract: a worker spawned with
    DEEPREC_HEARTBEAT_FILE set stamps that lease even when no Heartbeat
    was threaded through explicitly — otherwise the supervisor kills a
    healthy worker as wedged."""
    from deeprec_tpu.online.loop import TrainLoop

    hb = str(tmp_path / "w.hb")
    monkeypatch.setenv("DEEPREC_HEARTBEAT_FILE", hb)

    class _Ck:
        def latest_full(self):
            return None

    loop = TrainLoop(trainer=None, ckpt=_Ck(), batches=[])
    assert loop.heartbeat is not None and loop.heartbeat.path == hb
    loop._beat(3)
    assert Heartbeat.read(hb)["step"] == 3
    # An explicit Heartbeat still wins over the env var.
    other = Heartbeat(str(tmp_path / "explicit.hb"))
    assert TrainLoop(trainer=None, ckpt=_Ck(), batches=[],
                     heartbeat=other).heartbeat is other


def test_launch_supervise_worker_restarts_then_completes(tmp_path):
    """`python -m deeprec_tpu.launch --supervised`: a worker that crashes
    once is restarted and the clean second run ends the job with rc 0.
    Non-jax script (flag-file state machine) so this stays in tier-1."""
    from deeprec_tpu.launch import supervise_worker

    flag = str(tmp_path / "ran")
    script = str(tmp_path / "w.py")
    with open(script, "w") as f:
        f.write(
            "import os, sys\n"
            f"flag = {flag!r}\n"
            "if os.path.exists(flag): raise SystemExit(0)\n"
            "open(flag, 'w').close()\n"
            "raise SystemExit(7)\n"
        )
    rc = supervise_worker(script, [], heartbeat=None, max_restarts=3)
    assert rc == 0
    assert os.path.exists(flag)


# --------------------------------------------------- worker end-to-end


@pytest.mark.slow
def test_worker_subprocess_kill_resume_via_supervisor(tmp_path):
    """Full supervised generation cycle with the real jax worker: kill -9
    mid-run (via the deterministic env injector), supervisor restarts,
    worker RESUMEs from the chain and completes."""
    ck = str(tmp_path / "ck")
    hb = str(tmp_path / "t.hb")
    argv = [sys.executable, "-m", "deeprec_tpu.online.loop", "--ckpt", ck,
            "--steps", "24", "--save-every", "5", "--heartbeat", hb,
            "--batch-size", "96"]
    env = {"PYTHONPATH": os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "JAX_PLATFORMS": "cpu",
        faults.KILL_STEP_ENV: "12"}
    spec = ProcessSpec(
        name="trainer", argv=argv, heartbeat_path=hb, lease_secs=60,
        grace_secs=120, max_restarts=3, backoff_base_secs=0.2,
        env=env, stdout=str(tmp_path / "trainer.log"),
        # the restarted generation must NOT re-arm the kill
        on_rescale=None,
    )
    # Drop the kill env for respawns by mutating argv factory instead:
    spec.env = dict(env)
    sup = Supervisor([spec], poll_secs=0.2, on_event=lambda m: None)
    # first generation dies at step 12; scrub the injector before respawn
    orig_spawn = sup._spawn

    def spawn(s):
        orig_spawn(s)
        s.env.pop(faults.KILL_STEP_ENV, None)

    sup._spawn = spawn
    sup.start()
    try:
        assert _wait(lambda: sup.stats()["trainer"]["done"], timeout=300)
        st = sup.stats()["trainer"]
        assert st["restarts"] == 1
        log = open(tmp_path / "trainer.log").read().splitlines()
        assert any(l.startswith("RESUMED") for l in log)
        assert log[-1] == "DONE"
        from deeprec_tpu.training.checkpoint import CheckpointManager

        restored = CheckpointManager(ck, _mk_trainer()[0]).restore()
        assert int(restored.step) == 24
    finally:
        sup.stop()
