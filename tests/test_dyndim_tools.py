"""Dynamic-dimension embeddings + checkpoint shrink tool."""
import os
import subprocess
import sys

import jax.numpy as jnp
import numpy as np
import optax

from deeprec_tpu import EmbeddingTable, TableConfig
from deeprec_tpu.embedding.compose import DynamicDimEmbedding


def test_dynamic_dim_masks_by_frequency():
    t = EmbeddingTable(TableConfig(name="dd", dim=16, capacity=256))
    dd = DynamicDimEmbedding(t, dim_tiers=(4, 8, 16), freq_tiers=(3, 6))
    s = t.create()
    hot, cold = jnp.array([1], jnp.int32), jnp.array([2], jnp.int32)
    for i in range(7):
        s, _ = dd.lookup_unique(s, hot, step=i)
    s, res = dd.lookup_unique(s, jnp.array([1, 2], jnp.int32), step=8)
    by_id = {int(u): i for i, u in enumerate(np.asarray(res.uids))}
    e_hot = np.asarray(res.embeddings)[by_id[1]]
    e_cold = np.asarray(res.embeddings)[by_id[2]]
    assert np.abs(e_hot[8:]).max() > 0  # freq 8 >= 6 -> full 16 dims
    assert np.abs(e_cold[:4]).max() > 0  # fresh key: first tier active
    np.testing.assert_allclose(e_cold[4:], 0.0)  # tail masked


def test_shrink_ckpt_routes_by_name_not_shape(tmp_path):
    """A per-table array (bloom sketch) whose length coincidentally equals
    the row count must pass through unfiltered — routing is by NAME via
    checkpoint.is_per_row, never by shape."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "shrink_ckpt",
        os.path.join(os.path.dirname(__file__), "..", "tools", "shrink_ckpt.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    n = 4
    src = str(tmp_path / "table_t.npz")
    dst = str(tmp_path / "out.npz")
    np.savez(
        src,
        keys=np.arange(n, dtype=np.int64),
        values=np.ones((n, 2), np.float32),
        freqs=np.array([1, 5, 5, 5], np.int32),
        versions=np.zeros(n, np.int32),
        bloom=np.arange(n, dtype=np.int32),  # length == n by coincidence
        **{"slot:accum": np.full((n, 2), 0.1, np.float32)},
    )
    before, after, _ = mod.shrink_table(src, dst, min_freq=3, min_version=0)
    assert (before, after) == (4, 3)
    d = dict(np.load(dst))
    assert d["keys"].shape[0] == 3
    assert d["slot:accum"].shape[0] == 3
    np.testing.assert_array_equal(d["bloom"], np.arange(n))  # untouched


def test_shrink_ckpt_tool(tmp_path):
    import optax

    from deeprec_tpu.data import SyntheticCriteo
    from deeprec_tpu.models import WDL
    from deeprec_tpu.optim import Adagrad
    from deeprec_tpu.training import Trainer
    from deeprec_tpu.training.checkpoint import CheckpointManager

    model = WDL(emb_dim=8, capacity=1 << 12, hidden=(16,), num_cat=3, num_dense=2)
    tr = Trainer(model, Adagrad(lr=0.1), optax.adam(1e-3))
    st = tr.init(0)
    gen = SyntheticCriteo(batch_size=256, num_cat=3, num_dense=2, vocab=800, seed=1)
    for _ in range(3):
        st, _ = tr.train_step(st, {k: jnp.asarray(v) for k, v in gen.batch().items()})
    st, path = CheckpointManager(str(tmp_path), tr).save(st)

    r = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(__file__), "..",
                                      "tools", "shrink_ckpt.py"),
         path, "--min_freq", "3"],
        capture_output=True, text=True,
        env={**os.environ, "PYTHONPATH": "", "JAX_PLATFORMS": "cpu"},
    )
    assert r.returncode == 0, r.stderr
    out_dir = path.rstrip("/") + "-shrunk"
    # shrunk tables are strict subsets and still load
    import glob

    for f in glob.glob(os.path.join(out_dir, "table_*.npz")):
        d = dict(np.load(f))
        assert (d["freqs"] >= 3).all()
        orig = dict(np.load(os.path.join(path, os.path.basename(f))))
        assert d["keys"].shape[0] <= orig["keys"].shape[0]
