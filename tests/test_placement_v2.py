"""Placement v2 (parallel/placement.py + costmodel.py, sharded a2a
budgets): plan-aware per-destination exchange budgets, drift-driven
online replanning with migration amortization, and the learned cost
model's bit-identical fallback.

Contracts pinned here:
  * the per-dest a2a budget vector reproduces the legacy slack·U/N
    bucket bit-for-bit without a plan, and under a hot-key plan compiles
    a bucket STRICTLY tighter than the v1 global-headroom model — with
    zero overflow on the workload the plan was built for;
  * an unskewed stream never triggers the replanner (no thrash) and the
    plan trainer stays bit-identical to uniform;
  * a drift-triggered (automatic, non-forced) replan mid-stream leaves
    per-step losses bit-identical to a never-replanning uniform trainer
    across allgather + a2a, the K-step scan and the pipelined lookahead;
  * update_placement defers when modeled gain cannot amortize modeled
    migration bytes within the horizon, and adopts when it can;
  * checkpoints round-trip across a drift-triggered plan change;
  * build_plans(cost_model=) is bit-identical with an untrained model
    and re-ranks only analytic ties once trained.
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from deeprec_tpu.data import SyntheticCriteo
from deeprec_tpu.models import WDL
from deeprec_tpu.ops import traffic as T
from deeprec_tpu.optim import Adagrad
from deeprec_tpu.parallel import ShardedTrainer, make_mesh, shard_batch
from deeprec_tpu.parallel import placement as P
from deeprec_tpu.parallel.costmodel import PlacementCostModel


def J(b):
    return {k: jnp.asarray(v) for k, v in b.items()}


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= 8
    return make_mesh(8)


def model(capacity=1 << 12):
    return WDL(emb_dim=8, capacity=capacity, hidden=(16,), num_cat=4,
               num_dense=2)


def drifting_batches(n, rotate_every=None, batch_size=256, seed=7):
    """Shared-raw-id-space skewed stream whose hot set rotates every
    `rotate_every` batches — the Placement-v2 workload."""
    gen = SyntheticCriteo(
        batch_size=batch_size, num_cat=4, num_dense=2, vocab=3000,
        seed=seed, zipf_a=[1.6, 1.9, 2.2, 2.5], offset_ids=False,
        zipf_rotate_every=rotate_every,
    )
    return [J(gen.batch()) for _ in range(n)]


# --------------------------------------------------------- budget vector


def test_dest_budget_vector_uniform_parity_and_diet():
    """No plan -> the legacy slack·U/N bucket bit-for-bit; a hot-key plan
    subtracts the explicitly-routed keys from the tail share and charges
    each destination its own concentration — the bucket (vector max)
    lands strictly below the v1 global-headroom bucket."""
    import math

    for U in (16, 64, 250, 1024):
        b = T.a2a_dest_budgets(unique=U, num_shards=8, slack=2.0)
        legacy = max(8, ((math.ceil(U * 2.0 / 8) + 7) // 8) * 8)
        assert list(b) == [legacy] * 8
        assert T.a2a_bucket_rows(unique=U, num_shards=8) == legacy
        assert T.a2a_bucket_rows_global(unique=U, num_shards=8) == legacy

    U = 256
    hot = np.array([20, 12, 8, 16, 18, 10, 6, 10])
    bp = T.a2a_dest_budgets(
        unique=U, num_shards=8, slack=2.0, dest_hot=hot,
        hot_count=int(hot.sum()),
    )
    bucket = int(bp.max())
    global_bucket = T.a2a_bucket_rows_global(
        unique=U, num_shards=8, slack=2.0, hot_max=int(hot.max())
    )
    assert bucket < global_bucket
    # per-dest: each budget covers its own tail share + own hot count;
    # the tail subtraction caps at U/4 (the drift-safety margin — a
    # fully-rotated all-tail stream still gets 1.5x its expected
    # per-dest spread at slack=2)
    tail = math.ceil((U - min(int(hot.sum()), U // 4)) * 2.0 / 8)
    for d in range(8):
        assert bp[d] >= tail + hot[d]
        assert bp[d] % 8 == 0 and bp[d] >= 8
    # modeled wire at the vector max is strictly below the global model
    w_plan = T.a2a_exchange_wire_bytes(bucket_rows=bucket, num_shards=8,
                                       dim=16)
    w_global = T.a2a_exchange_wire_bytes(bucket_rows=global_bucket,
                                         num_shards=8, dim=16)
    assert w_plan < w_global
    with pytest.raises(ValueError):
        T.a2a_dest_budgets(unique=64, num_shards=8, dest_hot=[1, 2])


# --------------------------------------------------------- drift detector


def test_drift_detector_hysteresis_cooldown_and_projection():
    cfg = P.ReplanConfig(threshold=1.5, sustain=2, cooldown=2,
                         lead_secs=10.0)
    d = P.DriftDetector(cfg)
    # below threshold: never fires; a non-breach resets the run
    assert [d.observe(1.0), d.observe(1.6), d.observe(1.0),
            d.observe(1.6), d.observe(1.0)] == [False] * 5
    # sustained breach fires exactly at `sustain`
    assert d.observe(1.7) is False
    assert d.observe(1.7) is True
    # adoption starts the cooldown: quiet even while breaching
    d.adopted()
    assert [d.observe(1.8), d.observe(1.8)] == [False, False]
    assert d.observe(1.8) is True  # cooldown over, sustain re-reached
    # deferred(): re-arms without cooldown — needs another sustain run
    d.deferred()
    assert d.observe(1.8) is False
    assert d.observe(1.8) is True
    # slope projection breaches EARLY: level below threshold, but the
    # windowed slope projects it across within lead_secs
    d2 = P.DriftDetector(cfg)
    assert d2.observe(1.3, slope=0.05) is False  # 1.3 + 0.5 = 1.8 >= 1.5
    assert d2.observe(1.3, slope=0.05) is True
    # negative slope never projects
    d3 = P.DriftDetector(cfg)
    assert d3.observe(1.4, slope=-1.0) is False
    assert d3.last["projected"] == 1.4


def test_plan_moved_rows_matches_owner_diff():
    rng = np.random.default_rng(0)
    keys = rng.choice(1 << 20, 300, replace=False).astype(np.int32)
    m = P.MemberTraffic(bundle="b", member=0, keys=keys,
                        weight=np.ones(300), row_bytes=64.0, sentinel=-1)
    cand = {("b", 0): P.ShardPlan(num_shards=8, sentinel=-1, offset=3)}
    moved = P.plan_moved_rows([m], None, cand)
    # offset 3 moves every key off its hash home
    assert moved[("b", 0)] == 300
    same = {("b", 0): P.ShardPlan(num_shards=8, sentinel=-1)}
    assert P.plan_moved_rows([m], None, same)[("b", 0)] == 0
    assert P.plan_moved_rows([m], cand, cand)[("b", 0)] == 0


# ------------------------------------------------------------ cost model


def _tie_members(seed=1):
    """Two members whose second table's rotation costs tie analytically:
    a uniform-load first table makes every rotation of the second
    equivalent to the analytic model."""
    rng = np.random.default_rng(seed)
    ms = []
    for t in range(2):
        keys = (np.arange(256) + t * 4096).astype(np.int32)
        w = np.ones(256)
        ms.append(P.MemberTraffic(
            bundle=f"b{t}", member=0, keys=keys, weight=w,
            row_bytes=64.0, sentinel=-1,
        ))
    return ms


def test_cost_model_untrained_is_bit_identical():
    members = _tie_members()
    plain, rep_a = P.build_plans(8, members, hot_budget=4)
    with_model, rep_b = P.build_plans(
        8, members, hot_budget=4, cost_model=PlacementCostModel()
    )
    assert plain == with_model
    assert rep_a == rep_b


def test_cost_model_breaks_analytic_ties_once_trained():
    """Train the model on history where measured loads systematically
    exceed modeled on one shard: among analytically-tied rotations it
    must pick one avoiding that shard's hash bucket for the heavy load;
    and its choice must differ from (or justify) the analytic winner
    deterministically."""
    members = _tie_members()
    m = PlacementCostModel(min_rows=16)
    stats = m.member_stats(members[0])
    rng = np.random.default_rng(0)
    for _ in range(8):
        modeled = rng.random(8) * 1000
        measured = modeled.copy()
        measured[3] = modeled[3] * 3.0 + 500  # shard 3 runs hot
        m.record_window(stats, modeled, measured)
    assert m.trained
    # prediction is calibrated per shard: shard-3 loads inflate
    pred = m.predict_loads(stats, np.full(8, 100.0))
    assert pred.shape == (8,)
    plans_plain, _ = P.build_plans(8, members, hot_budget=0)
    plans_model, _ = P.build_plans(8, members, hot_budget=0, cost_model=m)
    # both are valid plan sets over the same members; determinism:
    assert plans_model == P.build_plans(8, members, hot_budget=0,
                                        cost_model=m)[0]
    assert set(plans_model) == set(plans_plain)


def test_cost_model_record_rejects_shape_mismatch_and_empty_windows():
    m = PlacementCostModel()
    stats = {"row_bytes": 64.0, "mass": 10.0, "unique_fraction": 0.5,
             "hot_mass": 0.1}
    with pytest.raises(ValueError):
        m.record_window(stats, np.ones(8), np.ones(4))
    m.record_window(stats, np.ones(8), np.zeros(8))  # empty: skipped
    assert m.info()["rows"] == 0 and not m.trained


# ------------------------------------------------------- synthetic drift


def test_zipf_rotation_off_is_stream_identical_and_on_is_deterministic():
    mk = lambda **kw: SyntheticCriteo(  # noqa: E731
        batch_size=64, num_cat=3, num_dense=2, vocab=997, seed=11, **kw
    )
    legacy, off, on1, on2 = (
        mk(), mk(zipf_rotate_every=None), mk(zipf_rotate_every=3),
        mk(zipf_rotate_every=3),
    )
    for i in range(7):
        bl, bo = legacy.batch(), off.batch()
        b1, b2 = on1.batch(), on2.batch()
        for k in bl:
            np.testing.assert_array_equal(bl[k], bo[k])  # off == legacy
            np.testing.assert_array_equal(b1[k], b2[k])  # deterministic
        if on1.rotation_at(i) == 0:
            for k in bl:  # pre-rotation: identical to the legacy stream
                np.testing.assert_array_equal(bl[k], b1[k])
    assert on1.rotation_at(2) == 0 and on1.rotation_at(3) == 1
    # the rotation MOVES the head: hot ids of rotation 0 and 1 differ
    def head(batch):
        vals, counts = np.unique(batch["C1"], return_counts=True)
        return set(vals[np.argsort(-counts)][:5].tolist())

    g = mk(zipf_rotate_every=1, zipf_a=2.5)
    head0, head1 = head(g.batch()), head(g.batch())
    assert head0 != head1
    with pytest.raises(ValueError):
        mk(zipf_rotate_every=0)


# ------------------------------------------- mesh: budgets + no-thrash


def test_unskewed_stream_never_replans_and_matches_uniform(mesh):
    """Balanced traffic: the drift trigger stays quiet (no thrash), the
    plan trainer keeps uniform routing, the compiled a2a bucket equals
    the legacy budget, and losses match the uniform trainer bit-exactly."""
    gen = SyntheticCriteo(batch_size=512, num_cat=4, num_dense=2,
                          vocab=50_000, seed=5, zipf_a=1.0)
    batches = [J(gen.batch()) for _ in range(4)]
    sb = [shard_batch(mesh, b) for b in batches]
    mk = lambda placement: ShardedTrainer(  # noqa: E731
        model(), Adagrad(lr=0.1), optax.sgd(0.01), mesh=mesh, comm="a2a",
        placement=placement,
        replan=P.ReplanConfig(threshold=1.5, sustain=1, cooldown=0),
    )
    tr_u, tr_p = mk("uniform"), mk("plan")
    s_u, s_p = tr_u.init(0), tr_p.init(0)
    for i in range(2):
        s_u, m_u = tr_u.train_step(s_u, sb[i])
        s_p, m_p = tr_p.train_step(s_p, sb[i])
        assert float(m_u["loss"]) == float(m_p["loss"])
    s_p, rep = tr_p.maintain(s_p)
    s_u, _ = tr_u.maintain(s_u)
    assert tr_p._replan_stats["replans"] == 0
    assert all(p.is_uniform for p in tr_p._plans.values()) or not tr_p._plans
    for name, sh in tr_p.sharded.items():
        assert sh.plan_dest_hot is None and sh.plan_hot_count == 0
        # no plan -> the per-dest vector degenerates to ONE legacy
        # budget on every destination (uniform bit-parity)
        assert len(set(np.asarray(sh.last_a2a_budgets).tolist())) == 1
        assert sh.last_a2a_bucket == int(sh.last_a2a_budgets[0])
    for i in range(2, 4):
        s_u, m_u = tr_u.train_step(s_u, sb[i])
        s_p, m_p = tr_p.train_step(s_p, sb[i])
        assert float(m_u["loss"]) == float(m_p["loss"])


def test_tight_budget_zero_overflow_and_strict_diet(mesh):
    """Force a hot-key plan on the skewed stream: the compiled bucket
    must land strictly below the v1 global-headroom bucket, serve the
    stream with ZERO a2a overflow, and keep loss parity with uniform."""
    batches = drifting_batches(6, rotate_every=None)
    sb = [shard_batch(mesh, b) for b in batches]
    mk = lambda placement: ShardedTrainer(  # noqa: E731
        model(), Adagrad(lr=0.1), optax.sgd(0.01), mesh=mesh, comm="a2a",
        placement=placement, placement_hot_budget=48,
    )
    tr_u, tr_p = mk("uniform"), mk("plan")
    s_u, s_p = tr_u.init(0), tr_p.init(0)
    for i in range(3):
        s_u, m_u = tr_u.train_step(s_u, sb[i])
        s_p, m_p = tr_p.train_step(s_p, sb[i])
        assert float(m_u["loss"]) == float(m_p["loss"])
    s_p, rep = tr_p.update_placement(s_p, force=True)
    assert any(r.get("adopted") for r in rep.values()), rep
    (bname, sh), = tr_p.sharded.items()
    assert sh.plan_dest_hot is not None and sh.plan_dest_hot.sum() > 0
    for i in range(3, 6):
        s_u, m_u = tr_u.train_step(s_u, sb[i])
        s_p, m_p = tr_p.train_step(s_p, sb[i])
        assert float(m_u["loss"]) == float(m_p["loss"])
    # the adopted-plan trace recorded its bucket: never above the v1
    # global-headroom bucket (STRICT improvement is shape-dependent —
    # the tail diet must clear the 8-row rounding; the pure-unit test
    # above and the bench drift arm pin the strict case)
    bp = tr_p._plans[bname]
    hot_max = int(bp.dest_hot_counts().max())
    U = _bucket_unique_from_budgets(sh)
    global_bucket = T.a2a_bucket_rows_global(
        unique=U, num_shards=8, slack=sh.a2a_slack, hot_max=hot_max,
    )
    assert sh.last_a2a_bucket <= global_bucket, (
        f"bucket {sh.last_a2a_bucket} > global {global_bucket}"
    )
    # measured == modeled: the trace's bucket is the model's vector max
    np.testing.assert_array_equal(
        sh.last_a2a_budgets,
        T.a2a_dest_budgets(unique=U, num_shards=8, slack=sh.a2a_slack,
                           dest_hot=sh.plan_dest_hot,
                           hot_count=sh.plan_hot_count),
    )
    # zero overflow under the tight budget
    ovf = sum(
        int(np.sum(np.asarray(jax.device_get(ts.a2a_overflow))))
        for ts in s_p.tables.values()
    )
    assert ovf == 0


def _bucket_unique_from_budgets(sh):
    """Recover the trace-time U from the recorded budget vector (tail =
    budget minus the known hot term on the least-hot destination)."""
    dest_hot = (
        np.zeros(sh.num_shards, np.int64) if sh.plan_dest_hot is None
        else np.asarray(sh.plan_dest_hot)
    )
    for U in range(1, 1 << 14):
        b = T.a2a_dest_budgets(unique=U, num_shards=sh.num_shards,
                               slack=sh.a2a_slack, dest_hot=dest_hot,
                               hot_count=sh.plan_hot_count)
        if np.array_equal(b, np.asarray(sh.last_a2a_budgets)):
            return U
    raise AssertionError("no U reproduces the recorded budget vector")


# ------------------------------------------------ mesh: drift replan


def _drift_cfg():
    return P.ReplanConfig(threshold=1.25, sustain=1, cooldown=0,
                          horizon_steps=100_000)


def _run_drift_parity(mesh, comm, pipeline_mode, n_windows=4,
                      steps_per_window=2):
    """Plan trainer with the automatic replanner vs a never-replanning
    uniform trainer on the SAME drifting stream: per-step losses must be
    bit-identical (placement moves rows, never math), and at least one
    AUTOMATIC (non-forced) replan must fire after the hot set rotates."""
    total = n_windows * steps_per_window
    batches = drifting_batches(total, rotate_every=total // 2)
    sb = [shard_batch(mesh, b) for b in batches]
    mk = lambda placement: ShardedTrainer(  # noqa: E731
        model(), Adagrad(lr=0.1), optax.sgd(0.01), mesh=mesh, comm=comm,
        placement=placement, placement_hot_budget=32,
        pipeline_mode=pipeline_mode, replan=_drift_cfg(),
    )
    tr_u, tr_p = mk("uniform"), mk("plan")
    s_u, s_p = tr_u.init(0), tr_p.init(0)
    i = 0
    for w in range(n_windows):
        for _ in range(steps_per_window):
            s_u, m_u = tr_u.train_step(s_u, sb[i])
            s_p, m_p = tr_p.train_step(s_p, sb[i])
            assert float(m_u["loss"]) == float(m_p["loss"]), f"step {i}"
            i += 1
        s_p, _ = tr_p.maintain(s_p)
        s_u, _ = tr_u.maintain(s_u)
    assert tr_p._replan_stats["replans"] >= 1
    assert tr_p._replan_stats["forced_replans"] == 0
    return tr_u, s_u, tr_p, s_p


def test_replan_under_drift_loss_parity_allgather_and_scan(mesh):
    from deeprec_tpu.training import stack_batches

    tr_u, s_u, tr_p, s_p = _run_drift_parity(mesh, "allgather", "off")
    # K-step scan AFTER the drift-triggered adoption
    extra = drifting_batches(3, rotate_every=1, seed=9)
    stacked = shard_batch(mesh, stack_batches(extra), stacked=True)
    s_u, m_u = tr_u.train_steps(s_u, stacked)
    s_p, m_p = tr_p.train_steps(s_p, stacked)
    np.testing.assert_array_equal(np.asarray(m_u["loss"]),
                                  np.asarray(m_p["loss"]))


def test_replan_under_drift_loss_parity_a2a_lookahead(mesh):
    from deeprec_tpu.training import stack_batches

    tr_u, s_u, tr_p, s_p = _run_drift_parity(mesh, "a2a", "lookahead")
    extra = drifting_batches(3, rotate_every=1, seed=9)
    stacked = shard_batch(mesh, stack_batches(extra), stacked=True)
    s_u, m_u = tr_u.train_steps(s_u, stacked)
    s_p, m_p = tr_p.train_steps(s_p, stacked)
    np.testing.assert_array_equal(np.asarray(m_u["loss"]),
                                  np.asarray(m_p["loss"]))
    # obs wiring: the automatic replan is visible on the process registry
    from deeprec_tpu.obs import metrics as M

    if M.metrics_enabled():
        snap = M.default_registry().snapshot()["metrics"]
        reps = {
            tuple(sorted(s["labels"].items())): s["value"]
            for s in snap["deeprec_placement_replans"]["series"]
        }
        assert reps.get((("trigger", "auto"),), 0) >= 1
        mig = snap["deeprec_placement_migration_bytes"]["series"][0]
        assert mig["value"] > 0
        assert snap["deeprec_placement_modeled_gain"]["series"][0][
            "value"] is not None
    pl = tr_p.dedup_stats(s_p)["__placement__"]
    assert pl["replans"] >= 1 and pl["migration_bytes"] > 0
    assert "cost_model" in pl and "drift" in pl


# -------------------------------------------------- mesh: amortization


def test_amortization_defers_below_horizon_and_adopts_above(mesh):
    batches = drifting_batches(5)
    sb = [shard_batch(mesh, b) for b in batches]
    tr = ShardedTrainer(
        model(), Adagrad(lr=0.1), optax.sgd(0.01), mesh=mesh,
        placement="plan", placement_hot_budget=16,
    )
    st = tr.init(0)
    for b in sb[:3]:
        st, _ = tr.train_step(st, b)
    # horizon 0: NO gain/step stream can ever repay a nonzero migration
    st, rep = tr.update_placement(st, horizon_steps=0)
    assert all(r.get("deferred") == "amortization" for r in rep.values())
    assert tr._replan_stats["replans"] == 0
    assert tr.last_placement["migration_bytes"] > 0
    assert tr.last_placement["gain_bytes_per_step"] > 0
    assert tr.last_placement["amortize_steps"] >= 1
    amortize = tr.last_placement["amortize_steps"]
    # a window later (the placer snapshots freqs per run — the next run
    # models the NEW window), a horizon past break-even adopts
    # (automatic, non-forced)
    for b in sb[3:]:
        st, _ = tr.train_step(st, b)
    st, rep = tr.update_placement(st, horizon_steps=amortize * 4 + 4)
    assert any(r.get("adopted") for r in rep.values()), rep
    assert tr._replan_stats["replans"] == 1
    assert tr._replan_stats["forced_replans"] == 0


# ----------------------------------------------- mesh: ckpt round-trip


def _table_maps(tr, state):
    """(bundle, member, key) -> per-row bytes, wherever the row lives
    (trimmed copy of tests/test_placement.py's placement-invariant view)."""
    from deeprec_tpu.embedding.table import empty_key
    from deeprec_tpu.ops.packed import unpack_array
    from deeprec_tpu.optim.sparse import SCALAR_PREFIX

    out = {}
    for bname, b in tr.bundles.items():
        ts = state.tables[bname]
        sent = empty_key(b.table.cfg)
        keys = np.asarray(jax.device_get(ts.keys))
        meta = np.asarray(jax.device_get(ts.meta))
        C = keys.shape[-1]
        vals = np.asarray(jax.device_get(ts.values))
        slots = {
            k: np.asarray(jax.device_get(v))
            for k, v in ts.slots.items() if not k.startswith(SCALAR_PREFIX)
        }
        for idx in np.ndindex(*keys.shape[:-1]):
            m = idx[0] if len(idx) == 2 else 0
            k_loc = keys[idx]
            v_loc = unpack_array(vals[idx], C)
            s_loc = [unpack_array(sl[idx], C) for sl in slots.values()]
            for s in np.nonzero(k_loc != sent)[0]:
                out[(bname, m, int(k_loc[s]))] = (
                    v_loc[s].tobytes(), meta[idx][:, s].tobytes(),
                    tuple(sl[s].tobytes() for sl in s_loc),
                )
    return out


def test_checkpoint_roundtrip_across_drift_triggered_replan(mesh, tmp_path):
    """Train through a drift-TRIGGERED (maintain-path, non-forced) plan
    change, save, restore into a uniform-routing trainer: rows land where
    the restoring plan looks for them and training continues bit-exactly."""
    from deeprec_tpu.training.checkpoint import CheckpointManager

    tr_u, s_u, tr_p, s_p = _run_drift_parity(
        mesh, "allgather", "off", n_windows=3, steps_per_window=2
    )
    ck = CheckpointManager(str(tmp_path / "ck"), tr_p)
    s_p, _ = ck.save(s_p)
    tr_c = ShardedTrainer(
        model(), Adagrad(lr=0.1), optax.sgd(0.01), mesh=mesh,
        placement="uniform",
    )
    r_c = CheckpointManager(str(tmp_path / "ck"), tr_c).restore()
    ma, mb = _table_maps(tr_p, s_p), _table_maps(tr_c, r_c)
    assert set(ma) == set(mb)
    assert all(ma[k] == mb[k] for k in ma)
    nxt = shard_batch(mesh, drifting_batches(1, rotate_every=1, seed=3)[0])
    s_p, m_p = tr_p.train_step(s_p, nxt)
    r_c, m_c = tr_c.train_step(r_c, nxt)
    assert float(m_p["loss"]) == float(m_c["loss"])
    mc, md = _table_maps(tr_p, s_p), _table_maps(tr_c, r_c)
    assert set(mc) == set(md) and all(mc[k] == md[k] for k in mc)
