"""All2all (SOK-style) exchange path must match the exact allgather path."""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from deeprec_tpu.data import SyntheticCriteo
from deeprec_tpu.models import WDL
from deeprec_tpu.optim import Adagrad, GradientDescent
from deeprec_tpu.parallel import ShardedTrainer, make_mesh, shard_batch
from deeprec_tpu.training import Trainer


def J(b):
    return {k: jnp.asarray(v) for k, v in b.items()}


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(8)


def small():
    return WDL(emb_dim=8, capacity=1 << 13, hidden=(32,), num_cat=4, num_dense=2)


def test_a2a_matches_allgather_and_local(mesh):
    gen = SyntheticCriteo(batch_size=256, num_cat=4, num_dense=2, vocab=3000, seed=11)
    batches = [J(gen.batch()) for _ in range(4)]

    t_local = Trainer(small(), GradientDescent(lr=0.1), optax.sgd(0.01))
    s_local = t_local.init(0)
    t_ag = ShardedTrainer(small(), GradientDescent(lr=0.1), optax.sgd(0.01),
                          mesh=mesh, comm="allgather")
    s_ag = t_ag.init(0)
    t_a2a = ShardedTrainer(small(), GradientDescent(lr=0.1), optax.sgd(0.01),
                           mesh=mesh, comm="a2a")
    s_a2a = t_a2a.init(0)

    for b in batches:
        s_local, ml = t_local.train_step(s_local, b)
        sb = shard_batch(mesh, b)
        s_ag, mag = t_ag.train_step(s_ag, sb)
        s_a2a, ma2a = t_a2a.train_step(s_a2a, sb)
        # a2a vs allgather: identical routing math, tiny fp-order differences
        np.testing.assert_allclose(
            float(mag["loss"]), float(ma2a["loss"]), rtol=1e-4
        )
        np.testing.assert_allclose(
            float(ml["loss"]), float(ma2a["loss"]), rtol=2e-2
        )


def test_a2a_learns_with_skewed_ids(mesh):
    """Zipf-skewed ids stress the per-destination budget; training must stay
    healthy and overflow must be (near) zero at slack=2."""
    model = small()
    tr = ShardedTrainer(model, Adagrad(lr=0.2), optax.adam(5e-3), mesh=mesh,
                        comm="a2a")
    st = tr.init(0)
    gen = SyntheticCriteo(batch_size=512, num_cat=4, num_dense=2, vocab=2000,
                          zipf_a=1.6, seed=13)
    losses = []
    for _ in range(30):
        st, m = tr.train_step(st, shard_batch(mesh, J(gen.batch())))
        losses.append(float(m["loss"]))
    assert np.mean(losses[-5:]) < np.mean(losses[:5])
    # overflow counter (separate from insert_fails): sum across shards/groups
    total_overflow = 0
    for bname, ts in st.tables.items():
        total_overflow += int(np.asarray(ts.a2a_overflow).sum())
    assert total_overflow == 0, total_overflow
