"""All2all (SOK-style) exchange path must match the exact allgather path."""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from deeprec_tpu.data import SyntheticCriteo
from deeprec_tpu.models import WDL
from deeprec_tpu.optim import Adagrad, GradientDescent
from deeprec_tpu.parallel import ShardedTrainer, make_mesh, shard_batch
from deeprec_tpu.training import Trainer


def J(b):
    return {k: jnp.asarray(v) for k, v in b.items()}


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(8)


def small():
    return WDL(emb_dim=8, capacity=1 << 13, hidden=(32,), num_cat=4, num_dense=2)


def test_a2a_matches_allgather_and_local(mesh):
    gen = SyntheticCriteo(batch_size=256, num_cat=4, num_dense=2, vocab=3000, seed=11)
    batches = [J(gen.batch()) for _ in range(4)]

    t_local = Trainer(small(), GradientDescent(lr=0.1), optax.sgd(0.01))
    s_local = t_local.init(0)
    t_ag = ShardedTrainer(small(), GradientDescent(lr=0.1), optax.sgd(0.01),
                          mesh=mesh, comm="allgather")
    s_ag = t_ag.init(0)
    t_a2a = ShardedTrainer(small(), GradientDescent(lr=0.1), optax.sgd(0.01),
                           mesh=mesh, comm="a2a")
    s_a2a = t_a2a.init(0)

    for b in batches:
        s_local, ml = t_local.train_step(s_local, b)
        sb = shard_batch(mesh, b)
        s_ag, mag = t_ag.train_step(s_ag, sb)
        s_a2a, ma2a = t_a2a.train_step(s_a2a, sb)
        # a2a vs allgather: identical routing math, tiny fp-order differences
        np.testing.assert_allclose(
            float(mag["loss"]), float(ma2a["loss"]), rtol=1e-4
        )
        np.testing.assert_allclose(
            float(ml["loss"]), float(ma2a["loss"]), rtol=2e-2
        )


def test_a2a_learns_with_skewed_ids(mesh):
    """Zipf-skewed ids stress the per-destination budget; training must stay
    healthy and overflow must be (near) zero at slack=2."""
    model = small()
    tr = ShardedTrainer(model, Adagrad(lr=0.2), optax.adam(5e-3), mesh=mesh,
                        comm="a2a")
    st = tr.init(0)
    gen = SyntheticCriteo(batch_size=512, num_cat=4, num_dense=2, vocab=2000,
                          zipf_a=1.6, seed=13)
    losses = []
    for _ in range(30):
        st, m = tr.train_step(st, shard_batch(mesh, J(gen.batch())))
        losses.append(float(m["loss"]))
    assert np.mean(losses[-5:]) < np.mean(losses[:5])
    # overflow counter (separate from insert_fails): sum across shards/groups
    total_overflow = 0
    for bname, ts in st.tables.items():
        total_overflow += int(np.asarray(ts.a2a_overflow).sum())
    assert total_overflow == 0, total_overflow


def test_a2a_overflow_under_zipf_skew_converges(mesh):
    """VERDICT round-2 weak #8: when per-destination budgets actually BIND
    (zipf-skewed ids + a tight a2a_slack), overflow must be (a) visible in
    the counter, (b) bounded in training impact — loss still trends down
    on a LEARNABLE stream and tracks the exact allgather path within a
    modest gap — and (c) strictly a budget artifact: default slack drives
    overflow to zero on the same stream."""
    gen = SyntheticCriteo(batch_size=2048, num_cat=4, num_dense=2,
                          vocab=3000, zipf_a=1.3, seed=7)
    batches = [J(gen.batch()) for _ in range(12)]

    def total_overflow(st):
        return sum(int(np.asarray(ts.a2a_overflow).sum())
                   for ts in st.tables.values())

    t_ag = ShardedTrainer(small(), Adagrad(lr=0.1), optax.adam(1e-3),
                          mesh=mesh, comm="allgather")
    s_ag = t_ag.init(0)
    t_tight = ShardedTrainer(small(), Adagrad(lr=0.1), optax.adam(1e-3),
                             mesh=mesh, comm="a2a", a2a_slack=0.1)
    s_tight = t_tight.init(0)

    ag_losses, tight_losses = [], []
    for b in batches:
        sb = shard_batch(mesh, b)
        s_ag, m = t_ag.train_step(s_ag, sb)
        ag_losses.append(float(m["loss"]))
        s_tight, m2 = t_tight.train_step(s_tight, sb)
        tight_losses.append(float(m2["loss"]))

    assert total_overflow(s_tight) > 0, \
        "slack=0.1 under zipf skew must bind the budget"

    # (b) training under overflow still learns the LEARNABLE signal, and
    # tracks allgather: mean loss over the last 4 steps within 10% of the
    # exact path (overflowed ids serve defaults + drop grads, but zipf
    # mass concentrates on ids that DO fit their budget)
    assert np.mean(tight_losses[-4:]) < np.mean(tight_losses[:2])
    tail_gap = abs(np.mean(tight_losses[-4:]) - np.mean(ag_losses[-4:]))
    assert tail_gap < 0.1 * np.mean(ag_losses[-4:]), (
        tight_losses, ag_losses)

    # (c) default slack on the same stream: no overflow at all
    t_ok = ShardedTrainer(small(), Adagrad(lr=0.1), optax.adam(1e-3),
                          mesh=mesh, comm="a2a")  # slack=2.0
    s_ok = t_ok.init(0)
    for b in batches[:4]:
        s_ok, _ = t_ok.train_step(s_ok, shard_batch(mesh, b))
    assert total_overflow(s_ok) == 0
