"""Live elastic scaling: a RUNNING training job rescales 2 -> 4 -> 2.

The reference negotiates mid-job scaling over gRPC
(core/protobuf/elastic_training.proto:38-76, driven by
contrib/elastic_grpc_server/elastic_grpc_server_lib_test.cc): workers
poll IsReadyScaling, checkpoint, ReadyToUpdate, and the cluster def is
swapped. Here the same choreography runs over the file control plane
(parallel/elastic.ElasticCoordinator) with the launcher's supervisor
respawning worker generations (launch.supervise_elastic), because jax
pins the process set at distributed-init time.

The test is the autoscaler: it starts the supervisor at 2 processes,
posts scale plans mid-run, and asserts afterwards that
  * the job ran three generations (2 -> 4 -> 2 process sets),
  * a fixed probe batch predicts IDENTICALLY across every rescale
    boundary (state equivalence through save -> re-shard -> restore),
  * the shared WorkQueue rebalanced with no item processed twice and
    nothing lost except items taken in the final incomplete lockstep
    round (< process_count of them).
"""
import json
import os
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

from deeprec_tpu.parallel.elastic import ElasticCoordinator

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))

WORKER = textwrap.dedent(
    """
    import glob, json, os, sys
    sys.path.insert(0, {repo!r})
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from deeprec_tpu.data import SyntheticCriteo
    from deeprec_tpu.data.work_queue import WorkQueue
    from deeprec_tpu.models import WDL
    from deeprec_tpu.optim import Adagrad
    from deeprec_tpu.parallel import ShardedTrainer, make_mesh, shard_batch
    from deeprec_tpu.parallel.elastic import EXIT_RESCALE, ElasticCoordinator
    from deeprec_tpu.training.checkpoint import CheckpointManager
    from jax.experimental import multihost_utils

    OUT = {outdir!r}
    pid = jax.process_index()
    n = jax.process_count()
    gen_tag = f"g{{n}}-{{os.environ['DEEPREC_ELASTIC_EPOCH']}}"

    coord = ElasticCoordinator(os.environ["DEEPREC_ELASTIC_DIR"])
    mesh = make_mesh()
    model = WDL(emb_dim=4, capacity=1 << 8, hidden=(8,), num_cat=2,
                num_dense=2)
    tr = ShardedTrainer(model, Adagrad(lr=0.1), optax.adam(1e-3), mesh=mesh)
    ck = CheckpointManager({ckdir!r}, tr)
    st = ck.restore() if ck.latest_full() is not None else tr.init(0)

    def J(b):
        return {{k: jnp.asarray(v) for k, v in b.items()}}

    def local_preds(p):
        # process-local slice of the global prediction array: every
        # process feeds the SAME 8 probe rows as its local slice, so this
        # fingerprint is identical across processes AND topologies
        shards = sorted(p.addressable_shards, key=lambda s: s.index)
        return np.concatenate([np.asarray(s.data) for s in shards])

    probe = J(SyntheticCriteo(batch_size=8, num_cat=2, num_dense=2,
                              vocab=200, seed=777).batch())

    # restored-state fingerprint on a FIXED probe batch (must equal the
    # fingerprint the previous generation wrote right before its save)
    _, p_in = tr.eval_step(st, shard_batch(mesh, probe))
    with open(f"{{OUT}}/probe-in-{{gen_tag}}-{{pid}}.json", "w") as f:
        json.dump({{"step": int(st.step),
                   "probe": local_preds(p_in).tolist()}}, f)

    q = WorkQueue([f"item{{i:03d}}" for i in range(64)], shuffle=False,
                  coordination_file={qfile!r})
    processed = []
    unprocessed = []
    while True:
        target = coord.should_scale()
        if target is not None and target != n:
            st, _ = ck.save(st)
            _, p_out = tr.eval_step(st, shard_batch(mesh, probe))
            with open(f"{{OUT}}/probe-out-{{gen_tag}}-{{pid}}.json", "w") as f:
                json.dump({{"step": int(st.step),
                           "probe": local_preds(p_out).tolist()}}, f)
            with open(f"{{OUT}}/items-{{gen_tag}}-{{pid}}.json", "w") as f:
                json.dump({{"processed": processed,
                           "unprocessed": unprocessed}}, f)
            coord.ack_rescale()
            sys.exit(EXIT_RESCALE)

        item = q.take()
        have = multihost_utils.process_allgather(
            np.asarray([0 if item is None else 1]))
        if int(have.sum()) < n:  # lockstep round incomplete: stop together
            if item is not None:
                unprocessed.append(item)
            break
        # train on this worker's item (its local slice of the global batch)
        seed = int(item[4:])
        b = J(SyntheticCriteo(batch_size=8, num_cat=2, num_dense=2,
                              vocab=200, seed=seed).batch())
        st, mets = tr.train_step(st, shard_batch(mesh, b))
        processed.append(item)
        if len(processed) == 3:  # autoscaler waits for real progress
            open(f"{{OUT}}/progress-{{gen_tag}}-{{pid}}", "w").close()

    st, _ = ck.save(st)
    _, p_fin = tr.eval_step(st, shard_batch(mesh, probe))
    with open(f"{{OUT}}/final-{{gen_tag}}-{{pid}}.json", "w") as f:
        json.dump({{"step": int(st.step), "ndev": len(jax.devices()),
                   "probe": local_preds(p_fin).tolist()}}, f)
    with open(f"{{OUT}}/items-{{gen_tag}}-{{pid}}.json", "w") as f:
        json.dump({{"processed": processed, "unprocessed": unprocessed}}, f)
    """
)


@pytest.mark.slow
def test_live_elastic_2_4_2(tmp_path):
    outdir = str(tmp_path / "out")
    os.makedirs(outdir)
    edir = str(tmp_path / "elastic")
    script = str(tmp_path / "worker.py")
    with open(script, "w") as f:
        f.write(WORKER.format(repo=REPO, outdir=outdir,
                              ckdir=str(tmp_path / "ckpt"),
                              qfile=str(tmp_path / "queue.json")))

    env = {
        **os.environ,
        "PYTHONPATH": "",
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
    }
    # log to a FILE, not a PIPE: three generations of workers inherit this
    # fd, and an undrained pipe would deadlock everyone at ~64KB
    log_path = str(tmp_path / "supervisor.log")
    log_f = open(log_path, "w")
    sup = subprocess.Popen(
        [sys.executable, "-m", "deeprec_tpu.launch",
         "--num_processes", "2", "--elastic_dir", edir, script],
        env={**env, "PYTHONPATH": REPO}, cwd=REPO,
        stdout=log_f, stderr=subprocess.STDOUT, text=True,
    )
    coord = ElasticCoordinator(edir)

    def wait_for(pattern, timeout=240):
        deadline = time.time() + timeout
        import glob as g

        while time.time() < deadline:
            if g.glob(os.path.join(outdir, pattern)):
                return
            if sup.poll() is not None:
                raise AssertionError(
                    "supervisor died early:\n" + open(log_path).read()
                )
            time.sleep(0.3)
        sup.kill()
        raise AssertionError(
            "timeout waiting for " + pattern + ":\n" + open(log_path).read()
        )

    try:
        # generation 1 (n=2) starts training...
        wait_for("progress-g2-0-*")  # gen 1 trained >= 3 items/worker
        coord.request_scale(4)
        # generation 2 (n=4) must come up and train...
        wait_for("progress-g4-1-*")
        coord.request_scale(2)
        # generation 3 (n=2) drains the queue and finishes
        rc = sup.wait(timeout=300)
        assert rc == 0, open(log_path).read()
    finally:
        if sup.poll() is None:
            sup.kill()
        log_f.close()

    import glob as g

    # --- three generations ran
    assert g.glob(os.path.join(outdir, "probe-in-g4-1-*.json"))
    assert g.glob(os.path.join(outdir, "final-g2-2-*.json"))

    # --- state equivalence across each rescale boundary: the fingerprint
    # written right before a generation's save equals the one the next
    # generation wrote right after restore (same step, same predictions)
    def load(pat):
        fs = sorted(g.glob(os.path.join(outdir, pat)))
        assert fs, pat
        return json.load(open(fs[0]))

    out1 = load("probe-out-g2-0-0.json")      # gen1 (n=2, epoch 0) save
    in2 = load("probe-in-g4-1-0.json")        # gen2 (n=4, epoch 1) restore
    assert out1["step"] == in2["step"]
    np.testing.assert_allclose(out1["probe"], in2["probe"], atol=1e-5)

    out2 = load("probe-out-g4-1-0.json")      # gen2 save
    in3 = load("probe-in-g2-2-0.json")        # gen3 (n=2, epoch 2) restore
    assert out2["step"] == in3["step"]
    np.testing.assert_allclose(out2["probe"], in3["probe"], atol=1e-5)

    # steps strictly advanced across generations (it really TRAINED in
    # each topology, not just bounced checkpoints)
    fin = load("final-g2-2-0.json")
    assert out1["step"] > 0
    assert in2["step"] == out1["step"]
    assert out2["step"] > in2["step"]
    assert fin["step"] > out2["step"]

    # --- WorkQueue rebalancing: no item processed twice; nothing lost
    # except items taken in a final incomplete lockstep round
    processed, unprocessed = [], []
    for p in g.glob(os.path.join(outdir, "items-*.json")):
        d = json.load(open(p))
        processed += d["processed"]
        unprocessed += d["unprocessed"]
    assert len(processed) == len(set(processed)), "item processed twice"
    all_items = {f"item{i:03d}" for i in range(64)}
    assert set(processed) | set(unprocessed) == all_items
    assert len(unprocessed) < 4  # < max process count


def test_coordinator_plan_epoch_and_acks(tmp_path):
    """Fast control-plane unit test (no subprocesses): plan epochs
    increment, applied plans don't re-trigger, acks gate the supervisor."""
    coord = ElasticCoordinator(str(tmp_path))
    assert coord.plan() == (0, None)
    assert coord.should_scale() is None  # no plan, single process

    assert coord.request_scale(4) == 1
    assert coord.plan() == (1, 4)
    assert coord.should_scale() == 4

    # after the supervisor applies epoch 1 (env bump), it must not re-run
    os.environ["DEEPREC_ELASTIC_EPOCH"] = "1"
    try:
        assert coord.should_scale() is None
        assert coord.request_scale(2) == 2  # next event
        assert coord.should_scale() == 2
    finally:
        del os.environ["DEEPREC_ELASTIC_EPOCH"]

    # ReadyToUpdate barrier: acks reference the DECIDED epoch (and carry
    # the decided target for the supervisor), not a re-read of plan.json
    e = coord.request_scale(2)
    assert coord.should_scale() == 2  # decision recorded at epoch e
    coord.request_scale(8)  # racing autoscaler posts e+1 mid-rescale
    assert not coord.acked(e, 2)
    coord.ack_rescale()  # process 0 (single-process jax) -> acks epoch e
    assert not coord.acked(e, 2)
    with open(os.path.join(str(tmp_path), f"ack-{e}-00001"), "w") as f:
        f.write("2")
    assert coord.acked(e, 2)
    coord.wait_acked(e, 2, timeout=1)
    # the supervisor scans for the workers' epoch, not the latest plan
    assert coord.wait_acked_after(e - 1, 2, timeout=1) == (e, 2)


def test_supervisor_aborts_on_worker_failure(tmp_path):
    """A worker exiting with a non-RESCALE failure code must abort the
    job loudly (no silent respawn loop)."""
    from deeprec_tpu.launch import supervise_elastic

    script = str(tmp_path / "bad_worker.py")
    with open(script, "w") as f:
        f.write("import sys; sys.exit(3)\n")
    with pytest.raises(RuntimeError,
                       match=r"elastic workers failed: \[\(0, 3\)\]"):
        supervise_elastic(
            script, [], 1, str(tmp_path / "edir"),
            env_extra={"PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu"},
        )
