"""Input pipeline tests: CSV/Parquet readers, staged prefetch, WorkQueue
(reference coverage: work_queue_test.py, prefetch_test.py, parquet dataset
tests — SURVEY §4)."""
import os
import threading

import numpy as np
import pytest

from deeprec_tpu.data import (
    CriteoCSVReader,
    ParquetReader,
    Prefetcher,
    SyntheticCriteo,
    WorkQueue,
    parse_slice,
    staged,
)


def _write_criteo_tsv(path, rows=300):
    rng = np.random.default_rng(0)
    with open(path, "w") as f:
        for _ in range(rows):
            label = rng.integers(0, 2)
            dense = "\t".join(str(rng.integers(0, 100)) for _ in range(13))
            cats = "\t".join(f"{rng.integers(0, 1 << 20):x}" for _ in range(26))
            f.write(f"{label}\t{dense}\t{cats}\n")


def test_criteo_csv_reader(tmp_path):
    p = str(tmp_path / "day0.tsv")
    _write_criteo_tsv(p, rows=300)
    batches = list(CriteoCSVReader([p], batch_size=128))
    assert len(batches) == 2  # 300 // 128, remainder dropped
    b = batches[0]
    assert b["label"].shape == (128,)
    assert b["I1"].shape == (128, 1)
    assert b["C1"].dtype == np.int32
    assert (b["C1"] >= 0).all()  # hashed to non-negative id space


def test_native_csv_parser_matches_pandas(tmp_path):
    """The C++ parser (native/csv_parser.cpp) must be bit-identical to the
    pandas path, including missing-field handling and id hashing."""
    import deeprec_tpu.native as N

    if N.load_library() is None:
        pytest.skip("native library not built")
    rng = np.random.default_rng(3)
    p = str(tmp_path / "day.tsv")
    with open(p, "w") as f:
        for _ in range(3000):
            label = rng.integers(0, 2)
            dense = "\t".join(
                str(rng.integers(0, 100)) if rng.random() > 0.1 else ""
                for _ in range(13)
            )
            cats = "\t".join(
                f"{rng.integers(0, 1 << 20):x}" if rng.random() > 0.1 else ""
                for _ in range(26)
            )
            f.write(f"{label}\t{dense}\t{cats}\n")
    native = list(CriteoCSVReader([p], batch_size=512)._iter_native())
    orig = N.load_library
    N.load_library = lambda: None
    try:
        pandas = list(CriteoCSVReader([p], batch_size=512))
    finally:
        N.load_library = orig
    assert len(native) == len(pandas) == 5
    for nb, pb in zip(native, pandas):
        np.testing.assert_array_equal(nb["label"], pb["label"])
        np.testing.assert_allclose(nb["I7"], pb["I7"], rtol=1e-6)
        for c in ("C1", "C13", "C26"):
            np.testing.assert_array_equal(nb[c], pb[c])


def test_parquet_reader(tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq

    p = str(tmp_path / "part0.parquet")
    n = 500
    rng = np.random.default_rng(1)
    table = pa.table(
        {
            "label": rng.integers(0, 2, n).astype(np.float32),
            "item": [f"item_{i % 50}" for i in range(n)],
            "price": rng.random(n).astype(np.float32),
        }
    )
    pq.write_table(table, p)
    batches = list(ParquetReader([p], batch_size=200))
    assert len(batches) == 2
    assert batches[0]["item"].dtype == np.int32  # strings hashed
    assert batches[0]["price"].dtype == np.float32


def test_prefetcher_overlaps_and_preserves_order():
    gen = SyntheticCriteo(batch_size=32, num_cat=2, num_dense=2, vocab=100, seed=0)
    src = (gen.batch() for _ in range(10))
    seen = list(Prefetcher(src, depth=3, transform=lambda b: b))
    assert len(seen) == 10


def test_prefetcher_propagates_errors():
    def bad():
        yield {"x": np.zeros(1)}
        raise RuntimeError("reader exploded")

    it = iter(staged(bad(), transform=lambda b: b))
    next(it)
    with pytest.raises(RuntimeError, match="reader exploded"):
        next(it)


def test_work_queue_epochs_shuffle_slices():
    wq = WorkQueue(["a", "b"], num_epochs=2, shuffle=True, num_slices=2, seed=3)
    items = list(wq)
    assert len(items) == 8  # 2 files x 2 slices x 2 epochs
    assert wq.take() is None
    path, k, n = parse_slice(items[0])
    assert path in ("a", "b") and n == 2 and k in (0, 1)


def test_work_queue_save_restore():
    wq = WorkQueue(["a", "b", "c"], shuffle=False)
    assert wq.take() == "a"
    st = wq.save()
    assert wq.take() == "b"
    wq.restore(st)
    assert wq.take() == "b"  # resumed from saved cursor


def test_work_queue_file_coordinated(tmp_path):
    coord = str(tmp_path / "wq.json")
    wq1 = WorkQueue([f"f{i}" for i in range(20)], shuffle=False,
                    coordination_file=coord)
    wq2 = WorkQueue([f"f{i}" for i in range(20)], shuffle=False,
                    coordination_file=coord)
    taken = [[], []]

    def worker(i, wq):
        while True:
            item = wq.take()
            if item is None:
                return
            taken[i].append(item)

    t1 = threading.Thread(target=worker, args=(0, wq1))
    t2 = threading.Thread(target=worker, args=(1, wq2))
    t1.start(); t2.start(); t1.join(); t2.join()
    # disjoint and complete
    all_items = taken[0] + taken[1]
    assert sorted(all_items) == sorted(f"f{i}" for i in range(20))
    assert not (set(taken[0]) & set(taken[1]))
