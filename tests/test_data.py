"""Input pipeline tests: CSV/Parquet readers, staged prefetch, WorkQueue
(reference coverage: work_queue_test.py, prefetch_test.py, parquet dataset
tests — SURVEY §4)."""
import os
import threading
import time

import numpy as np
import pytest

from deeprec_tpu.data import (
    CriteoCSVReader,
    ParquetReader,
    Prefetcher,
    SyntheticCriteo,
    WorkQueue,
    parse_slice,
    staged,
)


def _write_criteo_tsv(path, rows=300):
    rng = np.random.default_rng(0)
    with open(path, "w") as f:
        for _ in range(rows):
            label = rng.integers(0, 2)
            dense = "\t".join(str(rng.integers(0, 100)) for _ in range(13))
            cats = "\t".join(f"{rng.integers(0, 1 << 20):x}" for _ in range(26))
            f.write(f"{label}\t{dense}\t{cats}\n")


def test_criteo_csv_reader(tmp_path):
    p = str(tmp_path / "day0.tsv")
    _write_criteo_tsv(p, rows=300)
    batches = list(CriteoCSVReader([p], batch_size=128))
    assert len(batches) == 2  # 300 // 128, remainder dropped
    b = batches[0]
    assert b["label"].shape == (128,)
    assert b["I1"].shape == (128, 1)
    assert b["C1"].dtype == np.int32
    assert (b["C1"] >= 0).all()  # hashed to non-negative id space


def test_native_csv_parser_matches_pandas(tmp_path):
    """The C++ parser (native/csv_parser.cpp) must be bit-identical to the
    pandas path, including missing-field handling and id hashing."""
    import deeprec_tpu.native as N

    if N.load_library() is None:
        pytest.skip("native library not built")
    rng = np.random.default_rng(3)
    p = str(tmp_path / "day.tsv")
    with open(p, "w") as f:
        for _ in range(3000):
            label = rng.integers(0, 2)
            dense = "\t".join(
                str(rng.integers(0, 100)) if rng.random() > 0.1 else ""
                for _ in range(13)
            )
            cats = "\t".join(
                f"{rng.integers(0, 1 << 20):x}" if rng.random() > 0.1 else ""
                for _ in range(26)
            )
            f.write(f"{label}\t{dense}\t{cats}\n")
    native = list(CriteoCSVReader([p], batch_size=512)._iter_native())
    orig = N.load_library
    N.load_library = lambda: None
    try:
        pandas = list(CriteoCSVReader([p], batch_size=512))
    finally:
        N.load_library = orig
    assert len(native) == len(pandas) == 5
    for nb, pb in zip(native, pandas):
        np.testing.assert_array_equal(nb["label"], pb["label"])
        np.testing.assert_allclose(nb["I7"], pb["I7"], rtol=1e-6)
        for c in ("C1", "C13", "C26"):
            np.testing.assert_array_equal(nb[c], pb[c])


def test_native_parser_mt_bit_identical():
    """The multi-threaded parser path must produce bit-identical outputs to
    the single-thread path (disjoint row ranges, no synchronization).
    Speedup is only observable on multi-core hosts; correctness is not."""
    import deeprec_tpu.native as N

    if N.load_library() is None or not hasattr(N.load_library(), "criteo_parse_mt"):
        pytest.skip("native mt parser not built")
    rng = np.random.default_rng(5)
    lines = []
    for _ in range(5000):
        dense = "\t".join(
            str(rng.integers(0, 100)) if rng.random() > 0.1 else ""
            for _ in range(13))
        cats = "\t".join(
            f"{rng.integers(0, 1 << 20):x}" if rng.random() > 0.1 else ""
            for _ in range(26))
        lines.append(f"{rng.integers(0, 2)}\t{dense}\t{cats}\n")
    buf = "".join(lines).encode() + b"0\tpartial"  # trailing partial line
    a = N.criteo_parse_native(buf, 5000, threads=1)
    b = N.criteo_parse_native(buf, 5000, threads=4)
    assert a[0] == b[0] == 5000
    for i in (1, 2, 3):
        np.testing.assert_array_equal(a[i], b[i])
    assert a[4] == b[4]  # consumed stops at the same line boundary


def test_native_parser_keeps_unterminated_final_line(tmp_path):
    """A file whose last line lacks a trailing newline must parse identically
    through the native and pandas paths (the native parser only consumes
    complete lines; the reader now terminates the residual at EOF)."""
    import deeprec_tpu.native as N

    if N.load_library() is None:
        pytest.skip("native library not built")
    p = str(tmp_path / "day.tsv")
    _write_criteo_tsv(p, rows=10)
    with open(p, "rb") as f:
        data = f.read()
    with open(p, "wb") as f:
        f.write(data.rstrip(b"\n"))  # strip the final newline
    native = list(
        CriteoCSVReader([p], batch_size=4, drop_remainder=False)._iter_native()
    )
    orig = N.load_library
    N.load_library = lambda: None
    try:
        pandas = list(CriteoCSVReader([p], batch_size=4, drop_remainder=False))
    finally:
        N.load_library = orig
    assert sum(len(b["label"]) for b in native) == 10
    assert len(native) == len(pandas)
    for nb, pb in zip(native, pandas):
        np.testing.assert_array_equal(nb["label"], pb["label"])
        np.testing.assert_array_equal(nb["C26"], pb["C26"])


def test_file_tail_reader_grows_window_past_giant_record(tmp_path):
    """One record longer than the read window must not wedge the reader
    (it widens the window instead of re-reading the same newline-free
    bytes forever)."""
    from deeprec_tpu.data import FileTailReader

    giant = "x" * (3 << 20)  # 3 MiB, far beyond the 1 MiB default window
    parser = lambda lines: {"n": np.array([len(l) for l in lines])}
    # Case 1: giant record first. Case 2: a complete short line precedes the
    # giant record, so the first window DOES contain a newline but can never
    # fill a batch — the widen must fire on window exhaustion, not only on
    # "no newline found".
    for case, lines in enumerate((
        [giant, "short"], ["short", giant]
    )):
        p = str(tmp_path / f"log{case}.tsv")
        with open(p, "w") as f:
            f.write("\n".join(lines) + "\n")
        r = FileTailReader(p, batch_size=2, stop_at_eof=True, parser=parser)
        batches = list(r)
        lens = np.concatenate([b["n"] for b in batches])
        assert sorted(lens.tolist()) == [5, 3 << 20], case


def test_tcp_stream_reader_exactly_once_resume(tmp_path):
    """Network streaming (Kafka-analog): consume over a real socket, crash
    after two batches, resume from the saved offset in a new consumer —
    no record lost, none delivered twice, even with records appended
    between the crash and the resume."""
    from deeprec_tpu.data import FileStreamServer, TCPStreamReader

    p = str(tmp_path / "log.tsv")
    with open(p, "w") as f:
        for i in range(100):
            f.write(f"row{i:04d}\n")
    srv = FileStreamServer(p, follow=False).start()
    parser = lambda lines: {"rows": np.asarray(lines, object)}
    try:
        r1 = TCPStreamReader("127.0.0.1", srv.port, batch_size=32,
                             parser=parser, stop_at_eof=True)
        it = iter(r1)
        got = [next(it), next(it)]  # 64 rows, then "crash"
        ckpt = r1.save()
        with open(p, "a") as f:  # the stream keeps growing meanwhile
            for i in range(100, 120):
                f.write(f"row{i:04d}\n")
        r2 = TCPStreamReader("127.0.0.1", srv.port, batch_size=32,
                             parser=parser, stop_at_eof=True)
        r2.restore(ckpt)
        got += list(r2)
    finally:
        srv.stop()
    rows = np.concatenate([b["rows"] for b in got])
    assert list(rows) == [f"row{i:04d}" for i in range(120)]


def test_tcp_stream_reconnect_does_not_duplicate(tmp_path):
    """Broker drop mid-stream (follow=False closes after current bytes):
    the reconnect replays from the consumer offset without duplicating the
    rows that were buffered but never yielded."""
    from deeprec_tpu.data import FileStreamServer, TCPStreamReader

    p = str(tmp_path / "log.tsv")
    with open(p, "w") as f:
        for i in range(50):  # 50 rows: 1 full batch of 32 + 18 buffered
            f.write(f"row{i:04d}\n")
    srv = FileStreamServer(p, follow=False).start()
    parser = lambda lines: {"rows": np.asarray(lines, object)}
    try:
        r = TCPStreamReader("127.0.0.1", srv.port, batch_size=32,
                            parser=parser, stop_at_eof=False,
                            reconnect_secs=0.05)
        it = iter(r)
        got = [next(it)]  # 32 yielded; 18 complete rows sit un-yielded
        # broker closed (follow=False); more rows land before reconnect
        with open(p, "a") as f:
            for i in range(50, 70):
                f.write(f"row{i:04d}\n")
        got.append(next(it))  # replay from offset: rows 32..63, no dupes
    finally:
        srv.stop()
    rows = np.concatenate([b["rows"] for b in got])
    assert list(rows) == [f"row{i:04d}" for i in range(64)]


def test_tcp_stream_connect_refused_raises(tmp_path):
    """A bounded consume against a dead broker must raise, not complete
    as an empty stream."""
    import socket

    from deeprec_tpu.data import TCPStreamReader

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()  # nothing listens here now
    r = TCPStreamReader("127.0.0.1", port, batch_size=8, stop_at_eof=True)
    with pytest.raises(OSError):
        list(r)


def test_parquet_reader(tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq

    p = str(tmp_path / "part0.parquet")
    n = 500
    rng = np.random.default_rng(1)
    table = pa.table(
        {
            "label": rng.integers(0, 2, n).astype(np.float32),
            "item": [f"item_{i % 50}" for i in range(n)],
            "price": rng.random(n).astype(np.float32),
        }
    )
    pq.write_table(table, p)
    batches = list(ParquetReader([p], batch_size=200))
    assert len(batches) == 2
    assert batches[0]["item"].dtype == np.int32  # strings hashed
    assert batches[0]["price"].dtype == np.float32


def test_prefetcher_overlaps_and_preserves_order():
    gen = SyntheticCriteo(batch_size=32, num_cat=2, num_dense=2, vocab=100, seed=0)
    src = (gen.batch() for _ in range(10))
    seen = list(Prefetcher(src, depth=3, transform=lambda b: b))
    assert len(seen) == 10


def test_prefetcher_propagates_errors():
    def bad():
        yield {"x": np.zeros(1)}
        raise RuntimeError("reader exploded")

    it = iter(staged(bad(), transform=lambda b: b))
    next(it)
    with pytest.raises(RuntimeError, match="reader exploded"):
        next(it)


def test_prefetcher_close_does_not_strand_worker():
    """close() on an unconsumed infinite source: the worker's put is timed
    and re-checks the stop flag, so the thread exits instead of blocking
    forever on a full queue."""

    def forever():
        while True:
            yield {"x": np.zeros(4)}

    pf = Prefetcher(forever(), depth=1, transform=lambda b: b)
    # let the worker fill the queue and block in its (timed) put
    time.sleep(0.3)
    pf.close()
    pf._thread.join(timeout=2.0)
    assert not pf._thread.is_alive()


def test_prefetcher_close_after_error_path():
    """A reader error with no consumer must not strand the worker either
    (the old code unconditionally enqueued exception + None)."""

    def bad():
        yield {"x": np.zeros(1)}
        raise RuntimeError("boom")

    pf = Prefetcher(bad(), depth=1, transform=lambda b: b)
    time.sleep(0.3)  # batch fills the depth-1 queue; error waits behind it
    pf.close()
    pf._thread.join(timeout=2.0)
    assert not pf._thread.is_alive()


def test_file_tail_reader_streams_and_resumes(tmp_path):
    """Kafka-analog: follow an append-only log; offsets checkpoint/resume."""
    from deeprec_tpu.data import FileTailReader

    p = str(tmp_path / "stream.tsv")

    def write_rows(n, start=0):
        with open(p, "a") as f:
            for i in range(start, start + n):
                dense = "\t".join("1" for _ in range(13))
                cats = "\t".join(f"{i+j:x}" for j in range(26))
                f.write(f"{i % 2}\t{dense}\t{cats}\n")

    write_rows(64)
    r = FileTailReader(p, batch_size=32, stop_at_eof=True)
    batches = list(r)
    assert len(batches) == 2 and batches[0]["label"].shape == (32,)
    state = r.save()

    # producer appends more; a NEW reader restored from the offset reads
    # ONLY the new rows (exactly-once with checkpointed offsets)
    write_rows(32, start=64)
    r2 = FileTailReader(p, batch_size=32, stop_at_eof=True)
    r2.restore(state)
    new = list(r2)
    assert len(new) == 1
    assert float(new[0]["label"][0]) == 0.0  # row 64 -> label 64%2

    # restoring a checkpoint from a different file is rejected
    import pytest as _pytest

    with _pytest.raises(ValueError, match="offset checkpoint"):
        FileTailReader(str(tmp_path / "other.tsv"), 32).restore(state)


def test_file_tail_reader_partial_line_and_offset_exactness(tmp_path):
    from deeprec_tpu.data import FileTailReader

    p = str(tmp_path / "s.tsv")

    def row(i, nl=True):
        dense = "\t".join("1" for _ in range(13))
        cats = "\t".join("a" for _ in range(26))
        return f"{i % 2}\t{dense}\t{cats}" + ("\n" if nl else "")

    # 48 rows + one UNTERMINATED partial line: must not hang, must not parse
    # the partial, and offsets must only cover YIELDED rows.
    with open(p, "w") as f:
        for i in range(48):
            f.write(row(i))
        f.write(row(99, nl=False))  # partial (no newline)
    r = FileTailReader(p, batch_size=32, stop_at_eof=True)
    it = iter(r)
    first = next(it)
    assert first["label"].shape == (32,)
    mid = r.save()  # 16 full rows remain beyond this offset
    rest = list(it)  # final flush of the 16 complete rows; partial ignored
    assert sum(b["label"].shape[0] for b in rest) == 16

    # restore at the mid checkpoint re-delivers exactly the 16 undelivered
    # complete rows (none lost to internal buffering)
    r2 = FileTailReader(p, batch_size=32, stop_at_eof=True)
    r2.restore(mid)
    redelivered = list(r2)
    assert sum(b["label"].shape[0] for b in redelivered) == 16


def test_determinism_same_seed_same_results():
    """No hidden nondeterminism: two runs from the same seed/data produce
    bitwise-identical states (the race-detection tier: our lockless-map
    equivalent is correctness by construction, SURVEY §5)."""
    import jax
    import optax

    from deeprec_tpu.models import WDL
    from deeprec_tpu.optim import Adagrad
    from deeprec_tpu.training import Trainer

    def run():
        model = WDL(emb_dim=8, capacity=1 << 12, hidden=(16,), num_cat=3,
                    num_dense=2)
        tr = Trainer(model, Adagrad(lr=0.1), optax.adam(1e-3))
        st = tr.init(0)
        gen = SyntheticCriteo(batch_size=128, num_cat=3, num_dense=2,
                              vocab=500, seed=77)
        import jax.numpy as jnp

        for _ in range(5):
            st, m = tr.train_step(
                st, {k: jnp.asarray(v) for k, v in gen.batch().items()}
            )
        return st, float(m["loss"])

    s1, l1 = run()
    s2, l2 = run()
    assert l1 == l2
    for a, b in zip(jax.tree.leaves(s1), jax.tree.leaves(s2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_work_queue_epochs_shuffle_slices():
    wq = WorkQueue(["a", "b"], num_epochs=2, shuffle=True, num_slices=2, seed=3)
    items = list(wq)
    assert len(items) == 8  # 2 files x 2 slices x 2 epochs
    assert wq.take() is None
    path, k, n = parse_slice(items[0])
    assert path in ("a", "b") and n == 2 and k in (0, 1)


def test_work_queue_input_dataset_slices_cover_file(tmp_path):
    """input_dataset() over sliced work items: every row of the file is
    delivered exactly once across the slices (line-snapped byte ranges)."""
    from deeprec_tpu.data import WorkQueue

    p = str(tmp_path / "day0.tsv")
    _write_criteo_tsv(p, rows=300)
    q = WorkQueue([p], shuffle=False, num_slices=3)
    rows = 0
    labels = []
    # default delivers every row (a drop_remainder default would silently
    # drop up to batch_size-1 rows PER SLICE)
    for b in q.input_dataset(batch_size=32):
        rows += len(b["label"])
        labels.append(b["label"])
    assert rows == 300
    # parity with an unsliced read
    full = np.concatenate(
        [b["label"] for b in
         __import__("deeprec_tpu.data", fromlist=["CriteoCSVReader"])
         .CriteoCSVReader([p], 32, drop_remainder=False)]
    )
    np.testing.assert_array_equal(np.concatenate(labels), full)


def test_work_queue_save_restore():
    wq = WorkQueue(["a", "b", "c"], shuffle=False)
    assert wq.take() == "a"
    st = wq.save()
    assert wq.take() == "b"
    wq.restore(st)
    assert wq.take() == "b"  # resumed from saved cursor


def test_work_queue_file_coordinated(tmp_path):
    coord = str(tmp_path / "wq.json")
    wq1 = WorkQueue([f"f{i}" for i in range(20)], shuffle=False,
                    coordination_file=coord)
    wq2 = WorkQueue([f"f{i}" for i in range(20)], shuffle=False,
                    coordination_file=coord)
    taken = [[], []]

    def worker(i, wq):
        while True:
            item = wq.take()
            if item is None:
                return
            taken[i].append(item)

    t1 = threading.Thread(target=worker, args=(0, wq1))
    t2 = threading.Thread(target=worker, args=(1, wq2))
    t1.start(); t2.start(); t1.join(); t2.join()
    # disjoint and complete
    all_items = taken[0] + taken[1]
    assert sorted(all_items) == sorted(f"f{i}" for i in range(20))
    assert not (set(taken[0]) & set(taken[1]))


def test_tcp_backoff_delay_delegates_to_shared_policy():
    """Reconnect policy: the reader's backoff_delay IS the shared
    utils/backoff.py policy applied to (reconnect_secs,
    reconnect_max_secs) — the value pins themselves moved to
    tests/test_backoff.py with the dedup; this keeps the delegation
    honest (a reader-local fork would drift undetected)."""
    from deeprec_tpu.data import TCPStreamReader
    from deeprec_tpu.utils import backoff

    r = TCPStreamReader("127.0.0.1", 1, reconnect_secs=0.5,
                        reconnect_max_secs=8.0)
    for attempt in (1, 2, 3, 5, 50):
        assert r.backoff_delay(attempt) == backoff.backoff_delay(
            attempt, 0.5, 8.0)


def test_tcp_reader_counts_reconnect_attempts(tmp_path):
    """A dead broker drives consecutive_connect_failures up (visible to
    supervisors); a successful connect resets it and counts reconnects."""
    import socket

    from deeprec_tpu.data import FileStreamServer, TCPStreamReader

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()  # nothing listens here
    r = TCPStreamReader("127.0.0.1", port, batch_size=4,
                        reconnect_secs=0.01, reconnect_max_secs=0.03)
    t = threading.Thread(target=lambda: next(iter(r), None), daemon=True)
    t.start()
    deadline = time.time() + 10
    while r.consecutive_connect_failures < 3 and time.time() < deadline:
        time.sleep(0.01)
    assert r.consecutive_connect_failures >= 3
    assert r.connect_attempts >= 3

    # now a live broker: the counter must reset on the next connect
    p = str(tmp_path / "log.tsv")
    with open(p, "w") as f:
        for i in range(8):
            f.write(f"r{i}\n")
    srv = FileStreamServer(p, port=port, follow=True).start()
    try:
        deadline = time.time() + 10
        while r.consecutive_connect_failures != 0 and time.time() < deadline:
            time.sleep(0.01)
        assert r.consecutive_connect_failures == 0
    finally:
        srv.stop()


def test_work_queue_torn_cursor_write_never_observed(tmp_path):
    """A worker killed MID-WRITE of the shared cursor file must not
    strand the other workers: the commit goes to a tempfile + rename, so
    a torn attempt leaves the previous state fully intact and parseable.
    The kill is injected via the on_coord_write seam (partial bytes, then
    die), which is exactly what a SIGKILL between write() and rename()
    leaves behind."""
    import json as _json

    coord = str(tmp_path / "wq.json")
    items = [f"f{i}" for i in range(6)]
    wq1 = WorkQueue(items, shuffle=False, coordination_file=coord)
    assert wq1.take() == "f0"

    def torn(f, data):
        f.write(data[: len(data) // 3])  # partial JSON on disk...
        raise KeyboardInterrupt("injected kill mid-write")

    wq1.on_coord_write = torn
    with pytest.raises(KeyboardInterrupt):
        wq1.take()  # dies mid-commit of cursor 1 -> 2
    # the shared file is the PREVIOUS complete state, not a torn one
    with open(coord) as f:
        st = _json.load(f)
    assert st["cursor"] == 1

    # a concurrent taker (fresh worker process analog) proceeds unharmed
    wq2 = WorkQueue(items, shuffle=False, coordination_file=coord)
    assert wq2.take() == "f1"
    # and the dead worker's partial tempfile is never read as state
    wq1.on_coord_write = None
    assert wq1.take() == "f2"


def test_work_queue_torn_writes_with_concurrent_takers(tmp_path):
    """Hammer the coordinated queue from two threads while a third
    repeatedly injects torn writes: every item is taken exactly once and
    no taker ever hits a JSON parse error."""
    coord = str(tmp_path / "wq.json")
    items = [f"f{i}" for i in range(40)]
    torn_count = [0]

    def make_wq():
        return WorkQueue(items, shuffle=False, coordination_file=coord)

    wq_a, wq_b, wq_evil = make_wq(), make_wq(), make_wq()

    def torn(f, data):
        torn_count[0] += 1
        f.write(data[:7])
        raise KeyboardInterrupt("injected")

    wq_evil.on_coord_write = torn
    taken = [[], []]
    stop = threading.Event()

    def taker(i, wq):
        while True:
            item = wq.take()  # a parse error would raise out of here
            if item is None:
                return
            taken[i].append(item)
            time.sleep(0.001)

    def saboteur():
        while not stop.is_set():
            try:
                wq_evil.take()
            except KeyboardInterrupt:
                pass
            time.sleep(0.002)

    ts = [threading.Thread(target=taker, args=(0, wq_a)),
          threading.Thread(target=taker, args=(1, wq_b))]
    tsab = threading.Thread(target=saboteur, daemon=True)
    for t in ts:
        t.start()
    tsab.start()
    for t in ts:
        t.join(timeout=60)
    stop.set()
    tsab.join(timeout=5)
    assert torn_count[0] >= 1  # the fault actually fired
    got = taken[0] + taken[1]
    assert sorted(got) == sorted(items)
    assert not (set(taken[0]) & set(taken[1]))
