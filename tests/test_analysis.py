"""deeprec_tpu.analysis: lint rules (fixture snippets: positive, negative,
suppressed per rule), the checked-in baseline's integrity, the noqa/
baseline gate mechanics, and the runtime trace-guard — including the
acceptance pins:

  * removing a known `# noqa` from repo source makes `--check` exit
    nonzero (the gate actually guards the suppressed sites);
  * trace_guard(max_compiles=0) passes on steady-state K-step training;
  * trace_guard CATCHES a deliberately re-introduced per-call
    ``jit(lambda ...)`` retrace — the PR 5 `_prune_to_live` class.
"""
import io
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeprec_tpu.analysis import (
    TraceGuardViolation,
    annotations,
    compile_count,
    trace_guard,
)
from deeprec_tpu.analysis import lint


# ----------------------------------------------------------- lint harness


def lint_files(tmp_path, files, rules=None):
    """Write {relpath: source} under a temp root, lint it, return
    (all findings, active findings) as rendered-rule lists."""
    import os

    targets = set()
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
        targets.add(rel.split("/")[0] if "/" in rel else rel)
    mods = lint.collect_modules(str(tmp_path), sorted(targets))
    findings = lint.run_rules(mods, rules)
    active, _ = lint.split_suppressed(mods, findings)
    return findings, active


def codes(findings):
    return [f.rule for f in findings]


# ------------------------------------------------------------ DRT001 rule


def test_drt001_flags_per_call_jit_of_lambda_and_closure(tmp_path):
    _, active = lint_files(tmp_path, {"pkg/m.py": """
        import jax

        def hot(x):
            f = jax.jit(lambda v: v + 1)     # fresh wrapper per call
            def inner(v):
                return v * 2
            g = jax.jit(inner)               # nested closure per call
            return f(x) + g(x)
    """}, rules=["DRT001"])
    assert codes(active) == ["DRT001", "DRT001"]


def test_drt001_flags_per_call_jit_of_module_level_function(tmp_path):
    """jit-ing a STABLE module function per call is the same hazard: each
    jax.jit() call returns a new wrapper with its own empty cache."""
    _, active = lint_files(tmp_path, {"pkg/m.py": """
        import jax

        def prune(state):
            return state

        def poll(state):
            return jax.jit(prune)(state)     # fresh wrapper per poll
    """}, rules=["DRT001"])
    assert codes(active) == ["DRT001"]
    assert "fresh wrapper" in active[0].message


def test_drt001_negative_module_scope_decorator_and_init(tmp_path):
    _, active = lint_files(tmp_path, {"pkg/m.py": """
        import jax
        from functools import partial

        top = jax.jit(lambda v: v + 1)       # module scope: compiles once

        @jax.jit
        def decorated(v):
            return v * 2

        @partial(jax.jit, static_argnums=0)
        def decorated2(k, v):
            return v * k

        class T:
            def __init__(self):
                # idiomatic per-instance compile — allowed
                self._step = jax.jit(self._impl)

            def _impl(self, v):
                return v
    """}, rules=["DRT001"])
    assert active == []


def test_drt001_bound_method_rebuilder_flagged_and_suppressable(tmp_path):
    files = {"pkg/m.py": """
        import jax

        class T:
            def rebuild(self):
                self._step = jax.jit(self._impl)

            def _impl(self, v):
                return v
    """}
    _, active = lint_files(tmp_path, files, rules=["DRT001"])
    assert codes(active) == ["DRT001"]
    files["pkg/m.py"] = files["pkg/m.py"].replace(
        "self._step = jax.jit(self._impl)",
        "self._step = jax.jit(self._impl)  # noqa: DRT001 — deliberate",
    )
    _, active = lint_files(tmp_path, files, rules=["DRT001"])
    assert active == []


# ------------------------------------------------------------ DRT002 rule


HOT_PKG = {"pkg/m.py": """
    import numpy as np

    class T:
        def train_step(self, state, batch):
            return self._helper(state)

        def _helper(self, state):
            return float(state.loss.item())

    def cold(state):
        return np.asarray(state)             # unreachable from any root
"""}


def test_drt002_call_graph_reaches_helper_not_cold(tmp_path):
    _, active = lint_files(tmp_path, HOT_PKG, rules=["DRT002"])
    assert codes(active) == ["DRT002", "DRT002"]  # .item() and float()
    assert all(f.scope == "T._helper" for f in active)
    assert all("cold" not in f.scope for f in active)


def test_drt002_scan_body_nested_def_is_reachable(tmp_path):
    _, active = lint_files(tmp_path, {"pkg/m.py": """
        import numpy as np

        def train_steps(state, batches):
            def body(carry, b):
                host = np.asarray(b)         # sync inside the scan body
                return carry, host
            return body(state, batches)
    """}, rules=["DRT002"])
    assert codes(active) == ["DRT002"]
    assert "train_steps" in active[0].message


def test_drt002_suppressed_site_is_inactive_but_reported(tmp_path):
    all_f, active = lint_files(tmp_path, {"pkg/m.py": """
        import numpy as np

        def predict(batch):
            return np.asarray(batch)  # noqa: DRT002 — result D2H
    """}, rules=["DRT002"])
    assert codes(all_f) == ["DRT002"] and active == []


# ------------------------------------------------------------ DRT003 rule


def test_drt003_small_trailing_dim_and_nonpow2_in_ops_only(tmp_path):
    _, active = lint_files(tmp_path, {
        "pkg/ops/k.py": """
            import jax.numpy as jnp

            def f(C):
                bad_layout = jnp.zeros((C, 3))      # lane-hostile
                good_layout = jnp.zeros((3, C))
                bad_bucket = jnp.zeros((24,))       # non-pow2 static
                good_bucket = jnp.zeros((32,))
                return bad_layout, good_layout, bad_bucket, good_bucket
        """,
        # identical code OUTSIDE ops//embedding/ is not layout-lintable
        "pkg/serving/k.py": """
            import jax.numpy as jnp

            def f(C):
                return jnp.zeros((C, 3)), jnp.zeros((24,))
        """,
    }, rules=["DRT003"])
    assert codes(active) == ["DRT003", "DRT003"]
    assert all("ops/k.py" in f.path for f in active)


def test_drt003_numpy_host_arrays_not_flagged(tmp_path):
    _, active = lint_files(tmp_path, {"pkg/ops/k.py": """
        import numpy as np

        def f(C):
            return np.zeros((C, 3)), np.zeros((24,))   # host memory: fine
    """}, rules=["DRT003"])
    assert active == []


# ------------------------------------------------------------ DRT004 rule


THREADED_PKG = {"pkg/m.py": """
    import threading
    from deeprec_tpu.analysis.annotations import guarded_by, not_thread_safe

    @not_thread_safe
    class Store:
        def put(self, k, v):
            pass

    @guarded_by("_lock")
    class Stats:
        def __init__(self):
            self._lock = threading.Lock()
            self.count = 0

        def bump(self):
            with self._lock:
                self.count += 1

    class Owner:
        def __init__(self):
            self.store = Store()
            self.stats = Stats()
            self._t = threading.Thread(target=self._worker)

        def _worker(self):
            self.store.put(1, 2)             # NTS from a thread: flagged
            self.stats.bump()                # guarded METHOD call: fine
            self.stats.count = 5             # guarded FIELD write: flagged
            with self.stats._lock:
                self.stats.count = 6         # lock held: fine

        def main_thread_path(self):
            self.store.put(3, 4)             # not a thread entry: fine
"""}


def test_drt004_thread_entry_vs_main_and_lock_semantics(tmp_path):
    _, active = lint_files(tmp_path, THREADED_PKG, rules=["DRT004"])
    assert codes(active) == ["DRT004", "DRT004"]
    assert all(f.scope == "Owner._worker" for f in active)
    msgs = " / ".join(f.message for f in active)
    assert "not_thread_safe" in msgs and "guarded_by" in msgs


def test_drt004_nts_access_flagged_even_under_an_unrelated_lock(tmp_path):
    """Holding SOME lock proves nothing about who else touches a
    @not_thread_safe object — only an explicit noqa naming the
    serialization protocol clears it."""
    pkg = dict(THREADED_PKG)
    pkg["pkg/m.py"] = pkg["pkg/m.py"].replace(
        "self.store.put(1, 2)             # NTS from a thread: flagged",
        "with self.stats._lock:\n"
        "                self.store.put(1, 2)  # wrong lock: still flagged",
    )
    _, active = lint_files(tmp_path, pkg, rules=["DRT004"])
    assert [f.rule for f in active
            if "not_thread_safe" in f.message] == ["DRT004"]


def test_drt004_annotated_method_call_from_writer_thread(tmp_path):
    _, active = lint_files(tmp_path, {"pkg/m.py": """
        import threading
        from deeprec_tpu.analysis.annotations import not_thread_safe

        class CK:
            def save_async(self):
                t = threading.Thread(target=self._writer_main)
                t.start()

            def _writer_main(self):
                self._write_plan()           # flagged

            @not_thread_safe
            def _write_plan(self):
                pass

            def save_sync(self):
                self._write_plan()           # main thread: fine
    """}, rules=["DRT004"])
    assert codes(active) == ["DRT004"]
    assert active[0].scope == "CK._writer_main"


# ------------------------------------------------- DRT005 / DRT006 hygiene


def test_drt005_unused_import_pos_neg_and_init_exempt(tmp_path):
    _, active = lint_files(tmp_path, {
        "pkg/m.py": """
            import os
            import json

            def f():
                return json.dumps({})
        """,
        "pkg/__init__.py": "from pkg.m import f\nimport os\n",  # re-export surface
    }, rules=["DRT005"])
    assert codes(active) == ["DRT005"]
    assert "'os'" in active[0].message and "m.py" in active[0].path


def test_drt006_param_shadowing(tmp_path):
    _, active = lint_files(tmp_path, {"pkg/m.py": """
        import json

        def f(id, json, name):
            return id, json, name
    """}, rules=["DRT006"])
    assert sorted(f.message for f in active) == [
        "parameter 'id' shadows a builtin",
        "parameter 'json' shadows a module import",
    ]


# ------------------------------------------------------------ DRT007 rule


def test_drt007_flags_per_request_label_values(tmp_path):
    """Label values interpolating per-request data (user ids, raw keys)
    are unbounded-cardinality bugs — through dict literals, f-strings,
    the positional labels arg, and the prometheus-style .labels()."""
    _, active = lint_files(tmp_path, {"pkg/m.py": """
        def serve(reg, metric, user_id, raw_key, fn):
            reg.counter("hits", "h", {"user": user_id}).inc()
            reg.gauge("g", "h", labels={"key": f"k-{raw_key}"}).set(1)
            reg.histogram("lat", "h", {"who": str(user_id)})
            reg.register_callback("cb", fn, "h", {"req": raw_key})
            metric.labels(user=user_id).inc()
    """}, rules=["DRT007"])
    assert codes(active) == ["DRT007"] * 5
    assert all("unbounded" in f.message for f in active)


def test_drt007_negatives_bounded_label_sets(tmp_path):
    """Bounded label sources — constants, stage names, loop vars over
    fixed tuples, table names, shard indices — are the contract, not a
    finding; labels dicts the rule cannot see into are left alone."""
    _, active = lint_files(tmp_path, {"pkg/m.py": """
        STAGES = ("queue", "pad", "device", "post")

        def wire(reg, tname, labels):
            reg.counter("ok", "h", {"stage": "queue"}).inc()
            for s in STAGES:
                reg.histogram("lat", "h", {"stage": s})
            for i in range(8):
                reg.gauge("xb", "h", {"table": tname, "shard": str(i)})
            reg.counter("opaque", "h", labels)   # not a literal: skip
    """}, rules=["DRT007"])
    assert active == []


def test_drt007_suppressable_and_repo_is_clean(tmp_path):
    _, active = lint_files(tmp_path, {"pkg/m.py": """
        def serve(reg, user_id):
            reg.counter("hits", "h", {"user": user_id}).inc()  # noqa: DRT007 — bounded: user_id is a 4-way experiment arm
    """}, rules=["DRT007"])
    assert active == []
    # the shipped tree (obs plane included) carries no DRT007 findings
    mods = lint.collect_modules(lint.repo_root(), lint.DEFAULT_TARGETS)
    repo_active, _ = lint.split_suppressed(
        mods, lint.run_rules(mods, ["DRT007"]))
    assert repo_active == []


# ------------------------------------------- repo baseline + gate mechanics


def test_repo_check_is_green():
    """The shipped tree passes its own gate (the CI invariant)."""
    buf = io.StringIO()
    assert lint.check(out=buf) == 0, buf.getvalue()


def test_baseline_parses_and_every_entry_is_current():
    """Baseline integrity: each entry matches the fingerprint grammar AND
    still corresponds to a real finding in the tree — a stale entry (the
    finding was fixed but the baseline still lists it) must fail."""
    import re

    base = lint.load_baseline(lint.default_baseline_path())
    assert base, "baseline should carry the pre-existing DRT002 sites"
    gram = re.compile(r"^DRT\d{3}\|[^|]+\.py\|[^|]+\|.*$")
    for entry in base:
        assert gram.match(entry), f"malformed baseline entry: {entry}"
    mods = lint.collect_modules(lint.repo_root(), lint.DEFAULT_TARGETS)
    active, _ = lint.split_suppressed(mods, lint.run_rules(mods))
    current = set(lint.fingerprints(active))
    stale = set(base) - current
    assert not stale, f"stale baseline entries: {sorted(stale)[:5]}"


def test_removing_a_known_noqa_fails_the_check():
    """Acceptance pin: the suppressed sites are live gates, not comments —
    stripping one justification noqa from real repo source flips the CLI
    to nonzero with the right finding."""
    path = "deeprec_tpu/embedding/multi_tier.py"
    src = open(lint.repo_root() + "/" + path, encoding="utf-8").read()
    marker = ("  # noqa: DRT004 — worker owns the tier stores until "
              "_settle(); every other path drains first")
    assert marker in src, "known suppressed site moved — update this pin"
    buf = io.StringIO()
    rc = lint.check(source_overrides={path: src.replace(marker, "", 1)},
                    out=buf)
    assert rc != 0
    assert "DRT004" in buf.getvalue()
    assert "_worker_main" in buf.getvalue()


def test_new_violation_fails_and_fix_baseline_would_accept(tmp_path):
    """A brand-new hot-path sync in real repo source fails --check; the
    failure names the file and rule."""
    path = "deeprec_tpu/serving/predictor.py"
    src = open(lint.repo_root() + "/" + path, encoding="utf-8").read()
    anchor = "    def predict(self, batch: Dict[str, np.ndarray], " \
             "group_users: bool = False):\n" \
             '        """Probabilities for one batch (dict keyed per ' \
             'task for MTL)."""\n'
    assert anchor in src
    bad = anchor + "        _ = np.asarray(batch)\n"
    buf = io.StringIO()
    rc = lint.check(source_overrides={path: src.replace(anchor, bad, 1)},
                    out=buf)
    assert rc != 0
    out = buf.getvalue()
    assert "NEW finding" in out and "DRT002" in out and "predictor.py" in out


def test_stale_baseline_entry_fails_check(tmp_path):
    """An entry for a finding that no longer exists must fail (the
    baseline can never rot silently)."""
    stale_baseline = tmp_path / "baseline.txt"
    base = lint.load_baseline(lint.default_baseline_path())
    stale_baseline.write_text(
        "\n".join(base + ["DRT002|deeprec_tpu/gone.py|f|x = y.item()"])
        + "\n"
    )
    buf = io.StringIO()
    rc = lint.check(baseline_path=str(stale_baseline), out=buf)
    assert rc != 0
    assert "STALE" in buf.getvalue()


def test_annotations_runtime_metadata():
    @annotations.not_thread_safe
    class A:
        pass

    @annotations.guarded_by("_lock")
    class B:
        pass

    assert annotations.is_not_thread_safe(A)
    assert not annotations.is_not_thread_safe(B)
    assert annotations.guard_lock_of(B) == "_lock"
    assert annotations.guard_lock_of(A) is None


# ----------------------------------------------------------- trace guard


def tiny_trainer():
    from deeprec_tpu.data import SyntheticCriteo
    from deeprec_tpu.models import WDL
    from deeprec_tpu.optim import Adagrad
    from deeprec_tpu.training import Trainer

    model = WDL(emb_dim=4, capacity=512, hidden=(8,), num_cat=3,
                num_dense=2)
    tr = Trainer(model, Adagrad(lr=0.1))
    gen = SyntheticCriteo(batch_size=32, num_cat=3, num_dense=2, vocab=300,
                          seed=7)
    batches = [
        {k: jnp.asarray(v) for k, v in gen.batch().items()} for _ in range(4)
    ]
    return tr, batches


def test_trace_guard_steady_state_k_step_training_is_compile_free():
    """Acceptance pin: after the warmup dispatch, K-step training
    compiles NOTHING — the whole multi-step loop is cache-hit dispatch."""
    from deeprec_tpu.training import stack_batches

    tr, batches = tiny_trainer()
    state = tr.init(0)
    stacked = [stack_batches(batches[:2]), stack_batches(batches[2:])]
    for s in stacked:  # warmup: compiles the K path once
        state, mets = tr.train_steps(state, s)
    jax.block_until_ready(mets["loss"])
    with trace_guard(max_compiles=0, note="steady-state K-step") as g:
        for _ in range(2):
            for s in stacked:
                state, mets = tr.train_steps(state, s)
        jax.block_until_ready(mets["loss"])
    assert g.compiles == 0


def test_trace_guard_catches_reintroduced_per_call_jit_lambda():
    """Acceptance pin: the PR 5 retrace class — a jit wrapper rebuilt per
    call (here the literal `jit(lambda ...)`) — is CAUGHT, with the
    compile count surfaced on the exception."""
    x = jnp.ones((8,))
    jax.block_until_ready(jax.jit(lambda v: v * 2)(x))  # unrelated warm
    with pytest.raises(TraceGuardViolation) as ei:
        with trace_guard(max_compiles=0, note="retrace regression"):
            for _ in range(3):
                # the buggy shape: a fresh callable every iteration, so
                # the jit cache can never hit — exactly what the eager
                # _prune_to_live closure did on every delta replay
                jax.block_until_ready(jax.jit(lambda v: v + 1)(x))
    assert ei.value.compiles >= 3
    assert ei.value.max_compiles == 0
    assert "retrace regression" in str(ei.value)


def test_trace_guard_budget_and_measure_only_modes():
    x = jnp.ones((16,))

    def fresh_program(i):
        # one REAL compile per distinct static shape
        return jax.jit(lambda v: v[: i + 1] * 3)(x)

    with trace_guard(max_compiles=2) as g:
        jax.block_until_ready(fresh_program(3))
    assert g.compiles <= 2
    # measure-only: never raises no matter how many compiles land
    with trace_guard(max_compiles=None) as g:
        jax.block_until_ready(fresh_program(5))
        jax.block_until_ready(fresh_program(7))
    assert g.compiles >= 1
    assert compile_count() >= g.compiles


def test_trace_guard_does_not_mask_body_exceptions():
    with pytest.raises(ValueError, match="body failed"):
        with trace_guard(max_compiles=0):
            jax.jit(lambda v: v * 9)(jnp.ones((4,)))  # would violate
            raise ValueError("body failed")
