"""Sharded-trainer coverage beyond WDL: sequence models (shared tables +
ragged ids through the collective path), multi-task models, incremental
checkpointing under sharding, and dtype variants (int64 keys, bf16 values)."""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from deeprec_tpu import (
    EmbeddingTable,
    EmbeddingVariableOption,
    InitializerOption,
    TableConfig,
)
from deeprec_tpu.data import SyntheticBehaviorSequence, SyntheticMultiTask
from deeprec_tpu.models import DIN, MMoE
from deeprec_tpu.optim import Adagrad
from deeprec_tpu.parallel import ShardedTrainer, make_mesh, shard_batch
from deeprec_tpu.training import Trainer
from deeprec_tpu.training.checkpoint import CheckpointManager


def J(b):
    return {k: jnp.asarray(v) for k, v in b.items()}


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(8)


def test_din_sharded_matches_local(mesh):
    """Attention model with SHARED tables (target/hist) and [B, L] ragged ids
    must produce the same losses sharded as locally."""
    gen = SyntheticBehaviorSequence(batch_size=128, vocab=2000, seq_len=8, seed=2)
    batches = [J(gen.batch()) for _ in range(3)]

    def model():
        return DIN(emb_dim=8, capacity=1 << 12, hidden=(16,))

    tl = Trainer(model(), Adagrad(lr=0.1), optax.sgd(0.01))
    sl = tl.init(0)
    ts = ShardedTrainer(model(), Adagrad(lr=0.1), optax.sgd(0.01), mesh=mesh)
    ss = ts.init(0)
    for b in batches:
        sl, ml = tl.train_step(sl, b)
        ss, ms = ts.train_step(ss, shard_batch(mesh, b))
        np.testing.assert_allclose(
            float(ml["loss"]), float(ms["loss"]), rtol=2e-2
        )


def test_multitask_sharded_trains(mesh):
    model = MMoE(emb_dim=8, capacity=1 << 12, num_cat=4, num_dense=2,
                 num_experts=2, expert=(16,), tower=(8,))
    tr = ShardedTrainer(model, Adagrad(lr=0.1), optax.adam(2e-3), mesh=mesh)
    st = tr.init(0)
    gen = SyntheticMultiTask(batch_size=256, num_cat=4, num_dense=2, vocab=800,
                             seed=5)
    b0 = shard_batch(mesh, J(gen.batch()))
    losses = []
    for _ in range(10):
        st, m = tr.train_step(st, b0)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]


@pytest.mark.slow
def test_sharded_incremental_checkpoint(tmp_path, mesh):
    from deeprec_tpu.models import WDL

    model = WDL(emb_dim=8, capacity=1 << 12, hidden=(16,), num_cat=4, num_dense=2)
    tr = ShardedTrainer(model, Adagrad(lr=0.1), optax.adam(1e-3), mesh=mesh)
    st = tr.init(0)
    from deeprec_tpu.data import SyntheticCriteo

    gen = SyntheticCriteo(batch_size=256, num_cat=4, num_dense=2, vocab=1000,
                          seed=7)
    b = J(gen.batch())
    sb = shard_batch(mesh, b)
    for _ in range(2):
        st, _ = tr.train_step(st, sb)
    ck = CheckpointManager(str(tmp_path), tr)
    st, _ = ck.save(st)
    for _ in range(2):
        st, _ = tr.train_step(st, sb)
    st, _ = ck.save_incremental(st)

    tr2 = ShardedTrainer(model, Adagrad(lr=0.1), optax.adam(1e-3), mesh=mesh)
    st2 = CheckpointManager(str(tmp_path), tr2).restore()
    _, p1 = tr.eval_step(st, sb)
    _, p2 = tr2.eval_step(st2, sb)
    np.testing.assert_allclose(np.asarray(p1), np.asarray(p2), atol=1e-5)


def test_sharded_cbf_checkpoint_no_sketch_inflation(tmp_path, mesh):
    """CBF admission under sharding must survive save/restore WITHOUT the
    summed global sketch being handed back to every shard (which would
    inflate counts ~Nx per cycle and spuriously admit cold keys)."""
    import optax

    from deeprec_tpu import CBFFilter, EmbeddingVariableOption
    from deeprec_tpu.data import SyntheticCriteo
    from deeprec_tpu.models import WDL

    ev = EmbeddingVariableOption(
        cbf_filter=CBFFilter(filter_freq=50, max_element_size=1 << 12)
    )
    model = WDL(emb_dim=8, capacity=1 << 12, hidden=(16,), num_cat=3,
                num_dense=2, ev=ev)
    tr = ShardedTrainer(model, Adagrad(lr=0.1), optax.adam(1e-3), mesh=mesh)
    st = tr.init(0)
    gen = SyntheticCriteo(batch_size=256, num_cat=3, num_dense=2, vocab=5000,
                          seed=3)
    for _ in range(2):
        st, _ = tr.train_step(st, shard_batch(mesh, J(gen.batch())))

    def total_bloom(state):
        tot = 0
        for ts in state.tables.values():
            if ts.bloom is not None:
                tot += int(np.asarray(ts.bloom).sum())
        return tot

    before = total_bloom(st)
    ck = CheckpointManager(str(tmp_path), tr)
    st, _ = ck.save(st)
    tr2 = ShardedTrainer(model, Adagrad(lr=0.1), optax.adam(1e-3), mesh=mesh)
    st2 = CheckpointManager(str(tmp_path), tr2).restore()
    after = total_bloom(st2)
    # same shard count -> per-shard sketches restored EXACTLY (sub-threshold
    # admission progress survives), definitely no Nx inflation
    assert after == before, (before, after)
    # and a second save/restore cycle must not grow the sketch either
    st2, _ = CheckpointManager(str(tmp_path / "2"), tr2).save(st2)
    st3 = CheckpointManager(str(tmp_path / "2"), tr2).restore()
    assert total_bloom(st3) == after

    # re-shard (8 -> 4): sketches rebuild from admitted rows' freqs — with
    # nothing admitted at filter_freq=50, they come back empty, never inflated
    mesh4 = make_mesh(4)
    tr4 = ShardedTrainer(model, Adagrad(lr=0.1), optax.adam(1e-3), mesh=mesh4)
    st4 = CheckpointManager(str(tmp_path), tr4).restore()
    assert total_bloom(st4) <= before


def test_trainer_evict_tables_local_and_sharded(mesh):
    import optax

    from deeprec_tpu import EmbeddingVariableOption, GlobalStepEvict
    from deeprec_tpu.data import SyntheticCriteo
    from deeprec_tpu.models import WDL

    ev = EmbeddingVariableOption(global_step_evict=GlobalStepEvict(steps_to_live=2))
    model = WDL(emb_dim=8, capacity=1 << 12, hidden=(16,), num_cat=3,
                num_dense=2, ev=ev)
    gen = SyntheticCriteo(batch_size=256, num_cat=3, num_dense=2, vocab=2000,
                          seed=9)
    b_old = J(gen.batch())

    # local trainer: keys touched only at step 0 expire after TTL
    tr = Trainer(model, Adagrad(lr=0.1), optax.adam(1e-3))
    st = tr.init(0)
    st, _ = tr.train_step(st, b_old)
    size_before = sum(
        int(t.size(tr.table_state(st, n))) for n, t in tr.tables.items()
    )
    for _ in range(4):  # advance steps with a disjoint id range
        b_new = J(gen.batch())
        for k in list(b_new):
            if k.startswith("C"):
                b_new[k] = b_new[k] + 1_000_000
        st, _ = tr.train_step(st, b_new)
    st = tr.evict_tables(st)
    # old keys gone, recent keys survive
    sizes = {n: int(t.size(tr.table_state(st, n))) for n, t in tr.tables.items()}
    assert sum(sizes.values()) < size_before + sum(sizes.values())
    ids_old = b_old["C1"][:4]
    emb = tr.tables["C1"].lookup_readonly(tr.table_state(st, "C1"), ids_old)
    # expired keys serve initializer values again (not their trained rows)
    st2, res = tr.tables["C1"].lookup_unique(
        tr.table_state(st, "C1"), ids_old, step=10, train=False
    )
    assert int((np.asarray(res.slot_ix) >= 0).sum()) == 0  # all evicted

    # sharded trainer: evict runs per shard without shape errors
    trs = ShardedTrainer(model, Adagrad(lr=0.1), optax.adam(1e-3), mesh=mesh)
    sts = trs.init(0)
    sts, _ = trs.train_step(sts, shard_batch(mesh, b_old))
    sts = trs.evict_tables(sts, step=100)
    total = sum(
        int(jnp.sum(jax.vmap(t.size)(trs.table_state(sts, n))))
        for n, t in trs.tables.items()
    )
    assert total == 0  # everything older than TTL evicted


def test_bfloat16_table_values():
    t = EmbeddingTable(TableConfig(name="b", dim=8, capacity=256,
                                   value_dtype="bfloat16"))
    s = t.create()
    assert s.values.dtype == jnp.bfloat16
    s, res = t.lookup_unique(s, jnp.array([1, 2, 3], jnp.int32), step=0)
    assert res.embeddings.dtype == jnp.bfloat16
    from deeprec_tpu.optim import GradientDescent, apply_gradients, ensure_slots

    opt = GradientDescent(lr=1.0)
    s = ensure_slots(t, s, opt)
    s = apply_gradients(t, s, opt, res, jnp.ones((3, 8)), step=0)
    # values moved and stayed bf16
    assert s.values.dtype == jnp.bfloat16
    emb = t.lookup_readonly(s, jnp.array([1], jnp.int32))
    assert float(emb.astype(jnp.float32).max()) < 0.5


def test_int64_keys_when_x64_enabled():
    # int64 ids fold to 32-bit hashes but match exactly at full width
    if not jax.config.jax_enable_x64:
        pytest.skip("x64 disabled in this session")
    t = EmbeddingTable(TableConfig(name="k64", dim=4, capacity=128,
                                   key_dtype="int64"))
    s = t.create()
    big = jnp.array([2**40 + 1, 2**40 + 2, 5], jnp.int64)
    s, res = t.lookup_unique(s, big, step=0)
    assert int(t.size(s)) == 3
