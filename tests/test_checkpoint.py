"""Checkpoint/restore coverage — the incr_ckpt_test analog (SURVEY §3.3,
reference python/training/incr_ckpt_test.py): full save, incremental deltas,
failover restore, and restore onto a different topology (elastic re-shard)."""
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from deeprec_tpu.data import SyntheticCriteo
from deeprec_tpu.models import WDL
from deeprec_tpu.optim import Adagrad
from deeprec_tpu.parallel import ShardedTrainer, make_mesh, shard_batch
from deeprec_tpu.training import Trainer
from deeprec_tpu.training.checkpoint import CheckpointManager


def to_jnp(batch):
    return {k: jnp.asarray(v) for k, v in batch.items()}


def small():
    return WDL(emb_dim=8, capacity=1 << 12, hidden=(32,), num_cat=4, num_dense=2)


def gen(seed=3):
    return SyntheticCriteo(batch_size=256, num_cat=4, num_dense=2, vocab=1500,
                           seed=seed)


def test_full_save_restore_roundtrip(tmp_path):
    tr = Trainer(small(), Adagrad(lr=0.1), optax.adam(1e-3))
    st = tr.init(0)
    g = gen()
    batches = [to_jnp(g.batch()) for _ in range(5)]
    for b in batches:
        st, _ = tr.train_step(st, b)
    ck = CheckpointManager(str(tmp_path), tr)
    st, path = ck.save(st)
    assert os.path.exists(os.path.join(path, "manifest.json"))

    # fresh trainer restores and produces identical eval outputs
    tr2 = Trainer(small(), Adagrad(lr=0.1), optax.adam(1e-3))
    ck2 = CheckpointManager(str(tmp_path), tr2)
    st2 = ck2.restore()
    assert int(st2.step) == int(st.step)
    l1, p1 = tr.eval_step(st, batches[0])
    l2, p2 = tr2.eval_step(st2, batches[0])
    np.testing.assert_allclose(np.asarray(p1), np.asarray(p2), atol=1e-6)


def test_incremental_delta_replay(tmp_path):
    tr = Trainer(small(), Adagrad(lr=0.1), optax.adam(1e-3))
    st = tr.init(0)
    g = gen()
    for _ in range(3):
        st, _ = tr.train_step(st, to_jnp(g.batch()))
    ck = CheckpointManager(str(tmp_path), tr)
    st, _ = ck.save(st)  # full @3
    b_extra = to_jnp(g.batch())
    for _ in range(2):
        st, _ = tr.train_step(st, b_extra)
    st, _ = ck.save_incremental(st)  # deltas @5
    # after clearing, another step dirties fewer rows than a full table
    st, _ = tr.train_step(st, b_extra)
    st, _ = ck.save_incremental(st)  # deltas @6

    tr2 = Trainer(small(), Adagrad(lr=0.1), optax.adam(1e-3))
    st2 = CheckpointManager(str(tmp_path), tr2).restore()
    assert int(st2.step) == 6
    l1, p1 = tr.eval_step(st, b_extra)
    l2, p2 = tr2.eval_step(st2, b_extra)
    np.testing.assert_allclose(np.asarray(p1), np.asarray(p2), atol=1e-6)


def test_restore_to_larger_capacity(tmp_path):
    tr = Trainer(small(), Adagrad(lr=0.1), optax.adam(1e-3))
    st = tr.init(0)
    g = gen()
    for _ in range(3):
        st, _ = tr.train_step(st, to_jnp(g.batch()))
    st, _ = CheckpointManager(str(tmp_path), tr).save(st)

    big = WDL(emb_dim=8, capacity=1 << 13, hidden=(32,), num_cat=4, num_dense=2)
    tr2 = Trainer(big, Adagrad(lr=0.1), optax.adam(1e-3))
    st2 = CheckpointManager(str(tmp_path), tr2).restore()
    b = to_jnp(g.batch())
    _, p1 = tr.eval_step(st, b)
    _, p2 = tr2.eval_step(st2, b)
    np.testing.assert_allclose(np.asarray(p1), np.asarray(p2), atol=1e-6)


def test_sharded_save_restore_and_reshard(tmp_path):
    mesh = make_mesh(8)
    tr = ShardedTrainer(small(), Adagrad(lr=0.1), optax.adam(1e-3), mesh=mesh)
    st = tr.init(0)
    g = gen()
    batches = [to_jnp(g.batch()) for _ in range(3)]
    for b in batches:
        st, _ = tr.train_step(st, shard_batch(mesh, b))
    st, _ = CheckpointManager(str(tmp_path), tr).save(st)

    # restore onto a 4-device mesh (elastic scale-down)
    mesh4 = make_mesh(4)
    tr4 = ShardedTrainer(small(), Adagrad(lr=0.1), optax.adam(1e-3), mesh=mesh4)
    st4 = CheckpointManager(str(tmp_path), tr4).restore()
    _, p8 = tr.eval_step(st, shard_batch(mesh, batches[0]))
    _, p4 = tr4.eval_step(st4, shard_batch(mesh4, batches[0]))
    np.testing.assert_allclose(np.asarray(p8), np.asarray(p4), atol=1e-5)

    # and from sharded down to single-device
    tr1 = Trainer(small(), Adagrad(lr=0.1), optax.adam(1e-3))
    st1 = CheckpointManager(str(tmp_path), tr1).restore()
    _, p1 = tr1.eval_step(st1, batches[0])
    np.testing.assert_allclose(np.asarray(p8), np.asarray(p1), atol=1e-5)


def test_dataset_state_rides_checkpoints(tmp_path):
    """Input positions checkpoint WITH the model (the reference stores
    KafkaDataset offsets in TF checkpoints — kafka_dataset_op.cc
    SaveInternal): register readers with the CheckpointManager, save,
    restore into FRESH readers, and consumption resumes exactly."""
    import optax

    from deeprec_tpu.data import SyntheticCriteo, WorkQueue
    from deeprec_tpu.models import WDL
    from deeprec_tpu.optim import Adagrad
    from deeprec_tpu.training import Trainer

    model = WDL(emb_dim=8, capacity=1 << 10, hidden=(16,), num_cat=3,
                num_dense=2)
    tr = Trainer(model, Adagrad(lr=0.1), optax.adam(1e-3))
    st = tr.init(0)
    gen = SyntheticCriteo(batch_size=64, num_cat=3, num_dense=2, vocab=500,
                          seed=9)
    q = WorkQueue([f"file{i}" for i in range(10)], shuffle=False)
    for _ in range(4):
        q.take()
    for _ in range(2):
        st, _ = tr.train_step(
            st, {k: jnp.asarray(v) for k, v in gen.batch().items()})

    ck = CheckpointManager(str(tmp_path), tr, datasets={"queue": q})
    st, _ = ck.save(st)
    for _ in range(2):
        q.take()  # post-save progress: NOT saved

    q2 = WorkQueue([f"file{i}" for i in range(10)], shuffle=False)
    tr2 = Trainer(model, Adagrad(lr=0.1), optax.adam(1e-3))
    ck2 = CheckpointManager(str(tmp_path), tr2, datasets={"queue": q2})
    st2 = ck2.restore()
    assert int(st2.step) == int(st.step)
    # the restored queue resumes at the SAVED position (file4), replaying
    # the post-save items
    assert q2.take() == "file4"

    # incremental saves carry positions too, and restore uses the NEWEST
    st, _ = tr.train_step(
        st, {k: jnp.asarray(v) for k, v in gen.batch().items()})
    st, _ = ck.save_incremental(st)
    q3 = WorkQueue([f"file{i}" for i in range(10)], shuffle=False)
    ck3 = CheckpointManager(str(tmp_path), tr2, datasets={"queue": q3})
    ck3.restore()
    assert q3.take() == "file6"  # position at the incremental save

    # a checkpoint from BEFORE datasets existed restores cleanly (file
    # missing -> skipped)
    import os as _os

    for d in sorted(_os.listdir(str(tmp_path))):
        p = _os.path.join(str(tmp_path), d, "datasets.part00000.json")
        if _os.path.exists(p):
            _os.remove(p)
    q4 = WorkQueue([f"file{i}" for i in range(10)], shuffle=False)
    ck4 = CheckpointManager(str(tmp_path), tr2, datasets={"queue": q4})
    ck4.restore()
    assert q4.take() == "file0"  # untouched


def test_delta_replay_bucketed_preserves_scalar_slots(tmp_path):
    """Delta replay pads row counts to power-of-two buckets (compile-shape
    stability at serving cadence) — per-TABLE arrays (Adam's scalar beta
    powers, [1,1]) must pass through unpadded, and the replayed state must
    train on (shapes identical to the compiled step)."""
    import optax

    from deeprec_tpu.data import SyntheticCriteo
    from deeprec_tpu.models import WDL
    from deeprec_tpu.optim import Adam
    from deeprec_tpu.training import Trainer
    from deeprec_tpu.training.checkpoint import CheckpointManager

    model = WDL(emb_dim=8, capacity=1 << 10, hidden=(16,), num_cat=3,
                num_dense=2)
    tr = Trainer(model, Adam(lr=0.01), optax.adam(1e-3))
    st = tr.init(0)
    gen = SyntheticCriteo(batch_size=37, num_cat=3, num_dense=2, vocab=300)
    put = tr.stage_batch
    st, _ = tr.train_step(st, put(gen.batch()))
    ck = CheckpointManager(str(tmp_path), tr)
    st, _ = ck.save(st)
    # touch an odd, non-power-of-two number of rows, then delta-save
    st, _ = tr.train_step(st, put(gen.batch()))
    st, _ = ck.save_incremental(st)

    restored = ck.restore()
    for bname, ts in restored.tables.items():
        for sname, arr in ts.slots.items():
            ref = st.tables[bname].slots[sname]
            assert arr.shape == ref.shape, (bname, sname, arr.shape)
    # replayed state steps fine under the already-compiled train step
    out, _ = tr.train_step(restored, put(gen.batch()))
    assert out.step == st.step + 1
