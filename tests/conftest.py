"""Test harness: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's strategy of testing distributed behavior with
in-process fake clusters (SURVEY.md §4): jax's host-platform device-count
flag gives us 8 fake devices so sharding/collective paths compile and run
without TPU hardware.
"""
import os

# Force CPU: the environment may carry JAX_PLATFORMS=axon (the TPU tunnel),
# and tests must run on the virtual mesh regardless.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402  (import after env setup)

jax.config.update("jax_threefry_partitionable", True)
