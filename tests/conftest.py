"""Test harness: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's strategy of testing distributed behavior with
in-process fake clusters (SURVEY.md §4): jax's host-platform device-count
flag gives us 8 fake devices so sharding/collective paths compile and run
without TPU hardware.

Speed: the default run excludes tests marked ``slow`` (multi-process
launches, the largest compile grids) so `pytest -q` gives a quick green;
``DEEPREC_FULL_TESTS=1`` runs everything (any explicit ``-m`` expression
also takes over, e.g. ``-m 'slow or not slow'``). The XLA PERSISTENT
compilation cache is DISABLED: jax 0.4.37's CPU PJRT client
intermittently aborts/segfaults DESERIALIZING a cached executable
(compile path fine, reload path fatal; upstream serialization bug,
reproduced on pre-change code). A fresh per-run cache dir (the previous
mitigation) only avoided the cross-run reloads — within one run a later
test recompiling the same program from a fresh Trainer still hit the
reload path and died ~1 in 4 runs of the checkpoint-corruption module.
With no cross-run reuse the per-run cache bought nothing but that crash:
the in-memory jit cache still dedups compiles inside each test module,
which is where almost all of the win was anyway (measured +~20% on the
heaviest recompiling modules, well inside the tier-1 budget).
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# Also exported to subprocess workers (supervisor/launch tests): a spawned
# worker inheriting a shared cache dir would reload its predecessor's
# executables — the same fatal path.
os.environ["JAX_ENABLE_COMPILATION_CACHE"] = "false"
os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)

import pytest  # noqa: E402

import jax  # noqa: E402  (import after env setup)

# Force CPU for real: the TPU tunnel's sitecustomize hook (PYTHONPATH)
# registers an 'axon' PJRT plugin in every interpreter AND overrides
# jax_platforms to prefer it, so the env vars above aren't enough — when
# the tunnel is wedged, the plugin's backend init hangs even a CPU-only
# test run. Deregister the factory and restore the platform selection
# before any backend initializes (both no-ops when the hook is absent).
try:  # private jax internals — a rename must degrade, not break collection
    from jax._src import xla_bridge as _xb  # noqa: E402

    _xb._backend_factories.pop("axon", None)
except (ImportError, AttributeError):
    pass
jax.config.update("jax_platforms", "cpu")

jax.config.update("jax_threefry_partitionable", True)


def pytest_collection_modifyitems(config, items):
    """Skip slow-marked tests by default; DEEPREC_FULL_TESTS=1 (or an
    explicit -m) runs the full grid."""
    if os.environ.get("DEEPREC_FULL_TESTS") == "1" or config.option.markexpr:
        return
    skip = pytest.mark.skip(
        reason="slow; set DEEPREC_FULL_TESTS=1 (or -m slow) to run"
    )
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)
