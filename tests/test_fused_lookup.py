"""Fused Pallas lookup kernels vs XLA oracles (interpret mode on CPU).

Covers the three kernels in ops/fused_lookup.py — DMA gather, fused
gather+combine, stochastic-rounded scatter-apply — plus the XLA
stochastic_round utility's statistical contract.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeprec_tpu.ops.fused_lookup import (
    apply_rows_sr,
    fused_gather_combine,
    gather_rows,
    stochastic_round,
)


def test_gather_rows_matches_oracle():
    rng = np.random.default_rng(0)
    vals = jnp.asarray(rng.normal(0, 1, (512, 128)).astype(np.float32))
    ix = jnp.asarray(rng.integers(0, 512, 128), jnp.int32)
    out = gather_rows(vals, ix, block=16, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(vals)[np.asarray(ix)], rtol=1e-6
    )


def test_gather_rows_clamps_and_pads():
    vals = jnp.arange(64, dtype=jnp.float32).reshape(8, 8) * jnp.ones((8, 8))
    # n=6 is NOT a multiple of block=8: exercises the pad-and-slice path.
    ix = jnp.array([-5, 100, 3, 0, 7, 2], jnp.int32)
    out = gather_rows(vals, ix, block=8, interpret=True)
    expect = np.asarray(vals)[np.clip(np.asarray(ix), 0, 7)]
    assert out.shape == (6, 8)
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-6)


@pytest.mark.parametrize("combiner", ["sum", "mean"])
def test_fused_gather_combine_matches_oracle(combiner):
    rng = np.random.default_rng(1)
    C, D, B, L = 256, 16, 12, 5  # B=12 not a multiple of block_b=8
    vals = jnp.asarray(rng.normal(0, 1, (C, D)).astype(np.float32))
    row_ix = rng.integers(-1, C, (B, L)).astype(np.int32)  # -1 = pad
    n = np.maximum((row_ix >= 0).sum(1, keepdims=True), 1)
    w = np.where(row_ix >= 0, 1.0 if combiner == "sum" else 1.0 / n, 0.0)
    out = fused_gather_combine(
        vals, jnp.asarray(row_ix), jnp.asarray(w, jnp.float32),
        block_b=8, interpret=True,
    )
    e = np.asarray(vals)[np.clip(row_ix, 0, C - 1)]
    expect = (e * w[..., None]).sum(1)
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-5, atol=1e-6)


def test_apply_rows_f32_matches_oracle_interpret():
    rng = np.random.default_rng(2)
    C, D, U = 64, 8, 10  # U=10 pads to 16
    vals = jnp.asarray(rng.normal(0, 1, (C, D)).astype(np.float32))
    slot_ix = jnp.asarray([3, -1, 7, 0, 63, 5, -1, 9, 11, 2], jnp.int32)
    new_rows = jnp.asarray(rng.normal(0, 1, (U, D)).astype(np.float32))
    out = apply_rows_sr(vals, slot_ix, new_rows, jnp.int32(0),
                        block=8, interpret=True)
    expect = np.asarray(vals).copy()
    for u, s in enumerate(np.asarray(slot_ix)):
        if s >= 0:
            expect[s] = np.asarray(new_rows)[u]
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-6)


def test_apply_rows_bf16_rounds_to_neighbors_interpret():
    """bf16 writes must land on one of the two bf16 neighbors of the f32
    value (stochastic rounding), and skipped rows stay untouched."""
    C, D, U = 32, 8, 8
    vals = jnp.zeros((C, D), jnp.bfloat16)
    slot_ix = jnp.asarray([0, 1, 2, 3, -1, 5, 6, 7], jnp.int32)
    x = np.float32(1.0 + 1e-3)  # not bf16-representable
    new_rows = jnp.full((U, D), x, jnp.float32)
    out = apply_rows_sr(vals, slot_ix, new_rows, jnp.int32(7),
                        block=8, interpret=True)
    out = np.asarray(out, np.float32)
    lo = np.float32(jnp.bfloat16(1.0))
    hi = np.float32(np.nextafter(np.float32(lo), np.float32(2)))  # next bf16
    hi = np.float32(jnp.asarray(lo, jnp.float32) + 2.0 ** -7)
    written = out[[0, 1, 2, 3, 5, 6, 7]]
    assert np.isin(written, [lo, hi]).all(), np.unique(written)
    np.testing.assert_allclose(out[4], 0.0)


def test_stochastic_round_is_unbiased_and_exact_on_representable():
    key = jax.random.PRNGKey(0)
    # Exactly-representable values never move.
    x = jnp.asarray([0.0, 1.0, -2.5, 0.15625], jnp.float32)
    np.testing.assert_array_equal(
        np.asarray(stochastic_round(x, key), np.float32), np.asarray(x)
    )
    # Unrepresentable values round to a neighbor, unbiased in expectation.
    v = np.float32(1.0 + 2.0 ** -9)  # 1/4 of the way between 1.0 and 1+2^-7
    xs = jnp.full((200_000,), v, jnp.float32)
    r = np.asarray(stochastic_round(xs, key), np.float32)
    assert set(np.unique(r)) <= {np.float32(1.0), np.float32(1.0 + 2.0 ** -7)}
    mean = r.mean()
    np.testing.assert_allclose(mean, v, rtol=3e-4)


def test_kernel_config_wiring_end_to_end():
    """kernel="pallas" tables train identically to kernel="xla" off-TPU
    (the fallback is the same XLA program); exercises the full wiring
    through lookup_unique + apply_gradients."""
    import dataclasses

    from deeprec_tpu import EmbeddingTable, TableConfig
    from deeprec_tpu.optim import Adagrad, apply_gradients, ensure_slots

    res_by_kernel = {}
    for kernel in ("xla", "pallas"):
        cfg = TableConfig(name="k", dim=8, capacity=128, kernel=kernel)
        t = EmbeddingTable(cfg)
        opt = Adagrad(lr=0.5)
        s = ensure_slots(t, t.create(), opt)
        ids = jnp.asarray([5, 9, 5, 13], jnp.int32)
        for step in range(3):
            s, res = t.lookup_unique(s, ids, step=step)
            s = apply_gradients(t, s, opt, res,
                                jnp.ones_like(res.embeddings), step=step)
        res_by_kernel[kernel] = np.asarray(
            t.lookup_readonly(s, jnp.asarray([5, 9, 13], jnp.int32))
        )
    np.testing.assert_allclose(
        res_by_kernel["xla"], res_by_kernel["pallas"], rtol=1e-6
    )


def test_bf16_table_sr_preserves_small_updates_in_expectation():
    """A bf16 table with updates far below ulp/2 must still drift: SR keeps
    E[stored] == target where round-to-nearest would freeze at 1.0."""
    from deeprec_tpu import EmbeddingTable, TableConfig
    from deeprec_tpu.optim import GradientDescent, apply_gradients, ensure_slots

    cfg = TableConfig(name="sr", dim=128, capacity=1024,
                      value_dtype="bfloat16",
                      ev=__import__("deeprec_tpu").EmbeddingVariableOption(
                          init=__import__("deeprec_tpu").InitializerOption(
                              kind="constant", constant=1.0)))
    t = EmbeddingTable(cfg)
    opt = GradientDescent(lr=1.0)
    s = ensure_slots(t, t.create(), opt)
    ids = jnp.arange(256, dtype=jnp.int32)
    # each step subtracts 1e-4 — ulp(1.0) in bf16 is 2^-7 ≈ 7.8e-3, so RTN
    # would never move off 1.0; SR moves the mean by ~1e-4 per step.
    g = jnp.full((256, 128), 1e-4, jnp.float32)
    for step in range(200):
        s, res = t.lookup_unique(s, ids, step=step)
        s = apply_gradients(t, s, opt, res, g, step=step)
    mean = float(jnp.mean(s.values[:].astype(jnp.float32)
                          [np.asarray(t.occupied(s))]))
    expect = 1.0 - 200 * 1e-4  # 0.98
    assert abs(mean - expect) < 4e-3, mean


def test_gather_rows_xla_fallback_identical():
    """Off-TPU the public entry points use XLA with identical semantics."""
    rng = np.random.default_rng(3)
    vals = jnp.asarray(rng.normal(0, 1, (128, 32)).astype(np.float32))
    ix = jnp.asarray(rng.integers(0, 128, 24), jnp.int32)
    a = gather_rows(vals, ix)  # XLA path on CPU
    b = gather_rows(vals, ix, interpret=True)  # Pallas interpreter
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


def test_gather_rows_pair_bf16_matches_oracle():
    """bf16 pair-granule gather == XLA gather, including odd indices,
    duplicates, clamping, and non-block-multiple n."""
    from deeprec_tpu.ops.fused_lookup import gather_rows_pair

    rng = np.random.default_rng(3)
    vals = jnp.asarray(
        rng.normal(0, 1, (256, 128)).astype(np.float32)
    ).astype(jnp.bfloat16)
    ix = jnp.asarray([1, 1, 0, 255, 254, 7, -3, 300, 13, 13, 12, 200, 77],
                     jnp.int32)
    out = gather_rows_pair(vals, ix, block=8, interpret=True)
    expect = np.asarray(vals)[np.clip(np.asarray(ix), 0, 255)]
    assert out.dtype == jnp.bfloat16 and out.shape == (13, 128)
    np.testing.assert_array_equal(np.asarray(out), expect)

    # dispatch: gather_rows(pair_kernels=True) routes bf16 here under
    # interpret, and to XLA when pair_kernels=False
    out2 = gather_rows(vals, ix, block=8, interpret=True, pair_kernels=True)
    np.testing.assert_array_equal(np.asarray(out2), expect)


def test_apply_rows_sr_pair_bf16_matches_semantics():
    """Pair-granule RMW scatter: written rows round to a bf16 neighbor of
    the f32 target, untouched rows (including the OTHER half of a touched
    granule) are bit-identical, skips (<0) skip, and consecutive updates
    sharing a granule both land."""
    from deeprec_tpu.ops.fused_lookup import apply_rows_sr_pair

    rng = np.random.default_rng(4)
    vals = jnp.asarray(
        rng.normal(0, 1, (64, 128)).astype(np.float32)
    ).astype(jnp.bfloat16)
    before = np.asarray(vals).copy()
    # rows 6 and 7 share a granule; 11 is odd-half-only; 20 even-half-only
    slot_ix = jnp.asarray([6, 7, 11, 20, -1], jnp.int32)
    new = jnp.asarray(rng.normal(0, 1, (5, 128)).astype(np.float32))
    out = np.asarray(
        apply_rows_sr_pair(vals, slot_ix, new, jnp.int32(9), interpret=True)
    )
    newf = np.asarray(new, np.float32)
    for row, target in ((6, 0), (7, 1), (11, 2), (20, 3)):
        lo = np.asarray(jnp.asarray(newf[target]).astype(jnp.bfloat16))
        # stochastic rounding: each element equals a bf16 neighbor of the
        # f32 value (nextafter up or the truncation down)
        got = out[row]
        down = np.asarray(
            jax.lax.bitcast_convert_type(
                jax.lax.bitcast_convert_type(
                    jnp.asarray(newf[target]), jnp.uint32
                ) & jnp.uint32(0xFFFF0000), jnp.float32
            ).astype(jnp.bfloat16)
        )
        up = np.asarray(
            jax.lax.bitcast_convert_type(
                (jax.lax.bitcast_convert_type(
                    jnp.asarray(newf[target]), jnp.uint32
                ) & jnp.uint32(0xFFFF0000)) + jnp.uint32(0x10000),
                jnp.float32,
            ).astype(jnp.bfloat16)
        )
        ok = (got == down) | (got == up)
        assert ok.all(), (row, np.nonzero(~ok))
    # untouched rows — ESPECIALLY granule-mates 10 and 21 — unchanged
    untouched = [i for i in range(64) if i not in (6, 7, 11, 20)]
    np.testing.assert_array_equal(out[untouched], before[untouched])


@pytest.mark.parametrize("combiner", ["sum", "mean"])
def test_fused_gather_combine_pair_bf16(combiner):
    """bf16 pair-granule bag pooling == XLA oracle (weights carry the
    combiner; skips at -1; odd/even slots both land)."""
    rng = np.random.default_rng(6)
    vals = jnp.asarray(
        rng.normal(0, 1, (128, 128)).astype(np.float32)
    ).astype(jnp.bfloat16)
    B, L = 6, 5
    ix = rng.integers(-1, 128, (B, L)).astype(np.int32)
    n = np.maximum((ix >= 0).sum(axis=1), 1)
    w = np.where(ix >= 0, 1.0 / n[:, None] if combiner == "mean" else 1.0,
                 0.0).astype(np.float32)
    out = fused_gather_combine(
        vals, jnp.asarray(ix), jnp.asarray(w), block_b=4, interpret=True,
        pair_kernels=True,
    )
    e = np.asarray(vals, np.float32)[np.clip(ix, 0, 127)]
    expect = (e * w[..., None]).sum(axis=1)
    np.testing.assert_allclose(np.asarray(out), expect, rtol=2e-2, atol=2e-2)
