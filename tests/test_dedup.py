"""Hash dedup engine (ops/dedup.py) + unique budgets through the hot path.

Three layers, matching the test_train_steps standard (exact on table ints):

  * engine vs `jnp.unique`: same unique set / counts / inverse semantics
    (hash order instead of sorted order), pad-sentinel collapse, defined
    overflow saturation past the budget, and all of it under `vmap` (the
    stacked-bundle layout).
  * budgeted `lookup_unique` vs the legacy path: identical per-key table
    content when the budget covers the batch; default-serving + no-update
    semantics for overflowed ids when it does not.
  * budgeted trainers: `train_steps` scan == sequential steps exactly on
    table ints for Trainer and ShardedTrainer (allgather and a2a), plus
    the auto-budget measurement loop (update_budgets EMA engage).
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from deeprec_tpu.config import TableConfig
from deeprec_tpu.data import SyntheticCriteo
from deeprec_tpu.embedding.table import EmbeddingTable, empty_key
from deeprec_tpu.models import WDL
from deeprec_tpu.optim import Adagrad
from deeprec_tpu.ops import dedup
from deeprec_tpu.training import Trainer, stack_batches

SENT = int(np.iinfo(np.int32).min)


def _collapse(ids, pad=-1):
    return np.where(ids == pad, SENT, ids).astype(np.int32)


# ------------------------------------------------------------ engine level


def test_hash_dedup_matches_jnp_unique_semantics():
    rng = np.random.default_rng(0)
    for trial in range(4):
        N = int(rng.integers(64, 2000))
        ids = rng.integers(0, int(rng.integers(8, N)), size=N).astype(np.int32)
        ids[rng.random(N) < 0.25] = -1  # padding
        flat = _collapse(ids)
        size = dedup.resolve_size(N, N)  # no-overflow budget
        u, inv, c, ovf = map(
            np.asarray, dedup.hash_dedup(jnp.asarray(flat), size, sentinel=SENT)
        )
        ref = np.unique(flat[flat != SENT])
        # same unique set (hash order, not sorted), zero overflow
        assert np.array_equal(np.sort(u[u != SENT]), ref)
        assert ovf == 0
        # sentinel bucket reserved at index 0 with no counts
        assert u[0] == SENT and c[0] == 0
        # inverse reconstructs every real position; pads point at bucket 0
        real = flat != SENT
        assert np.array_equal(u[inv[real]], flat[real])
        assert (inv[~real] == 0).all()
        # counts == occurrences, exactly
        for uu in ref:
            assert c[u == uu][0] == (flat == uu).sum()
        # count mass equals real positions (pads contribute nothing)
        assert c.sum() == real.sum()


def test_hash_dedup_overflow_saturation():
    """More distinct ids than budget: exactly budget-many survive, the rest
    are counted in overflow and their positions collapse onto the sentinel
    bucket (inverse 0) — never onto another id's row."""
    N = 512
    flat = np.arange(N, dtype=np.int32)  # all distinct
    size = dedup.resolve_size(100, N)
    u, inv, c, ovf = map(
        np.asarray, dedup.hash_dedup(jnp.asarray(flat), size, sentinel=SENT)
    )
    kept = u[u != SENT]
    assert len(kept) == size - 1
    assert ovf == N - len(kept)
    surv = inv > 0
    assert np.array_equal(u[inv[surv]], flat[surv])
    assert (inv[~surv] == 0).all()
    assert c.sum() == surv.sum()


def test_hash_dedup_under_vmap():
    rng = np.random.default_rng(3)
    T, N = 5, 384
    ids = rng.integers(0, 60, size=(T, N)).astype(np.int32)
    ids[rng.random((T, N)) < 0.2] = -1
    flat = _collapse(ids)
    size = dedup.resolve_size(N, N)
    vu, vi, vc, vo = jax.vmap(
        lambda f: dedup.hash_dedup(f, size, sentinel=SENT)
    )(jnp.asarray(flat))
    for t in range(T):
        u, inv, c, o = (np.asarray(x[t]) for x in (vu, vi, vc, vo))
        su, si, sc, so = map(
            np.asarray,
            dedup.hash_dedup(jnp.asarray(flat[t]), size, sentinel=SENT),
        )
        np.testing.assert_array_equal(u, su)
        np.testing.assert_array_equal(inv, si)
        np.testing.assert_array_equal(c, sc)
        assert o == so == 0


def test_hash_dedup_weighted_counts():
    """Owner-side dedup segment-sums exchanged counts via `weights`."""
    flat = np.array([7, 7, 9, SENT, 9, 7], np.int32)
    w = np.array([2, 3, 5, 100, 1, 4], np.int32)
    size = dedup.resolve_size(6, 6)
    u, inv, c, _ = map(
        np.asarray,
        dedup.hash_dedup(
            jnp.asarray(flat), size, sentinel=SENT, weights=jnp.asarray(w)
        ),
    )
    assert c[u == 7][0] == 2 + 3 + 4
    assert c[u == 9][0] == 5 + 1
    assert c[0] == 0  # sentinel weight never lands


# ------------------------------------------------------------ table level


def _table(**kw):
    return EmbeddingTable(TableConfig(name="t", dim=4, capacity=1 << 10, **kw))


def test_lookup_unique_budget_matches_legacy_per_key():
    """With a covering budget, the budgeted lookup builds the same table as
    the legacy sort-unique path: same key set, per-key freq/version/values,
    and per-position embeddings."""
    t = _table()
    rng = np.random.default_rng(1)
    ids = jnp.asarray(rng.integers(0, 50, size=(16, 4)).astype(np.int32))
    s0, r0 = t.lookup_unique(t.create(), ids, step=1)
    size = dedup.resolve_size(64, 64)
    s1, r1 = t.lookup_unique(t.create(), ids, step=1, unique_size=size)
    k0, k1 = np.asarray(s0.keys), np.asarray(s1.keys)
    occ0, occ1 = k0 != SENT, k1 != SENT
    assert set(k0[occ0].tolist()) == set(k1[occ1].tolist())
    f0 = dict(zip(k0.tolist(), np.asarray(s0.freq).tolist()))
    f1 = dict(zip(k1.tolist(), np.asarray(s1.freq).tolist()))
    for k in k0[occ0].tolist():
        assert f0[k] == f1[k]
    # per-position embeddings identical across dedup orders
    e0 = np.asarray(r0.embeddings)[np.asarray(r0.inverse)]
    e1 = np.asarray(r1.embeddings)[np.asarray(r1.inverse)]
    np.testing.assert_allclose(e0, e1, atol=0)
    # telemetry counters recorded on both paths
    assert int(s1.dedup_unique) == int(s0.dedup_unique) == occ0.sum()
    assert int(s1.dedup_ids) == ids.size


def test_lookup_unique_budget_overflow_serves_default():
    """Ids past the budget: counted in dedup_overflow, not inserted, and
    their positions serve the blocked default (0.0) for the step."""
    cfg = TableConfig(name="t", dim=4, capacity=1 << 10)
    t = EmbeddingTable(cfg)
    ids = jnp.arange(100, dtype=jnp.int32)
    size = dedup.resolve_size(10, 100)
    s, r = t.lookup_unique(t.create(), ids, step=0, unique_size=size)
    kept = size - 1
    assert int(s.dedup_overflow) == 100 - kept
    assert int(t.size(s)) == kept
    inv = np.asarray(r.inverse)
    emb = np.asarray(r.embeddings)[inv]
    dropped = inv == 0
    assert dropped.sum() == 100 - kept
    np.testing.assert_array_equal(emb[dropped], 0.0)
    # non-dropped ids get real (initializer) embeddings
    assert np.abs(emb[~dropped]).sum() > 0


def test_table_budget_never_applies_to_eval_lookups():
    """An int cfg.unique_budget budgets TRAIN lookups only: eval/serving
    must read resident keys exactly (and overflow on read-only state would
    be invisible to the counters)."""
    t = _table(unique_budget=8)
    ids = jnp.arange(20, dtype=jnp.int32)
    s, _ = t.lookup_unique(t.create(), ids, step=0)  # train: budget applies
    assert int(s.dedup_overflow) > 0
    _, r = t.lookup_unique(s, ids, train=False)  # eval: exact U=N
    assert len(np.unique(np.asarray(r.inverse))) == 20


def test_trainer_budget_typo_rejected():
    """The trainer-wide override shares the config grammar check — an
    unvalidated typo would silently mean "auto"."""
    with pytest.raises(ValueError, match="unique_budget"):
        Trainer(_model(), Adagrad(lr=0.1), unique_budget="Off")


def test_default_unique_size_resolution():
    """cfg.unique_budget routes the no-argument lookup: int engages the
    hash engine at that size, None/"auto"/"off" keep legacy U=N."""
    assert _table().default_unique_size(128) is None
    assert _table(unique_budget="auto").default_unique_size(128) is None
    assert _table(unique_budget="off").default_unique_size(128) is None
    sz = _table(unique_budget=32).default_unique_size(128)
    assert sz == dedup.resolve_size(32, 128)
    # resolve_size caps at the no-overflow size and reserves the sentinel
    assert dedup.resolve_size(10_000, 64) == dedup.resolve_size(64, 64)


# ---------------------------------------------------------- trainer level


def _model():
    return WDL(emb_dim=8, capacity=1 << 12, hidden=(16,), num_cat=4,
               num_dense=2)


def _batches(K=4, batch_size=64, seed=7):
    gen = SyntheticCriteo(batch_size=batch_size, num_cat=4, num_dense=2,
                          vocab=500, seed=seed)
    batches = [{k: jnp.asarray(v) for k, v in gen.batch().items()}
               for _ in range(K)]
    for t in range(1, K):
        batches[t]["C1"] = batches[t]["C1"] + jnp.int32(10_000 * t)
    return batches


def _assert_tables_exact(s_a, s_b):
    for bname in s_a.tables:
        a, b = s_a.tables[bname], s_b.tables[bname]
        np.testing.assert_array_equal(np.asarray(a.keys), np.asarray(b.keys))
        np.testing.assert_array_equal(np.asarray(a.freq), np.asarray(b.freq))
        np.testing.assert_array_equal(
            np.asarray(a.version), np.asarray(b.version)
        )
        np.testing.assert_allclose(
            np.asarray(a.values), np.asarray(b.values), atol=1e-5
        )


def test_budgeted_train_matches_legacy_per_key():
    """Fixed covering budget vs legacy: same loss stream and same per-key
    table content after training (layouts differ — hash vs sorted order)."""
    batches = _batches()
    tr0 = Trainer(_model(), Adagrad(lr=0.1), optax.adam(2e-3))
    tr1 = Trainer(_model(), Adagrad(lr=0.1), optax.adam(2e-3),
                  unique_budget=64)
    s0, s1 = tr0.init(0), tr1.init(0)
    for b in batches:
        s0, m0 = tr0.train_step(s0, b)
        s1, m1 = tr1.train_step(s1, b)
        np.testing.assert_allclose(
            float(m0["loss"]), float(m1["loss"]), atol=1e-6
        )
    for bname in s0.tables:
        a, b = s0.tables[bname], s1.tables[bname]
        ka, kb = np.asarray(a.keys), np.asarray(b.keys)
        for t in range(ka.shape[0] if ka.ndim > 1 else 1):
            k0 = ka[t] if ka.ndim > 1 else ka
            k1 = kb[t] if kb.ndim > 1 else kb
            assert set(k0[k0 != SENT].tolist()) == set(k1[k1 != SENT].tolist())


def test_train_steps_scan_parity_with_budget():
    """K-step scan == K sequential steps, exact on table ints, with the
    hash dedup engine engaged (fixed budget)."""
    K = 4
    batches = _batches(K)
    tr = Trainer(_model(), Adagrad(lr=0.1), optax.adam(2e-3),
                 unique_budget=64)
    s_seq = tr.init(0)
    seq_losses = []
    for b in batches:
        s_seq, m = tr.train_step(s_seq, b)
        seq_losses.append(float(m["loss"]))
    s_scan, mets = tr.train_steps(tr.init(0), stack_batches(batches))
    assert mets["loss"].shape == (K,)
    np.testing.assert_allclose(np.asarray(mets["loss"]), seq_losses,
                               atol=1e-5)
    _assert_tables_exact(s_scan, s_seq)
    # dedup telemetry accumulates identically through the scan carry
    for bname in s_scan.tables:
        np.testing.assert_array_equal(
            np.asarray(s_scan.tables[bname].dedup_unique),
            np.asarray(s_seq.tables[bname].dedup_unique),
        )


def test_auto_budget_measure_then_engage():
    """"auto": the first window runs at U=N seeding the counters; after
    update_budgets the quantized EMA budget engages, training continues,
    and stats report per-table fractions."""
    batches = _batches()
    tr = Trainer(_model(), Adagrad(lr=0.1), unique_budget="auto")
    s = tr.init(0)
    for b in batches:
        s, _ = tr.train_step(s, b)
    assert not tr._auto_frac  # not engaged yet
    stats = tr.dedup_stats(s)
    assert all(0 < v["unique_fraction"] <= 1 for v in stats.values())
    s, report = tr.update_budgets(s)
    assert tr._auto_frac  # engaged
    for rep in report.values():
        assert 0 < rep["unique_budget_fraction"] <= 1
    # counters were reset
    for ts in s.tables.values():
        assert int(np.sum(np.asarray(ts.dedup_ids))) == 0
    before = {k: v for k, v in tr._auto_frac.items()}
    for b in batches:
        s, m = tr.train_step(s, b)
    assert np.isfinite(float(m["loss"]))
    # overflow stays 0: the budget's slack covers the measured fraction
    assert all(
        v["dedup_overflow"] == 0 for v in tr.dedup_stats(s).values()
    )
    assert tr._auto_frac == before  # no drift without update_budgets


def test_auto_budget_engages_compiled_step_and_eval_stays_exact():
    """update_budgets must reach ALREADY-COMPILED executables: train on
    low-unique batches (tight budget), then feed a high-unique batch of
    the same shape — the budgeted trace must overflow, proving the jit
    caches were rebuilt (a stale executable would still run at U=N).
    Eval lookups on the same trainer stay exact at U=N."""
    gen = SyntheticCriteo(batch_size=64, num_cat=4, num_dense=2, vocab=500,
                          seed=1)
    low = {k: jnp.asarray(v) for k, v in gen.batch().items()}
    high = {k: jnp.asarray(v) for k, v in gen.batch().items()}
    for c in range(1, 5):
        low[f"C{c}"] = jnp.asarray(np.arange(64) % 4 + 1000 * c, jnp.int32)
        high[f"C{c}"] = jnp.asarray(np.arange(64) + 1000 * c, jnp.int32)
    tr = Trainer(_model(), Adagrad(lr=0.1), unique_budget="auto")
    s = tr.init(0)
    s, _ = tr.train_step(s, low)  # compiles the step at U=N
    s, rep = tr.update_budgets(s)  # ~0.06 fraction -> tight budget bucket
    assert all(r["unique_budget_fraction"] < 0.5 for r in rep.values())
    s, _ = tr.train_step(s, high)  # same avals as the pre-budget trace
    ovf = sum(v["dedup_overflow"] for v in tr.dedup_stats(s).values())
    assert ovf > 0  # the budgeted executable really ran
    # Eval/serving is never budgeted: a high-unique eval batch resolves
    # more uniques than the train budget allows.
    views, _ = tr.forward_views(s, high)
    inv = np.asarray(views["C1"][1])
    assert len(np.unique(inv)) == 64


def test_update_budgets_rebuild_recompiles_then_runs_steady():
    """The PR 2 stale-executable contract, pinned as a compile budget
    (analysis/trace_guard.py): steady-state training after warmup
    compiles NOTHING; update_budgets engaging a new budget bucket
    REBUILDS the jitted step (the next dispatch really compiles — a
    stale executable would be a silent cache hit at the old U); and the
    rebuilt step is itself steady afterwards."""
    from deeprec_tpu.analysis import trace_guard

    batches = _batches()
    tr = Trainer(_model(), Adagrad(lr=0.1), unique_budget="auto")
    s = tr.init(0)
    s, m = tr.train_step(s, batches[0])  # warmup: compiles the U=N step
    jax.block_until_ready(m["loss"])
    with trace_guard(max_compiles=0, note="pre-budget steady state"):
        for b in batches:
            s, m = tr.train_step(s, b)
        jax.block_until_ready(m["loss"])
    s, _ = tr.update_budgets(s)  # budget bucket engages -> jits rebuilt
    with trace_guard(max_compiles=None) as g:
        s, m = tr.train_step(s, batches[0])
        jax.block_until_ready(m["loss"])
    assert g.compiles > 0, (
        "update_budgets engaged a budget but the next dispatch compiled "
        "nothing — the stale pre-budget executable is still serving"
    )
    with trace_guard(max_compiles=0, note="post-budget steady state"):
        for b in batches:
            s, m = tr.train_step(s, b)
        jax.block_until_ready(m["loss"])


def test_maintain_reports_dedup_and_resets():
    batches = _batches()
    tr = Trainer(_model(), Adagrad(lr=0.1), unique_budget="auto")
    s = tr.init(0)
    for b in batches:
        s, _ = tr.train_step(s, b)
    s, report = tr.maintain(s)
    assert all("unique_fraction" in r for r in report.values())
    for ts in s.tables.values():
        assert int(np.sum(np.asarray(ts.dedup_ids))) == 0


# ---------------------------------------------------------- sharded level


@pytest.fixture(scope="module")
def mesh():
    from deeprec_tpu.parallel import make_mesh

    return make_mesh(8)


@pytest.mark.parametrize("comm", ["allgather", "a2a"])
def test_sharded_budget_scan_parity(mesh, comm):
    """Budgeted dedup BEFORE the exchange: train_steps scan == sequential,
    exact table ints, on both exchange strategies."""
    from deeprec_tpu.parallel import ShardedTrainer, shard_batch

    tr = ShardedTrainer(_model(), Adagrad(lr=0.1), optax.adam(2e-3),
                        mesh=mesh, comm=comm, unique_budget=64)
    batches = [shard_batch(mesh, b) for b in _batches(3, seed=2)]
    s_seq = tr.init(0)
    seq_losses = []
    for b in batches:
        s_seq, m = tr.train_step(s_seq, b)
        seq_losses.append(float(m["loss"]))
    s_scan, mets = tr.train_steps(tr.init(0), batches)
    np.testing.assert_allclose(np.asarray(mets["loss"]), seq_losses,
                               atol=1e-5)
    _assert_tables_exact(s_scan, s_seq)


def test_sharded_auto_budget_clamps_at_global_capacity(mesh):
    """The auto-budget capacity clamp must use the GLOBAL table capacity:
    the sharded bundle cfg is per-shard (C/N), but a local batch's unique
    ids hash across every shard — a per-shard clamp would latch the budget
    N× too tight and permanently overflow resident keys."""
    from deeprec_tpu.parallel import ShardedTrainer

    tr = ShardedTrainer(_model(), Adagrad(lr=0.1), mesh=mesh,
                        unique_budget="auto")
    b = next(iter(tr.bundles.values()))
    tr._auto_frac[b.name] = 1.0
    C_local = b.table.cfg.capacity
    n = tr.num_shards * C_local  # far beyond the per-shard capacity
    size = tr._resolve_budget(b, n)
    assert size > dedup.resolve_size(C_local, n)  # not per-shard-clamped
    assert size == dedup.resolve_size(C_local * tr.num_shards, n)


def test_sharded_budget_matches_legacy_keys(mesh):
    """Budgeted vs legacy sharded training agree on losses and on the
    global key set per table (the a2a payload shrank, semantics did not)."""
    from deeprec_tpu.parallel import ShardedTrainer, shard_batch

    batches_raw = _batches(3, seed=5)
    out = {}
    for budget in (None, 64):
        tr = ShardedTrainer(_model(), Adagrad(lr=0.1), mesh=mesh,
                            unique_budget=budget)
        batches = [shard_batch(mesh, b) for b in batches_raw]
        s = tr.init(0)
        losses = []
        for b in batches:
            s, m = tr.train_step(s, b)
            losses.append(float(m["loss"]))
        keys = {
            bname: set(np.asarray(ts.keys).ravel().tolist()) - {SENT}
            for bname, ts in s.tables.items()
        }
        out[budget] = (losses, keys)
    np.testing.assert_allclose(out[None][0], out[64][0], atol=1e-6)
    assert out[None][1] == out[64][1]
