"""Sharded-table tests on the virtual 8-device CPU mesh — the distributed
coverage tier (SURVEY.md §4: in-process fake clusters)."""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from deeprec_tpu.data import SyntheticCriteo
from deeprec_tpu.models import WDL
from deeprec_tpu.optim import Adagrad, GradientDescent
from deeprec_tpu.parallel import ShardedTrainer, make_mesh, shard_batch
from deeprec_tpu.training import Trainer


def to_jnp(batch):
    return {k: jnp.asarray(v) for k, v in batch.items()}


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    return make_mesh(8)


def small_model():
    return WDL(emb_dim=8, capacity=1 << 13, hidden=(32,), num_cat=4, num_dense=2)


def test_sharded_matches_single_device(mesh):
    """The collective path must produce the same math as the local path:
    same loss trajectory and same embeddings for the same ids."""
    gen = SyntheticCriteo(batch_size=256, num_cat=4, num_dense=2, vocab=3000, seed=3)
    batches = [to_jnp(gen.batch()) for _ in range(5)]

    t_local = Trainer(small_model(), GradientDescent(lr=0.1), optax.sgd(0.01))
    s_local = t_local.init(0)
    t_shard = ShardedTrainer(
        small_model(), GradientDescent(lr=0.1), optax.sgd(0.01), mesh=mesh
    )
    s_shard = t_shard.init(0)

    for b in batches:
        s_local, m_local = t_local.train_step(s_local, b)
        s_shard, m_shard = t_shard.train_step(s_shard, shard_batch(mesh, b))
        # bf16 matmuls + different reduction orders (psum_scatter partial
        # sums) make this approximate; a formula bug diverges by orders of
        # magnitude, not fractions of a percent.
        np.testing.assert_allclose(
            float(m_local["loss"]), float(m_shard["loss"]), rtol=2e-2
        )

    # spot-check an id's embedding across the two worlds
    ids = batches[0]["C1"][:8]
    e_local = t_local.tables["C1"].lookup_readonly(
        t_local.table_state(s_local, "C1"), ids
    )
    # sharded: find each id on its owner shard
    from deeprec_tpu.utils.hashing import hash_shard

    owners = np.asarray(hash_shard(ids, 8))
    sharded_ts = t_shard.table_state(s_shard, "C1")  # [N, C_local, ...]
    got = []
    for i, oid in enumerate(np.asarray(ids)):
        shard_state = jax.tree.map(lambda a: a[owners[i]], sharded_ts)
        got.append(
            np.asarray(
                t_shard.tables["C1"].lookup_readonly(
                    shard_state, jnp.asarray([oid])
                )
            )[0]
        )
    np.testing.assert_allclose(np.asarray(e_local), np.asarray(got), atol=2e-2)


def test_sharded_learns(mesh):
    model = small_model()
    tr = ShardedTrainer(model, Adagrad(lr=0.2), optax.adam(5e-3), mesh=mesh)
    st = tr.init(0)
    gen = SyntheticCriteo(batch_size=512, num_cat=4, num_dense=2, vocab=2000, seed=5)
    losses = []
    for _ in range(60):
        st, m = tr.train_step(st, shard_batch(mesh, to_jnp(gen.batch())))
        losses.append(float(m["loss"]))
    assert np.mean(losses[-10:]) < np.mean(losses[:10])
    # tables sharded: every shard holds some keys, none holds all
    ts = tr.table_state(st, "C1")  # [N, C_local, ...]
    sizes = np.asarray(
        [int(tr.tables["C1"].size(jax.tree.map(lambda a: a[i], ts))) for i in range(8)]
    )
    assert (sizes > 0).all() and sizes.sum() <= 2000 * 1.01


def test_sharded_eval(mesh):
    model = small_model()
    tr = ShardedTrainer(model, Adagrad(lr=0.2), optax.adam(5e-3), mesh=mesh)
    st = tr.init(0)
    gen = SyntheticCriteo(batch_size=256, num_cat=4, num_dense=2, vocab=2000, seed=5)
    for _ in range(20):
        st, _ = tr.train_step(st, shard_batch(mesh, to_jnp(gen.batch())))
    mets = tr.evaluate(st, [shard_batch(mesh, to_jnp(gen.batch())) for _ in range(4)])
    assert 0.4 < mets["auc"] <= 1.0
