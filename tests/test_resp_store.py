"""RESP (Redis protocol) feature store tests against a scripted RESP
server — socket-level, like the Kafka wire tests: no redis dependency,
the bytes on the wire are the spec.

Reference contract being pinned (redis_feature_store.cc):
  * binary row keys: LE u64 model_version ++ LE u64 feature2id ++ LE i64 id
  * raw-f32 row values, MGET/MSET batches, nil => missing
  * literal metadata commands: GET/SET model_version ("full,latest"),
    GET/SET active, SET model_lock <v> ex <t> nx
"""
import socketserver
import struct
import threading

import numpy as np
import pytest

from deeprec_tpu.serving.resp_store import (
    RedisFeatureStore,
    RespConnection,
    RespError,
    encode_command,
)


class FakeRedis:
    """In-memory RESP server: AUTH/SELECT/GET/SET[ex/nx]/MGET/MSET/DEL —
    the command subset the feature store uses."""

    def __init__(self, password=None):
        self.data = {}
        self.password = password
        self.commands = []  # uppercased command names, in arrival order
        outer = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                authed = outer.password is None
                while True:
                    try:
                        args = outer._read_command(self.rfile)
                    except (ConnectionError, ValueError):
                        return
                    if args is None:
                        return
                    cmd = args[0].upper().decode()
                    outer.commands.append(cmd)
                    if cmd == "AUTH":
                        if args[1].decode() == (outer.password or ""):
                            authed = True
                            self.wfile.write(b"+OK\r\n")
                        else:
                            self.wfile.write(b"-ERR invalid password\r\n")
                    elif not authed:
                        self.wfile.write(b"-NOAUTH Authentication required.\r\n")
                    elif cmd == "SELECT":
                        self.wfile.write(b"+OK\r\n")
                    elif cmd == "GET":
                        v = outer.data.get(args[1])
                        self.wfile.write(outer._bulk(v))
                    elif cmd == "SET":
                        key, val = args[1], args[2]
                        opts = [a.upper() for a in args[3:]]
                        if b"NX" in opts and key in outer.data:
                            self.wfile.write(b"$-1\r\n")  # nil: not set
                        else:
                            outer.data[key] = val
                            self.wfile.write(b"+OK\r\n")
                    elif cmd == "MGET":
                        out = b"*%d\r\n" % (len(args) - 1)
                        for k in args[1:]:
                            out += outer._bulk(outer.data.get(k))
                        self.wfile.write(out)
                    elif cmd == "MSET":
                        for i in range(1, len(args) - 1, 2):
                            outer.data[args[i]] = args[i + 1]
                        self.wfile.write(b"+OK\r\n")
                    elif cmd == "DEL":
                        n = 0
                        for k in args[1:]:
                            n += 1 if outer.data.pop(k, None) is not None else 0
                        self.wfile.write(b":%d\r\n" % n)
                    else:
                        self.wfile.write(b"-ERR unknown command\r\n")
                    self.wfile.flush()

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._srv = Server(("127.0.0.1", 0), Handler)
        self.port = self._srv.server_address[1]
        threading.Thread(target=self._srv.serve_forever, daemon=True).start()

    @staticmethod
    def _bulk(v):
        return b"$-1\r\n" if v is None else b"$%d\r\n%s\r\n" % (len(v), v)

    @staticmethod
    def _read_command(rfile):
        line = rfile.readline()
        if not line:
            return None
        if not line.startswith(b"*"):
            raise ValueError(f"inline commands unsupported: {line!r}")
        n = int(line[1:].strip())
        args = []
        for _ in range(n):
            hdr = rfile.readline()
            if not hdr.startswith(b"$"):
                raise ValueError(f"expected bulk string, got {hdr!r}")
            ln = int(hdr[1:].strip())
            data = rfile.read(ln)
            rfile.read(2)  # CRLF
            if len(data) != ln:
                raise ConnectionError("short read")
            args.append(data)
        return args

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()


def test_encode_command_resp_bytes():
    assert encode_command(b"GET", b"k") == b"*2\r\n$3\r\nGET\r\n$1\r\nk\r\n"
    assert encode_command("SET", "k", 12) == (
        b"*3\r\n$3\r\nSET\r\n$1\r\nk\r\n$2\r\n12\r\n"
    )


def test_connection_roundtrip_and_pipeline():
    srv = FakeRedis()
    try:
        c = RespConnection("127.0.0.1", srv.port)
        assert c.command(b"SET", b"a", b"1") == b"OK"
        assert c.command(b"GET", b"a") == b"1"
        assert c.command(b"GET", b"missing") is None
        replies = c.pipeline([
            (b"SET", b"b", b"2"), (b"GET", b"b"), (b"DEL", b"b"),
            (b"GET", b"b"),
        ])
        assert replies == [b"OK", b"2", 1, None]
        with pytest.raises(RespError, match="unknown"):
            c.command(b"NOSUCH")
        c.close()
    finally:
        srv.stop()


def test_auth_and_select_on_connect():
    srv = FakeRedis(password="sekrit")
    try:
        good = RespConnection("127.0.0.1", srv.port, password="sekrit", db=3)
        assert good.command(b"SET", b"x", b"y") == b"OK"
        # AUTH and SELECT happened before the first user command
        assert srv.commands[:2] == ["AUTH", "SELECT"]
        good.close()

        bad = RespConnection("127.0.0.1", srv.port, password="wrong")
        with pytest.raises(RespError, match="invalid password"):
            bad.command(b"GET", b"x")
        bad.close()
    finally:
        srv.stop()


def test_store_put_get_reference_key_scheme():
    """Rows land under the reference's exact binary key layout and read
    back with a correct found mask."""
    srv = FakeRedis()
    try:
        store = RedisFeatureStore("127.0.0.1", srv.port, dim=4,
                                  model_version=7, feature2id=3)
        keys = np.asarray([5, -2, 1 << 40], np.int64)
        rows = np.arange(12, dtype=np.float32).reshape(3, 4)
        store.put(keys, rows)

        # the wire keys are memcpy(model_version) ++ memcpy(feature2id)
        # ++ memcpy(key) — exactly what redis_feature_store.cc builds
        want_key = struct.pack("<QQq", 7, 3, 5)
        assert want_key in srv.data
        assert srv.data[want_key] == rows[0].tobytes()

        vals, freqs, vers, found = store.get(
            np.asarray([5, 99, -2, 1 << 40], np.int64)
        )
        assert found.tolist() == [True, False, True, True]
        np.testing.assert_array_equal(vals[0], rows[0])
        np.testing.assert_array_equal(vals[2], rows[1])
        np.testing.assert_array_equal(vals[3], rows[2])
        np.testing.assert_array_equal(vals[1], 0.0)
        assert freqs.tolist() == [0, 0, 0, 0] and vers.tolist() == [0, 0, 0, 0]

        # a different model_version namespace misses
        other = RedisFeatureStore("127.0.0.1", srv.port, dim=4,
                                  model_version=8, feature2id=3,
                                  conn=store.conn)
        _, _, _, found2 = other.get(keys)
        assert not found2.any()
        assert store.delete(keys) == 3
        store.close()
    finally:
        srv.stop()


def test_store_dim_mismatch_is_loud():
    srv = FakeRedis()
    try:
        w = RedisFeatureStore("127.0.0.1", srv.port, dim=8)
        w.put(np.asarray([1], np.int64), np.ones((1, 8), np.float32))
        r = RedisFeatureStore("127.0.0.1", srv.port, dim=4, conn=w.conn)
        with pytest.raises(ConnectionError, match="dim mismatch"):
            r.get(np.asarray([1], np.int64))
        w.close()
    finally:
        srv.stop()


def test_store_metadata_commands():
    """model_version / active / lock: the literal reference commands."""
    srv = FakeRedis()
    try:
        store = RedisFeatureStore("127.0.0.1", srv.port, dim=2)
        assert store.get_model_version() == (-1, -1)
        store.set_model_version(41, 42)
        assert srv.data[b"model_version"] == b"41,42"
        assert store.get_model_version() == (41, 42)

        assert store.get_active() is False
        store.set_active(True)
        assert store.get_active() is True
        assert srv.data[b"active"] == b"1"

        assert store.acquire_lock(1, 30) is True
        assert store.acquire_lock(2, 30) is False  # NX: already held
        store.release_lock()
        assert store.acquire_lock(2, 30) is True
        store.close()
    finally:
        srv.stop()


def test_predictor_read_through_via_resp(tmp_path):
    """End-to-end: a Redis-protocol store plugs into Predictor(stores=...)
    exactly like the bespoke RemoteKVClient — missing device keys serve
    the Redis row (redis_feature_store.h read-through parity)."""
    import jax.numpy as jnp
    import optax

    from deeprec_tpu.data import SyntheticCriteo
    from deeprec_tpu.models import WDL
    from deeprec_tpu.optim import Adagrad
    from deeprec_tpu.serving import Predictor
    from deeprec_tpu.training import Trainer
    from deeprec_tpu.training.checkpoint import CheckpointManager

    model = WDL(emb_dim=8, capacity=1 << 12, hidden=(32,), num_cat=4,
                num_dense=2)
    tr = Trainer(model, Adagrad(lr=0.1), optax.adam(1e-3))
    st = tr.init(0)
    gen = SyntheticCriteo(batch_size=64, num_cat=4, num_dense=2, vocab=500,
                          seed=3)
    batch = {k: jnp.asarray(v) for k, v in gen.batch().items()}
    for _ in range(3):
        st, _ = tr.train_step(st, batch)
    CheckpointManager(str(tmp_path), tr).save(st)

    srv = FakeRedis()
    try:
        tname = sorted(tr.tables)[0]
        dim = tr.tables[tname].cfg.dim
        store = RedisFeatureStore("127.0.0.1", srv.port, dim=dim)
        novel = 999_999
        store.put(np.asarray([novel], np.int64),
                  np.full((1, dim), 2.5, np.float32))

        req = {k: np.asarray(v) for k, v in batch.items()
               if not k.startswith("label")}
        req[tname] = np.full_like(req[tname], novel)
        out_plain = Predictor(model, str(tmp_path)).predict(req)
        out_store = Predictor(
            model, str(tmp_path), stores={tname: store}
        ).predict(req)
        assert np.abs(np.asarray(out_store) - np.asarray(out_plain)).max() \
            > 1e-6
        store.close()
    finally:
        srv.stop()
