"""The parallel host input pipeline (PR 20, data/pipeline.py):

  * `criteo_block_parse` is bit-identical to the per-line
    `criteo_line_parser` — values, dtypes AND error counters — on clean
    blocks (the vectorized cube fast path) and on the garbage matrix
    (bad labels, unparseable floats, nonfinite values, short/long/empty
    rows) that falls back to the per-line lane.
  * the N-worker pipeline emits the SAME batch stream as the serial
    single-reader assembly for ANY worker count — including under an
    artificially slow worker (the reorder buffer, not thread luck,
    owns ordering) and with k_stack'ed emission.
  * kill-and-resume is exactly-once: consumed-position save/restore
    through the staged ring, through a ParquetReader shard, and through
    a real SIGKILL with 3 workers mid-file at different offsets.
  * the hoisted `pad_ragged`/`pad_rect` (utils/ragged.py) match the
    legacy per-row padding rules serving depended on.
"""
import glob
import hashlib
import json
import os
import signal
import sys
import textwrap

import numpy as np
import pytest

from deeprec_tpu.data.pipeline import ParallelInputPipeline, plan_shards
from deeprec_tpu.data.readers import (
    RecordErrors,
    criteo_block_parse,
    criteo_hash_salts,
    sanitize_batch,
)
from deeprec_tpu.data.stream import criteo_line_parser

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))

NUM_DENSE, NUM_CAT = 13, 26


def _write_criteo(dirname, rows_per_file, seed=0):
    """Deterministic Criteo TSV files; I1 carries the global record index
    so every record in every emitted batch is identity-checkable."""
    rng = np.random.default_rng(seed)
    paths, gid = [], 0
    for fi, n in enumerate(rows_per_file):
        p = os.path.join(str(dirname), f"day{fi}.tsv")
        with open(p, "w") as f:
            for _ in range(n):
                cols = [str(rng.integers(0, 2)), str(gid)]
                cols += ["" if rng.random() < 0.1 else
                         str(rng.integers(0, 100))
                         for _ in range(NUM_DENSE - 1)]
                cols += [f"{rng.integers(0, 1 << 20):x}"
                         if rng.random() > 0.05 else ""
                         for _ in range(NUM_CAT)]
                f.write("\t".join(cols) + "\n")
                gid += 1
        paths.append(p)
    return paths


def _serial_stream(paths, B):
    """The baseline the pipeline must be bit-identical to: per-file
    `criteo_line_parser` batches, per-file remainder dropped."""
    err = RecordErrors(metrics=False)
    parse = criteo_line_parser(errors=err)
    for p in paths:
        with open(p) as f:
            lines = f.read().split("\n")[:-1]
        for i in range(len(lines) // B):
            yield sanitize_batch(parse(lines[i * B:(i + 1) * B]), err)


def _assert_batches_equal(got, want, msg=""):
    assert len(got) == len(want), f"{msg}: {len(got)} vs {len(want)} batches"
    for bi, (a, b) in enumerate(zip(got, want)):
        assert set(a) == set(b)
        for k in b:
            assert a[k].dtype == b[k].dtype, (msg, bi, k)
            np.testing.assert_array_equal(a[k], b[k],
                                          err_msg=f"{msg}: batch {bi} {k}")


# ------------------------------------------------------------ block parse


def test_block_parse_clean_parity_uses_cube_path(tmp_path, monkeypatch):
    import deeprec_tpu.data.readers as readers

    paths = _write_criteo(tmp_path, [300])
    data = open(paths[0], "rb").read()

    calls = {"n": 0}
    real = readers._cube_parse_into

    def spy(*a, **kw):
        calls["n"] += 1
        out = real(*a, **kw)
        assert out  # clean uniform-arity block must take the fast lane
        return out

    monkeypatch.setattr(readers, "_cube_parse_into", spy)
    e1, e2 = RecordErrors(metrics=False), RecordErrors(metrics=False)
    got = criteo_block_parse(data, errors=e1)
    want = criteo_line_parser(errors=e2)(data.decode().split("\n")[:-1])
    assert calls["n"] == 1
    _assert_batches_equal([got], [want], "clean block")
    assert e1.counts == e2.counts == {}


def test_block_parse_garbage_matrix_parity():
    rng = np.random.default_rng(7)
    rows = []
    for _ in range(200):  # clean filler the garbage hides between
        cols = [str(rng.integers(0, 2))]
        cols += ["" if rng.random() < 0.1 else str(rng.integers(0, 100))
                 for _ in range(NUM_DENSE)]
        cols += [f"{rng.integers(0, 1 << 20):x}" for _ in range(NUM_CAT)]
        rows.append("\t".join(cols))
    rows += [
        "x\t" + "\t".join(["1"] * 13 + ["aa"] * 26),        # bad label
        "1\tzz\t" + "\t".join(["2"] * 12 + ["bb"] * 26),    # bad float
        "1\t" + "\t".join(["1e999"] * 13 + ["cc"] * 26),    # inf -> clamp
        "0\t" + "\t".join(["nan"] * 13 + [""] * 26),        # nan + no cats
        "1\t1\t2",                                          # short row
        "\t".join(["5"] * 45),                              # long row
        "",                                                 # empty line
        "1\t  3  \t" + "\t".join(["4"] * 12 + ["dd"] * 26),  # ws float
    ]
    rng.shuffle(rows)
    data = ("\n".join(rows) + "\n").encode()

    e1, e2 = RecordErrors(metrics=False), RecordErrors(metrics=False)
    got = criteo_block_parse(data, errors=e1)
    want = criteo_line_parser(errors=e2)(data.decode().split("\n")[:-1])
    _assert_batches_equal([got], [want], "garbage matrix")
    assert e1.counts == e2.counts
    assert e1.counts["bad_label"] >= 1 and e1.counts["bad_float"] >= 1
    assert e1.counts["nonfinite_float"] >= 1


def test_block_parse_non_utf8_and_unterminated_tail():
    clean = b"1\t" + b"\t".join([b"2"] * 13 + [b"ad"] * 26) + b"\n"
    dirty = b"0\t" + b"\t".join([b"3"] * 13 + [b"\xff\xfe"] * 26)
    data = clean + dirty  # no trailing newline: tail still a record
    e1, e2 = RecordErrors(metrics=False), RecordErrors(metrics=False)
    got = criteo_block_parse(data, errors=e1)
    want = criteo_line_parser(errors=e2)(
        data.decode("utf-8", errors="replace").split("\n"))
    _assert_batches_equal([got], [want], "non-utf8")
    assert e1.counts == e2.counts


# ------------------------------------------------------- shard plan


def test_plan_shards_record_aligned_and_deterministic(tmp_path):
    paths = _write_criteo(tmp_path, [700, 450, 96])
    shards = plan_shards(paths, batch_size=64, shard_batches=2)
    assert shards == plan_shards(paths, batch_size=64, shard_batches=2)
    for s in shards:
        # every shard starts at a record boundary and units are whole
        # batches: batches can never span a shard (or a file)
        blob = open(s.path, "rb").read()
        assert s.lo == 0 or blob[s.lo - 1:s.lo] == b"\n"
        assert s.records == s.units * 64
        assert blob[s.lo:s.hi].count(b"\n") >= s.records - 1
    # unit sequence is gapless and totals the per-file floor sum
    assert [s.first_unit for s in shards] == \
        list(np.cumsum([0] + [s.units for s in shards[:-1]]))
    assert sum(s.units for s in shards) == 700 // 64 + 450 // 64 + 96 // 64


# ------------------------------------------------------------ pipeline


@pytest.mark.parametrize("workers", [1, 2, 5])
def test_pipeline_bit_identical_to_serial_any_worker_count(tmp_path, workers):
    paths = _write_criteo(tmp_path, [700, 450, 96])
    want = list(_serial_stream(paths, 64))
    pl = ParallelInputPipeline(paths, batch_size=64, num_workers=workers,
                               shard_batches=2, metrics=False)
    got = list(pl)
    pl.close()
    _assert_batches_equal(got, want, f"workers={workers}")


def test_pipeline_deterministic_under_slow_worker(tmp_path, monkeypatch):
    """Order must come from the reorder buffer, not thread timing: stall
    the worker that claimed shard 0 and the stream must not change."""
    import deeprec_tpu.data.pipeline as pl_mod

    paths = _write_criteo(tmp_path, [700, 450, 96])
    want = list(_serial_stream(paths, 64))

    real = pl_mod.criteo_block_parse
    hit = {"first": True}

    def slow(data, *a, **kw):
        import time
        if hit["first"]:
            hit["first"] = False
            time.sleep(0.25)
        return real(data, *a, **kw)

    monkeypatch.setattr(pl_mod, "criteo_block_parse", slow)
    pl = ParallelInputPipeline(paths, batch_size=64, num_workers=4,
                               shard_batches=2, metrics=False)
    got = list(pl)
    pl.close()
    assert not hit["first"]
    _assert_batches_equal(got, want, "slow worker")


def test_pipeline_k_stack_matches_stacked_batches(tmp_path):
    paths = _write_criteo(tmp_path, [700, 450])
    want = list(_serial_stream(paths, 64))
    pl = ParallelInputPipeline(paths, batch_size=64, num_workers=3,
                               shard_batches=2, k_stack=2, metrics=False)
    got = list(pl)
    pl.close()
    # each emitted item is K serial batches stacked on a leading axis —
    # exactly what trainer.stack_batches hands train_steps — and the
    # remainder contract drops per-plan-unit (a multiple of K batches)
    flat = []
    for item in got:
        assert item["label"].shape[0] == 2
        for j in range(2):
            flat.append({k: v[j] for k, v in item.items()})
    _assert_batches_equal(flat, want[:len(flat)], "k_stack")
    assert len(flat) >= len(want) - 2 * len(paths)


def test_pipeline_staged_ring_exactly_once_resume(tmp_path):
    """The training-loop shape: pipeline -> staged() ring with the
    consumed-position hookup (Trainer.stage wires the same). Save after 5
    DELIVERED batches (ring depth 4 means producers ran ahead), restore a
    fresh pipeline: the union replays every record exactly once."""
    from deeprec_tpu.data.prefetch import staged

    paths = _write_criteo(tmp_path, [700, 450, 96])
    want = list(_serial_stream(paths, 64))

    pl = ParallelInputPipeline(paths, batch_size=64, num_workers=3,
                               shard_batches=2, metrics=False)
    pl.attach_consumer()
    ring = staged(pl, depth=4, transform=lambda b: b,
                  on_consume=pl.mark_consumed)
    head = [next(ring) for _ in range(5)]
    state = pl.save()
    assert state["consumed"] == 5
    ring.close()
    pl.close()

    pl2 = ParallelInputPipeline(paths, batch_size=64, num_workers=3,
                                shard_batches=2, metrics=False)
    pl2.restore(json.loads(json.dumps(state)))  # state is JSON-clean
    tail = list(pl2)
    pl2.close()
    _assert_batches_equal(head + tail, want, "staged resume")


# ------------------------------------------------------------- parquet


def _to_parquet(paths, dirname):
    pa = pytest.importorskip("pyarrow")
    pq = pytest.importorskip("pyarrow.parquet")
    out = []
    for p in paths:
        cols = {"label": [], **{f"I{i}": [] for i in range(1, NUM_DENSE + 1)},
                **{f"C{i}": [] for i in range(1, NUM_CAT + 1)}}
        with open(p) as f:
            for line in f.read().split("\n")[:-1]:
                parts = line.split("\t")
                cols["label"].append(float(parts[0]))
                for i in range(NUM_DENSE):
                    v = parts[1 + i]
                    cols[f"I{i + 1}"].append(float(v) if v else 0.0)
                for c in range(NUM_CAT):
                    v = parts[1 + NUM_DENSE + c]
                    cols[f"C{c + 1}"].append(v if v else None)
        dst = os.path.join(str(dirname), os.path.basename(p) + ".parquet")
        pq.write_table(pa.table(cols), dst, row_group_size=50)
        out.append(dst)
    return out


def test_parquet_pipeline_bit_identical_to_csv(tmp_path):
    paths = _write_criteo(tmp_path, [300, 170])
    pq_paths = _to_parquet(paths, tmp_path)
    a = ParallelInputPipeline(paths, batch_size=64, num_workers=2,
                              shard_batches=2, metrics=False)
    want = list(a)
    a.close()
    b = ParallelInputPipeline(pq_paths, batch_size=64, num_workers=2,
                              fmt="parquet",
                              hash_salts=criteo_hash_salts(),
                              metrics=False)
    got = list(b)
    b.close()
    _assert_batches_equal(got, want, "parquet vs csv")


def test_parquet_resume_exactly_once(tmp_path):
    paths = _write_criteo(tmp_path, [300, 170])
    pq_paths = _to_parquet(paths, tmp_path)
    mk = lambda: ParallelInputPipeline(  # noqa: E731
        pq_paths, batch_size=64, num_workers=2, fmt="parquet",
        hash_salts=criteo_hash_salts(), metrics=False)
    full = mk()
    want = list(full)
    full.close()

    pl = mk()
    pl.attach_consumer()
    it = iter(pl)
    head = []
    for _ in range(3):
        head.append(next(it))
        pl.mark_consumed()
    state = pl.save()
    pl.close()

    pl2 = mk()
    pl2.restore(state)
    tail = list(pl2)
    pl2.close()
    _assert_batches_equal(head + tail, want, "parquet resume")


# ------------------------------------------------------------- SIGKILL


SIGKILL_WORKER = textwrap.dedent(
    """
    import glob, hashlib, json, os, sys, time
    sys.path.insert(0, {repo!r})
    from deeprec_tpu.data.pipeline import ParallelInputPipeline

    paths = sorted(glob.glob(os.path.join({data!r}, "*.tsv")))
    state_path = {state!r}
    pl = ParallelInputPipeline(paths, batch_size=64, num_workers=3,
                               shard_batches=2, metrics=False)
    if os.path.exists(state_path):
        with open(state_path) as f:
            pl.restore(json.load(f))
        print("RESUMED", flush=True)
    pl.attach_consumer()
    for batch in pl:
        digest = hashlib.md5(
            b"".join(batch[k].tobytes() for k in sorted(batch))
        ).hexdigest()
        pl.mark_consumed()
        st = pl.save()
        print(f"BATCH {{st['consumed'] - 1}} {{digest}}", flush=True)
        with open(state_path + ".tmp", "w") as f:
            json.dump(st, f)
        os.replace(state_path + ".tmp", state_path)
        time.sleep(0.02)
    print("DONE", flush=True)
    """
)


def test_sigkill_midstream_resumes_exactly_once(tmp_path):
    """kill -9 the consumer process while 3 workers sit at different
    offsets in different files; the restarted process restores per-shard
    consumed offsets and the union of both runs is the full serial stream
    with every record exactly once (replay only past the last durable
    save, never a gap)."""
    from deeprec_tpu.online import faults

    data_dir = tmp_path / "data"
    data_dir.mkdir()
    paths = _write_criteo(data_dir, [700, 450, 263], seed=3)
    want = list(_serial_stream(paths, 64))
    oracle = [hashlib.md5(b"".join(b[k].tobytes() for k in sorted(b))
                          ).hexdigest() for b in want]
    state = str(tmp_path / "stream_state.json")
    script = str(tmp_path / "worker.py")
    with open(script, "w") as f:
        f.write(SIGKILL_WORKER.format(repo=REPO, data=str(data_dir),
                                      state=state))

    p = faults.spawn_worker([sys.executable, script])
    hit, lines1 = faults.wait_for_line(
        p, lambda l: l.startswith("BATCH") and int(l.split()[1]) >= 4,
        timeout=120)
    assert hit is not None, lines1[-10:]
    assert faults.sigkill(p) == -signal.SIGKILL

    p = faults.spawn_worker([sys.executable, script])
    done, lines2 = faults.wait_for_line(
        p, lambda l: l.startswith("DONE"), timeout=120)
    assert done is not None, lines2[-10:]
    assert p.wait(timeout=30) == 0
    assert any(l == "RESUMED" for l in lines2), lines2[:3]

    run1 = {int(l.split()[1]): l.split()[2]
            for l in lines1 if l.startswith("BATCH")}
    run2 = {int(l.split()[1]): l.split()[2]
            for l in lines2 if l.startswith("BATCH")}
    first2 = min(run2)
    # no gap: everything before the resume point was delivered in run 1;
    # replay (kill between deliver and durable save) only ever re-emits
    # the tail at/after the resume point, bit-identically
    combined = {i: d for i, d in run1.items() if i < first2}
    combined.update(run2)
    assert sorted(combined) == list(range(len(oracle)))
    assert [combined[i] for i in range(len(oracle))] == oracle
    for i, d in run1.items():
        assert d == oracle[i]  # replayed tail is bit-identical too


# ------------------------------------------------------- ragged padding


def _legacy_ragged_pad(v, L, pad_value, want):
    rows = [(list(r) + [pad_value] * (L - len(r)))[:L] for r in v]
    return np.asarray(rows, want)


def test_pad_ragged_hoisted_single_implementation():
    from deeprec_tpu.serving import predictor
    from deeprec_tpu.utils import ragged

    assert predictor.pad_ragged is ragged.pad_ragged  # delegation, no fork


def test_pad_ragged_and_pad_rect_parity():
    from deeprec_tpu.utils.ragged import pad_rect, pad_ragged

    rng = np.random.default_rng(0)
    L, pad = 6, -1
    cases = {
        "ragged": [[7, 8, 9], [10], [], [1, 2, 3, 4, 5]],
        "over_long": [list(range(12)), list(range(9)), [3]],
        "exact": [[1, 2, 3, 4, 5, 6], [9] * 6],
        "random": [list(map(int, rng.integers(0, 99, rng.integers(0, 11))))
                   for _ in range(64)],
    }
    for name, v in cases.items():
        for want in (np.dtype(np.int64), np.dtype(np.int32)):
            got = pad_ragged(v, L, pad, want)
            np.testing.assert_array_equal(
                got, _legacy_ragged_pad(v, L, pad, want), err_msg=name)
            assert got.dtype == want

    # pad_rect: already-rectangular fast path — scalar bags widen to
    # [n, 1] then pad, over-long truncates, exact passes through
    for name, v in {
        "scalar_bag": [1, 2, 3],
        "rect_short": [[1, 2], [3, 4]],
        "rect_long": [list(range(12)), list(range(12, 24))],
        "rect_exact": [[1, 2, 3, 4, 5, 6]],
    }.items():
        want = np.dtype(np.int32)
        ref_rows = [[r] if np.isscalar(r) else r for r in v]
        got = pad_rect(np.asarray(v), L, pad, want)
        np.testing.assert_array_equal(
            got, _legacy_ragged_pad(ref_rows, L, pad, want), err_msg=name)
        assert got.dtype == want


# ---------------------------------------------------------- observability


def test_pipeline_exports_input_metrics(tmp_path):
    from deeprec_tpu.obs import metrics as obs_metrics

    if not obs_metrics.metrics_enabled():
        pytest.skip("metrics plane off")
    paths = _write_criteo(tmp_path, [300])
    pl = ParallelInputPipeline(paths, batch_size=64, num_workers=2,
                               shard_batches=2, metrics=True)
    n = sum(b["label"].shape[0] for b in pl)
    pl.close()
    text = obs_metrics.default_registry().render_prometheus()
    assert "deeprec_input_batches" in text
    assert "deeprec_input_records" in text
    assert "deeprec_input_bytes" in text
    assert 'deeprec_input_stall_seconds{site="pipeline"}' in text
    assert n == (300 // 64) * 64
    st = pl.stats()
    assert st["records"] == n and st["bytes"] > 0
    assert st["parse_s"] >= 0 and st["pack_s"] >= 0
