"""Multi-hash / adaptive embedding, elastic reshard, DSSM group scoring,
and the filter×optimizer matrix (the embedding_variable_ops_test.py:1007
coverage pattern)."""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from deeprec_tpu import (
    CBFFilter,
    CounterFilter,
    EmbeddingTable,
    EmbeddingVariableOption,
    InitializerOption,
    TableConfig,
)
from deeprec_tpu.data import SyntheticCriteo, SyntheticTwoTower
from deeprec_tpu.embedding.compose import (
    AdaptiveEmbedding,
    MultiHashConfig,
    MultiHashTable,
)
from deeprec_tpu.models import DSSM, WDL
from deeprec_tpu.optim import apply_gradients, ensure_slots, make
from deeprec_tpu.parallel import ShardedTrainer, make_mesh, shard_batch
from deeprec_tpu.parallel.elastic import reshard
from deeprec_tpu.training import ModelInputs, Trainer


def J(b):
    return {k: jnp.asarray(v) for k, v in b.items()}


# ----------------------------------------------------------- multi-hash / QR


def test_multihash_composes_and_compresses():
    cfg = MultiHashConfig(name="mh", dim=8, num_buckets_q=64, num_buckets_r=64)
    mh = MultiHashTable(cfg)
    params = mh.create(jax.random.PRNGKey(0))
    ids = jnp.arange(0, 4000, 37, dtype=jnp.int32)
    emb = mh.lookup(params, ids)
    assert emb.shape == (len(ids), 8)
    # distinct ids in a 4096-vocab get distinct embeddings despite 128 rows
    u = np.unique(np.asarray(emb).round(5), axis=0)
    assert len(u) == len(ids)
    # concat doubles width
    mh2 = MultiHashTable(MultiHashConfig("mh2", 8, 64, 64, "concat"))
    assert mh2.lookup(mh2.create(jax.random.PRNGKey(1)), ids).shape == (len(ids), 16)


def test_multihash_differentiable():
    mh = MultiHashTable(MultiHashConfig("mh", 4, 32, 32))
    params = mh.create(jax.random.PRNGKey(0))
    ids = jnp.array([3, 99, 1000], jnp.int32)

    def loss(params):
        return jnp.sum(mh.lookup(params, ids) ** 2)

    g = jax.grad(loss)(params)
    assert float(jnp.abs(g[0]).sum()) > 0 and float(jnp.abs(g[1]).sum()) > 0


# ------------------------------------------------------------- adaptive emb


def test_adaptive_embedding_routes_by_admission():
    t = EmbeddingTable(
        TableConfig(
            name="ae", dim=4, capacity=256,
            ev=EmbeddingVariableOption(counter_filter=CounterFilter(filter_freq=3)),
        )
    )
    ae = AdaptiveEmbedding(t, static_buckets=64)
    static = ae.create_static(jax.random.PRNGKey(0))
    s = t.create()
    ids = jnp.array([7, 7, 7, 42], jnp.int32)  # 7 seen 3x -> admitted; 42 cold
    s, res, use_exact = ae.lookup_unique(s, static, ids)
    by_id = {int(u): i for i, u in enumerate(np.asarray(res.uids))}
    assert bool(use_exact[by_id[7]])
    assert not bool(use_exact[by_id[42]])
    # cold id serves the static bucket row
    from deeprec_tpu.utils.hashing import hash_to_bucket

    b42 = int(hash_to_bucket(jnp.array([42], jnp.int32), 64, salt=0xADA)[0])
    np.testing.assert_allclose(
        np.asarray(res.embeddings)[by_id[42]], np.asarray(static)[b42], rtol=1e-6
    )
    # grads split to the right paths
    g = jnp.ones_like(res.embeddings)
    g_exact, (bucket, g_static) = ae.grads(res, use_exact, g)
    assert float(jnp.abs(g_exact[by_id[7]]).sum()) > 0
    assert float(jnp.abs(g_exact[by_id[42]]).sum()) == 0
    assert float(jnp.abs(g_static[by_id[42]]).sum()) > 0


# ------------------------------------------------------------ elastic scale


@pytest.mark.slow
def test_elastic_reshard_single_to_mesh_and_back(tmp_path):
    model = WDL(emb_dim=8, capacity=1 << 12, hidden=(32,), num_cat=4, num_dense=2)
    tr1 = Trainer(model, make("adagrad", lr=0.1), optax.adam(1e-3))
    st1 = tr1.init(0)
    gen = SyntheticCriteo(batch_size=256, num_cat=4, num_dense=2, vocab=1200, seed=9)
    batches = [J(gen.batch()) for _ in range(3)]
    for b in batches:
        st1, _ = tr1.train_step(st1, b)

    mesh = make_mesh(8)
    tr8 = ShardedTrainer(model, make("adagrad", lr=0.1), optax.adam(1e-3), mesh=mesh)
    st8 = reshard(tr1, st1, tr8, scratch_dir=str(tmp_path / "up"))
    _, p1 = tr1.eval_step(st1, batches[0])
    _, p8 = tr8.eval_step(st8, shard_batch(mesh, batches[0]))
    np.testing.assert_allclose(np.asarray(p1), np.asarray(p8), atol=1e-5)

    # continue training on the mesh, then scale back down
    st8, _ = tr8.train_step(st8, shard_batch(mesh, batches[1]))
    tr1b = Trainer(model, make("adagrad", lr=0.1), optax.adam(1e-3))
    st1b = reshard(tr8, st8, tr1b, scratch_dir=str(tmp_path / "down"))
    _, pa = tr8.eval_step(st8, shard_batch(mesh, batches[2]))
    _, pb = tr1b.eval_step(st1b, batches[2])
    np.testing.assert_allclose(np.asarray(pa), np.asarray(pb), atol=1e-5)


# -------------------------------------------------------- DSSM group scoring


def test_sample_aware_group_compression():
    """General sample-aware compression (reference
    Sample-awared-Graph-Compression): a row-independent user tower applied
    through apply_grouped gives row-identical outputs while computing only
    one row per distinct group."""
    import jax

    from deeprec_tpu import nn

    rng = np.random.default_rng(0)
    B, G, D = 64, 8, 12
    group_ids = jnp.asarray(rng.integers(0, G, B), jnp.int32)
    x = jnp.asarray(rng.normal(0, 1, (B, D)).astype(np.float32))
    # make user-side inputs constant within a group (the packed format)
    base = jnp.asarray(rng.normal(0, 1, (G, D)).astype(np.float32))
    x = base[group_ids]

    params = nn.mlp_init(jax.random.PRNGKey(0), D, [16, 4])
    calls = []

    def tower(inp):
        calls.append(inp.shape)
        return nn.mlp_apply(params, inp)

    out_grouped = nn.apply_grouped(tower, x, group_ids, num_groups=G)
    out_full = nn.mlp_apply(params, x)
    np.testing.assert_allclose(
        np.asarray(out_grouped), np.asarray(out_full), rtol=1e-5, atol=1e-6
    )
    assert calls == [(G, D)]  # tower ran on G rows, not B

    # packer violation (more distinct groups than G): overflow rows come
    # back NaN — loud, never another group's output
    out_over = nn.apply_grouped(
        lambda inp: nn.mlp_apply(params, inp), x, group_ids, num_groups=G // 2
    )
    over = np.isnan(np.asarray(out_over)).any(axis=-1)
    assert over.any() and not over.all()
    kept_groups = np.sort(np.unique(np.asarray(group_ids)))[: G // 2]
    assert set(np.asarray(group_ids)[~over].tolist()) == set(kept_groups.tolist())


def test_dssm_score_items_matches_pairwise():
    model = DSSM(emb_dim=8, capacity=1 << 12, num_user_feats=2, num_item_feats=2,
                 hidden=(16, 8))
    tr = Trainer(model, make("adagrad", lr=0.1), optax.adam(1e-3))
    st = tr.init(0)
    gen = SyntheticTwoTower(batch_size=64, num_user=2, num_item=2, vocab=500, seed=3)
    b = J(gen.batch())
    st, _ = tr.train_step(st, b)
    # build inputs for eval and compare score_items against apply()
    tables = dict(st.tables)
    tables, views, _ = tr._lookup_all(tables, b, st.step, False)
    embs = {n: v[0].astype(jnp.float32) for n, v in views.items()}
    inputs = tr._build_inputs(embs, views, b)
    u, v = model.towers(st.dense, inputs)
    pair = model.apply(st.dense, inputs, train=False)
    grouped = model.score_items(st.dense, u, v[:, None, :])[:, 0]
    np.testing.assert_allclose(np.asarray(pair), np.asarray(grouped), rtol=1e-5)


# ------------------------------------------------- filter × optimizer matrix


FILTERS = [
    None,
    CounterFilter(filter_freq=2),
    CBFFilter(filter_freq=2, max_element_size=1 << 12),
]
OPTS = ["sgd", "adagrad", "adagrad_decay", "adam", "adam_async", "adamw", "ftrl"]


@pytest.mark.parametrize("opt_name", OPTS)
@pytest.mark.parametrize("filt", FILTERS, ids=["none", "counter", "cbf"])
def test_filter_optimizer_matrix(opt_name, filt):
    """Every admission filter must compose with every optimizer: blocked keys
    take no updates, admitted keys train (the reference's ~80-test matrix)."""
    ev = EmbeddingVariableOption(
        init=InitializerOption(kind="constant", constant=0.0),
        counter_filter=filt if isinstance(filt, CounterFilter) else None,
        cbf_filter=filt if isinstance(filt, CBFFilter) else None,
    )
    t = EmbeddingTable(TableConfig(name="m", dim=4, capacity=256, ev=ev))
    opt = make(opt_name, lr=0.1)
    s = ensure_slots(t, t.create(), opt)
    ids = jnp.array([5], jnp.int32)
    for i in range(3):
        s, res = t.lookup_unique(s, ids, step=i)
        s = apply_gradients(t, s, opt, res, jnp.ones_like(res.embeddings), step=i)
    emb = np.asarray(t.lookup_readonly(s, ids))[0]
    # after 3 touches every filter admits (freq >= 2) and training moved
    # the weight negative
    assert (emb < 0).all(), (opt_name, filt, emb)
    if filt is not None:
        # fresh key blocked on first touch: no update applied
        ids2 = jnp.array([99], jnp.int32)
        s, res2 = t.lookup_unique(s, ids2, step=10)
        s = apply_gradients(t, s, opt, res2, jnp.ones_like(res2.embeddings), step=10)
        emb2 = np.asarray(t.lookup_readonly(s, ids2))[0]
        np.testing.assert_allclose(emb2, 0.0, atol=1e-7)
