"""Sparse-optimizer tests — semantics coverage in the spirit of DeepRec's
filter×optimizer matrix (python/ops/embedding_variable_ops_test.py:1007-1063)
plus numeric cross-checks against hand-computed updates."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeprec_tpu import (
    CounterFilter,
    EmbeddingTable,
    EmbeddingVariableOption,
    InitializerOption,
    TableConfig,
)
from deeprec_tpu.optim import (
    Adagrad,
    AdagradDecay,
    Adam,
    AdamAsync,
    AdamW,
    Ftrl,
    GradientDescent,
    apply_gradients,
    ensure_slots,
    make,
)

ALL_OPTS = [
    GradientDescent(lr=0.1),
    Adagrad(lr=0.1),
    AdagradDecay(lr=0.1, accumulator_decay_step=5),
    Adam(lr=0.01),
    AdamAsync(lr=0.01),
    AdamAsync(lr=0.01, apply_sparse_rmsprop=True),
    AdamW(lr=0.01),
    Ftrl(lr=0.1),
]


def zero_init_table(**kw):
    base = dict(
        name="t",
        dim=4,
        capacity=128,
        ev=EmbeddingVariableOption(init=InitializerOption(kind="constant", constant=0.0)),
    )
    base.update(kw)
    return EmbeddingTable(TableConfig(**base))


def run_steps(t, opt, ids, grads, n=3):
    s = ensure_slots(t, t.create(), opt)
    for i in range(n):
        s, res = t.lookup_unique(s, ids, step=i)
        g = jnp.broadcast_to(grads, res.embeddings.shape)
        s = apply_gradients(t, s, opt, res, g, step=i)
    return t, s


@pytest.mark.parametrize("opt", ALL_OPTS, ids=lambda o: type(o).__name__ + (
    "_rmsprop" if getattr(o, "apply_sparse_rmsprop", False) else ""))
def test_optimizer_moves_weights_down_gradient(opt):
    t = zero_init_table()
    ids = jnp.array([11, 22], jnp.int32)
    t, s = run_steps(t, opt, ids, jnp.float32(1.0), n=3)
    emb = np.asarray(t.lookup_readonly(s, ids))
    # constant positive gradient must push weights negative
    assert (emb < 0).all(), emb


def test_sgd_exact():
    t = zero_init_table()
    opt = GradientDescent(lr=0.5)
    s = ensure_slots(t, t.create(), opt)
    ids = jnp.array([7], jnp.int32)
    s, res = t.lookup_unique(s, ids, step=0)
    g = jnp.ones_like(res.embeddings)
    s = apply_gradients(t, s, opt, res, g, step=0)
    emb = np.asarray(t.lookup_readonly(s, ids))[0]
    np.testing.assert_allclose(emb, -0.5, rtol=1e-6)


def test_adagrad_exact():
    t = zero_init_table()
    opt = Adagrad(lr=1.0, initial_accumulator_value=0.0)
    s = ensure_slots(t, t.create(), opt)
    ids = jnp.array([7], jnp.int32)
    s, res = t.lookup_unique(s, ids, step=0)
    g = jnp.full_like(res.embeddings, 2.0)
    s = apply_gradients(t, s, opt, res, g, step=0)
    # acc = 4, update = 1.0 * 2 / 2 = 1
    emb = np.asarray(t.lookup_readonly(s, ids))[0]
    np.testing.assert_allclose(emb, -1.0, rtol=1e-5)


def test_adam_matches_reference_formula():
    t = zero_init_table()
    opt = Adam(lr=0.1)
    s = ensure_slots(t, t.create(), opt)
    ids = jnp.array([3], jnp.int32)
    w, m, v = 0.0, 0.0, 0.0
    for i in range(4):
        s, res = t.lookup_unique(s, ids, step=i)
        g = jnp.full_like(res.embeddings, 0.5)
        s = apply_gradients(t, s, opt, res, g, step=i)
        m = 0.9 * m + 0.1 * 0.5
        v = 0.999 * v + 0.001 * 0.25
        alpha = 0.1 * np.sqrt(1 - 0.999 ** (i + 1)) / (1 - 0.9 ** (i + 1))
        w = w - alpha * m / (np.sqrt(v) + 1e-8)
    emb = np.asarray(t.lookup_readonly(s, ids))[0]
    np.testing.assert_allclose(emb, w, rtol=1e-3)


def test_adam_async_beta_powers_advance():
    t = zero_init_table()
    opt = AdamAsync(lr=0.01)
    s = ensure_slots(t, t.create(), opt)
    ids = jnp.array([3], jnp.int32)
    for i in range(3):
        s, res = t.lookup_unique(s, ids, step=i)
        s = apply_gradients(t, s, opt, res, jnp.ones_like(res.embeddings), step=i)
    b1p = float(s.slots["scalar/beta1_power"][0, 0])
    np.testing.assert_allclose(b1p, 0.9**4, rtol=1e-5)


def test_ftrl_l1_produces_zeros():
    t = zero_init_table()
    opt = Ftrl(lr=0.5, l1=100.0)  # huge l1 -> everything clamped to 0
    s = ensure_slots(t, t.create(), opt)
    ids = jnp.array([9], jnp.int32)
    s, res = t.lookup_unique(s, ids, step=0)
    s = apply_gradients(t, s, opt, res, jnp.ones_like(res.embeddings), step=0)
    emb = np.asarray(t.lookup_readonly(s, ids))[0]
    np.testing.assert_allclose(emb, 0.0)


def test_grad_averaging_with_counts():
    t = zero_init_table()
    opt = GradientDescent(lr=1.0)
    s = ensure_slots(t, t.create(), opt)
    # id 5 appears 4 times; summed grad = 4, averaged = 1
    ids = jnp.array([5, 5, 5, 5], jnp.int32)
    s, res = t.lookup_unique(s, ids, step=0)
    g_sum = jnp.full_like(res.embeddings, 4.0)
    s = apply_gradients(t, s, opt, res, g_sum, step=0, grad_averaging=True)
    emb = np.asarray(t.lookup_readonly(s, jnp.array([5], jnp.int32)))[0]
    np.testing.assert_allclose(emb, -1.0, rtol=1e-6)


def test_filter_blocks_updates_until_admitted():
    t = zero_init_table(
        ev=EmbeddingVariableOption(
            init=InitializerOption(kind="constant", constant=0.0),
            counter_filter=CounterFilter(filter_freq=2),
        )
    )
    opt = GradientDescent(lr=1.0)
    s = ensure_slots(t, t.create(), opt)
    ids = jnp.array([77], jnp.int32)
    s, res = t.lookup_unique(s, ids, step=0)  # freq 1: blocked
    s = apply_gradients(t, s, opt, res, jnp.ones_like(res.embeddings), step=0)
    assert np.allclose(np.asarray(t.lookup_readonly(s, ids)), 0.0)
    s, res = t.lookup_unique(s, ids, step=1)  # freq 2: admitted
    s = apply_gradients(t, s, opt, res, jnp.ones_like(res.embeddings), step=1)
    assert np.asarray(t.lookup_readonly(s, ids)).max() < 0


def test_dynamic_lr_override_no_recompile():
    t = zero_init_table()
    opt = GradientDescent(lr=0.1)
    s = ensure_slots(t, t.create(), opt)

    @jax.jit
    def step(s, ids, lr, i):
        s, res = t.lookup_unique(s, ids, step=i)
        return apply_gradients(t, s, opt, res, jnp.ones_like(res.embeddings),
                               step=i, lr=lr)

    ids = jnp.array([1], jnp.int32)
    s = step(s, ids, jnp.float32(1.0), 0)
    s = step(s, ids, jnp.float32(0.5), 1)
    emb = np.asarray(t.lookup_readonly(s, ids))[0]
    np.testing.assert_allclose(emb, -1.5, rtol=1e-6)


def test_slots_survive_rebuild():
    t = zero_init_table()
    opt = Adagrad(lr=0.1, initial_accumulator_value=0.0)
    s = ensure_slots(t, t.create(), opt)
    ids = jnp.array([1, 2, 3], jnp.int32)
    s, res = t.lookup_unique(s, ids, step=0)
    s = apply_gradients(t, s, opt, res, jnp.ones_like(res.embeddings), step=0)
    s2 = t.grow(s, 256)
    t2 = EmbeddingTable(TableConfig(name="t", dim=4, capacity=256,
        ev=t.cfg.ev))
    _, res2 = t2.lookup_unique(s2, ids, step=1)
    from deeprec_tpu.ops.packed import unpack_array

    ok = np.asarray(res2.valid)
    acc = unpack_array(np.asarray(s2.slots["accum"]), s2.capacity)[
        np.asarray(res2.slot_ix)[ok]
    ]
    np.testing.assert_allclose(acc, 1.0, rtol=1e-6)  # g^2 carried over
