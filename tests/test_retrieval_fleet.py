"""Fleet-path retrieval (frontend RETR/RITM fan-out + edge merge):
2-shard merge parity vs single-shard exact, member death mid-query ->
partial top-k served + health degraded (never a failed request), and
sticky grouped PRED routing unaffected by the new ops."""
import numpy as np
import jax.numpy as jnp
import optax
import pytest

from deeprec_tpu.data import SyntheticTwoTower
from deeprec_tpu.models import DSSM
from deeprec_tpu.optim import Adagrad
from deeprec_tpu.serving import (
    BackendServer,
    Frontend,
    ModelServer,
    Predictor,
    RetrievalEngine,
)
from deeprec_tpu.serving.predictor import parse_features
from deeprec_tpu.serving.retrieval import fill_missing_item_features

VOCAB = 200


def J(b):
    return {k: jnp.asarray(v) for k, v in b.items()}


@pytest.fixture(scope="module")
def trained(tmp_path_factory):
    from deeprec_tpu.training import Trainer
    from deeprec_tpu.training.checkpoint import CheckpointManager

    tmp = str(tmp_path_factory.mktemp("retr-fleet"))
    model = DSSM(emb_dim=8, capacity=1 << 12, num_user_feats=2,
                 num_item_feats=2, hidden=(16, 8))
    tr = Trainer(model, Adagrad(lr=0.1), optax.adam(1e-3))
    st = tr.init(0)
    gen = SyntheticTwoTower(batch_size=256, num_user=2, num_item=2,
                            vocab=VOCAB, seed=3)
    for _ in range(8):
        st, _ = tr.train_step(st, J(gen.batch()))
    CheckpointManager(tmp, tr).save(st)
    return tmp, model, gen


def make_items(n, seed=0):
    rng = np.random.default_rng(seed)
    ids = np.arange(1, n + 1, dtype=np.int64)
    return ids, {"V0": VOCAB + rng.integers(0, VOCAB, size=n),
                 "V1": 2 * VOCAB + rng.integers(0, VOCAB, size=n)}


def spawn_fleet(tmp, model, shards=2):
    backends = []
    for i in range(shards):
        p = Predictor(model, tmp)
        ms = ModelServer(p, max_batch=64, max_wait_ms=0.5)
        ms.attach_retrieval(RetrievalEngine(
            p, quantize="int8", block_rows=256, chunk=128,
            shard_index=i, num_shards=shards))
        backends.append(BackendServer(ms, port=0).start())
    fe = Frontend([("127.0.0.1", b.port) for b in backends], model)
    return backends, fe


def user_batch(pred, gen, rows=4):
    b = gen.batch()
    user = {k: np.asarray(v)[:rows] for k, v in b.items()
            if k.startswith("U")}
    return parse_features(pred, fill_missing_item_features(pred, user))


def test_two_shard_merge_parity_and_kill_partial(trained):
    tmp, model, gen = trained
    backends, fe = spawn_fleet(tmp, model)
    try:
        ids, feats = make_items(2000)
        acc = fe.ingest_items(ids, feats)
        # broadcast ingest partitions itself: disjoint, exhaustive
        assert len(acc) == 2 and sum(acc.values()) == 2000
        assert all(v > 0 for v in acc.values())

        ref_pred = Predictor(model, tmp)
        ref = RetrievalEngine(ref_pred, quantize="int8", block_rows=256,
                              chunk=128)
        ref.upsert_items(ids, feats)
        batch = user_batch(ref_pred, gen)
        res_fleet = fe.retrieve_versioned(batch, 10)
        res_ref = ref.retrieve(batch, 10)
        assert not res_fleet.partial
        assert res_fleet.scanned == res_ref.scanned == 2000 * 4
        for i in range(4):
            assert set(res_fleet.ids[i].tolist()) == \
                set(res_ref.ids[i].tolist()), i
            np.testing.assert_allclose(
                np.sort(res_fleet.scores[i]), np.sort(res_ref.scores[i]),
                rtol=1e-5)

        # the frontend surfaces retrieval accounting
        snap = fe.stats_snapshot()
        assert snap["frontend"]["retrieval_requests"] == 1
        assert snap["frontend"]["retrieval_partials"] == 0

        # member death mid-query: partial top-k served, never a failed
        # request; health degrades but answers keep flowing
        backends[0].stop(unregister=False)  # process-death stand-in
        res_part = fe.retrieve_versioned(batch, 10)
        assert res_part.partial
        assert (res_part.ids >= 0).all()  # surviving shard fills k=10
        surviving = set(backends[1].server.retrieval.engine
                        .host_vectors()[0].tolist())
        assert set(res_part.ids.ravel().tolist()) <= surviving
        h = fe.predictor.health()
        assert h["status"] in ("degraded", "down")
        assert h["reachable"] == 1
        assert h["retrieval_partials"] == 1
        # follow-up sweeps skip the backed-off member (no connect stall)
        # but STILL report partial — its shard is missing either way
        res_next = fe.retrieve_versioned(batch, 10)
        assert res_next.partial
        assert set(res_next.ids.ravel().tolist()) <= surviving
    finally:
        for b in backends:
            try:
                b.stop()
            except Exception:
                pass
        fe.close()


def test_retr_op_leaves_grouped_routing_sticky(trained):
    """Grouped PRED requests route on the consistent-hash ring keyed by
    user payload; interleaving RETR fan-outs (which touch EVERY member)
    must not perturb that stickiness — one user keeps landing on one
    backend."""
    tmp, model, gen = trained
    backends, fe = spawn_fleet(tmp, model)
    try:
        ids, feats = make_items(500)
        fe.ingest_items(ids, feats)
        b = gen.batch()

        def grouped_req(u):
            req = {}
            for k, v in b.items():
                if k.startswith("label"):
                    continue
                v = np.asarray(v)
                req[k] = (np.repeat(v[u:u + 1], 4, axis=0)
                          if k in model.user_feats else v[u * 4:(u + 1) * 4])
            return req

        owners = {}
        for u in range(4):
            fe.request(grouped_req(u), group_users=True)
            key = fe._group_key(grouped_req(u))
            owners[u] = fe._ring.preference(key)[0]
        ubatch = user_batch(Predictor(model, tmp), gen)
        for _ in range(3):  # RETR sweeps hit EVERY member
            fe.retrieve_versioned(ubatch, 5)
        for u in range(4):
            fe.request(grouped_req(u), group_users=True)
            assert fe._ring.preference(fe._group_key(grouped_req(u)))[0] \
                == owners[u], f"user {u} remapped by RETR traffic"
        assert {e["addr"]: e["requests"] for e in
                (m.snapshot() for m in fe._members)}  # members all alive
    finally:
        for srv in backends:
            srv.stop()
        fe.close()


def test_draining_member_stays_in_retrieval_fanout(trained):
    """Corpus shards are disjoint: a DRAINING member (rolling restart)
    must keep answering RETR sweeps — excluding it would silently drop
    1/N of the catalog for the whole drain window with partial=False."""
    tmp, model, gen = trained
    backends, fe = spawn_fleet(tmp, model)
    try:
        ids, feats = make_items(1000)
        fe.ingest_items(ids, feats)
        batch = user_batch(Predictor(model, tmp), gen)
        full = fe.retrieve_versioned(batch, 10)
        fe._members[0].draining = True  # what the membership sweep sets
        drained = fe.retrieve_versioned(batch, 10)
        assert not drained.partial
        assert drained.scanned == full.scanned  # both shards swept
        for i in range(len(drained.ids)):
            assert set(drained.ids[i].tolist()) == \
                set(full.ids[i].tolist())
    finally:
        for b in backends:
            b.stop()
        fe.close()


def test_empty_shard_after_restart_degrades_health(trained):
    """A retrieval backend that respawned lost its in-process corpus and
    answers sweeps 'successfully' with nothing — health must surface the
    missing coverage (degraded: retrieval_shard_empty) even though every
    request succeeds."""
    tmp, model, gen = trained
    backends, fe = spawn_fleet(tmp, model)
    try:
        ids, feats = make_items(600)
        # ingest ONLY into shard 1's engine — shard 0 stands in for a
        # freshly respawned member with an empty corpus
        backends[1].server.retrieval.engine.upsert_items(ids, feats)
        batch = user_batch(Predictor(model, tmp), gen)
        res = fe.retrieve_versioned(batch, 5)
        assert not res.partial  # every member answered — that's the trap
        h = fe.predictor.health()
        assert h["status"] == "degraded", h
        assert h.get("degraded_reason") == "retrieval_shard_empty", h
        assert h["retrieval_empty_shards"] == 1
    finally:
        for b in backends:
            b.stop()
        fe.close()


def test_frontend_http_clamps_bad_ids_instead_of_crashing(trained):
    """The parse_features firewall through a FRONTEND-backed HttpServer:
    a negative user id must clamp-and-serve (counted), not
    AttributeError inside the parser (_FrontendPredictor implements the
    count_record_error contract the parser calls)."""
    import json
    import urllib.request

    from deeprec_tpu.serving import HttpServer

    tmp, model, gen = trained
    backends, fe = spawn_fleet(tmp, model)
    http = HttpServer(fe, port=0).start()
    try:
        ids, feats = make_items(200)
        fe.ingest_items(ids, feats)
        b = gen.batch()
        user = {k: np.asarray(v)[:2].tolist() for k, v in b.items()
                if k.startswith("U")}
        user["U0"][0] = -7  # negative id: clamp to pad, never a crash
        body = json.dumps({"features": user, "k": 5}).encode()
        r = urllib.request.urlopen(urllib.request.Request(
            f"http://127.0.0.1:{http.port}/v1/retrieve", data=body,
            headers={"Content-Type": "application/json"}, method="POST"),
            timeout=30)
        out = json.loads(r.read())
        assert len(out["items"]) == 2 and len(out["items"][0]) == 5
        assert fe.predictor.record_errors.get("bad_id") == 1
    finally:
        http.stop()
        for srv in backends:
            srv.stop()
        fe.close()


def test_backend_without_retrieval_rejects_retr(trained):
    tmp, model, gen = trained
    p = Predictor(model, tmp)
    ms = ModelServer(p, max_batch=16, max_wait_ms=0.5)
    backend = BackendServer(ms, port=0).start()
    fe = Frontend([("127.0.0.1", backend.port)], model)
    try:
        from deeprec_tpu.serving.predictor import BadRequest

        batch = user_batch(p, gen)
        with pytest.raises(BadRequest, match="retrieval not enabled"):
            fe.retrieve_versioned(batch, 5)
    finally:
        backend.stop()
        fe.close()


def test_http_retrieve_route(trained):
    """POST /v1/retrieve end to end: user-only features, pad-filled item
    side, JSON answer with items/scores/version/partial."""
    import json
    import urllib.request

    from deeprec_tpu.serving import HttpServer

    tmp, model, gen = trained
    p = Predictor(model, tmp)
    ms = ModelServer(p, max_batch=16, max_wait_ms=0.5)
    ms.attach_retrieval(RetrievalEngine(p, quantize="int8",
                                        block_rows=256, chunk=128))
    ids, feats = make_items(300)
    ms.retrieval.engine.upsert_items(ids, feats)
    http = HttpServer(ms, port=0).start()
    try:
        b = gen.batch()
        user = {k: np.asarray(v)[:2].tolist() for k, v in b.items()
                if k.startswith("U")}
        body = json.dumps({"features": user, "k": 7}).encode()
        r = urllib.request.urlopen(urllib.request.Request(
            f"http://127.0.0.1:{http.port}/v1/retrieve", data=body,
            headers={"Content-Type": "application/json"}, method="POST"),
            timeout=30)
        out = json.loads(r.read())
        assert len(out["items"]) == 2 and len(out["items"][0]) == 7
        assert all(i in set(ids.tolist()) for i in out["items"][0])
        assert out["partial"] is False
        assert out["candidates_scanned"] == 600
        assert "model_version" in out
        # k past the corpus: ids pad -1 and scores serialize as null
        # (json.dumps would emit non-RFC `-Infinity` for -inf)
        body = json.dumps({"features": user, "k": 400}).encode()
        r = urllib.request.urlopen(urllib.request.Request(
            f"http://127.0.0.1:{http.port}/v1/retrieve", data=body,
            headers={"Content-Type": "application/json"}, method="POST"),
            timeout=30)
        wide = json.loads(r.read().decode())  # strict: text was valid JSON
        assert wide["items"][0][-1] == -1
        assert wide["scores"][0][-1] is None
        assert all(s is not None for s in wide["scores"][0][:300])
        # /v1/stats covers the lane
        stats = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{http.port}/v1/stats", timeout=10).read())
        assert stats["retrieval"]["requests"] == 2
        assert stats["retrieval_corpus"]["corpus_rows"] == 300
    finally:
        http.stop()
        ms.close()
