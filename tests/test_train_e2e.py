"""End-to-end training slice: WDL on synthetic Criteo must learn (AUC>0.55)
— the minimum viable milestone of SURVEY.md §7 step 6."""
import jax.numpy as jnp
import numpy as np
import optax

from deeprec_tpu.data import SyntheticCriteo
from deeprec_tpu.models import WDL
from deeprec_tpu.optim import Adagrad
from deeprec_tpu.training import Trainer


def to_jnp(batch):
    return {k: jnp.asarray(v) for k, v in batch.items()}


def test_wdl_learns_synthetic_criteo():
    model = WDL(emb_dim=8, capacity=1 << 14, hidden=(64, 32), num_cat=6, num_dense=4)
    trainer = Trainer(model, Adagrad(lr=0.2), optax.adam(5e-3))
    state = trainer.init(0)
    gen = SyntheticCriteo(batch_size=512, num_cat=6, num_dense=4, vocab=2000, seed=1)

    losses = []
    for i in range(100):
        state, mets = trainer.train_step(state, to_jnp(gen.batch()))
        losses.append(float(mets["loss"]))
    assert np.mean(losses[-10:]) < np.mean(losses[:10]), losses

    eval_gen = SyntheticCriteo(batch_size=512, num_cat=6, num_dense=4, vocab=2000, seed=99)
    mets = trainer.evaluate(state, [to_jnp(eval_gen.batch()) for _ in range(8)])
    assert mets["auc"] > 0.55, mets
    # tables actually populated (bundle-aware accessor)
    assert int(state.step) == 100
    sizes = {
        n: int(t.size(trainer.table_state(state, n)))
        for n, t in trainer.tables.items()
    }
    assert all(v > 100 for v in sizes.values()), sizes
    # Criteo tables share a config -> they must have been bundled (grouped)
    assert any(b.stacked for b in trainer.bundles.values())
