"""Auto-stage (Trainer.stage): the SmartStage analog.

The reference auto-carves the IO subgraph with a graph pass
(smart_stage_pass.cc:30); here the boundary is derived from the model's
input signature — these tests pin the derivation (key filtering), the
IO/compute overlap, and the mesh-aware sharded placement.
"""
import threading
import time

import jax
import numpy as np
import pytest

from deeprec_tpu.data import SyntheticCriteo
from deeprec_tpu.models import WDL
from deeprec_tpu.optim import Adagrad
from deeprec_tpu.training import Trainer


def small_wdl(**kw):
    return WDL(emb_dim=8, capacity=1 << 10, hidden=(16,), num_cat=4,
               num_dense=2, **kw)


def test_input_keys_from_model_signature():
    tr = Trainer(small_wdl(), Adagrad(lr=0.1))
    keys = tr.input_keys()
    assert keys == {"C1", "C2", "C3", "C4", "I1", "I2"}


def test_stage_batch_filters_and_transfers():
    tr = Trainer(small_wdl(), Adagrad(lr=0.1))
    gen = SyntheticCriteo(batch_size=32, num_cat=4, num_dense=2, vocab=100)
    batch = gen.batch()
    batch["junk_column"] = np.zeros(32)
    batch["label_aux"] = np.zeros(32, np.float32)
    staged = tr.stage_batch(batch)
    assert "junk_column" not in staged  # outside the signature: dropped
    assert "label" in staged and "label_aux" in staged  # labels ride
    assert isinstance(staged["C1"], jax.Array)
    # staged batches train as-is, and re-staging is an idempotent no-op
    state = tr.init(0)
    state, mets = tr.train_step(state, tr.stage_batch(staged))
    assert np.isfinite(float(mets["loss"]))


def test_stage_off_and_validation():
    tr = Trainer(small_wdl(), Adagrad(lr=0.1), stage="off")
    src = iter([1, 2, 3])
    assert tr.stage(src) is src
    with pytest.raises(ValueError):
        Trainer(small_wdl(), Adagrad(lr=0.1), stage="sometimes")


def test_stage_overlaps_io_with_compute():
    """With a depth-2 ring, the producer must be pulling batch i+1 while
    the consumer is still 'computing' on batch i. Sleep-based, so it
    holds even on a one-core box."""
    tr = Trainer(small_wdl(), Adagrad(lr=0.1))
    gen = SyntheticCriteo(batch_size=16, num_cat=4, num_dense=2, vocab=100)
    pulls = []

    # IO strictly faster than compute so the producer cycle (0.04s +
    # stage_batch transform) provably finishes inside the consumer's
    # 0.08s window — equal sleeps made the ordering a coin flip.
    def slow_source(n=6):
        for _ in range(n):
            time.sleep(0.04)  # "IO"
            pulls.append(time.monotonic())
            yield gen.batch()

    staged = tr.stage(slow_source())
    finishes = []
    for _ in staged:
        time.sleep(0.08)  # "compute"
        finishes.append(time.monotonic())
    assert len(finishes) == 6 and len(pulls) == 6
    # overlap: while we computed on batch i, the ring fetched ahead —
    # batch i+1 was pulled BEFORE we finished computing batch i
    overlapped = sum(
        pulls[i + 1] < finishes[i] for i in range(5)
    )
    assert overlapped >= 4, (pulls, finishes)
    # No wall-clock bound: the ordering assertion above IS the overlap
    # proof, and a scheduler hiccup on a loaded single-core box pushed a
    # wall < 0.68s check into flake territory (sleep() only guarantees a
    # MINIMUM delay).


def test_prefetcher_sharding_places_on_mesh():
    """The bare `staged()` default lands batches on device 0 (then a
    sharded step re-transfers them); the `sharding=` knob threads the mesh
    placement through the DEFAULT transform so the staged transfer lands
    already split. Pins both placements, and that Trainer.stage's ring
    (the auto path) stays mesh-placed end to end."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from deeprec_tpu.data.prefetch import staged
    from deeprec_tpu.parallel import ShardedTrainer, make_mesh

    mesh = make_mesh()
    gen = SyntheticCriteo(batch_size=32, num_cat=4, num_dense=2, vocab=100)

    # default transform: everything on ONE device (the confirmed hazard)
    ring = staged(iter([gen.batch()]))
    b0 = next(ring)
    ring.close()
    assert {len(v.sharding.device_set) for v in b0.values()} == {1}

    # sharding= threads the mesh through the default transform
    from deeprec_tpu.parallel.mesh import DATA_AXIS

    sh = NamedSharding(mesh, P(DATA_AXIS))
    ring = staged(iter([gen.batch()]), sharding=sh)
    b1 = next(ring)
    ring.close()
    assert all(v.sharding == sh for v in b1.values())

    # the auto-stage ring (Trainer.stage) places mesh-wide via its own
    # transform — batches delivered by the ring are split over every device
    tr = ShardedTrainer(small_wdl(), Adagrad(lr=0.1), mesh=mesh)
    ring = tr.stage(iter([gen.batch()]))
    b2 = next(ring)
    ring.close()
    assert {len(v.sharding.device_set) for v in b2.values()} == {
        mesh.devices.size
    }


def test_sharded_stage_places_on_mesh():
    from deeprec_tpu.parallel import ShardedTrainer, make_mesh

    mesh = make_mesh()
    tr = ShardedTrainer(small_wdl(), Adagrad(lr=0.1), mesh=mesh)
    gen = SyntheticCriteo(batch_size=32, num_cat=4, num_dense=2, vocab=100)
    staged = tr.stage_batch(gen.batch())
    shard_counts = {len(v.sharding.device_set) for v in staged.values()}
    assert shard_counts == {mesh.devices.size}  # split over every device
    state = tr.init(0)
    state, mets = tr.train_step(state, staged)
    assert np.isfinite(float(mets["loss"]))
