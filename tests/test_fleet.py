"""Elastic serving fleet (serving/fleet.py + the frontend's dynamic
membership): lease-file discovery edge cases, consistent-hash remap
bounds, the drain protocol, dead-member re-probe, and the autoscaler's
hysteresis/cooldown policy — all deterministic (fake clocks, direct
sweep calls), no test sleeps to observe a state it can force."""
import json
import os
import threading
import time

import numpy as np
import pytest

from deeprec_tpu.online import faults
from deeprec_tpu.online.supervisor import ProcessSpec, Supervisor
from deeprec_tpu.serving import fleet
from deeprec_tpu.serving.fleet import (
    FleetAutoscaler,
    FleetLoad,
    FleetRegistry,
    HashRing,
    LeaseStamper,
)

# --------------------------------------------------------------- registry


def test_registry_stamp_sweep_unregister(tmp_path):
    r = FleetRegistry(str(tmp_path), lease_secs=5.0)
    st = LeaseStamper(r, "127.0.0.1:7001", capacity=4,
                      version_fn=lambda: 3, name="b0")
    st.stamp()
    (m,) = r.members()
    assert (m.addr, m.status, m.capacity, m.model_version, m.name) == (
        "127.0.0.1:7001", "up", 4, 3, "b0")
    assert m.age < 5.0 and m.pid == os.getpid()
    st.stop()  # unregisters
    assert r.members() == []


def test_registry_stale_lease_eviction_and_readmission_race(tmp_path):
    """The eviction race with a live-but-slow member: a stale lease
    drops the member from routing, but the FILE survives (eviction is a
    routing decision, not a tombstone) — the moment the slow member
    stamps again it is readmitted. gc() only reaps on a much longer
    clock, so the re-stamp never races an unlink."""
    r = FleetRegistry(str(tmp_path), lease_secs=5.0)
    st = LeaseStamper(r, "127.0.0.1:7002")
    st.stamp()
    now = time.time()
    assert len(r.members(now=now)) == 1
    late = now + 6.0
    assert r.members(now=late) == []          # stale -> evicted
    assert os.path.exists(st.registry.lease_path("127.0.0.1:7002"))
    # not even a 10x-stale sweep unlinked it yet
    assert r.gc(evict_secs=50.0) == 0
    st.stamp()                                 # the slow member catches up
    assert len(r.members()) == 1               # readmitted, same lease file
    # long-dead: gc reaps
    assert r.gc(evict_secs=-1.0) == 1
    assert r.members() == []


def test_registry_torn_lease_write_is_skipped_not_trusted(tmp_path):
    """A torn lease (non-atomic writer / FS corruption — planted by the
    fault injector, since the registry's own writes are atomic
    tmp+rename) reads as 'no lease': the sweep skips it without
    crashing, and a later GOOD stamp over the same path recovers."""
    r = FleetRegistry(str(tmp_path), lease_secs=5.0)
    good = LeaseStamper(r, "127.0.0.1:7003")
    good.stamp()
    path = faults.torn_lease_write(r, "127.0.0.1:7004")
    assert os.path.exists(path)
    ms = r.members()
    assert [m.addr for m in ms] == ["127.0.0.1:7003"]  # torn one invisible
    # schema garbage (valid JSON, wrong shape) is equally skipped
    with open(r.lease_path("127.0.0.1:7005"), "w") as f:
        json.dump({"time": "not-a-number", "addr": 9}, f)
    assert [m.addr for m in r.members()] == ["127.0.0.1:7003"]
    # the torn path recovers when its owner stamps properly
    LeaseStamper(r, "127.0.0.1:7004").stamp()
    assert [m.addr for m in r.members()] == ["127.0.0.1:7003",
                                             "127.0.0.1:7004"]


def test_registry_duplicate_addr_last_writer_wins_quarantine(tmp_path):
    """Two backend processes claiming ONE addr (a respawn racing the old
    generation, a copy-paste config): the newest stamp wins the addr,
    the older lease is quarantined (renamed, visible) — and membership
    never shows the addr twice."""
    r = FleetRegistry(str(tmp_path), lease_secs=30.0)
    old_path = r.lease_path("127.0.0.1:7010", pid=1111)
    new_path = r.lease_path("127.0.0.1:7010", pid=2222)
    t = time.time()
    for path, pid, stamp in ((old_path, 1111, t - 5), (new_path, 2222, t)):
        with open(path + ".tmp", "w") as f:
            json.dump({"pid": pid, "time": stamp, "step": None,
                       "status": "up", "addr": "127.0.0.1:7010",
                       "role": "backend", "capacity": 1,
                       "model_version": 0, "started_at": stamp,
                       "name": ""}, f)
        os.replace(path + ".tmp", path)
    ms = r.members()
    assert len(ms) == 1 and ms[0].pid == 2222    # last writer wins
    assert not os.path.exists(old_path)          # older claim quarantined
    assert os.path.exists(old_path + ".quarantined")
    assert os.path.exists(new_path)


def test_registry_drain_request_roundtrip(tmp_path):
    r = FleetRegistry(str(tmp_path))
    assert r.drain_requested("127.0.0.1:7020") is None
    r.request_drain("127.0.0.1:7020", respawn=True)
    req = r.drain_requested("127.0.0.1:7020")
    assert req and req["respawn"] is True
    r.clear_drain("127.0.0.1:7020")
    assert r.drain_requested("127.0.0.1:7020") is None


def test_lease_stamper_picks_up_drain_and_exit_codes(tmp_path):
    """The member side of the drain protocol: the stamper's loop sees
    the drain-request file, stamps ``draining`` (frontends stop new
    assignments off that), and the exit code follows the respawn flag —
    EXIT_RESCALE for rolling restarts, 0 for retirement."""
    from deeprec_tpu.parallel.elastic import EXIT_RESCALE

    r = FleetRegistry(str(tmp_path), lease_secs=5.0)
    st = LeaseStamper(r, "127.0.0.1:7030", interval=0.05).start()
    try:
        assert r.members()[0].status == "up"
        r.request_drain("127.0.0.1:7030", respawn=True)
        assert st.draining.wait(timeout=5.0)
        (m,) = r.members()                      # still a member...
        assert m.status == "draining"           # ...but marked leaving
        assert r.members(include_draining=False) == []
        assert st.exit_code() == EXIT_RESCALE
    finally:
        st.stop()
    st2 = LeaseStamper(r, "127.0.0.1:7031")
    st2.begin_drain(respawn=False)
    assert st2.exit_code() == 0


# -------------------------------------------------------------- hash ring


def test_ring_remap_fraction_on_join_at_most_2_over_n():
    """THE consistency pin (ISSUE acceptance): adding one member to an
    N-member ring remaps at most 2/N of sticky users (expected ~1/(N+1);
    modular routing would remap ~N/(N+1)). Pinned across fleet sizes on
    10k keys."""
    keys = list(range(10_000))
    for n in (2, 3, 4, 8):
        members = [f"10.0.0.{i}:8500" for i in range(n)]
        before = HashRing(members)
        after = HashRing(members + [f"10.0.0.{n}:8500"])
        moved = sum(1 for k in keys if before.lookup(k) != after.lookup(k))
        frac = moved / len(keys)
        assert frac <= 2.0 / n, (n, frac)
        # and the ring actually hands the new member SOME keys
        assert frac > 0.0, n


def test_ring_leave_falls_to_preference_successor():
    """When a member leaves, each of its keys lands exactly on that
    key's next preference — so sibling-retry failover and post-churn
    routing agree (a retried request warms the SAME backend the users
    are about to move to)."""
    members = [f"10.0.0.{i}:8500" for i in range(4)]
    ring = HashRing(members)
    gone = members[1]
    shrunk = HashRing([m for m in members if m != gone])
    for k in range(3000):
        pref = ring.preference(k)
        if pref[0] == gone:
            assert shrunk.lookup(k) == pref[1], k
        else:
            assert shrunk.lookup(k) == pref[0], k


def test_ring_spread_and_determinism():
    members = [f"10.0.0.{i}:8500" for i in range(4)]
    ring = HashRing(members)
    counts = {m: 0 for m in members}
    for k in range(8000):
        counts[ring.lookup(k)] += 1
    # virtual nodes keep the split sane (no member starved or doubled)
    for m, c in counts.items():
        assert 0.5 * 2000 < c < 2.0 * 2000, counts
    # identical across instances (unsalted hash — every frontend replica
    # and every restart builds the same ring)
    again = HashRing(list(reversed(members)))
    assert all(ring.lookup(k) == again.lookup(k) for k in range(500))
    with pytest.raises(RuntimeError, match="empty hash ring"):
        HashRing([]).lookup(1)


# ------------------------------------------------------------- autoscaler


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _scaler(n0=2, **kw):
    state = {"n": n0, "ups": 0, "downs": 0}

    def up():
        state["n"] += 1
        state["ups"] += 1

    def down(_n):
        state["n"] -= 1
        state["downs"] += 1

    clock = _Clock()
    kw.setdefault("min_members", 1)
    kw.setdefault("max_members", 4)
    kw.setdefault("p99_high_ms", 100.0)
    kw.setdefault("p99_low_ms", 20.0)
    kw.setdefault("queue_high", 64)
    kw.setdefault("queue_low", 4)
    kw.setdefault("sustain", 3)
    kw.setdefault("cooldown_secs", 30.0)
    a = FleetAutoscaler(members_fn=lambda: state["n"], scale_up=up,
                        scale_down=down, clock=clock, **kw)
    return a, state, clock


def _hot(p99=500.0, q=0):
    return FleetLoad(p99_ms=p99, queue_depth=q, members=0)


def _cold():
    return FleetLoad(p99_ms=1.0, queue_depth=0, members=0)


def test_autoscaler_hysteresis_requires_sustained_breach():
    a, state, clock = _scaler()
    assert a.observe(_hot()) is None      # 1st breach: no action
    assert a.observe(_cold()) is None     # breach streak broken
    assert a.observe(_hot()) is None
    assert a.observe(_hot()) is None
    assert a.observe(_hot()) == "up"      # 3rd consecutive: scale up
    assert state["n"] == 3


def test_autoscaler_cooldown_blocks_flapping():
    a, state, clock = _scaler()
    for _ in range(3):
        a.observe(_hot())
    assert state["n"] == 3
    for _ in range(10):                    # still hot, but cooling down
        assert a.observe(_hot()) is None
    clock.t += 31.0                        # cooldown expired: the breach
    # streak accumulated through the cooldown, so the FIRST eligible
    # tick acts (sustained hot shouldn't restart its hysteresis count)
    assert a.observe(_hot()) == "up" and state["n"] == 4


def test_autoscaler_bounds_and_scale_down():
    a, state, clock = _scaler(n0=4)
    for _ in range(6):                     # hot at max: never exceeds
        a.observe(_hot())
        clock.t += 100.0
    assert state["n"] == 4 and state["ups"] == 0
    for _ in range(3):
        a.observe(_cold())
    assert state["n"] == 3                 # calm sustained: retire one
    clock.t += 100.0
    for _ in range(10):
        a.observe(_cold())
        clock.t += 100.0
    assert state["n"] == 1 and state["downs"] == 3  # floor holds


def test_autoscaler_queue_depth_alone_breaches():
    a, state, clock = _scaler()
    for _ in range(3):
        a.observe(_hot(p99=1.0, q=1000))   # p99 fine, queue exploding
    assert state["n"] == 3


def test_autoscaler_no_signal_never_acts():
    a, state, clock = _scaler()
    for _ in range(10):
        assert a.observe(None) is None
        assert a.observe(FleetLoad(p99_ms=None, queue_depth=0,
                                   members=2)) is None
    assert state["n"] == 2


def test_autoscaler_manual_target_walks_2_4_2():
    """The bench's deterministic scale event: set_target overrides load,
    one member per tick, cooldown-paced, and hands control back to the
    load policy at the target."""
    a, state, clock = _scaler(cooldown_secs=5.0)
    a.set_target(4)
    assert a.observe(None) == "up" and state["n"] == 3
    assert a.observe(None) is None         # cooling
    clock.t += 6.0
    assert a.observe(None) == "up" and state["n"] == 4
    clock.t += 6.0
    assert a.observe(None) is None and a.at_target()
    a.set_target(2)
    assert a.observe(_hot()) == "down"     # manual target beats load
    clock.t += 6.0
    assert a.observe(_hot()) == "down" and state["n"] == 2
    assert a.actions[-1]["why"] == "target 2"


def test_load_from_stats_decodes_fleet_load():
    assert fleet.load_from_stats({}) is None
    got = fleet.load_from_stats({"fleet_load": {
        "e2e_p99_ms": 12.5, "queue_depth": 3, "members": 2}})
    assert got == FleetLoad(p99_ms=12.5, queue_depth=3, members=2)


# ------------------------------------------------- supervisor dynamic specs


def test_supervisor_add_remove_specs_runtime(tmp_path):
    """The autoscaler's supervisor surface: add_spec spawns a NEW worker
    while the watch loop runs (keep_alive: the loop survives every
    current worker finishing), remove_spec releases one; clean exits
    mark done without respawn."""
    import sys

    sup = Supervisor([], poll_secs=0.05, keep_alive=True,
                     on_event=lambda line: None).start()
    try:
        sleeper = [sys.executable, "-c",
                   "import time; time.sleep(60)"]
        quick = [sys.executable, "-c", "pass"]
        sup.add_spec(ProcessSpec(name="w1", argv=sleeper, lease_secs=None))
        sup.add_spec(ProcessSpec(name="w2", argv=quick, lease_secs=None))
        assert sup.pid("w1") is not None
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not sup.state("w2").done:
            time.sleep(0.05)
        assert sup.state("w2").done           # clean exit: done, no respawn
        assert sup.stats()["w2"]["restarts"] == 0
        assert sup.remove_spec("w2", kill=False)
        assert sup.state("w2") is None
        assert sup.remove_spec("w1", kill=True)   # reaps the sleeper
        assert not sup.remove_spec("nope")
        sup.add_spec(ProcessSpec(name="w3", argv=quick))
        with pytest.raises(ValueError, match="duplicate"):
            sup.add_spec(ProcessSpec(name="w3", argv=quick))
    finally:
        sup.stop()


# ----------------------------------------------- frontend fleet integration

jnp = pytest.importorskip("jax.numpy")


def _make_tier_ckpt(tmp_path):
    import optax

    from deeprec_tpu.data import SyntheticCriteo
    from deeprec_tpu.models import WDL
    from deeprec_tpu.optim import Adagrad
    from deeprec_tpu.training import Trainer
    from deeprec_tpu.training.checkpoint import CheckpointManager

    model = WDL(emb_dim=8, capacity=1 << 12, hidden=(32, 16), num_cat=4,
                num_dense=2)
    tr = Trainer(model, Adagrad(lr=0.1), optax.adam(1e-3))
    st = tr.init(0)
    gen = SyntheticCriteo(batch_size=64, num_cat=4, num_dense=2,
                          vocab=2000, seed=13)
    for _ in range(3):
        st, _ = tr.train_step(
            st, {k: jnp.asarray(v) for k, v in gen.batch().items()})
    CheckpointManager(str(tmp_path), tr).save(st)
    req = {k: np.asarray(v) for k, v in gen.batch().items()
           if not k.startswith("label")}
    return model, req


def _backend(model, ckpt, registry, **kw):
    from deeprec_tpu.serving import BackendServer, ModelServer, Predictor

    return BackendServer(
        ModelServer(Predictor(model, ckpt), max_batch=64, max_wait_ms=1.0),
        registry=registry, **kw).start()


@pytest.fixture(scope="module")
def fleet_ckpt(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("fleet-wdl")
    model, req = _make_tier_ckpt(tmp)
    return model, str(tmp), req


def test_frontend_discovers_admits_and_retires_by_lease(fleet_ckpt,
                                                        tmp_path):
    """Dynamic membership end to end, no frontend restart anywhere: a
    frontend born with an EMPTY registry admits a backend when its lease
    lands, admits a second joiner at runtime, spreads traffic over both,
    and retires a member whose lease unregisters — all through direct
    sweep calls (deterministic), traffic green throughout."""
    from deeprec_tpu.serving import Frontend

    model, ckpt, req = fleet_ckpt
    reg = FleetRegistry(str(tmp_path), lease_secs=30.0)
    fe = Frontend(None, model, registry=reg, membership_secs=0.0,
                  reprobe_secs=0.0)
    try:
        with pytest.raises(RuntimeError, match="no fleet members"):
            fe.request(req)
        b0 = _backend(model, ckpt, reg, member_name="b0")
        try:
            # lazy admission: the next request forces one sweep
            out = fe.request(req)
            assert np.asarray(out).size > 0
            assert [m.addr for m in fe._members] == [b0.addr]
            b1 = _backend(model, ckpt, reg, member_name="b1")
            try:
                fe.refresh_membership()
                assert len(fe._members) == 2
                for _ in range(8):
                    fe.request(req)
                counts = [m.snapshot()["requests"] for m in fe._members]
                assert all(c > 0 for c in counts), counts
            finally:
                b1.stop()            # unregisters its lease
            admitted, retired = fe.refresh_membership()
            assert retired == [b1.addr]
            assert [m.addr for m in fe._members] == [b0.addr]
            fe.request(req)          # tier keeps serving
        finally:
            b0.stop()
    finally:
        fe.close()


def test_frontend_drain_excludes_new_assignments_zero_failures(fleet_ckpt,
                                                               tmp_path):
    """The drain protocol under live traffic: request_drain -> the
    member stamps ``draining`` -> the frontend's next sweep stops NEW
    assignments (ring excludes it; plain round-robin skips it) while
    in-flight work finishes -> backend.drain() returns the retirement
    exit code -> retirement. Zero failed requests throughout."""
    from deeprec_tpu.serving import Frontend

    model, ckpt, req = fleet_ckpt
    # short leases -> fast stamper loops (lease_secs/3), so the drain
    # request lands within the test without sleeping multiples of 10 s
    reg = FleetRegistry(str(tmp_path), lease_secs=1.5)
    b0 = _backend(model, ckpt, reg, member_name="b0")
    b1 = _backend(model, ckpt, reg, member_name="b1")
    fe = Frontend(None, model, registry=reg, membership_secs=0.05,
                  reprobe_secs=0.0)
    errors, done = [], threading.Event()

    def driver():
        try:
            while not done.is_set():
                fe.request(req)
        except Exception as e:  # pragma: no cover - the assertion
            errors.append(e)

    th = threading.Thread(target=driver)
    try:
        fe.refresh_membership()
        assert len(fe._members) == 2
        assert fe.warmup(req) == 2        # compile both before load
        th.start()
        time.sleep(0.2)
        reg.request_drain(b1.addr, respawn=False)
        assert b1.stamper.draining.wait(timeout=5.0)
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            m = fe._by_addr.get(b1.addr)
            if m is not None and m.draining:
                break
            time.sleep(0.02)
        m = fe._by_addr[b1.addr]
        assert m.draining                 # sweep saw the draining lease
        assert b1.addr not in fe._ring.members  # no NEW grouped routing
        # Requests ASSIGNED before the sweep flipped the flag may still
        # land (that's the protocol: in-flight finishes) — wait for the
        # counter to go quiet, THEN pin that no NEW assignments arrive.
        before = m.snapshot()["requests"]
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            time.sleep(0.2)
            cur = m.snapshot()["requests"]
            if cur == before:
                break
            before = cur
        time.sleep(0.4)                   # traffic continues on b0 only
        assert m.snapshot()["requests"] == before
        rc = b1.drain(timeout=10.0)       # in-flight quiet -> stop
        assert rc == 0                    # retirement, not respawn
        time.sleep(0.2)                   # frontend retires the lease
        assert b1.addr not in fe._by_addr
        done.set()
        th.join(timeout=30)
        assert not errors, errors         # zero failed requests
        assert fe._members and fe._members[0].addr == b0.addr
    finally:
        done.set()
        if th.is_alive():
            th.join(timeout=10)
        fe.close()
        b0.stop()
        b1.stop()


def test_frontend_reprobes_and_readmits_same_addr(fleet_ckpt):
    """Satellite pin: a member that died and came back at the SAME addr
    (external restart — no membership churn, static list) is readmitted
    by the periodic re-probe WITHOUT any client traffic, health call, or
    frontend restart risking a request on it."""
    from deeprec_tpu.serving import BackendServer, Frontend, ModelServer, \
        Predictor

    model, ckpt, req = fleet_ckpt
    b = BackendServer(ModelServer(Predictor(model, ckpt), max_batch=64,
                                  max_wait_ms=1.0)).start()
    port = b.port
    fe = Frontend([("127.0.0.1", port)], model, reprobe_secs=0.1)
    try:
        fe.request(req)
        b.stop()                          # death: sockets sever
        with pytest.raises(RuntimeError):
            fe.request(req)               # all members down
        m = fe._members[0]
        assert m.fails > 0
        b2 = BackendServer(ModelServer(Predictor(model, ckpt),
                                       max_batch=64, max_wait_ms=1.0),
                           port=port).start()
        try:
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline and m.fails:
                time.sleep(0.05)          # NO traffic: re-probe only
            assert m.fails == 0 and m.available(time.monotonic())
            fe.request(req)               # traffic resumes
        finally:
            b2.stop()
    finally:
        fe.close()


def test_frontend_stats_carry_fleet_load_window(fleet_ckpt):
    """/v1/stats now carries the autoscaler's observation: a windowed
    e2e p99 and member queue depth under fleet_load, decodable by
    fleet.load_from_stats."""
    from deeprec_tpu.serving import BackendServer, Frontend, ModelServer, \
        Predictor

    model, ckpt, req = fleet_ckpt
    b = BackendServer(ModelServer(Predictor(model, ckpt), max_batch=64,
                                  max_wait_ms=1.0)).start()
    fe = Frontend([("127.0.0.1", b.port)], model, reprobe_secs=0.0)
    try:
        for _ in range(5):
            fe.request(req)
        snap = fe.stats_snapshot()
        fl = snap["fleet_load"]
        assert fl["members"] == 1 and fl["draining"] == 0
        assert fl["queue_depth"] >= 0
        load = fleet.load_from_stats(snap)
        if fe.stats.registry is not None:   # obs plane on (default)
            assert load.p99_ms is not None and load.p99_ms > 0
        member = snap["members"][0]
        assert "window" in member["stats"]
        assert member["stats"]["window"]["window_seconds"] == 60
    finally:
        fe.close()
        b.stop()
