"""Off-the-hot-path choreography (round 9): background checkpoint writer,
device-compacted incremental saves, overlapped multi-tier migration.

Overlap is asserted by EVENT ORDERING (a gated writer that a synchronous
implementation would deadlock against), never by wall-clock margins — the
ADVICE round-5 deflake lesson. Parity is asserted bit-exact on table ints
(keys + fused metadata) and byte-exact on float leaves: the async writer
must produce files indistinguishable from the synchronous saver's.
"""
import os
import threading

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from deeprec_tpu.data import SyntheticCriteo
from deeprec_tpu.models import WDL
from deeprec_tpu.optim import Adagrad
from deeprec_tpu.training import Trainer
from deeprec_tpu.training.checkpoint import CheckpointManager


def small():
    return WDL(emb_dim=8, capacity=1 << 12, hidden=(32,), num_cat=4,
               num_dense=2)


def make_trainer():
    return Trainer(small(), Adagrad(lr=0.1), optax.adam(1e-3))


def id_batch(ids):
    """A WDL batch touching exactly `ids` (dirty-row control)."""
    ids = np.asarray(ids, np.int32)
    n = len(ids)
    rng = np.random.default_rng(ids[0] if n else 0)
    b = {f"C{i + 1}": jnp.asarray(ids) for i in range(4)}
    b["I1"] = jnp.asarray(rng.standard_normal((n, 1)).astype(np.float32))
    b["I2"] = jnp.asarray(rng.standard_normal((n, 1)).astype(np.float32))
    b["label"] = jnp.asarray((rng.random(n) < 0.5).astype(np.float32))
    return b


def gen_batches(n, seed=3):
    g = SyntheticCriteo(batch_size=256, num_cat=4, num_dense=2, vocab=1500,
                        seed=seed)
    return [{k: jnp.asarray(v) for k, v in g.batch().items()}
            for _ in range(n)]


def assert_states_identical(tr, a, b):
    """Bit-exact on table ints, byte-exact on every float leaf."""
    assert int(a.step) == int(b.step)
    for bname in tr.bundles:
        ta, tb = a.tables[bname], b.tables[bname]
        np.testing.assert_array_equal(np.asarray(ta.keys), np.asarray(tb.keys))
        np.testing.assert_array_equal(np.asarray(ta.meta), np.asarray(tb.meta))
        np.testing.assert_array_equal(
            np.asarray(ta.values), np.asarray(tb.values))
        assert set(ta.slots) == set(tb.slots)
        for sname in ta.slots:
            np.testing.assert_array_equal(
                np.asarray(ta.slots[sname]), np.asarray(tb.slots[sname]))
    for la, lb in zip(jax.tree.leaves(a.dense), jax.tree.leaves(b.dense)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


# ------------------------------------------------------------ sync == async


def test_async_full_save_restores_identical_to_sync(tmp_path):
    tr = make_trainer()
    st = tr.init(0)
    for b in gen_batches(4):
        st, _ = tr.train_step(st, b)
    ck_s = CheckpointManager(str(tmp_path / "sync"), tr)
    ck_a = CheckpointManager(str(tmp_path / "async"), tr)
    st_s, _ = ck_s.save(st)
    st_a, path = ck_a.save_async(st)
    ck_a.wait()
    assert os.path.exists(os.path.join(path, "manifest.json"))
    # the returned (dirty-cleared) states agree too
    assert_states_identical(tr, st_s, st_a)
    r_s = CheckpointManager(str(tmp_path / "sync"), make_trainer()).restore()
    r_a = CheckpointManager(str(tmp_path / "async"), make_trainer()).restore()
    assert_states_identical(tr, r_s, r_a)


def test_async_incremental_chain_restores_identical_to_sync(tmp_path):
    """full + 2 deltas, one lineage saved synchronously and one async from
    the SAME states — the restored chains must be bit-identical (the
    device-compacted export and the background writer change WHERE the
    work happens, never the bytes)."""
    tr = make_trainer()
    st = tr.init(0)
    for b in gen_batches(3):
        st, _ = tr.train_step(st, b)
    ck_s = CheckpointManager(str(tmp_path / "sync"), tr)
    ck_a = CheckpointManager(str(tmp_path / "async"), tr)
    ck_s.save(st)
    st, _ = ck_a.save_async(st)
    ck_a.wait()
    extra = gen_batches(2, seed=11)
    for b in extra:
        st, _ = tr.train_step(st, b)
    ck_s.save_incremental(st)
    st, _ = ck_a.save_incremental_async(st)
    ck_a.wait()
    st, _ = tr.train_step(st, extra[0])
    ck_s.save_incremental(st)
    st, _ = ck_a.save_incremental_async(st)
    ck_a.wait()
    r_s = CheckpointManager(str(tmp_path / "sync"), make_trainer()).restore()
    r_a = CheckpointManager(str(tmp_path / "async"), make_trainer()).restore()
    assert_states_identical(tr, r_s, r_a)


@pytest.mark.parametrize("sharded_io", [False, True])
def test_async_parity_sharded_and_parts(tmp_path, sharded_io):
    """Sharded trainer, both file formats (gathered / parts): async full +
    delta chains restore bit-identical to the synchronous saver's."""
    from deeprec_tpu.parallel import ShardedTrainer, make_mesh, shard_batch

    mesh = make_mesh(8)

    def mk():
        return ShardedTrainer(small(), Adagrad(lr=0.1), optax.adam(1e-3),
                              mesh=mesh)

    tr = mk()
    st = tr.init(0)
    batches = gen_batches(3)
    for b in batches:
        st, _ = tr.train_step(st, shard_batch(mesh, b))
    ck_s = CheckpointManager(str(tmp_path / "sync"), tr,
                             sharded_io=sharded_io)
    ck_a = CheckpointManager(str(tmp_path / "async"), tr,
                             sharded_io=sharded_io)
    ck_s.save(st)
    st, _ = ck_a.save_async(st)
    ck_a.wait()
    st, _ = tr.train_step(st, shard_batch(mesh, batches[0]))
    ck_s.save_incremental(st)
    st, _ = ck_a.save_incremental_async(st)
    ck_a.wait()
    r_s = CheckpointManager(str(tmp_path / "sync"), mk(),
                            sharded_io=sharded_io).restore()
    r_a = CheckpointManager(str(tmp_path / "async"), mk(),
                            sharded_io=sharded_io).restore()
    assert_states_identical(tr, r_s, r_a)


# ------------------------------------------------- transfer-bytes accounting


def test_incremental_transfer_bytes_scale_with_dirty_fraction(tmp_path):
    """The tentpole acceptance: incremental device->host bytes follow the
    DIRTY fraction, not the capacity. Asserted from the manager's
    accounting, with proportionality bounds loose enough for the pow2
    padding and the per-shard [C] key array the delta always carries."""
    tr = make_trainer()
    st = tr.init(0)
    st, _ = tr.train_step(st, id_batch(np.arange(2048)))  # fill
    ck = CheckpointManager(str(tmp_path), tr)
    st, _ = ck.save(st)
    full_bytes = ck.last_save["transfer_bytes"]

    st, _ = tr.train_step(st, id_batch(np.arange(32)))  # few dirty rows
    st, _ = ck.save_incremental(st)
    small_bytes = ck.last_save["transfer_bytes"]

    st, _ = tr.train_step(st, id_batch(np.arange(2048)))  # many dirty rows
    st, _ = ck.save_incremental(st)
    large_bytes = ck.last_save["transfer_bytes"]

    assert small_bytes < large_bytes < full_bytes
    # 32 vs 2048 dirty rows: even with pow2 padding and the fixed key-
    # array overhead the small delta must move well under half the big one
    assert small_bytes < large_bytes / 2, (small_bytes, large_bytes)
    assert small_bytes < full_bytes / 3, (small_bytes, full_bytes)
    # and the restored chain is intact
    r = CheckpointManager(str(tmp_path), make_trainer()).restore()
    assert int(r.step) == int(st.step)


# ------------------------------------------------------ ordering-based overlap


def test_async_writer_overlaps_training_by_ordering(tmp_path):
    """The writer's IO happens WHILE the training loop dispatches steps:
    the writer blocks on a gate only the post-save training loop opens, so
    a synchronous implementation (write inside save_async) would time the
    gate out instead of interleaving. Pure ordering — no wall-clock."""
    tr = make_trainer()
    st = tr.init(0)
    batches = gen_batches(3)
    for b in batches:
        st, _ = tr.train_step(st, b)
    ck = CheckpointManager(str(tmp_path), tr)
    events = []
    gate = threading.Event()

    def on_write(path):
        events.append("writer_enter")
        events.append("writer_gated" if gate.wait(timeout=60)
                      else "writer_timeout")

    ck.on_write = on_write
    st, path = ck.save_async(st)
    events.append("save_returned")
    # training continues (and donates the live state) while the writer
    # is parked pre-IO — the staged snapshot must not care
    for i, b in enumerate(batches):
        st, mets = tr.train_step(st, b)
        jax.block_until_ready(mets["loss"])
        events.append(f"step{i}")
    gate.set()
    ck.wait()
    events.append("wait_done")
    assert "writer_timeout" not in events, events
    assert events.index("save_returned") < events.index("step2")
    assert events.index("step2") < events.index("wait_done")
    # the checkpoint committed (manifest last) and restores
    assert os.path.exists(os.path.join(path, "manifest.json"))
    r = CheckpointManager(str(tmp_path), make_trainer()).restore()
    assert int(r.step) > 0


def test_at_most_one_save_in_flight(tmp_path):
    """A second async save drains the first before staging: writer events
    never interleave with each other."""
    tr = make_trainer()
    st = tr.init(0)
    for b in gen_batches(2):
        st, _ = tr.train_step(st, b)
    ck = CheckpointManager(str(tmp_path), tr)
    events = []

    def on_write(path):
        events.append(("enter", os.path.basename(path)))
        events.append(("exit", os.path.basename(path)))

    ck.on_write = on_write
    st, p1 = ck.save_async(st)
    st, _ = tr.train_step(st, gen_batches(1)[0])
    st, p2 = ck.save_incremental_async(st)
    ck.wait()
    names = [n for _, n in events]
    assert names == [os.path.basename(p1)] * 2 + [os.path.basename(p2)] * 2
    assert os.path.exists(os.path.join(p2, "manifest.json"))


def test_failed_incr_writer_escalates_next_save_to_full(tmp_path):
    """save_incremental_async clears dirty bits BEFORE the delta is
    durable; if the writer then dies, those rows are marked clean but in
    no checkpoint. The manager must not let the next delta paper over the
    hole: after a failed incr writer, the next save escalates to FULL so
    the chain re-anchors with every row."""
    tr = make_trainer()
    st = tr.init(0)
    st, _ = tr.train_step(st, id_batch(np.arange(256)))
    ck = CheckpointManager(str(tmp_path / "ck"), tr)
    st, _ = ck.save(st)

    st, _ = tr.train_step(st, id_batch(np.arange(64)))  # the doomed delta

    def die(path):
        raise KeyboardInterrupt("simulated writer death")

    ck.on_write = die
    st, _ = ck.save_incremental_async(st)  # dirty cleared, writer dies
    with pytest.raises(RuntimeError, match="writer failed"):
        ck.wait()
    ck.on_write = None

    st, _ = tr.train_step(st, id_batch(np.arange(64, 96)))  # other rows
    st, path = ck.save_incremental(st)  # must escalate
    assert os.path.basename(path).startswith("full-"), path

    # the escalated save carries the lost delta's rows: restore matches a
    # reference full save of the same state, bit-exactly
    ref = CheckpointManager(str(tmp_path / "ref"), tr)
    ref.save(st)
    r = CheckpointManager(str(tmp_path / "ck"), make_trainer()).restore()
    r_ref = CheckpointManager(str(tmp_path / "ref"), make_trainer()).restore()
    assert_states_identical(tr, r, r_ref)
    # once a full landed durably, deltas resume as deltas
    st, _ = tr.train_step(st, id_batch(np.arange(8)))
    st, p2 = ck.save_incremental(st)
    assert os.path.basename(p2).startswith("incr-")


# --------------------------------------------------------------- GC


def test_gc_sweeps_orphaned_incr_chains(tmp_path):
    """Incr dirs whose base full aged out of `keep` are garbage-collected;
    deltas riding a KEPT full survive (they are its replay chain)."""
    tr = make_trainer()
    st = tr.init(0)
    ck = CheckpointManager(str(tmp_path), tr, keep=2)
    batches = gen_batches(8)
    for i in range(4):
        st, _ = tr.train_step(st, batches[2 * i])
        st, _ = ck.save(st)           # fulls @ 1, 3, 5, 7
        st, _ = tr.train_step(st, batches[2 * i + 1])
        st, _ = ck.save_incremental(st)  # incrs @ 2, 4, 6, 8
    dirs = sorted(d for d in os.listdir(str(tmp_path)))
    assert dirs == ["full-5", "full-7", "incr-6", "incr-8"], dirs
    r = CheckpointManager(str(tmp_path), make_trainer()).restore()
    assert int(r.step) == 8


# ------------------------------------------------------- multi-tier overlap


def _tier_setup(capacity=64):
    from deeprec_tpu import (
        EmbeddingTable, EmbeddingVariableOption, StorageOption, TableConfig,
    )

    cfg = TableConfig(
        name="mt_async", dim=4, capacity=capacity,
        ev=EmbeddingVariableOption(
            storage=StorageOption(storage_type="hbm_dram")),
    )
    from deeprec_tpu.embedding.multi_tier import MultiTierTable

    t = EmbeddingTable(cfg)
    return t, MultiTierTable(t, high_watermark=0.75, low_watermark=0.5)


def test_tier_async_demote_promote_round_trip():
    """sync_async semantics match sync() one boundary late: demotion lands
    in the host tier via the background round; a re-created key's
    promotion is found in the background and APPLIED at the next
    boundary, restoring the exact demoted values."""
    t, mt = _tier_setup()
    s = t.create()
    ids = jnp.arange(52, dtype=jnp.int32)
    s, res = t.lookup_unique(s, ids, step=0)
    s = t.scatter_update(s, res.slot_ix,
                         jnp.full_like(res.embeddings, 3.25), mask=res.valid)
    s, st1 = mt.sync_async(s, step=1)
    assert st1.demoted > 0
    s, _ = mt.drain(s)
    assert len(mt.host) == st1.demoted
    demoted = [
        k for k in range(52)
        if np.abs(np.asarray(
            t.lookup_readonly(s, jnp.array([k], jnp.int32)))).max() < 3
    ]
    assert demoted
    k = demoted[0]
    s, _ = t.lookup_unique(s, jnp.array([k], jnp.int32), step=2)
    s, _ = mt.sync_async(s, step=3)   # background round finds the candidate
    s, st3 = mt.drain(s)              # next boundary applies it
    assert st3.promoted >= 1
    emb = np.asarray(t.lookup_readonly(s, jnp.array([k], jnp.int32)))
    np.testing.assert_allclose(emb[0], 3.25, rtol=1e-6)
    assert k not in {int(x) for x in np.asarray(mt.host.export()[0])}


def test_tier_async_overlap_by_ordering():
    """The HostKV IO round runs while the caller keeps working: the worker
    parks on a gate only the post-sync caller opens (a synchronous
    implementation would time out, not interleave)."""
    t, mt = _tier_setup()
    events = []
    gate = threading.Event()

    def on_io():
        events.append("io_enter")
        events.append("io_gated" if gate.wait(timeout=60) else "io_timeout")

    mt.on_io = on_io
    s = t.create()
    s, _ = t.lookup_unique(s, jnp.arange(52, dtype=jnp.int32), step=0)
    s, stats = mt.sync_async(s, step=1)
    events.append("sync_returned")
    # the caller trains on while the IO round is parked — device state is
    # fully rebuilt already (the demotion's device half is synchronous)
    s, res = t.lookup_unique(s, jnp.arange(5, dtype=jnp.int32), step=2)
    jax.block_until_ready(res.embeddings)
    events.append("trained")
    gate.set()
    s, _ = mt.drain(s)
    events.append("drained")
    assert "io_timeout" not in events, events
    assert events.index("sync_returned") < events.index("trained")
    assert events.index("trained") < events.index("drained")
    assert stats.demoted > 0 and len(mt.host) == stats.demoted


def test_tier_async_never_clobbers_training_during_overlap():
    """The double-buffer guard: a key whose device row trains PAST its
    host copy during the background round must not be overwritten at
    apply time — its tier copy is kept (ambiguous), then dropped as stale
    once a later snapshot confirms the device is newer."""
    from deeprec_tpu.embedding.table import META_FREQ

    t, mt = _tier_setup()
    s = t.create()
    s, res = t.lookup_unique(s, jnp.arange(52, dtype=jnp.int32), step=0)
    s = t.scatter_update(s, res.slot_ix,
                         jnp.full_like(res.embeddings, 3.25), mask=res.valid)
    s, st1 = mt.sync_async(s, step=1)
    s, _ = mt.drain(s)
    demoted = [
        k for k in range(52)
        if np.abs(np.asarray(
            t.lookup_readonly(s, jnp.array([k], jnp.int32)))).max() < 3
    ]
    k = demoted[0]
    # re-create the key (device freq 1 <= host freq) and launch the round
    s, _ = t.lookup_unique(s, jnp.array([k], jnp.int32), step=2)
    s, _ = mt.sync_async(s, step=3)
    # ... the key trains hard during the overlap window: freq passes the
    # host copy's, and the row gets fresh values
    keys_np = np.asarray(s.keys)
    slot = int(np.nonzero(keys_np == k)[0][0])
    s = s.replace(meta=s.meta.at[META_FREQ, slot].add(1000))
    from deeprec_tpu.ops.packed import scatter_rows_any

    s = s.replace(values=scatter_rows_any(
        s.values, jnp.asarray([slot], jnp.int32),
        jnp.full((1, 4), 9.5, jnp.float32), s.capacity))
    s, st = mt.drain(s)
    assert st.promoted == 0  # ambiguous: not clobbered
    emb = np.asarray(t.lookup_readonly(s, jnp.array([k], jnp.int32)))
    np.testing.assert_allclose(emb[0], 9.5, rtol=1e-6)  # training preserved
    host_keys = {int(x) for x in np.asarray(mt.host.export()[0])}
    assert k in host_keys  # tier copy retained for the next round
    # next round sees snap_freq > host freq -> stale, copy dropped
    s, _ = mt.sync_async(s, step=4)
    s, _ = mt.drain(s)
    host_keys = {int(x) for x in np.asarray(mt.host.export()[0])}
    assert k not in host_keys
    np.testing.assert_allclose(
        np.asarray(t.lookup_readonly(s, jnp.array([k], jnp.int32)))[0], 9.5,
        rtol=1e-6)


def test_maintain_tier_async_round_trip():
    """Trainer.maintain(tier_async=True): demotions land in the member
    tiers through the background rounds; a later maintain() applies the
    promotions. Throughput accounting stays visible via tier_stall_ms."""
    from deeprec_tpu import EmbeddingVariableOption, StorageOption

    ev = EmbeddingVariableOption(
        storage=StorageOption(storage_type="hbm_dram"))
    model = WDL(emb_dim=8, capacity=1 << 8, hidden=(16,), num_cat=2,
                num_dense=2, ev=ev)
    tr = Trainer(model, Adagrad(lr=0.1))
    st = tr.init(0)
    rng = np.random.default_rng(0)

    def batch(ids):
        n = len(ids)
        return {
            "C1": jnp.asarray(ids, jnp.int32),
            "C2": jnp.asarray(ids, jnp.int32),
            "I1": jnp.asarray(rng.standard_normal((n, 1)).astype(np.float32)),
            "I2": jnp.asarray(rng.standard_normal((n, 1)).astype(np.float32)),
            "label": jnp.asarray((rng.random(n) < 0.5).astype(np.float32)),
        }

    # occupancy 230/256 > the 0.8 high watermark: maintain must demote
    st, _ = tr.train_step(st, batch(np.arange(230)))
    st, rep = tr.maintain(st, tier_async=True)
    demoted = sum(r.get("demoted", 0) for r in rep.values())
    assert demoted > 0, rep
    # drain via another async maintain (applies pending, launches round 2)
    st, rep2 = tr.maintain(st, tier_async=True)
    for mt in tr._tiers.values():
        mt.join()  # settle outstanding rounds for clean teardown
    assert tr.tier_stall_ms() > 0
    # the state still trains
    st, mets = tr.train_step(st, batch(np.arange(64)))
    assert np.isfinite(float(mets["loss"]))
