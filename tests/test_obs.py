"""obs core (deeprec_tpu/obs/): metrics registry semantics — labeled
counters/gauges/histograms, ring-buffer windowed queries (p99 over a
window, rate, slope), Prometheus render/parse round trip, mergeable
snapshots, the DEEPREC_OBS=off null plane — and the tracer: off by
default with a PROVABLY allocation-free disabled path, span
nesting/propagation, append-only files that survive a process restart
while process-local counters reset, and the Perfetto exporter."""
import json
import os
import subprocess
import sys
import tempfile

import pytest

from deeprec_tpu.obs import metrics as M
from deeprec_tpu.obs import schema, trace as T

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


@pytest.fixture
def clockreg():
    """Registry on an injectable clock, so window queries are exact."""
    clk = [1000.0]
    reg = M.MetricsRegistry(clock=lambda: clk[0])
    return clk, reg


@pytest.fixture(autouse=True)
def _tracing_off():
    """Every test starts and ends with the tracer disabled."""
    T.shutdown()
    yield
    T.shutdown()


# ------------------------------------------------------------- registry


def test_counter_window_rate_and_total(clockreg):
    clk, reg = clockreg
    c = reg.counter("deeprec_x_steps", "steps")
    for _ in range(20):
        c.inc()
        clk[0] += 1.0
    assert c.value == 20
    # only the last 10 s of increments are inside the window
    w = reg.window("deeprec_x_steps", seconds=10.0)
    assert w["delta"] == pytest.approx(10.0, abs=2.0)
    assert w["rate_per_sec"] == pytest.approx(1.0, abs=0.2)
    # get-or-create: same (name, labels) -> same object
    assert reg.counter("deeprec_x_steps", "steps") is c
    assert reg.counter("deeprec_x_steps", labels={"a": "b"}) is not c


def test_gauge_window_slope(clockreg):
    clk, reg = clockreg
    g = reg.gauge("deeprec_x_imb", "imbalance", {"table": "t0"})
    for i in range(8):
        g.set(2.0 + 0.5 * i)   # slope 0.25/s at 2 s per set
        clk[0] += 2.0
    w = reg.window("deeprec_x_imb", {"table": "t0"}, seconds=30.0)
    assert w["last"] == 5.5
    assert w["slope_per_sec"] == pytest.approx(0.25, rel=0.05)


def test_histogram_windowed_p99_forgets_old_samples(clockreg):
    clk, reg = clockreg
    h = reg.histogram("deeprec_x_lat", "lat", {"stage": "e2e"})
    for _ in range(100):
        h.record(0.5)          # old: 500 ms spike era
    clk[0] += 300.0            # ... scrolls out of the ring entirely
    for _ in range(100):
        h.record(0.001)
    win = h.window_summary(60.0)
    assert win["count"] == 100
    assert win["p99_ms"] < 10.0          # the spike era is forgotten
    assert h.summary()["p99_ms"] > 100.0  # lifetime totals still see it


def test_histogram_summary_shape_matches_latency_histogram():
    """ServingStats swaps LatencyHistogram for the registry Histogram —
    identical recordings must produce the identical summary dict."""
    from deeprec_tpu.training.profiler import LatencyHistogram

    reg = M.MetricsRegistry()
    h = reg.histogram("deeprec_x_h", "")
    ref = LatencyHistogram()
    for v in (0.0001, 0.002, 0.03, 0.4, 5.0, 0.002, 0.002):
        h.record(v)
        ref.record(v)
    assert h.summary() == ref.summary()


def test_prometheus_render_parse_roundtrip_and_callbacks(clockreg):
    _, reg = clockreg
    reg.counter("deeprec_x_req", "requests", {"stage": "e2e"}).inc(7)
    reg.gauge("deeprec_x_g", "a gauge").set(1.5)
    reg.histogram("deeprec_x_h", "hist").record(0.01)
    depth = [3]
    reg.register_callback("deeprec_x_depth", lambda: depth[0], "queue",
                          {"srv": "a"})
    text = reg.render_prometheus()
    parsed = M.parse_prometheus(text)
    assert parsed[("deeprec_x_req_total", '{stage="e2e"}')] == 7.0
    assert parsed[("deeprec_x_g", "")] == 1.5
    assert parsed[("deeprec_x_depth", '{srv="a"}')] == 3.0
    assert parsed[("deeprec_x_h_count", "")] == 1.0
    assert any(k[0] == "deeprec_x_h_bucket" for k in parsed)
    # callbacks are live, and survive a reset() (bindings, not counts)
    depth[0] = 9
    reg.reset()
    parsed = M.parse_prometheus(reg.render_prometheus())
    assert parsed[("deeprec_x_depth", '{srv="a"}')] == 9.0
    assert ("deeprec_x_req_total", '{stage="e2e"}') not in parsed


def test_render_extra_labels_and_stale_marking(clockreg):
    _, reg = clockreg
    reg.counter("deeprec_x_req", "r").inc()
    text = M.render_snapshot(reg.snapshot(),
                             extra_labels={"member": "h:1"}, stale=True)
    parsed = M.parse_prometheus(text)
    assert parsed[("deeprec_x_req_total",
                   '{member="h:1",stale="1"}')] == 1.0


def test_concat_prometheus_dedupes_family_headers(clockreg):
    """Real Prometheus parsers reject a repeated # TYPE line for the
    same family — concatenating per-member renders must collapse them
    while keeping every sample line."""
    _, reg = clockreg
    reg.counter("deeprec_x_req", "r").inc()
    a = M.render_snapshot(reg.snapshot(), extra_labels={"member": "h:1"})
    b = M.render_snapshot(reg.snapshot(), extra_labels={"member": "h:2"},
                          stale=True)
    text = M.concat_prometheus([a, b])
    lines = text.splitlines()
    assert lines.count("# TYPE deeprec_x_req counter") == 1
    assert sum(1 for ln in lines
               if ln.startswith("deeprec_x_req_total")) == 2
    M.parse_prometheus(text)  # still well-formed


def test_merge_snapshots_sums_counters_and_hists(clockreg):
    _, reg = clockreg
    reg.counter("deeprec_x_req", "r").inc(3)
    reg.histogram("deeprec_x_h", "h").record(0.01)
    s = reg.snapshot()
    merged = M.merge_snapshots([s, s, s])
    ent = merged["metrics"]["deeprec_x_req"]["series"][0]
    assert ent["value"] == 9.0
    assert merged["metrics"]["deeprec_x_h"]["series"][0]["n"] == 3


def test_disabled_plane_hands_out_noops(monkeypatch):
    M.set_metrics_enabled(False)
    try:
        reg = M.MetricsRegistry()
        c = reg.counter("deeprec_x", "")
        g = reg.gauge("deeprec_y", "")
        h = reg.histogram("deeprec_z", "")
        assert c is g is h  # THE null singleton
        c.inc()
        g.set(3)
        h.record(0.5)
        assert h.summary()["count"] == 0
        assert reg.snapshot() == {"metrics": {}}
    finally:
        M.set_metrics_enabled(None)


def test_serving_stats_works_with_plane_off():
    """DEEPREC_OBS=off must leave the legacy /v1/stats surface fully
    functional (plain LatencyHistograms, no registry)."""
    from deeprec_tpu.serving.stats import ServingStats

    M.set_metrics_enabled(False)
    try:
        st = ServingStats()
        assert st.registry is None
        st.record_stage("e2e", 0.01)
        st.record_batch(2, 16)
        snap = st.snapshot()
        assert snap["requests"] == 2 and snap["rows"] == 16
        assert snap["stages"]["e2e"]["count"] == 1
        assert st.window_p99_ms() is None
        assert st.metrics_snapshot() is None
    finally:
        M.set_metrics_enabled(None)


def test_serving_stats_registry_backed_windows():
    from deeprec_tpu.serving.stats import ServingStats

    st = ServingStats()
    assert st.registry is not None
    st.record_stage("e2e", 0.02)
    st.record_batch(1, 4)
    assert st.snapshot()["stages"]["e2e"]["count"] == 1
    assert st.window_p99_ms("e2e", 60.0) == pytest.approx(20.0, rel=0.6)
    text = M.render_snapshot(st.metrics_snapshot())
    assert "deeprec_serving_stage_seconds_bucket" in text


# --------------------------------------------------------------- schema


def test_health_payload_canonical_keys_and_aliases():
    h = schema.health_payload("ok", model_version=3, step=10,
                              staleness_seconds=0.5, quarantined=1,
                              member="h:1")
    assert schema.is_health_payload(h)
    assert h["schema"] == schema.HEALTH_SCHEMA
    # the historical keys ARE canonical members — old readers keep working
    for k in ("status", "model_version", "step", "staleness_seconds",
              "consecutive_poll_failures", "last_good_version",
              "quarantined"):
        assert k in h
    assert h["member"] == "h:1"  # surface-specific extras ride along


# ---------------------------------------------------------------- trace


def test_tracing_off_by_default_and_identity_noop():
    assert not T.tracing_enabled()
    s1 = T.span("a")
    s2 = T.server_span("b", "c")
    assert s1 is s2 is T.NOOP_SPAN
    assert T.start_request() is None
    with s1:
        assert T.current() is None


def test_disabled_tracing_is_zero_allocation():
    """The disabled path allocates NOTHING per call: span() returns the
    module singleton, emit()/phase_span() return before building
    anything. Pinned with tracemalloc over 2000 calls — the only
    allocations attributable to trace.py are a handful of transient
    CPython frame objects (frame-pool noise, O(1) count), never O(N)."""
    import tracemalloc

    assert not T.tracing_enabled()
    with T.span("warm"):   # touch every lazy path once before measuring
        pass
    T.emit("warm", "", 0.0, 0.0)
    N = 2000
    tracemalloc.start()
    try:
        for _ in range(N):
            with T.span("x", "y"):
                pass
            T.emit("x", "y", 0.0, 1.0)
            T.phase_span("x", 0.0, 1.0)
        snap = tracemalloc.take_snapshot()
    finally:
        tracemalloc.stop()
    tfile = T.__file__
    stats = [st for st in snap.statistics("filename")
             if st.traceback[0].filename == tfile]
    count = sum(st.count for st in stats)
    size = sum(st.size for st in stats)
    assert count < N / 100, (
        f"disabled tracing allocated {count} objects over {N} calls "
        f"({size}B) — the no-op path is allocating per call")


def test_span_nesting_propagation_and_export(tmp_path):
    path = str(tmp_path / "t.jsonl")
    T.configure(path, sample=1.0, service="svc")
    with T.server_span("edge", "serving") as edge:
        assert T.current() == edge.ctx
        with T.span("inner") as inner:
            assert inner.ctx[0] == edge.ctx[0]  # same trace id
            assert inner.parent == edge.ctx[1]
    # retrospective child emission (the micro-batcher idiom)
    T.emit("stage_queue", "serving", 1.0, 2.0,
           ctx=T.child(edge.ctx), parent=edge.ctx[1])
    T.flush()
    evs = [json.loads(ln) for ln in open(path)]
    names = {e["name"] for e in evs}
    assert names == {"edge", "inner", "stage_queue"}
    tids = {e["args"]["trace"] for e in evs}
    assert len(tids) == 1
    assert all(e["args"]["service"] == "svc" for e in evs)

    # header + wire propagation round-trips
    hdr = T.to_header(edge.ctx)
    assert T.from_header(hdr) == edge.ctx
    assert T.from_header("garbage") is None
    assert T.unpack_wire(T.pack_wire(edge.ctx)) == edge.ctx

    # exporter: Perfetto/Chrome shape + trace-id filter
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import obs_trace

    out = str(tmp_path / "trace.json")
    rep = obs_trace.export([path], out)
    assert rep["events"] == 3 and rep["traces"] == 1
    doc = json.load(open(out))
    assert {e["name"] for e in doc["traceEvents"]} >= names
    assert any(e.get("ph") == "M" for e in doc["traceEvents"])
    ids = obs_trace.trace_ids(obs_trace.load_events([path]))
    (tid,) = ids
    assert set(ids[tid]) == names


def test_sampling_zero_never_traces(tmp_path):
    T.configure(str(tmp_path / "t.jsonl"), sample=0.0)
    assert all(T.start_request() is None for _ in range(50))
    # ...but a propagated context is always honored
    sp = T.server_span("hop", header="00000000000000aa-00000000000000bb")
    assert sp is not T.NOOP_SPAN
    assert sp.ctx[0] == 0xAA


def test_restart_resets_counters_but_trace_file_survives(tmp_path):
    """The supervisor-restart contract: a respawned worker starts its
    process-local registry from zero, while the shared trace JSONL only
    GROWS (append mode) — two real worker processes prove both halves."""
    trace_path = str(tmp_path / "worker.jsonl")
    script = (
        "import json, os, sys\n"
        "from deeprec_tpu.obs import metrics as M, trace as T\n"
        "reg = M.default_registry()\n"
        "c = reg.counter('deeprec_restart_probe', '')\n"
        "before = c.value\n"
        "c.inc(5)\n"
        "T.phase_span('work', 1.0, 2.0)\n"
        "T.flush()\n"
        "print(json.dumps({'pid': os.getpid(), 'before': before,"
        " 'after': c.value}))\n"
    )
    outs = []
    for _ in range(2):  # generation 0, then the "restarted" generation
        r = subprocess.run(
            [sys.executable, "-c", script], capture_output=True, text=True,
            env={**os.environ, "PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu",
                 "DEEPREC_TRACE": trace_path},
            timeout=120, check=True)
        outs.append(json.loads(r.stdout.strip().splitlines()[-1]))
    assert [o["before"] for o in outs] == [0.0, 0.0]  # counters reset
    assert [o["after"] for o in outs] == [5.0, 5.0]
    evs = [json.loads(ln) for ln in open(trace_path)]
    assert len(evs) == 2                              # file accumulated
    assert {e["pid"] for e in evs} == {o["pid"] for o in outs}


def test_exporter_skips_torn_tail(tmp_path):
    """A SIGKILL mid-append leaves a torn last line — the exporter must
    load everything else, not die (fault traces are the point)."""
    p = tmp_path / "t.jsonl"
    good = json.dumps({"name": "a", "ph": "X", "ts": 1, "dur": 1, "pid": 1,
                       "tid": 1})
    p.write_text(good + "\n" + good[: len(good) // 2])
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import obs_trace

    assert len(obs_trace.load_events([str(p)])) == 1
