"""Pod-scale 2-D mesh (parallel/mesh.py make_mesh_2d + the hierarchical
two-tier exchange, comm="hier"): the mesh SHAPE must be invisible to the
training math and to persistence.

Contracts pinned here:
  * device order is host-major — flat rank g*intra+i equals the 1-D
    position, so hash ownership, placement and checkpoints are
    mesh-shape independent by construction;
  * the FLAT exchanges (allgather, a2a) run BITWISE identically on a
    2-D mesh (tuple axis names enumerate devices in 1-D rank order);
  * the hierarchical exchange keeps every per-key TABLE INT (meta:
    freq/version, key sets, shard ownership) exactly equal to the flat
    path; float rows and per-step losses agree to ulp-level tolerance
    (the relay's fp32 pre-sum regroups the owner-side reduction — same
    class as the a2a-vs-allgather precedent in test_a2a.py), and the
    FIRST step from a fresh init is bitwise (forward is exact: one
    contributor per psum_scatter position);
  * pipeline_mode="nested" (two-tier lookahead) is bitwise identical to
    "off" — losses AND full table state;
  * checkpoints round-trip across mesh-shape changes in both directions;
  * elastic rescale factorization never wedges (degrades to 1-D);
  * the two-tier wire model puts the inter tier on a real diet at the
    reference 2x4 shape.
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from deeprec_tpu.data import SyntheticCriteo
from deeprec_tpu.models import WDL
from deeprec_tpu.optim import Adagrad
from deeprec_tpu.parallel import (
    ShardedTrainer,
    make_mesh,
    make_mesh_2d,
    mesh_batch_axes,
    shard_batch,
)
from deeprec_tpu.parallel.elastic import factorize_mesh, plan_mesh_after_rescale
from deeprec_tpu.parallel.mesh import DATA_AXIS, INTER_AXIS, INTRA_AXIS
from deeprec_tpu.training import stack_batches


def J(b):
    return {k: jnp.asarray(v) for k, v in b.items()}


def model():
    return WDL(emb_dim=8, capacity=1 << 12, hidden=(16,), num_cat=4,
               num_dense=2)


def overlap_batches(n, batch_size=256, seed=7):
    """Shared raw id space + per-table zipf: heavy cross-device id
    overlap, the regime where the relay pre-sum actually regroups."""
    gen = SyntheticCriteo(
        batch_size=batch_size, num_cat=4, num_dense=2, vocab=3000,
        seed=seed, zipf_a=[1.2, 1.5, 1.8, 2.1], offset_ids=False,
    )
    return [J(gen.batch()) for _ in range(n)]


def build(mesh, comm, pipeline_mode="off", group_factor=None):
    return ShardedTrainer(
        model(), Adagrad(lr=0.1), optax.sgd(0.01), mesh=mesh, comm=comm,
        pipeline_mode=pipeline_mode, pipeline_chunks=2,
        hier_group_factor=group_factor,
    )


def split_maps(tr, state):
    """Two views of the live rows, keyed (bundle, member, key):
    ints — shard ownership + meta columns, compared EXACTLY;
    floats — value row + optimizer slot rows, compared to tolerance.
    Slot LAYOUT inside a shard's hash table may differ between runs
    (insertion order), so only per-key content is comparable."""
    from deeprec_tpu.embedding.table import empty_key
    from deeprec_tpu.ops.packed import unpack_array
    from deeprec_tpu.optim.sparse import SCALAR_PREFIX

    ints, floats = {}, {}
    for bname, b in tr.bundles.items():
        ts = state.tables[bname]
        sent = empty_key(b.table.cfg)
        keys = np.asarray(jax.device_get(ts.keys))
        meta = np.asarray(jax.device_get(ts.meta))
        C = keys.shape[-1]
        vals = np.asarray(jax.device_get(ts.values))
        slots = {
            k: np.asarray(jax.device_get(v))
            for k, v in ts.slots.items()
            if not k.startswith(SCALAR_PREFIX)
        }
        lead = keys.shape[:-1]  # [T?, N]
        for idx in np.ndindex(*lead):
            m = idx[0] if len(idx) == 2 else 0
            shard = idx[-1]
            k_loc = keys[idx]
            v_loc = unpack_array(vals[idx], C)
            s_loc = [unpack_array(sl[idx], C) for sl in slots.values()]
            occ = np.nonzero(k_loc != sent)[0]
            for s in occ:
                ref = (bname, m, int(k_loc[s]))
                assert ref not in ints, f"key on two shards: {ref}"
                ints[ref] = (shard, meta[idx][:, s].tobytes())
                floats[ref] = (
                    v_loc[s].copy(),
                    tuple(sl[s].copy() for sl in s_loc),
                )
    return ints, floats


def assert_same_tables(tr_a, s_a, tr_b, s_b, exact=True):
    ia, fa = split_maps(tr_a, s_a)
    ib, fb = split_maps(tr_b, s_b)
    assert set(ia) == set(ib), (
        f"live key sets differ: {len(set(ia) ^ set(ib))} keys"
    )
    bad = [k for k in ia if ia[k] != ib[k]]
    assert not bad, f"{len(bad)} keys differ on ints/ownership: {bad[:3]}"
    for k in fa:
        va, sa = fa[k]
        vb, sb_ = fb[k]
        if exact:
            np.testing.assert_array_equal(va, vb, err_msg=str(k))
            for x, y in zip(sa, sb_):
                np.testing.assert_array_equal(x, y, err_msg=str(k))
        else:
            np.testing.assert_allclose(va, vb, rtol=1e-3, atol=1e-5,
                                       err_msg=str(k))
            for x, y in zip(sa, sb_):
                np.testing.assert_allclose(x, y, rtol=1e-3, atol=1e-5,
                                           err_msg=str(k))


# --------------------------------------------------------- mesh plumbing


def test_make_mesh_2d_layout():
    assert len(jax.devices()) >= 8
    mesh = make_mesh_2d(4, 2)
    assert tuple(mesh.axis_names) == (INTER_AXIS, INTRA_AXIS)
    assert mesh.shape[INTER_AXIS] == 2 and mesh.shape[INTRA_AXIS] == 4
    # Host-major: flat rank g*intra+i is the 1-D device position — the
    # property that makes hash ownership mesh-shape independent.
    np.testing.assert_array_equal(
        np.asarray([d.id for d in mesh.devices.flatten()]),
        np.asarray([d.id for d in jax.devices()[:8]]),
    )
    # inter inferred from the available device count
    assert make_mesh_2d(2).shape[INTER_AXIS] == len(jax.devices()) // 2
    # a 3x2 carve-out of the 8 devices is legal; inference is not (3 ∤ 8)
    assert make_mesh_2d(3, 2).devices.size == 6
    with pytest.raises(ValueError):
        make_mesh_2d(3)  # intra must divide the device count to infer
    with pytest.raises(ValueError):
        make_mesh_2d(16, 2)  # more devices than exist


def test_mesh_batch_axes_and_shard_batch():
    m1, m2 = make_mesh(8), make_mesh_2d(4, 2)
    assert mesh_batch_axes(m1) == DATA_AXIS
    assert mesh_batch_axes(m2) == (INTER_AXIS, INTRA_AXIS)
    b = {"x": jnp.arange(64, dtype=jnp.int32)}
    s1 = shard_batch(m1, b)
    s2 = shard_batch(m2, b)
    assert len(s1["x"].sharding.device_set) == 8
    assert len(s2["x"].sharding.device_set) == 8
    # identical global content, identical per-device slices (host-major)
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(s1["x"])),
        np.asarray(jax.device_get(s2["x"])),
    )


def test_hier_requires_2d_mesh():
    with pytest.raises(ValueError):
        build(make_mesh(8), "hier")


# ----------------------------------------------------- elastic rescaling


def test_factorize_mesh_never_wedges():
    # (survivors, prefer_intra) -> (intra, inter)
    assert factorize_mesh(8, 4) == (4, 2)
    assert factorize_mesh(6, 4) == (3, 2)
    assert factorize_mesh(4, 4) == (2, 2)
    assert factorize_mesh(12, 4) == (4, 3)
    # primes / tiny counts degrade to 1-D rather than wedge
    assert factorize_mesh(7, 4) == (7, 1)
    assert factorize_mesh(3, 4) == (3, 1)
    assert factorize_mesh(1, 4) == (1, 1)
    for n in range(1, 9):
        intra, inter = factorize_mesh(n, 4)
        assert intra * inter == n and intra >= 1 and inter >= 1
    with pytest.raises(ValueError):
        factorize_mesh(0, 4)


def test_plan_mesh_after_rescale_shapes():
    old2d = make_mesh_2d(4, 2)
    # a host group leaves: 8 -> 4 survivors refactorize to 2x2
    m = plan_mesh_after_rescale(4, old2d)
    assert tuple(m.axis_names) == (INTER_AXIS, INTRA_AXIS)
    assert m.shape[INTRA_AXIS] == 2 and m.shape[INTER_AXIS] == 2
    # prime survivor count degrades to 1-D — never wedges
    m = plan_mesh_after_rescale(7, old2d)
    assert tuple(m.axis_names) == (DATA_AXIS,)
    # 1-D stays 1-D
    m = plan_mesh_after_rescale(4, make_mesh(8))
    assert tuple(m.axis_names) == (DATA_AXIS,)
    assert m.devices.size == 4


def test_exit_rescale_2d_to_1d_resumes(tmp_path):
    """The EXIT_RESCALE cycle across a mesh-shape change: train on the
    2-D hier mesh, reshard through the checkpoint container onto the
    degraded 1-D topology a prime survivor count forces, keep training.
    (The PR 12 drain discipline: state moves via the tested export/
    import path, keys re-probe into their new owners' shards.)"""
    from deeprec_tpu.parallel.elastic import reshard

    batches = overlap_batches(4)
    tr_a = build(make_mesh_2d(4, 2), "hier")
    s_a = tr_a.init(0)
    for i in range(3):
        s_a, m_a = tr_a.train_step(s_a, shard_batch(tr_a.mesh, batches[i]))
    # survivors = 2: no >=2 co-factor under prefer_intra, degrades to
    # 1-D (2 also divides the table capacity, which a resharded trainer
    # still requires of its mesh size)
    new_mesh = plan_mesh_after_rescale(2, tr_a.mesh)
    assert tuple(new_mesh.axis_names) == (DATA_AXIS,)
    assert new_mesh.devices.size == 2
    tr_b = ShardedTrainer(model(), Adagrad(lr=0.1), optax.sgd(0.01),
                          mesh=new_mesh, comm="a2a")
    s_b = reshard(tr_a, s_a, tr_b, scratch_dir=str(tmp_path))
    s_b, m_b = tr_b.train_step(s_b, shard_batch(new_mesh, batches[3]))
    assert np.isfinite(float(m_b["loss"]))


# -------------------------------------------- flat-comm mesh-shape parity


@pytest.mark.parametrize("comm", ["allgather", "a2a"])
def test_flat_comm_parity_across_mesh_shapes(comm):
    """The flat exchanges on a 2-D mesh (axis = the tuple) enumerate
    devices in 1-D rank order: losses and the full table state must be
    BITWISE identical across {1-D, 2x4, 4x2} — including the K-scan."""
    batches = overlap_batches(5)
    tr_1d = build(make_mesh(8), comm)
    s_1d = tr_1d.init(0)
    runs = []
    for intra, inter in ((4, 2), (2, 4)):
        tr = build(make_mesh_2d(intra, inter), comm)
        runs.append((tr, tr.init(0)))
    for i in range(3):
        s_1d, m_1d = tr_1d.train_step(s_1d, shard_batch(tr_1d.mesh,
                                                        batches[i]))
        for j, (tr, st) in enumerate(runs):
            st, m = tr.train_step(st, shard_batch(tr.mesh, batches[i]))
            runs[j] = (tr, st)
            assert float(m["loss"]) == float(m_1d["loss"]), (
                f"step {i}, mesh {tr.mesh.shape}: "
                f"{float(m['loss'])} != {float(m_1d['loss'])}"
            )
    # K-step scan: same program shape, still bitwise
    stacked_1d = shard_batch(tr_1d.mesh, stack_batches(batches[3:5]),
                             stacked=True)
    s_1d, m_1d = tr_1d.train_steps(s_1d, stacked_1d)
    for j, (tr, st) in enumerate(runs):
        stacked = shard_batch(tr.mesh, stack_batches(batches[3:5]),
                              stacked=True)
        st, m = tr.train_steps(st, stacked)
        runs[j] = (tr, st)
        np.testing.assert_array_equal(
            np.asarray(m["loss"]), np.asarray(m_1d["loss"])
        )
    for tr, st in runs:
        assert_same_tables(tr_1d, s_1d, tr, st, exact=True)


# ------------------------------------------------- hierarchical exchange


def test_hier_parity_vs_flat():
    """comm="hier" vs the flat 1-D path on a high-overlap stream: first
    step bitwise (fresh tables, forward exact), every per-key table INT
    and the shard ownership exactly equal throughout, float rows and
    later losses within the a2a-precedent tolerance (the relay's fp32
    pre-sum regroups the owner-side reduction)."""
    batches = overlap_batches(6)
    tr_f = build(make_mesh(8), "allgather")
    s_f = tr_f.init(0)
    runs = []
    for intra, inter in ((4, 2), (2, 4)):
        tr = build(make_mesh_2d(intra, inter), "hier")
        runs.append((tr, tr.init(0)))
    for i in range(4):
        s_f, m_f = tr_f.train_step(s_f, shard_batch(tr_f.mesh, batches[i]))
        for j, (tr, st) in enumerate(runs):
            st, m = tr.train_step(st, shard_batch(tr.mesh, batches[i]))
            runs[j] = (tr, st)
            if i == 0:
                assert float(m["loss"]) == float(m_f["loss"]), (
                    "first step must be bitwise (forward is exact)"
                )
            else:
                np.testing.assert_allclose(
                    float(m["loss"]), float(m_f["loss"]), rtol=1e-4
                )
    for tr, st in runs:
        assert_same_tables(tr_f, s_f, tr, st, exact=False)
        overflow = sum(
            int(np.sum(np.asarray(jax.device_get(ts.a2a_overflow))))
            for ts in st.tables.values()
        )
        assert overflow == 0, f"hier overflow on {tr.mesh.shape}"


def test_hier_group_budget_discipline():
    """A finite group_factor engages the budgeted inter bucket: the
    compiled bucket must equal ops/traffic.py's model max (one formula,
    shared by construction) with ZERO overflow at a roomy factor."""
    from deeprec_tpu.ops import traffic as T

    batches = overlap_batches(4)
    tr = build(make_mesh_2d(4, 2), "hier", group_factor=2.0)
    st = tr.init(0)
    for i in range(4):
        st, m = tr.train_step(st, shard_batch(tr.mesh, batches[i]))
    assert np.isfinite(float(m["loss"]))
    for bname in tr.bundles:
        sh = tr.sharded[bname]
        budgets = T.hier_dest_budgets(
            unique=sh.last_a2a_unique, intra=4, inter=2,
            slack=sh.a2a_slack, group_factor=2.0,
            dest_hot=sh.plan_dest_hot, hot_count=sh.plan_hot_count,
        )
        assert int(budgets.max()) == sh.last_a2a_bucket
        np.testing.assert_array_equal(
            np.asarray(budgets), np.asarray(sh.last_a2a_budgets)
        )
    overflow = sum(
        int(np.sum(np.asarray(jax.device_get(ts.a2a_overflow))))
        for ts in st.tables.values()
    )
    assert overflow == 0


def test_nested_lookahead_bitwise_vs_off():
    """pipeline_mode="nested" on the hier K-scan: the inter-tier id
    exchange of batch t+1 is hoisted behind dense(t) across BOTH tiers —
    same-exact-no-staleness contract, pinned bitwise against "off" on
    losses AND the full table state."""
    batches = overlap_batches(7)
    tr_o = build(make_mesh_2d(4, 2), "hier", pipeline_mode="off")
    tr_n = build(make_mesh_2d(4, 2), "hier", pipeline_mode="nested")
    s_o, s_n = tr_o.init(0), tr_n.init(0)
    for i in range(3):
        s_o, m_o = tr_o.train_step(s_o, shard_batch(tr_o.mesh, batches[i]))
        s_n, m_n = tr_n.train_step(s_n, shard_batch(tr_n.mesh, batches[i]))
        assert float(m_o["loss"]) == float(m_n["loss"])
    stacked_o = shard_batch(tr_o.mesh, stack_batches(batches[3:7]),
                            stacked=True)
    stacked_n = shard_batch(tr_n.mesh, stack_batches(batches[3:7]),
                            stacked=True)
    s_o, m_o = tr_o.train_steps(s_o, stacked_o)
    s_n, m_n = tr_n.train_steps(s_n, stacked_n)
    np.testing.assert_array_equal(
        np.asarray(m_o["loss"]), np.asarray(m_n["loss"])
    )
    assert_same_tables(tr_o, s_o, tr_n, s_n, exact=True)


# ------------------------------------------------- checkpoints x meshes


def test_checkpoint_roundtrip_across_mesh_shapes(tmp_path):
    """Save under 1-D, restore under 2-D hier (and the reverse): restore
    re-probes keys into the restoring trainer's shards, which the
    host-major 2-D layout maps to the same owners — both directions must
    resume with the flat path's exact table state and a bitwise resumed
    forward loss."""
    from deeprec_tpu.training.checkpoint import CheckpointManager

    batches = overlap_batches(5)
    tr_a = build(make_mesh(8), "allgather")
    s_a = tr_a.init(0)
    for i in range(3):
        s_a, _ = tr_a.train_step(s_a, shard_batch(tr_a.mesh, batches[i]))
    ck_a = CheckpointManager(str(tmp_path / "ck"), tr_a)
    s_a, _ = ck_a.save(s_a)

    # 1-D -> 2-D hier
    tr_b = build(make_mesh_2d(4, 2), "hier")
    r_b = CheckpointManager(str(tmp_path / "ck"), tr_b).restore()
    assert_same_tables(tr_a, s_a, tr_b, r_b, exact=True)
    s_a, m_a = tr_a.train_step(s_a, shard_batch(tr_a.mesh, batches[3]))
    r_b, m_b = tr_b.train_step(r_b, shard_batch(tr_b.mesh, batches[3]))
    assert float(m_a["loss"]) == float(m_b["loss"])

    # 2-D hier -> 1-D a2a
    ck_b = CheckpointManager(str(tmp_path / "ck_b"), tr_b)
    r_b, _ = ck_b.save(r_b)
    tr_c = ShardedTrainer(model(), Adagrad(lr=0.1), optax.sgd(0.01),
                          mesh=make_mesh(8), comm="a2a")
    r_c = CheckpointManager(str(tmp_path / "ck_b"), tr_c).restore()
    assert_same_tables(tr_b, r_b, tr_c, r_c, exact=True)
    r_b, m_b = tr_b.train_step(r_b, shard_batch(tr_b.mesh, batches[4]))
    r_c, m_c = tr_c.train_step(r_c, shard_batch(tr_c.mesh, batches[4]))
    assert float(m_b["loss"]) == float(m_c["loss"])


# ------------------------------------------------------ two-tier model


def test_hier_wire_model_reference_shape():
    """At the reference 8-device 2x4 shape the modeled inter-tier bytes
    must undercut BOTH baselines: <= 0.5x the flat a2a's inter-host
    bytes and <= 1/intra of the flat a2a's total — the acceptance bound
    `roofline.py --assert-hierarchy` gates on the recorded bench JSON."""
    from deeprec_tpu.ops import traffic as T

    U, D = 1024, 32
    hb = T.hier_exchange_bytes(
        unique=U, intra=4, inter=2, dim=D, wire_bytes=4, slack=2.0,
        group_factor=1.5,
    )
    fb = T.flat_exchange_tier_bytes(
        unique=U, num_shards=8, intra=4, comm="a2a", dim=D, wire_bytes=4,
        slack=2.0,
    )
    assert hb["inter_bytes"] <= 0.5 * fb["inter_bytes"], (hb, fb)
    assert hb["inter_bytes"] <= fb["total_bytes"] / 4, (hb, fb)
    # budget algebra: U_g caps at intra*U with no factor, the bucket is
    # the max of the per-group vector, rows round to 8
    assert T.hier_group_unique_budget(unique=U, intra=4) == 4 * U
    ug = T.hier_group_unique_budget(unique=U, intra=4, group_factor=1.5)
    assert ug == int(np.ceil(1.5 * U / 8)) * 8
    budgets = T.hier_dest_budgets(unique=U, intra=4, inter=2, slack=2.0,
                                  group_factor=1.5)
    assert int(budgets.max()) == T.hier_bucket_rows(
        unique=U, intra=4, inter=2, slack=2.0, group_factor=1.5
    )
    # per-tier ms only with bandwidths given
    hb2 = T.hier_exchange_bytes(
        unique=U, intra=4, inter=2, dim=D, slack=2.0, group_factor=1.5,
        intra_bw_gbs=100.0, inter_bw_gbs=10.0,
    )
    assert hb2["intra_ms"] > 0 and hb2["inter_ms"] > 0
