"""Skew-aware table placement (parallel/placement.py): any ShardPlan must
be INVISIBLE to the training math — placement changes WHERE a row lives,
never its values. The parity suite pins per-step losses and the full
per-key table contents (values, meta, optimizer slots) bit-exact between
uniform hash routing and an adopted plan, across both comm modes, the
K-step scan and the pipelined lookahead; plus the hot-key budget fallback
(H exceeded -> hash owner, no drops), the re-shard failure contract
(cannot-place aborts, old plan keeps serving) and the checkpoint
round-trip across a plan change (save under plan A, restore under plan B,
both directions)."""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from deeprec_tpu.data import SyntheticCriteo
from deeprec_tpu.models import WDL
from deeprec_tpu.optim import Adagrad
from deeprec_tpu.parallel import ShardedTrainer, make_mesh, shard_batch
from deeprec_tpu.parallel import placement as P
from deeprec_tpu.training import Trainer
from deeprec_tpu.utils import hashing


def J(b):
    return {k: jnp.asarray(v) for k, v in b.items()}


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= 8
    return make_mesh(8)


def model():
    return WDL(emb_dim=8, capacity=1 << 12, hidden=(16,), num_cat=4,
               num_dense=2)


def skewed_batches(n, batch_size=256, seed=7):
    """Shared raw id space + per-table zipf exponents: every table's head
    is the same raw ids, the correlated-head case the plan flattens."""
    gen = SyntheticCriteo(
        batch_size=batch_size, num_cat=4, num_dense=2, vocab=3000,
        seed=seed, zipf_a=[1.2, 1.5, 1.8, 2.1], offset_ids=False,
    )
    return [J(gen.batch()) for _ in range(n)]


def build(mesh, placement="uniform", comm="allgather", pipeline_mode="off"):
    return ShardedTrainer(
        model(), Adagrad(lr=0.1), optax.sgd(0.01), mesh=mesh, comm=comm,
        placement=placement, placement_hot_budget=16,
        pipeline_mode=pipeline_mode,
    )


def table_maps(tr, state):
    """(bundle, member, key) -> all per-row state, wherever the row lives.

    The placement-invariant view of a TrainState: migrating a row between
    shards must leave this map bit-identical."""
    from deeprec_tpu.embedding.table import empty_key
    from deeprec_tpu.ops.packed import unpack_array
    from deeprec_tpu.optim.sparse import SCALAR_PREFIX

    out = {}
    for bname, b in tr.bundles.items():
        ts = state.tables[bname]
        sent = empty_key(b.table.cfg)
        keys = np.asarray(jax.device_get(ts.keys))
        meta = np.asarray(jax.device_get(ts.meta))
        C = keys.shape[-1]
        vals = np.asarray(jax.device_get(ts.values))
        slots = {
            k: np.asarray(jax.device_get(v))
            for k, v in ts.slots.items()
            if not k.startswith(SCALAR_PREFIX)
        }
        lead = keys.shape[:-1]  # [T?, N]
        for idx in np.ndindex(*lead):
            m = idx[0] if len(idx) == 2 else 0
            k_loc = keys[idx]
            v_loc = unpack_array(vals[idx], C)  # numpy: zero-copy view
            s_loc = [unpack_array(sl[idx], C) for sl in slots.values()]
            occ = np.nonzero(k_loc != sent)[0]
            for s in occ:
                key = int(k_loc[s])
                row = (
                    v_loc[s].tobytes(),
                    meta[idx][:, s].tobytes(),
                    tuple(sl[s].tobytes() for sl in s_loc),
                )
                ref = (bname, m, key)
                assert ref not in out, f"key {key} on two shards: {ref}"
                out[ref] = row
    return out


def assert_same_rows(tr_a, s_a, tr_b, s_b):
    ma, mb = table_maps(tr_a, s_a), table_maps(tr_b, s_b)
    assert set(ma) == set(mb), (
        f"live key sets differ: {len(set(ma) ^ set(mb))} keys"
    )
    diff = [k for k in ma if ma[k] != mb[k]]
    assert not diff, f"{len(diff)} keys differ, e.g. {diff[:3]}"


def adopt(tr, st):
    st, rep = tr.update_placement(st, force=True)
    assert any(r.get("adopted") for r in rep.values()), rep
    assert not any(r.get("migrate_failed") for r in rep.values()), rep
    plans = {n: p for n, p in tr._plans.items() if not p.is_uniform}
    assert plans, "forced adoption produced only uniform plans"
    return st, rep


# ------------------------------------------------------------ route parity


def _parity_run(mesh, comm):
    batches = skewed_batches(8)
    sb = [shard_batch(mesh, b) for b in batches]
    tr_u = build(mesh, "uniform", comm)
    tr_p = build(mesh, "plan", comm)
    s_u, s_p = tr_u.init(0), tr_p.init(0)
    for i in range(4):
        s_u, m_u = tr_u.train_step(s_u, sb[i])
        s_p, m_p = tr_p.train_step(s_p, sb[i])
        assert float(m_u["loss"]) == float(m_p["loss"])
    s_p, rep = adopt(tr_p, s_p)
    assert sum(r.get("moved", 0) for r in rep.values()) > 0, (
        "plan adoption moved nothing — the parity run is vacuous"
    )
    assert_same_rows(tr_u, s_u, tr_p, s_p)  # migration itself is invisible
    for i in range(4, 8):
        s_u, m_u = tr_u.train_step(s_u, sb[i])
        s_p, m_p = tr_p.train_step(s_p, sb[i])
        assert float(m_u["loss"]) == float(m_p["loss"]), (
            f"step {i}: {float(m_u['loss'])} != {float(m_p['loss'])}"
        )
    assert_same_rows(tr_u, s_u, tr_p, s_p)
    return tr_u, s_u, tr_p, s_p, batches


def test_plan_parity_allgather(mesh):
    """Bit-exact per-step losses and per-key rows across a forced plan
    adoption mid-training, allgather exchange — including the K-step scan
    after the swap."""
    from deeprec_tpu.training import stack_batches

    tr_u, s_u, tr_p, s_p, batches = _parity_run(mesh, "allgather")
    stacked = shard_batch(mesh, stack_batches(batches[:3]), stacked=True)
    s_u, m_u = tr_u.train_steps(s_u, stacked)
    s_p, m_p = tr_p.train_steps(s_p, stacked)
    np.testing.assert_array_equal(
        np.asarray(m_u["loss"]), np.asarray(m_p["loss"])
    )
    assert_same_rows(tr_u, s_u, tr_p, s_p)


def test_plan_parity_a2a(mesh):
    """Same contract on the budgeted all2all exchange: the plan changes
    the owner bucketing, not the math."""
    _parity_run(mesh, "a2a")


def test_plan_parity_lookahead_scan(mesh):
    """pipeline_mode="lookahead": route(t+1) is issued a step early with
    the plan constants baked into the scan — parity must survive the
    hoisted routing."""
    from deeprec_tpu.training import stack_batches

    batches = skewed_batches(7)
    sb = [shard_batch(mesh, b) for b in batches]
    tr_u = build(mesh, "uniform", pipeline_mode="lookahead")
    tr_p = build(mesh, "plan", pipeline_mode="lookahead")
    s_u, s_p = tr_u.init(0), tr_p.init(0)
    for i in range(4):
        s_u, _ = tr_u.train_step(s_u, sb[i])
        s_p, _ = tr_p.train_step(s_p, sb[i])
    s_p, _ = adopt(tr_p, s_p)
    stacked = shard_batch(mesh, stack_batches(batches[4:7]), stacked=True)
    s_u, m_u = tr_u.train_steps(s_u, stacked)
    s_p, m_p = tr_p.train_steps(s_p, stacked)
    np.testing.assert_array_equal(
        np.asarray(m_u["loss"]), np.asarray(m_p["loss"])
    )
    assert_same_rows(tr_u, s_u, tr_p, s_p)


# ------------------------------------------------------- hot-key fallback


def test_plan_owner_device_host_parity():
    """`plan_owner` (device, consulted inside shard_map) and
    `ShardPlan.owner_np` (host, used by restore + migration) must agree
    bit-for-bit — a disagreement strands migrated rows."""
    rng = np.random.default_rng(0)
    keys = rng.integers(0, 1 << 30, 4096).astype(np.int32)
    plan = P.ShardPlan(
        num_shards=8, sentinel=-1, offset=3,
        hot_keys=tuple(int(k) for k in keys[:5]),
        hot_owners=(0, 7, 3, 3, 1),
    )
    # device side consults the sentinel-PADDED routing table (bundles pad
    # every member to a common H)
    leaves = plan.leaves(np.int32, pad_h=12)
    dev = np.asarray(P.plan_owner(jnp.asarray(keys), 8, leaves))
    host = plan.owner_np(keys)
    np.testing.assert_array_equal(dev, host)
    # hot keys take their table entry...
    np.testing.assert_array_equal(host[:5], [0, 7, 3, 3, 1])
    # ...every other key its rotated hash-home (H exceeded -> fallback)
    rest = keys[5:]
    np.testing.assert_array_equal(
        host[5:], (hashing.hash_shard_np(rest, 8) + 3) % 8
    )


def test_empty_plan_is_uniform_hash():
    keys = np.arange(100, dtype=np.int32)
    for leaves in (None, {}):
        np.testing.assert_array_equal(
            np.asarray(P.plan_owner(jnp.asarray(keys), 8, leaves)),
            hashing.hash_shard_np(keys, 8),
        )
    assert P.ShardPlan(num_shards=8, sentinel=-1).is_uniform


def test_build_plans_hot_budget_and_balance():
    """The placer respects the hot budget (overflow falls back to the
    rotation — no key is ever dropped from routing), only promotes keys
    present on >1 source shard, and reduces modeled imbalance."""
    rng = np.random.default_rng(1)
    members = []
    for t in range(4):
        n = 400
        keys = rng.choice(1 << 20, n, replace=False).astype(np.int32)
        weight = np.ones(n)
        weight[:20] = 8.0  # zipf head: on every source shard
        members.append(P.MemberTraffic(
            bundle=f"b{t}", member=0, keys=keys, weight=weight,
            row_bytes=64.0, sentinel=-1,
        ))
    plans, report = P.build_plans(8, members, hot_budget=6)
    for m in members:
        p = plans[(m.bundle, 0)]
        assert len(p.hot_keys) <= 6
        # every hot key has weight > 1 (worth moving)
        w = dict(zip(m.keys.tolist(), m.weight.tolist()))
        assert all(w[k] > 1.0 for k in p.hot_keys)
        # non-hot keys route by rotation — budget overflow = fallback
        rest = np.array(
            [k for k in m.keys if k not in set(p.hot_keys)], np.int32
        )
        np.testing.assert_array_equal(
            p.owner_np(rest),
            (hashing.hash_shard_np(rest, 8) + p.offset) % 8,
        )
    assert report["imbalance_after"] <= report["imbalance_before"]
    # modeled_loads under the returned plans reproduces the report
    after = P.modeled_loads(8, members, plans)
    from deeprec_tpu.ops.traffic import shard_imbalance

    assert round(shard_imbalance(after), 4) == report["imbalance_after"]


def test_reshard_failure_leaves_state_untouched(mesh):
    """A plan that cannot place every key (shard over local capacity)
    must abort the migration — update_placement keeps the old plan and
    the caller's state."""
    from deeprec_tpu.embedding.table import empty_key

    tr = ShardedTrainer(
        WDL(emb_dim=8, capacity=1 << 9, hidden=(16,), num_cat=4,
            num_dense=2),
        Adagrad(lr=0.1), optax.sgd(0.01), mesh=mesh, placement="plan",
    )
    st = tr.init(0)
    for b in skewed_batches(2, batch_size=256, seed=3):
        st, _ = tr.train_step(st, shard_batch(mesh, b))
    bname, b = next(iter(tr.bundles.items()))
    ts = st.tables[bname]
    lead = tr._bundle_lead_dims(b)
    members = [
        jax.tree.map(lambda a, i=i: a[i], ts) for i in np.ndindex(*lead)
    ]
    shard_states = members[: tr.num_shards]
    sent = empty_key(b.table.cfg)
    total = sum(
        int(np.sum(np.asarray(s.keys) != sent)) for s in shard_states
    )
    assert total > int(shard_states[0].keys.shape[0]), "not enough rows"
    res, moved, reason = P.reshard_members(
        b.table, shard_states,
        lambda keys: np.zeros(len(np.asarray(keys)), np.int32),  # all -> 0
    )
    assert res is None and moved == 0
    assert "capacity" in reason or "overflow" in reason


def test_multi_tier_bundle_is_never_replanned(mesh):
    """hbm_dram tables keep demoted rows in per-shard tier stores the
    migration cannot move — update_placement must pin them to uniform
    routing (skipped: multi_tier), even under force."""
    from deeprec_tpu import EmbeddingVariableOption, StorageOption

    ev = EmbeddingVariableOption(
        storage=StorageOption(storage_type="hbm_dram")
    )
    tr = ShardedTrainer(
        WDL(emb_dim=8, capacity=1 << 12, hidden=(16,), num_cat=4,
            num_dense=2, ev=ev),
        Adagrad(lr=0.1), optax.sgd(0.01), mesh=mesh, placement="plan",
    )
    st = tr.init(0)
    for b in skewed_batches(3, batch_size=128):
        st, _ = tr.train_step(st, shard_batch(mesh, b))
    st, rep = tr.update_placement(st, force=True)
    assert all(r == {"adopted": False, "skipped": "multi_tier"}
               for r in rep.values()), rep
    assert not tr._plans
    # and the routing fingerprint stays uniform for checkpoint purposes
    assert all(tr.routing_fingerprint(bn) == "uniform" for bn in tr.bundles)


# ----------------------------------------------------------- observability


def test_per_shard_dedup_stats(mesh):
    """Exchange skew is observable from a live TrainState: per mesh
    position owner-unique / arrivals / modeled exchange bytes + max/mean
    imbalance, reset on the update_budgets window like the dedup
    counters."""
    batches = skewed_batches(3, batch_size=128)
    tr = build(mesh)
    st = tr.init(0)
    for b in batches:
        st, _ = tr.train_step(st, shard_batch(mesh, b))
    stats = tr.dedup_stats(st)
    assert stats, "no tables reported"
    for t, d in stats.items():
        ps = d["per_shard"]
        assert len(ps["owner_unique"]) == 8
        assert len(ps["exchange_bytes"]) == 8
        assert sum(ps["owner_unique"]) > 0
        assert sum(ps["owner_arrivals"]) >= sum(ps["owner_unique"])
        assert ps["imbalance"] >= 1.0
    # window reset: counters zero after update_budgets
    st, _ = tr.update_budgets(st)
    for t, d in tr.dedup_stats(st).items():
        assert sum(d["per_shard"]["owner_arrivals"]) == 0
    # the single-device trainer has no shard axis -> no per_shard key
    tr1 = Trainer(model(), Adagrad(lr=0.1))
    s1 = tr1.init(0)
    s1, _ = tr1.train_step(s1, batches[0])
    assert all("per_shard" not in d for d in tr1.dedup_stats(s1).values())


# ------------------------------------------------------- checkpoint round


def test_checkpoint_roundtrip_across_plan_change(mesh, tmp_path):
    """Save under plan A, restore under plan B (and the reverse): rows
    must land on the shard where the RESTORING trainer's active plan will
    look them up, and training after the restore must match the saved
    trainer bit-exactly."""
    from deeprec_tpu.training.checkpoint import CheckpointManager

    batches = skewed_batches(6)
    sb = [shard_batch(mesh, b) for b in batches]

    # trainer A: uniform plan, train, save
    tr_a = build(mesh, "uniform")
    s_a = tr_a.init(0)
    for i in range(4):
        s_a, _ = tr_a.train_step(s_a, sb[i])
    ck_a = CheckpointManager(str(tmp_path / "ck"), tr_a)
    s_a, _ = ck_a.save(s_a)

    # trainer B: non-uniform plan adopted from its own counters
    tr_b = build(mesh, "plan")
    s_b = tr_b.init(0)
    for i in range(4):
        s_b, _ = tr_b.train_step(s_b, sb[i])
    s_b, _ = adopt(tr_b, s_b)

    # uniform-saved checkpoint restores into the plan-B topology
    ck_b = CheckpointManager(str(tmp_path / "ck"), tr_b)
    r_b = ck_b.restore()
    assert_same_rows(tr_a, s_a, tr_b, r_b)
    # ...and every restored key is where plan B routes it
    from deeprec_tpu.embedding.table import empty_key

    for ref, plan in tr_b._plans.items():
        b = tr_b.bundles[ref]
        ts = r_b.tables[ref]
        sent = empty_key(b.table.cfg)
        keys = np.asarray(jax.device_get(ts.keys))
        lead = keys.shape[:-1]
        for idx in np.ndindex(*lead):
            m = idx[0] if len(idx) == 2 else 0
            shard = idx[-1]
            k_loc = keys[idx]
            live = k_loc[k_loc != sent]
            if live.size:
                np.testing.assert_array_equal(
                    plan.member(m).owner_np(live),
                    np.full(live.size, shard),
                )
    # training resumes bit-exactly on both sides
    for i in range(4, 6):
        s_a, m_a = tr_a.train_step(s_a, sb[i])
        r_b, m_b = tr_b.train_step(r_b, sb[i])
        assert float(m_a["loss"]) == float(m_b["loss"])
    assert_same_rows(tr_a, s_a, tr_b, r_b)

    # reverse direction: save under plan B, restore under uniform C
    ck_b2 = CheckpointManager(str(tmp_path / "ck_b"), tr_b)
    r_b, _ = ck_b2.save(r_b)
    tr_c = build(mesh, "uniform")
    ck_c = CheckpointManager(str(tmp_path / "ck_b"), tr_c)
    r_c = ck_c.restore()
    assert_same_rows(tr_b, r_b, tr_c, r_c)
    s_cont_b, m_b = tr_b.train_step(r_b, sb[0])
    s_cont_c, m_c = tr_c.train_step(r_c, sb[0])
    assert float(m_b["loss"]) == float(m_c["loss"])
    assert_same_rows(tr_b, s_cont_b, tr_c, s_cont_c)


def test_cbf_sketch_rebuilds_across_plan_change(mesh, tmp_path):
    """A saved per-shard CBF sketch describes the rows save-time ROUTING
    put on that shard. Restoring under a DIFFERENT plan must not reuse it
    shard-for-shard (the manifest routing fingerprint gates it) — the
    sketches rebuild from the rows each shard imports, so every ADMITTED
    key's count stays exact on the shard that now owns it."""
    from deeprec_tpu.config import CBFFilter, EmbeddingVariableOption
    from deeprec_tpu.embedding import filters as F
    from deeprec_tpu.embedding.table import empty_key
    from deeprec_tpu.training.checkpoint import CheckpointManager

    ev = EmbeddingVariableOption(
        cbf_filter=CBFFilter(filter_freq=2, max_element_size=1 << 12)
    )
    batches = skewed_batches(4, batch_size=256)
    sb = [shard_batch(mesh, b) for b in batches]

    def mk(placement):
        return ShardedTrainer(
            WDL(emb_dim=8, capacity=1 << 12, hidden=(16,), num_cat=4,
                num_dense=2, ev=ev),
            Adagrad(lr=0.1), optax.sgd(0.01), mesh=mesh,
            placement=placement, placement_hot_budget=16,
        )

    tr_a = mk("uniform")
    s_a = tr_a.init(0)
    for b in sb:
        s_a, _ = tr_a.train_step(s_a, b)
    ck_a = CheckpointManager(str(tmp_path / "cbf"), tr_a)
    s_a, _ = ck_a.save(s_a)

    tr_b = mk("plan")
    s_b = tr_b.init(0)
    for b in sb:
        s_b, _ = tr_b.train_step(s_b, b)
    s_b, _ = adopt(tr_b, s_b)
    assert tr_b.routing_fingerprint(
        next(iter(tr_b.bundles))
    ) != "uniform"
    r_b = CheckpointManager(str(tmp_path / "cbf"), tr_b).restore()

    # every shard's sketch must cover each of ITS OWN admitted keys'
    # full count (CBF estimates over-count on collisions, never under) —
    # shard-for-shard reuse of the save-time sketches would query
    # re-routed keys against another shard's counts and UNDER-count them
    cbf = ev.cbf_filter
    for bname, b in tr_b.bundles.items():
        ts = r_b.tables[bname]
        sent = empty_key(b.table.cfg)
        keys = np.asarray(jax.device_get(ts.keys))
        freq = np.asarray(jax.device_get(ts.freq))
        bloom = np.asarray(jax.device_get(ts.bloom))
        lead = keys.shape[:-1]
        for idx in np.ndindex(*lead):
            k_loc = keys[idx]
            occ = k_loc != sent
            if not occ.any():
                continue
            est = np.asarray(F.cbf_estimate(
                cbf, jnp.asarray(bloom[idx]), jnp.asarray(k_loc[occ])
            ))
            under = est < freq[idx][occ]
            assert not under.any(), (
                f"{bname}{idx}: {int(under.sum())} admitted keys "
                f"under-counted after cross-plan restore"
            )
