"""Gradient micro-batching (Auto-Micro-Batch parity)."""
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from deeprec_tpu.data import SyntheticCriteo
from deeprec_tpu.models import WDL
from deeprec_tpu.optim import Adagrad, GradientDescent
from deeprec_tpu.training import Trainer


def J(b):
    return {k: jnp.asarray(v) for k, v in b.items()}


def model():
    return WDL(emb_dim=8, capacity=1 << 12, hidden=(16,), num_cat=4, num_dense=2)


def test_accum_learns_and_counts_one_step():
    tr = Trainer(model(), Adagrad(lr=0.1), optax.adam(2e-3))
    st = tr.init(0)
    gen = SyntheticCriteo(batch_size=512, num_cat=4, num_dense=2, vocab=1000, seed=3)
    b = J(gen.batch())
    losses = []
    for _ in range(10):
        st, m = tr.train_step_accum(st, b, accum_steps=4)
        losses.append(float(m["loss"]))
    assert int(st.step) == 10  # one global step per accum call
    assert losses[-1] < losses[0]


def test_sharded_accum_learns():
    from deeprec_tpu.parallel import ShardedTrainer, make_mesh, shard_batch

    mesh = make_mesh(8)
    tr = ShardedTrainer(model(), Adagrad(lr=0.1), optax.adam(2e-3), mesh=mesh)
    st = tr.init(0)
    gen = SyntheticCriteo(batch_size=512, num_cat=4, num_dense=2, vocab=1000, seed=4)
    b = shard_batch(mesh, J(gen.batch()))
    losses = []
    for _ in range(8):
        st, m = tr.train_step_accum(st, b, accum_steps=2)
        losses.append(float(m["loss"]))
    assert int(st.step) == 8
    assert losses[-1] < losses[0]


def test_accum_dense_grads_match_full_batch():
    """With plain SGD and a single pass, accumulated dense grads must equal
    the full-batch gradient (sparse applies differ by design: per-micro)."""
    gen = SyntheticCriteo(batch_size=256, num_cat=4, num_dense=2, vocab=500, seed=5)
    b = J(gen.batch())

    tr1 = Trainer(model(), GradientDescent(lr=0.0), optax.sgd(0.5))
    s1 = tr1.init(0)
    s1, _ = tr1.train_step(s1, b)

    tr2 = Trainer(model(), GradientDescent(lr=0.0), optax.sgd(0.5))
    s2 = tr2.init(0)
    s2, _ = tr2.train_step_accum(s2, b, accum_steps=4)

    # sparse lr=0 -> embeddings identical; dense updates must match because
    # mean of micro-grads == full-batch grad for a mean loss
    d1 = jnp.concatenate([x.reshape(-1) for x in
                          (s1.dense["deep"]["layers"][0]["w"],
                           s1.dense["wide_w"])])
    d2 = jnp.concatenate([x.reshape(-1) for x in
                          (s2.dense["deep"]["layers"][0]["w"],
                           s2.dense["wide_w"])])
    np.testing.assert_allclose(np.asarray(d1), np.asarray(d2), atol=2e-4)
