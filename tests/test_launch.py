"""Multi-host launcher CI test: 2 real processes over the DCN control
plane (jax.distributed on CPU), driving a global psum and the
file-coordinated WorkQueue (reference: distribute/launch.py + WorkQueue's
PS-hosted queue, re-cut for a shared filesystem).
"""
import json
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

REPO = os.path.join(os.path.dirname(__file__), "..")

WORKER = textwrap.dedent(
    """
    import json, os, sys
    import jax
    import jax.numpy as jnp
    import numpy as np
    sys.path.insert(0, {repo!r})
    from deeprec_tpu.data.work_queue import WorkQueue

    # launched via deeprec_tpu.launch: distributed is already initialized
    pid = jax.process_index()
    n = jax.process_count()
    assert n == 2, n

    # global collective across processes
    mesh = jax.sharding.Mesh(jax.devices(), ("d",))
    ones = jnp.ones((len(jax.devices()),))
    total = jax.jit(
        jax.shard_map(
            lambda x: jax.lax.psum(x, "d"),
            mesh=mesh,
            in_specs=jax.sharding.PartitionSpec("d"),
            out_specs=jax.sharding.PartitionSpec("d"),
        )
    )(ones)
    # the result is a global array; each process reads its local shard
    got = float(np.asarray(total.addressable_shards[0].data)[0])

    # file-coordinated WorkQueue: both processes drain a shared queue
    q = WorkQueue([f"work{{i}}" for i in range(20)], shuffle=False,
                  coordination_file={coord!r})
    taken = [w for w in q]

    # full multi-host training: ShardedTrainer over the GLOBAL mesh, each
    # process feeding its local slice of the batch
    import optax
    from deeprec_tpu.data import SyntheticCriteo
    from deeprec_tpu.models import WDL
    from deeprec_tpu.optim import Adagrad
    from deeprec_tpu.parallel import ShardedTrainer, make_mesh, shard_batch

    gmesh = make_mesh()  # all 4 devices across both processes
    model = WDL(emb_dim=4, capacity=1 << 8, hidden=(8,), num_cat=2,
                num_dense=2)
    tr = ShardedTrainer(model, Adagrad(lr=0.1), optax.adam(1e-3), mesh=gmesh)
    st = tr.init(0)
    gen = SyntheticCriteo(batch_size=8, num_cat=2, num_dense=2, vocab=200,
                          seed=100 + pid)  # local slice: half the global batch
    losses = []
    for _ in range(3):
        batch = shard_batch(gmesh, {{k: jnp.asarray(v)
                                     for k, v in gen.batch().items()}})
        st, mets = tr.train_step(st, batch)
        losses.append(float(mets["loss"]))

    # multi-host checkpoint: all processes gather, proc 0 writes, barrier
    from deeprec_tpu.training.checkpoint import CheckpointManager
    ck = CheckpointManager({ckdir!r}, tr)
    st, ck_path = ck.save(st)
    # restore on the SAME 2-process mesh and keep training: loss identical
    st2 = ck.restore()
    batch = shard_batch(gmesh, {{k: jnp.asarray(v)
                                 for k, v in gen.batch().items()}})
    _, m_orig = tr.train_step(st, batch)
    _, m_rest = tr.train_step(st2, batch)
    restore_pair = [float(m_orig["loss"]), float(m_rest["loss"])]

    out = {{"pid": pid, "psum": got, "taken": taken, "losses": losses,
            "restore_pair": restore_pair, "ndev": len(jax.devices())}}
    with open({outdir!r} + f"/out{{pid}}.json", "w") as f:
        json.dump(out, f)
    """
)


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_two_process_launch_psum_and_workqueue(tmp_path):
    import numpy as np

    coord_file = str(tmp_path / "queue.json")
    ckdir = str(tmp_path / "ckpt")
    script = str(tmp_path / "worker.py")
    with open(script, "w") as f:
        f.write(WORKER.format(repo=os.path.abspath(REPO), coord=coord_file,
                              outdir=str(tmp_path), ckdir=ckdir))
    port = _free_port()
    env = {
        **os.environ,
        "PYTHONPATH": "",
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
    }
    procs = [
        subprocess.Popen(
            [
                sys.executable, "-m", "deeprec_tpu.launch",
                "--coordinator", f"127.0.0.1:{port}",
                "--num_processes", "2", "--process_id", str(i),
                script,
            ],
            env=env, cwd=os.path.abspath(REPO),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        for i in range(2)
    ]
    outs = [p.communicate(timeout=180)[0] for p in procs]
    for p, o in zip(procs, outs):
        assert p.returncode == 0, o.decode()[-2000:]

    results = []
    for i in range(2):
        with open(tmp_path / f"out{i}.json") as f:
            results.append(json.load(f))
    # 2 processes x 2 local devices = 4 global devices; psum of ones = 4
    assert all(r["ndev"] == 4 for r in results), results
    assert all(r["psum"] == 4.0 for r in results), results
    # WorkQueue: disjoint union covering all 20 items, both workers active
    taken = [set(r["taken"]) for r in results]
    assert taken[0].isdisjoint(taken[1])
    assert taken[0] | taken[1] == {f"work{i}" for i in range(20)}
    assert taken[0] and taken[1]
    # sharded training across hosts: same replicated loss on both, finite
    assert results[0]["losses"] == results[1]["losses"], results
    assert all(np.isfinite(l) for l in results[0]["losses"])
    # multi-host save -> same-topology restore continues identically
    for r in results:
        a, b = r["restore_pair"]
        assert abs(a - b) < 1e-6, r["restore_pair"]

    # ELASTIC: restore the 2-process checkpoint in a SINGLE process on its
    # own 2-device mesh (4 shards -> 2 shards) and keep training
    single = textwrap.dedent(
        f"""
        import sys
        sys.path.insert(0, {os.path.abspath(REPO)!r})
        import jax.numpy as jnp
        import numpy as np
        import optax
        from deeprec_tpu.data import SyntheticCriteo
        from deeprec_tpu.models import WDL
        from deeprec_tpu.optim import Adagrad
        from deeprec_tpu.parallel import ShardedTrainer, make_mesh, shard_batch
        from deeprec_tpu.training.checkpoint import CheckpointManager

        mesh = make_mesh(2)
        model = WDL(emb_dim=4, capacity=1 << 8, hidden=(8,), num_cat=2,
                    num_dense=2)
        tr = ShardedTrainer(model, Adagrad(lr=0.1), optax.adam(1e-3),
                            mesh=mesh)
        st = CheckpointManager({ckdir!r}, tr).restore()
        gen = SyntheticCriteo(batch_size=16, num_cat=2, num_dense=2,
                              vocab=200, seed=7)
        st, m = tr.train_step(
            st, shard_batch(mesh, {{k: jnp.asarray(v)
                                    for k, v in gen.batch().items()}})
        )
        assert np.isfinite(float(m["loss"]))
        print("ELASTIC_OK", float(m["loss"]))
        """
    )
    single_py = str(tmp_path / "single.py")
    with open(single_py, "w") as f:
        f.write(single)
    out = subprocess.run(
        [sys.executable, single_py], env=env, timeout=240,
        capture_output=True, text=True,
    )
    assert out.returncode == 0, out.stdout[-1500:] + out.stderr[-1500:]
    assert "ELASTIC_OK" in out.stdout
