"""In-step pipelining (`pipeline_mode`): the K-step scan with a one-batch
lookahead must be EXACT — bit-identical table ints, values, dense params and
per-step losses vs the sequential `pipeline_mode="off"` scan — across
single-device, sharded-allgather and sharded-a2a, in both "lookahead" and
"chunked" modes, including the hazard case where batch t+1 touches rows
batch t's apply dirties (the reason the value gather/exchange runs AFTER
the apply instead of speculating)."""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from deeprec_tpu.data import SyntheticCriteo
from deeprec_tpu.models import WDL
from deeprec_tpu.optim import Adagrad
from deeprec_tpu.training import Trainer


def J(b):
    return {k: jnp.asarray(v) for k, v in b.items()}


@pytest.fixture(scope="module")
def mesh():
    from deeprec_tpu.parallel import make_mesh

    return make_mesh(8)


def model():
    return WDL(emb_dim=8, capacity=1 << 12, hidden=(16,), num_cat=4,
               num_dense=2)


def window_batches(K=4, batch_size=64, seed=7, fresh_ids=True):
    """K batches; fresh_ids=True gives later batches never-seen ids (the
    insert path mid-window), fresh_ids=False keeps every batch in one
    small vocab so consecutive batches HEAVILY overlap — batch t+1 reads
    rows batch t's apply just wrote (the staleness hazard)."""
    gen = SyntheticCriteo(batch_size=batch_size, num_cat=4, num_dense=2,
                          vocab=500 if fresh_ids else 40, seed=seed)
    batches = [J(gen.batch()) for _ in range(K)]
    if fresh_ids:
        for t in range(1, K):
            batches[t]["C1"] = batches[t]["C1"] + jnp.int32(10_000 * t)
    return batches


def assert_states_bitwise(s_a, s_b):
    """Full exactness: table ints AND values bitwise, dense/opt bitwise."""
    for bname in s_a.tables:
        a, b = s_a.tables[bname], s_b.tables[bname]
        np.testing.assert_array_equal(np.asarray(a.keys), np.asarray(b.keys))
        np.testing.assert_array_equal(np.asarray(a.meta), np.asarray(b.meta))
        np.testing.assert_array_equal(
            np.asarray(a.insert_fails), np.asarray(b.insert_fails)
        )
        np.testing.assert_array_equal(
            np.asarray(a.dedup_unique), np.asarray(b.dedup_unique)
        )
        np.testing.assert_array_equal(
            np.asarray(a.values), np.asarray(b.values)
        )
        for k in a.slots:
            np.testing.assert_array_equal(
                np.asarray(a.slots[k]), np.asarray(b.slots[k])
            )
    for x, y in zip(jax.tree.leaves(s_a.dense), jax.tree.leaves(s_b.dense)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    for x, y in zip(
        jax.tree.leaves(s_a.opt_state), jax.tree.leaves(s_b.opt_state)
    ):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# --------------------------------------------------------------- single dev


def test_lookahead_matches_off_single_device():
    K = 4
    batches = window_batches(K)
    t_off = Trainer(model(), Adagrad(lr=0.1), optax.adam(2e-3))
    t_la = Trainer(model(), Adagrad(lr=0.1), optax.adam(2e-3),
                   pipeline_mode="lookahead")
    s0, m0 = t_off.train_steps(t_off.init(0), batches)
    s1, m1 = t_la.train_steps(t_la.init(0), batches)
    assert m1["loss"].shape == (K,)
    np.testing.assert_array_equal(np.asarray(m0["loss"]), np.asarray(m1["loss"]))
    np.testing.assert_array_equal(
        np.asarray(m0["accuracy"]), np.asarray(m1["accuracy"])
    )
    assert int(s1.step) == K
    assert_states_bitwise(s0, s1)


def test_lookahead_k1_window():
    """K=1 degenerates to prologue + epilogue (the scan runs zero
    iterations) and still matches the sequential step exactly."""
    batches = window_batches(1)
    t_off = Trainer(model(), Adagrad(lr=0.1))
    t_la = Trainer(model(), Adagrad(lr=0.1), pipeline_mode="lookahead")
    s0, m0 = t_off.train_steps(t_off.init(0), batches)
    s1, m1 = t_la.train_steps(t_la.init(0), batches)
    assert m1["loss"].shape == (1,)
    np.testing.assert_array_equal(np.asarray(m0["loss"]), np.asarray(m1["loss"]))
    assert_states_bitwise(s0, s1)


def test_lookahead_hazard_overlapping_ids_single_device():
    """Tiny vocab: every batch rewrites rows the next batch reads — the
    finish-after-apply placement must make the lookahead see post-apply
    values (a speculative pre-apply gather would diverge here)."""
    batches = window_batches(4, fresh_ids=False)
    t_off = Trainer(model(), Adagrad(lr=0.3))
    t_la = Trainer(model(), Adagrad(lr=0.3), pipeline_mode="lookahead")
    s0, m0 = t_off.train_steps(t_off.init(0), batches)
    s1, m1 = t_la.train_steps(t_la.init(0), batches)
    np.testing.assert_array_equal(np.asarray(m0["loss"]), np.asarray(m1["loss"]))
    assert_states_bitwise(s0, s1)


def test_lookahead_with_unique_budget():
    """The split-phase route carries the hash dedup engine: budgeted
    pipelined scan == budgeted sequential scan exactly."""
    batches = window_batches(3)
    t_off = Trainer(model(), Adagrad(lr=0.1), unique_budget=64)
    t_la = Trainer(model(), Adagrad(lr=0.1), unique_budget=64,
                   pipeline_mode="lookahead")
    s0, m0 = t_off.train_steps(t_off.init(0), batches)
    s1, m1 = t_la.train_steps(t_la.init(0), batches)
    np.testing.assert_array_equal(np.asarray(m0["loss"]), np.asarray(m1["loss"]))
    assert_states_bitwise(s0, s1)


def test_pipeline_mode_validated():
    with pytest.raises(ValueError, match="pipeline_mode"):
        Trainer(model(), Adagrad(lr=0.1), pipeline_mode="sideways")


# ------------------------------------------------------------------ sharded


@pytest.mark.parametrize("comm", ["allgather", "a2a"])
@pytest.mark.parametrize("mode", ["lookahead", "chunked"])
def test_sharded_pipelined_matches_off(mesh, comm, mode):
    from deeprec_tpu.parallel import ShardedTrainer, shard_batch

    K = 3
    batches = [
        shard_batch(mesh, b)
        for b in window_batches(K, batch_size=64, seed=9)
    ]
    t_off = ShardedTrainer(model(), Adagrad(lr=0.1), optax.adam(2e-3),
                           mesh=mesh, comm=comm)
    t_p = ShardedTrainer(model(), Adagrad(lr=0.1), optax.adam(2e-3),
                         mesh=mesh, comm=comm, pipeline_mode=mode,
                         pipeline_chunks=3)
    s0, m0 = t_off.train_steps(t_off.init(0), batches)
    s1, m1 = t_p.train_steps(t_p.init(0), batches)
    assert m1["loss"].shape == (K,)
    np.testing.assert_array_equal(np.asarray(m0["loss"]), np.asarray(m1["loss"]))
    assert_states_bitwise(s0, s1)


def test_sharded_hazard_overlapping_ids(mesh):
    """Sharded hazard case: consecutive batches share most ids, so the
    owner value gather of batch t+1 reads rows batch t's grad exchange +
    apply just updated."""
    from deeprec_tpu.parallel import ShardedTrainer, shard_batch

    batches = [
        shard_batch(mesh, b)
        for b in window_batches(4, batch_size=64, seed=3, fresh_ids=False)
    ]
    t_off = ShardedTrainer(model(), Adagrad(lr=0.3), mesh=mesh)
    t_la = ShardedTrainer(model(), Adagrad(lr=0.3), mesh=mesh,
                          pipeline_mode="lookahead")
    s0, m0 = t_off.train_steps(t_off.init(0), batches)
    s1, m1 = t_la.train_steps(t_la.init(0), batches)
    np.testing.assert_array_equal(np.asarray(m0["loss"]), np.asarray(m1["loss"]))
    assert_states_bitwise(s0, s1)


def test_chunked_single_step_exchange(mesh):
    """pipeline_mode="chunked" splits the value/grad exchanges on EVERY
    path — the single-step program too — bitwise identical to whole
    exchanges."""
    from deeprec_tpu.parallel import ShardedTrainer, shard_batch

    batches = [
        shard_batch(mesh, b) for b in window_batches(3, batch_size=64, seed=5)
    ]
    t_off = ShardedTrainer(model(), Adagrad(lr=0.1), mesh=mesh, comm="a2a")
    t_ch = ShardedTrainer(model(), Adagrad(lr=0.1), mesh=mesh, comm="a2a",
                          pipeline_mode="chunked", pipeline_chunks=4)
    assert all(s.exchange_chunks == 4 for s in t_ch.sharded.values())
    s0, s1 = t_off.init(0), t_ch.init(0)
    for b in batches:
        s0, m0 = t_off.train_step(s0, b)
        s1, m1 = t_ch.train_step(s1, b)
        np.testing.assert_array_equal(
            np.asarray(m0["loss"]), np.asarray(m1["loss"])
        )
    assert_states_bitwise(s0, s1)


# --------------------------------------------------- shared-table sequential


def _shared_model():
    from deeprec_tpu.config import TableConfig
    from deeprec_tpu.features import DenseFeature, SparseFeature

    tab = TableConfig(name="item", dim=8, capacity=1 << 10)

    class TinyShared:
        features = [
            SparseFeature("item", table=tab),
            SparseFeature("item2", shared_table="item"),
            DenseFeature("d", 1),
        ]

        def init(self, key):
            return {"w": jax.random.normal(key, (16,)) * 0.1}

        def apply(self, dense, inputs, train):
            x = jnp.concatenate(
                [inputs.pooled["item"], inputs.pooled["item2"]], -1
            )
            return x @ dense["w"]

    return TinyShared()


def _shared_batches(K=3, n=32):
    rng = np.random.default_rng(0)
    out = []
    for _ in range(K):
        ids = rng.integers(0, 20, size=(n,)).astype(np.int32)
        out.append(J({
            "item": ids,
            "item2": ids[::-1].copy(),  # heavy overlap, different layout
            "d": rng.normal(size=(n, 1)).astype(np.float32),
            "label": (rng.random(n) < 0.5).astype(np.float32),
        }))
    return out


def test_shared_table_pipelined_single_device():
    """Two features on ONE shared table (sequential lookups + sequential
    re-gathering applies) under the pipelined scan: the resolve of both
    features chains inserts exactly as the sequential path, both finishes
    read post-apply values, and the second apply still sees the first's
    writes."""
    batches = _shared_batches()
    t_off = Trainer(_shared_model(), Adagrad(lr=0.2))
    t_la = Trainer(_shared_model(), Adagrad(lr=0.2), pipeline_mode="lookahead")
    b = next(iter(t_la.bundles.values()))
    assert not t_la._bundle_reuse_rows(b)
    s0, m0 = t_off.train_steps(t_off.init(0), batches)
    s1, m1 = t_la.train_steps(t_la.init(0), batches)
    np.testing.assert_array_equal(np.asarray(m0["loss"]), np.asarray(m1["loss"]))
    assert_states_bitwise(s0, s1)


def test_shared_table_pipelined_sharded(mesh):
    from deeprec_tpu.parallel import ShardedTrainer, shard_batch

    batches = [shard_batch(mesh, b) for b in _shared_batches(K=3, n=64)]
    t_off = ShardedTrainer(_shared_model(), Adagrad(lr=0.2), mesh=mesh)
    t_la = ShardedTrainer(_shared_model(), Adagrad(lr=0.2), mesh=mesh,
                          pipeline_mode="lookahead")
    s0, m0 = t_off.train_steps(t_off.init(0), batches)
    s1, m1 = t_la.train_steps(t_la.init(0), batches)
    np.testing.assert_array_equal(np.asarray(m0["loss"]), np.asarray(m1["loss"]))
    assert_states_bitwise(s0, s1)


# ------------------------------------------------------- async via split-phase


def test_async_state_is_pipeline_carry():
    """The stale-by-one carry is the generic PipelineCarry (the redundant
    private struct is gone), and its carried lookup results drop the
    owner-side residual (keep_rows=False through the split-phase finish)."""
    from deeprec_tpu.parallel import AsyncState
    from deeprec_tpu.training.trainer import PipelineCarry

    assert AsyncState is PipelineCarry


def test_async_bootstrap_strips_residual(mesh):
    from deeprec_tpu.parallel import AsyncShardedTrainer, shard_batch

    batches = [shard_batch(mesh, b) for b in window_batches(2)]
    asy = AsyncShardedTrainer(model(), Adagrad(lr=0.1), mesh=mesh)
    ast = asy.bootstrap(asy.init(0), batches[0])
    for r in jax.tree.leaves(
        ast.bundle_res, is_leaf=lambda x: hasattr(x, "owner_res")
    ):
        assert r.owner_res.rows.size == 0  # residual not carried
        assert r.embeddings.size > 0  # but the lookup IS finished (stale)
    ast, mets = asy.train_steps_async(ast, batches)
    assert np.isfinite(np.asarray(mets["loss"])).all()


# ------------------------------------------------------------- model pieces


def test_overlap_model_and_buffer_accounting():
    from deeprec_tpu.ops import traffic as T

    off = T.modeled_overlap_step(dense_ms=4.0, route_ms=3.0, other_ms=2.0,
                                 mode="off")
    la = T.modeled_overlap_step(dense_ms=4.0, route_ms=3.0, other_ms=2.0,
                                mode="lookahead")
    assert off == 9.0 and la == 6.0  # route hidden behind dense
    # route longer than dense: only dense's worth hides
    assert T.modeled_overlap_step(dense_ms=2.0, route_ms=5.0, other_ms=1.0,
                                  mode="lookahead") == 6.0
    assert T.pipeline_buffer_bytes(unique=10, dim=4,
                                   pipeline_mode="off") == 0.0
    b = T.pipeline_buffer_bytes(unique=10, dim=4, pipeline_mode="lookahead")
    assert b > 0
    ref = T.dlrm_reference_traffic(pipeline_mode="lookahead")
    assert ref["pipeline_buffer_bytes"] > 0
    assert T.dlrm_reference_traffic(pipeline_mode="off")[
        "pipeline_buffer_bytes"] == 0.0
