"""The modelzoo/features demo catalog stays runnable (the reference's
features/ dirs are executable documentation — ours must be too). Fast
non-training demos run by default; training demos are slow-marked."""
import os
import subprocess
import sys

import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
FEATURES = os.path.join(REPO, "modelzoo", "features")


def run_demo(d, *args, timeout=280):
    env = {**os.environ, "PYTHONPATH": "", "JAX_PLATFORMS": "cpu"}
    r = subprocess.run(
        [sys.executable, os.path.join(FEATURES, d, "train.py"), *args],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO,
    )
    assert r.returncode == 0, f"{d}: {r.stdout}\n{r.stderr}"
    return r.stdout


@pytest.mark.parametrize("demo", [
    "multihash_variable", "dynamic_dimension_embedding_variable",
    "work_queue", "multi_tier_storage",
])
def test_fast_demos(demo):
    run_demo(demo)


def test_kafka_streaming_demo():
    out = run_demo("kafka_streaming", "--selftest")
    assert "exactly once: 512" in out


@pytest.mark.slow
@pytest.mark.parametrize("demo", [
    "adamasync_optimizer", "adagraddecay_optimizer",
    "grouped_embedding", "fused_kernels", "sparse_operation_kit",
])
def test_training_demos(demo):
    out = run_demo(demo, "--steps", "40")
    assert "loss" in out


@pytest.mark.slow
def test_embedding_variable_demo_evicts():
    # 101 steps so the step-100 evict hook (the demo's headline feature)
    # actually executes under test
    out = run_demo("embedding_variable", "--steps", "101", timeout=400)
    assert "evict @ 100" in out


def test_catalog_complete():
    """Catalog consistency BOTH ways: every dir on disk is runnable or a
    recipe, and every dir the README table lists exists on disk."""
    import re

    listed = [d for d in os.listdir(FEATURES)
              if os.path.isdir(os.path.join(FEATURES, d))]
    for d in listed:
        if d.startswith("_"):
            continue
        has_train = os.path.exists(os.path.join(FEATURES, d, "train.py"))
        has_doc = os.path.exists(os.path.join(FEATURES, d, "README.md"))
        assert has_train or has_doc, f"{d}: neither train.py nor README.md"
    readme = open(os.path.join(FEATURES, "README.md")).read()
    for name in re.findall(r"^\| `([\w./]+)/`", readme, re.M):
        assert os.path.isdir(os.path.join(FEATURES, name)), (
            f"README lists {name}/ but the directory is missing")
