"""Modelzoo coverage: each model must compile a train step, run a few steps,
and reduce loss on its synthetic workload (the steps/sec+AUC regression tier
of the reference's modelzoo harness, SURVEY.md §4)."""
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from deeprec_tpu.data import (
    SyntheticBehaviorSequence,
    SyntheticCriteo,
    SyntheticMultiTask,
    SyntheticTwoTower,
)
from deeprec_tpu.models import (
    BST,
    DBMTL,
    DCNv2,
    DIEN,
    DIN,
    DLRM,
    DSSM,
    ESMM,
    MMoE,
    PLE,
    WDL,
    DeepFM,
    MaskNet,
    SimpleMultiTask,
)
from deeprec_tpu.optim import Adagrad
from deeprec_tpu.training import Trainer


def to_jnp(batch):
    return {k: jnp.asarray(v) for k, v in batch.items()}


CRITEO_MODELS = [
    WDL(emb_dim=8, capacity=1 << 12, hidden=(32,), num_cat=4, num_dense=3),
    DeepFM(emb_dim=8, capacity=1 << 12, hidden=(32,), num_cat=4, num_dense=3),
    DLRM(emb_dim=8, capacity=1 << 12, bottom=(16, 8), top=(16, 1), num_cat=4,
         num_dense=3),
    DCNv2(emb_dim=8, capacity=1 << 12, cross_depth=2, hidden=(32,), num_cat=4,
          num_dense=3),
    MaskNet(emb_dim=8, capacity=1 << 12, num_blocks=2, block_dim=16,
            mask_hidden=16, hidden=(16,), num_cat=4, num_dense=3),
]


@pytest.mark.parametrize("model", CRITEO_MODELS, ids=lambda m: type(m).__name__)
def test_criteo_model_trains(model):
    tr = Trainer(model, Adagrad(lr=0.1), optax.adam(2e-3))
    st = tr.init(0)
    gen = SyntheticCriteo(batch_size=256, num_cat=4, num_dense=3, vocab=1000, seed=7)
    b0 = to_jnp(gen.batch())
    losses = []
    for _ in range(15):
        st, m = tr.train_step(st, b0)  # same batch: loss must drop fast
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0], (type(model).__name__, losses)
    assert np.isfinite(losses).all()


SEQ_MODELS = [
    DIN(emb_dim=8, capacity=1 << 12, hidden=(32,)),
    DIEN(emb_dim=8, capacity=1 << 12, gru_hidden=8, hidden=(32,)),
    BST(emb_dim=8, capacity=1 << 12, heads=2, ff=32, max_len=16, hidden=(32,)),
]


@pytest.mark.parametrize("model", SEQ_MODELS, ids=lambda m: type(m).__name__)
def test_sequence_model_trains(model):
    tr = Trainer(model, Adagrad(lr=0.1), optax.adam(2e-3))
    st = tr.init(0)
    gen = SyntheticBehaviorSequence(batch_size=128, vocab=2000, seq_len=16, seed=11)
    b0 = to_jnp(gen.batch())
    losses = []
    for _ in range(15):
        st, m = tr.train_step(st, b0)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0], (type(model).__name__, losses)
    assert np.isfinite(losses).all()
    # shared tables: hist_items and target_item use one table
    assert tr.tables["target_item"] is tr.tables["target_item"]
    ts = tr.table_state(st, "target_item")
    assert int(tr.tables["target_item"].size(ts)) > 0


MT_MODELS = [
    SimpleMultiTask(emb_dim=8, capacity=1 << 12, num_cat=4, num_dense=2,
                    bottom=(32,), tower=(16,)),
    ESMM(emb_dim=8, capacity=1 << 12, num_cat=4, num_dense=2, tower=(16,)),
    MMoE(emb_dim=8, capacity=1 << 12, num_cat=4, num_dense=2, num_experts=2,
         expert=(16,), tower=(8,)),
    PLE(emb_dim=8, capacity=1 << 12, num_cat=4, num_dense=2, expert=(16,),
        tower=(8,)),
    DBMTL(emb_dim=8, capacity=1 << 12, num_cat=4, num_dense=2, bottom=(32,),
          tower=(8,)),
]


@pytest.mark.parametrize("model", MT_MODELS, ids=lambda m: type(m).__name__)
def test_multitask_model_trains(model):
    tr = Trainer(model, Adagrad(lr=0.1), optax.adam(2e-3))
    st = tr.init(0)
    gen = SyntheticMultiTask(batch_size=256, num_cat=4, num_dense=2, vocab=1000,
                             seed=13)
    b0 = to_jnp(gen.batch())
    losses = []
    for _ in range(12):
        st, m = tr.train_step(st, b0)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0], (type(model).__name__, losses)
    assert np.isfinite(losses).all()


def test_dssm_trains_and_evaluates():
    model = DSSM(emb_dim=8, capacity=1 << 12, num_user_feats=2, num_item_feats=2,
                 hidden=(32, 16))
    tr = Trainer(model, Adagrad(lr=0.1), optax.adam(2e-3))
    st = tr.init(0)
    gen = SyntheticTwoTower(batch_size=256, num_user=2, num_item=2, vocab=2000,
                            seed=17)
    b0 = to_jnp(gen.batch())
    losses = []
    for _ in range(15):
        st, m = tr.train_step(st, b0)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0], losses
