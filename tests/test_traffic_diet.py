"""Traffic diet (forward-residual reuse + fused metadata + bf16 exchanges).

Parity contract: the diet deletes REDUNDANT work — the apply-side value
re-gather (the forward already gathered those rows) and the apply-side
version/dirty re-stamps (the same-step train lookup already stamped them) —
so the diet path must be indistinguishable from the legacy apply
(`apply_gradients(reuse_rows=False, stamp_meta=True)`): bit-identical
keys/freq/version/dirty and identical loss trajectories, single-device and
sharded under both comm modes.  The bf16 wire format is the one deliberate
numeric change and gets its own convergence bound; eval exchanges must
ignore it entirely.
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from deeprec_tpu.config import TableConfig
from deeprec_tpu.data import SyntheticCriteo
from deeprec_tpu.features import DenseFeature, SparseFeature
from deeprec_tpu.models import WDL
from deeprec_tpu.optim import Adagrad
from deeprec_tpu.optim.apply import apply_gradients, ensure_slots
from deeprec_tpu.parallel import ShardedTrainer, make_mesh, shard_batch
from deeprec_tpu.training import Trainer


def J(b):
    return {k: jnp.asarray(v) for k, v in b.items()}


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(8)


def small():
    return WDL(emb_dim=8, capacity=1 << 12, hidden=(16,), num_cat=4,
               num_dense=2)


def retable(model, **cfg):
    model.features = [
        dataclasses.replace(f, table=dataclasses.replace(f.table, **cfg))
        if isinstance(f, SparseFeature) and f.table is not None
        else f
        for f in model.features
    ]
    return model


class LegacyApplyTrainer(Trainer):
    """The pre-diet apply: re-gather value rows, re-stamp version/dirty."""

    def _apply_one(self, b, state, res, grad, step, lr):
        return apply_gradients(
            b.table, state, self.sparse_opt, res, grad, step=step, lr=lr,
            grad_averaging=self.grad_averaging,
            reuse_rows=False, stamp_meta=True,
        )


class LegacyApplySharded(ShardedTrainer):
    def _apply_one(self, b, state, res, grad, step, lr):
        return self.sharded[b.name].apply_gradients(
            state, self.sparse_opt, res, grad, step=step, lr=lr,
            grad_averaging=self.grad_averaging,
            reuse_rows=False, stamp_meta=True,
        )


def batches_with_inserts(K=4, batch_size=64, seed=7):
    gen = SyntheticCriteo(batch_size=batch_size, num_cat=4, num_dense=2,
                          vocab=400, seed=seed)
    batches = [J(gen.batch()) for _ in range(K)]
    for t in range(1, K):
        batches[t]["C1"] = batches[t]["C1"] + jnp.int32(10_000 * t)
    return batches


def assert_tables_bitwise(s_a, s_b, values_exact=True):
    for bname in s_a.tables:
        a, b = s_a.tables[bname], s_b.tables[bname]
        np.testing.assert_array_equal(np.asarray(a.keys), np.asarray(b.keys))
        np.testing.assert_array_equal(np.asarray(a.freq), np.asarray(b.freq))
        np.testing.assert_array_equal(
            np.asarray(a.version), np.asarray(b.version)
        )
        np.testing.assert_array_equal(
            np.asarray(a.dirty), np.asarray(b.dirty)
        )
        if values_exact:
            np.testing.assert_array_equal(
                np.asarray(a.values), np.asarray(b.values)
            )
        else:
            np.testing.assert_allclose(
                np.asarray(a.values), np.asarray(b.values), atol=1e-6
            )


# ----------------------------------------------------------- exact parity


def test_diet_matches_legacy_apply_single_device():
    batches = batches_with_inserts(4)
    t_diet = Trainer(small(), Adagrad(lr=0.1), optax.adam(2e-3))
    t_leg = LegacyApplyTrainer(small(), Adagrad(lr=0.1), optax.adam(2e-3))
    s_d, s_l = t_diet.init(0), t_leg.init(0)
    for b in batches:
        s_d, m_d = t_diet.train_step(s_d, b)
        s_l, m_l = t_leg.train_step(s_l, b)
        np.testing.assert_allclose(
            float(m_d["loss"]), float(m_l["loss"]), rtol=0, atol=0
        )
    assert_tables_bitwise(s_d, s_l)


@pytest.mark.parametrize("comm", ["allgather", "a2a"])
def test_diet_matches_legacy_apply_sharded(mesh, comm):
    batches = [
        shard_batch(mesh, b) for b in batches_with_inserts(3, seed=5)
    ]
    t_diet = ShardedTrainer(small(), Adagrad(lr=0.1), optax.adam(2e-3),
                            mesh=mesh, comm=comm)
    t_leg = LegacyApplySharded(small(), Adagrad(lr=0.1), optax.adam(2e-3),
                               mesh=mesh, comm=comm)
    s_d, s_l = t_diet.init(0), t_leg.init(0)
    for b in batches:
        s_d, m_d = t_diet.train_step(s_d, b)
        s_l, m_l = t_leg.train_step(s_l, b)
        np.testing.assert_allclose(
            float(m_d["loss"]), float(m_l["loss"]), rtol=0, atol=0
        )
    assert_tables_bitwise(s_d, s_l)


def test_diet_matches_legacy_apply_async(mesh):
    """The async stage re-gathers by design (its carried residual is a step
    stale); its trajectory must equal the pre-diet async path exactly —
    which it is, since stamp_meta=True restores the apply-side stamps."""
    from deeprec_tpu.parallel import AsyncShardedTrainer

    class LegacyAsync(AsyncShardedTrainer):
        def _apply_one(self, b, state, res, grad, step, lr):
            return self.sharded[b.name].apply_gradients(
                state, self.sparse_opt, res, grad, step=step, lr=lr,
                grad_averaging=self.grad_averaging,
                reuse_rows=False, stamp_meta=True,
            )

    batches = [
        shard_batch(mesh, b) for b in batches_with_inserts(4, seed=11)
    ]
    t_a = AsyncShardedTrainer(small(), Adagrad(lr=0.1), optax.adam(2e-3),
                              mesh=mesh)
    t_b = LegacyAsync(small(), Adagrad(lr=0.1), optax.adam(2e-3), mesh=mesh)
    a = t_a.bootstrap(t_a.init(0), batches[0])
    b_ = t_b.bootstrap(t_b.init(0), batches[0])
    for x in batches[1:]:
        a, m_a = t_a.train_step_async(a, x)
        b_, m_b = t_b.train_step_async(b_, x)
        np.testing.assert_allclose(
            float(m_a["loss"]), float(m_b["loss"]), rtol=0, atol=0
        )
    assert_tables_bitwise(a.inner, b_.inner)


def test_diet_matches_legacy_through_train_steps_scan(mesh):
    """K-step scan path: the residual rides the scan body unchanged."""
    batches = batches_with_inserts(4, seed=3)
    t_diet = Trainer(small(), Adagrad(lr=0.1))
    t_leg = LegacyApplyTrainer(small(), Adagrad(lr=0.1))
    s_d, m_d = t_diet.train_steps(t_diet.init(0), batches)
    s_l, m_l = t_leg.train_steps(t_leg.init(0), batches)
    np.testing.assert_array_equal(
        np.asarray(m_d["loss"]), np.asarray(m_l["loss"])
    )
    assert_tables_bitwise(s_d, s_l)


# ------------------------------------------------ residual contract & hazard


def test_unique_lookup_rows_residual_contract():
    """UniqueLookup.rows == the raw post-insert value rows at safe_ix;
    embeddings is its admission-masked view."""
    cfg = TableConfig(name="t", dim=8, capacity=1 << 10)
    from deeprec_tpu.embedding.table import EmbeddingTable

    t = EmbeddingTable(cfg)
    s = t.create()
    s, res = t.lookup_unique(s, jnp.array([5, 5, 9, -1, 3], jnp.int32),
                             step=2)
    safe = jnp.where(res.slot_ix >= 0, res.slot_ix, 0)
    raw = np.asarray(t._gather(s.values, safe, s.capacity))
    np.testing.assert_array_equal(np.asarray(res.rows), raw)
    want = np.where(np.asarray(res.admitted)[:, None], raw, 0.0)
    np.testing.assert_array_equal(np.asarray(res.embeddings), want)


def _shared_model():
    tab = TableConfig(name="item", dim=8, capacity=1 << 10)

    class TinyShared:
        features = [
            SparseFeature("item", table=tab),
            SparseFeature("item2", shared_table="item"),
            DenseFeature("d", 1),
        ]

        def init(self, key):
            return {"w": jax.random.normal(key, (16,)) * 0.1}

        def apply(self, dense, inputs, train):
            x = jnp.concatenate(
                [inputs.pooled["item"], inputs.pooled["item2"]], -1
            )
            return x @ dense["w"]

    return TinyShared()


def test_shared_table_sequential_applies_regather():
    """Two features on ONE shared table with overlapping ids: the second
    apply must see the first apply's writes (re-gather), not its own
    pre-apply residual — parity with the legacy apply proves the bundle
    policy (_bundle_reuse_rows) keeps shared tables safe."""
    rng = np.random.default_rng(0)

    def batch():
        ids = rng.integers(0, 20, size=(32,)).astype(np.int32)
        return J({
            "item": ids,
            "item2": ids[::-1].copy(),  # heavy overlap, different layout
            "d": rng.normal(size=(32, 1)).astype(np.float32),
            "label": (rng.random(32) < 0.5).astype(np.float32),
        })

    batches = [batch() for _ in range(3)]
    t_diet = Trainer(_shared_model(), Adagrad(lr=0.2))
    t_leg = LegacyApplyTrainer(_shared_model(), Adagrad(lr=0.2))
    # the bundle is shared (2 features, unstacked) -> both arms re-gather
    b = next(iter(t_diet.bundles.values()))
    assert not t_diet._bundle_reuse_rows(b)
    s_d, s_l = t_diet.init(0), t_leg.init(0)
    for x in batches:
        s_d, m_d = t_diet.train_step(s_d, x)
        s_l, m_l = t_leg.train_step(s_l, x)
        np.testing.assert_allclose(
            float(m_d["loss"]), float(m_l["loss"]), rtol=0, atol=0
        )
    assert_tables_bitwise(s_d, s_l)


# ------------------------------------------------------------ bf16 exchange


def test_bf16_exchange_convergence_a2a(mesh):
    """bf16 wire on the zipf a2a workload: learns, and lands within a small
    epsilon of the fp32-exchange trajectory (the one deliberate numeric
    change of the diet)."""
    gen = SyntheticCriteo(batch_size=512, num_cat=4, num_dense=2,
                          vocab=2000, zipf_a=1.6, seed=13)
    batches = [shard_batch(mesh, J(gen.batch())) for _ in range(20)]

    t_bf = ShardedTrainer(small(), Adagrad(lr=0.2), optax.adam(5e-3),
                          mesh=mesh, comm="a2a")
    t_f32 = ShardedTrainer(
        retable(small(), exchange_dtype="float32"),
        Adagrad(lr=0.2), optax.adam(5e-3), mesh=mesh, comm="a2a",
    )
    assert next(iter(t_bf.bundles.values())).table.cfg.exchange_dtype == "bfloat16"
    s_bf, s_f = t_bf.init(0), t_f32.init(0)
    l_bf, l_f = [], []
    for b in batches:
        s_bf, m = t_bf.train_step(s_bf, b)
        l_bf.append(float(m["loss"]))
        s_f, m = t_f32.train_step(s_f, b)
        l_f.append(float(m["loss"]))
    # both learn
    assert np.mean(l_bf[-5:]) < np.mean(l_bf[:5])
    # and the bf16 tail tracks fp32 within epsilon
    gap = abs(np.mean(l_bf[-5:]) - np.mean(l_f[-5:]))
    assert gap < 0.02 * np.mean(l_f[-5:]), (l_bf[-5:], l_f[-5:])


def test_eval_exchange_stays_fp32(mesh):
    """The exchange_dtype knob must not touch eval: the same trained state
    evaluated under a bf16-exchange trainer and an fp32-exchange trainer
    produces bit-identical losses (both run the exact fp32 eval wire)."""
    gen = SyntheticCriteo(batch_size=256, num_cat=4, num_dense=2,
                          vocab=1500, seed=9)
    t_f32 = ShardedTrainer(
        retable(small(), exchange_dtype="float32"),
        Adagrad(lr=0.2), optax.adam(5e-3), mesh=mesh,
    )
    st = t_f32.init(0)
    for _ in range(6):
        st, _ = t_f32.train_step(st, shard_batch(mesh, J(gen.batch())))
    t_bf = ShardedTrainer(small(), Adagrad(lr=0.2), optax.adam(5e-3),
                          mesh=mesh)
    eval_b = [shard_batch(mesh, J(gen.batch())) for _ in range(2)]
    for b in eval_b:
        l_f, _ = t_f32.eval_step(st, b)
        l_b, _ = t_bf.eval_step(st, b)
        assert float(l_f) == float(l_b)


# ------------------------------------------------------- checkpoint compat


def test_columnar_checkpoint_restores_into_packed_meta(tmp_path):
    """The on-disk format stays columnar (freqs/versions arrays): an
    old-format rows dict — exactly what pre-diet checkpoints hold —
    restores into the packed-meta state unchanged, and a full manager
    round-trip preserves the fused metadata bit-for-bit."""
    from deeprec_tpu.embedding.table import EmbeddingTable
    from deeprec_tpu.training.checkpoint import (
        CheckpointManager, _state_to_np, export_table_arrays, import_rows,
    )

    cfg = TableConfig(name="t", dim=8, capacity=1 << 10)
    t = EmbeddingTable(cfg)
    opt = Adagrad(lr=0.1)
    s = ensure_slots(t, t.create(), opt)
    s, res = t.lookup_unique(s, jnp.arange(40, dtype=jnp.int32) * 7, step=3)
    s = apply_gradients(t, s, opt, res, jnp.ones_like(res.embeddings),
                        step=3)

    rows = export_table_arrays(t, _state_to_np(s), only_dirty=False)
    # the export is the legacy columnar layout — old checkpoints look
    # exactly like this
    assert {"keys", "values", "freqs", "versions"} <= set(rows)
    s2 = import_rows(t, ensure_slots(t, t.create(), opt), rows)
    by_key = {int(k): i for i, k in enumerate(np.asarray(s.keys))
              if int(k) != np.iinfo(np.int32).min}
    k2 = np.asarray(s2.keys)
    f1, v1 = np.asarray(s.freq), np.asarray(s.version)
    f2, v2 = np.asarray(s2.freq), np.asarray(s2.version)
    for slot2, key in enumerate(k2):
        if int(key) == np.iinfo(np.int32).min:
            continue
        slot1 = by_key[int(key)]
        assert f1[slot1] == f2[slot2] and v1[slot1] == v2[slot2]

    # full-manager round trip on a trainer: meta survives save+restore
    tr = Trainer(small(), Adagrad(lr=0.1))
    st = tr.init(0)
    gen = SyntheticCriteo(batch_size=64, num_cat=4, num_dense=2, vocab=300,
                          seed=1)
    for _ in range(3):
        st, _ = tr.train_step(st, J(gen.batch()))
    ck = CheckpointManager(str(tmp_path), tr)
    st_saved, _ = ck.save(st)
    rest = ck.restore()
    for f in ("C1", "C2", "C3", "C4"):
        a, b = tr.table_state(st, f), tr.table_state(rest, f)
        ka, kb = np.asarray(a.keys), np.asarray(b.keys)
        fa, fb = np.asarray(a.freq), np.asarray(b.freq)
        va, vb = np.asarray(a.version), np.asarray(b.version)
        ma = {int(k): (fa[i], va[i]) for i, k in enumerate(ka)
              if int(k) != np.iinfo(np.int32).min}
        mb = {int(k): (fb[i], vb[i]) for i, k in enumerate(kb)
              if int(k) != np.iinfo(np.int32).min}
        assert ma == mb
    # dirty cleared by the save on the RETURNED state
    for bname in st_saved.tables:
        assert int(np.sum(np.asarray(st_saved.tables[bname].dirty))) == 0


# ------------------------------------------------------ tooling satellites


def test_phase_profiler_report():
    from deeprec_tpu.training.profiler import PhaseProfiler

    prof = PhaseProfiler()
    x = jnp.ones((128, 128))
    f = jax.jit(lambda a: a @ a)
    for _ in range(2):
        prof.timed("matmul", f, x)
    with prof.phase("idle"):
        pass
    rep = prof.phase_report()
    assert rep["matmul"]["calls"] == 2
    assert rep["matmul"]["total_ms"] >= rep["matmul"]["min_ms"] > 0
    assert rep["idle"]["calls"] == 1


def test_traffic_op_model_matches_lowered_program():
    """In-suite drift gate (the CI smoke asserts the same through
    bench.py + roofline --assert-traffic): the traffic model's expected
    gather/scatter counts must equal what the hot path actually lowers
    to, on both arms and both dedup front-ends."""
    from deeprec_tpu.embedding.table import EmbeddingTable
    from deeprec_tpu.ops import dedup
    from deeprec_tpu.ops.traffic import (
        count_stablehlo_ops, expected_lookup_apply_ops,
    )

    t = EmbeddingTable(TableConfig(name="probe", dim=16, capacity=1 << 12))
    opt = Adagrad(lr=0.05)
    s = ensure_slots(t, t.create(), opt)
    ids = jnp.arange(256, dtype=jnp.int32)

    def prog(s, ids, diet, U):
        s, res = t._lookup_unique_impl(s, ids, jnp.int32(0), True, -1, U)
        g = jnp.ones_like(res.embeddings, jnp.float32)
        return apply_gradients(t, s, opt, res, g, step=0,
                               reuse_rows=diet, stamp_meta=not diet)

    for budgeted in (True, False):
        U = dedup.resolve_size(128, 256) if budgeted else None
        for diet in (True, False):
            txt = jax.jit(
                lambda s, ids, d=diet, u=U: prog(s, ids, d, u)
            ).lower(s, ids).as_text()
            got = count_stablehlo_ops(txt)
            want = expected_lookup_apply_ops(diet=diet, budgeted=budgeted,
                                             n_row_slots=1)
            assert got == want, (diet, budgeted, got, want)
    # the structural claim: the diet removes 4 scatters (3-scatter trio +
    # apply re-stamp pair -> 1 fused scatter) at an unchanged gather count
    d = expected_lookup_apply_ops(diet=True, budgeted=True)
    l = expected_lookup_apply_ops(diet=False, budgeted=True)
    assert l["scatter"] - d["scatter"] == 4 and l["gather"] == d["gather"]
