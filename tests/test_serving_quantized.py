"""Quantized serving-side row residency (train fp32, serve bf16/int8):
prediction epsilon vs fp32, residency bytes pinned against the
ops/traffic.py model, delta replay + prune stability at zero steady-state
compiles, and the modelzoo DSSM AUC floor at int8 serving."""
import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

from deeprec_tpu.data import SyntheticCriteo, SyntheticTwoTower
from deeprec_tpu.models import DSSM, WDL
from deeprec_tpu.optim import Adagrad
from deeprec_tpu.serving import Predictor
from deeprec_tpu.training import Trainer
from deeprec_tpu.training.checkpoint import CheckpointManager
from deeprec_tpu.training.metrics import AucState, auc_compute, auc_update


def J(b):
    return {k: jnp.asarray(v) for k, v in b.items()}


def strip_labels(b):
    return {k: np.asarray(v) for k, v in b.items() if not k.startswith("label")}


def make_trained(tmp_path, steps=4):
    model = WDL(emb_dim=8, capacity=1 << 12, hidden=(32, 16), num_cat=4,
                num_dense=2)
    tr = Trainer(model, Adagrad(lr=0.1), optax.adam(1e-3))
    st = tr.init(0)
    gen = SyntheticCriteo(batch_size=128, num_cat=4, num_dense=2, vocab=2000,
                          seed=7)
    for _ in range(steps):
        st, _ = tr.train_step(st, J(gen.batch()))
    ck = CheckpointManager(str(tmp_path), tr)
    st, _ = ck.save(st)
    return model, tr, st, ck, gen


def test_quantized_prediction_epsilon_and_residency(tmp_path):
    """int8 predictions stay within a tight epsilon of fp32 (per-row
    symmetric scale bounds the element error by max|row|/254), bf16
    within its mantissa epsilon — and the measured residency bytes match
    the traffic model exactly, with int8 at ~¼–⅜ of fp32."""
    model, tr, st, ck, gen = make_trained(tmp_path)
    req = strip_labels(gen.batch())

    p32 = Predictor(model, str(tmp_path))
    p8 = Predictor(model, str(tmp_path), quantize="int8")
    pb = Predictor(model, str(tmp_path), quantize="bf16")
    a = np.asarray(p32.predict(req))
    b = np.asarray(p8.predict(req))
    c = np.asarray(pb.predict(req))
    # probabilities: absolute epsilon is the meaningful bound
    assert np.abs(a - b).max() < 5e-3
    assert np.abs(a - c).max() < 2e-2
    # quantized tables really store int8 + per-row scale
    ts = p8._trainer.table_state(p8._state, model.features[0].table.name)
    assert ts.values.dtype == jnp.int8
    assert ts.qscale is not None and ts.qscale.dtype == jnp.float32

    ri32, ri8, rib = (p.residency_info() for p in (p32, p8, pb))
    for ri in (ri32, ri8, rib):
        assert ri["measured_bytes"] == ri["modeled_bytes"]
    assert ri32["measured_bytes"] == ri32["fp32_bytes"]
    # dim 8: int8 rows are 8B + 4B scale vs 32B fp32 -> 0.375x; the
    # contract is "at most ~half"
    assert ri8["measured_bytes"] <= 0.55 * ri32["measured_bytes"]
    assert rib["measured_bytes"] == 0.5 * ri32["measured_bytes"]


def test_quantized_delta_replay_zero_compiles(tmp_path):
    """Delta replay onto a quantized residency: quantize-on-import rides
    the same fixed-chunk import program (warm_replay compiled it at
    init), so the serving-cadence steady state compiles NOTHING — the
    PR 5 zero-retrace contract extended to the quantized path — and
    replayed predictions track the fp32 predictor within epsilon."""
    from deeprec_tpu.analysis.trace_guard import trace_guard

    model, tr, st, ck, gen = make_trained(tmp_path)
    req = strip_labels(gen.batch())
    p8 = Predictor(model, str(tmp_path), quantize="int8")
    v0 = p8.version
    shapes0 = jax.tree.map(
        lambda a: (a.shape, str(a.dtype)),
        p8._trainer.table_state(p8._state, model.features[0].table.name),
    )

    def land_delta():
        nonlocal st
        for _ in range(2):
            st, _ = tr.train_step(st, J(gen.batch()))
        s2, _ = ck.save_incremental(st)
        st = s2

    p8.predict(req)  # compile the predict bucket outside the guard
    land_delta()
    assert p8.poll_updates()  # first replay: warm already, but pad cache
    land_delta()
    with trace_guard(max_compiles=None) as g:
        assert p8.poll_updates()
        out = p8.predict(req)
    assert g.compiles == 0, "quantized delta replay must not retrace"
    assert p8.version == v0 + 2
    shapes1 = jax.tree.map(
        lambda a: (a.shape, str(a.dtype)),
        p8._trainer.table_state(p8._state, model.features[0].table.name),
    )
    assert shapes0 == shapes1  # residency bit-stable in shape/dtype
    expect = np.asarray(Predictor(model, str(tmp_path)).predict(req))
    assert np.abs(np.asarray(out) - expect).max() < 5e-3


def test_quantized_prune_rebuild_carries_scale(tmp_path):
    """The keep-mask rebuild (the delta-replay prune path) relocates the
    per-row scale with its row: surviving keys decode identically after
    a prune, dropped keys leave no stale scale behind."""
    from deeprec_tpu.training.checkpoint import _rebuild_keep_jit

    model, tr, st, ck, gen = make_trained(tmp_path)
    p8 = Predictor(model, str(tmp_path), quantize="int8")
    tname = model.features[0].table.name
    table = p8._trainer.tables[tname]
    ts = p8._trainer.table_state(p8._state, tname)
    keys = np.asarray(ts.keys)
    occ = keys != np.iinfo(keys.dtype).min
    live = keys[occ]
    assert live.size > 8
    drop = set(live[: live.size // 2].tolist())
    keep = np.array([k not in drop for k in keys], bool)

    ids = jnp.asarray(live[live.size // 2:][:8].reshape(-1, 1))
    before = np.asarray(table.lookup_readonly(ts, ids))
    fills = p8._trainer._slot_fills(
        next(b for b in p8._trainer.bundles.values()
             if any(f.name == tname for f in b.features)))
    pruned = _rebuild_keep_jit(table, ts, jnp.asarray(keep), fills)
    assert pruned.qscale is not None
    after = np.asarray(table.lookup_readonly(pruned, ids))
    np.testing.assert_array_equal(before, after)
    # dropped keys fell back to the (full-precision) initializer default
    gone = jnp.asarray(np.fromiter(drop, keys.dtype, count=4).reshape(-1, 1))
    got = np.asarray(table.lookup_readonly(pruned, gone))
    init = np.asarray(table._init_rows(jnp.asarray(
        np.fromiter(drop, keys.dtype, count=4))))
    np.testing.assert_allclose(got.reshape(4, -1), init, rtol=1e-6, atol=1e-6)


def test_int8_training_lookup_raises():
    """int8 residency is serving-only: a train-mode lookup fails loudly
    instead of silently truncating gradients into the int8 store."""
    import dataclasses

    from deeprec_tpu.embedding.table import EmbeddingTable

    cfg = dataclasses.replace(
        WDL(emb_dim=8, capacity=1 << 10, hidden=(16,), num_cat=1,
            num_dense=1).features[0].table,
        value_dtype="int8")
    table = EmbeddingTable(cfg)
    state = table.create()
    with pytest.raises(ValueError, match="serving-only"):
        table.lookup_unique(state, jnp.arange(8).reshape(-1, 1), train=True)


@pytest.mark.slow
def test_dssm_auc_floor_at_int8_serving(tmp_path):
    """Modelzoo DSSM served at int8 holds the fp32 AUC floor: ranking
    quality survives the quantized residency (the scale is per row, so
    relative order within a row's dot products is barely perturbed)."""
    model = DSSM(emb_dim=8, capacity=1 << 13, num_user_feats=2,
                 num_item_feats=2, hidden=(32, 16))
    tr = Trainer(model, Adagrad(lr=0.1), optax.adam(2e-3))
    st = tr.init(0)
    gen = SyntheticTwoTower(batch_size=256, num_user=2, num_item=2,
                            vocab=1000, seed=5)
    for _ in range(20):
        st, _ = tr.train_step(st, J(gen.batch()))
    CheckpointManager(str(tmp_path), tr).save(st)

    held = [gen.batch() for _ in range(4)]
    aucs = {}
    for q in ("fp32", "int8"):
        pred = Predictor(model, str(tmp_path), quantize=q)
        s = AucState.create()
        for b in held:
            probs = pred.predict(strip_labels(b))
            s = auc_update(s, jnp.asarray(np.asarray(probs)),
                           jnp.asarray(b["label"]))
        aucs[q] = float(auc_compute(s))
    # learn-bar: clearly off coin-flip in 20 budgeted steps; the CONTRACT
    # under test is the next line — int8 holds the fp32 floor
    assert aucs["fp32"] > 0.55, f"fp32 baseline failed to learn: {aucs}"
    assert aucs["int8"] >= aucs["fp32"] - 0.01, aucs
