"""Full-corpus retrieval engine (serving/retrieval.py + ops/topk.py):
blocked top-k exactness vs argsort, deterministic tie handling across
block sizes, k/corpus edge cases, int8 recall floors vs exact fp32
scan, delta-replay corpus folding (targeted + zero steady-state
compiles), and corpus growth."""
import numpy as np
import jax.numpy as jnp
import optax
import pytest

from deeprec_tpu.data import SyntheticTwoTower
from deeprec_tpu.models import DSSM
from deeprec_tpu.ops.topk import blocked_topk
from deeprec_tpu.optim import Adagrad
from deeprec_tpu.serving import Predictor, RetrievalEngine
from deeprec_tpu.serving.predictor import parse_features
from deeprec_tpu.serving.retrieval import (
    fill_missing_item_features,
    merge_shard_topk,
)
from deeprec_tpu.training import Trainer
from deeprec_tpu.training.checkpoint import CheckpointManager

VOCAB = 200


def J(b):
    return {k: jnp.asarray(v) for k, v in b.items()}


def exact_topk_np(scores, valid, k):
    """Reference: full argsort with the engine's tie order (score desc,
    row index asc); invalid rows lose, short corpora pad with -1."""
    s = np.where(valid[None, :], scores, -np.inf)
    rows = np.broadcast_to(np.arange(s.shape[1]), s.shape)
    order = np.lexsort((rows, -s), axis=-1)[:, :k]
    vals = np.take_along_axis(s, order, axis=1)
    idx = np.where(np.isfinite(vals), order, -1)
    pad = k - order.shape[1]
    if pad > 0:
        vals = np.concatenate(
            [vals, np.full((s.shape[0], pad), -np.inf)], axis=1)
        idx = np.concatenate(
            [idx, np.full((s.shape[0], pad), -1, idx.dtype)], axis=1)
    return vals, idx


@pytest.mark.parametrize("block", [8, 32, 128])
@pytest.mark.parametrize("k", [1, 5, 40])
def test_blocked_topk_matches_argsort(block, k):
    """Blocked streaming merge == full-scan argsort for every block
    size, including k > block (the merge buffer is k + block wide)."""
    rng = np.random.default_rng(0)
    C, H, B = 256, 16, 3
    corpus = rng.normal(size=(C, H)).astype(np.float32)
    valid = rng.random(C) < 0.9
    user = rng.normal(size=(B, H)).astype(np.float32)
    vals, rows = blocked_topk(
        jnp.asarray(user), jnp.asarray(corpus), jnp.asarray(valid), k,
        block_rows=block)
    ref_vals, ref_rows = exact_topk_np(user @ corpus.T, valid, k)
    np.testing.assert_array_equal(np.asarray(rows), ref_rows)
    np.testing.assert_allclose(np.asarray(vals)[ref_rows >= 0],
                               ref_vals[ref_rows >= 0], rtol=1e-5)


def test_tie_determinism_block_size_independent():
    """Duplicate corpus rows score EQUAL — the winner must be the lowest
    corpus row index, for every block size (the carry-precedes-block
    merge invariant)."""
    rng = np.random.default_rng(1)
    H = 8
    base = rng.normal(size=(4, H)).astype(np.float32)
    corpus = np.tile(base, (16, 1))  # 64 rows, every vector ×16
    valid = np.ones(64, bool)
    user = rng.normal(size=(2, H)).astype(np.float32)
    picks = []
    for block in (4, 16, 64):
        _, rows = blocked_topk(
            jnp.asarray(user), jnp.asarray(corpus), jnp.asarray(valid),
            8, block_rows=block)
        picks.append(np.asarray(rows))
    np.testing.assert_array_equal(picks[0], picks[1])
    np.testing.assert_array_equal(picks[0], picks[2])
    _, ref_rows = exact_topk_np(user @ corpus.T, valid, 8)
    np.testing.assert_array_equal(picks[0], ref_rows)


def test_topk_empty_and_overask_edges():
    """Zero valid rows -> all -1; k past the valid count pads with -1;
    an all-padding block never wins."""
    rng = np.random.default_rng(2)
    corpus = rng.normal(size=(16, 4)).astype(np.float32)
    user = rng.normal(size=(1, 4)).astype(np.float32)
    vals, rows = blocked_topk(
        jnp.asarray(user), jnp.asarray(corpus),
        jnp.zeros(16, bool), 5, block_rows=8)
    assert (np.asarray(rows) == -1).all()
    valid = np.zeros(16, bool)
    valid[:3] = True
    vals, rows = blocked_topk(
        jnp.asarray(user), jnp.asarray(corpus), jnp.asarray(valid), 5,
        block_rows=8)
    rows = np.asarray(rows)
    assert set(rows[0, :3]) == {0, 1, 2}
    assert (rows[0, 3:] == -1).all()


def test_merge_shard_topk_order_and_invalid():
    ids = [np.array([[5, 3, -1]], np.int64), np.array([[4, 9, 2]], np.int64)]
    scores = [np.array([[3.0, 1.0, -np.inf]], np.float32),
              np.array([[3.0, 2.0, 0.5]], np.float32)]
    out_i, out_v = merge_shard_topk(ids, scores, 4)
    # score desc, tie on 3.0 broken by id asc (4 < 5), -1 never chosen
    assert out_i[0].tolist() == [4, 5, 9, 3]
    np.testing.assert_allclose(out_v[0], [3.0, 3.0, 2.0, 1.0])


def make_stack(tmp_path, steps=8, quantize="int8", **eng_kw):
    model = DSSM(emb_dim=8, capacity=1 << 12, num_user_feats=2,
                 num_item_feats=2, hidden=(16, 8))
    tr = Trainer(model, Adagrad(lr=0.1), optax.adam(1e-3))
    st = tr.init(0)
    gen = SyntheticTwoTower(batch_size=256, num_user=2, num_item=2,
                            vocab=VOCAB, seed=3)
    for _ in range(steps):
        st, _ = tr.train_step(st, J(gen.batch()))
    ck = CheckpointManager(str(tmp_path), tr)
    st, _ = ck.save(st)
    pred = Predictor(model, str(tmp_path))
    eng = RetrievalEngine(pred, quantize=quantize, block_rows=256,
                          chunk=128, **eng_kw)
    return model, tr, st, ck, gen, pred, eng


def make_items(n, seed=0):
    rng = np.random.default_rng(seed)
    ids = np.arange(1, n + 1, dtype=np.int64)
    feats = {"V0": VOCAB + rng.integers(0, VOCAB, size=n),
             "V1": 2 * VOCAB + rng.integers(0, VOCAB, size=n)}
    return ids, feats


def user_batch(pred, gen, rows=4):
    b = gen.batch()
    user = {k: np.asarray(v)[:rows] for k, v in b.items()
            if k.startswith("U")}
    return parse_features(pred, fill_missing_item_features(pred, user))


def test_engine_recall_floor_vs_exact_fp32(tmp_path):
    """int8 blocked sweep vs exact fp32 full scan over the SAME item
    vectors: tie-aware recall@{10,100} floors (identical-vector items
    are interchangeable answers)."""
    _, _, _, _, gen, pred, eng8 = make_stack(tmp_path)
    eng32 = RetrievalEngine(pred, quantize="fp32", block_rows=256,
                            chunk=128)
    ids, feats = make_items(5000)
    eng8.upsert_items(ids, feats)
    eng32.upsert_items(ids, feats)
    batch = user_batch(pred, gen, rows=8)
    hids, hv = eng32.host_vectors()
    uvec = np.asarray(eng32._user_jit(pred._snap.state, J(batch)))[:8]
    exact = uvec @ hv.T
    res = eng8.retrieve(batch, 100)
    cols = np.searchsorted(hids, res.ids)
    got = np.take_along_axis(exact, np.clip(cols, 0, exact.shape[1] - 1),
                             axis=1)
    got = np.where(res.ids >= 0, got, -np.inf)
    for k in (10, 100):
        kth = -np.partition(-exact, k - 1, axis=1)[:, k - 1]
        recall = float((got[:, :k] >= kth[:, None] - 1e-6).mean())
        assert recall >= 0.95, (k, recall)
    # the fp32 engine against its own vectors is EXACT (tie order and all)
    res32 = eng32.retrieve(batch, 50)
    _, ref_rows = exact_topk_np(exact, np.ones(exact.shape[1], bool), 50)
    np.testing.assert_array_equal(res32.ids, hids[ref_rows])


def test_engine_empty_one_block_and_growth(tmp_path):
    """Empty corpus serves all -1 (never raises); a one-block corpus
    works; ingest past capacity grows by pow2 blocks and retrieval stays
    exact over the grown matrix."""
    _, _, _, _, gen, pred, eng = make_stack(tmp_path)
    batch = user_batch(pred, gen)
    res = eng.retrieve(batch, 5)
    assert (res.ids == -1).all() and res.scanned == 0
    ids, feats = make_items(10)
    eng.upsert_items(ids, feats)
    res = eng.retrieve(batch, 20)
    assert set(res.ids[0][res.ids[0] >= 0]) == set(ids.tolist())
    assert (res.ids[0] == -1).sum() == 10  # k past the corpus pads -1
    cap0 = eng.capacity
    ids2, feats2 = make_items(cap0 + 100, seed=7)
    eng.upsert_items(ids2, feats2)
    assert eng.capacity > cap0 and eng.capacity % eng.block_rows == 0
    assert eng.corpus_rows() == cap0 + 100
    res = eng.retrieve(batch, 10)
    assert (res.ids >= 0).all()
    # sweep accounting stays exact after growth
    si = eng.sweep_info()
    assert si["measured_bytes"] == si["modeled_bytes"]


def frozen_dense_trainer(model, tr, st, tmp_path):
    """The sparse-only online-update regime (embeddings train, towers
    frozen) — the regime where the targeted corpus fold is sound. Same
    checkpoint chain, fresh manager over the same dir."""
    import optax as _optax

    from deeprec_tpu.training.trainer import TrainState

    tr2 = Trainer(model, Adagrad(lr=0.1), _optax.set_to_zero())
    st2 = TrainState(step=st.step, tables=st.tables, dense=st.dense,
                     opt_state=tr2.dense_opt.init(st.dense))
    return tr2, st2, CheckpointManager(str(tmp_path), tr2)


def test_delta_fold_targets_changed_items_and_zero_compiles(tmp_path):
    """With the item tower frozen (sparse-only online updates), delta
    replay folds ONLY the corpus rows whose item keys the delta touched,
    inside the same poll round — and the steady-state fold + retrieve
    compiles NOTHING (trace-guard, the PR 5 contract on the retrieval
    lane)."""
    from deeprec_tpu.analysis.trace_guard import trace_guard

    model, tr0, st0, ck0, gen, pred, eng = make_stack(tmp_path)
    tr, st, ck = frozen_dense_trainer(model, tr0, st0, tmp_path)
    ids, feats = make_items(1000)
    # give items 0..9 reserved V0/V1 ids the bulk corpus never uses, so
    # a delta training ONLY those ids dirties exactly those ten rows
    res0, res1 = 2 * VOCAB - 1, 3 * VOCAB - 1
    feats = {k: v.copy() for k, v in feats.items()}
    feats["V0"][10:] = VOCAB + (feats["V0"][10:] % (VOCAB - 1))
    feats["V1"][10:] = 2 * VOCAB + (feats["V1"][10:] % (VOCAB - 1))
    feats["V0"][:10] = res0
    feats["V1"][:10] = res1
    eng.upsert_items(ids, feats)
    batch = user_batch(pred, gen)

    def land_delta(targeted):
        nonlocal st
        for _ in range(2):
            b = gen.batch()
            if targeted:
                b["V0"] = np.full_like(b["V0"], res0)
                b["V1"] = np.full_like(b["V1"], res1)
            st2, _ = tr.train_step(st, J(b))
            st = st2
        st2, _ = ck.save_incremental(st)
        st = st2

    before = np.asarray(eng._corpus.vecs).copy()
    land_delta(targeted=True)
    assert pred.poll_updates()
    assert eng.last_fold is not None
    assert eng.last_fold["rows"] == 10, eng.last_fold
    changed = np.nonzero(
        (np.asarray(eng._corpus.vecs) != before).any(axis=1))[0]
    assert set(changed.tolist()) <= set(range(10))
    # steady state: second targeted delta + retrieve under the guard
    eng.retrieve(batch, 10)
    land_delta(targeted=True)
    with trace_guard(max_compiles=None) as g:
        assert pred.poll_updates()
        res = eng.retrieve(batch, 10)
    assert g.compiles == 0, "corpus fold retraced in steady state"
    assert res.version == pred.version
    # fold parity: the folded rows decode exactly what a fresh encode of
    # the same rows produces (same program, same state)
    eng2 = RetrievalEngine(pred, quantize="int8", block_rows=256,
                           chunk=128)
    eng2.upsert_items(ids, feats)
    np.testing.assert_array_equal(np.asarray(eng._corpus.vecs)[:1000],
                                  np.asarray(eng2._corpus.vecs)[:1000])


def test_full_reload_refreshes_whole_corpus(tmp_path):
    """A full checkpoint reload marks every resident row dirty (any
    vector may have moved)."""
    model, tr, st, ck, gen, pred, eng = make_stack(tmp_path)
    ids, feats = make_items(500)
    eng.upsert_items(ids, feats)
    for _ in range(2):
        st, _ = tr.train_step(st, J(gen.batch()))
    st, _ = ck.save(st)
    assert pred.poll_updates()
    assert eng.last_fold["full"] and eng.last_fold["rows"] == 500


def test_dense_tower_drift_escalates_fold_to_full(tmp_path):
    """A delta that moved the item tower's DENSE params invalidates
    every resident vector — the fold must escalate to a full re-encode
    (key-targeted folding would serve stale vectors for every untouched
    item), and the refreshed corpus must match a fresh engine's encode
    of the post-delta state bit-for-bit."""
    model, tr, st, ck, gen, pred, eng = make_stack(tmp_path)
    ids, feats = make_items(300)
    eng.upsert_items(ids, feats)
    for _ in range(2):  # adam trainer: dense moves every step
        st, _ = tr.train_step(st, J(gen.batch()))
    st, _ = ck.save_incremental(st)
    assert pred.poll_updates()
    assert eng.last_fold["dense_drift"] and eng.last_fold["full"]
    assert eng.last_fold["rows"] == 300
    eng2 = RetrievalEngine(pred, quantize="int8", block_rows=256,
                           chunk=128)
    eng2.upsert_items(ids, feats)
    np.testing.assert_array_equal(np.asarray(eng._corpus.vecs)[:300],
                                  np.asarray(eng2._corpus.vecs)[:300])


def test_upsert_updates_existing_and_shards_partition(tmp_path):
    """Re-ingesting an id keeps its row (and re-encodes it with the new
    features); sharded engines keep disjoint, exhaustive subsets."""
    _, _, _, _, gen, pred, eng = make_stack(tmp_path)
    ids, feats = make_items(100)
    assert eng.upsert_items(ids, feats) == 100
    rows0 = eng.corpus_rows()
    feats2 = {k: v.copy() for k, v in feats.items()}
    feats2["V0"][:] = VOCAB + 1
    assert eng.upsert_items(ids[:10], {k: v[:10] for k, v in feats2.items()}) == 10
    assert eng.corpus_rows() == rows0  # updated in place, no new rows
    shards = [RetrievalEngine(pred, quantize="fp32", block_rows=256,
                              chunk=128, shard_index=i, num_shards=2)
              for i in range(2)]
    counts = [s.upsert_items(ids, feats) for s in shards]
    assert sum(counts) == 100 and all(c > 0 for c in counts)
    all_ids = np.concatenate([s.host_vectors()[0] for s in shards])
    assert sorted(all_ids.tolist()) == ids.tolist()


def test_retrieval_server_coalesces_and_accounts(tmp_path):
    """Concurrent requests through the RetrievalServer share sweeps and
    land in the stats plane: retrieval stage histogram + candidates
    counter + corpus gauges."""
    import threading

    from deeprec_tpu.serving import ModelServer

    _, _, _, _, gen, pred, eng = make_stack(tmp_path)
    ids, feats = make_items(800)
    eng.upsert_items(ids, feats)
    ms = ModelServer(pred, max_batch=64, max_wait_ms=1.0)
    rs = ms.attach_retrieval(eng)
    batch = user_batch(pred, gen, rows=2)
    rs.engine.warmup(batch, k=8)
    outs = [None] * 6

    def call(i):
        outs[i] = ms.retrieve_versioned(batch, 8)

    threads = [threading.Thread(target=call, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert all(o is not None and o.ids.shape == (2, 8) for o in outs)
    snap = ms.stats_snapshot()
    assert snap["retrieval"]["requests"] == 6
    assert snap["retrieval"]["candidates_scanned"] > 0
    assert snap["stages"]["retrieval"]["count"] == 6
    assert snap["retrieval_corpus"]["corpus_rows"] == 800
    assert (snap["retrieval_corpus"]["measured_bytes"]
            == snap["retrieval_corpus"]["modeled_bytes"])
    if ms.stats.registry is not None:
        text = ms.metrics_text()
        assert "deeprec_retrieval_corpus_rows" in text
        assert "deeprec_retrieval_candidates_scanned" in text
    ms.close()


def test_non_two_tower_model_raises(tmp_path):
    import jax.numpy as jnp  # noqa: F401

    from deeprec_tpu.data import SyntheticCriteo
    from deeprec_tpu.models import WDL

    model = WDL(emb_dim=8, capacity=1 << 10, hidden=(16,), num_cat=2,
                num_dense=2)
    tr = Trainer(model, Adagrad(lr=0.1), optax.adam(1e-3))
    st = tr.init(0)
    gen = SyntheticCriteo(batch_size=64, num_cat=2, num_dense=2,
                          vocab=500, seed=1)
    st, _ = tr.train_step(st, J(gen.batch()))
    CheckpointManager(str(tmp_path), tr).save(st)
    pred = Predictor(model, str(tmp_path))
    with pytest.raises(ValueError, match="two-tower"):
        RetrievalEngine(pred)
