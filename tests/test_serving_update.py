"""Zero-stall serving update discipline (PR 5).

Pins the read-mostly/copy-on-update contract:
  * torn-read: a predict racing poll_updates() is served entirely from
    the old or entirely from the new snapshot — never a mix — proven by
    EVENT ORDERING through the predictor's pre-swap seam, not wall-clock
    (the PR4 gated-seam style);
  * shadow replay (restore_into, fixed-chunk imports) is bit-identical
    on table ints to the legacy whole-delta in-place-style replay;
  * the live snapshot is never touched while the next one is built;
  * parse_features' vectorized ragged padding matches the old per-row
    Python loop on ragged / over-long / scalar-bag inputs;
  * HTTP robustness: oversized and malformed bodies get structured 400s;
  * /v1/stats serves live per-stage histograms;
  * ServerGroup pins one member per distinct device and degrades to a
    single member on a single-device host (shared-queue dispatcher).
"""
import json
import threading
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from deeprec_tpu.data import SyntheticCriteo
from deeprec_tpu.models import WDL
from deeprec_tpu.optim import Adagrad
from deeprec_tpu.serving import HttpServer, ModelServer, Predictor, ServerGroup
from deeprec_tpu.training import Trainer
from deeprec_tpu.training.checkpoint import (
    CheckpointManager,
    _state_to_np,
)


def J(b):
    return {k: jnp.asarray(v) for k, v in b.items()}


def strip_labels(b):
    return {k: np.asarray(v) for k, v in b.items() if not k.startswith("label")}


def make_trained(tmp_path, steps=5):
    model = WDL(emb_dim=8, capacity=1 << 12, hidden=(32,), num_cat=4,
                num_dense=2)
    tr = Trainer(model, Adagrad(lr=0.1), optax.adam(1e-3))
    st = tr.init(0)
    gen = SyntheticCriteo(batch_size=128, num_cat=4, num_dense=2, vocab=800,
                          seed=33)
    batches = [J(gen.batch()) for _ in range(steps)]
    for b in batches:
        st, _ = tr.train_step(st, b)
    ck = CheckpointManager(str(tmp_path), tr)
    st, _ = ck.save(st)
    return model, tr, st, ck, batches


def advance_delta(tr, st, ck, batches, n=3):
    for _ in range(n):
        st, _ = tr.train_step(st, batches[0])
    st, _ = ck.save_incremental(st)
    return st


# --------------------------------------------------------------- torn read


def test_torn_read_predict_never_mixes_versions(tmp_path):
    """Gate the snapshot swap on an event: predicts issued while the next
    state is FULLY BUILT but unpublished must serve the old version
    end-to-end; predicts after the swap serve the new one. Ordering is
    enforced by events, not sleeps."""
    model, tr, st, ck, batches = make_trained(tmp_path)
    p = Predictor(model, str(tmp_path))
    req = strip_labels(batches[0])
    old_probs, v0 = p.predict_versioned(req)

    st = advance_delta(tr, st, ck, batches)
    _, expect_new = tr.eval_step(st, batches[0])

    built = threading.Event()
    release = threading.Event()

    def gate():
        built.set()
        assert release.wait(timeout=60)

    p._pre_swap = gate
    poll_result = {}

    def updater():
        poll_result["changed"] = p.poll_updates()

    th = threading.Thread(target=updater)
    th.start()
    assert built.wait(timeout=60)
    # The next state exists and is warmed; the live snapshot must still be
    # the OLD one, and a predict must be old-version in BOTH fields.
    mid_probs, v_mid = p.predict_versioned(req)
    assert v_mid == v0
    np.testing.assert_array_equal(np.asarray(mid_probs),
                                  np.asarray(old_probs))
    assert p.model_info()["model_version"] == v0
    release.set()
    th.join(timeout=60)
    assert poll_result["changed"] is True

    new_probs, v1 = p.predict_versioned(req)
    assert v1 == v0 + 1
    np.testing.assert_allclose(np.asarray(new_probs),
                               np.asarray(expect_new), atol=1e-6)
    # the OLD snapshot's arrays were never invalidated by the update
    # (no donation, no in-place writes): predicts against the retained
    # reference still reproduce the old answers exactly
    assert np.abs(np.asarray(new_probs) - np.asarray(old_probs)).max() > 1e-6


def test_torn_read_through_model_server_stamped_versions(tmp_path):
    """Same contract through the coalescing front: requests racing a gated
    update each carry ONE stamped version, and every pre-swap answer is
    the old model's bit-for-bit."""
    model, tr, st, ck, batches = make_trained(tmp_path)
    server = ModelServer(Predictor(model, str(tmp_path)), max_batch=64,
                         max_wait_ms=2)
    p = server.predictor
    req = strip_labels(batches[0])
    single = {k: v[:4] for k, v in req.items()}
    old_out, v0 = server.request_versioned(single)

    st = advance_delta(tr, st, ck, batches)
    built = threading.Event()
    release = threading.Event()
    p._pre_swap = lambda: (built.set(), release.wait(timeout=60)) and None

    th = threading.Thread(target=p.poll_updates)
    th.start()
    try:
        assert built.wait(timeout=60)
        outs = [None] * 6
        errs = []

        def client(i):
            try:
                outs[i] = server.request_versioned(single)
            except Exception as e:
                errs.append(e)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(len(outs))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errs, errs
        for out, v in outs:
            assert v == v0  # swap is gated: every answer is old-version
            np.testing.assert_array_equal(np.asarray(out),
                                          np.asarray(old_out))
    finally:
        release.set()
        th.join(timeout=60)
    new_out, v1 = server.request_versioned(single)
    assert v1 == v0 + 1
    assert np.abs(np.asarray(new_out) - np.asarray(old_out)).max() > 1e-6
    server.close()


# ------------------------------------------------- shadow replay parity


def table_ints(ck, state):
    """Occupied-row content of every table, sorted by (member, key):
    slot ASSIGNMENT may legally differ between import orders (probe claim
    races), table CONTENT may not — so compare the key→row mapping, with
    float payloads viewed as raw bytes for bit-exactness."""
    from deeprec_tpu.embedding.table import empty_key

    out = {}
    for bname, b in ck.trainer.bundles.items():
        nps = _state_to_np(state.tables[bname])
        C = nps["keys"].shape[-1]
        keys = nps["keys"].reshape(-1)
        member = np.repeat(np.arange(keys.shape[0] // C), C)
        vals = nps["values"].reshape(keys.shape[0], -1)
        freq = nps["freq"].reshape(-1)
        ver = nps["version"].reshape(-1)
        occ = keys != empty_key(b.table.cfg)
        order = np.lexsort((keys[occ], member[occ]))
        out[bname] = {
            "keys": keys[occ][order],
            "member": member[occ][order],
            "value_bits": np.ascontiguousarray(
                vals[occ][order]).view(np.uint8),
            "freq": freq[occ][order],
            "version": ver[occ][order],
        }
    return out


FIELDS = ("keys", "member", "value_bits", "freq", "version")


def test_shadow_chunked_replay_bit_identical_to_legacy(tmp_path):
    """restore_into with a fixed chunk == the legacy one-shot import,
    bit-identical on table ints; and the input (live) state is untouched
    by every replay (the functional contract the atomic swap rests on)."""
    import os

    model, tr, st, ck, batches = make_trained(tmp_path)
    p = Predictor(model, str(tmp_path))  # restores with default chunk
    live = p._state
    req = strip_labels(batches[0])
    old_probs = np.asarray(p.predict(req))

    st = advance_delta(tr, st, ck, batches)
    incr = sorted(d for d in p._dirs() if d.startswith("incr-"))
    assert incr, "expected an incremental checkpoint"
    path = os.path.join(str(tmp_path), incr[-1])
    legacy = ck._apply_ckpt(live, path, load_dense=True)  # one-shot import
    b_ints = table_ints(ck, legacy)
    for chunk in (64, 4096):
        shadow = ck.restore_into(live, path, chunk=chunk)
        a_ints = table_ints(ck, shadow)
        for bname in a_ints:
            for field in FIELDS:
                np.testing.assert_array_equal(
                    a_ints[bname][field], b_ints[bname][field],
                    err_msg=f"{bname}/{field} chunk={chunk}")
        assert int(shadow.step) == int(st.step)
    # live snapshot untouched: the predictor still serves the OLD answers
    np.testing.assert_array_equal(np.asarray(p.predict(req)), old_probs)
    assert p.step == 5


def test_full_restore_chunked_matches_unchunked(tmp_path):
    """Full restore through the fixed-chunk path serves the same model as
    the exact-shape restore (Predictor init parity across chunk sizes)."""
    model, tr, st, ck, batches = make_trained(tmp_path)
    exact = ck.restore()
    chunked = ck.restore(chunk=128)
    a_ints, b_ints = table_ints(ck, chunked), table_ints(ck, exact)
    for bname in a_ints:
        for field in FIELDS:
            np.testing.assert_array_equal(
                a_ints[bname][field], b_ints[bname][field],
                err_msg=f"{bname}/{field}")


# ---------------------------------------------- parse_features vectorized


def _legacy_ragged_pad(v, L, pad_value, want):
    """The pre-PR5 per-row Python implementation, kept as the parity
    oracle for the vectorized pad_ragged."""
    rows = [(r + [pad_value] * (L - len(r)))[:L] for r in v]
    return np.asarray(rows, want)


def test_parse_features_vectorized_parity(tmp_path):
    from deeprec_tpu.serving.predictor import pad_ragged

    rng = np.random.default_rng(0)
    L, pad_value = 6, -1
    cases = {
        "ragged": [[7, 8, 9], [10], [], [1, 2, 3, 4, 5]],
        "over_long": [list(range(12)), list(range(9)), [3]],
        "exact": [[1, 2, 3, 4, 5, 6], [9, 9, 9, 9, 9, 9]],
        "random": [list(map(int, rng.integers(0, 100, rng.integers(0, 11))))
                   for _ in range(64)],
    }
    for name, v in cases.items():
        for want in (np.dtype(np.int64), np.dtype(np.int32)):
            got = pad_ragged(v, L, pad_value, want)
            ref = _legacy_ragged_pad(v, L, pad_value, want)
            np.testing.assert_array_equal(got, ref, err_msg=name)
            assert got.dtype == ref.dtype

    # end-to-end through parse_features on a real model: ragged, over-long
    # and scalar-bag forms all coerce identically to the legacy rules
    from deeprec_tpu.data import SyntheticBehaviorSequence
    from deeprec_tpu.models import DIN

    model = DIN(emb_dim=4, capacity=1 << 10, hidden=(8,))
    tr = Trainer(model, Adagrad(lr=0.1), optax.adam(1e-3))
    st = tr.init(0)
    gen = SyntheticBehaviorSequence(batch_size=16, vocab=100, seq_len=6,
                                    seed=1)
    st, _ = tr.train_step(st, J(gen.batch()))
    ck = CheckpointManager(str(tmp_path), tr)
    ck.save(st)
    p = Predictor(model, str(tmp_path))
    from deeprec_tpu.serving.predictor import parse_features

    seq_feats = [f for f in tr.sparse_specs if f.max_len]
    assert seq_feats
    feats = {
        "user": [1, 2, 3],
        "target_item": [3, 4, 5],
        "target_cat": [5, 6, 7],
        "hist_items": [[7, 8, 9], list(range(20)), []],   # ragged+overlong
        "hist_cats": [[1], [2, 3], [4, 5, 6, 7, 8, 9, 10]],
    }
    batch = parse_features(p, feats)
    for f in seq_feats:
        L = f.max_len
        ref = _legacy_ragged_pad(feats[f.name], L, f.pad_value,
                                 p.feature_dtypes[f.name])
        np.testing.assert_array_equal(batch[f.name], ref)
    # scalar bags still widen to [B, 1] then pad
    scalar = dict(feats)
    scalar["hist_items"] = [7, 8, 9]
    b2 = parse_features(p, scalar)
    assert b2["hist_items"].shape == (3, seq_feats[0].max_len)
    # garbage inside a bag is a BadRequest, not a crash
    from deeprec_tpu.serving.predictor import BadRequest

    bad = dict(feats)
    bad["hist_items"] = [["x", "y"], [1]]
    with pytest.raises(BadRequest):
        parse_features(p, bad)


# ------------------------------------------------------- HTTP robustness


def test_http_body_cap_and_malformed_json(tmp_path):
    model, tr, st, ck, batches = make_trained(tmp_path)
    server = ModelServer(Predictor(model, str(tmp_path)), max_batch=32,
                         max_wait_ms=1)
    http = HttpServer(server, port=0, max_body_bytes=4096).start()
    base = f"http://127.0.0.1:{http.port}"
    feats = {k: np.asarray(v)[:2].tolist()
             for k, v in strip_labels(batches[0]).items()}

    def post(body, headers=None):
        req = urllib.request.Request(
            base + "/v1/predict", data=body,
            headers=headers or {"Content-Type": "application/json"},
            method="POST")
        return urllib.request.urlopen(req, timeout=30)

    try:
        # oversized body: structured 400 with the limit, not a 500/OOM
        big = json.dumps(
            {"features": {k: v * 500 for k, v in feats.items()}}
        ).encode()
        assert len(big) > 4096
        with pytest.raises(urllib.error.HTTPError) as ei:
            post(big)
        assert ei.value.code == 400
        err = json.loads(ei.value.read())
        assert err["error"] == "request body too large"
        assert err["limit_bytes"] == 4096
        assert err["content_length"] == len(big)

        # malformed JSON: structured 400
        with pytest.raises(urllib.error.HTTPError) as ei:
            post(b'{"features": {oops')
        assert ei.value.code == 400
        assert "bad json" in json.loads(ei.value.read())["error"]

        # and the server still serves fine afterwards, version-stamped
        out = json.loads(post(
            json.dumps({"features": feats}).encode()).read())
        assert len(out["predictions"]) == 2
        assert out["model_version"] == server.predictor.version
    finally:
        http.stop()
        server.close()


def test_http_stats_endpoint_live(tmp_path):
    model, tr, st, ck, batches = make_trained(tmp_path)
    server = ModelServer(Predictor(model, str(tmp_path)), max_batch=32,
                         max_wait_ms=1)
    http = HttpServer(server, port=0).start()
    base = f"http://127.0.0.1:{http.port}"
    feats = {k: np.asarray(v)[:4].tolist()
             for k, v in strip_labels(batches[0]).items()}

    def call(path, payload=None):
        req = urllib.request.Request(
            base + path,
            data=None if payload is None else json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
            method="GET" if payload is None else "POST")
        with urllib.request.urlopen(req, timeout=30) as r:
            return json.loads(r.read())

    try:
        for _ in range(3):
            call("/v1/predict", {"features": feats})
        stats = call("/v1/stats")
        assert stats["requests"] == 3 and stats["rows"] == 12
        assert stats["batches"] >= 1 and stats["errors"] == 0
        for stage in ("queue", "pad", "device", "post", "e2e"):
            s = stats["stages"][stage]
            assert s["count"] >= 3, stage
            assert s["max_ms"] >= 0.0 and s["p99_ms"] >= s["p50_ms"] >= 0.0
        assert stats["model"]["version"] == server.predictor.version
        assert stats["model"]["step"] == 5

        # a delta update shows up in the update counters + version bump
        advance_delta(tr, st, ck, batches)
        assert call("/v1/reload", {})["updated"] is True
        stats2 = call("/v1/stats")
        assert stats2["model"]["updates"] == 1
        assert stats2["model"]["version"] == stats["model"]["version"] + 1
        assert stats2["model"]["last_update_ms"] > 0
        # the named-model route serves the same body shape
        named = call("/v1/models/default/stats")
        assert named["model"]["version"] == stats2["model"]["version"]
    finally:
        http.stop()
        server.close()


# ----------------------------------------------------- group dispatcher


def test_server_group_shared_queue_and_device_pinning(tmp_path):
    model, tr, st, ck, batches = make_trained(tmp_path)
    assert len(jax.local_devices()) >= 2
    group = ServerGroup(model, str(tmp_path), replicas=3, max_wait_ms=1.0)
    try:
        # one member per DISTINCT device, all draining one shared queue
        assert len(group.members) == 3
        qs = {id(m._q) for m in group.members}
        assert qs == {id(group._q)}
        devs = [
            next(iter(jax.tree.leaves(m.predictor._state))).devices().pop()
            for m in group.members
        ]
        assert len(set(devs)) == 3
        req = strip_labels(batches[0])
        expect = np.asarray(Predictor(model, str(tmp_path)).predict(req))
        outs = [None] * 8
        errs = []

        def client(i):
            try:
                sl = {k: v[i * 4: i * 4 + 4] for k, v in req.items()}
                outs[i] = np.asarray(group.request(sl))
            except Exception as e:
                errs.append(e)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errs, errs
        np.testing.assert_allclose(np.concatenate(outs), expect[:32],
                                   rtol=2e-5, atol=2e-5)
        snap = group.stats_snapshot()
        assert snap["replicas"] == 3 and snap["requests"] == 8
    finally:
        group.close()


def test_server_group_degrades_to_single_member_on_one_device(
        tmp_path, monkeypatch):
    """The negative-scaling fix: requested replicas cap at the device
    count — N members thrashing one backend is replaced by one member
    batching for it."""
    model, tr, st, ck, batches = make_trained(tmp_path)
    one = jax.local_devices()[:1]
    monkeypatch.setattr(jax, "local_devices", lambda *a, **k: one)
    group = ServerGroup(model, str(tmp_path), replicas=4, max_wait_ms=1.0)
    try:
        assert len(group.members) == 1
        assert group.predictor.model_info()["replicas"] == 1
        req = strip_labels(batches[0])
        out = np.asarray(group.request({k: v[:4] for k, v in req.items()}))
        assert out.shape == (4,)
    finally:
        group.close()


def test_batches_never_overflow_bucket_ladder(tmp_path):
    """A request that would push the forming batch past max_batch ROWS is
    carried to the NEXT batch instead of producing an off-ladder shape
    (off-ladder totals trace fresh XLA programs under live traffic)."""
    model, tr, st, ck, batches = make_trained(tmp_path)
    server = ModelServer(Predictor(model, str(tmp_path)), max_batch=8,
                         max_wait_ms=5.0)
    req = strip_labels(batches[0])
    five = {k: v[:5] for k, v in req.items()}
    outs = [None] * 10
    errs = []

    def client(i):
        try:
            outs[i] = np.asarray(server.request(five))
        except Exception as e:
            errs.append(e)

    threads = [threading.Thread(target=client, args=(i,)) for i in range(10)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    try:
        assert not errs, errs
        assert all(o is not None and o.shape == (5,) for o in outs)
        snap = server.stats.snapshot()
        assert snap["requests"] == 10
        # no batch ever exceeded max_batch rows (5+5 > 8 -> one per batch)
        assert snap["batch_rows"]["max"] <= 8
    finally:
        server.close()


# ----------------------------------------------------- adaptive batching


def test_adaptive_wait_policy(tmp_path):
    """Deadline tuning is pure arithmetic over the EWMA estimate — pin the
    policy, not wall-clock: full buckets never wait, sparse traffic never
    waits, dense traffic waits only long enough to fill the bucket,
    capped by max_wait."""
    from deeprec_tpu.serving.predictor import _ArrivalEWMA

    model, tr, st, ck, batches = make_trained(tmp_path)
    server = ModelServer(Predictor(model, str(tmp_path)), max_batch=64,
                         max_wait_ms=2.0)
    try:
        # no history yet: fixed behavior
        assert server._pick_wait(8) == server.max_wait
        # full bucket: dispatch now
        assert server._pick_wait(64) == 0.0
        # sparse traffic (inter-arrival many windows out): dispatch now
        server._arrivals._tau, server._arrivals._rows = 0.5, 8.0
        assert server._pick_wait(8) == 0.0
        # bursty-but-live traffic (a few windows): wait the cap — closed-
        # loop bursts must still coalesce
        server._arrivals._tau = 2.5 * server.max_wait
        assert server._pick_wait(8) == server.max_wait
        # dense traffic: wait ≈ tau * requests-needed, under the cap
        server._arrivals._tau = 50e-6
        want = 50e-6 * (64 - 8) / 8.0
        assert abs(server._pick_wait(8) - want) < 1e-9
        # ...and the cap binds when the bucket is far from full
        server._arrivals._rows = 1.0
        assert server._pick_wait(1) == server.max_wait
        # fixed mode ignores the estimator entirely
        server.adaptive = False
        assert server._pick_wait(8) == server.max_wait

        ew = _ArrivalEWMA()
        ew.note(0.0, 4)
        assert ew.estimate() == (None, 4.0)  # one arrival: no interval yet
        ew.note(0.010, 4)
        tau, rows = ew.estimate()
        assert tau == pytest.approx(0.010) and rows == 4.0
    finally:
        server.close()


# ------------------------------------------------------------ trace guard


def test_poll_updates_no_evict_delta_replay_is_trace_free(tmp_path):
    """The PR 5 _prune_to_live incident, pinned forever as a hard compile
    budget (analysis/trace_guard.py): replaying a no-evict delta through
    poll_updates — next to hypothetical live traffic — must be pure
    cache-hit dispatch. warm_replay() precompiled the chunked-import and
    prune programs at Predictor init; anything compiling inside this
    region is a GIL-held XLA trace on the serving update path, the exact
    class that produced 45–115 ms request stalls per delta."""
    from deeprec_tpu.analysis import trace_guard

    model, tr, st, ck, batches = make_trained(tmp_path)
    p = Predictor(model, str(tmp_path))
    req = strip_labels(batches[0])
    p.predict(req)  # warm the predict path for the shape being served
    # Prime one replay round: one-time host->device transfer machinery
    # and the warm pass against the shape above land here, not in the
    # guarded round.
    st = advance_delta(tr, st, ck, batches)
    assert p.poll_updates() is True
    st = advance_delta(tr, st, ck, batches)
    with trace_guard(max_compiles=0, note="no-evict delta replay") as g:
        assert p.poll_updates() is True
        p.predict(req)  # serving from the swapped state: still cache-hit
    assert g.compiles == 0
    assert p.version >= 2
