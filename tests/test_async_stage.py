"""Async embedding stage: stale-by-one semantics + training health.

Reference parity: async_embedding_stage.py / config.proto:328
do_async_embedding — the model consumes embeddings one step stale and
sparse grads apply one step late; training still converges.
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax

from deeprec_tpu.data import SyntheticCriteo
from deeprec_tpu.models import WDL
from deeprec_tpu.optim import Adagrad
from deeprec_tpu.parallel import AsyncShardedTrainer, ShardedTrainer, make_mesh, shard_batch


def _setup(comm="allgather", lr=0.2):
    mesh = make_mesh(8)
    model = WDL(emb_dim=4, capacity=1 << 10, hidden=(16,), num_cat=3,
                num_dense=2)
    tr = AsyncShardedTrainer(model, Adagrad(lr=lr), optax.adam(5e-3),
                             mesh=mesh, comm=comm)
    gen = SyntheticCriteo(batch_size=256, num_cat=3, num_dense=2,
                          vocab=800, seed=0)
    batches = [
        shard_batch(mesh, {k: jnp.asarray(v) for k, v in gen.batch().items()})
        for _ in range(8)
    ]
    return mesh, model, tr, batches


def test_async_step_is_stale_by_one():
    """With lr=0 everywhere (no updates), the loss reported by async step t
    must equal the SYNC eval loss of batch t-1 — i.e. the dense compute
    really consumes the previous batch's embeddings."""
    mesh, model, tr, batches = _setup(lr=0.0)
    zero_dense = optax.sgd(0.0)
    tr_async = AsyncShardedTrainer(model, Adagrad(lr=0.0), zero_dense,
                                   mesh=mesh)
    tr_sync = ShardedTrainer(model, Adagrad(lr=0.0), zero_dense, mesh=mesh)
    st = tr_async.init(0)
    ast = tr_async.bootstrap(st, batches[0])
    for t in range(1, 4):
        ast, mets = tr_async.train_step_async(ast, batches[t])
        # sync eval of batch t-1 against equivalent (lr=0) tables
        st_sync = tr_sync.init(0)
        for b in batches[:t]:  # populate the same keys (initializer values)
            st_sync, _ = tr_sync.train_step(st_sync, b)
        loss_ref, _ = tr_sync.eval_step(st_sync, batches[t - 1])
        np.testing.assert_allclose(
            float(mets["loss"]), float(loss_ref), rtol=2e-5
        )


def test_async_training_converges():
    mesh, model, tr, batches = _setup()
    st = tr.init(0)
    ast = tr.bootstrap(st, batches[0])
    losses = []
    for t in range(1, 40):
        ast, mets = tr.train_step_async(ast, batches[t % len(batches)])
        losses.append(float(mets["loss"]))
    assert np.isfinite(losses).all()
    # learning signal: the tail is clearly below the head
    assert np.mean(losses[-8:]) < np.mean(losses[:8]) - 0.01, (
        np.mean(losses[:8]), np.mean(losses[-8:])
    )


def test_async_a2a_path():
    mesh, model, tr, batches = _setup(comm="a2a")
    st = tr.init(0)
    ast = tr.bootstrap(st, batches[0])
    for t in range(1, 6):
        ast, mets = tr.train_step_async(ast, batches[t % len(batches)])
        assert np.isfinite(float(mets["loss"]))
