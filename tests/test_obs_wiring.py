"""obs plane wired through the serving stack: Prometheus /metrics on a
backend server and merged across the socket tier (killed backend →
stale-marked series, never silent disappearance), one trace id from the
HTTP edge over the TCP frames into the backend stage spans, the unified
health schema on every surface, the train-to-serve lag gauge, and the
dedup/supervisor gauges on the process-wide registry."""
import json
import urllib.request

import jax.numpy as jnp
import numpy as np
import optax
import pytest

from deeprec_tpu.data import SyntheticCriteo
from deeprec_tpu.models import WDL
from deeprec_tpu.obs import metrics as M, schema, trace as T
from deeprec_tpu.optim import Adagrad
from deeprec_tpu.serving import (
    BackendServer,
    Frontend,
    HttpServer,
    ModelServer,
    Predictor,
)
from deeprec_tpu.training import Trainer
from deeprec_tpu.training.checkpoint import CheckpointManager


def J(b):
    return {k: jnp.asarray(v) for k, v in b.items()}


@pytest.fixture(scope="module")
def trained(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("obs-wdl")
    model = WDL(emb_dim=4, capacity=1 << 10, hidden=(8,), num_cat=2,
                num_dense=2)
    tr = Trainer(model, Adagrad(lr=0.1), optax.adam(1e-3))
    st = tr.init(0)
    gen = SyntheticCriteo(batch_size=32, num_cat=2, num_dense=2, vocab=300,
                          seed=5)
    for _ in range(2):
        st, _ = tr.train_step(st, J(gen.batch()))
    ck = CheckpointManager(str(tmp), tr)
    st, _ = ck.save(st)
    req = {k: np.asarray(v)[:4] for k, v in gen.batch().items()
           if not k.startswith("label")}
    # train_step donates its state arg — tests that advance training must
    # thread the live state through this holder
    holder = {"st": st}
    return model, tr, holder, ck, gen, str(tmp), req


@pytest.fixture(autouse=True)
def _tracing_off():
    T.shutdown()
    yield
    T.shutdown()


def scrape(port):
    text = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=10).read().decode()
    return text, M.parse_prometheus(text)


def test_backend_metrics_endpoint_and_lag_gauge(trained):
    model, tr, holder, ck, gen, tmp, req = trained
    pred = Predictor(model, tmp)
    server = ModelServer(pred, max_batch=32, max_wait_ms=0.5)
    http = HttpServer(server, port=0).start()
    try:
        server.request(req)
        text, parsed = scrape(http.port)
        names = {k[0] for k in parsed}
        # serving series: per-stage histograms (p99 derivable), queue
        # depth and model identity as live collector gauges
        assert "deeprec_serving_stage_seconds_bucket" in names
        assert "deeprec_serving_requests_total" in names
        assert parsed[("deeprec_serving_queue_depth", "")] == 0.0
        assert ("deeprec_serving_model_version", "") in parsed
        # the lag gauge appears once an update has been APPLIED
        assert "deeprec_train_to_serve_lag_seconds" not in names
        holder["st"], _ = tr.train_step(holder["st"], J(gen.batch()))
        holder["st"], _ = ck.save_incremental(holder["st"])
        assert pred.poll_updates()
        lag = pred.last_apply_lag_seconds
        assert lag is not None and 0.0 <= lag < 30.0
        _, parsed = scrape(http.port)
        assert parsed[("deeprec_train_to_serve_lag_seconds", "")] == lag
        # windowed query straight off the stats registry ring
        p99 = server.stats.window_p99_ms("e2e", 60.0)
        assert p99 is not None and p99 > 0.0
    finally:
        http.stop()
        server.close()


def test_stats_snapshot_health_uses_unified_schema(trained):
    model, _, _, _, _, tmp, req = trained
    pred = Predictor(model, tmp)
    server = ModelServer(pred, max_batch=32, max_wait_ms=0.5)
    try:
        snap = server.stats_snapshot()
        assert schema.is_health_payload(snap["health"])
        assert snap["health"]["schema"] == schema.HEALTH_SCHEMA
        # legacy keys unchanged for existing consumers
        assert "staleness_seconds" in snap["health"]
        assert "consecutive_poll_failures" in snap["health"]
    finally:
        server.close()


def make_tier(model, tmp, n=2):
    backends = [
        BackendServer(
            ModelServer(Predictor(model, tmp), max_batch=32,
                        max_wait_ms=0.5)).start()
        for _ in range(n)
    ]
    fe = Frontend([("127.0.0.1", b.port) for b in backends], model)
    return backends, fe


def test_frontend_metrics_merge_and_stale_marking(trained):
    model, _, _, _, _, tmp, req = trained
    backends, fe = make_tier(model, tmp)
    http = HttpServer(fe, port=0).start()
    try:
        for _ in range(4):
            fe.request(req)
        text, parsed = scrape(http.port)
        addrs = [m.addr for m in fe._members]
        # every member's serving series appear relabeled, plus the
        # frontend's own edge series and the per-member up gauge
        for a in addrs:
            assert parsed[("deeprec_member_up", f'{{member="{a}"}}')] == 1.0
            assert any(k[0] == "deeprec_serving_batches_total"
                       and f'member="{a}"' in k[1] for k in parsed)
        assert any("tier=\"frontend\"" in k[1] for k in parsed)
        # one # TYPE line per family across the per-member blocks —
        # real Prometheus parsers reject duplicates
        type_lines = [ln for ln in text.splitlines()
                      if ln.startswith("# TYPE ")]
        assert len(type_lines) == len(set(type_lines)), type_lines

        # kill backend 0: its series must survive STALE-MARKED in the
        # merge (visible absence), and its up gauge must read 0
        backends[0].server.close()
        backends[0].stop()
        text, parsed = scrape(http.port)
        dead = addrs[0]
        assert parsed[("deeprec_member_up", f'{{member="{dead}"}}')] == 0.0
        stale = [k for k in parsed
                 if k[0] == "deeprec_serving_batches_total"
                 and f'member="{dead}"' in k[1] and 'stale="1"' in k[1]]
        assert stale, f"dead member's series vanished from:\n{text}"
        # the failed SCRAPE must not have mutated routing state: the
        # member is only marked down when request/health traffic fails
        assert fe._members[0].available(__import__("time").monotonic())
        # the live member's series stay fresh (no stale label)
        assert any(k[0] == "deeprec_serving_batches_total"
                   and f'member="{addrs[1]}"' in k[1]
                   and "stale" not in k[1] for k in parsed)
    finally:
        http.stop()
        fe.close()
        for b in backends:
            try:
                b.server.close()
                b.stop()
            except Exception:
                pass


def test_trace_id_spans_http_edge_to_backend_stages(trained, tmp_path):
    """One trace id, propagated from the X-Deeprec-Trace header through
    the frontend's TCP frame into the backend micro-batcher: the edge,
    frontend dispatch, backend dispatch and all four stage spans share
    it (in-process backends share this process's tracer, so the wire
    decode path is exactly what a remote backend runs)."""
    model, _, _, _, _, tmp, req = trained
    path = str(tmp_path / "tier.jsonl")
    backends, fe = make_tier(model, tmp, n=1)
    http = HttpServer(fe, port=0).start()
    T.configure(path, sample=1.0, service="tier")
    try:
        body = json.dumps(
            {"features": {k: v.tolist() for k, v in req.items()}}).encode()
        trace_hex = "00000000000abcde"
        r = urllib.request.urlopen(
            urllib.request.Request(
                f"http://127.0.0.1:{http.port}/v1/predict", data=body,
                headers={"Content-Type": "application/json",
                         T.HEADER: f"{trace_hex}-0000000000000001"},
                method="POST"),
            timeout=30)
        assert r.status == 200
        T.flush()
        evs = [json.loads(ln) for ln in open(path)]
        mine = [e for e in evs
                if (e.get("args") or {}).get("trace") == trace_hex]
        names = {e["name"] for e in mine}
        assert {"http_predict", "frontend_dispatch", "dispatch",
                "stage_queue", "stage_pad", "stage_device",
                "stage_post"} <= names, names
    finally:
        http.stop()
        fe.close()
        for b in backends:
            b.server.close()
            b.stop()


def test_frontend_health_sweep_unified_schema_with_down_member(trained):
    model, _, _, _, _, tmp, req = trained
    backends, fe = make_tier(model, tmp)
    try:
        backends[1].server.close()
        backends[1].stop()
        h = fe.predictor.health()
        assert schema.is_health_payload(h)
        assert h["status"] == "degraded"
        assert h["reachable"] == 1 and h["members"] == 2
    finally:
        fe.close()
        backends[0].server.close()
        backends[0].stop()


def test_dedup_stats_publishes_placement_gauges(trained):
    model, tr, holder, _, _, _, _ = trained
    stats = tr.dedup_stats(holder["st"])
    assert stats  # at least one table reported
    reg = M.default_registry()
    tname = next(iter(stats))
    w = reg.window("deeprec_dedup_unique_fraction", {"table": tname})
    if stats[tname]["unique_fraction"] is not None:
        assert w["last"] == stats[tname]["unique_fraction"]
    # single-device trainer has no shard axis -> no per_shard series;
    # the sharded path is exercised by the bench/placement suites
    assert "per_shard" not in stats[tname] or (
        reg.window("deeprec_shard_imbalance", {"table": tname})["last"]
        is not None)


def test_supervisor_stats_lease_view_and_gauges():
    from deeprec_tpu.online.supervisor import ProcessSpec, Supervisor

    spec = ProcessSpec(name="w0", argv=["true"], max_restarts=5)
    sup = Supervisor([spec])
    stats = sup.stats()["w0"]
    assert stats["restart_budget_remaining"] == 5
    assert stats["heartbeat_age_seconds"] is None  # no lease configured
    reg = M.default_registry()
    w = reg.window("deeprec_supervisor_restart_budget_remaining",
                   {"worker": "w0"})
    assert w["last"] == 5.0
