"""Pallas embedding-row gather: DMA-pipelined random-row fetch from HBM.

The TPU shape of DeepRec's KvResourceGather hot loop (SURVEY.md §3.1 —
per-key memcpy from table storage into the output batch): row indices ride
scalar prefetch (SMEM, available before the kernel body), the value table
stays in HBM, and rows stream through a double-buffered VMEM scratch so the
next row's DMA overlaps the current row's store — the classic embedding-bag
DMA pattern from the Pallas guide.

Status: experimental alternative to XLA's native gather for serving-path
lookups of wide rows (D >= 128, where per-row DMA amortizes); correctness is
oracle-tested in interpret mode. Callers opt in explicitly by calling
gather_rows — it is not wired into the default lookup path yet.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def gather_rows(values, ix, *, block: int = 8, interpret: bool = False):
    """values [C, D] (HBM), ix [n] int32 -> [n, D]. n must divide by block.

    Out-of-range indices are clamped (mode='clip' semantics, matching the
    jnp fallback used on non-TPU backends).
    """
    n = ix.shape[0]
    C, D = values.shape
    if n % block:
        raise ValueError(f"n={n} not a multiple of block={block}")
    if not interpret and jax.default_backend() != "tpu":
        return values.at[ix].get(mode="clip")

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kernel(ix_ref, values_ref, out_ref, scratch, sems):
        base = pl.program_id(0) * block

        def row_dma(slot, i):
            idx = jnp.clip(ix_ref[base + i], 0, C - 1)
            return pltpu.make_async_copy(
                values_ref.at[idx], scratch.at[slot], sems.at[slot]
            )

        row_dma(0, 0).start()

        def body(i, _):
            cur = i % 2
            nxt = (i + 1) % 2

            @pl.when(i + 1 < block)
            def _():
                row_dma(nxt, i + 1).start()

            row_dma(cur, i).wait()
            out_ref[i, :] = scratch[cur]
            return 0

        jax.lax.fori_loop(0, block, body, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n // block,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec(
            (block, D), lambda i, ix_ref: (i, 0), memory_space=pltpu.VMEM
        ),
        scratch_shapes=[
            pltpu.VMEM((2, D), values.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n, D), values.dtype),
        interpret=interpret,
    )(ix.astype(jnp.int32), values)
