"""Blocked streaming top-k over a device-resident corpus matrix.

The full-corpus retrieval hot path (serving/retrieval.py) scores ONE
user batch against EVERY resident item vector. Materializing the whole
[B, C] score matrix is the naive shape — at C = 10M items it is 40 MB
per user row and the scores are read exactly once. Instead the sweep is
blocked: the corpus lives as [C/Bk, Bk, H] pow2-padded blocks, each
block contributes a [B, Bk] score tile, and a [B, k] top-k carry is
merged per block with `lax.top_k` — the score matrix never exists, peak
residency is one tile + the carry, and the HBM traffic of a sweep is
exactly one read of the corpus (ops/traffic.py `retrieval_sweep_bytes`
models it; the bench asserts measured == modeled).

Tie handling is DETERMINISTIC and block-size independent: equal scores
resolve to the LOWEST corpus row index. `lax.top_k` breaks value ties
by position; the carry is kept sorted (score desc, row asc) and always
precedes the current block's rows — which are themselves in ascending
row order — in the merge buffer, so the position tie-break IS the
ascending-row-index tie-break, inductively across blocks. The fleet
merge (frontend) re-establishes the same order across shards with a
host-side lexsort on (-score, item id).

int8 corpora ride the PR 10 residency story: rows store int8 codes plus
a per-row fp32 scale, and because the score is a dot product the
dequantization moves OUT of the row axis — score = (u · q_row) * scale —
so the sweep reads 1 byte/element and pays one [Bk] multiply per block
instead of dequantizing [Bk, H] rows.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

# Score assigned to padding / invalid corpus rows: they can never win a
# merge against any finite score, and surviving -inf entries mark "fewer
# than k valid rows" (the caller maps them to item id -1).
NEG_INF = jnp.float32(-jnp.inf)


def blocked_topk(
    user: jnp.ndarray,
    corpus: jnp.ndarray,
    valid: jnp.ndarray,
    k: int,
    *,
    block_rows: int,
    scale: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Top-k rows of `corpus` by dot-product score for each user vector.

    user    [B, H] float32 — query vectors (the user tower's output).
    corpus  [Cp, H] int8/bf16/f32 — resident item matrix, Cp a multiple
            of `block_rows` (pow2-padded; padding rows are invalid).
    valid   [Cp] bool — live corpus rows; invalid rows score -inf.
    k       static — results per user row.
    scale   [Cp] f32 or None — per-row dequant scale (int8 residency):
            score = (user · row) * scale[row].

    Returns (scores [B, k] f32 desc-sorted, rows [B, k] int32 corpus row
    indices; -1 where fewer than k valid rows exist). Ties are broken by
    the lowest row index, independent of `block_rows`.
    """
    B = user.shape[0]
    Cp, H = corpus.shape
    if Cp % block_rows:
        raise ValueError(
            f"corpus rows {Cp} not a multiple of block_rows {block_rows}")
    nb = Cp // block_rows
    user = jnp.asarray(user, jnp.float32)
    init = (
        jnp.full((B, k), NEG_INF, jnp.float32),
        jnp.full((B, k), -1, jnp.int32),
    )
    if nb == 0:
        return init

    blocks = corpus.reshape(nb, block_rows, H)
    vblocks = valid.reshape(nb, block_rows)
    base = (jnp.arange(nb, dtype=jnp.int32) * block_rows)
    xs = (blocks, vblocks, base)
    if scale is not None:
        xs = xs + (scale.astype(jnp.float32).reshape(nb, block_rows),)

    def body(carry, x):
        vals, rows = carry
        if scale is not None:
            blk, vld, b0, s = x
        else:
            blk, vld, b0 = x
            s = None
        # One tile of scores: the int8/bf16 block is widened in-register;
        # HBM only ever read the storage dtype.
        tile = jax.lax.dot_general(
            user, blk.astype(jnp.float32),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [B, Bk]
        if s is not None:
            tile = tile * s[None, :]
        tile = jnp.where(vld[None, :], tile, NEG_INF)
        gidx = (b0 + jnp.arange(block_rows, dtype=jnp.int32))[None, :]
        # Merge buffer: carry FIRST (earlier/lower rows among ties, by
        # the invariant), block rows after in ascending order — so
        # top_k's position tie-break keeps lowest-row-wins exact.
        mv = jnp.concatenate([vals, tile], axis=1)
        mi = jnp.concatenate(
            [rows, jnp.broadcast_to(gidx, tile.shape)], axis=1)
        top_v, pos = jax.lax.top_k(mv, k)
        top_i = jnp.take_along_axis(mi, pos, axis=1)
        return (top_v, top_i), None

    (vals, rows), _ = jax.lax.scan(body, init, xs)
    rows = jnp.where(vals > NEG_INF, rows, -1)
    return vals, rows
