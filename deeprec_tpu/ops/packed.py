"""Packed small-dim storage layout: ride the fused DMA kernels at dim < 128.

Why: every Pallas kernel in ops/fused_lookup.py needs rows that fill a
128-lane HBM granule, but the flagship DLRM/Criteo tables are dim 16 — and
worse, XLA pads a [C, 16] f32 array's minor dim to 128 lanes on TPU, so a
small-dim table wastes 8x HBM *and* 8x gather bandwidth. The reference's
CUDA group/fused lookups cover small dims as a matter of course
(/root/reference/tensorflow/core/kernels/group_embedding/
group_embedding_lookup_sparse_forward_base_ops.cu.h); the TPU answer is a
layout change, not a new kernel:

  * store P = 128 // dim logical rows per 128-lane granule — the physical
    array is [C // P, P * dim], exactly a row-major reshape, so host-side
    unpack is a free numpy view and the checkpoint format (compacted
    LOGICAL rows) is unchanged;
  * gather = granule gather (the already-measured f32 row / bf16 pair DMA
    kernels apply verbatim, the packed array IS a dim-128 table) + a cheap
    XLA sub-row select on the batch-sized result;
  * scatter = merge updates granule-wise in XLA (unique granules -> patch
    + mask), then read-modify-write whole granules through apply_rows_sr.
    bf16 merge is safe because stochastic rounding of an exactly-
    representable bf16 value is the identity (its low 16 mantissa bits are
    zero, so no carry can reach the kept bits) — untouched lanes round
    through unchanged.

Every helper here is layout-polymorphic: the pack factor is derived from
the array shape (P = capacity // arr.shape[0]), so P == 1 arrays take the
original unpacked path and callers never branch.
"""
from __future__ import annotations

import jax.numpy as jnp

from deeprec_tpu.ops import fused_lookup as _fl

LANES = 128


def pack_factor(width: int, capacity: int) -> int:
    """Rows per 128-lane granule for a [capacity, width] per-row array;
    1 when packing does not apply (width already lane-sized, width does
    not divide 128, or capacity not a granule multiple)."""
    if width <= 0 or width >= LANES or LANES % width:
        return 1
    p = LANES // width
    if capacity % p:
        return 1
    return p


def row_factor(arr, capacity: int) -> int:
    """Recover the pack factor of a possibly-packed per-row array from its
    shape (shapes are static under jit, so this is a python int)."""
    rows = arr.shape[-2] if arr.ndim >= 2 else arr.shape[0]
    if rows and capacity % rows == 0:
        return capacity // rows
    return 1


def is_unpacked(arr, capacity: int) -> bool:
    """True when `arr` stores one logical row per physical row — the layout
    the fused-step kernels (ops/fused_lookup.fused_sparse_*) require, since
    their per-row DMAs address whole logical rows."""
    return row_factor(arr, capacity) == 1


def pack_array(arr: jnp.ndarray, p: int) -> jnp.ndarray:
    """[C, w] -> [C // p, p * w] (row-major; a relayout copy on device,
    a free view on host numpy)."""
    if p == 1:
        return arr
    c, w = arr.shape
    return arr.reshape(c // p, p * w)


def unpack_array(arr, capacity: int):
    """Inverse of pack_array: [C // p, p * w] -> [C, w]. Works on jnp and
    numpy arrays (numpy: zero-copy view). No-op for unpacked arrays."""
    return arr.reshape(capacity, -1)


def gather_rows_any(arr: jnp.ndarray, ix: jnp.ndarray, capacity: int, *,
                    use_pallas: bool = False, pair_kernels: bool = False,
                    interpret: bool = False) -> jnp.ndarray:
    """values[ix] with clip semantics for a possibly-packed per-row array.

    Packed arrays DMA one granule per lookup (minimum possible HBM
    traffic — the hardware reads 128 lanes regardless) and select the
    sub-row in XLA on the [n, 128] result.
    """
    p = row_factor(arr, capacity)
    if p == 1:
        if use_pallas:
            return _fl.gather_rows(arr, ix, pair_kernels=pair_kernels,
                                   interpret=interpret)
        return arr.at[ix].get(mode="clip")
    ix = jnp.clip(ix.astype(jnp.int32), 0, capacity - 1)
    g = ix // p
    if use_pallas:
        gran = _fl.gather_rows(arr, g, pair_kernels=pair_kernels,
                               interpret=interpret)
    else:
        gran = arr.at[g].get(mode="clip")
    n = ix.shape[0]
    w = arr.shape[1] // p
    sub = gran.reshape(n, p, w)
    return jnp.take_along_axis(sub, (ix % p)[:, None, None], axis=1).reshape(
        n, w
    )


def scatter_rows_any(arr: jnp.ndarray, slot_ix: jnp.ndarray,
                     rows: jnp.ndarray, capacity: int,
                     seed: jnp.ndarray | int = 0, *,
                     use_pallas: bool = False, pair_kernels: bool = False,
                     interpret: bool = False) -> jnp.ndarray:
    """Write rows [U, w] at logical slot_ix [U] (< 0 = skip) into a
    possibly-packed per-row array; bf16 targets stochastic-round.

    Caller contract (same as apply_rows_sr): slot indices are unique among
    the valid entries — two updates to one logical row would race. Packed
    arrays merge the updates granule-wise first (distinct rows of one
    granule occupy disjoint lanes, so the merge scatter cannot collide),
    then RMW whole granules; untouched lanes pass through SR unchanged
    (exactly-representable values round to themselves).
    """
    p = row_factor(arr, capacity)
    rows = rows.astype(jnp.float32)
    slot_ix = slot_ix.astype(jnp.int32)
    seed = jnp.asarray(seed, jnp.int32)
    if p == 1:
        return _fl.apply_rows_sr(arr, slot_ix, rows, seed,
                                 use_pallas=use_pallas,
                                 pair_kernels=pair_kernels,
                                 interpret=interpret)
    u, w = rows.shape
    ok = slot_ix >= 0
    g = jnp.where(ok, slot_ix // p, -1)
    r = jnp.where(ok, slot_ix % p, 0)
    # Merge in unique-granule space: invalid updates share the -1 entry
    # (dropped at scatter time), valid ones land at distinct (granule,
    # sub-row) coordinates.
    ug, inv = jnp.unique(g, size=u, fill_value=-1, return_inverse=True)
    patch = jnp.zeros((u, p, w), jnp.float32).at[inv, r].set(rows)
    mask = jnp.zeros((u, p), bool).at[inv, r].set(ok)
    # Old granule contents ride the same DMA gather the lookup path uses.
    if use_pallas:
        gran = _fl.gather_rows(arr, jnp.clip(ug, 0, arr.shape[0] - 1),
                               pair_kernels=pair_kernels,
                               interpret=interpret)
    else:
        gran = arr.at[jnp.clip(ug, 0, arr.shape[0] - 1)].get(mode="clip")
    merged = jnp.where(
        mask[:, :, None], patch, gran.reshape(u, p, w).astype(jnp.float32)
    ).reshape(u, p * w)
    return _fl.apply_rows_sr(arr, jnp.where(ug >= 0, ug, -1), merged, seed,
                             use_pallas=use_pallas,
                             pair_kernels=pair_kernels, interpret=interpret)
