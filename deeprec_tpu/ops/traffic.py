"""Embedding-engine traffic accounting: one model, asserted against reality.

The train-step hot path of a hash-embedding table is a fixed set of
gathers/scatters per unique id (docs/perf.md "Roofline methodology").  This
module is the single source of truth for that set, in two forms:

  * **Bytes** (`table_step_traffic`): per-table per-step HBM bytes of the
    engine plus, for sharded tables, the wire bytes of the collective
    exchange at a given wire dtype.  `tools/roofline.py` divides these by
    measured step time; `bench.py` records them as
    `engine_bytes_per_step` so a before/after is an artifact, not a claim.
  * **Op counts** (`expected_lookup_apply_ops`): how many stablehlo
    gather/scatter ops the single-table lookup+apply program should lower
    to.  `bench.py` measures the real counts off the lowered program
    (`count_stablehlo_ops`); `tools/roofline.py --assert-traffic` fails CI
    when model and measurement drift — so the model can never silently
    describe a hot path the code no longer runs.

Both forms carry a `diet` switch describing the pre/post state of the
traffic-diet PR (forward-residual reuse + fused metadata + dropped
apply-side re-stamps), which is how the "before" column of the accounting
stays reproducible after the "before" code is gone.
"""
from __future__ import annotations

import re
from typing import Dict, Optional, Sequence

META_COLS = 3  # freq / version / dirty, int32 each (embedding/table.py)


# ----------------------------------------------------------- imbalance model
#
# The wire terms below model the MEAN per-device exchange payload; under a
# uniform hash and zipf traffic the max shard does a multiple of that, and
# after the in-step pipelining PR the exchange straggler is exactly what
# bounds step time. These two helpers are the shared vocabulary between the
# placement cost model (parallel/placement.py), the live owner counters
# (Trainer.dedup_stats per_shard) and the bench/CI gate
# (`bench.py --placement`, `roofline.py --assert-imbalance`): everyone
# reports load as exchange bytes and skew as max/mean of that.


def exchange_row_bytes(
    *, dim: int, wire_bytes: int = 4, key_bytes: int = 4
) -> float:
    """Wire bytes ONE exchanged row costs its owner shard per step:
    embedding down + grad up at the wire dtype, plus the id + count int32
    ride-along. This is the per-arrival weight of the placement cost
    model and of the per-shard `exchange_bytes` telemetry."""
    return float(2 * dim * wire_bytes + key_bytes + 4)


def shard_imbalance(loads) -> float:
    """max/mean of a per-shard load vector — 1.0 is perfectly balanced,
    N is everything-on-one-shard. Defined as 1.0 for empty/zero loads
    (nothing exchanged is not skewed)."""
    import numpy as np

    l = np.asarray(loads, dtype=np.float64)
    if l.size == 0:
        return 1.0
    mean = float(l.mean())
    if mean <= 0.0:
        return 1.0
    return float(l.max()) / mean


# ------------------------------------------------- a2a budget model (plan v2)
#
# The a2a exchange buckets ids by destination with a static per-bucket
# budget. Placement v1 modeled the budget as hash-uniform spread
# (slack·U/N) plus one GLOBAL hot-key headroom — the plan's worst
# per-destination hot concentration added to EVERY bucket. Placement v2
# replaces that with a per-destination budget VECTOR derived from the
# plan's own routing: destination d pays the tail share (the uniques the
# plan's hot table does NOT route explicitly — slack·(U−H)/N) plus
# exactly the hot-key arrivals the plan routes to d. The compiled bucket
# is the vector's max (all_to_all moves equal chunks — SPMD programs
# cannot ship ragged per-destination buckets), which is still strictly
# tighter than the global-headroom bucket whenever the plan routes enough
# hot keys to shrink the tail share past the 8-row rounding.
# `ShardedTable._a2a_budget` calls `a2a_dest_budgets` directly, so the
# model and the program share one formula by construction; bench.py's
# drift arm additionally records the bucket the trace actually used next
# to the modeled vector (measured == modeled, the residency discipline).


def a2a_dest_budgets(
    *,
    unique: int,
    num_shards: int,
    slack: float = 2.0,
    dest_hot=None,
    hot_count: int = 0,
    floor: int = 8,
):
    """Per-destination a2a bucket budgets [N] (rows).

    `dest_hot` is the plan's per-destination explicit hot-key arrival
    counts (None = uniform hash: no hot routing) and `hot_count` the
    number of plan hot keys removed from the hash-spread tail (each hot
    key is a local unique that the plan routes explicitly, so it never
    competes for tail slots). dest_hot=None/hot_count=0 reproduces the
    legacy slack·U/N budget bit-for-bit. Each budget rounds up to a
    VPU-friendly multiple of 8 with a floor of `floor`.

    Drift-safety margin: the tail subtraction is capped at U/4, so even
    when the ENTIRE routed hot set goes cold at once (a rotated key
    distribution — the window between a drift and the replan that chases
    it) every destination still budgets ≥ 3/4·slack × the uniform
    per-dest spread of what is then an all-tail stream (1.5× the
    expected per-dest load at the default slack=2 — real variance
    headroom, not just the mean). Shortfall beyond that degrades via the
    sentinel bucket (default-served, counted), never drops rows."""
    import math

    import numpy as np

    N = int(num_shards)  # noqa: DRT002 — trace-time budget arithmetic on static shapes, no device value
    h_eff = min(max(0, int(hot_count)), int(unique) // 4)  # noqa: DRT002 — trace-time budget arithmetic on static shapes, no device value
    tail = math.ceil(max(0, int(unique) - h_eff) * slack / N)  # noqa: DRT002 — trace-time budget arithmetic on static shapes, no device value
    hot = (
        np.zeros((N,), np.int64)
        if dest_hot is None
        else np.asarray(dest_hot, np.int64)  # noqa: DRT002 — host plan constants (numpy), never a device value
    )
    if hot.shape != (N,):
        raise ValueError(
            f"dest_hot must be a length-{N} vector, got shape {hot.shape}"
        )
    b = np.maximum(int(floor), ((tail + hot + 7) // 8) * 8)  # noqa: DRT002 — trace-time budget arithmetic on static shapes, no device value
    return b.astype(np.int64)


def a2a_bucket_rows(
    *,
    unique: int,
    num_shards: int,
    slack: float = 2.0,
    dest_hot=None,
    hot_count: int = 0,
    floor: int = 8,
) -> int:
    """The uniform physical bucket the a2a program compiles: the max of
    the per-destination budget vector (all_to_all chunks are equal)."""
    return int(a2a_dest_budgets(
        unique=unique, num_shards=num_shards, slack=slack,
        dest_hot=dest_hot, hot_count=hot_count, floor=floor,
    ).max())


def a2a_bucket_rows_global(
    *,
    unique: int,
    num_shards: int,
    slack: float = 2.0,
    hot_max: int = 0,
    floor: int = 8,
) -> int:
    """The placement-v1 global-headroom bucket: the full hash-spread tail
    (hot keys NOT subtracted) plus the plan's worst per-destination hot
    concentration on every bucket. Kept as the reproducible "before"
    column of the per-dest budget diet (the traffic-diet discipline)."""
    import math

    per = math.ceil(int(unique) * slack / num_shards) + int(hot_max)
    return max(int(floor), ((per + 7) // 8) * 8)


def a2a_exchange_wire_bytes(
    *,
    bucket_rows: int,
    num_shards: int,
    dim: int,
    wire_bytes: int = 4,
    key_bytes: int = 4,
) -> float:
    """Per-device per-step wire bytes of the budgeted a2a exchange at a
    physical bucket of `bucket_rows`: id + count buckets out, embeddings
    back, grads out — (N−1) remote buckets each direction (the bucket a
    shard addresses to itself never leaves the chip)."""
    per_dir = (num_shards - 1) * int(bucket_rows)
    return float(
        per_dir * (key_bytes + 4) + 2 * per_dir * dim * wire_bytes
    )


# ------------------------------------------ hierarchical (two-tier) model
#
# The 2-D mesh splits the flat device axis into a cheap `intra` tier
# (same host group: ICI/NVLink) and an expensive `inter` tier (DCN).
# The hierarchical exchange aggregates ids per host-group on the cheap
# tier first — cross-device duplicates collapse at a relay before
# anything crosses the expensive tier — so the inter-tier bucket is
# budgeted off the GROUP uniques (U_g ≤ group_factor·U ≤ intra·U), not
# off intra·U raw gathered rows. `ShardedTable._hier_budget` calls
# `hier_dest_budgets` directly: model and program share one formula by
# construction, and `bench.py --mesh` records both per-tier modeled and
# measured bytes for `roofline.py --assert-hierarchy` to gate.


def hier_group_unique_budget(
    *, unique: int, intra: int, group_factor: Optional[float] = None,
) -> int:
    """Static budget U_g for the per-host-group unique ids after the
    intra-tier aggregation. `group_factor=None` means exact (intra·U —
    no dedup assumed, the inter bucket can never bind on group overlap);
    a float f budgets U_g = ceil(f·U), capped at intra·U, expressing the
    expected cross-device id overlap inside a group (f→1 as devices in a
    group see the same hot ids). Rounded up to a multiple of 8."""
    import math

    U, I = int(unique), int(intra)  # noqa: DRT002 — trace-time budget arithmetic on static shapes, no device value
    cap = I * U
    if group_factor is None:
        return cap
    ug = min(cap, math.ceil(float(group_factor) * U))  # noqa: DRT002 — group_factor is a host float knob, no device value
    return min(cap, ((ug + 7) // 8) * 8)


def hier_relay_rows(*, unique: int, intra: int) -> int:
    """Static size of the relay dedup stage: the intra-tier allgather
    hands every device intra·U rows; the relay (device i of each group
    handles gathered ids whose owner sits at intra position i) dedups
    over that full static extent — compute-only, nothing crosses a
    wire at this size."""
    return int(intra) * int(unique)  # noqa: DRT002 — trace-time budget arithmetic on static shapes, no device value


def hier_dest_budgets(
    *,
    unique: int,
    intra: int,
    inter: int,
    slack: float = 2.0,
    group_factor: Optional[float] = None,
    dest_hot=None,
    hot_count: int = 0,
    floor: int = 8,
):
    """Per-destination-GROUP budgets [J] (rows) of the inter-tier a2a.

    Each relay holds ~U_g/intra of its group's uniques (owner intra-pos
    partitions the group uniques across relays under a uniform hash), and
    buckets them by owner GROUP — J destinations. This reuses the per-dest
    budget discipline of `a2a_dest_budgets` verbatim at the group tier:
    `dest_hot` is the plan's per-device hot arrival vector [N] folded to
    per-group maxima over the relay position (all relays compile one
    bucket), `hot_count` the plan hot keys removed from the tail (split
    across relays). Overflow degrades via the sentinel bucket exactly as
    in the flat a2a — default-served, counted, never dropped."""
    import math

    import numpy as np

    I, J = int(intra), int(inter)  # noqa: DRT002 — trace-time budget arithmetic on static shapes, no device value
    ug = hier_group_unique_budget(
        unique=unique, intra=I, group_factor=group_factor
    )
    relay_u = math.ceil(ug / I)
    group_hot = None
    if dest_hot is not None:
        hot = np.asarray(dest_hot, np.int64)  # noqa: DRT002 — host plan constants (numpy), never a device value
        if hot.shape != (J * I,):
            raise ValueError(
                f"dest_hot must be a length-{J * I} per-device vector, "
                f"got shape {hot.shape}"
            )
        group_hot = hot.reshape(J, I).max(axis=1)
    return a2a_dest_budgets(
        unique=relay_u, num_shards=J, slack=slack,
        dest_hot=group_hot, hot_count=math.ceil(int(hot_count) / I),  # noqa: DRT002 — trace-time budget arithmetic on static shapes, no device value
        floor=floor,
    )


def hier_bucket_rows(
    *,
    unique: int,
    intra: int,
    inter: int,
    slack: float = 2.0,
    group_factor: Optional[float] = None,
    dest_hot=None,
    hot_count: int = 0,
    floor: int = 8,
) -> int:
    """The uniform physical inter-tier bucket (max of the per-group
    budget vector — all_to_all chunks are equal)."""
    return int(hier_dest_budgets(
        unique=unique, intra=intra, inter=inter, slack=slack,
        group_factor=group_factor, dest_hot=dest_hot, hot_count=hot_count,
        floor=floor,
    ).max())


def hier_exchange_bytes(
    *,
    unique: int,
    intra: int,
    inter: int,
    dim: int,
    wire_bytes: int = 4,
    key_bytes: int = 4,
    slack: float = 2.0,
    group_factor: Optional[float] = None,
    dest_hot=None,
    hot_count: int = 0,
    intra_bw_gbs: Optional[float] = None,
    inter_bw_gbs: Optional[float] = None,
) -> Dict[str, float]:
    """Per-device per-step wire bytes of the hierarchical exchange, split
    by tier (the whole point of the 2-D mesh: the tiers have different
    bandwidths, so one aggregate byte count hides the term that matters).

    intra tier (cheap) per device:
      id+count allgather        (I−1)·U·(kb+4)
      value psum_scatter        (I−1)·U·D·wb   (tiled partial sums)
      grad allgather            (I−1)·U·D·wb
    inter tier (expensive) per device, bucket B_g = hier_bucket_rows:
      id+count buckets out      (J−1)·B_g·(kb+4)
      embeddings back           (J−1)·B_g·D·wb
      grads out                 (J−1)·B_g·D·wb

    With `intra_bw_gbs`/`inter_bw_gbs` (GB/s per device, e.g. ICI vs DCN
    injection bandwidth) the dict also carries modeled per-tier
    milliseconds — the roofline form `bench.py --mesh` records."""
    U, D, I, J = int(unique), int(dim), int(intra), int(inter)  # noqa: DRT002 — trace-time budget arithmetic on static shapes, no device value
    kb, wb = int(key_bytes), int(wire_bytes)  # noqa: DRT002 — trace-time budget arithmetic on static shapes, no device value
    Bg = hier_bucket_rows(
        unique=U, intra=I, inter=J, slack=slack, group_factor=group_factor,
        dest_hot=dest_hot, hot_count=hot_count,
    )
    intra_b = float(
        (I - 1) * U * (kb + 4) + 2 * (I - 1) * U * D * wb
    )
    inter_b = float(
        (J - 1) * Bg * (kb + 4) + 2 * (J - 1) * Bg * D * wb
    )
    out: Dict[str, float] = {
        "intra_bytes": intra_b,
        "inter_bytes": inter_b,
        "total_bytes": intra_b + inter_b,
        "bucket_rows": float(Bg),
        "group_unique_budget": float(hier_group_unique_budget(
            unique=U, intra=I, group_factor=group_factor
        )),
    }
    if intra_bw_gbs:
        out["intra_ms"] = intra_b / (float(intra_bw_gbs) * 1e9) * 1e3
    if inter_bw_gbs:
        out["inter_ms"] = inter_b / (float(inter_bw_gbs) * 1e9) * 1e3
    return out


def flat_exchange_tier_bytes(
    *,
    unique: int,
    num_shards: int,
    intra: int,
    comm: str = "a2a",
    dim: int = 16,
    wire_bytes: int = 4,
    key_bytes: int = 4,
    slack: float = 2.0,
) -> Dict[str, float]:
    """The FLAT exchange's per-device bytes mapped onto the two-tier
    topology: of its N−1 remote peers, I−1 sit inside the host group
    (intra tier) and N−I across groups (inter tier). This is the
    baseline column of the hierarchy diet — `roofline.py
    --assert-hierarchy` pins hier inter_bytes ≤ total/intra and
    ≤ 0.5 × this function's inter_bytes at the reference shape."""
    U, D, N, I = int(unique), int(dim), int(num_shards), int(intra)  # noqa: DRT002 — trace-time budget arithmetic on static shapes, no device value
    kb, wb = int(key_bytes), int(wire_bytes)  # noqa: DRT002 — trace-time budget arithmetic on static shapes, no device value
    if comm == "a2a":
        Bd = a2a_bucket_rows(unique=U, num_shards=N, slack=slack)
        row = (kb + 4) + 2 * D * wb
        return {
            "intra_bytes": float((I - 1) * Bd * row),
            "inter_bytes": float((N - I) * Bd * row),
            "total_bytes": float((N - 1) * Bd * row),
        }
    if comm == "allgather":
        row = (kb + 4) + 2 * D * wb
        return {
            "intra_bytes": float((I - 1) * U * row),
            "inter_bytes": float((N - I) * U * row),
            "total_bytes": float((N - 1) * U * row),
        }
    raise ValueError(f"unknown comm {comm!r}")


# --------------------------------------------- replanning amortization model


def migration_bytes(moved_rows: int, *, row_bytes: float) -> float:
    """Modeled one-shot cost of migrating `moved_rows` between shards at
    plan adoption: `exchange_row_bytes` over the moved rows — the same
    per-row unit as the placement load model, so gain/step and cost live
    in one currency and the amortization horizon is a plain division."""
    return float(moved_rows) * float(row_bytes)


def replan_gain_bytes(loads_current, loads_candidate) -> float:
    """Modeled per-step byte gain of adopting a candidate plan: the drop
    in the MAX-shard exchange load (after round 11's pipelining the
    exchange straggler is what bounds step time, so straggler bytes are
    the honest unit — mean load is invariant under re-routing)."""
    import numpy as np

    cur = np.asarray(loads_current, np.float64)
    cand = np.asarray(loads_candidate, np.float64)
    if cur.size == 0 or cand.size == 0:
        return 0.0
    return float(cur.max() - cand.max())


# --------------------------------------------------------------- bytes model


def table_step_traffic(
    *,
    unique: int,
    dim: int,
    value_bytes: int = 4,
    key_bytes: int = 4,
    slot_widths: Sequence[int] = (0,),
    diet: bool = True,
    counter_filter: bool = False,
    num_shards: int = 1,
    comm: Optional[str] = None,
    wire_bytes: int = 4,
    a2a_slack: float = 2.0,
    imbalance: float = 1.0,
) -> Dict[str, float]:
    """Per-table per-step traffic of the embedding engine.

    `unique` is the number of unique rows the step touches (post-dedup, the
    budgeted U); `slot_widths` the optimizer's per-row slot widths (f32).
    Steady state: the initializer scatter for newly created rows is
    excluded (it is proportional to table GROWTH, not step traffic).

    Returns {"hbm_bytes", "wire_bytes", "total_bytes"} — wire_bytes is 0
    for unsharded tables; for num_shards > 1 it models the per-device
    payload of the `comm` exchange ("allgather" | "a2a") at `wire_bytes`
    per value/grad element (4 = fp32, 2 = bf16; ids/counts always ride
    int32).

    `imbalance` is the max/mean per-shard owner-load skew
    (`shard_imbalance`): wire_bytes stays the MEAN payload, and a
    "wire_bytes_max_shard" entry models the straggler shard that actually
    bounds the exchange (mean x imbalance) — the quantity the placement
    plan flattens.
    """
    U, D, vb, kb = unique, dim, value_bytes, key_bytes
    slot_b = sum(w * 4 for w in slot_widths)

    # --- HBM: per-unique-id engine traffic (gathers read, scatters write;
    # .add reads and writes).
    probe = 2 * kb * U  # key gather + claim scatter
    value = (1 * D * vb) * U  # lookup row gather — the apply reuses it
    value += (1 * D * vb) * U  # apply row scatter
    slots = 2 * slot_b * U  # apply slot gather + scatter
    if diet:
        # one fused [3] gather + one fused [3] scatter
        meta = 2 * META_COLS * 4 * U
    else:
        # forward: freq RMW (r+w) + version set + dirty set; admission
        # freq gather when a counter filter gates; apply re-gather of the
        # value rows and the duplicate version/dirty re-stamps.
        meta = (2 * 4 + 4 + 1) * U
        meta += (4 * U) if counter_filter else 0
        meta += (4 + 1) * U  # apply-side version/dirty re-stamp
        value += (1 * D * vb) * U  # apply-side value re-gather
    hbm = probe + value + slots + meta

    # --- wire: per-device exchange payload for sharded tables.
    wire = 0.0
    if num_shards > 1 and comm:
        N = num_shards
        if comm == "allgather":
            # ids + counts allgather (int32), value psum_scatter, grad
            # allgather — each moves ~(N-1)·U rows per device.
            wire += (N - 1) * U * (kb + 4)
            wire += (N - 1) * U * D * wire_bytes  # embeddings down
            wire += (N - 1) * U * D * wire_bytes  # grads up
        elif comm == "a2a":
            # Placement v2: the bucket is the max of the per-destination
            # budget vector (uniform hash: hot terms zero — identical to
            # the legacy slack·U/N bucket).
            Bd = a2a_bucket_rows(unique=U, num_shards=N, slack=a2a_slack)
            wire += a2a_exchange_wire_bytes(
                bucket_rows=Bd, num_shards=N, dim=D,
                wire_bytes=wire_bytes, key_bytes=kb,
            )
        else:
            raise ValueError(f"unknown comm {comm!r}")
    return {
        "hbm_bytes": float(hbm),
        "wire_bytes": float(wire),
        "wire_bytes_max_shard": float(wire) * max(1.0, float(imbalance)),
        "total_bytes": float(hbm + wire),
    }


def fused_sparse_step_traffic(
    *,
    positions: int,
    batch: int,
    unique: int,
    dim: int,
    value_bytes: int = 4,
    key_bytes: int = 4,
    slot_widths: Sequence[int] = (0,),
    fused: bool = True,
) -> Dict[str, float]:
    """Modeled HBM bytes of one fwd+bwd sparse bag step (lookup + combine
    + optimizer apply) for one table — the quantity `roofline.py
    --assert-fused` gates on.

    `positions` is the flattened id-stream length N = B·L, `batch` the bag
    count B, `unique` the budgeted U. The split-phase model
    (`fused=False`) counts every HBM materialization the XLA path makes,
    including the O(N·D) expansion terms the fused kernels eliminate: the
    `emb_u[inverse]` gather that materializes [N, D] before the combine
    reduction, and the mirrored [N, D] per-position grad contributions the
    backward `.at[inverse].add` expands before segment-summing. The fused
    model (`fused=True`) keeps only the irreducible stream: ids in, unique
    rows DMA'd once, bags out, grads in, unique value/slot rows
    read-modify-written once — the [U, D] and [N, D] intermediates live
    and die in VMEM.
    """
    N, B, U, D = positions, batch, unique, dim
    vb, kb = value_bytes, key_bytes
    slot_b = sum(w * 4 for w in slot_widths)

    if not fused:
        hbm = 2 * kb * N  # dedup: key gather + claim scatter over N lanes
        hbm += U * D * vb  # unique row gather (read)
        hbm += 2 * U * D * vb  # [U, D] emb_u round-trip (write, re-read)
        hbm += N * D * vb  # combine: emb_u[inverse] expands to [N, D]
        hbm += B * D * 4  # combined bags out (f32)
        hbm += B * D * 4  # backward: bag grads in (f32)
        hbm += N * D * 4  # per-position grad contribs expand to [N, D]
        hbm += 2 * U * D * 4  # [U, D] grad_u round-trip (scatter, re-read)
        hbm += 2 * U * D * vb  # apply: value row gather + scatter
        hbm += 2 * slot_b * U  # apply: slot gather + scatter
    else:
        hbm = kb * N  # forward reads the id stream once; probe is in VMEM
        hbm += U * D * vb  # unique rows DMA'd HBM -> VMEM once
        hbm += B * D * 4  # combined bags out (f32)
        hbm += B * D * 4  # backward: bag grads in (f32)
        hbm += kb * N  # backward re-reads ids/inverse
        hbm += 2 * U * D * vb  # value rows: DMA in + updated DMA out
        hbm += 2 * slot_b * U  # slot rows: DMA in + out
        if vb == 2:
            hbm += U * D * 4  # row-keyed SR bits (u32) for bf16 tables
    return {"hbm_bytes": float(hbm)}


def dlrm_reference_traffic(
    *,
    batch: int = 2048,
    num_tables: int = 26,
    dim: int = 16,
    unique_fraction: float = 1.0,
    slot_widths: Sequence[int] = (16,),
    diet: bool = True,
    num_shards: int = 1,
    comm: Optional[str] = None,
    exchange_dtype: str = "float32",
    pipeline_mode: str = "off",
) -> Dict[str, float]:
    """Whole-model per-step traffic at the reference DLRM shape (26 single-
    hot features, dim 16, Adagrad).  `unique_fraction` scales the per-table
    touched rows (the dedup budget); sharded shapes split the batch across
    devices and add the exchange term.  `pipeline_mode != "off"` adds the
    lookahead's double-buffer residency under "pipeline_buffer_bytes"
    (per-step traffic itself is unchanged by pipelining — same ops,
    reordered)."""
    wire_bytes = 2 if exchange_dtype == "bfloat16" else 4
    local_batch = batch // max(num_shards, 1)
    U = max(1, int(round(local_batch * unique_fraction)))
    per_table = table_step_traffic(
        unique=U, dim=dim, slot_widths=slot_widths, diet=diet,
        num_shards=num_shards, comm=comm, wire_bytes=wire_bytes,
    )
    out = {k: v * num_tables for k, v in per_table.items()}
    out["pipeline_buffer_bytes"] = num_tables * pipeline_buffer_bytes(
        unique=U, dim=dim, positions=local_batch, num_shards=num_shards,
        comm=comm, pipeline_mode=pipeline_mode,
    )
    return out


# ------------------------------------------------------ serving residency


def serving_residency_bytes(
    *, capacity: int, dim: int, value_dtype: str = "float32",
) -> float:
    """Resident HBM bytes of ONE serving table's value storage at a given
    residency dtype — the quantity `Predictor(quantize=...)` halves/quarters
    and `roofline.py --assert-serving` pins against the measured arrays:

      float32  : C * D * 4
      bfloat16 : C * D * 2
      int8     : C * D * 1  +  C * 4   (per-row fp32 dequant scale)

    Keys/meta are excluded (identical across residencies — the comparison
    is about the value rows, the term that scales with dim). The packed
    small-dim layout is byte-neutral ([C//P, P*D] holds the same C*D
    elements), so the model needs no layout arm."""
    vb = {"float32": 4, "bfloat16": 2, "int8": 1}
    if value_dtype not in vb:
        raise ValueError(f"unknown residency dtype {value_dtype!r}")
    b = float(capacity) * float(dim) * vb[value_dtype]
    if value_dtype == "int8":
        b += float(capacity) * 4  # per-row fp32 scale (TableState.qscale)
    return float(b)


# ------------------------------------------------------- retrieval sweep


def retrieval_sweep_bytes(
    *, corpus_rows: int, dim: int, value_dtype: str = "int8",
    block_rows: int = 4096,
) -> float:
    """HBM bytes ONE full-corpus retrieval sweep reads
    (serving/retrieval.py + ops/topk.py): the resident item matrix at
    its storage dtype, the per-row dequant scale (int8 residency only),
    and the validity mask. `corpus_rows` is the POW2-PADDED resident
    capacity (a multiple of `block_rows` — the blocked sweep reads whole
    blocks, padding included; padding rows score -inf and cost their
    bytes, which is why the engine keeps the block count pow2-tight).

      float32  : C * D * 4  +  C        (values + valid mask)
      bfloat16 : C * D * 2  +  C
      int8     : C * D * 1  +  C * 4  +  C   (+ per-row fp32 scale)

    The [B, k] top-k carry and the per-block score tile live on-chip and
    are excluded — the sweep's defining property is that the full [C]
    score vector never touches HBM. `RetrievalEngine.sweep_info()`
    measures the same quantity off the actual device arrays and
    `roofline.py --assert-retrieval` pins measured == modeled (shape
    math, not an estimate — the serving-residency discipline)."""
    vb = {"float32": 4, "bfloat16": 2, "int8": 1}
    if value_dtype not in vb:
        raise ValueError(f"unknown residency dtype {value_dtype!r}")
    if block_rows <= 0 or corpus_rows % block_rows:
        raise ValueError(
            f"corpus_rows {corpus_rows} must be a positive multiple of "
            f"block_rows {block_rows}")
    b = float(corpus_rows) * float(dim) * vb[value_dtype]
    if value_dtype == "int8":
        b += float(corpus_rows) * 4  # per-row fp32 dequant scale
    b += float(corpus_rows)  # validity mask (1 byte/row)
    return float(b)


# ------------------------------------------------------- compute reuse


def serving_reuse_speedup(
    *, hit_rate: float, hit_cost_ratio: float = 0.0,
) -> float:
    """Modeled effective-qps factor of the serving compute-reuse layer
    (serving/reuse.py) at a given answer-cache hit rate, closed-loop:

        speedup = 1 / (1 - h + h * c)

    where ``h`` is the hit rate and ``c`` the cost of serving a hit
    relative to a full evaluation (fingerprint + dict lookup vs a device
    dispatch; ~0 for the answer cache, larger for the user-tower cache
    where the candidate-only lane still runs the item tower). Amdahl on
    the per-request serial cost: at h=0.5, c=0 the tier answers 2x the
    requests per second from the same compute — the ROADMAP's >=2x
    target IS this curve at the zipf-population hit rate.

    `tools/bench_serving.py compute_reuse` records the measured factor
    next to this model and `roofline.py --assert-reuse` gates the
    measured one; the model is the capacity-planning knob (what hit rate
    does a target speedup need?)."""
    h = float(hit_rate)
    c = float(hit_cost_ratio)
    if not 0.0 <= h <= 1.0:
        raise ValueError(f"hit_rate must be in [0, 1], got {h}")
    if c < 0.0:
        raise ValueError(f"hit_cost_ratio must be >= 0, got {c}")
    denom = (1.0 - h) + h * c
    if denom <= 0.0:
        raise ValueError("hit_rate 1.0 with zero hit cost: infinite model")
    return 1.0 / denom


def reuse_hit_rate_for_speedup(
    *, speedup: float, hit_cost_ratio: float = 0.0,
) -> float:
    """Inverse of `serving_reuse_speedup`: the answer-cache hit rate a
    target effective-qps factor requires (capacity planning: size the
    cache/population so the zipf head clears this rate)."""
    s = float(speedup)
    c = float(hit_cost_ratio)
    if s < 1.0:
        raise ValueError(f"speedup must be >= 1, got {s}")
    if c >= 1.0:
        raise ValueError(f"hit_cost_ratio must be < 1, got {c}")
    return (1.0 - 1.0 / s) / (1.0 - c)


def zipf_expected_hit_rate(*, users: int, alpha: float,
                           resident: int) -> float:
    """Expected answer-cache hit rate for a zipf(alpha) population of
    `users` distinct request keys with the hottest `resident` keys
    cached (steady state, capacity >= resident): the probability mass of
    the resident head,

        sum_{r<resident} r^-alpha / sum_{r<users} r^-alpha.

    The shape `bench_serving --user-zipf A --users N` drives; recorded
    beside the measured hit rate so the bench can show the LRU converges
    on the head."""
    if users < 1 or resident < 0:
        raise ValueError(f"bad population users={users} resident={resident}")
    ranks = [float(r + 1) ** (-float(alpha)) for r in range(int(users))]  # noqa: DRT002 — host-side analytic model, no device values
    total = sum(ranks)
    return sum(ranks[: min(int(resident), int(users))]) / total


# ---------------------------------------------------------- pipelining model


def pipeline_buffer_bytes(
    *,
    unique: int,
    dim: int,
    positions: Optional[int] = None,
    value_bytes: int = 4,
    key_bytes: int = 4,
    num_shards: int = 1,
    comm: Optional[str] = None,
    pipeline_mode: str = "lookahead",
) -> float:
    """Extra RESIDENT bytes per table of the one-batch lookahead
    (`pipeline_mode != "off"`): the pipelined K-step scan double-buffers
    one in-flight lookup — the carried batch's finished embedding buffer,
    its routing arrays and the owner-side residual live alongside the
    current step's. This is capacity, not per-step traffic: the per-step
    byte totals of `table_step_traffic` are unchanged by pipelining (the
    same ops run, reordered), which is why `roofline.py --assert-traffic`
    needs no pipeline-mode arms — this function accounts the HBM headroom
    the lookahead costs instead.

    `positions` is the flattened id-position count of the batch (B·L per
    table); the carried inverse/mask/batch-ids are batch-shaped, not
    unique-shaped, so under a dedup budget (U < positions) they dominate
    the int side of the carry. Defaults to `unique` (the no-dedup U = N
    case)."""
    if pipeline_mode == "off":
        return 0.0
    U, D = unique, dim
    pos = unique if positions is None else int(positions)
    b = U * key_bytes  # carried uids
    b += U * 4  # counts
    b += pos * 4  # inverse (batch-shaped [B, L])
    b += pos * key_bytes  # the prefetched batch's ids themselves
    b += pos * 1  # per-position mask in the carried views
    b += U * D * value_bytes  # finished local embedding buffer
    b += U * D * value_bytes  # owner-side residual rows (reuse_rows diet)
    if num_shards > 1 and comm == "a2a":
        b += U * 4  # send_slot routing metadata
    return float(b)


def modeled_overlap_step(
    *,
    dense_ms: float,
    route_ms: float,
    other_ms: float,
    mode: str = "off",
    chunks: int = 1,
) -> float:
    """Modeled step time (ms) under the in-step pipelining schedule.

    `route_ms` is the hoistable half of the lookup — id dedup + id
    exchange + owner probe/metadata (everything the pipelined scan issues
    ahead of the dense compute); `dense_ms` the dense fwd/bwd it hides
    behind; `other_ms` everything that stays serial (value gather +
    embedding exchange, grad exchange, sparse apply, dense update).

      off:       dense + route + other           (strictly sequential)
      lookahead: max(dense, route) + other       (route hidden behind dense)
      chunked:   like lookahead, with the serial half's EXCHANGE portion
                 internally pipelined — the model conservatively keeps
                 other_ms whole (it cannot split gather from wire without
                 a trace), so chunked == lookahead here; the measured
                 difference only exists on sharded exchanges
                 (tools/bench_async.py --pipeline-mode chunked on a mesh).

    `roofline.py --assert-overlap` compares this against the measured
    pipelined step and gates CI on the ratio (overlap efficiency)."""
    dense_ms = max(0.0, float(dense_ms))
    route_ms = max(0.0, float(route_ms))
    other_ms = max(0.0, float(other_ms))
    if mode == "off":
        return dense_ms + route_ms + other_ms
    return max(dense_ms, route_ms) + other_ms


# ------------------------------------------------------------ op-count model


def count_stablehlo_ops(text: str) -> Dict[str, int]:
    """Count gather/scatter ops in a StableHLO module (the output of
    `jax.jit(fn).lower(*args).as_text()`).  Collectives (all_gather etc.)
    spell differently and are not counted."""
    return {
        "gather": len(re.findall(r'"stablehlo\.gather"|stablehlo\.gather\b', text)),
        "scatter": len(re.findall(r'"stablehlo\.scatter"|stablehlo\.scatter\b', text)),
    }


def expected_lookup_apply_ops(
    *,
    diet: bool = True,
    budgeted: bool = True,
    n_row_slots: int = 1,
) -> Dict[str, int]:
    """Expected stablehlo gather/scatter counts for the single-table TRAIN
    `lookup_unique` + `apply_gradients` program (no sharding, no admission
    filter, one per-row optimizer slot unless overridden).

    Base constants are CALIBRATED against the lowered program (jax 0.4.37;
    the extra ops over a hand inventory come from jnp.unique / hash-dedup
    internals and clip/where index lowering).  The diet deltas are the
    structural facts this PR is about and what the CI assertion guards:

      * non-diet adds 4 scatters — the forward's separate freq/version/
        dirty trio plus the apply-side version/dirty re-stamp collapse
        into ONE fused meta scatter under the diet (5 -> 1);
      * the gather count is net-unchanged — the apply-side value re-gather
        the diet removes is replaced by the fused [3, U] meta gather the
        forward adds (which also absorbed the admission freq read).

    `tools/roofline.py --assert-traffic` compares this against the counts
    `bench.py` measures off the actually-lowered program, so any change to
    the engine's op mix must be reflected here (that is the point).
    """
    if budgeted:  # hash dedup engine front-end (ops/dedup.py)
        counts = {"gather": 20, "scatter": 14}
    else:  # legacy sort-based jnp.unique front-end
        counts = {"gather": 14, "scatter": 18}
    if not diet:
        counts["scatter"] += 4
    extra_slots = n_row_slots - 1
    counts["gather"] += extra_slots
    counts["scatter"] += extra_slots
    return counts
