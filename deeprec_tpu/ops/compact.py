"""Scatter-free masked-row compaction at a static budget.

The prefix-sum / searchsorted index compaction PR 2 built for the dedup
engine (ops/dedup.py: occupied scratch slots -> dense ranks) is the
general device-side primitive for "collect the rows where mask is True
without a sort and without a data-dependent shape". This module hoists it
out so the incremental-checkpoint exporter (training/checkpoint.py) and
the multi-tier migration extractor (embedding/multi_tier.py) can compact
dirty/demotable rows ON DEVICE — the device->host transfer then scales
with the selected fraction, not the table capacity, which is the whole
point of taking checkpoint/migration traffic off the training stall path.

Contract:

  * `size` is STATIC. `rank_compact(mask, size)` returns the indices of
    the first `size` True positions of `mask` in ASCENDING index order
    (-1 padding past the count) — the same ordering `np.nonzero` gives the
    legacy host-side exporter, so compacted exports are byte-identical to
    the host-masked ones after truncation.
  * Everything is cumsum + searchsorted + gathers: scatter is the
    expensive primitive on every backend (measured ~50x a gather on CPU,
    ops/dedup.py), and none is needed.
  * `quantize_rows` buckets a measured count to a power of two so drift
    in the dirty fraction re-traces at most log2(C) times per table, the
    same never-recompile posture as the dedup budget grid.
"""
from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp


def next_pow2(n: int) -> int:
    return 1 << max(0, int(n) - 1).bit_length()


def quantize_rows(n: int, capacity: int, floor: int = 64) -> int:
    """Static row budget for a measured count `n`: next power of two, at
    least `floor` (tiny exports share one executable), never beyond
    `capacity` (a full table needs no padding)."""
    e = max(next_pow2(max(int(n), 1)), floor)
    return min(e, int(capacity)) if capacity else e


def rank_compact(
    mask: jnp.ndarray, size: int
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Dense indices of `mask`'s True positions, at static length `size`.

    Returns `(idx [size] int32, n [] int32, rank [C] int32)`:
      * `idx[j]` is the index of the (j+1)-th True position (ascending),
        -1 once j >= n; positions past `size` are silently truncated —
        size the budget from a count read when that matters.
      * `n` is the total True count (NOT clipped to `size`).
      * `rank` is the inclusive prefix sum (`rank[i]` = number of True
        positions at or before i) — callers that need the inverse map
        (ops/dedup.py ranks its scratch slots with it) reuse it for free.
    """
    rank = jnp.cumsum(mask.astype(jnp.int32))
    n = rank[-1]
    j = jnp.arange(1, size + 1, dtype=jnp.int32)
    sel = jnp.searchsorted(rank, j, side="left").astype(jnp.int32)
    idx = jnp.where(j <= n, sel, -1)
    return idx, n, rank
