from deeprec_tpu.ops.dedup import hash_dedup, resolve_size, sort_unique
from deeprec_tpu.ops.flash_attention import attention_reference, flash_attention
from deeprec_tpu.ops.fused_lookup import (
    apply_rows_sr,
    fused_gather_combine,
    gather_rows,
    stochastic_round,
)
