"""Hash-based device dedup at a static unique budget.

`jnp.unique(size=U)` is sort-based: O(N log N) compare/exchange passes over
the full flattened batch, and with the default U = N every downstream op —
probe, embedding gather, freq/version/dirty scatters, `_init_rows`, the
backward segment-sum — runs at batch size rather than unique-id size. On
zipf-skewed recsys batches that is a multi-x waste (docs/perf.md charges
~25% of the CPU step to "probe bookkeeping, unique, combiners").

This module replaces the sort with the same vectorized open-addressing
claim-race probe the embedding table already uses for its own slots
(`EmbeddingTable._probe`): every position gathers its scratch-slot
candidate, first-comers claim empty slots via a batched scatter, losers of
a claim race advance one probe offset. The loop is a `lax.while_loop` of
pure gathers/scatters — O(N · expected-probes) with expected-probes ~1-2
at the <=50% scratch load the sizing below guarantees. No sort anywhere.

Budget contract (`hash_dedup`):

  * `size` is STATIC — the returned arrays are `uids [size]`,
    `counts [size]`, plus `inverse [N]` and a scalar `overflow`.
  * `uids[0]` is RESERVED for the sentinel: padding positions and ids that
    did not win a budget slot point their `inverse` at 0, where
    `valid=False` downstream serves the admission-blocked default and the
    gradient mask drops their update — exactly the per-step degradation
    contract of the budgeted all2all (`ShardedTable`, `a2a_overflow`). At
    most `size - 1` real unique ids fit.
  * `overflow` counts the distinct ids compacted out past the budget plus
    any positions whose probe never resolved (near-impossible at the
    default scratch sizing) — the same transient-counter contract as
    `insert_fails` / `a2a_overflow`; consume it at host cadence
    (`Trainer.update_budgets`) to widen the budget.

Everything is shape-static and built from vmap/scan-safe primitives, so it
runs unchanged inside the stacked-bundle `vmap`, the K-step `lax.scan`
dispatch loop and `shard_map`.
"""
from __future__ import annotations

import logging
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from deeprec_tpu.utils import hashing

logger = logging.getLogger("deeprec_tpu.dedup")

# Tables that already logged the U=N fallback (log once per table name).
_logged_full_fallback: set = set()


def next_pow2(n: int) -> int:
    return 1 << max(0, int(n) - 1).bit_length()


def _mult8(n: int) -> int:
    return max(8, ((int(n) + 7) // 8) * 8)


def resolve_size(budget: int, n: int) -> int:
    """uids-array size for a requested budget of `budget` real ids over a
    flattened batch of `n` positions: +1 for the reserved sentinel slot,
    rounded up to a VPU-friendly multiple of 8, and never beyond the
    no-overflow size (which is `n` real ids + the sentinel slot)."""
    full = _mult8(n + 1)
    return min(_mult8(max(int(budget), 1) + 1), full)


def log_full_fallback(name: str, n: int) -> None:
    """Record (once per table) that a lookup fell back to U = N — the
    full-batch sort-unique whose downstream waste the budget exists to cut.
    Visible so the silent default never hides the cost again."""
    if name in _logged_full_fallback:
        return
    _logged_full_fallback.add(name)
    try:  # same counter family as the Pallas dispatch rejections
        from deeprec_tpu.obs.metrics import default_registry

        default_registry().counter(
            "deeprec_pallas_fallback",
            help="Pallas kernel dispatches that fell back to XLA, by cause",
            labels={"kernel": "dedup", "reason": "no_budget"},
        ).inc()
    except Exception:  # obs must never break the lookup path
        pass
    logger.info(
        "table %s: no unique budget resolved — dedup falls back to U=N=%d "
        "(sort-based, every downstream op at batch size). Set "
        "TableConfig.unique_budget / SparseFeature.unique_budget or "
        "Trainer(unique_budget=...) to engage the hash dedup engine.",
        name, n,
    )


def scratch_size(n: int) -> int:
    """Scratch-table size for an N-position dedup: the next power of two
    >= 4·(N+1), so even an all-distinct batch loads the table at <=25% and
    linear-probe chains stay short. The loop cost is per-ITERATION (one
    claim scatter over all N lanes — the dominant primitive on every
    backend), so a wider scratch that removes one probe round pays for its
    extra int32 rows many times over (measured: 5 -> 4 rounds at N=53k)."""
    return next_pow2(4 * (int(n) + 1))


def hash_dedup(
    flat: jnp.ndarray,
    size: int,
    *,
    sentinel,
    weights: Optional[jnp.ndarray] = None,
    max_probes: int = 64,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Deduplicate `flat` [N] into at most `size - 1` unique ids, O(N).

    Args:
      flat: [N] ids with padding already collapsed onto `sentinel`.
      size: static length of the returned unique arrays; index 0 is the
        reserved sentinel bucket (see module docstring).
      sentinel: the reserved never-a-real-id key (python int or scalar).
      weights: optional [N] int per-position weights for `counts`
        (default 1 each — occurrence counts). Sentinel positions never
        contribute.
      max_probes: probe-chain bound; unresolved positions count as
        overflow.

    Returns `(uids [size], inverse [N] int32, counts [size] int32,
    overflow [] int32)` where `uids[inverse]` reconstructs every budgeted
    position and `inverse == 0` marks padding/overflow positions.
    """
    N = flat.shape[0]
    sent = jnp.asarray(sentinel, flat.dtype)
    S = scratch_size(N)
    mask_s = jnp.uint32(S - 1)
    h = hashing.mix32(hashing.fold64(flat))
    valid = flat != sent

    scratch0 = jnp.full((S,), sent, flat.dtype)
    slot0 = jnp.full((N,), -1, jnp.int32)

    def cond(carry):
        step, pending, *_ = carry
        return jnp.logical_and(step < max_probes, jnp.any(pending))

    def body(carry):
        step, pending, slot, scratch = carry
        pos = ((h + jnp.uint32(step)) & mask_s).astype(jnp.int32)  # [N]
        k = scratch[pos]
        hit = pending & (k == flat)
        slot = jnp.where(hit, pos, slot)
        pending = pending & ~hit
        # Claim race on empty scratch slots: scatter all claimants, the
        # re-gather reveals the one winner; losers advance a probe offset.
        # (The fused step kernel — ops/fused_lookup.fused_sparse_forward —
        # replaces this whole O(N)-lane scatter round with a sequential
        # in-VMEM slot write per id, so the ~50x-a-gather cost below never
        # appears on the fused path.)
        want = pending & (k == sent)
        claim_pos = jnp.where(want, pos, S)  # S = out of bounds -> dropped
        scratch = scratch.at[claim_pos].set(flat, mode="drop")
        won = want & (scratch[pos] == flat)
        slot = jnp.where(won, pos, slot)
        pending = pending & ~won
        return step + 1, pending, slot, scratch

    _, failed, slot, scratch = jax.lax.while_loop(
        cond, body, (jnp.int32(0), valid, slot0, scratch0)
    )

    # Budget compaction: the j-th occupied scratch slot (slot order) takes
    # dense index j in 1..size-1; the rest compact out as overflow.
    # Deliberately scatter-free — the shared prefix-sum + searchsorted
    # compaction (ops/compact.py, also behind the incremental-checkpoint
    # dirty export) — because scatter is the expensive primitive here (an
    # [S]-lane scatter measured ~50x a gather on CPU); the one remaining
    # scatter is the [N]-lane counts segment-add.
    from deeprec_tpu.ops.compact import rank_compact

    occ = scratch != sent  # [S]
    sel, n_occ, rank = rank_compact(occ, size - 1)
    uids_tail = jnp.where(
        sel >= 0, scratch.at[sel].get(mode="clip"), sent
    )
    uids = jnp.concatenate([jnp.full((1,), sent, flat.dtype), uids_tail])

    pos_ok = valid & (slot >= 0)
    r = rank.at[jnp.where(pos_ok, slot, 0)].get(mode="clip")  # lane's rank
    budgeted = pos_ok & (r < size)
    inverse = jnp.where(budgeted, r, 0).astype(jnp.int32)

    w = (
        jnp.ones((N,), jnp.int32)
        if weights is None
        else weights.astype(jnp.int32)
    )
    counts = (
        jnp.zeros((size,), jnp.int32)
        .at[jnp.where(budgeted, inverse, size)]
        .add(w, mode="drop")
    )
    overflow = (
        jnp.maximum(n_occ - jnp.int32(size - 1), 0) + jnp.sum(failed)
    ).astype(jnp.int32)
    return uids, inverse, counts, overflow


def route_ids(
    ids: jnp.ndarray,
    *,
    pad_value,
    sentinel,
    unique_size: Optional[int] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray, Optional[jnp.ndarray]]:
    """The apply-independent ROUTING half of a lookup: flatten, collapse
    padding onto the sentinel, dedup (hash engine at `unique_size`, legacy
    sort at None). A pure function of the id batch — it reads NO table
    state — which is what lets the pipelined trainers hoist it (and, for
    sharded tables, the id exchange built on it) a full step ahead of the
    tables it will hit (docs/perf.md "in-step pipelining").

    Returns `(uids [U], inverse [ids.shape], counts [U], valid [U],
    overflow)` — overflow is None on the legacy sort path, a scalar int32
    under a budget. Shared by the single-table lookup front-end
    (`EmbeddingTable._route_ids`) and both sharded exchange paths
    (`ShardedTable.route`), which used to duplicate it.
    """
    flat = ids.reshape(-1)
    sent = jnp.asarray(sentinel, flat.dtype)
    flat = jnp.where(flat == jnp.asarray(pad_value, flat.dtype), sent, flat)
    if unique_size is None:
        uids, inverse, counts = sort_unique(
            flat, flat.shape[0], sentinel=sentinel
        )
        overflow = None
    else:
        uids, inverse, counts, overflow = hash_dedup(
            flat, unique_size, sentinel=sentinel
        )
    valid = uids != sent
    return uids, inverse.reshape(ids.shape), counts, valid, overflow


def sort_unique(
    flat: jnp.ndarray, size: int, *, sentinel
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """The legacy sort-based dedup (`jnp.unique` at a static size) with the
    table's sentinel/counts conventions — kept as the U=N fallback and as
    the reference curve for `tools/bench_dedup.py`. Note its budget
    semantics are WEAKER than `hash_dedup`: past-`size` uniques are
    silently truncated with an undefined inverse, which is why the hash
    engine (defined overflow) is the one budgets route through."""
    sent = jnp.asarray(sentinel, flat.dtype)
    uids, inverse, counts = jnp.unique(
        flat, size=size, fill_value=sent, return_inverse=True,
        return_counts=True,
    )
    valid = uids != sent
    counts = jnp.where(valid, counts, 0).astype(jnp.int32)
    return uids, inverse.astype(jnp.int32), counts


def auto_budget_fraction(ema_fraction: float, *, slack: float = 1.5,
                         grid: int = 16) -> float:
    """Quantize an EMA'd measured unique fraction into the budget grid:
    apply the safety slack, then round UP to the next 1/`grid` bucket so
    step-to-step EMA drift inside a bucket never recompiles the step."""
    f = min(1.0, max(0.0, ema_fraction) * slack)
    return min(1.0, math.ceil(f * grid - 1e-9) / grid)
