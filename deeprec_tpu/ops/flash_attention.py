"""Flash attention: Pallas TPU forward + memory-efficient blockwise backward.

Long-context support is first-class in this framework (BST/SIM-style long
behavior histories; DeepRec itself has no attention sharding — SURVEY.md §5).
The forward pass is a classic online-softmax Pallas kernel: Q blocks stream
from HBM to VMEM, K/V blocks iterate in-kernel, running (max, denom, acc)
carry the softmax — O(L·block) VMEM instead of the O(L²) score matrix. The
backward is Pallas too (flash-2 structure, exact gradients from the saved
LSE): a dK/dV kernel where each K/V block accumulates over streamed Q
blocks in VMEM scratch, and a dQ kernel with the forward's access pattern —
no atomics, no [L, S] materialization, causal blocks skipped on both sides
of the diagonal.

On non-TPU backends the kernels run in interpreter mode (tests) or fall
back to a blockwise lax.scan implementation with the same memory shape.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = -1e30


def _use_pallas() -> bool:
    return jax.default_backend() == "tpu"


# ------------------------------------------------------------ reference impl


def attention_reference(q, k, v, mask=None, causal=False, sm_scale=None):
    """Plain jnp attention (oracle + CPU fallback). q,k,v: [B, H, L, D]."""
    B, H, Lq, D = q.shape
    S = k.shape[2]
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(D)
    logits = jnp.einsum("bhld,bhsd->bhls", q, k) * scale
    if mask is not None:
        logits = jnp.where(mask[:, None, None, :], logits, NEG_INF)
    if causal:
        qi = jax.lax.broadcasted_iota(jnp.int32, (Lq, S), 0)
        ki = jax.lax.broadcasted_iota(jnp.int32, (Lq, S), 1)
        logits = jnp.where((ki <= qi)[None, None], logits, NEG_INF)
    p = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhls,bhsd->bhld", p, v)


# ------------------------------------------------------------- pallas forward


def _masked_scores(q, k, mk, qb, kb, block_q, block_k, sm_scale, causal):
    """Scaled QK^T with padding + causal masking — the one definition all
    three kernels (fwd, dKdV, dQ) share; a drift here would silently
    desynchronize forward and backward. Inlines at trace time.
    q [block_q, D] f32, k [block_k, D] f32, mk [block_k] int; qb/kb are
    the Q/K *block* indices."""
    s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * sm_scale
    s = jnp.where(mk[None, :] > 0, s, NEG_INF)
    if causal:
        qpos = qb * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        kpos = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        s = jnp.where(kpos <= qpos, s, NEG_INF)
    return s


def _ds_from_p(p, do, v, delta, sm_scale):
    """dS = P ∘ (dO·Vᵀ − Δ)·scale — shared by both backward kernels."""
    dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
    return p * (dp - delta[:, None]) * sm_scale


def _probs_from_lse(s, lse):
    """exp(s − LSE) with the dead-row guard: a row whose visible keys are
    ALL masked stores lse ≈ NEG_INF, and exp(NEG_INF − NEG_INF) = 1 would
    broadcast garbage into dk/dv/dq — such rows attend to nothing, so
    their probabilities are exactly zero. Shared by every backward path."""
    dead = lse <= NEG_INF * 0.5
    return jnp.where(dead[..., None], 0.0, jnp.exp(s - lse[..., None]))


def _fa_fwd_kernel(
    q_ref, k_ref, v_ref, mask_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr, *,
    block_k: int, sm_scale: float, causal: bool, block_q: int, num_kb: int,
):
    """Grid = (BH, Lq/block_q, S/block_k); only ONE K/V block is resident in
    VMEM per step (O(block) memory), the (m, l, acc) running softmax lives in
    scratch that persists across the sequential K-block grid steps."""
    from jax.experimental import pallas as pl

    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    qb = pl.program_id(1)
    # Causal: K blocks fully above the diagonal contribute nothing — skip
    # their compute (~2x FLOPs saved on long sequences).
    diag_reached = (kb * block_k) <= (qb + 1) * block_q - 1
    run = diag_reached if causal else (kb >= 0)

    @pl.when(run)
    def _step():
        q = q_ref[0].astype(jnp.float32)  # [block_q, D]
        k = k_ref[0].astype(jnp.float32)  # [block_k, D]
        v = v_ref[0].astype(jnp.float32)
        mk = mask_ref[0]  # [block_k]
        s = _masked_scores(q, k, mk, qb, kb, block_q, block_k, sm_scale,
                           causal)
        m = m_scr[:]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        m_scr[:] = m_new
        l_scr[:] = l_scr[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jnp.dot(
            p, v, preferred_element_type=jnp.float32
        )

    @pl.when(kb == num_kb - 1)
    def _finish():
        l_safe = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0] = (m_scr[:, 0] + jnp.log(l_safe[:, 0])).astype(jnp.float32)


def _pallas_forward(q, k, v, mask, causal, sm_scale, block_q, block_k, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, Lq, D = q.shape
    S = k.shape[2]
    BH = B * H
    qr = q.reshape(BH, Lq, D)
    kr = k.reshape(BH, S, D)
    vr = v.reshape(BH, S, D)
    maskr = jnp.repeat(mask.astype(jnp.int32), H, axis=0)  # [BH, S]

    num_kb = S // block_k
    grid = (BH, Lq // block_q, num_kb)
    kernel = functools.partial(
        _fa_fwd_kernel, block_k=block_k, sm_scale=sm_scale, causal=causal,
        block_q=block_q, num_kb=num_kb,
    )
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, kb: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, kb: (b, kb, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, kb: (b, kb, 0)),
            pl.BlockSpec((1, block_k), lambda b, i, kb: (b, kb)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, kb: (b, i, 0)),
            pl.BlockSpec((1, block_q), lambda b, i, kb: (b, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Lq, D), q.dtype),
            jax.ShapeDtypeStruct((BH, Lq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        interpret=interpret,
    )(qr, kr, vr, maskr)
    return o.reshape(B, H, Lq, D), lse.reshape(B, H, Lq)


# --------------------------------------------------- blockwise jnp fwd (lse)


def _blockwise_forward(q, k, v, mask, causal, sm_scale, block_k):
    """Same math as the kernel, in scanned jnp — used on non-TPU backends and
    as the recompute inside the backward."""
    B, H, Lq, D = q.shape
    S = k.shape[2]
    nb = S // block_k
    qpos = jax.lax.broadcasted_iota(jnp.int32, (Lq, block_k), 0)

    def body(carry, kb):
        m, l, acc = carry
        ks = jax.lax.dynamic_slice_in_dim(k, kb * block_k, block_k, axis=2)
        vs = jax.lax.dynamic_slice_in_dim(v, kb * block_k, block_k, axis=2)
        mk = jax.lax.dynamic_slice_in_dim(mask, kb * block_k, block_k, axis=1)
        s = jnp.einsum("bhld,bhsd->bhls", q, ks) * sm_scale
        s = jnp.where(mk[:, None, None, :], s, NEG_INF)
        if causal:
            kpos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (Lq, block_k), 1
            )
            s = jnp.where((kpos <= qpos)[None, None], s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * corr + jnp.einsum("bhls,bhsd->bhld", p, vs)
        return (m_new, l, acc), None

    m0 = jnp.full((B, H, Lq, 1), NEG_INF, jnp.float32)  # noqa: DRT003 — keepdims accumulator for the scan's broadcast; one padded sublane, Pallas path owns the real layout
    l0 = jnp.zeros((B, H, Lq, 1), jnp.float32)  # noqa: DRT003 — keepdims accumulator, same contract as m0 above
    a0 = jnp.zeros((B, H, Lq, D), jnp.float32)
    (m, l, acc), _ = jax.lax.scan(body, (m0, l0, a0), jnp.arange(nb))
    l_safe = jnp.maximum(l, 1e-30)
    o = (acc / l_safe).astype(q.dtype)
    lse = m[..., 0] + jnp.log(l_safe[..., 0])
    return o, lse


# ---------------------------------------------------------- pallas backward


def _fa_bwd_dkdv_kernel(
    q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref, delta_ref,
    dk_ref, dv_ref, dk_scr, dv_scr, *,
    block_q: int, block_k: int, sm_scale: float, causal: bool, num_qb: int,
):
    """dK/dV: grid = (BH, S/block_k, Lq/block_q). One K/V block owns the
    kernel instance; Q blocks stream through the sequential minor grid
    axis, accumulating dk/dv in VMEM scratch (flash-2 structure: no
    atomics, no [L, S] materialization)."""
    from jax.experimental import pallas as pl

    qb = pl.program_id(2)
    kb = pl.program_id(1)

    @pl.when(qb == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    # Causal: Q blocks entirely above this K block's diagonal see none of
    # it — skip their compute (the backward mirror of the forward skip).
    diag_reached = (kb * block_k) <= ((qb + 1) * block_q - 1)
    run = diag_reached if causal else (qb >= 0)

    @pl.when(run)
    def _step():
        q = q_ref[0].astype(jnp.float32)       # [block_q, D]
        k = k_ref[0].astype(jnp.float32)       # [block_k, D]
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)     # [block_q, D]
        lse = lse_ref[0].astype(jnp.float32)   # [block_q]
        delta = delta_ref[0].astype(jnp.float32)
        mk = mask_ref[0]                       # [block_k]
        s = _masked_scores(q, k, mk, qb, kb, block_q, block_k, sm_scale,
                           causal)
        p = _probs_from_lse(s, lse)            # exact probs from saved LSE
        dv_scr[:] += jnp.dot(p.T, do, preferred_element_type=jnp.float32)
        ds = _ds_from_p(p, do, v, delta, sm_scale)
        dk_scr[:] += jnp.dot(ds.T, q, preferred_element_type=jnp.float32)

    @pl.when(qb == num_qb - 1)
    def _finish():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _fa_bwd_dq_kernel(
    q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref, delta_ref,
    dq_ref, dq_scr, *,
    block_q: int, block_k: int, sm_scale: float, causal: bool, num_kb: int,
):
    """dQ: grid = (BH, Lq/block_q, S/block_k), accumulating over K blocks
    in scratch — the forward kernel's access pattern with ds in place of p."""
    from jax.experimental import pallas as pl

    kb = pl.program_id(2)
    qb = pl.program_id(1)

    @pl.when(kb == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    diag_reached = (kb * block_k) <= (qb + 1) * block_q - 1
    run = diag_reached if causal else (kb >= 0)

    @pl.when(run)
    def _step():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0].astype(jnp.float32)
        delta = delta_ref[0].astype(jnp.float32)
        mk = mask_ref[0]
        s = _masked_scores(q, k, mk, qb, kb, block_q, block_k, sm_scale,
                           causal)
        p = _probs_from_lse(s, lse)
        ds = _ds_from_p(p, do, v, delta, sm_scale)
        dq_scr[:] += jnp.dot(ds, k, preferred_element_type=jnp.float32)

    @pl.when(kb == num_kb - 1)
    def _finish():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _pallas_backward(q, k, v, mask, causal, sm_scale, block_q, block_k,
                     o, lse, do, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, Lq, D = q.shape
    S = k.shape[2]
    BH = B * H
    qr = q.reshape(BH, Lq, D)
    kr = k.reshape(BH, S, D)
    vr = v.reshape(BH, S, D)
    dor = do.reshape(BH, Lq, D)
    lser = lse.reshape(BH, Lq)
    maskr = jnp.repeat(mask.astype(jnp.int32), H, axis=0)  # [BH, S]
    # delta = rowsum(do * o): cheap elementwise+reduce, XLA fuses it; the
    # kernels read it per Q block.
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1
    ).reshape(BH, Lq)

    num_qb, num_kb = Lq // block_q, S // block_k
    qspec = pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0))
    common = dict(interpret=interpret)

    dkdv_kernel = functools.partial(
        _fa_bwd_dkdv_kernel, block_q=block_q, block_k=block_k,
        sm_scale=sm_scale, causal=causal, num_qb=num_qb,
    )
    dk, dv = pl.pallas_call(
        dkdv_kernel,
        grid=(BH, num_kb, num_qb),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, kb, qb: (b, qb, 0)),  # q
            pl.BlockSpec((1, block_k, D), lambda b, kb, qb: (b, kb, 0)),  # k
            pl.BlockSpec((1, block_k, D), lambda b, kb, qb: (b, kb, 0)),  # v
            pl.BlockSpec((1, block_k), lambda b, kb, qb: (b, kb)),        # mask
            pl.BlockSpec((1, block_q, D), lambda b, kb, qb: (b, qb, 0)),  # do
            pl.BlockSpec((1, block_q), lambda b, kb, qb: (b, qb)),        # lse
            pl.BlockSpec((1, block_q), lambda b, kb, qb: (b, qb)),        # delta
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda b, kb, qb: (b, kb, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, kb, qb: (b, kb, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), k.dtype),
            jax.ShapeDtypeStruct((BH, S, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        **common,
    )(qr, kr, vr, maskr, dor, lser, delta)

    dq_kernel = functools.partial(
        _fa_bwd_dq_kernel, block_q=block_q, block_k=block_k,
        sm_scale=sm_scale, causal=causal, num_kb=num_kb,
    )
    (dq,) = pl.pallas_call(
        dq_kernel,
        grid=(BH, num_qb, num_kb),
        in_specs=[
            qspec,                                                        # q
            pl.BlockSpec((1, block_k, D), lambda b, i, kb: (b, kb, 0)),   # k
            pl.BlockSpec((1, block_k, D), lambda b, i, kb: (b, kb, 0)),   # v
            pl.BlockSpec((1, block_k), lambda b, i, kb: (b, kb)),         # mask
            qspec,                                                        # do
            pl.BlockSpec((1, block_q), lambda b, i, kb: (b, i)),          # lse
            pl.BlockSpec((1, block_q), lambda b, i, kb: (b, i)),          # delta
        ],
        out_specs=[qspec],
        out_shape=[jax.ShapeDtypeStruct((BH, Lq, D), q.dtype)],
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        **common,
    )(qr, kr, vr, maskr, dor, lser, delta)

    return (
        dq.reshape(B, H, Lq, D),
        dk.reshape(B, H, S, D),
        dv.reshape(B, H, S, D),
    )


# ------------------------------------------------------------------ backward


def _blockwise_backward(q, k, v, mask, causal, sm_scale, block_k, o, lse, do):
    """Flash-style exact backward from the saved LSE; scans K blocks."""
    B, H, Lq, D = q.shape
    S = k.shape[2]
    nb = S // block_k
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)  # [B,H,L]
    qf = q.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    qpos = jax.lax.broadcasted_iota(jnp.int32, (Lq, block_k), 0)

    def body(dq, kb):
        ks = jax.lax.dynamic_slice_in_dim(k, kb * block_k, block_k, axis=2).astype(jnp.float32)
        vs = jax.lax.dynamic_slice_in_dim(v, kb * block_k, block_k, axis=2).astype(jnp.float32)
        mk = jax.lax.dynamic_slice_in_dim(mask, kb * block_k, block_k, axis=1)
        s = jnp.einsum("bhld,bhsd->bhls", qf, ks) * sm_scale
        s = jnp.where(mk[:, None, None, :], s, NEG_INF)
        if causal:
            kpos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (Lq, block_k), 1)
            s = jnp.where((kpos <= qpos)[None, None], s, NEG_INF)
        p = _probs_from_lse(s, lse)  # exact probabilities (dead rows -> 0)
        dp = jnp.einsum("bhld,bhsd->bhls", dof, vs)
        ds = p * (dp - delta[..., None]) * sm_scale
        dq = dq + jnp.einsum("bhls,bhsd->bhld", ds, ks)
        dk_b = jnp.einsum("bhls,bhld->bhsd", ds, qf)
        dv_b = jnp.einsum("bhls,bhld->bhsd", p, dof)
        return dq, (dk_b, dv_b)

    dq0 = jnp.zeros((B, H, Lq, D), jnp.float32)
    dq, (dk_blocks, dv_blocks) = jax.lax.scan(body, dq0, jnp.arange(nb))
    # scan stacks blocks on axis 0: [nb, B, H, block_k, D] -> [B, H, S, D]
    dk = jnp.moveaxis(dk_blocks, 0, 2).reshape(B, H, S, D)
    dv = jnp.moveaxis(dv_blocks, 0, 2).reshape(B, H, S, D)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


# ------------------------------------------------------------------- public


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8)
)
def flash_attention(
    q, k, v, mask, causal=False, sm_scale=None, block_q=128, block_k=128,
    interpret=False,
):
    """Masked multi-head attention, O(L·block) memory.

    q: [B, H, Lq, D]; k, v: [B, H, S, D]; mask: [B, S] bool (True = real).
    Lq/S must be multiples of the block sizes (pad outside; padded KV rows
    are masked, padded Q rows produce zeros-safe outputs).
    """
    return _fa_impl(q, k, v, mask, causal, sm_scale, block_q, block_k, interpret)[0]


def _fa_impl(q, k, v, mask, causal, sm_scale, block_q, block_k, interpret):
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(q.shape[-1])
    if _use_pallas() or interpret:
        return _pallas_forward(q, k, v, mask, causal, scale, block_q, block_k,
                               interpret or not _use_pallas())
    return _blockwise_forward(q, k, v, mask, causal, scale, block_k)


def _fa_fwd(q, k, v, mask, causal, sm_scale, block_q, block_k, interpret):
    o, lse = _fa_impl(q, k, v, mask, causal, sm_scale, block_q, block_k, interpret)
    return o, (q, k, v, mask, o, lse)


def _fa_bwd(causal, sm_scale, block_q, block_k, interpret, res, do):
    q, k, v, mask, o, lse = res
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(q.shape[-1])
    if _use_pallas() or interpret:
        dq, dk, dv = _pallas_backward(
            q, k, v, mask, causal, scale, block_q, block_k, o, lse, do,
            interpret or not _use_pallas(),
        )
    else:
        dq, dk, dv = _blockwise_backward(
            q, k, v, mask, causal, scale, block_k, o, lse, do
        )
    return dq, dk, dv, None


flash_attention.defvjp(_fa_fwd, _fa_bwd)
