"""Fused TPU lookup kernels: DMA-pipelined gather, gather+combine, and a
stochastic-rounded scatter-apply.

Why these exist: the reference spends 5.5k LoC of CUDA on fused embedding
lookups (core/ops/fused_embedding_ops.cc:65, core/kernels/group_embedding/
group_embedding_lookup_sparse_forward_base_ops.cu.h) because op-composed
sparse gathers leave bandwidth on the table. The TPU analog is a Pallas
kernel that streams random table rows HBM->VMEM through a double-buffered
DMA pipeline, so the next row's fetch overlaps the current row's compute:

  * ``gather_rows``          — values[ix] for [U] unique slots (the hot
    [U, D] gather inside every lookup).
  * ``fused_gather_combine`` — bag-pooling straight out of the table:
    out[b] = sum_l w[b,l] * values[ix[b,l]] without materializing the
    [B, L, D] intermediate (serving/eval path; the train path needs the
    unique-space embeddings for autodiff and uses gather_rows).
  * ``apply_rows_sr``        — scatter updated rows back with stochastic
    rounding when the table is bf16 (plain round-to-nearest silently drops
    small gradient updates once |update| < ulp(value)/2).

Eligibility (measured on v5e): the DMA kernels require **f32 tables with
dim % 128 == 0** — Mosaic's HBM tiling constraint, see ``_dma_ok``. With
``TableConfig.kernel = "auto"`` (the default) eligible tables take the
Pallas path (bench-crowned winner: gather 494 vs 362 GB/s, scatter 1117 vs
726 — tools/bench_lookup.py, docs/perf.md) and everything else falls back
to the identical-semantics XLA path, including bf16 stochastic rounding,
which on hardware therefore always runs the XLA branch of apply_rows_sr.
Off-TPU all calls are XLA, so every caller is oracle-testable on CPU (the
kernels themselves via interpret mode, where the in-kernel SR branch is
also covered).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_BLOCK = 8  # rows per grid step; sublane-aligned for f32
_LANES = 128  # Mosaic HBM tiling: DMA row slices must be lane-aligned


def _on_tpu() -> bool:
    return jax.default_backend() == "tpu"


def _dma_ok(dim: int, dtype) -> bool:
    """Row-DMA kernels slice single rows out of the HBM-resident table;
    Mosaic requires those slices aligned to the HBM tiling, so the Pallas
    path only exists for f32 tables with dim % 128 == 0 (measured on v5e:
    misaligned widths are a compile error, not a slowdown — dim 64 fails
    "must be aligned to tiling (128)"; bf16 tiles (2, 128) so a dynamic
    single-row slice fails "index in dimension 0 is a multiple of 2").
    Narrower tables take the XLA gather/scatter path, which is
    bandwidth-bound anyway at small rows (a D<128 row underfills even one
    DMA granule)."""
    return dim % _LANES == 0 and jnp.dtype(dtype).itemsize == 4


def _pad_rows(ix: jnp.ndarray, block: int, fill: int = 0) -> jnp.ndarray:
    n = ix.shape[0]
    pad = (-n) % block
    if pad:
        ix = jnp.concatenate([ix, jnp.full((pad,), fill, ix.dtype)])
    return ix


# ------------------------------------------------------------- gather_rows


def gather_rows(values: jnp.ndarray, ix: jnp.ndarray, *,
                block: int = _BLOCK, interpret: bool = False) -> jnp.ndarray:
    """values [C, D], ix [n] int32 -> [n, D]; out-of-range ix clamp (the
    'clip' semantics of the jnp fallback). Rows ride a 2-deep DMA pipeline."""
    n = ix.shape[0]
    if not interpret and not (_on_tpu() and _dma_ok(values.shape[1], values.dtype)):
        return values.at[ix].get(mode="clip")

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    C, D = values.shape
    ixp = _pad_rows(ix.astype(jnp.int32), block)
    np_ = ixp.shape[0]

    def kernel(ix_ref, values_ref, out_ref, scratch, sems):
        base = pl.program_id(0) * block

        def row_dma(slot, i):
            idx = jnp.clip(ix_ref[base + i], 0, C - 1)
            return pltpu.make_async_copy(
                values_ref.at[idx], scratch.at[slot], sems.at[slot]
            )

        row_dma(0, 0).start()

        def body(i, _):
            cur = i % 2

            @pl.when(i + 1 < block)
            def _():
                row_dma((i + 1) % 2, i + 1).start()

            row_dma(cur, i).wait()
            out_ref[i, :] = scratch[cur]
            return 0

        jax.lax.fori_loop(0, block, body, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(np_ // block,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(
            (block, D), lambda i, ix_ref: (i, 0), memory_space=pltpu.VMEM
        ),
        scratch_shapes=[
            pltpu.VMEM((2, D), values.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((np_, D), values.dtype),
        interpret=interpret,
    )(ixp, values)
    return out[:n]


# ----------------------------------------------------- fused gather+combine


def fused_gather_combine(values: jnp.ndarray, row_ix: jnp.ndarray,
                         weights: jnp.ndarray, *, block_b: int = 8,
                         interpret: bool = False) -> jnp.ndarray:
    """Pooled bags straight from the table.

    values [C, D]; row_ix [B, L] int32 slot per position (< 0 = skip);
    weights [B, L] f32 per-position weight (carry the combiner here: 1 for
    sum, 1/n_b for mean, 1/sqrt(n_b) for sqrtn, 0 for pad/blocked).
    Returns [B, D] f32: out[b] = sum_l weights[b, l] * values[row_ix[b, l]].
    """
    B, L = row_ix.shape
    C, D = values.shape
    if not interpret and not (_on_tpu() and _dma_ok(D, values.dtype)):
        e = values.at[jnp.clip(row_ix, 0, C - 1)].get(mode="clip")
        w = jnp.where(row_ix >= 0, weights, 0.0)
        return jnp.sum(e.astype(jnp.float32) * w[..., None], axis=1)

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    padB = (-B) % block_b
    if padB:
        row_ix = jnp.concatenate(
            [row_ix, jnp.full((padB, L), -1, row_ix.dtype)]
        )
        weights = jnp.concatenate([weights, jnp.zeros((padB, L), weights.dtype)])
    Bp = row_ix.shape[0]
    flat_ix = row_ix.reshape(-1).astype(jnp.int32)
    # Weights ride SMEM as a second scalar-prefetch operand: a dynamic
    # per-position scalar read from a VMEM block is not expressible on TPU
    # ("index in dimension 1 must be a multiple of 128"); SMEM scalar loads
    # at computed offsets are.
    flat_w = weights.reshape(-1).astype(jnp.float32)
    rows_per_blk = block_b * L

    def kernel(ix_ref, w_ref, values_ref, out_ref, scratch, sems):
        base = pl.program_id(0) * rows_per_blk

        def row_dma(slot, j):
            idx = jnp.clip(ix_ref[base + j], 0, C - 1)
            return pltpu.make_async_copy(
                values_ref.at[idx], scratch.at[slot], sems.at[slot]
            )

        row_dma(0, 0).start()
        out_ref[:] = jnp.zeros_like(out_ref)

        def body(j, _):
            cur = j % 2

            @pl.when(j + 1 < rows_per_blk)
            def _():
                row_dma((j + 1) % 2, j + 1).start()

            row_dma(cur, j).wait()
            b = j // L
            w = jnp.where(ix_ref[base + j] >= 0, w_ref[base + j], 0.0)
            out_ref[b, :] = out_ref[b, :] + w * scratch[cur].astype(jnp.float32)
            return 0

        jax.lax.fori_loop(0, rows_per_blk, body, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(Bp // block_b,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(
            (block_b, D), lambda i, ix_ref, w_ref: (i, 0),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=[
            pltpu.VMEM((2, D), values.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((Bp, D), jnp.float32),
        interpret=interpret,
    )(flat_ix, flat_w, values)
    return out[:B]


# --------------------------------------------------- stochastic-rounded apply


def stochastic_round(x: jnp.ndarray, key: jnp.ndarray,
                     dtype=jnp.bfloat16) -> jnp.ndarray:
    """XLA stochastic rounding f32 -> bf16: add uniform noise below the
    mantissa cut, then truncate. E[round(x)] == x, so tiny optimizer updates
    survive bf16 tables in expectation instead of vanishing at ulp/2."""
    assert dtype == jnp.bfloat16, "only bf16 targets supported"
    bits = jax.random.bits(key, x.shape, jnp.uint32)
    u = jax.lax.bitcast_convert_type(x.astype(jnp.float32), jnp.uint32)
    u = u + (bits & jnp.uint32(0xFFFF))  # carry into the kept mantissa
    u = u & jnp.uint32(0xFFFF0000)  # truncate to bf16-representable
    return jax.lax.bitcast_convert_type(u, jnp.float32).astype(jnp.bfloat16)


def apply_rows_sr(values: jnp.ndarray, slot_ix: jnp.ndarray,
                  new_rows: jnp.ndarray, seed: jnp.ndarray, *,
                  block: int = _BLOCK, interpret: bool = False,
                  use_pallas: bool = True) -> jnp.ndarray:
    """Scatter new_rows [U, D] f32 into values [C, D] at slot_ix [U]
    (< 0 = skip). bf16 tables round stochastically; f32 tables store exact.
    Returns the updated values array (aliased in-place under jit on TPU).
    use_pallas=False keeps the XLA scatter (still stochastic-rounding bf16)."""
    U, D = new_rows.shape
    C = values.shape[0]
    if not interpret and not (use_pallas and _on_tpu() and _dma_ok(D, values.dtype)):
        if values.dtype == jnp.bfloat16:
            key = jax.random.fold_in(jax.random.PRNGKey(0x5EED), seed)
            rows = stochastic_round(new_rows, key)
        else:
            rows = new_rows.astype(values.dtype)
        ix = jnp.where(slot_ix >= 0, slot_ix, C)
        return values.at[ix].set(rows, mode="drop")

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    # Pad with -1 (skip): a 0-fill would scatter garbage rows into slot 0.
    ixp = _pad_rows(jnp.where(slot_ix >= 0, slot_ix, -1).astype(jnp.int32)
                    .reshape(-1), block, fill=-1)
    if ixp.shape[0] != U:
        new_rows = jnp.concatenate(
            [new_rows, jnp.zeros((ixp.shape[0] - U, D), new_rows.dtype)]
        )
    Up = ixp.shape[0]
    sr = values.dtype == jnp.bfloat16
    # Random bits come in as a tensor (not in-kernel PRNG): identical
    # numerics across compiled TPU and interpret mode, at the cost of
    # U*D*4 extra bytes of traffic — negligible next to the row writes.
    if sr:
        key = jax.random.fold_in(jax.random.PRNGKey(0x5EED), seed)
        bits = jax.random.bits(key, (Up, D), jnp.uint32)
        bits_dim = D
    else:
        # f32 path never reads the bits: ship a 1-wide dummy, not U*D zeros.
        bits = jnp.zeros((Up, 1), jnp.uint32)
        bits_dim = 1

    def kernel(ix_ref, rows_ref, bits_ref, vin_ref, vout_ref, scratch, sems):
        del vin_ref  # aliased with vout_ref
        g = pl.program_id(0)

        def body(i, _):
            slot = i % 2
            row = rows_ref[pl.ds(i, 1), :].astype(jnp.float32)  # (1, D)
            if sr:
                u = pltpu.bitcast(row, jnp.uint32)
                u = u + (bits_ref[pl.ds(i, 1), :] & jnp.uint32(0xFFFF))
                u = u & jnp.uint32(0xFFFF0000)
                row = pltpu.bitcast(u, jnp.float32)
            scratch[pl.ds(slot, 1), :] = row.astype(scratch.dtype)
            idx = ix_ref[g * block + i]

            @pl.when(idx >= 0)
            def _():
                dma = pltpu.make_async_copy(
                    scratch.at[slot], vout_ref.at[idx], sems.at[slot]
                )
                dma.start()
                dma.wait()

            return 0

        jax.lax.fori_loop(0, block, body, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(Up // block,),
        in_specs=[
            pl.BlockSpec(
                (block, D), lambda i, ix_ref: (i, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (block, bits_dim), lambda i, ix_ref: (i, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.VMEM((2, D), values.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(values.shape, values.dtype),
        input_output_aliases={3: 0},
        compiler_params=pltpu.CompilerParams(has_side_effects=True),
        interpret=interpret,
    )(ixp, new_rows, bits, values)
