"""Fused TPU lookup kernels: DMA-pipelined gather, gather+combine, and a
stochastic-rounded scatter-apply.

Why these exist: the reference spends 5.5k LoC of CUDA on fused embedding
lookups (core/ops/fused_embedding_ops.cc:65, core/kernels/group_embedding/
group_embedding_lookup_sparse_forward_base_ops.cu.h) because op-composed
sparse gathers leave bandwidth on the table. The TPU analog is a Pallas
kernel that streams random table rows HBM->VMEM through a double-buffered
DMA pipeline, so the next row's fetch overlaps the current row's compute:

  * ``gather_rows``          — values[ix] for [U] unique slots (the hot
    [U, D] gather inside every lookup).
  * ``fused_gather_combine`` — bag-pooling straight out of the table:
    out[b] = sum_l w[b,l] * values[ix[b,l]] without materializing the
    [B, L, D] intermediate (serving/eval path; the train path needs the
    unique-space embeddings for autodiff and uses gather_rows).
  * ``apply_rows_sr``        — scatter updated rows back with stochastic
    rounding when the table is bf16 (plain round-to-nearest silently drops
    small gradient updates once |update| < ulp(value)/2).

Eligibility: the single-row DMA kernels require **f32 tables with
dim % 128 == 0** (Mosaic's HBM tiling constraint, ``_dma_ok``; measured
winners on v5e — gather 494 vs 362 GB/s, scatter 1117 vs 726). **bf16
tables with dim % 128 == 0** ride the PAIR-granule variants
(``gather_rows_pair`` / ``apply_rows_sr_pair`` / the pair branch of
``fused_gather_combine``): 2-row even-aligned DMAs with the half-select
or read-modify-write done in VMEM, including IN-KERNEL stochastic
rounding — gated behind kernel="pallas" / AUTO_TRUSTS_BF16_PAIR until a
hardware bench crowns them. Everything else falls back to the
identical-semantics XLA path. Off-TPU all calls are XLA, so every caller
is oracle-testable on CPU (the kernels themselves via interpret mode,
where the in-kernel SR branches are also covered).
"""
from __future__ import annotations


from typing import Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp

_BLOCK = 8  # rows per grid step; sublane-aligned for f32
_LANES = 128  # Mosaic HBM tiling: DMA row slices must be lane-aligned


def _on_tpu() -> bool:
    return jax.default_backend() == "tpu"


def _dma_ok(dim: int, dtype) -> bool:
    """Single-row DMA eligibility: f32 tables with dim % 128 == 0 —
    Mosaic requires HBM slices aligned to the tiling (measured on v5e:
    misaligned widths are a compile error, not a slowdown — dim 64 fails
    "must be aligned to tiling (128)"; bf16 tiles pack 2 sublanes per
    32-bit word so a dynamic single-row slice fails "index in dimension 0
    is a multiple of 2"). bf16 tables with dim % 128 == 0 have their own
    PAIR-granule kernels (gather_rows / apply_rows_sr /
    fused_gather_combine route them via _dma_pair_ok); narrower tables
    take the XLA path (a D<128 row
    underfills even one DMA granule — beating XLA there needs a packed
    storage layout, not a better kernel; see docs/perf.md)."""
    return dim % _LANES == 0 and jnp.dtype(dtype).itemsize == 4


def _dma_pair_ok(shape, dtype) -> bool:
    """bf16 pair-granule eligibility: rows ride 2-row granules (the bf16
    packing unit), so the table needs dim % 128 == 0 AND an even row
    count — checked here, not assumed, since the ops are public (an odd
    C would let a clamped index DMA one row past the array)."""
    C, dim = shape
    return (
        dim % _LANES == 0
        and C % 2 == 0
        and jnp.dtype(dtype) == jnp.bfloat16
    )


# Which (kernel, shape-class) combos "auto" trusts. The policy is that
# auto only resolves to Pallas where a live-hardware bench crowned it
# (tools/bench_lookup.py, docs/perf.md); the bf16 pair kernels are
# implemented + oracle-tested but NOT yet measured on hardware, so auto
# keeps XLA for them until a measurement flips these flags. Both flags
# are consulted by EmbeddingTable.use_pallas / .pair_kernels.
AUTO_TRUSTS_F32_ROW = True     # measured round 2: +37% gather, +54% scatter
AUTO_TRUSTS_BF16_PAIR = False  # pending hardware window
AUTO_TRUSTS_FUSED_STEP = False  # single-pass step kernels: pending hardware


# ------------------------------------------------ fallback observability
#
# Every dispatch predicate above can silently reject a kernel="pallas"
# request and take the XLA path — correct, but invisible: a table that
# was supposed to ride the DMA kernels can spend its life on the
# fallback because of one misaligned dim. Mirror dedup.log_full_fallback:
# note each distinct rejection exactly once per (kernel, reason, shape,
# dtype) on the obs registry, where /metrics renders it as
# deeprec_pallas_fallback_total{kernel,reason}.

_fallback_noted: set = set()


def _note_fallback(kernel: str, reason: str, shape, dtype) -> None:
    """Count a Pallas→XLA dispatch rejection. Runs at TRACE time (shapes
    and dtypes are static), so the counter costs nothing inside the
    compiled step and dedup keeps a steady-state loop from re-counting
    the same miss on every retrace."""
    key = (kernel, reason, tuple(shape), str(jnp.dtype(dtype)))
    if key in _fallback_noted:
        return
    _fallback_noted.add(key)
    from deeprec_tpu.obs.metrics import default_registry

    default_registry().counter(
        "deeprec_pallas_fallback",
        help="Pallas kernel dispatches that fell back to XLA, by cause",
        labels={"kernel": kernel, "reason": reason},
    ).inc()


def _row_reason(dim: int, dtype) -> str:
    """Why _on_tpu() + _dma_ok rejected a single-row-DMA dispatch."""
    if not _on_tpu():
        return "not_tpu"
    if dim % _LANES != 0:
        return "dim_unaligned"
    return "dtype"


def _pair_reason(shape, dtype) -> str:
    """Why _on_tpu() + _dma_pair_ok rejected a pair-granule dispatch."""
    if not _on_tpu():
        return "not_tpu"
    _, dim = shape
    if dim % _LANES != 0:
        return "dim_unaligned"
    if jnp.dtype(dtype) != jnp.bfloat16:
        return "dtype"
    return "odd_capacity"


def _pad_rows(ix: jnp.ndarray, block: int, fill: int = 0) -> jnp.ndarray:
    n = ix.shape[0]
    pad = (-n) % block
    if pad:
        ix = jnp.concatenate([ix, jnp.full((pad,), fill, ix.dtype)])
    return ix


def _pad_updates(slot_ix, new_rows, block):
    """Shared scatter preamble: pad slot indices (-1 = skip) and update
    rows to a block multiple."""
    ixp = _pad_rows(
        jnp.where(slot_ix >= 0, slot_ix, -1).astype(jnp.int32).reshape(-1),
        block, fill=-1,
    )
    if ixp.shape[0] != new_rows.shape[0]:
        new_rows = jnp.concatenate([
            new_rows,
            jnp.zeros(
                (ixp.shape[0] - new_rows.shape[0], new_rows.shape[1]),
                new_rows.dtype,
            ),
        ])
    return ixp, new_rows


def _compiler_params(pltpu_mod, **kw):
    """Mosaic compiler params across jax versions: TPUCompilerParams was
    renamed CompilerParams and grew fields over time (has_side_effects is
    absent in older jax — safe to drop there: these kernels' outputs are
    always consumed, the flag only guards against DCE). Unknown fields are
    filtered rather than crashing the whole kernel path."""
    import dataclasses

    cls = getattr(pltpu_mod, "CompilerParams", None) \
        or pltpu_mod.TPUCompilerParams
    names = {f.name for f in dataclasses.fields(cls)}
    return cls(**{k: v for k, v in kw.items() if k in names})


def _sr_bits(seed, shape):
    """The one seed-derivation for stochastic-rounding bits: every SR
    path (XLA fallback, row kernel, pair kernel) must use this so their
    numerics stay interchangeable."""
    key = jax.random.fold_in(jax.random.PRNGKey(0x5EED), seed)
    return jax.random.bits(key, shape, jnp.uint32)


def _sr_round_in_kernel(row_f32, bits_u32):
    """In-kernel stochastic rounding f32 -> bf16-representable f32
    (same bit-twiddle as stochastic_round): add uniform noise below the
    mantissa cut, truncate. Shared by both scatter kernels."""
    from jax.experimental.pallas import tpu as pltpu

    u = pltpu.bitcast(row_f32, jnp.uint32)
    u = u + (bits_u32 & jnp.uint32(0xFFFF))
    u = u & jnp.uint32(0xFFFF0000)
    return pltpu.bitcast(u, jnp.float32)


# ------------------------------------------------- bf16 pair-granule ops


def gather_rows_pair(values: jnp.ndarray, ix: jnp.ndarray, *,
                     block: int = _BLOCK,
                     interpret: bool = False) -> jnp.ndarray:
    """bf16 gather via 2-row granules: values [C, D] bf16 (D % 128 == 0,
    C even), ix [n] int32 -> [n, D]. A dynamic single-row HBM slice is
    not expressible for bf16 (rows pack 2 sublanes per 32-bit word), so
    each lookup DMAs the even-aligned PAIR containing the row and emits
    the wanted half — 2x the HBM read volume of an f32 row gather, but
    the pair shares the granule the hardware reads anyway."""
    n = ix.shape[0]
    C, D = values.shape
    if not interpret and not (
        _on_tpu() and _dma_pair_ok(values.shape, values.dtype)
    ):
        _note_fallback("gather_rows_pair",
                       _pair_reason(values.shape, values.dtype),
                       values.shape, values.dtype)
        return values.at[ix].get(mode="clip")

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    ixp = _pad_rows(ix.astype(jnp.int32), block)
    np_ = ixp.shape[0]

    def kernel(ix_ref, values_ref, out_ref, scratch, sems):
        base = pl.program_id(0) * block

        def pair_dma(slot, i):
            idx = jnp.clip(ix_ref[base + i], 0, C - 1)
            g = (idx // 2) * 2  # even-aligned granule base
            return pltpu.make_async_copy(
                values_ref.at[pl.ds(g, 2), :],
                scratch.at[slot],
                sems.at[slot],
            )

        pair_dma(0, 0).start()

        def body(i, _):
            cur = i % 2

            @pl.when(i + 1 < block)
            def _():
                pair_dma((i + 1) % 2, i + 1).start()

            pair_dma(cur, i).wait()
            idx = jnp.clip(ix_ref[base + i], 0, C - 1)
            out_ref[i, :] = scratch[cur, idx % 2, :]
            return 0

        jax.lax.fori_loop(0, block, body, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(np_ // block,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(
            (block, D), lambda i, ix_ref: (i, 0), memory_space=pltpu.VMEM
        ),
        scratch_shapes=[
            pltpu.VMEM((2, 2, D), values.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((np_, D), values.dtype),
        interpret=interpret,
    )(ixp, values)
    return out[:n]


def apply_rows_sr_pair(values: jnp.ndarray, slot_ix: jnp.ndarray,
                       new_rows: jnp.ndarray, seed: jnp.ndarray, *,
                       interpret: bool = False) -> jnp.ndarray:
    """bf16 scatter with IN-KERNEL stochastic rounding via 2-row
    granules: read-modify-write the even-aligned pair containing each
    target row. Fully serialized (one granule in flight): consecutive
    updates may share a granule, and the read of update i+1 must observe
    the write of update i. new_rows [U, D] f32; values [C, D] bf16."""
    U, D = new_rows.shape
    C = values.shape[0]
    if not interpret and not (
        _on_tpu() and _dma_pair_ok(values.shape, values.dtype)
    ):
        _note_fallback("apply_rows_sr_pair",
                       _pair_reason(values.shape, values.dtype),
                       values.shape, values.dtype)
        return apply_rows_sr(values, slot_ix, new_rows, seed,
                             use_pallas=False, interpret=False)

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    ixp, new_rows = _pad_updates(slot_ix, new_rows, _BLOCK)
    Up = ixp.shape[0]
    bits = _sr_bits(seed, (Up, D))

    def kernel(ix_ref, rows_ref, bits_ref, vin_ref, vout_ref, scratch, sem):
        del vin_ref  # aliased with vout_ref
        g0 = pl.program_id(0) * _BLOCK

        def body(i, _):
            idx = ix_ref[g0 + i]

            @pl.when(idx >= 0)
            def _():
                g = (idx // 2) * 2
                rd = pltpu.make_async_copy(
                    vout_ref.at[pl.ds(g, 2), :], scratch, sem.at[0]
                )
                rd.start()
                rd.wait()
                row = _sr_round_in_kernel(
                    rows_ref[pl.ds(i, 1), :].astype(jnp.float32),
                    bits_ref[pl.ds(i, 1), :],
                )
                scratch[pl.ds(idx % 2, 1), :] = row.astype(scratch.dtype)
                wr = pltpu.make_async_copy(
                    scratch, vout_ref.at[pl.ds(g, 2), :], sem.at[0]
                )
                wr.start()
                wr.wait()

            return 0

        jax.lax.fori_loop(0, _BLOCK, body, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(Up // _BLOCK,),
        in_specs=[
            pl.BlockSpec(
                (_BLOCK, D), lambda i, ix_ref: (i, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (_BLOCK, D), lambda i, ix_ref: (i, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.VMEM((2, D), values.dtype),
            pltpu.SemaphoreType.DMA((1,)),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(values.shape, values.dtype),
        input_output_aliases={3: 0},
        compiler_params=_compiler_params(pltpu, has_side_effects=True),
        interpret=interpret,
    )(ixp, new_rows, bits, values)


# ------------------------------------------------------------- gather_rows


def gather_rows(values: jnp.ndarray, ix: jnp.ndarray, *,
                block: int = _BLOCK, interpret: bool = False,
                pair_kernels: bool = False) -> jnp.ndarray:
    """values [C, D], ix [n] int32 -> [n, D]; out-of-range ix clamp (the
    'clip' semantics of the jnp fallback). Rows ride a 2-deep DMA pipeline.
    pair_kernels=True additionally routes eligible bf16 tables through the
    pair-granule kernel (explicit kernel="pallas" or a measured-winners
    flag — see AUTO_TRUSTS_BF16_PAIR)."""
    n = ix.shape[0]
    if pair_kernels and _dma_pair_ok(values.shape, values.dtype) and (
        interpret or _on_tpu()
    ):
        return gather_rows_pair(values, ix, block=block, interpret=interpret)
    if not interpret and not (_on_tpu() and _dma_ok(values.shape[1], values.dtype)):
        _note_fallback("gather_rows",
                       _row_reason(values.shape[1], values.dtype),
                       values.shape, values.dtype)
        return values.at[ix].get(mode="clip")

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    C, D = values.shape
    ixp = _pad_rows(ix.astype(jnp.int32), block)
    np_ = ixp.shape[0]

    def kernel(ix_ref, values_ref, out_ref, scratch, sems):
        base = pl.program_id(0) * block

        def row_dma(slot, i):
            idx = jnp.clip(ix_ref[base + i], 0, C - 1)
            return pltpu.make_async_copy(
                values_ref.at[idx], scratch.at[slot], sems.at[slot]
            )

        row_dma(0, 0).start()

        def body(i, _):
            cur = i % 2

            @pl.when(i + 1 < block)
            def _():
                row_dma((i + 1) % 2, i + 1).start()

            row_dma(cur, i).wait()
            out_ref[i, :] = scratch[cur]
            return 0

        jax.lax.fori_loop(0, block, body, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(np_ // block,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(
            (block, D), lambda i, ix_ref: (i, 0), memory_space=pltpu.VMEM
        ),
        scratch_shapes=[
            pltpu.VMEM((2, D), values.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((np_, D), values.dtype),
        interpret=interpret,
    )(ixp, values)
    return out[:n]


# ----------------------------------------------------- fused gather+combine


def fused_gather_combine(values: jnp.ndarray, row_ix: jnp.ndarray,
                         weights: jnp.ndarray, *, block_b: int = 8,
                         interpret: bool = False,
                         pair_kernels: bool = False) -> jnp.ndarray:
    """Pooled bags straight from the table.

    values [C, D]; row_ix [B, L] int32 slot per position (< 0 = skip);
    weights [B, L] f32 per-position weight (carry the combiner here: 1 for
    sum, 1/n_b for mean, 1/sqrt(n_b) for sqrtn, 0 for pad/blocked).
    Returns [B, D] f32: out[b] = sum_l weights[b, l] * values[row_ix[b, l]].
    pair_kernels routes eligible bf16 tables through 2-row granule DMAs
    (same rationale as gather_rows_pair).
    """
    B, L = row_ix.shape
    C, D = values.shape
    pair = pair_kernels and _dma_pair_ok(values.shape, values.dtype) and (
        interpret or _on_tpu()
    )
    if not pair and not interpret and not (
        _on_tpu() and _dma_ok(D, values.dtype)
    ):
        _note_fallback("fused_gather_combine", _row_reason(D, values.dtype),
                       values.shape, values.dtype)
        e = values.at[jnp.clip(row_ix, 0, C - 1)].get(mode="clip")
        w = jnp.where(row_ix >= 0, weights, 0.0)
        return jnp.sum(e.astype(jnp.float32) * w[..., None], axis=1)

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    padB = (-B) % block_b
    if padB:
        row_ix = jnp.concatenate(
            [row_ix, jnp.full((padB, L), -1, row_ix.dtype)]
        )
        weights = jnp.concatenate([weights, jnp.zeros((padB, L), weights.dtype)])
    Bp = row_ix.shape[0]
    flat_ix = row_ix.reshape(-1).astype(jnp.int32)
    # Weights ride SMEM as a second scalar-prefetch operand: a dynamic
    # per-position scalar read from a VMEM block is not expressible on TPU
    # ("index in dimension 1 must be a multiple of 128"); SMEM scalar loads
    # at computed offsets are.
    flat_w = weights.reshape(-1).astype(jnp.float32)
    rows_per_blk = block_b * L

    def kernel(ix_ref, w_ref, values_ref, out_ref, scratch, sems):
        base = pl.program_id(0) * rows_per_blk

        def row_dma(slot, j):
            idx = jnp.clip(ix_ref[base + j], 0, C - 1)
            if pair:
                g = (idx // 2) * 2  # even-aligned bf16 granule
                return pltpu.make_async_copy(
                    values_ref.at[pl.ds(g, 2), :], scratch.at[slot],
                    sems.at[slot],
                )
            return pltpu.make_async_copy(
                values_ref.at[idx], scratch.at[slot], sems.at[slot]
            )

        row_dma(0, 0).start()
        out_ref[:] = jnp.zeros_like(out_ref)

        def body(j, _):
            cur = j % 2

            @pl.when(j + 1 < rows_per_blk)
            def _():
                row_dma((j + 1) % 2, j + 1).start()

            row_dma(cur, j).wait()
            b = j // L
            w = jnp.where(ix_ref[base + j] >= 0, w_ref[base + j], 0.0)
            if pair:
                idx = jnp.clip(ix_ref[base + j], 0, C - 1)
                row = scratch[cur, idx % 2, :]
            else:
                row = scratch[cur]
            out_ref[b, :] = out_ref[b, :] + w * row.astype(jnp.float32)
            return 0

        jax.lax.fori_loop(0, rows_per_blk, body, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(Bp // block_b,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(
            (block_b, D), lambda i, ix_ref, w_ref: (i, 0),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=[
            pltpu.VMEM(((2, 2, D) if pair else (2, D)), values.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((Bp, D), jnp.float32),
        interpret=interpret,
    )(flat_ix, flat_w, values)
    return out[:B]


# --------------------------------------------------- stochastic-rounded apply


def stochastic_round(x: jnp.ndarray, key: jnp.ndarray,
                     dtype=jnp.bfloat16) -> jnp.ndarray:
    """XLA stochastic rounding f32 -> bf16: add uniform noise below the
    mantissa cut, then truncate. E[round(x)] == x, so tiny optimizer updates
    survive bf16 tables in expectation instead of vanishing at ulp/2."""
    assert dtype == jnp.bfloat16, "only bf16 targets supported"
    bits = jax.random.bits(key, x.shape, jnp.uint32)
    u = jax.lax.bitcast_convert_type(x.astype(jnp.float32), jnp.uint32)
    u = u + (bits & jnp.uint32(0xFFFF))  # carry into the kept mantissa
    u = u & jnp.uint32(0xFFFF0000)  # truncate to bf16-representable
    return jax.lax.bitcast_convert_type(u, jnp.float32).astype(jnp.bfloat16)


def apply_rows_sr(values: jnp.ndarray, slot_ix: jnp.ndarray,
                  new_rows: jnp.ndarray, seed: jnp.ndarray, *,
                  block: int = _BLOCK, interpret: bool = False,
                  use_pallas: bool = True,
                  pair_kernels: bool = False) -> jnp.ndarray:
    """Scatter new_rows [U, D] f32 into values [C, D] at slot_ix [U]
    (< 0 = skip). bf16 tables round stochastically; f32 tables store exact.
    Returns the updated values array (aliased in-place under jit on TPU).
    use_pallas=False keeps the XLA scatter (still stochastic-rounding bf16);
    pair_kernels=True routes eligible bf16 tables through the pair-granule
    read-modify-write kernel with IN-KERNEL stochastic rounding."""
    U, D = new_rows.shape
    C = values.shape[0]
    if use_pallas and pair_kernels and _dma_pair_ok(values.shape, values.dtype) and (
        interpret or _on_tpu()
    ):
        return apply_rows_sr_pair(values, slot_ix, new_rows, seed,
                                  interpret=interpret)
    if not interpret and not (use_pallas and _on_tpu() and _dma_ok(D, values.dtype)):
        if use_pallas:
            # only a *rejected* Pallas request is a fallback worth noting;
            # use_pallas=False callers asked for the XLA scatter.
            _note_fallback("apply_rows_sr", _row_reason(D, values.dtype),
                           values.shape, values.dtype)
        if values.dtype == jnp.bfloat16:
            key = jax.random.fold_in(jax.random.PRNGKey(0x5EED), seed)
            rows = stochastic_round(new_rows, key)
        else:
            rows = new_rows.astype(values.dtype)
        ix = jnp.where(slot_ix >= 0, slot_ix, C)
        return values.at[ix].set(rows, mode="drop")

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    # Pad with -1 (skip): a 0-fill would scatter garbage rows into slot 0.
    ixp, new_rows = _pad_updates(slot_ix, new_rows, block)
    Up = ixp.shape[0]
    sr = values.dtype == jnp.bfloat16
    # Random bits come in as a tensor (not in-kernel PRNG): identical
    # numerics across compiled TPU and interpret mode, at the cost of
    # U*D*4 extra bytes of traffic — negligible next to the row writes.
    if sr:
        bits = _sr_bits(seed, (Up, D))
        bits_dim = D
    else:
        # f32 path never reads the bits: ship a 1-wide dummy, not U*D zeros.
        bits = jnp.zeros((Up, 1), jnp.uint32)  # noqa: DRT003 — deliberate 1-wide dummy: f32 path never reads it, padding beats shipping U*D zeros
        bits_dim = 1

    def kernel(ix_ref, rows_ref, bits_ref, vin_ref, vout_ref, scratch, sems):
        del vin_ref  # aliased with vout_ref
        g = pl.program_id(0)

        def body(i, _):
            slot = i % 2
            row = rows_ref[pl.ds(i, 1), :].astype(jnp.float32)  # (1, D)
            if sr:
                row = _sr_round_in_kernel(row, bits_ref[pl.ds(i, 1), :])
            scratch[pl.ds(slot, 1), :] = row.astype(scratch.dtype)
            idx = ix_ref[g * block + i]

            @pl.when(idx >= 0)
            def _():
                dma = pltpu.make_async_copy(
                    scratch.at[slot], vout_ref.at[idx], sems.at[slot]
                )
                dma.start()
                dma.wait()

            return 0

        jax.lax.fori_loop(0, block, body, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(Up // block,),
        in_specs=[
            pl.BlockSpec(
                (block, D), lambda i, ix_ref: (i, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (block, bits_dim), lambda i, ix_ref: (i, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.VMEM((2, D), values.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(values.shape, values.dtype),
        input_output_aliases={3: 0},
        compiler_params=_compiler_params(pltpu, has_side_effects=True),
        interpret=interpret,
    )(ixp, new_rows, bits, values)


# ------------------------------------------------------- fused sparse step
#
# The single-pass per-table step kernels (docs/kernels.md). Forward: one
# Pallas pass runs the hash-probe dedup inline (the scratch table lives in
# VMEM, so the claim-scatter that costs ~50x a gather as an [S]-lane XLA
# scatter — ops/dedup.py's compaction comment — becomes a plain in-kernel
# slot write), DMAs each unique row from HBM exactly once, and
# segment-combines straight into the [B, D] output: the [U, D] unique-rows
# buffer never round-trips through HBM. Backward: one pass segment-sums the
# per-example output gradient into unique-row space in VMEM, stages the
# touched value/slot rows in, applies the optimizer row-function, and
# DMA-scatters the results back — the [U, D] gradient buffer never exists
# outside the kernel either. Both are oracle-tested on CPU via
# interpret=True against the XLA composition below (bit-identical fp32,
# same-bits SR equality bf16; tests/test_fused_step.py).


class FusedBags(NamedTuple):
    """Everything fused_sparse_forward produced / the backward consumes.

    out      [B, D] f32 pooled bags (always f32: rows are cast up before
             the combine on BOTH paths, so bf16 tables pool exactly).
    uids     [U] int32 unique row indices; uids[0] == -1 (reserved
             sentinel, the hash_dedup contract). NOTE the ORDER of uids is
             path-dependent (kernel claims in first-occurrence order, the
             XLA fallback compacts in scratch-slot order); `out` and the
             uids↔inverse correspondence are order-independent.
    inverse  [B, L] int32 position -> unique slot (0 = pad/overflow).
    counts   [U] int32 occurrences per unique slot (counts[0] == 0).
    overflow [] int32 distinct ids past the budget + unresolved probes.
    """

    out: jnp.ndarray
    uids: jnp.ndarray
    inverse: jnp.ndarray
    counts: jnp.ndarray
    overflow: jnp.ndarray


def _sr_bits_rows(seed, uids: jnp.ndarray, dim: int) -> jnp.ndarray:
    """Row-KEYED stochastic-rounding bits: a pure integer hash of
    (seed, row id, column). The positional `_sr_bits` stream would hand a
    row different noise depending on the order dedup emitted it — and the
    fused kernel and the XLA fallback emit uids in different (equally
    valid) orders — so bf16 parity across paths needs bits that are a
    function of the ROW, not its position in the unique set."""
    from deeprec_tpu.utils import hashing

    s = hashing.mix32(jnp.asarray(seed).astype(jnp.uint32))
    base = hashing.mix32(hashing.fold64(uids) ^ s)  # [U]
    col = hashing.mix32(
        jnp.arange(dim, dtype=jnp.uint32) * jnp.uint32(0x9E3779B9)
    )  # [D]
    return hashing.mix32(base[:, None] ^ col[None, :])  # [U, D]


def _sr_round_bits(x: jnp.ndarray, bits: jnp.ndarray) -> jnp.ndarray:
    """XLA stochastic rounding from caller-supplied bits — the same
    twiddle as stochastic_round / _sr_round_in_kernel, so the fallback
    and the kernel are bit-interchangeable when fed the same bits."""
    u = jax.lax.bitcast_convert_type(x.astype(jnp.float32), jnp.uint32)
    u = u + (bits & jnp.uint32(0xFFFF))
    u = u & jnp.uint32(0xFFFF0000)
    return jax.lax.bitcast_convert_type(u, jnp.float32).astype(jnp.bfloat16)


def _bag_denominator(mask: jnp.ndarray, combiner: str) -> jnp.ndarray:
    """Per-bag combine denominator [B, 1] f32: 1 for sum, max(n,1) for
    mean, sqrt(max(n,1)) for sqrtn. Always applied OUTSIDE the
    kernel-vs-fallback branch (forward epilogue and backward grad
    pre-scaling), so the division is one shared traced computation: XLA's
    algebraic simplifier rewrites x/sqrt(n) into x*rsqrt(n) in some graph
    contexts and not others (1-ulp apart — observed on CPU), and a
    division INSIDE the branch would let the two paths drift by exactly
    that rewrite."""
    n = jnp.sum(mask.astype(jnp.float32), axis=1, keepdims=True)
    if combiner == "sum":
        return jnp.ones_like(n)
    if combiner == "mean":
        return jnp.maximum(n, 1.0)
    if combiner == "sqrtn":
        return jnp.sqrt(jnp.maximum(n, 1.0))
    raise ValueError(f"unknown combiner: {combiner}")


def _combine_epilogue(bags: "FusedBags", ids: jnp.ndarray,
                      combiner: str) -> "FusedBags":
    """mean/sqrtn scaling over the raw per-bag sums, shared by both
    forward paths (see _bag_denominator for why it must live out here)."""
    if combiner == "sum":
        return bags
    return bags._replace(
        out=bags.out / _bag_denominator(ids >= 0, combiner)
    )


def fusable_optimizer(opt, dim: int) -> bool:
    """The fused backward stages slot rows in VMEM as [U, dim] tiles: an
    optimizer qualifies iff every slot is a full-width (dim,) row — no
    per-table scalars (AdamAsync's beta powers), no (1,)-wide rows
    (AdagradDecay's decay_period). sgd/adagrad/adam/adamw/ftrl qualify;
    the rest keep the split-phase apply_gradients path."""
    from deeprec_tpu.optim.sparse import SCALAR_PREFIX

    for name, (shape, _) in opt.slot_specs(dim).items():
        if name.startswith(SCALAR_PREFIX) or tuple(shape) != (dim,):
            return False
    return True


def fused_sparse_forward(values: jnp.ndarray, ids: jnp.ndarray, *,
                         combiner: str = "sum", unique_size: int,
                         max_probes: int = 64, interpret: bool = False,
                         use_pallas: bool = True) -> FusedBags:
    """Single-pass budgeted lookup: dedup-probe + unique-row gather +
    segment-combine, one kernel per table.

    values [C, D]; ids [B, L] int32 ROW indices into values (< 0 = pad);
    unique_size the static dedup budget U (>= 2; index 0 is the reserved
    sentinel slot — use dedup.resolve_size). Returns FusedBags.

    Off-TPU (and for any shape _dma_ok rejects) this is the identical-
    semantics XLA composition hash_dedup -> gather -> combiners.combine,
    which doubles as the oracle for the interpret-mode kernel tests.
    When `overflow > 0` the SET of budgeted ids is path-dependent (claim
    order vs scratch-slot order) — both satisfy the budget contract.
    """
    B, L = ids.shape
    C, D = values.shape
    U = int(unique_size)
    N = B * L
    flat = jnp.where(ids >= 0, ids, -1).reshape(-1).astype(jnp.int32)

    if not interpret and not (
        use_pallas and _on_tpu() and _dma_ok(D, values.dtype)
    ):
        if use_pallas:
            _note_fallback("fused_sparse_forward",
                           _row_reason(D, values.dtype),
                           values.shape, values.dtype)
        from deeprec_tpu.embedding import combiners
        from deeprec_tpu.ops import dedup

        uids, inverse, counts, overflow = dedup.hash_dedup(
            flat, U, sentinel=-1, max_probes=max_probes
        )
        emb = values.at[jnp.clip(uids, 0, C - 1)].get(mode="clip").astype(
            jnp.float32
        )
        emb = jnp.where((uids >= 0)[:, None], emb, 0.0)
        out = combiners.combine(emb, inverse.reshape(B, L), ids >= 0,
                                "sum")
        return _combine_epilogue(
            FusedBags(out, uids, inverse.reshape(B, L), counts, overflow),
            ids, combiner,
        )

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from deeprec_tpu.ops import dedup
    from deeprec_tpu.utils import hashing

    # Probe table sizing: same load-factor policy as the XLA engine, but
    # laid out (S // 128, 128) so slot access is a dynamic SUBLANE slice
    # plus an iota-select over lanes (a dynamic LANE index is not
    # expressible on TPU). Floor of one full lane row.
    S = max(dedup.scratch_size(N), _LANES)

    def kernel(ids_ref, values_ref, out_ref, uids_ref, inv_ref, cnt_ref,
               ovf_ref, ubuf, lbuf, tabk, tabu, usm, sem):
        lane = jax.lax.broadcasted_iota(jnp.int32, (1, _LANES), 1)
        mask_s = jnp.uint32(S - 1)

        def tab_read(ref, pos):
            row = ref[pl.ds(pos // _LANES, 1), :]
            return jnp.sum(jnp.where(lane == (pos % _LANES), row, 0))

        def tab_write(ref, pos, val):
            r = pos // _LANES
            row = ref[pl.ds(r, 1), :]
            ref[pl.ds(r, 1), :] = jnp.where(lane == (pos % _LANES), val, row)

        tabk[...] = jnp.full_like(tabk[...], -1)
        tabu[...] = jnp.zeros_like(tabu[...])
        cnt_ref[...] = jnp.zeros_like(cnt_ref[...])
        uids_ref[...] = jnp.full_like(uids_ref[...], -1)
        # Only row 0 (the sentinel every pad/overflow position points at)
        # is ever read without having been DMA'd; zero the lot anyway so
        # no uninitialized VMEM can leak through a future indexing bug.
        ubuf[...] = jnp.zeros_like(ubuf[...])

        # ---- phase 1: sequential hash-probe insert — ops/dedup.py's
        # claim-scatter as an in-kernel slot write (the insert loop is
        # serial in here, so there is no claim race to re-check and no
        # O(N)-lane scatter to pay for).
        def insert(n, carry):
            nu, ovf = carry
            idv = ids_ref[n]
            valid = idv >= 0
            h0 = hashing.mix32(hashing.fold64(idv))

            def cond(c):
                return jnp.logical_and(~c[1], c[0] < max_probes)

            def body(c):
                p_step, done, u, nu, ovf = c
                pos = ((h0 + p_step.astype(jnp.uint32)) & mask_s).astype(
                    jnp.int32
                )
                k = tab_read(tabk, pos)
                hit = k == idv
                empty = k == -1
                u = jnp.where(hit, tab_read(tabu, pos), u)
                new_u = jnp.where(nu < jnp.int32(U), nu, 0)

                @pl.when(empty)
                def _():
                    tab_write(tabk, pos, idv)
                    tab_write(tabu, pos, new_u)

                @pl.when(empty & (nu < jnp.int32(U)))
                def _():
                    uids_ref[pl.ds(new_u, 1), :] = idv.reshape(1, 1)
                    usm[new_u] = idv

                u = jnp.where(empty, new_u, u)
                ovf = ovf + jnp.where(
                    empty & (nu >= jnp.int32(U)), 1, 0
                ).astype(jnp.int32)
                nu = nu + jnp.where(
                    empty & (nu < jnp.int32(U)), 1, 0
                ).astype(jnp.int32)
                done = done | hit | empty
                return p_step + 1, done, u, nu, ovf

            _, done, u, nu, ovf = jax.lax.while_loop(
                cond, body,
                (jnp.int32(0), ~valid, jnp.int32(0), nu, ovf),
            )
            # probe chain exhausted: same per-position overflow accounting
            # as hash_dedup's `sum(failed)`.
            ovf = ovf + jnp.where(valid & ~done, 1, 0).astype(jnp.int32)
            inv_ref[pl.ds(n, 1), :] = u.reshape(1, 1)

            @pl.when(u > 0)
            def _():
                cnt_ref[pl.ds(u, 1), :] = cnt_ref[pl.ds(u, 1), :] + 1

            return nu, ovf

        _, ovf = jax.lax.fori_loop(
            0, N, insert, (jnp.int32(1), jnp.int32(0))
        )
        ovf_ref[...] = ovf.reshape(1, 1)

        # ---- phase 2: DMA each unique row HBM -> VMEM once (2-deep
        # pipeline, same idiom as gather_rows). Unclaimed tail slots
        # fetch a clamped row unconditionally so start/wait stay paired.
        def fetch(slot, u):
            idx = jnp.clip(usm[u], 0, C - 1)
            return pltpu.make_async_copy(
                values_ref.at[idx], ubuf.at[u], sem.at[slot]
            )

        if U > 1:
            def fbody(u, _):
                @pl.when(u + 1 < U)
                def _():
                    fetch((u + 1) % 2, u + 1).start()

                fetch(u % 2, u).wait()
                return 0

            fetch(1, 1).start()
            jax.lax.fori_loop(1, U, fbody, 0)

        # Re-zero rows nobody claimed (their DMA fetched a clamped row):
        # inverse never points at them, but uids/counts are public and
        # tests reconstruct embeddings from the buffer's contract.
        def clear(u, _):
            @pl.when(usm[u] < 0)
            def _():
                ubuf[pl.ds(u, 1), :] = jnp.zeros_like(
                    ubuf[pl.ds(u, 1), :]
                )

            return 0

        jax.lax.fori_loop(1, U, clear, 0)

        # ---- phase 3: segment-sum into [B, D], mirroring
        # combiners.combine(..., "sum") term by term (per-position
        # multiply, one axis-reduction per bag) so fp32 output is
        # bit-identical to the fallback; the mean/sqrtn division happens
        # in the shared _combine_epilogue outside the kernel.
        def bag(b, _):
            def pos(loc, nb):
                j = b * L + loc
                w = jnp.where(ids_ref[j] >= 0, 1.0, 0.0).astype(
                    jnp.float32
                )
                u = jnp.sum(inv_ref[pl.ds(j, 1), :])
                row = ubuf[pl.ds(u, 1), :].astype(jnp.float32)
                lbuf[pl.ds(loc, 1), :] = row * w
                return nb + w

            jax.lax.fori_loop(0, L, pos, jnp.float32(0.0))
            out_ref[pl.ds(b, 1), :] = jnp.sum(
                lbuf[...], axis=0, keepdims=True
            )
            return 0

        jax.lax.fori_loop(0, B, bag, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(1,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=(
            pl.BlockSpec((B, D), lambda i, ids_ref: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((U, 1), lambda i, ids_ref: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((N, 1), lambda i, ids_ref: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((U, 1), lambda i, ids_ref: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i, ids_ref: (0, 0),
                         memory_space=pltpu.VMEM),
        ),
        scratch_shapes=[
            pltpu.VMEM((U, D), values.dtype),          # unique rows
            pltpu.VMEM((max(L, 1), D), jnp.float32),   # one bag's terms
            pltpu.VMEM((S // _LANES, _LANES), jnp.int32),  # probe keys
            pltpu.VMEM((S // _LANES, _LANES), jnp.int32),  # probe -> uid
            pltpu.SMEM((U,), jnp.int32),  # uids mirror: scalar DMA indices
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    out, uids, inv, cnt, ovf = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct((B, D), jnp.float32),
            jax.ShapeDtypeStruct((U, 1), jnp.int32),
            jax.ShapeDtypeStruct((N, 1), jnp.int32),
            jax.ShapeDtypeStruct((U, 1), jnp.int32),
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
        ),
        compiler_params=_compiler_params(pltpu, has_side_effects=True),
        interpret=interpret,
    )(flat, values)
    return _combine_epilogue(
        FusedBags(out, uids[:, 0], inv[:, 0].reshape(B, L), cnt[:, 0],
                  ovf[0, 0]),
        ids, combiner,
    )


def fused_sparse_backward(values: jnp.ndarray,
                          slots: Dict[str, jnp.ndarray],
                          grad_out: jnp.ndarray, ids: jnp.ndarray,
                          res: FusedBags, opt, *, combiner: str = "sum",
                          step=0, lr=None, seed=0,
                          grad_averaging: bool = False,
                          interpret: bool = False,
                          use_pallas: bool = True,
                          ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Single-pass backward: segment-sum per-example grads to unique rows
    and apply the optimizer update, fused into the scatter.

    values [C, D]; slots {name: [C, D] f32} (the optimizer's row slots —
    must satisfy fusable_optimizer, else the XLA composition runs even
    under interpret); grad_out [B, D] grad w.r.t. the forward's `out`;
    ids/res from the matching fused_sparse_forward call. bf16 tables
    stochastic-round with ROW-keyed bits (_sr_bits_rows), so kernel and
    fallback round identically regardless of uid order. Returns
    (new_values, new_slots).
    """
    B, L = ids.shape
    C, D = values.shape
    U = res.uids.shape[0]
    N = B * L
    step = jnp.asarray(step, jnp.int32)
    lr = jnp.asarray(opt.lr if lr is None else lr, jnp.float32)
    mask = ids >= 0
    sr = values.dtype == jnp.bfloat16
    bits = _sr_bits_rows(seed, res.uids, D) if sr else None
    # Combiner scaling happens HERE, shared by both paths (see
    # _bag_denominator for why the division can't live inside the branch).
    gs = grad_out.astype(jnp.float32) / _bag_denominator(mask, combiner)
    fusable = fusable_optimizer(opt, D)
    snames = sorted(slots)
    for name in snames:
        if slots[name].shape != (C, D):
            # A silent fallback here would gather WRONG rows (a packed
            # slot's row space is C // P) — reject loudly instead.
            raise ValueError(
                f"fused_sparse_backward: slot {name!r} has shape "
                f"{slots[name].shape}, want {(C, D)} — packed slot "
                "layouts keep the split-phase apply_gradients path"
            )

    if not fusable or (
        not interpret and not (use_pallas and _on_tpu()
                               and _dma_ok(D, values.dtype))
    ):
        if use_pallas and not interpret:
            _note_fallback(
                "fused_sparse_backward",
                "optimizer" if not fusable else _row_reason(D, values.dtype),
                values.shape, values.dtype,
            )
        g = gs  # [B, D], combiner-scaled above
        w = mask.astype(jnp.float32)[..., None]
        contrib = (jnp.broadcast_to(g[:, None, :], (B, L, D)) * w).reshape(
            N, D
        )
        grad_u = jnp.zeros((U, D), jnp.float32).at[
            res.inverse.reshape(-1)
        ].add(contrib)
        grad_u = grad_u.at[0].set(0.0)
        if grad_averaging:
            grad_u = grad_u / jnp.maximum(
                res.counts.astype(jnp.float32), 1.0
            )[:, None]
        ok = res.uids >= 0
        safe = jnp.where(ok, jnp.clip(res.uids, 0, C - 1), 0)
        value = values.at[safe].get(mode="clip").astype(jnp.float32)
        row_slots = {
            name: slots[name].at[safe].get(mode="clip").astype(jnp.float32)
            for name in snames
        }
        new_value, new_slots = opt.update(value, row_slots, grad_u,
                                          res.counts, step, lr)
        rows = (_sr_round_bits(new_value, bits) if sr
                else new_value.astype(values.dtype))
        drop = jnp.where(ok, safe, C)
        out_values = values.at[drop].set(rows, mode="drop")
        out_slots = {
            name: slots[name].at[drop].set(
                new_slots[name].astype(slots[name].dtype), mode="drop"
            )
            for name in snames
        }
        return out_values, out_slots

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    K = len(snames)
    if sr:
        bits_in, bits_dim = bits, D
    else:
        # f32 path never reads the bits: ship a 1-wide dummy, not U*D zeros.
        bits_in = jnp.zeros((U, 1), jnp.uint32)  # noqa: DRT003 — deliberate 1-wide dummy: f32 path never reads it
        bits_dim = 1

    def kernel(*refs):
        (uids_ref, inv_ref, m_ref, step_ref, lr_ref,
         g_ref, cnt_ref, bits_ref) = refs[:8]
        # refs[8 : 9+K] are the aliased value/slot inputs — read through
        # the output refs below (aliasing makes them the same buffers).
        vout = refs[9 + K]
        souts = refs[10 + K:10 + 2 * K]
        gbuf = refs[10 + 2 * K]
        vstage = refs[11 + 2 * K]
        stgs = refs[12 + 2 * K:12 + 3 * K]
        sem = refs[12 + 3 * K]

        # ---- phase A: segment-sum grads into unique-row space, same
        # accumulation order (flat position order) as the XLA scatter-add.
        gbuf[...] = jnp.zeros_like(gbuf[...])

        def accum(n, _):
            u = inv_ref[n]
            w = jnp.where(m_ref[n] > 0, 1.0, 0.0).astype(jnp.float32)
            b = n // L
            row = g_ref[pl.ds(b, 1), :]  # combiner-scaled by the caller
            gbuf[pl.ds(u, 1), :] = gbuf[pl.ds(u, 1), :] + row * w
            return 0

        jax.lax.fori_loop(0, N, accum, 0)
        gbuf[pl.ds(0, 1), :] = jnp.zeros_like(gbuf[pl.ds(0, 1), :])
        if grad_averaging:
            gbuf[...] = gbuf[...] / jnp.maximum(
                cnt_ref[...].astype(jnp.float32), 1.0
            )

        # ---- phase B: stage the touched value + slot rows VMEM-side
        # (one DMA per row per array; unclaimed tail rows fetch a clamped
        # row that phase D never writes back).
        def stage(u, _):
            idx = jnp.clip(uids_ref[u], 0, C - 1)
            cps = [pltpu.make_async_copy(
                vout.at[idx], vstage.at[u], sem.at[0]
            )]
            for k in range(K):
                cps.append(pltpu.make_async_copy(
                    souts[k].at[idx], stgs[k].at[u], sem.at[1 + k]
                ))
            for c in cps:
                c.start()
            for c in cps:
                c.wait()
            return 0

        jax.lax.fori_loop(1, U, stage, 0)

        # ---- phase C: the optimizer row-function over the whole [U, D]
        # stage — the SAME update() the unfused apply calls, so numerics
        # agree by construction; bf16 adds row-keyed SR before downcast.
        new_value, new_slots = opt.update(
            vstage[...].astype(jnp.float32),
            {snames[k]: stgs[k][...] for k in range(K)},
            gbuf[...],
            cnt_ref[...][:, 0],
            step_ref[0],
            lr_ref[0],
        )
        if sr:
            new_value = _sr_round_in_kernel(new_value, bits_ref[...])
        vstage[...] = new_value.astype(vstage.dtype)
        for k in range(K):
            stgs[k][...] = new_slots[snames[k]].astype(stgs[k].dtype)

        # ---- phase D: DMA-scatter the updated rows back (guarded: the
        # sentinel row and unclaimed tail slots are never written).
        def unstage(u, _):
            @pl.when(uids_ref[u] >= 0)
            def _():
                idx = jnp.clip(uids_ref[u], 0, C - 1)
                cps = [pltpu.make_async_copy(
                    vstage.at[u], vout.at[idx], sem.at[0]
                )]
                for k in range(K):
                    cps.append(pltpu.make_async_copy(
                        stgs[k].at[u], souts[k].at[idx], sem.at[1 + k]
                    ))
                for c in cps:
                    c.start()
                for c in cps:
                    c.wait()

            return 0

        jax.lax.fori_loop(1, U, unstage, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(1,),
        in_specs=[
            pl.BlockSpec((B, D), lambda i, *_: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((U, 1), lambda i, *_: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((U, bits_dim), lambda i, *_: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),
        ] + [pl.BlockSpec(memory_space=pl.ANY) for _ in range(K)],
        out_specs=tuple(
            pl.BlockSpec(memory_space=pl.ANY) for _ in range(1 + K)
        ),
        scratch_shapes=[
            pltpu.VMEM((U, D), jnp.float32),   # grad_u (never leaves VMEM)
            pltpu.VMEM((U, D), values.dtype),  # value stage
        ] + [pltpu.VMEM((U, D), jnp.float32) for _ in range(K)]
        + [pltpu.SemaphoreType.DMA((1 + K,))],
    )
    outs = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=tuple(
            [jax.ShapeDtypeStruct(values.shape, values.dtype)]
            + [jax.ShapeDtypeStruct(slots[n].shape, slots[n].dtype)
               for n in snames]
        ),
        input_output_aliases={8 + i: i for i in range(1 + K)},
        compiler_params=_compiler_params(pltpu, has_side_effects=True),
        interpret=interpret,
    )(
        jnp.clip(res.uids, -1, C - 1).astype(jnp.int32),
        res.inverse.reshape(-1).astype(jnp.int32),
        mask.reshape(-1).astype(jnp.int32),
        step.reshape(1),
        lr.reshape(1),
        gs,
        res.counts.reshape(U, 1),
        bits_in,
        values,
        *[slots[n] for n in snames],
    )
    return outs[0], {snames[k]: outs[1 + k] for k in range(K)}
