"""Fused TPU lookup kernels: DMA-pipelined gather, gather+combine, and a
stochastic-rounded scatter-apply.

Why these exist: the reference spends 5.5k LoC of CUDA on fused embedding
lookups (core/ops/fused_embedding_ops.cc:65, core/kernels/group_embedding/
group_embedding_lookup_sparse_forward_base_ops.cu.h) because op-composed
sparse gathers leave bandwidth on the table. The TPU analog is a Pallas
kernel that streams random table rows HBM->VMEM through a double-buffered
DMA pipeline, so the next row's fetch overlaps the current row's compute:

  * ``gather_rows``          — values[ix] for [U] unique slots (the hot
    [U, D] gather inside every lookup).
  * ``fused_gather_combine`` — bag-pooling straight out of the table:
    out[b] = sum_l w[b,l] * values[ix[b,l]] without materializing the
    [B, L, D] intermediate (serving/eval path; the train path needs the
    unique-space embeddings for autodiff and uses gather_rows).
  * ``apply_rows_sr``        — scatter updated rows back with stochastic
    rounding when the table is bf16 (plain round-to-nearest silently drops
    small gradient updates once |update| < ulp(value)/2).

Eligibility: the single-row DMA kernels require **f32 tables with
dim % 128 == 0** (Mosaic's HBM tiling constraint, ``_dma_ok``; measured
winners on v5e — gather 494 vs 362 GB/s, scatter 1117 vs 726). **bf16
tables with dim % 128 == 0** ride the PAIR-granule variants
(``gather_rows_pair`` / ``apply_rows_sr_pair`` / the pair branch of
``fused_gather_combine``): 2-row even-aligned DMAs with the half-select
or read-modify-write done in VMEM, including IN-KERNEL stochastic
rounding — gated behind kernel="pallas" / AUTO_TRUSTS_BF16_PAIR until a
hardware bench crowns them. Everything else falls back to the
identical-semantics XLA path. Off-TPU all calls are XLA, so every caller
is oracle-testable on CPU (the kernels themselves via interpret mode,
where the in-kernel SR branches are also covered).
"""
from __future__ import annotations


import jax
import jax.numpy as jnp

_BLOCK = 8  # rows per grid step; sublane-aligned for f32
_LANES = 128  # Mosaic HBM tiling: DMA row slices must be lane-aligned


def _on_tpu() -> bool:
    return jax.default_backend() == "tpu"


def _dma_ok(dim: int, dtype) -> bool:
    """Single-row DMA eligibility: f32 tables with dim % 128 == 0 —
    Mosaic requires HBM slices aligned to the tiling (measured on v5e:
    misaligned widths are a compile error, not a slowdown — dim 64 fails
    "must be aligned to tiling (128)"; bf16 tiles pack 2 sublanes per
    32-bit word so a dynamic single-row slice fails "index in dimension 0
    is a multiple of 2"). bf16 tables with dim % 128 == 0 have their own
    PAIR-granule kernels (gather_rows / apply_rows_sr /
    fused_gather_combine route them via _dma_pair_ok); narrower tables
    take the XLA path (a D<128 row
    underfills even one DMA granule — beating XLA there needs a packed
    storage layout, not a better kernel; see docs/perf.md)."""
    return dim % _LANES == 0 and jnp.dtype(dtype).itemsize == 4


def _dma_pair_ok(shape, dtype) -> bool:
    """bf16 pair-granule eligibility: rows ride 2-row granules (the bf16
    packing unit), so the table needs dim % 128 == 0 AND an even row
    count — checked here, not assumed, since the ops are public (an odd
    C would let a clamped index DMA one row past the array)."""
    C, dim = shape
    return (
        dim % _LANES == 0
        and C % 2 == 0
        and jnp.dtype(dtype) == jnp.bfloat16
    )


# Which (kernel, shape-class) combos "auto" trusts. The policy is that
# auto only resolves to Pallas where a live-hardware bench crowned it
# (tools/bench_lookup.py, docs/perf.md); the bf16 pair kernels are
# implemented + oracle-tested but NOT yet measured on hardware, so auto
# keeps XLA for them until a measurement flips these flags. Both flags
# are consulted by EmbeddingTable.use_pallas / .pair_kernels.
AUTO_TRUSTS_F32_ROW = True     # measured round 2: +37% gather, +54% scatter
AUTO_TRUSTS_BF16_PAIR = False  # pending hardware window


def _pad_rows(ix: jnp.ndarray, block: int, fill: int = 0) -> jnp.ndarray:
    n = ix.shape[0]
    pad = (-n) % block
    if pad:
        ix = jnp.concatenate([ix, jnp.full((pad,), fill, ix.dtype)])
    return ix


def _pad_updates(slot_ix, new_rows, block):
    """Shared scatter preamble: pad slot indices (-1 = skip) and update
    rows to a block multiple."""
    ixp = _pad_rows(
        jnp.where(slot_ix >= 0, slot_ix, -1).astype(jnp.int32).reshape(-1),
        block, fill=-1,
    )
    if ixp.shape[0] != new_rows.shape[0]:
        new_rows = jnp.concatenate([
            new_rows,
            jnp.zeros(
                (ixp.shape[0] - new_rows.shape[0], new_rows.shape[1]),
                new_rows.dtype,
            ),
        ])
    return ixp, new_rows


def _compiler_params(pltpu_mod, **kw):
    """Mosaic compiler params across jax versions: TPUCompilerParams was
    renamed CompilerParams and grew fields over time (has_side_effects is
    absent in older jax — safe to drop there: these kernels' outputs are
    always consumed, the flag only guards against DCE). Unknown fields are
    filtered rather than crashing the whole kernel path."""
    import dataclasses

    cls = getattr(pltpu_mod, "CompilerParams", None) \
        or pltpu_mod.TPUCompilerParams
    names = {f.name for f in dataclasses.fields(cls)}
    return cls(**{k: v for k, v in kw.items() if k in names})


def _sr_bits(seed, shape):
    """The one seed-derivation for stochastic-rounding bits: every SR
    path (XLA fallback, row kernel, pair kernel) must use this so their
    numerics stay interchangeable."""
    key = jax.random.fold_in(jax.random.PRNGKey(0x5EED), seed)
    return jax.random.bits(key, shape, jnp.uint32)


def _sr_round_in_kernel(row_f32, bits_u32):
    """In-kernel stochastic rounding f32 -> bf16-representable f32
    (same bit-twiddle as stochastic_round): add uniform noise below the
    mantissa cut, truncate. Shared by both scatter kernels."""
    from jax.experimental.pallas import tpu as pltpu

    u = pltpu.bitcast(row_f32, jnp.uint32)
    u = u + (bits_u32 & jnp.uint32(0xFFFF))
    u = u & jnp.uint32(0xFFFF0000)
    return pltpu.bitcast(u, jnp.float32)


# ------------------------------------------------- bf16 pair-granule ops


def gather_rows_pair(values: jnp.ndarray, ix: jnp.ndarray, *,
                     block: int = _BLOCK,
                     interpret: bool = False) -> jnp.ndarray:
    """bf16 gather via 2-row granules: values [C, D] bf16 (D % 128 == 0,
    C even), ix [n] int32 -> [n, D]. A dynamic single-row HBM slice is
    not expressible for bf16 (rows pack 2 sublanes per 32-bit word), so
    each lookup DMAs the even-aligned PAIR containing the row and emits
    the wanted half — 2x the HBM read volume of an f32 row gather, but
    the pair shares the granule the hardware reads anyway."""
    n = ix.shape[0]
    C, D = values.shape
    if not interpret and not (
        _on_tpu() and _dma_pair_ok(values.shape, values.dtype)
    ):
        return values.at[ix].get(mode="clip")

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    ixp = _pad_rows(ix.astype(jnp.int32), block)
    np_ = ixp.shape[0]

    def kernel(ix_ref, values_ref, out_ref, scratch, sems):
        base = pl.program_id(0) * block

        def pair_dma(slot, i):
            idx = jnp.clip(ix_ref[base + i], 0, C - 1)
            g = (idx // 2) * 2  # even-aligned granule base
            return pltpu.make_async_copy(
                values_ref.at[pl.ds(g, 2), :],
                scratch.at[slot],
                sems.at[slot],
            )

        pair_dma(0, 0).start()

        def body(i, _):
            cur = i % 2

            @pl.when(i + 1 < block)
            def _():
                pair_dma((i + 1) % 2, i + 1).start()

            pair_dma(cur, i).wait()
            idx = jnp.clip(ix_ref[base + i], 0, C - 1)
            out_ref[i, :] = scratch[cur, idx % 2, :]
            return 0

        jax.lax.fori_loop(0, block, body, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(np_ // block,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(
            (block, D), lambda i, ix_ref: (i, 0), memory_space=pltpu.VMEM
        ),
        scratch_shapes=[
            pltpu.VMEM((2, 2, D), values.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((np_, D), values.dtype),
        interpret=interpret,
    )(ixp, values)
    return out[:n]


def apply_rows_sr_pair(values: jnp.ndarray, slot_ix: jnp.ndarray,
                       new_rows: jnp.ndarray, seed: jnp.ndarray, *,
                       interpret: bool = False) -> jnp.ndarray:
    """bf16 scatter with IN-KERNEL stochastic rounding via 2-row
    granules: read-modify-write the even-aligned pair containing each
    target row. Fully serialized (one granule in flight): consecutive
    updates may share a granule, and the read of update i+1 must observe
    the write of update i. new_rows [U, D] f32; values [C, D] bf16."""
    U, D = new_rows.shape
    C = values.shape[0]
    if not interpret and not (
        _on_tpu() and _dma_pair_ok(values.shape, values.dtype)
    ):
        return apply_rows_sr(values, slot_ix, new_rows, seed,
                             use_pallas=False, interpret=False)

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    ixp, new_rows = _pad_updates(slot_ix, new_rows, _BLOCK)
    Up = ixp.shape[0]
    bits = _sr_bits(seed, (Up, D))

    def kernel(ix_ref, rows_ref, bits_ref, vin_ref, vout_ref, scratch, sem):
        del vin_ref  # aliased with vout_ref
        g0 = pl.program_id(0) * _BLOCK

        def body(i, _):
            idx = ix_ref[g0 + i]

            @pl.when(idx >= 0)
            def _():
                g = (idx // 2) * 2
                rd = pltpu.make_async_copy(
                    vout_ref.at[pl.ds(g, 2), :], scratch, sem.at[0]
                )
                rd.start()
                rd.wait()
                row = _sr_round_in_kernel(
                    rows_ref[pl.ds(i, 1), :].astype(jnp.float32),
                    bits_ref[pl.ds(i, 1), :],
                )
                scratch[pl.ds(idx % 2, 1), :] = row.astype(scratch.dtype)
                wr = pltpu.make_async_copy(
                    scratch, vout_ref.at[pl.ds(g, 2), :], sem.at[0]
                )
                wr.start()
                wr.wait()

            return 0

        jax.lax.fori_loop(0, _BLOCK, body, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(Up // _BLOCK,),
        in_specs=[
            pl.BlockSpec(
                (_BLOCK, D), lambda i, ix_ref: (i, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (_BLOCK, D), lambda i, ix_ref: (i, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.VMEM((2, D), values.dtype),
            pltpu.SemaphoreType.DMA((1,)),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(values.shape, values.dtype),
        input_output_aliases={3: 0},
        compiler_params=_compiler_params(pltpu, has_side_effects=True),
        interpret=interpret,
    )(ixp, new_rows, bits, values)


# ------------------------------------------------------------- gather_rows


def gather_rows(values: jnp.ndarray, ix: jnp.ndarray, *,
                block: int = _BLOCK, interpret: bool = False,
                pair_kernels: bool = False) -> jnp.ndarray:
    """values [C, D], ix [n] int32 -> [n, D]; out-of-range ix clamp (the
    'clip' semantics of the jnp fallback). Rows ride a 2-deep DMA pipeline.
    pair_kernels=True additionally routes eligible bf16 tables through the
    pair-granule kernel (explicit kernel="pallas" or a measured-winners
    flag — see AUTO_TRUSTS_BF16_PAIR)."""
    n = ix.shape[0]
    if pair_kernels and _dma_pair_ok(values.shape, values.dtype) and (
        interpret or _on_tpu()
    ):
        return gather_rows_pair(values, ix, block=block, interpret=interpret)
    if not interpret and not (_on_tpu() and _dma_ok(values.shape[1], values.dtype)):
        return values.at[ix].get(mode="clip")

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    C, D = values.shape
    ixp = _pad_rows(ix.astype(jnp.int32), block)
    np_ = ixp.shape[0]

    def kernel(ix_ref, values_ref, out_ref, scratch, sems):
        base = pl.program_id(0) * block

        def row_dma(slot, i):
            idx = jnp.clip(ix_ref[base + i], 0, C - 1)
            return pltpu.make_async_copy(
                values_ref.at[idx], scratch.at[slot], sems.at[slot]
            )

        row_dma(0, 0).start()

        def body(i, _):
            cur = i % 2

            @pl.when(i + 1 < block)
            def _():
                row_dma((i + 1) % 2, i + 1).start()

            row_dma(cur, i).wait()
            out_ref[i, :] = scratch[cur]
            return 0

        jax.lax.fori_loop(0, block, body, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(np_ // block,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(
            (block, D), lambda i, ix_ref: (i, 0), memory_space=pltpu.VMEM
        ),
        scratch_shapes=[
            pltpu.VMEM((2, D), values.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((np_, D), values.dtype),
        interpret=interpret,
    )(ixp, values)
    return out[:n]


# ----------------------------------------------------- fused gather+combine


def fused_gather_combine(values: jnp.ndarray, row_ix: jnp.ndarray,
                         weights: jnp.ndarray, *, block_b: int = 8,
                         interpret: bool = False,
                         pair_kernels: bool = False) -> jnp.ndarray:
    """Pooled bags straight from the table.

    values [C, D]; row_ix [B, L] int32 slot per position (< 0 = skip);
    weights [B, L] f32 per-position weight (carry the combiner here: 1 for
    sum, 1/n_b for mean, 1/sqrt(n_b) for sqrtn, 0 for pad/blocked).
    Returns [B, D] f32: out[b] = sum_l weights[b, l] * values[row_ix[b, l]].
    pair_kernels routes eligible bf16 tables through 2-row granule DMAs
    (same rationale as gather_rows_pair).
    """
    B, L = row_ix.shape
    C, D = values.shape
    pair = pair_kernels and _dma_pair_ok(values.shape, values.dtype) and (
        interpret or _on_tpu()
    )
    if not pair and not interpret and not (
        _on_tpu() and _dma_ok(D, values.dtype)
    ):
        e = values.at[jnp.clip(row_ix, 0, C - 1)].get(mode="clip")
        w = jnp.where(row_ix >= 0, weights, 0.0)
        return jnp.sum(e.astype(jnp.float32) * w[..., None], axis=1)

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    padB = (-B) % block_b
    if padB:
        row_ix = jnp.concatenate(
            [row_ix, jnp.full((padB, L), -1, row_ix.dtype)]
        )
        weights = jnp.concatenate([weights, jnp.zeros((padB, L), weights.dtype)])
    Bp = row_ix.shape[0]
    flat_ix = row_ix.reshape(-1).astype(jnp.int32)
    # Weights ride SMEM as a second scalar-prefetch operand: a dynamic
    # per-position scalar read from a VMEM block is not expressible on TPU
    # ("index in dimension 1 must be a multiple of 128"); SMEM scalar loads
    # at computed offsets are.
    flat_w = weights.reshape(-1).astype(jnp.float32)
    rows_per_blk = block_b * L

    def kernel(ix_ref, w_ref, values_ref, out_ref, scratch, sems):
        base = pl.program_id(0) * rows_per_blk

        def row_dma(slot, j):
            idx = jnp.clip(ix_ref[base + j], 0, C - 1)
            if pair:
                g = (idx // 2) * 2  # even-aligned bf16 granule
                return pltpu.make_async_copy(
                    values_ref.at[pl.ds(g, 2), :], scratch.at[slot],
                    sems.at[slot],
                )
            return pltpu.make_async_copy(
                values_ref.at[idx], scratch.at[slot], sems.at[slot]
            )

        row_dma(0, 0).start()
        out_ref[:] = jnp.zeros_like(out_ref)

        def body(j, _):
            cur = j % 2

            @pl.when(j + 1 < rows_per_blk)
            def _():
                row_dma((j + 1) % 2, j + 1).start()

            row_dma(cur, j).wait()
            b = j // L
            w = jnp.where(ix_ref[base + j] >= 0, w_ref[base + j], 0.0)
            if pair:
                idx = jnp.clip(ix_ref[base + j], 0, C - 1)
                row = scratch[cur, idx % 2, :]
            else:
                row = scratch[cur]
            out_ref[b, :] = out_ref[b, :] + w * row.astype(jnp.float32)
            return 0

        jax.lax.fori_loop(0, rows_per_blk, body, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(Bp // block_b,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(
            (block_b, D), lambda i, ix_ref, w_ref: (i, 0),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=[
            pltpu.VMEM(((2, 2, D) if pair else (2, D)), values.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((Bp, D), jnp.float32),
        interpret=interpret,
    )(flat_ix, flat_w, values)
    return out[:B]


# --------------------------------------------------- stochastic-rounded apply


def stochastic_round(x: jnp.ndarray, key: jnp.ndarray,
                     dtype=jnp.bfloat16) -> jnp.ndarray:
    """XLA stochastic rounding f32 -> bf16: add uniform noise below the
    mantissa cut, then truncate. E[round(x)] == x, so tiny optimizer updates
    survive bf16 tables in expectation instead of vanishing at ulp/2."""
    assert dtype == jnp.bfloat16, "only bf16 targets supported"
    bits = jax.random.bits(key, x.shape, jnp.uint32)
    u = jax.lax.bitcast_convert_type(x.astype(jnp.float32), jnp.uint32)
    u = u + (bits & jnp.uint32(0xFFFF))  # carry into the kept mantissa
    u = u & jnp.uint32(0xFFFF0000)  # truncate to bf16-representable
    return jax.lax.bitcast_convert_type(u, jnp.float32).astype(jnp.bfloat16)


def apply_rows_sr(values: jnp.ndarray, slot_ix: jnp.ndarray,
                  new_rows: jnp.ndarray, seed: jnp.ndarray, *,
                  block: int = _BLOCK, interpret: bool = False,
                  use_pallas: bool = True,
                  pair_kernels: bool = False) -> jnp.ndarray:
    """Scatter new_rows [U, D] f32 into values [C, D] at slot_ix [U]
    (< 0 = skip). bf16 tables round stochastically; f32 tables store exact.
    Returns the updated values array (aliased in-place under jit on TPU).
    use_pallas=False keeps the XLA scatter (still stochastic-rounding bf16);
    pair_kernels=True routes eligible bf16 tables through the pair-granule
    read-modify-write kernel with IN-KERNEL stochastic rounding."""
    U, D = new_rows.shape
    C = values.shape[0]
    if use_pallas and pair_kernels and _dma_pair_ok(values.shape, values.dtype) and (
        interpret or _on_tpu()
    ):
        return apply_rows_sr_pair(values, slot_ix, new_rows, seed,
                                  interpret=interpret)
    if not interpret and not (use_pallas and _on_tpu() and _dma_ok(D, values.dtype)):
        if values.dtype == jnp.bfloat16:
            key = jax.random.fold_in(jax.random.PRNGKey(0x5EED), seed)
            rows = stochastic_round(new_rows, key)
        else:
            rows = new_rows.astype(values.dtype)
        ix = jnp.where(slot_ix >= 0, slot_ix, C)
        return values.at[ix].set(rows, mode="drop")

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    # Pad with -1 (skip): a 0-fill would scatter garbage rows into slot 0.
    ixp, new_rows = _pad_updates(slot_ix, new_rows, block)
    Up = ixp.shape[0]
    sr = values.dtype == jnp.bfloat16
    # Random bits come in as a tensor (not in-kernel PRNG): identical
    # numerics across compiled TPU and interpret mode, at the cost of
    # U*D*4 extra bytes of traffic — negligible next to the row writes.
    if sr:
        bits = _sr_bits(seed, (Up, D))
        bits_dim = D
    else:
        # f32 path never reads the bits: ship a 1-wide dummy, not U*D zeros.
        bits = jnp.zeros((Up, 1), jnp.uint32)  # noqa: DRT003 — deliberate 1-wide dummy: f32 path never reads it, padding beats shipping U*D zeros
        bits_dim = 1

    def kernel(ix_ref, rows_ref, bits_ref, vin_ref, vout_ref, scratch, sems):
        del vin_ref  # aliased with vout_ref
        g = pl.program_id(0)

        def body(i, _):
            slot = i % 2
            row = rows_ref[pl.ds(i, 1), :].astype(jnp.float32)  # (1, D)
            if sr:
                row = _sr_round_in_kernel(row, bits_ref[pl.ds(i, 1), :])
            scratch[pl.ds(slot, 1), :] = row.astype(scratch.dtype)
            idx = ix_ref[g * block + i]

            @pl.when(idx >= 0)
            def _():
                dma = pltpu.make_async_copy(
                    scratch.at[slot], vout_ref.at[idx], sems.at[slot]
                )
                dma.start()
                dma.wait()

            return 0

        jax.lax.fori_loop(0, block, body, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(Up // block,),
        in_specs=[
            pl.BlockSpec(
                (block, D), lambda i, ix_ref: (i, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (block, bits_dim), lambda i, ix_ref: (i, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.VMEM((2, D), values.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(values.shape, values.dtype),
        input_output_aliases={3: 0},
        compiler_params=_compiler_params(pltpu, has_side_effects=True),
        interpret=interpret,
    )(ixp, new_rows, bits, values)
