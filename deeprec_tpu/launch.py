"""Multi-host launcher: `python -m deeprec_tpu.launch [...] -- script.py args`.

The counterpart of the reference's distributed launcher
(tensorflow/python/distribute/launch.py:55-97), which reads the cluster
layout from env vars, exports TF_CONFIG and execs the training script. The
JAX/TPU shape of the same job:

  * wire jax.distributed.initialize(coordinator, num_processes, process_id)
    BEFORE any jax import in the user script — after that, jax.devices()
    spans the whole pod and every shard_map/psum in this framework rides
    the global mesh (DCN between hosts, ICI within);
  * then run the target script in-process (runpy), so the user code needs
    zero changes to go multi-host.

Cluster layout comes from flags or, like the reference, from environment
variables: DEEPREC_COORDINATOR (host:port), DEEPREC_NUM_PROCESSES,
DEEPREC_PROCESS_ID. On TPU pods all three are optional —
jax.distributed.initialize() autodetects the pod topology.

Single-host multi-process CPU testing works the same way (the 2-process CI
test in tests/test_launch.py drives a psum and a file-coordinated WorkQueue
across processes).
"""
from __future__ import annotations

import argparse
import os
import runpy
import sys
from typing import Optional


def initialize(
    coordinator: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Wire the DCN control plane (idempotent — a no-op when already
    initialized, so scripts may call it defensively even under the CLI).
    Call before creating any arrays."""
    import jax

    try:
        if jax.distributed.is_initialized():
            return
    except AttributeError:  # older jax without the predicate
        pass

    kw = {}
    coordinator = coordinator or os.environ.get("DEEPREC_COORDINATOR")
    if num_processes is None and os.environ.get("DEEPREC_NUM_PROCESSES"):
        num_processes = int(os.environ["DEEPREC_NUM_PROCESSES"])
    if process_id is None and os.environ.get("DEEPREC_PROCESS_ID"):
        process_id = int(os.environ["DEEPREC_PROCESS_ID"])
    if coordinator:
        kw["coordinator_address"] = coordinator
    if num_processes is not None:
        kw["num_processes"] = num_processes
    if process_id is not None:
        kw["process_id"] = process_id
    jax.distributed.initialize(**kw)


def supervise_elastic(
    script: str,
    script_args,
    num_processes: int,
    elastic_dir: str,
    max_generations: int = 100,
    env_extra: Optional[dict] = None,
) -> int:
    """Single-host elastic supervisor: the UpdateServerDef analog.

    jax pins the process set at jax.distributed.initialize, so a topology
    change means a new worker generation: spawn `num_processes` workers
    running `script` under this launcher; when they exit with
    elastic.EXIT_RESCALE (having checkpointed and acked the plan), respawn
    at the plan's target count and bump DEEPREC_ELASTIC_EPOCH so the plan
    isn't re-run. A zero exit from all workers ends the job. Mirrors the
    reference choreography (elastic_training.proto:38-76) with the
    supervisor in the coordinator role.

    Scope: SINGLE-host process sets (the CI topology, and one TPU-VM
    driving its local chips). A multi-host pod needs an external
    orchestrator (e.g. the K8s operator pattern the reference's modelzoo
    distribute recipes assume) running this same choreography across
    hosts: per-host supervisors alone cannot form one jax job, because
    each would pin its own coordinator address and process-id range.
    """
    import subprocess

    from deeprec_tpu.parallel.elastic import EXIT_RESCALE, ElasticCoordinator

    coord = ElasticCoordinator(elastic_dir)
    n = num_processes
    epoch_done = coord.plan()[0]  # plans at/below this are already applied
    for _generation in range(max_generations):
        port = _free_port()
        procs = []
        for pid in range(n):
            env = dict(os.environ)
            env.update(env_extra or {})
            env.update(
                DEEPREC_COORDINATOR=f"127.0.0.1:{port}",
                DEEPREC_NUM_PROCESSES=str(n),
                DEEPREC_PROCESS_ID=str(pid),
                DEEPREC_ELASTIC_DIR=elastic_dir,
                DEEPREC_ELASTIC_EPOCH=str(epoch_done),
            )
            procs.append(
                subprocess.Popen(
                    [sys.executable, "-m", "deeprec_tpu.launch", script]
                    + list(script_args),
                    env=env,
                )
            )
        rcs = [q.wait() for q in procs]
        if all(rc == 0 for rc in rcs):
            return 0
        if all(rc == EXIT_RESCALE for rc in rcs):
            # The workers acked the epoch they COLLECTIVELY decided on,
            # which may be older than the latest plan.json (an autoscaler
            # can post again mid-rescale); scan the acks, don't re-read
            # the plan. A newer plan triggers the next generation.
            epoch, target = coord.wait_acked_after(epoch_done, n)
            print(
                f"deeprec_tpu.launch: elastic rescale {n} -> {target} "
                f"(plan epoch {epoch})",
                flush=True,
            )
            n = target
            epoch_done = epoch
            continue
        bad = [(i, rc) for i, rc in enumerate(rcs) if rc not in (0, EXIT_RESCALE)]
        raise RuntimeError(f"elastic workers failed: {bad}")
    raise RuntimeError("elastic: max_generations exceeded")


def supervise_worker(
    script: str,
    script_args,
    heartbeat: Optional[str] = None,
    lease_secs: float = 30.0,
    max_restarts: int = 5,
    env_extra: Optional[dict] = None,
) -> int:
    """Run ONE worker under liveness supervision (deeprec_tpu.online):
    restart it on crash or wedged heartbeat lease with capped-backoff
    budget, respawn EXIT_RESCALE exits for free. The worker sees
    DEEPREC_HEARTBEAT_FILE and must stamp it per step (TrainLoop and the
    `deeprec_tpu.online.loop` CLI pick the env var up automatically;
    custom loops stamp a `Heartbeat` themselves); without a heartbeat
    only death is detected, not wedging. Returns the final exit code (0 done,
    1 budget exhausted). The continuous-training analog of
    `supervise_elastic` — see docs/fault-tolerance.md."""
    from deeprec_tpu.online.supervisor import ProcessSpec, Supervisor

    def env():
        # Fresh single-process jax.distributed layout per (re)spawn —
        # the coordinator service dies with the worker, so a respawn
        # must not try to rebind the old generation's port.
        e = {
            "DEEPREC_COORDINATOR": f"127.0.0.1:{_free_port()}",
            "DEEPREC_NUM_PROCESSES": "1",
            "DEEPREC_PROCESS_ID": "0",
            **(env_extra or {}),
        }
        if heartbeat:
            e["DEEPREC_HEARTBEAT_FILE"] = heartbeat
        return e

    spec = ProcessSpec(
        name="worker",
        argv=[sys.executable, "-m", "deeprec_tpu.launch", script]
        + list(script_args),
        heartbeat_path=heartbeat,
        lease_secs=lease_secs if heartbeat else None,
        max_restarts=max_restarts,
        env=env,
    )
    sup = Supervisor([spec])
    sup.run()  # foreground; returns when done or budget exhausted
    st = sup.stats()["worker"]
    return 0 if st["done"] else 1


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def main(argv=None):
    p = argparse.ArgumentParser(
        description="deeprec_tpu multi-host launcher",
        usage="python -m deeprec_tpu.launch [flags] -- script.py [args...]",
    )
    p.add_argument("--coordinator", default=None, help="host:port of proc 0")
    p.add_argument("--num_processes", type=int, default=None)
    p.add_argument("--process_id", type=int, default=None)
    p.add_argument(
        "--elastic_dir", default=None,
        help="run as elastic SUPERVISOR: spawn --num_processes workers and "
        "respawn the set at the plan's target size on rescale exits",
    )
    p.add_argument(
        "--supervised", action="store_true",
        help="run ONE worker under liveness supervision: restart on crash "
        "or wedged heartbeat lease (see --heartbeat), capped-backoff "
        "restart budget, EXIT_RESCALE respawns free",
    )
    p.add_argument("--heartbeat", default=None,
                   help="heartbeat lease file for --supervised wedge "
                   "detection (exported as DEEPREC_HEARTBEAT_FILE)")
    p.add_argument("--lease_secs", type=float, default=30.0)
    p.add_argument("--max_restarts", type=int, default=5)
    p.add_argument("script", help="training script to run after init")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    args = p.parse_args(argv)

    if args.elastic_dir:
        sys.exit(
            supervise_elastic(
                args.script, args.script_args,
                args.num_processes or 1, args.elastic_dir,
            )
        )
    if args.supervised:
        sys.exit(
            supervise_worker(
                args.script, args.script_args, heartbeat=args.heartbeat,
                lease_secs=args.lease_secs, max_restarts=args.max_restarts,
            )
        )

    initialize(args.coordinator, args.num_processes, args.process_id)

    import jax

    print(
        f"deeprec_tpu.launch: process {jax.process_index()}/"
        f"{jax.process_count()} up, {len(jax.local_devices())} local / "
        f"{len(jax.devices())} global devices",
        flush=True,
    )
    sys.argv = [args.script] + list(args.script_args)
    runpy.run_path(args.script, run_name="__main__")


if __name__ == "__main__":
    main()
