"""Multi-host launcher: `python -m deeprec_tpu.launch [...] -- script.py args`.

The counterpart of the reference's distributed launcher
(tensorflow/python/distribute/launch.py:55-97), which reads the cluster
layout from env vars, exports TF_CONFIG and execs the training script. The
JAX/TPU shape of the same job:

  * wire jax.distributed.initialize(coordinator, num_processes, process_id)
    BEFORE any jax import in the user script — after that, jax.devices()
    spans the whole pod and every shard_map/psum in this framework rides
    the global mesh (DCN between hosts, ICI within);
  * then run the target script in-process (runpy), so the user code needs
    zero changes to go multi-host.

Cluster layout comes from flags or, like the reference, from environment
variables: DEEPREC_COORDINATOR (host:port), DEEPREC_NUM_PROCESSES,
DEEPREC_PROCESS_ID. On TPU pods all three are optional —
jax.distributed.initialize() autodetects the pod topology.

Single-host multi-process CPU testing works the same way (the 2-process CI
test in tests/test_launch.py drives a psum and a file-coordinated WorkQueue
across processes).
"""
from __future__ import annotations

import argparse
import os
import runpy
import sys
from typing import Optional


def initialize(
    coordinator: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Wire the DCN control plane (idempotent — a no-op when already
    initialized, so scripts may call it defensively even under the CLI).
    Call before creating any arrays."""
    import jax

    try:
        if jax.distributed.is_initialized():
            return
    except AttributeError:  # older jax without the predicate
        pass

    kw = {}
    coordinator = coordinator or os.environ.get("DEEPREC_COORDINATOR")
    if num_processes is None and os.environ.get("DEEPREC_NUM_PROCESSES"):
        num_processes = int(os.environ["DEEPREC_NUM_PROCESSES"])
    if process_id is None and os.environ.get("DEEPREC_PROCESS_ID"):
        process_id = int(os.environ["DEEPREC_PROCESS_ID"])
    if coordinator:
        kw["coordinator_address"] = coordinator
    if num_processes is not None:
        kw["num_processes"] = num_processes
    if process_id is not None:
        kw["process_id"] = process_id
    jax.distributed.initialize(**kw)


def main(argv=None):
    p = argparse.ArgumentParser(
        description="deeprec_tpu multi-host launcher",
        usage="python -m deeprec_tpu.launch [flags] -- script.py [args...]",
    )
    p.add_argument("--coordinator", default=None, help="host:port of proc 0")
    p.add_argument("--num_processes", type=int, default=None)
    p.add_argument("--process_id", type=int, default=None)
    p.add_argument("script", help="training script to run after init")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    args = p.parse_args(argv)

    initialize(args.coordinator, args.num_processes, args.process_id)

    import jax

    print(
        f"deeprec_tpu.launch: process {jax.process_index()}/"
        f"{jax.process_count()} up, {len(jax.local_devices())} local / "
        f"{len(jax.devices())} global devices",
        flush=True,
    )
    sys.argv = [args.script] + list(args.script_args)
    runpy.run_path(args.script, run_name="__main__")


if __name__ == "__main__":
    main()
