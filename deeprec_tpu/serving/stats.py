"""Serving observability: per-request stage timers aggregated into
histograms, reported into the obs metrics plane.

Every request through the micro-batching front is accounted in four
stages, the same decomposition bench.py's phase profiler gives training
steps:

  * ``queue``  — enqueue until a batcher worker picks the request up
                 (coalescing wait + head-of-line blocking)
  * ``pad``    — concat + bucket-pad of the coalesced batch
  * ``device`` — the jitted predict (dispatch + device compute + D2H)
  * ``post``   — per-request slicing and reply delivery
  * ``e2e``    — enqueue to reply received (the client-visible latency)
  * ``retrieval`` — full-corpus top-k requests end to end (the retrieval
                 lane: user tower + blocked corpus sweep + merge; see
                 serving/retrieval.py)

One ``ServingStats`` may be shared by several ``ModelServer`` members
(a ``ServerGroup`` passes one instance to every member), so the numbers
describe the serving front as a whole. Snapshots are cheap JSON-ready
dicts — `GET /v1/stats` returns one live, and tools/bench_serving.py
records one per measured configuration.

Registry adoption (obs/metrics.py): unless ``DEEPREC_OBS=off``, the
stage histograms and counters live in a per-stats ``MetricsRegistry``
(per-stats so two servers in one process never share series and
`/v1/stats` stays per-server) — the SAME objects back both the legacy
snapshot() and the Prometheus ``GET /metrics`` exposition, and their
ring buffers answer windowed queries ("p99 over the last 60 s") for the
autoscaler. With the plane off, plain ``LatencyHistogram``s keep the
legacy surface identical at zero obs cost.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from deeprec_tpu.analysis.annotations import guarded_by
from deeprec_tpu.obs import metrics as obs_metrics
from deeprec_tpu.training.profiler import LatencyHistogram

STAGES = ("queue", "pad", "device", "post", "e2e", "retrieval")

_COUNTERS = ("requests", "batches", "rows", "errors")


@guarded_by("_lock")
class ServingStats:
    """Thread-safe aggregate of the serving front's stage timers plus
    batch-shape and error counters."""

    def __init__(self, registry: Optional["obs_metrics.MetricsRegistry"]
                 = None):
        self._t0 = time.monotonic()
        self._lock = threading.Lock()
        if registry is None and obs_metrics.metrics_enabled():
            registry = obs_metrics.MetricsRegistry()
        self.registry = registry  # None when the obs plane is off
        self._make_metrics()
        self.requests = 0
        self.batches = 0
        self.rows = 0
        self.errors = 0
        self.retrieval_requests = 0
        self.candidates_scanned = 0

    def _make_metrics(self) -> None:
        r = self.registry
        if r is not None:
            self.stage = {
                s: r.histogram(
                    "deeprec_serving_stage_seconds",
                    "per-request serving stage latency", {"stage": s})
                for s in STAGES
            }
            self.batch_rows = r.histogram(
                "deeprec_serving_batch_rows",
                "rows per coalesced device batch", lo=1.0, hi=1 << 20)
            self._counters = {
                k: r.counter(f"deeprec_serving_{k}",
                             f"serving front {k} total")
                for k in _COUNTERS
            }
            # Retrieval-lane counters (serving/retrieval.py): requests
            # through the lane and candidate rows scanned for them (a
            # request scanning a C-row corpus for B user rows counts
            # B*C). Unlabeled — DRT007 cardinality contract.
            self._retr_counters = {
                "requests": r.counter(
                    "deeprec_retrieval_requests",
                    "full-corpus retrieval requests served"),
                "candidates": r.counter(
                    "deeprec_retrieval_candidates_scanned",
                    "corpus candidate rows scanned by retrieval sweeps"),
            }
        else:
            self.stage = {s: LatencyHistogram() for s in STAGES}
            self.batch_rows = LatencyHistogram(lo=1.0, hi=1 << 20)
            self._counters = None
            self._retr_counters = None

    # ----------------------------------------------------------- recording

    def record_stage(self, stage: str, seconds: float) -> None:
        self.stage[stage].record(seconds)

    def record_batch(self, n_requests: int, n_rows: int) -> None:
        with self._lock:
            self.batches += 1
            self.requests += n_requests
            self.rows += n_rows
        self.batch_rows.record(float(n_rows))
        c = self._counters
        if c is not None:
            c["batches"].inc()
            c["requests"].inc(n_requests)
            c["rows"].inc(n_rows)

    def record_error(self, n: int = 1) -> None:
        with self._lock:
            self.errors += n
        if self._counters is not None:
            self._counters["errors"].inc(n)

    def record_retrieval(self, n_requests: int, candidates: int) -> None:
        """Account one coalesced retrieval dispatch: `n_requests` rode the
        sweep, which scanned `candidates` corpus rows in total."""
        with self._lock:
            self.retrieval_requests += n_requests
            self.candidates_scanned += candidates
        c = self._retr_counters
        if c is not None:
            c["requests"].inc(n_requests)
            c["candidates"].inc(candidates)

    # ----------------------------------------------------------- reporting

    def window_p99_ms(self, stage: str = "e2e",
                      seconds: float = 60.0) -> Optional[float]:
        """p99 of `stage` over the trailing window (None with the obs
        plane off) — the autoscaler's load signal, answered from the
        metric's own ring buffer."""
        h = self.stage.get(stage)
        if self.registry is None or h is None:
            return None
        return h.window_summary(seconds)["p99_ms"]

    def snapshot(self) -> Dict:
        """JSON-ready view: per-stage latency summaries + counters. The
        batch_rows histogram reuses the latency summary shape with rows in
        place of milliseconds (keys renamed accordingly)."""
        with self._lock:
            out = {
                "requests": self.requests,
                "batches": self.batches,
                "rows": self.rows,
                "errors": self.errors,
                "uptime_s": round(time.monotonic() - self._t0, 3),
            }
        out["stages"] = {s: h.summary() for s, h in self.stage.items()}
        with self._lock:
            if self.retrieval_requests:
                out["retrieval"] = {
                    "requests": self.retrieval_requests,
                    "candidates_scanned": self.candidates_scanned,
                }
        rows = self.batch_rows.summary()
        out["batch_rows"] = {
            "count": rows["count"],
            "mean": round(rows["mean_ms"] / 1e3, 2),
            "p50": rows["p50_ms"] / 1e3,
            "p99": rows["p99_ms"] / 1e3,
            "max": rows["max_ms"] / 1e3,
        }
        return out

    def metrics_snapshot(self) -> Optional[Dict]:
        """The registry snapshot (None with the plane off) — what the
        socket frontend merges across backends for its `/metrics`."""
        return None if self.registry is None else self.registry.snapshot()

    def reset(self) -> None:
        with self._lock:
            if self.registry is not None:
                # drops metric accumulations; collector callbacks
                # registered on this registry (queue depth, model
                # version) survive a stats reset by design
                self.registry.reset()
            self._make_metrics()
            self.requests = self.batches = self.rows = self.errors = 0
            self.retrieval_requests = self.candidates_scanned = 0
            self._t0 = time.monotonic()
