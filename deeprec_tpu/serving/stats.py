"""Serving observability: per-request stage timers aggregated into
histograms.

Every request through the micro-batching front is accounted in four
stages, the same decomposition bench.py's phase profiler gives training
steps:

  * ``queue``  — enqueue until a batcher worker picks the request up
                 (coalescing wait + head-of-line blocking)
  * ``pad``    — concat + bucket-pad of the coalesced batch
  * ``device`` — the jitted predict (dispatch + device compute + D2H)
  * ``post``   — per-request slicing and reply delivery
  * ``e2e``    — enqueue to reply received (the client-visible latency)

One ``ServingStats`` may be shared by several ``ModelServer`` members
(a ``ServerGroup`` passes one instance to every member), so the numbers
describe the serving front as a whole. Snapshots are cheap JSON-ready
dicts — `GET /v1/stats` returns one live, and tools/bench_serving.py
records one per measured configuration.
"""
from __future__ import annotations

import threading
import time
from typing import Dict

from deeprec_tpu.analysis.annotations import guarded_by
from deeprec_tpu.training.profiler import LatencyHistogram

STAGES = ("queue", "pad", "device", "post", "e2e")


@guarded_by("_lock")
class ServingStats:
    """Thread-safe aggregate of the serving front's stage timers plus
    batch-shape and error counters."""

    def __init__(self):
        self._t0 = time.monotonic()
        self._lock = threading.Lock()
        self.stage = {s: LatencyHistogram() for s in STAGES}
        self.batch_rows = LatencyHistogram(lo=1.0, hi=1 << 20)  # rows, not s
        self.requests = 0
        self.batches = 0
        self.rows = 0
        self.errors = 0

    # ----------------------------------------------------------- recording

    def record_stage(self, stage: str, seconds: float) -> None:
        self.stage[stage].record(seconds)

    def record_batch(self, n_requests: int, n_rows: int) -> None:
        with self._lock:
            self.batches += 1
            self.requests += n_requests
            self.rows += n_rows
        self.batch_rows.record(float(n_rows))

    def record_error(self, n: int = 1) -> None:
        with self._lock:
            self.errors += n

    # ----------------------------------------------------------- reporting

    def snapshot(self) -> Dict:
        """JSON-ready view: per-stage latency summaries + counters. The
        batch_rows histogram reuses the latency summary shape with rows in
        place of milliseconds (keys renamed accordingly)."""
        with self._lock:
            out = {
                "requests": self.requests,
                "batches": self.batches,
                "rows": self.rows,
                "errors": self.errors,
                "uptime_s": round(time.monotonic() - self._t0, 3),
            }
        out["stages"] = {s: h.summary() for s, h in self.stage.items()}
        rows = self.batch_rows.summary()
        out["batch_rows"] = {
            "count": rows["count"],
            "mean": round(rows["mean_ms"] / 1e3, 2),
            "p50": rows["p50_ms"] / 1e3,
            "p99": rows["p99_ms"] / 1e3,
            "max": rows["max_ms"] / 1e3,
        }
        return out

    def reset(self) -> None:
        with self._lock:
            self.stage = {s: LatencyHistogram() for s in STAGES}
            self.batch_rows = LatencyHistogram(lo=1.0, hi=1 << 20)
            self.requests = self.batches = self.rows = self.errors = 0
            self._t0 = time.monotonic()
