from deeprec_tpu.serving.predictor import ModelServer, Predictor
