from deeprec_tpu.serving.predictor import ModelServer, Predictor, ServerGroup
from deeprec_tpu.serving.frontend import (
    BackendServer,
    Frontend,
    spawn_backends,
    spawn_frontends,
)
from deeprec_tpu.serving.fleet import (
    FleetAutoscaler,
    FleetClient,
    FleetRegistry,
    HashRing,
    LeaseStamper,
)
from deeprec_tpu.serving.http_server import HttpServer
from deeprec_tpu.serving.retrieval import (
    RetrievalEngine,
    RetrievalResult,
    RetrievalServer,
)
from deeprec_tpu.serving.stats import ServingStats
from deeprec_tpu.serving.remote_store import RemoteKVClient, RemoteKVServer
from deeprec_tpu.serving.resp_store import RedisFeatureStore, RespConnection
