from deeprec_tpu.serving.predictor import ModelServer, Predictor
from deeprec_tpu.serving.http_server import HttpServer
